"""The long-lived in-process solve server.

One :class:`Server` owns a :class:`~.registry.Registry` of models and
LS systems, a bounded :class:`~.admission.AdmissionQueue`, and ONE
worker thread that drains the queue in coalesced batches through
``batcher.run_batch``.  Requests enter through :meth:`submit` (async,
returns a future) or :meth:`call` (blocking); both always resolve to a
protocol response dict — errors are structured envelopes, never raised
across the serving boundary.

Warm start: :meth:`start` replays the policy layer's hot-plan profiles
(``policy.warm_start`` — XLA cache dir + plan re-trace) and then
*primes* every registered system/model through its own executor at
every ladder rung a coalesced batch can reach, so neither the first
request nor the first full batch pays a trace+compile.

Telemetry: every request lands counters under the ``serve.`` prefix
(requests/ok/errors/sheds/batches/coalesced/fallbacks), queue-wait and
latency histograms, and a bounded latency reservoir for the p50/p99
that ``telemetry.snapshot()["serve"]`` folds.  All of it rides the
``SKYLARK_TELEMETRY`` gate: disabled, a server run is bit-identical
and allocation-free on the telemetry side (pinned in
``tests/test_review_regressions.py``).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import asdict, dataclass

import numpy as np

from .. import telemetry
from ..core.context import SketchContext
from ..utils.exceptions import (
    DeadlineExceededError,
    InvalidParameters,
    QuotaExceededError,
    RegistryEpochError,
    SkylarkError,
)
from . import batcher, protocol
from .admission import AdmissionQueue, Entry
from .cache import ResultCache, payload_digest
from .qos import DEFAULT_TENANT, LaneConfig, TenantQuotas, tenant_of
from .registry import Registry

__all__ = ["ServeParams", "Server", "latency_percentiles", "record_latency"]

# Process-wide latency reservoir (most recent completions AND sheds)
# feeding the p50/p99 in telemetry.snapshot()["serve"]; the registry's
# histograms keep only streaming moments, so the tails need their own
# samples.  Shed requests record their queue time with ``shed=True`` —
# otherwise saturation, the one regime where sheds dominate, is exactly
# when the reservoir would flatter p99 by dropping them.  Appended ONLY
# when telemetry is enabled — a disabled run allocates nothing here.
_LATENCIES: deque[tuple[float, bool]] = deque(maxlen=4096)


def record_latency(ms: float, shed: bool = False) -> None:
    if telemetry.enabled():
        _LATENCIES.append((float(ms), bool(shed)))


def latency_percentiles() -> dict:
    """p50/p99 over ALL samples (sheds included), plus ``_served``
    variants excluding sheds and the shed sample count whenever any
    shed is in the window — so both views are always computable."""
    if not _LATENCIES:
        return {}
    samples = list(_LATENCIES)
    lat = np.sort(np.asarray([m for m, _ in samples]))
    out = {
        "latency_p50_ms": round(float(np.percentile(lat, 50)), 4),
        "latency_p99_ms": round(float(np.percentile(lat, 99)), 4),
    }
    served = np.asarray([m for m, s in samples if not s])
    shed_n = len(samples) - served.size
    if shed_n:
        out["latency_shed_samples"] = int(shed_n)
        if served.size:
            served = np.sort(served)
            out["latency_p50_ms_served"] = round(
                float(np.percentile(served, 50)), 4)
            out["latency_p99_ms_served"] = round(
                float(np.percentile(served, 99)), 4)
    return out


@dataclass
class ServeParams:
    """Knobs of one server instance.

    - ``max_queue``: admission depth cap; requests past it shed with
      :class:`AdmissionError` (code 112).
    - ``max_coalesce``: most requests one fused dispatch may carry
      (``1`` disables coalescing — the serial-per-request reference the
      bitwise tests and the bench SLO compare against).
    - ``coalesce_window_ms``: optional linger after the head request is
      taken, trading that much latency for fuller batches.
    - ``default_deadline_ms``: deadline applied to requests that carry
      none (``None`` = no deadline).
    - ``warm_start`` / ``prime``: replay policy warm-start profiles /
      pre-compile registered entities' first-rung executables at
      :meth:`Server.start`.
    - ``workers``: batcher worker threads draining the one admission
      queue.  ``1`` (the default) is PR-10 behavior bit-for-bit; ``K>1``
      pins worker ``i`` to local device ``i % ndevices`` (the PR-11
      ``pinned_placer`` seam), so small-batch traffic scales with chip
      count instead of serializing through one device.  Coalescing is
      unchanged — ``take_batch`` is already multi-consumer-safe, and
      per-slot purity keeps results bitwise identical to a single
      worker's.
    - ``cache`` / ``cache_max_entries`` / ``cache_max_bytes``: the
      front-door :class:`~.cache.ResultCache`.  ``None`` defers to the
      ``SKYLARK_CACHE`` / ``SKYLARK_CACHE_MAX_ENTRIES`` /
      ``SKYLARK_CACHE_MAX_BYTES`` knobs.
    - ``qos_quantum`` / ``tenant_weights``: deficit-round-robin lane
      scheduling (``SKYLARK_QOS_QUANTUM`` / ``SKYLARK_QOS_WEIGHTS``).
    - ``tenant_quota_rps`` / ``tenant_quota_burst`` / ``tenant_quotas``:
      per-tenant token-bucket admission quotas shedding code-117
      envelopes (``SKYLARK_QOS_QUOTA_RPS`` / ``SKYLARK_QOS_QUOTA_BURST``
      / ``SKYLARK_QOS_QUOTAS``); the rate default 0 means unlimited.
    - ``state_dir`` / ``recover`` / ``journal_compact_every``: the
      durability layer.  A ``state_dir`` attaches a write-ahead
      :class:`~.journal.Journal` to the registry (every mint journals
      durably BEFORE it publishes); ``recover=True`` additionally
      restores the registry from that directory's snapshot + journal
      tail at construction, bitwise-identical to the process that died.
      ``journal_compact_every`` overrides ``SKYLARK_JOURNAL_COMPACT_EVERY``
      (records between snapshot compactions; ``0`` disables compaction).
    """

    max_queue: int = 256
    max_coalesce: int = 16
    coalesce_window_ms: float = 0.0
    default_deadline_ms: float | None = None
    warm_start: bool = True
    prime: bool = True
    workers: int = 1
    cache: bool | None = None
    cache_max_entries: int | None = None
    cache_max_bytes: int | None = None
    qos_quantum: float | None = None
    tenant_weights: str | dict | None = None
    tenant_quota_rps: float | None = None
    tenant_quota_burst: float | None = None
    tenant_quotas: str | dict | None = None
    state_dir: str | None = None
    recover: bool = False
    journal_compact_every: int | None = None


class Server:
    def __init__(
        self,
        params: ServeParams | None = None,
        *,
        seed: int = 0,
        context: SketchContext | None = None,
    ):
        self.params = params or ServeParams()
        self.ctx = context if context is not None else SketchContext(seed=seed)
        # ONE cache instance: the front door's response cache, the
        # cond/ppr report memo, and the load-report census are all this
        # object, so registry mints invalidate everything at once.
        self.cache = ResultCache(
            max_entries=self.params.cache_max_entries,
            max_bytes=self.params.cache_max_bytes,
            enabled=self.params.cache,
        )
        if self.params.state_dir is not None and self.params.recover:
            # Restart path: snapshot + journal tail replay, pinned
            # bitwise-identical to the registry that died (same entity
            # bits, same epoch counter, same epoch_log) — the replica
            # rejoins the fleet at the exact epoch callers observed.
            self.registry = Registry.recover(
                self.params.state_dir,
                cache=self.cache,
                compact_every=self.params.journal_compact_every,
            )
        elif self.params.state_dir is not None:
            from .journal import Journal

            self.registry = Registry(
                cache=self.cache,
                journal=Journal(
                    self.params.state_dir,
                    compact_every=self.params.journal_compact_every,
                ),
            )
        else:
            self.registry = Registry(cache=self.cache)
        self.quotas = TenantQuotas(
            default_rps=self.params.tenant_quota_rps,
            default_burst=self.params.tenant_quota_burst,
            quotas=self.params.tenant_quotas,
        )
        self.queue = AdmissionQueue(
            self.params.max_queue,
            lanes=LaneConfig(
                quantum=self.params.qos_quantum,
                weights=self.params.tenant_weights,
            ),
        )
        # Bounded per-tenant metric labels: the tenant key is client-
        # controlled (header/payload), so minting counter names from it
        # raw is a cardinality DoS on the telemetry registry and the
        # Prometheus exposition.  Configured tenants (weights/quotas)
        # are always labelled; unconfigured ones claim a label first-
        # come up to the cap, and everything past it folds into the
        # "other" bucket.  Lanes/quotas/trace envelopes keep raw keys.
        self._metric_tenants = {DEFAULT_TENANT}
        self._metric_tenants.update(self.queue.lanes.weights)
        self._metric_tenants.update(self.quotas.quotas)
        self._metric_tenant_cap = max(
            len(self._metric_tenants),
            int(os.environ.get("SKYLARK_QOS_TENANT_METRICS_MAX", "32")),
        )
        # Bucket registration for the phase clock + the serve latency
        # histogram: configuration, not data (registration is free and
        # survives telemetry.reset()), so the fleet's _bucket{le=...}
        # series exist from the first traced request onward.  Non-serve
        # processes never call this, so their histograms stay moment-only.
        telemetry.enable_phase_buckets()
        telemetry.enable_buckets("serve.latency_ms")
        self.warm_summary: dict | None = None
        self.primed: list[str] = []
        self._thread: threading.Thread | None = None
        self._threads: list[threading.Thread] = []
        self._fresh_seq = 0
        # per-placement-key {key: [requests, busy_seconds]} — the
        # throughput half of the load report the fleet router places by
        self._key_stats: dict[str, list] = {}
        self._stats_lock = threading.Lock()

    # -- registration (delegates; the server's context is the default
    #    counter stream, so registration order is deterministic) ------------

    def register_model(self, name, model):
        self.registry.register_model(name, model)

    def load_model(self, name, path):
        return self.registry.load_model(name, path)

    def register_system(self, name, A, **kw):
        kw.setdefault("context", self.ctx)
        return self.registry.register_system(name, A, **kw)

    def register_graph(self, name, G, **kw):
        kw.setdefault("context", self.ctx)
        return self.registry.register_graph(name, G, **kw)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Server":
        if self._thread is not None:
            return self
        if self.params.warm_start:
            from .. import policy

            self.warm_summary = policy.warm_start()
        if self.params.prime:
            self.prime()
        for i, dev in enumerate(self._worker_devices()):
            t = threading.Thread(
                target=self._worker, args=(dev,),
                name=f"skylark-serve-worker-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        self._thread = self._threads[0]
        return self

    def _worker_devices(self) -> list:
        """One slot per worker thread: ``[None]`` for the single-worker
        server (no pinning — PR-10 behavior exactly), else worker ``i``
        pins ``jax.local_devices()[i % ndevices]`` so independent
        batches land on disjoint chips."""
        k = max(1, self.params.workers)
        if k == 1:
            return [None]
        import jax

        devs = jax.local_devices()
        return [devs[i % len(devs)] for i in range(k)]

    def prime(self) -> list[str]:
        """Compile every executable a coalesced batch can reach, NOW.

        Not just the first rung: a batch of k requests pads to the
        k-dependent ladder rung, so a server primed only at rung 8 still
        pays trace+compile for rung 16/24/32 batches MID-TRAFFIC — and
        because one worker drains the queue, every request behind the
        compiling batch eats that stall (the bench measured KRR-predict
        coalesced slower than serial before this primed the ladder)."""
        mc = max(1, self.params.max_coalesce)
        # Multi-worker servers prime once per DISTINCT pinned device:
        # XLA executables are per-device, so a rung warm on chip 0 still
        # stalls the first batch chip 1 draws.  Single-worker = [None],
        # exactly the PR-10 prime.
        devices = sorted(
            {id(d): d for d in self._worker_devices()}.values(),
            key=lambda d: getattr(d, "id", -1),
        )
        for name, system in self.registry.systems.items():
            widths = sorted({batcher._lane_bucket(k) for k in range(1, mc + 1)})
            for dev in devices:
                for w in widths:
                    entries = [
                        Entry(
                            {"op": "ls_solve", "system": name}, Future(), None,
                            "ls_solve", payload=np.zeros(system.m),
                        )
                        for _ in range(w)
                    ]
                    batcher._execute_ls(self.registry, entries, dev)
            # cond-est answers from this cached report; probing it here
            # keeps the first served cond_est request off the probe cost
            system.cond_report(cache=self.cache)
            self.primed.append(f"system:{name}:{widths}")
        from .. import plans

        for name, model in self.registry.models.items():
            d = getattr(model, "input_dim", None)
            if not d:
                continue
            rungs = sorted({plans.bucket_for(k) for k in range(1, mc + 1)})
            for dev in devices:
                for r in rungs:
                    entries = [
                        Entry(
                            {"op": "predict", "model": name}, Future(), None,
                            "predict", payload=np.zeros((1, int(d))),
                        )
                        for _ in range(r)
                    ]
                    batcher._execute_predict(self.registry, entries, dev)
            self.primed.append(f"model:{name}:{rungs}")
        for name, gsys in self.registry.graphs.items():
            # Graph queries serve from host arrays — nothing to compile;
            # one executor pass makes the first request's path identical
            # to every later one (and catches a broken embedding NOW).
            if gsys.G.n:
                entries = [
                    Entry(
                        {"op": "ase_embed", "graph": name}, Future(), None,
                        "ase_embed",
                        payload=("rows", np.zeros(1, np.int64)),
                    )
                ]
                batcher._execute_ase_embed(self.registry, entries, None)
            self.primed.append(f"graph:{name}:k={gsys.k}")
        return self.primed

    def stop(self, timeout: float = 10.0) -> None:
        self.queue.close()
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(max(0.0, deadline - time.monotonic()))
        self._threads = []
        self._thread = None
        for e in self.queue.drain():  # anything the workers never reached
            self._resolve_error(
                e, SkylarkError("server stopped before dispatch")
            )

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()

    # -- request path -------------------------------------------------------

    def submit(self, request: dict) -> Future:
        """Admit one request; ALWAYS returns a future resolving to a
        protocol response dict (sheds and validation failures resolve
        immediately with structured errors — nothing raises)."""
        fut: Future = Future()
        telemetry.inc("serve.requests")
        try:
            entry = self._validate(request, fut)
        except SkylarkError as e:
            telemetry.inc("serve.errors")
            telemetry.error_event(
                "serve.validate", e, op=request.get("op")
            )
            fut.set_result(
                protocol.error_response(
                    request.get("id"), e, {"events": []}
                )
            )
            return fut
        if entry is None:  # ping/stats answered inline
            return fut
        entry.tenant = tenant_of(request)
        entry.tenant_label = self._tenant_label(entry.tenant)
        entry.trace["tenant"] = entry.tenant
        self._tenant_inc(entry.tenant_label, "requests")
        # Trace minting at admission: None (no allocation) with
        # telemetry off; the context's event list aliases entry.trace's.
        entry.tctx = telemetry.mint(
            entry.op,
            key=entry.key,
            request_id=request.get("id"),
            deadline_ms=request.get(
                "deadline_ms", self.params.default_deadline_ms
            ),
            events=entry.trace["events"],
        )
        if entry.tctx is not None:
            entry.trace["trace_id"] = entry.tctx.trace_id
        # -- exactly-once updates (idempotency-key dedup window) ------------
        # A replayed op:"update" — the router's 112/114 failover resends
        # the same request dict, or a client retried on a timeout whose
        # first send actually landed — must NOT re-execute the mutation.
        # The registry's journal-backed dedup window keyed (tenant,
        # idem_key) holds the epoch-ledger receipt the first execution
        # minted; a hit resolves with that recorded receipt and costs
        # zero queue/quota pressure, exactly like a cache hit.
        if entry.idem_key is not None:
            # The dedup identity is (tenant, key) — tenant is only known
            # HERE, after lane assignment, so the executor-bound payload
            # picks it up now.
            entry.payload["idem"] = (entry.tenant, entry.idem_key)
            receipt = self.registry.idem_receipt(
                entry.tenant, entry.idem_key
            )
            if receipt is not None:
                entry.trace["events"].append(
                    {
                        "kind": "idem_replay",
                        "idem_key": entry.idem_key,
                        "epoch": receipt.get("epoch"),
                    }
                )
                telemetry.inc("serve.ok")
                telemetry.inc("serve.idem_hits")
                telemetry.finish_trace(entry.tctx, "ok")
                fut.set_result(
                    protocol.ok_response(
                        request.get("id"), receipt, entry.trace
                    )
                )
                return fut
        # -- front-door result cache ---------------------------------------
        # Key = (placement key, canonical payload digest, pinned entity
        # epoch): the epoch component makes a registry mint observable by
        # the VERY NEXT request structurally — it computes a new key and
        # misses.  A hit costs zero device work AND zero queue/quota
        # pressure, so it deliberately bypasses the tenant token bucket:
        # quotas meter dispatches, not dict lookups.
        t_hit = time.monotonic()
        self._stamp_cache_key(entry)
        if entry.cache_key is not None:
            hit = self.cache.get(entry.cache_key)
            if hit is not None:
                entry.trace["events"].append(
                    {"kind": "cache_hit", "epoch": entry.cache_key[2]}
                )
                entry.trace["cache_hit"] = True
                if entry.entity is not None:
                    entry.trace["registry_epoch"] = int(
                        getattr(entry.entity, "epoch", 0)
                    )
                telemetry.inc("serve.ok")
                self._tenant_inc(entry.tenant_label, "cache_hits")
                telemetry.finish_trace(entry.tctx, "ok")
                ms = (time.monotonic() - t_hit) * 1e3
                telemetry.observe("serve.latency_ms", ms)
                record_latency(ms)
                telemetry.observe_slo(
                    entry.op, ms, tenant=entry.tenant_label
                )
                self._tenant_observe(entry.tenant_label, ms)
                fut.set_result(
                    protocol.ok_response(request.get("id"), hit, entry.trace)
                )
                return fut
        # -- per-tenant quota (code 117, BEFORE the global depth gate) ------
        try:
            self.quotas.admit(entry.tenant)
        except QuotaExceededError as e:
            telemetry.inc("serve.shed_quota")
            telemetry.inc("serve.errors")
            self._tenant_inc(entry.tenant_label, "shed_quota")
            entry.trace["events"].append(
                {
                    "kind": "quota_shed",
                    "tenant": entry.tenant,
                    "retry_after_ms": e.retry_after_ms,
                    **self._queue_state(),
                }
            )
            with telemetry.activate([entry.tctx]):
                telemetry.error_event(
                    "serve.quota", e, op=entry.op, tenant=entry.tenant
                )
            telemetry.finish_trace(entry.tctx, "shed_quota", code=e.code)
            fut.set_result(
                protocol.error_response(request.get("id"), e, entry.trace)
            )
            return fut
        try:
            self.queue.offer(entry, on_admit=self._on_admit)
        except SkylarkError as e:  # AdmissionError
            telemetry.inc("serve.shed_admission")
            telemetry.inc("serve.errors")
            self._tenant_inc(entry.tenant_label, "shed_admission")
            # The envelope carries the queue state that caused the shed:
            # depth/percentile context a backing-off caller (or a
            # post-mortem) needs, without a second round trip.
            entry.trace["events"].append(
                {
                    "kind": "admission_shed",
                    "queue_depth": getattr(e, "queue_depth", None),
                    "max_depth": getattr(e, "max_depth", None),
                    **self._queue_state(),
                }
            )
            with telemetry.activate([entry.tctx]):
                telemetry.error_event("serve.admission", e, op=entry.op)
            telemetry.finish_trace(
                entry.tctx, "shed_admission", code=e.code
            )
            # Door sheds spend ~0ms queued, but they still count:
            # excluding them is what flattered p99 under saturation.
            shed_ms = (time.monotonic() - t_hit) * 1e3
            record_latency(shed_ms, shed=True)
            telemetry.observe_slo(
                entry.op, shed_ms, tenant=entry.tenant_label, shed=True
            )
            fut.set_result(
                protocol.error_response(request.get("id"), e, entry.trace)
            )
        return fut

    def call(self, request: dict | None = None, /, **fields) -> dict:
        req = dict(request or {}, **fields)
        return self.submit(req).result()

    def stats(self) -> dict:
        counters = {
            k.split(".", 1)[1]: v
            for k, v in telemetry.REGISTRY.snapshot()["counters"].items()
            if k.startswith("serve.")
        }
        return {
            "queue_depth": len(self.queue),
            "params": asdict(self.params),
            "registry": self.registry.describe(),
            "counters": counters,
            "latency": latency_percentiles(),
            "warm_start": self.warm_summary,
            "primed": list(self.primed),
        }

    # -- fleet surface ------------------------------------------------------

    def census(self) -> dict:
        """The sorted names this replica serves — the human half of the
        membership check (the bit-exact half is :meth:`signature`)."""
        d = self.registry.describe()
        return {
            "models": sorted(d["models"]),
            "systems": sorted(d["systems"]),
            "graphs": sorted(d["graphs"]),
        }

    def signature(self) -> int:
        """CRC32 of the canonical registry description.  Two replicas
        may join one fleet only when their signatures agree — the same
        fencing discipline as the elastic layer's partition signature
        (``streaming/elastic.py``): a fleet that silently mixed
        registries would route requests to replicas that resolve the
        same name to different models."""
        import json
        import zlib

        blob = json.dumps(
            self.registry.describe(), sort_keys=True, default=str
        )
        return zlib.crc32(blob.encode())

    def load_report(self) -> dict:
        """Everything the front-door router needs to place a request,
        in one snapshot: live queue pressure, per-key measured
        throughput (this process), the policy profile store's prior
        (survives restarts), what's primed, and the membership identity
        (census + signature).  Served over HTTP as ``/fleet`` and folded
        into ``/healthz`` as ``"load"``."""
        with self._stats_lock:
            throughput = {
                k: {
                    "requests": c,
                    "busy_s": round(s, 6),
                    "rows_per_s": round(c / s, 3) if s > 0 else None,
                }
                for k, (c, s) in self._key_stats.items()
            }
        report = {
            "queue_depth": len(self.queue),
            "max_queue": self.params.max_queue,
            "epoch": self.registry.epoch,
            "workers": max(1, self.params.workers),
            "worker_alive": any(t.is_alive() for t in self._threads),
            "throughput": throughput,
            "latency": latency_percentiles(),
            "primed": list(self.primed),
            "census": self.census(),
            "signature": self.signature(),
            # The fleet-wide hit-sharing plane: which placement keys this
            # replica already holds warm results for (and how its cache
            # is doing) — the router's tie-break reads "keys", so a hot
            # seed set costs the fleet ONE dispatch.
            "cache": self.cache.stats(),
            "tenants": self.queue.depth_by_tenant(),
        }
        try:
            from ..policy import profile as _profile

            view = _profile.load_entries()
        except Exception:  # noqa: BLE001 — profiles are advisory
            view = None
        if view:
            profiles = {
                k: e["throughput"]
                for k, e in view.get("entries", {}).items()
                if e.get("throughput")
            }
            if profiles:
                report["profiles"] = profiles
        return report

    # -- internals ----------------------------------------------------------

    def _tenant_label(self, tenant: str) -> str:
        """Bounded metric label for a client-controlled tenant key:
        the raw name while the label budget lasts, ``"other"`` after —
        counter-name cardinality stays capped no matter what an
        untrusted client sends."""
        with self._stats_lock:
            if tenant in self._metric_tenants:
                return tenant
            if len(self._metric_tenants) < self._metric_tenant_cap:
                self._metric_tenants.add(tenant)
                return tenant
        return "other"

    def _tenant_inc(self, tenant: str, what: str, n: int = 1) -> None:
        # Per-tenant counter names are f-strings — gate on the telemetry
        # switch so a disabled run stays allocation-free (the pinned
        # disabled-telemetry contract).  ``tenant`` here is always the
        # entry's bounded ``tenant_label``, never the raw client key.
        if telemetry.enabled():
            telemetry.inc(f"serve.tenant.{tenant}.{what}", n)

    def _tenant_observe(self, tenant: str, ms: float) -> None:
        if telemetry.enabled():
            telemetry.observe(f"serve.tenant.{tenant}.latency_ms", ms)

    def _stamp_cache_key(self, entry: Entry) -> None:
        """Compute the result-cache identity of a validated entry, or
        leave it None (uncacheable).  Cacheable: every idempotent read
        op.  NOT cacheable: fresh-sketch solves (each draws a unique
        counter-addressed sketch — the request is *defined* to differ),
        updates (mutations), ping/stats (answered inline already)."""
        if not self.cache.enabled:
            return
        op = entry.op
        if op == "ls_solve":
            if entry.request.get("fresh_sketch"):
                return
            src = entry.payload  # b AFTER retired-row zeroing
        elif op == "cond_est":
            src = ()
        elif op == "ppr":
            src = entry.payload  # canonical (seeds, alpha, gamma, eps)
        elif op == "ase_embed":
            src = (entry.payload, entry.squeeze)
        elif op == "predict":
            src = (
                entry.payload,
                bool(entry.request.get("labels")),
                entry.squeeze,
            )
        else:
            return
        entry.cache_key = (
            protocol.placement_key(entry.request),
            payload_digest(src),
            int(getattr(entry.entity, "epoch", 0)),
        )
        entry.cache_entity = (
            entry.request.get("system")
            or entry.request.get("model")
            or entry.request.get("graph")
        )

    def _validate(self, request: dict, fut: Future) -> Entry | None:
        op = request.get("op")
        if op == "ping":
            fut.set_result(
                protocol.ok_response(request.get("id"), "pong", {"events": []})
            )
            telemetry.inc("serve.ok")
            return None
        if op == "stats":
            fut.set_result(
                protocol.ok_response(
                    request.get("id"), self.stats(), {"events": []}
                )
            )
            telemetry.inc("serve.ok")
            return None
        if op == "ls_solve":
            system = self.registry.get_system(request.get("system"))
            self._check_epoch(request, system, "system")
            b = np.asarray(request.get("b"), np.float64)
            if b.ndim != 1 or b.shape[0] != system.m:
                raise InvalidParameters(
                    f"ls_solve b must be 1-D of length {system.m}, "
                    f"got shape {b.shape} (coalesce multi-RHS as "
                    "multiple requests)"
                )
            if system.retired:
                # Retired rows are zero in the held S·A; zeroing their b
                # entries drops them from the solve exactly (the caller's
                # other rows are untouched).
                b = b.copy()
                b[sorted(system.retired)] = 0.0
            ep = getattr(system, "epoch", 0)
            if request.get("fresh_sketch"):
                self._fresh_seq += 1
                key = ("ls", request["system"], ep, "fresh", self._fresh_seq)
            else:
                key = ("ls", request["system"], ep)
            entry = Entry(request, fut, key, op, payload=b)
            entry.entity = system
            return entry
        if op == "cond_est":
            # validate the name at the door; the executor serves the
            # system's cached sketched-spectrum report to the batch
            system = self.registry.get_system(request.get("system"))
            self._check_epoch(request, system, "system")
            entry = Entry(
                request, fut,
                ("cond", request["system"], getattr(system, "epoch", 0)),
                op, payload=np.zeros(0),
            )
            entry.entity = system
            return entry
        if op == "predict":
            model = self.registry.get_model(request.get("model"))
            self._check_epoch(request, model, "model")
            dtype = np.dtype(request.get("dtype", "float64"))
            x = np.asarray(request.get("x"), dtype)
            squeeze = x.ndim == 1
            if squeeze:
                x = x[None, :]
            d = getattr(model, "input_dim", None)
            if x.ndim != 2 or (d and x.shape[1] != int(d)):
                raise InvalidParameters(
                    f"predict x must be (r, {d or '?'}) or ({d or '?'},), "
                    f"got shape {np.asarray(request.get('x')).shape}"
                )
            if request.get("labels"):
                request["_classes"] = getattr(model, "classes", None)
            entry = Entry(
                request, fut,
                ("predict", request["model"], str(dtype),
                 getattr(model, "epoch", 0)),
                op, payload=x,
            )
            entry.squeeze = squeeze
            entry.entity = model
            return entry
        if op == "ppr":
            gsys = self.registry.get_graph(request.get("graph"))
            self._check_epoch(request, gsys, "graph")
            seeds = request.get("seeds")
            if not isinstance(seeds, (list, tuple)) or not seeds:
                raise InvalidParameters(
                    "ppr seeds must be a non-empty list of vertex "
                    f"ids/names, got {seeds!r}"
                )
            ids = self._graph_ids(gsys, seeds, "ppr seeds")
            # Canonical payload: the memo key in GraphSystem.ppr_report.
            # Sorting/deduping HERE means riders with the same seed set
            # in any order coalesce onto one diffusion.
            payload = (
                tuple(sorted(set(ids))),
                float(request.get("alpha", 0.85)),
                float(request.get("gamma", 5.0)),
                float(request.get("epsilon", 0.001)),
            )
            entry = Entry(
                request, fut,
                ("ppr", request["graph"], getattr(gsys, "epoch", 0)),
                op, payload=payload,
            )
            entry.entity = gsys
            return entry
        if op == "ase_embed":
            gsys = self.registry.get_graph(request.get("graph"))
            self._check_epoch(request, gsys, "graph")
            has_ids = "ids" in request
            has_nb = "neighbors" in request
            if has_ids == has_nb:
                raise InvalidParameters(
                    "ase_embed takes exactly one of 'ids' (embedding row "
                    "lookup) or 'neighbors' (out-of-sample projection)"
                )
            if has_ids:
                items = request["ids"]
                squeeze = not isinstance(items, (list, tuple))
                if squeeze:
                    items = [items]
                idx = self._graph_ids(gsys, items, "ase_embed ids")
                payload = ("rows", np.asarray(idx, np.int64))
            else:
                items = request["neighbors"]
                squeeze = False
                if not isinstance(items, (list, tuple)) or not items:
                    raise InvalidParameters(
                        "ase_embed neighbors must be a non-empty list of "
                        f"vertex ids/names, got {items!r}"
                    )
                idx = self._graph_ids(gsys, items, "ase_embed neighbors")
                payload = ("oos", np.asarray(idx, np.int64))
            entry = Entry(
                request, fut,
                ("ase", request["graph"], getattr(gsys, "epoch", 0)),
                op, payload=payload,
            )
            entry.squeeze = squeeze
            entry.entity = gsys
            return entry
        if op == "update":
            return self._validate_update(request, fut)
        raise InvalidParameters(
            f"unknown op {op!r}; supported: {list(protocol.OPS)}"
        )

    def _validate_update(self, request: dict, fut: Future) -> Entry:
        """Door validation for live-registry mutations.  The mutation
        itself runs in the WORKER (the update executor) — updates ride
        the same admission queue as traffic, so a coalesced batch that
        admitted before the update keeps its pinned pre-update version
        and everything admitted after sees the new epoch: the queue
        order IS the epoch order.  Each update gets a UNIQUE coalesce
        key: mutations must apply exactly once, so they never batch and
        never enter the solo-retry path."""
        targets = [t for t in ("graph", "system", "model") if t in request]
        if targets != ["graph"] and targets != ["system"]:
            raise InvalidParameters(
                "update takes exactly one target: 'graph' (with 'edges') "
                "or 'system' (with 'append' or 'drop'); model updates are "
                "a server-side API (Registry.update_model), got "
                f"targets {targets!r}"
            )
        if targets == ["graph"]:
            name = request["graph"]
            self.registry.get_graph(name)  # validate at the door
            edges = request.get("edges")
            if not isinstance(edges, (list, tuple)) or not all(
                isinstance(p, (list, tuple)) and len(p) == 2 for p in edges
            ):
                raise InvalidParameters(
                    "graph update needs 'edges': a list of (u, v) pairs, "
                    f"got {type(edges).__name__}"
                )
            payload = {"kind": "graph_fold", "name": name,
                       "edges": [tuple(p) for p in edges]}
        else:
            name = request["system"]
            self.registry.get_system(name)
            has_append = "append" in request
            if has_append == ("drop" in request):
                raise InvalidParameters(
                    "system update takes exactly one of 'append' (row "
                    "block) or 'drop' (row index list)"
                )
            if has_append:
                payload = {
                    "kind": "row_append", "name": name,
                    "rows": np.asarray(request["append"], np.float64),
                }
            else:
                payload = {
                    "kind": "row_downdate", "name": name,
                    "drop": [int(i) for i in request["drop"]],
                }
        self._fresh_seq += 1
        entry = Entry(
            request, fut, ("update", name, self._fresh_seq), "update",
            payload=payload,
        )
        idem = request.get("idem_key")
        if idem is not None:
            if not isinstance(idem, str) or not idem or len(idem) > 256:
                raise InvalidParameters(
                    "idem_key must be a non-empty string of at most 256 "
                    f"characters, got {idem!r}"
                )
            entry.idem_key = idem
        return entry

    def _check_epoch(self, request: dict, entity, kind: str) -> None:
        """The code-116 fence: a request may pin ``registry_epoch`` to
        demand the exact version it knows; if the entity has moved on
        (or has not reached that epoch), refuse with the two epochs in
        the envelope rather than serve silently-different bits."""
        want = request.get("registry_epoch")
        if want is None:
            return
        current = int(getattr(entity, "epoch", 0))
        if int(want) != current:
            telemetry.inc("registry.epoch.misses")
            raise RegistryEpochError(
                f"{kind} {getattr(entity, 'name', '?')!r} is at registry "
                f"epoch {current}, request pinned epoch {int(want)} — the "
                "pinned version is retired (or not yet minted)",
                requested=int(want), current=current,
                entity=getattr(entity, "name", None),
            )

    @staticmethod
    def _graph_ids(gsys, items, what: str) -> list:
        """Resolve a seed/id list to vertex ids at the door: ints are
        range-checked, anything else goes through the graph's name
        index — so executors never see an unresolvable vertex."""
        n = gsys.G.n
        ids = []
        for v in items:
            if isinstance(v, (int, np.integer)) and not isinstance(v, bool):
                i = int(v)
                if not (0 <= i < n):
                    raise InvalidParameters(
                        f"{what}: vertex id {i} outside [0, {n})"
                    )
            else:
                try:
                    i = gsys.G.index[v]
                except (KeyError, TypeError):
                    raise InvalidParameters(
                        f"{what}: unknown vertex {v!r} in graph "
                        f"{gsys.name!r}"
                    ) from None
            ids.append(i)
        return ids

    def _on_admit(self, entry: Entry) -> None:
        """Admission-ordered side effects, under the queue lock: the
        deadline stamp, and for fresh-sketch requests the counter
        reservation — the server context advances HERE, in admission
        order, so batching can never perturb the counter stream."""
        dm = entry.request.get(
            "deadline_ms", self.params.default_deadline_ms
        )
        if dm is not None:
            entry.deadline = entry.t_admit + float(dm) / 1e3
        if entry.op == "ls_solve" and entry.request.get("fresh_sketch"):
            system = self.registry.get_system(entry.request["system"])
            entry.counter_base = self.ctx.counter
            entry.sketch = type(system.S)(system.m, system.S.s, self.ctx)

    def _queue_state(self) -> dict:
        """Queue/latency context folded into shed envelopes (satellite of
        the observability plane): depth always; serve counters and the
        p50/p99 only when telemetry is on (they are empty otherwise)."""
        state: dict = {"depth": len(self.queue)}
        if telemetry.enabled():
            counters = telemetry.REGISTRY.snapshot()["counters"]
            for k in ("requests", "shed_admission", "shed_deadline"):
                v = counters.get(f"serve.{k}")
                if v:
                    state[k] = v
            state.update(latency_percentiles())
        return state

    def _resolve_error(
        self, entry: Entry, e: SkylarkError, status: str = "error"
    ) -> None:
        telemetry.inc("serve.errors")
        telemetry.finish_trace(
            entry.tctx, status, code=getattr(e, "code", 100)
        )
        entry.future.set_result(
            protocol.error_response(entry.request.get("id"), e, entry.trace)
        )

    def _worker(self, device=None) -> None:
        while True:
            batch = self.queue.take_batch(
                self.params.max_coalesce,
                self.params.coalesce_window_ms / 1e3,
            )
            if batch is None:
                return
            now = time.monotonic()
            phased = telemetry.phases_enabled()
            live = []
            for e in batch:
                waited_ms = (now - e.t_admit) * 1e3
                e.trace["queue_ms"] = round(waited_ms, 4)
                if e.deadline is not None and now > e.deadline:
                    telemetry.inc("serve.shed_deadline")
                    self._tenant_inc(e.tenant_label, "shed_deadline")
                    e.trace["events"].append(
                        {
                            "kind": "deadline_shed",
                            "waited_ms": round(waited_ms, 4),
                            **self._queue_state(),
                        }
                    )
                    exc = DeadlineExceededError(
                        "deadline expired before dispatch",
                        deadline_ms=e.request.get(
                            "deadline_ms",
                            self.params.default_deadline_ms,
                        ),
                        waited_ms=round(waited_ms, 4),
                    )
                    with telemetry.activate([e.tctx]):
                        telemetry.error_event(
                            "serve.deadline", exc, op=e.op
                        )
                    self._resolve_error(e, exc, status="shed_deadline")
                    # A deadline shed IS the saturation signal: its
                    # queue time joins the reservoir flagged shed=True.
                    record_latency(waited_ms, shed=True)
                    telemetry.observe_slo(
                        e.op, waited_ms, tenant=e.tenant_label, shed=True
                    )
                    continue
                telemetry.observe("serve.queue_ms", waited_ms)
                if phased and e.tctx is not None and e.t_pop is not None:
                    # Phase clock: the chained monotonic stamps make the
                    # phases sum to the request's end-to-end latency by
                    # construction (the batcher fills in the rest).
                    e.phases = {
                        "admit_wait": (e.t_pop - e.t_admit) * 1e3,
                        "coalesce_linger": (now - e.t_pop) * 1e3,
                        "_t_take": now,
                    }
                live.append(e)
            if not live:
                continue
            telemetry.inc("serve.batches")
            telemetry.observe("serve.batch_size", len(live))
            if len(live) > 1:
                telemetry.inc("serve.coalesced", len(live))
            t_exec = time.monotonic()
            try:
                batcher.run_batch(self.registry, live, device)
            except Exception as e:  # noqa: BLE001 — the worker must survive
                for entry in live:
                    if not entry.future.done():
                        self._resolve_error(
                            entry, SkylarkError(f"serve worker error: {e}")
                        )
            done = time.monotonic()
            self._fold_key_stats(live, done - t_exec)
            for e in live:
                ms = (done - e.t_admit) * 1e3
                telemetry.observe("serve.latency_ms", ms)
                record_latency(ms)
                telemetry.observe_slo(e.op, ms, tenant=e.tenant_label)
                self._tenant_observe(e.tenant_label, ms)
            # Roll the time-series ring forward (lazy tick: a no-op
            # until the window interval elapses, nothing when disabled).
            telemetry.timeline_tick(
                extra={"queue_depth": len(self.queue)}
            )

    def _fold_key_stats(self, live, busy_s: float) -> None:
        """Per-placement-key throughput accounting, fed by every batch
        regardless of the telemetry gate — the router's placement logic
        needs it even on telemetry-dark replicas.  One batch is one key
        (``take_batch`` coalesces same-key only)."""
        key = protocol.placement_key(live[0].request)
        with self._stats_lock:
            slot = self._key_stats.setdefault(key, [0, 0.0])
            slot[0] += len(live)
            slot[1] += busy_s
