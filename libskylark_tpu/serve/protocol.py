"""JSON wire protocol for the serve layer.

One request = one JSON object; one response = one JSON object.  The
interchange unit for anything sketch-shaped is the serialized-sketch
JSON — the same ~100-byte counter-addressed record the ``native/``
C-API parity surface exchanges (``NativeSketch.to_json``), so a C shim
or a foreign-language client speaks this protocol without new
marshalling.

Request schema::

    {"id": str|int,            # caller-chosen correlation id (optional)
     "op": "ls_solve" | "cond_est" | "predict" | "ppr" | "ase_embed"
           | "update" | "ping" | "stats",
     # ls_solve:
     "system": str,            # registered system name
     "b": [float, ...],        # RHS, length m
     "fresh_sketch": bool,     # per-request sketch from the server's
                               # counter stream (slow path; bitwise-
                               # addressable via trace.counter_base)
     # cond_est: {"system": str} — result is the system's cached
     # sketched-spectrum report {cond, sigma_max, sigma_min,
     # effective_rank, n, sketch_size}; coalesced riders share one probe
     # predict:
     "model": str,             # registered model name
     "x": [..] | [[..], ..],   # one row (d,) or a block (r, d)
     "labels": bool,           # decode through the model's classes
     # ppr: {"graph": str, "seeds": [id|name, ...],
     #       "alpha"/"gamma"/"epsilon": float (optional)} — result is
     # the memoized seed-set community report {graph, seeds, cluster,
     # conductance, alpha, gamma, epsilon}; same-seed riders share one
     # active-support diffusion
     # ase_embed: {"graph": str} plus EXACTLY ONE of
     #   "ids": id|name|[...]       — embedding row lookup
     #   "neighbors": [id|name,...] — out-of-sample projection from a
     #                                new vertex's neighbor list
     # update: live-registry mutation — EXACTLY ONE target of
     #   {"graph": str, "edges": [[u, v], ...]}       — edge fold
     #   {"system": str, "append": [[...], ...]}      — row append
     #   {"system": str, "drop": [int, ...]}          — row downdate
     # result is the minted epoch-ledger record {name, kind, epoch,
     # ...delta counts}; updates never coalesce and apply exactly once,
     # in admission order
     "idem_key": str,          # optional update idempotency key (≤256
                               # chars): the server's journal-backed
                               # dedup window keyed (tenant, idem_key)
                               # makes retried/failover-replayed updates
                               # apply EXACTLY once — a replayed key
                               # returns the originally minted epoch
                               # receipt instead of re-executing
     # either:
     "registry_epoch": int,    # pin to an exact registry version: served
                               # bitwise at that epoch, or refused with a
                               # code-116 RegistryEpochError envelope
                               # carrying {requested, current, entity}
     "deadline_ms": float}     # shed if not dispatched in time

Response schema::

    {"id": ...,
     "ok": true,  "result": ...,            # arrays as nested lists
     "trace": {"queue_ms", "exec_ms", "batch_size", "bucket",
               "coalesced", "events": [...], ...}}
    {"id": ...,
     "ok": false, "error": {"code": int,    # the 100-118 ladder
                            "type": str, "message": str},
     "trace": {...}}

Error codes ride ``utils.exceptions``: admission shed = 112
(``AdmissionError``), deadline shed = 113 (``DeadlineExceededError``),
retired registry version = 116 (``RegistryEpochError``), per-tenant
quota shed = 117 (``QuotaExceededError``, carrying
``{tenant, rate, burst, retry_after_ms}``), serve-probe numerical
failures = 108 (``NumericalHealthError``), write-ahead-journal damage
= 118 (``JournalError``, carrying ``{path, record, reason}``); foreign
exceptions degrade to the base code 100.

Requests may also carry ``"tenant": str`` — the QoS lane key (the HTTP
transport maps an ``X-Skylark-Tenant`` header onto it).  Absent tenant
means the default lane, preserved bitwise.
"""

from __future__ import annotations

import json

import numpy as np

from ..utils import exceptions as exc

__all__ = [
    "OPS",
    "decode",
    "encode",
    "error_payload",
    "error_response",
    "exception_for",
    "make_request",
    "ok_response",
    "placement_key",
    "raise_for_error",
]

OPS = ("ls_solve", "cond_est", "predict", "ppr", "ase_embed",
       "update", "ping", "stats")


def placement_key(request: dict) -> str:
    """The routing identity of a request — the string granularity at
    which the fleet router tracks affinity and replicas report
    throughput.  Mirrors the batcher's coalescing key (``Entry.key``
    minus the fresh-sketch suffix): requests sharing a placement key
    can share a fused dispatch, so the router sends them to the same
    replica to keep batches full."""
    op = request.get("op")
    if op == "ls_solve":
        return f"ls:{request.get('system')}"
    if op == "cond_est":
        return f"cond:{request.get('system')}"
    if op == "predict":
        return (
            f"predict:{request.get('model')}"
            f":{np.dtype(request.get('dtype', 'float64')).name}"
        )
    if op == "ppr":
        return f"ppr:{request.get('graph')}"
    if op == "ase_embed":
        return f"ase:{request.get('graph')}"
    if op == "update":
        name = (request.get("graph") or request.get("system")
                or request.get("model"))
        return f"update:{name}"
    return str(op)

# code -> exception class, for client-side re-raising (raise_for_error)
_CODE_CLASSES = {
    cls.code: cls
    for cls in vars(exc).values()
    if isinstance(cls, type) and issubclass(cls, exc.SkylarkError)
}


def make_request(op: str, *, id=None, **fields) -> dict:
    req = {"op": op, **fields}
    if id is not None:
        req["id"] = id
    return req


def _jsonable(obj):
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if hasattr(obj, "tolist"):  # jax arrays, np scalars
        return obj.tolist()
    return str(obj)


def encode(obj: dict) -> str:
    """One JSON line (arrays as nested lists, no trailing newline)."""
    return json.dumps(obj, default=_jsonable)


def decode(line: str) -> dict:
    obj = json.loads(line)
    if not isinstance(obj, dict):
        raise exc.InvalidParameters(
            f"protocol frame must be a JSON object, got {type(obj).__name__}"
        )
    return obj


def error_payload(e: BaseException) -> dict:
    """The structured error envelope: stable code + type + message."""
    payload = {
        "code": int(getattr(e, "code", exc.SkylarkError.code)),
        "type": type(e).__name__,
        "message": str(e),
    }
    for attr in (
        "queue_depth", "max_depth", "deadline_ms", "waited_ms", "stage",
        "requested", "current", "entity", "tenant", "rate", "burst",
        "retry_after_ms", "path", "record", "reason",
    ):
        v = getattr(e, attr, None)
        if v is not None:
            payload[attr] = v
    report = getattr(e, "report", None)
    if report is not None:
        to_dict = getattr(report, "to_dict", None)
        payload["recovery"] = to_dict() if callable(to_dict) else report
    return payload


def ok_response(req_id, result, trace: dict) -> dict:
    return {"id": req_id, "ok": True, "result": result, "trace": trace}


def error_response(req_id, e: BaseException, trace: dict) -> dict:
    return {
        "id": req_id,
        "ok": False,
        "error": error_payload(e),
        "trace": trace,
    }


def exception_for(payload: dict) -> exc.SkylarkError:
    """Rebuild the closest exception class from an error envelope."""
    cls = _CODE_CLASSES.get(int(payload.get("code", 100)), exc.SkylarkError)
    try:
        return cls(payload.get("message", "serve error"))
    except TypeError:  # classes with mandatory extra args
        return exc.SkylarkError(payload.get("message", "serve error"))


def raise_for_error(response: dict) -> dict:
    """Pass an ok response through; raise the mapped exception otherwise."""
    if response.get("ok"):
        return response
    raise exception_for(response.get("error") or {})
