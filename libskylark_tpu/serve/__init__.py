"""High-throughput sketch-serving: cross-request coalescing onto warm plans.

The layer that makes the plan cache (``plans/``), policy warm start
(``policy/``), and fused kernels pay rent under "millions of users"
traffic (ROADMAP north-star): a long-lived, multi-tenant in-process
solve service whose hot path coalesces concurrent requests that hash to
the same (serialized sketch, abstract signature) key into ONE padded,
plan-compiled dispatch — N single-row requests cost one executable
launch instead of N — then de-pads and fans the results back out,
bit-identical per request to serving them one at a time.

Layout (see ``docs/serving.md``):

- :mod:`.protocol` — the JSON frames (native-parity interchange);
- :mod:`.admission` — bounded queue with deficit-weighted round-robin
  tenant lanes, depth/deadline shedding (error codes 112/113 on the
  ``utils.exceptions`` ladder);
- :mod:`.cache` — the versioned, bounded front-door result cache
  (keyed on placement key + canonical payload digest + registry epoch;
  hits cost zero device work, invalidation rides the epoch mint);
- :mod:`.qos` — tenant keys, weighted-fair lane config, token-bucket
  quotas (code-117 ``QuotaExceededError`` sheds);
- :mod:`.registry` — models + LS systems, loaded once, device-resident;
- :mod:`.journal` — the durability layer: a CRC-framed write-ahead
  journal every registry mint appends to (fsync'd) BEFORE it
  publishes, snapshot compaction into a ``CheckpointStore`` slot, and
  ``Registry.recover`` — bitwise-identical crash recovery plus the
  journal-backed idempotency window that makes ``op:"update"``
  exactly-once across router failover (code-118 ``JournalError``);
- :mod:`.batcher` — the coalescing executors + solo-retry fault
  isolation (code-108 structured degradation, batch-mates unaffected);
- :mod:`.server` — the worker loop (``workers=K`` pins K batcher
  threads to disjoint devices), warm start, telemetry;
- :mod:`.dispatch` — probe-verified device-parallel dispatch: batches
  whose padded rung clears the flop gate run their heavy half sharded
  over every local chip, bitwise-identical to single-device by
  construction;
- :mod:`.router` — the fleet front door: signature-fenced membership,
  profile-aware placement (key affinity → coalescing), 112/114
  shedding, heartbeat ejection with in-flight re-placement;
- :mod:`.transport` / :mod:`.client` — stdio + HTTP/1.1 keep-alive
  fronts and the Python client (``skylark-serve`` is the CLI wrapper);
- :mod:`.autoscale` — the chaos-tested membership control loop: spawns
  replicas against queue-depth/p99 targets (prime-before-placeable,
  join-fenced) and drains idle ones to zero in-flight before they
  leave; registries are LIVE — epoch-versioned edge folds, row
  appends/downdates (code 116 for retired-epoch pins), with in-flight
  batches pinned bitwise to the version they admitted under.
"""

from .admission import AdmissionQueue, Entry
from .autoscale import AutoscaleParams, Autoscaler
from .cache import ResultCache, payload_crc, payload_digest
from .client import Client
from .qos import (
    DEFAULT_TENANT,
    LaneConfig,
    TenantQuotas,
    TokenBucket,
    tenant_of,
)
from .protocol import (
    decode,
    encode,
    error_payload,
    error_response,
    exception_for,
    make_request,
    ok_response,
    placement_key,
    raise_for_error,
)
from .journal import Journal
from .registry import GraphSystem, LSSystem, Registry
from .router import (
    HttpReplica,
    InProcessReplica,
    Router,
    RouterParams,
    choose_replica,
)
from .server import ServeParams, Server, latency_percentiles, record_latency
from .transport import serve_http, serve_stdio

__all__ = [
    "AdmissionQueue",
    "AutoscaleParams",
    "Autoscaler",
    "Client",
    "DEFAULT_TENANT",
    "Entry",
    "GraphSystem",
    "HttpReplica",
    "InProcessReplica",
    "Journal",
    "LSSystem",
    "LaneConfig",
    "Registry",
    "ResultCache",
    "Router",
    "RouterParams",
    "ServeParams",
    "Server",
    "TenantQuotas",
    "TokenBucket",
    "choose_replica",
    "decode",
    "encode",
    "error_payload",
    "error_response",
    "exception_for",
    "latency_percentiles",
    "make_request",
    "ok_response",
    "payload_crc",
    "payload_digest",
    "placement_key",
    "raise_for_error",
    "record_latency",
    "serve_http",
    "serve_stdio",
    "tenant_of",
]
