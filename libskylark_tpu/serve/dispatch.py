"""Device-parallel dispatch: one coalesced batch, every local chip.

The serve executors (``batcher.py``) run one fused program per batch on
whatever device the worker thread is pinned to.  For batches whose
padded rung clears a flop gate, this module reroutes the heavy half of
the executor — the sketch apply for LS-solve, the feature-map /
Gram-matrix block for predict — through a ``shard_map`` program over the
batch axis, so a single dispatch uses every local device instead of one
(the serving answer to the reference's one-engine-many-clients ``capi/``
surface).  The light half (the (s, kb) triangular solve, the Z·W
coefficient matmul) stays on the worker's device, UNCHANGED from the
single-device path — which is what makes the parity argument short.

Schedules (both communication-free — no psum ever reorders a sum):

- LS-solve shards the RHS **column** (batch) axis through
  ``parallel.collectives.batch_sharded_program``: each shard applies the
  FULL sketch to its column block (contrast ``columnwise_sharded``,
  which splits the contraction and merges with a psum — approximate by
  construction).  Widths keep the batcher's lane-uniform sub-ladder:
  ``d | kb`` AND ``(kb / d) % 8 == 0``.
- Predict shards the **row** (request) axis — the
  ``rowwise_sharded`` schedule — under the same width gate.

Bit-parity contract — VERIFIED, not assumed.  Per-slot purity makes
each output slot depend only on its own input slot, but XLA's CPU
kernels (gemm micro-kernel tiling, pocketfft batch vectorization) pick
accumulation schedules BY OPERAND WIDTH, so a kb-wide program and d
(kb/d)-wide programs agree bitwise only for some (transform, geometry,
dtype) combinations — measured, not derivable.  So the first dispatch
of every (anchor, rung, d, dtype) program is a **parity probe**: it
runs the sharded program AND the caller's single-device reference on
the live batch, compares bits, and caches the verdict.  A matching
program serves sharded from then on; a mismatch tombstones the program
and the executor keeps its single-device path.  Either way the caller
returns single-device bits on the probe call — sharded dispatch is
bitwise-identical to single-device dispatch by construction.

Gates, in the ``sketch/pallas_window.py`` idiom:

- :func:`supported`: hard feasibility (device count divides the rung,
  lane-uniform shard width).  Honored even when forced.
- :func:`worthwhile`: amortization — enough flops in the heavy half to
  pay the cross-device staging.  ``SKYLARK_SERVE_SHARD=1`` forces the
  route past this gate (tests, benchmarks); ``=0`` disables it
  entirely (bit-for-bit the PR-10 executor, probes and all); unset =
  auto.  ``SKYLARK_SERVE_SHARD_MIN_FLOPS`` overrides the threshold.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry
from ..parallel.collectives import _shard_map_fn, batch_sharded_program
from ..sketch.base import Dimension

_shard_map = _shard_map_fn()

__all__ = [
    "supported",
    "worthwhile",
    "shard_devices",
    "maybe_sketch_sharded",
    "maybe_feature_sharded",
    "maybe_kernel_sharded",
    "clear_cache",
]

# Default amortization floor: below ~3e7 flops in the heavy half, the
# per-shard dispatch + resharding overhead eats the win on every
# backend we measured.  Env-overridable for hardware with a different
# crossover (and mooted by SKYLARK_SERVE_SHARD=1 in tests/benches).
_MIN_FLOPS = 3e7

_AXIS = "serve_batch"

# (id(anchor), kind, kb, d, dtype) -> [anchor, program, verdict].  The
# anchor (sketch / model) is kept strongly referenced so the id key can
# never be recycled under us; the population is bounded by the registry
# census × rung ladder × device splits — the same budget Server.prime
# compiles.  verdict: None = unprobed, True = parity held (serve
# sharded), False = tombstoned (single-device forever).
_PROGRAMS: dict = {}


def clear_cache() -> None:
    _PROGRAMS.clear()


def supported(kb: int, d: int) -> bool:
    """Can a kb-wide rung split over d devices without leaving the
    lane-uniform sub-ladder (shard width a multiple of the base rung)?"""
    return d >= 2 and kb % d == 0 and (kb // d) % 8 == 0


def worthwhile(flops: float) -> bool:
    """Amortization gate for the AUTO route (forced mode skips it)."""
    floor = _MIN_FLOPS
    env = os.environ.get("SKYLARK_SERVE_SHARD_MIN_FLOPS")
    if env:
        try:
            floor = float(env)
        except ValueError:
            pass
    return flops >= floor


def shard_devices(kb: int, flops: float):
    """The device list a kb-wide dispatch may shard over, or ``None``.

    Largest feasible split wins (every chip busy beats a tidy factor);
    ``None`` whenever the gates say the single-device path should run.
    """
    mode = os.environ.get("SKYLARK_SERVE_SHARD", "")
    if mode == "0":
        return None
    if mode != "1" and not worthwhile(flops):
        return None
    devs = jax.local_devices()
    for d in range(len(devs), 1, -1):
        if supported(kb, d):
            return devs[:d]
    return None


def _dispatch_sharded(anchor, kind, kb, devs, dtype, build, x, spec,
                      reference, rows, entries):
    """Shared probe-then-serve core.  Returns the result the caller
    must use, or ``None`` (tombstoned / never feasible) meaning "run
    your single-device path yourself"."""
    key = (id(anchor), kind, kb, len(devs), str(dtype))
    slot = _PROGRAMS.get(key)
    if slot is None:
        mesh = Mesh(np.array(devs), (_AXIS,))
        slot = [anchor, jax.jit(build(mesh)), None]
        _PROGRAMS[key] = slot
    _, prog, verdict = slot
    if verdict is False:
        return None
    # Explicit reshard first: the worker thread may hand us an array
    # committed to its pinned device, which a jitted shard_map would
    # reject as an incompatible-devices error instead of moving.
    mesh = Mesh(np.array(devs), (_AXIS,))
    xs = jax.device_put(x, NamedSharding(mesh, spec))
    out = prog(xs)
    if verdict is None:
        ref = reference()
        a = np.asarray(out)
        b = np.asarray(ref)
        if rows is not None:  # padding rows are garbage on both routes
            a, b = a[:rows], b[:rows]
        match = bool(np.array_equal(a, b))
        slot[2] = match
        telemetry.inc(
            "serve.sharded_verified" if match else "serve.sharded_rejected"
        )
        telemetry.event(
            "serve", "sharded_probe",
            {"kind": kind, "bucket": kb, "devices": len(devs),
             "match": match},
        )
        for e in entries or ():
            e.trace["events"].append(
                {"kind": "sharded_probe", "op": kind,
                 "devices": len(devs), "match": match}
            )
        if not match:
            return None
        # Parity held: the sharded bits ARE the reference bits; hand
        # back the reference object so the probe call is free of doubt.
        return ref
    telemetry.inc("serve.sharded_dispatch")
    for e in entries or ():
        e.trace["events"].append(
            {"kind": "sharded", "op": kind, "devices": len(devs)}
        )
    return out


def maybe_sketch_sharded(S, B, kb: int, entries=None, reference=None):
    """S·B with B's kb columns (the coalesced RHS batch) sharded over
    local devices; ``None`` when the gates (or a failed parity probe)
    say stay single-device.  ``B`` is the (m, kb) padded block, already
    dtype-cast; ``reference`` computes the single-device S·B for the
    probe."""
    m = B.shape[0]
    devs = shard_devices(kb, 2.0 * m * S.s * kb)
    if devs is None:
        return None

    def build(mesh):
        def local(b):
            return S.apply(b, Dimension.COLUMNWISE)

        return batch_sharded_program(local, mesh)

    return _dispatch_sharded(
        S, "ls", kb, devs, B.dtype, build, B, P(None, _AXIS),
        reference, None, entries,
    )


def maybe_feature_sharded(model, Xp, true_rows: int, entries=None,
                          reference=None):
    """The feature-map block Z of a predict batch, rows (requests)
    sharded; ``None`` when gated off or tombstoned.  Mirrors the
    planned ``_feature_map_predict`` math; the probe compares true rows
    only (padding rows are zeroed on the planned route, garbage here —
    both die at the caller's slice)."""
    maps = getattr(model, "maps", None)
    if not maps:
        return None
    kb, d_in = Xp.shape
    flops = 2.0 * kb * d_in * sum(s.s for s in maps)
    devs = shard_devices(kb, flops)
    if devs is None:
        return None

    def build(mesh):
        axes = tuple(mesh.axis_names)

        def local(x):
            blocks = []
            for s in maps:
                Z = s.apply(x, Dimension.ROWWISE)
                if model.scale_maps:
                    Z = Z * jnp.asarray(
                        np.sqrt(Z.shape[-1] / d_in), Z.dtype
                    )
                blocks.append(Z)
            return jnp.concatenate(blocks, axis=-1)

        return _shard_map(
            local, mesh=mesh, in_specs=P(axes, None),
            out_specs=P(axes, None), check_rep=False,
        )

    return _dispatch_sharded(
        model, "predict", kb, devs, Xp.dtype, build, jnp.asarray(Xp),
        P(_AXIS, None), reference, true_rows, entries,
    )


def maybe_kernel_sharded(model, Xp, true_rows: int, entries=None,
                         reference=None):
    """Gram-matrix predict with query rows sharded; ``None`` when gated
    off or tombstoned.  Returns the full padded (kb, t) output — the
    caller slices true rows."""
    if not hasattr(model, "kernel"):
        return None
    kb, d_in = Xp.shape
    n_train = model.X_train.shape[0]
    devs = shard_devices(kb, 2.0 * kb * n_train * d_in)
    if devs is None:
        return None

    def build(mesh):
        axes = tuple(mesh.axis_names)

        def local(x):
            return model.kernel.gram(x, model.X_train) @ model.A

        return _shard_map(
            local, mesh=mesh, in_specs=P(axes, None),
            out_specs=P(axes, None), check_rep=False,
        )

    return _dispatch_sharded(
        model, "kernel", kb, devs, Xp.dtype, build, jnp.asarray(Xp),
        P(_AXIS, None), reference, true_rows, entries,
    )
