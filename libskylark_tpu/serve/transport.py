"""Process-boundary transports: JSON-lines stdio and HTTP loopback.

Both speak the exact :mod:`.protocol` frames — the serialized-sketch /
JSON parity contract the ``native/`` C-API surface uses — so any
language that can write a JSON line can drive a server.

- :func:`serve_stdio` — one request per input line, one response per
  output line, in order.  The systemd/inetd-style deployment: a parent
  process owns the pipe pair.
- :func:`serve_http` — a loopback ``ThreadingHTTPServer``: ``POST /``
  with a request object (or a list of them — submitted concurrently,
  answered as a list, which is how a remote caller reaches the
  coalescer), ``GET /stats``, ``GET /healthz``.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import protocol

__all__ = ["serve_stdio", "serve_http"]


def serve_stdio(server, in_stream, out_stream) -> int:
    """Drain ``in_stream`` line-by-line until EOF; returns the number of
    requests served.  Malformed lines get a structured error response
    (the stream stays usable)."""
    served = 0
    for line in in_stream:
        line = line.strip()
        if not line:
            continue
        try:
            request = protocol.decode(line)
        except Exception as e:  # noqa: BLE001 — bad frame, keep serving
            out_stream.write(
                protocol.encode(
                    protocol.error_response(None, e, {"events": []})
                ) + "\n"
            )
            out_stream.flush()
            continue
        response = server.call(request)
        out_stream.write(protocol.encode(response) + "\n")
        out_stream.flush()
        served += 1
    return served


class _Handler(BaseHTTPRequestHandler):
    server_version = "skylark-serve"

    def log_message(self, *args):  # quiet: telemetry owns observability
        pass

    def _send(self, code: int, obj) -> None:
        body = protocol.encode(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv = self.server.skylark_server
        if self.path == "/healthz":
            self._send(200, {"ok": True})
        elif self.path == "/stats":
            self._send(200, srv.stats())
        else:
            self._send(404, {"ok": False, "error": {"message": "not found"}})

    def do_POST(self):
        srv = self.server.skylark_server
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
        except Exception as e:  # noqa: BLE001 — bad frame
            self._send(
                400, protocol.error_response(None, e, {"events": []})
            )
            return
        if isinstance(payload, list):
            # concurrent submission IS the point: a remote batch rides
            # the same cross-request coalescer in-process callers hit
            futures = [srv.submit(r) for r in payload]
            self._send(200, [f.result() for f in futures])
        else:
            self._send(200, srv.call(payload))


def serve_http(server, host: str = "127.0.0.1", port: int = 0):
    """Bind a loopback HTTP front end; returns the ``ThreadingHTTPServer``
    (``.server_address`` has the bound port; call ``serve_forever`` /
    ``shutdown`` to run and stop it)."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.skylark_server = server
    return httpd
