"""Process-boundary transports: JSON-lines stdio and HTTP loopback.

Both speak the exact :mod:`.protocol` frames — the serialized-sketch /
JSON parity contract the ``native/`` C-API surface uses — so any
language that can write a JSON line can drive a server.

- :func:`serve_stdio` — one request per input line, one response per
  output line, in order.  The systemd/inetd-style deployment: a parent
  process owns the pipe pair.
- :func:`serve_http` — a loopback ``ThreadingHTTPServer``: ``POST /``
  with a request object (or a list of them — submitted concurrently,
  answered as a list, which is how a remote caller reaches the
  coalescer), plus the read-only observability surface: ``GET /stats``,
  ``GET /healthz`` (resolved backend, registry census, primed rungs),
  ``GET /metrics`` (Prometheus text format 0.0.4),
  ``GET /traces`` (flight-recorder ids; ``?drain=1`` removes what it
  returns) and ``GET /traces/<id>`` (one trace, JSON).

Every GET is served from snapshots/copies taken under the telemetry
locks — scrapes never block the worker thread and can never observe a
torn registry (pinned by the concurrent-scrape test in
``tests/test_trace.py``).
"""

from __future__ import annotations

import json
import os
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import telemetry
from . import protocol

__all__ = ["serve_stdio", "serve_http"]


def serve_stdio(server, in_stream, out_stream) -> int:
    """Drain ``in_stream`` line-by-line until EOF; returns the number of
    requests served.  Malformed lines get a structured error response
    (the stream stays usable)."""
    served = 0
    for line in in_stream:
        line = line.strip()
        if not line:
            continue
        try:
            request = protocol.decode(line)
        except Exception as e:  # noqa: BLE001 — bad frame, keep serving
            out_stream.write(
                protocol.encode(
                    protocol.error_response(None, e, {"events": []})
                ) + "\n"
            )
            out_stream.flush()
            continue
        response = server.call(request)
        out_stream.write(protocol.encode(response) + "\n")
        out_stream.flush()
        served += 1
    return served


def _healthz(srv) -> dict:
    """Liveness + identity: which backend actually resolved, how much is
    registered, whether the compile ladder is primed — the three facts a
    probe needs to tell 'up' from 'up but will stall mid-traffic'.  For
    fleet members the full load report rides along as ``"load"`` (queue
    pressure, per-key throughput, census signature) — one GET is both
    the probe and the router's heartbeat."""
    try:
        import jax

        backend = str(jax.default_backend())
    except Exception:  # noqa: BLE001 — health must answer even so
        backend = "unknown"
    out = {"ok": True, "backend": backend, "telemetry": telemetry.enabled()}
    registry = getattr(srv, "registry", None)
    if registry is not None:  # a Server (a Router front door has none)
        out["registry"] = {
            "models": len(registry.models),
            "systems": len(registry.systems),
        }
        out["primed"] = list(srv.primed)
        out["worker_alive"] = (
            srv._thread is not None and srv._thread.is_alive()
        )
    if hasattr(srv, "load_report"):
        out["load"] = srv.load_report()
    if hasattr(srv, "fleet_report"):
        out["fleet"] = srv.fleet_report()
    scaler = getattr(srv, "autoscaler", None)
    if scaler is not None:  # a Router front door with a control loop
        out["autoscale"] = scaler.report()
    return out


class _Handler(BaseHTTPRequestHandler):
    server_version = "skylark-serve"
    # Keep-alive: HTTP/1.0 (the BaseHTTPRequestHandler default) closes
    # the socket per response, making every ~100-byte frame pay a TCP
    # handshake; every _send path always sets Content-Length, which is
    # what HTTP/1.1 persistence requires.
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # quiet: telemetry owns observability
        pass

    def _send(self, code: int, obj) -> None:
        body = protocol.encode(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str, content_type: str) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv = self.server.skylark_server
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            self._send(200, _healthz(srv))
        elif path == "/stats":
            self._send(200, srv.stats())
        elif path == "/fleet":
            # A Router front door answers with the membership table; a
            # plain Server answers with its own load report, so one
            # probe URL works against either end of the fleet.
            if hasattr(srv, "fleet_report"):
                self._send(200, srv.fleet_report())
            elif hasattr(srv, "load_report"):
                self._send(200, srv.load_report())
            else:
                self._send(
                    404, {"ok": False, "error": {"message": "not found"}}
                )
        elif path == "/metrics":
            from ..telemetry.exposition import CONTENT_TYPE

            queue = getattr(srv, "queue", None)
            self._send_text(
                200,
                telemetry.prometheus_text(
                    extra_gauges={"serve_queue_depth": len(queue)}
                    if queue is not None else None
                ),
                CONTENT_TYPE,
            )
        elif path == "/slo":
            # Error-budget state for every declared objective (empty
            # objectives dict when SKYLARK_SLO is unset — the endpoint
            # answers either way so probes can distinguish "no SLOs"
            # from "old replica without the endpoint").
            self._send(200, {
                "objectives": telemetry.slo_report(),
                "slo_spec": os.environ.get("SKYLARK_SLO") or "",
            })
        elif path == "/timeline":
            # Serving the ring also rolls it forward: an idle replica
            # still closes windows when scraped.
            queue = getattr(srv, "queue", None)
            telemetry.timeline_tick(
                extra={"queue_depth": len(queue)}
                if queue is not None else None
            )
            self._send(200, telemetry.timeline_state())
        elif path == "/traces":
            if "drain=1" in query.split("&"):
                self._send(200, telemetry.drain_traces())
            else:
                self._send(200, telemetry.trace_ids())
        elif path.startswith("/traces/"):
            trace = telemetry.get_trace(path[len("/traces/"):])
            if trace is None:
                self._send(
                    404, {"ok": False, "error": {"message": "unknown trace"}}
                )
            else:
                self._send(200, trace)
        else:
            self._send(404, {"ok": False, "error": {"message": "not found"}})

    def do_POST(self):
        srv = self.server.skylark_server
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
        except Exception as e:  # noqa: BLE001 — bad frame
            self._send(
                400, protocol.error_response(None, e, {"events": []})
            )
            return
        if self.path.partition("?")[0] == "/join":
            # Fleet membership: a replica announces itself to a Router
            # front door.  Signature mismatches come back as structured
            # code-109 envelopes (HTTP 409), not stack traces.
            if not hasattr(srv, "handle_join"):
                self._send(
                    404, {"ok": False, "error": {"message": "not a router"}}
                )
            else:
                try:
                    self._send(200, {"ok": True, **srv.handle_join(payload)})
                except Exception as e:  # noqa: BLE001 — structured join errors
                    self._send(
                        409, protocol.error_response(None, e, {"events": []})
                    )
            return
        # QoS lane key from the wire: an X-Skylark-Tenant header stamps
        # every request in the body that doesn't already carry its own
        # "tenant" field (payload wins — the header is the transport-
        # level default, e.g. one gateway per tenant).
        tenant = self.headers.get("X-Skylark-Tenant")
        if tenant:
            if isinstance(payload, dict):
                payload.setdefault("tenant", tenant)
            elif isinstance(payload, list):
                for r in payload:
                    if isinstance(r, dict):
                        r.setdefault("tenant", tenant)
        if isinstance(payload, list):
            # concurrent submission IS the point: a remote batch rides
            # the same cross-request coalescer in-process callers hit
            futures = [srv.submit(r) for r in payload]
            self._send(200, [f.result() for f in futures])
        else:
            self._send(200, srv.call(payload))


def serve_http(server, host: str = "127.0.0.1", port: int = 0):
    """Bind a loopback HTTP front end; returns the ``ThreadingHTTPServer``
    (``.server_address`` has the bound port; call ``serve_forever`` /
    ``shutdown`` to run and stop it)."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.skylark_server = server
    return httpd
