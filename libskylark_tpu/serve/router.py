"""The fleet front door: profile-aware routing over serving replicas.

One :class:`Router` owns a membership table of replicas — in-process
:class:`~.server.Server` objects and/or remote HTTP servers — and
places each request on one of them.  Placement is a PURE function of
the request's :func:`~.protocol.placement_key` and the frozen load
reports (:func:`choose_replica` — deterministic, unit-testable with
hand-built reports):

1. **Key affinity first.**  Requests sharing a placement key coalesce
   into one fused dispatch only if they land on the same replica, so
   the router keeps a key→replica affinity map and honors it while the
   replica stays placeable and unsaturated.  Affinity is what makes a
   fleet of K replicas behave like K independent coalescers rather
   than one diluted one.
2. **Profile-aware spill.**  A new (or evicted) key goes to the
   unsaturated replica with the lowest live queue depth, ties broken
   by measured per-key throughput — the replica's own ``load_report``
   numbers first, the policy profile store's prior (which survives
   restarts) when the replica hasn't served the key yet — then by name
   for determinism.
3. **Shed at the door.**  When every placeable replica reports a full
   queue, the router sheds with the same code-112
   :class:`~..utils.exceptions.AdmissionError` envelope a single
   server's admission queue uses: one backoff discipline fleet-wide.

Membership rides the elastic layer's fencing discipline
(``streaming/elastic.py``): every replica carries a registry
*signature* (CRC32 of its canonical census) and the fleet admits a
joiner only on signature match — a code-109
:class:`~..utils.exceptions.WorldMismatchError` otherwise, because a
fleet that silently mixed registries would resolve one model name to
different models.  Every membership change bumps the fleet *epoch*
(placement decisions are stamped with it).  A replica whose heartbeat
goes stale past the timeout is ejected — code 114,
:class:`~..utils.exceptions.ReplicaLostError` — its affinity entries
dropped, and requests that were in flight to it are transparently
re-placed on the survivors; 114 reaches a caller only when no
placeable replica remains.

Zero-downtime join: :meth:`Router.join` marks a member placeable only
once its load report shows a live worker — and :meth:`Server.start`
primes the plan-cache ladder *before* spawning workers, so a joining
replica can never receive traffic it would stall on compiling.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field

from .. import telemetry
from ..utils.exceptions import (
    AdmissionError,
    ReplicaLostError,
    WorldMismatchError,
)
from . import protocol

__all__ = [
    "HttpReplica",
    "InProcessReplica",
    "Router",
    "RouterParams",
    "choose_replica",
]


class InProcessReplica:
    """A same-process :class:`~.server.Server` as a fleet member."""

    def __init__(self, name: str, server):
        self.name = name
        self.server = server

    def submit(self, request: dict) -> Future:
        return self.server.submit(request)

    def load_report(self) -> dict:
        return self.server.load_report()


class HttpReplica:
    """A remote server (``serve_http`` front end) as a fleet member.

    ``submit`` runs the blocking HTTP call on the router's pool so the
    router thread never blocks on a slow replica; a transport-level
    failure surfaces as the future's exception, which the router's
    failover path converts into ejection + re-placement."""

    def __init__(self, name: str, url: str, *, timeout: float | None = None,
                 pool: ThreadPoolExecutor | None = None,
                 retries: int = 3, backoff: float = 0.05):
        import random

        from .client import Client

        self.name = name
        self.url = url.rstrip("/")
        # timeout=None defers to SKYLARK_HTTP_TIMEOUT_S (default 60s,
        # bounded): a hung replica's recv must raise so the failover /
        # ejection ladder (114) can run instead of wedging this thread.
        self._client = Client(url=url, timeout=timeout)
        self._pool = pool
        self.retries = int(retries)
        self.backoff = float(backoff)
        self._sleep = time.sleep  # injectable: tests skip the real wait
        self._jitter = random.random  # likewise

    def submit(self, request: dict) -> Future:
        if self._pool is None:
            fut: Future = Future()
            try:
                fut.set_result(self._client.call(request))
            except Exception as e:  # noqa: BLE001 — transport loss
                fut.set_exception(e)
            return fut
        return self._pool.submit(self._client.call, request)

    def load_report(self) -> dict:
        """Fetch the replica's load report, retrying transient transport
        errors with jittered exponential backoff (the ``FsspecSource.
        open`` ladder): ONE dropped connection must not read as a dead
        heartbeat — ejection is for real silence, which the poll loop
        measures against ``heartbeat_timeout_s``, not for a flaky TCP
        accept."""
        attempt = 0
        while True:
            try:
                health = self._client.healthz()
                break
            except Exception as e:  # noqa: BLE001 — transport loss
                if isinstance(e, TimeoutError):
                    # A hung (not dead) replica: recv hit the bounded
                    # socket timeout.  Counted separately from generic
                    # retries — a fleet where these climb has replicas
                    # wedged in compute, not a flaky network.
                    telemetry.inc("router.report_timeouts")
                if attempt >= self.retries:
                    raise
                # Full jitter on the exponential step: a fleet's router
                # re-polling K replicas must not thunder in lockstep.
                delay = self.backoff * (2**attempt) * (0.5 + self._jitter())
                if telemetry.enabled():
                    telemetry.inc("router.report_retries")
                    telemetry.event(
                        "router", "report_retry",
                        {
                            "replica": self.name,
                            "attempt": attempt + 1,
                            "delay": round(delay, 4),
                            "error": f"{type(e).__name__}: {e}"[:200],
                        },
                    )
                self._sleep(delay)
                attempt += 1
        load = health.get("load")
        if not isinstance(load, dict):
            raise ReplicaLostError(
                f"replica {self.name} reports no load (old server?)",
                replica=self.name,
            )
        return load


@dataclass
class RouterParams:
    """Fleet knobs.

    - ``heartbeat_interval_s``: background load-report poll period;
      ``0`` (default) disables the thread — callers (and tests) drive
      :meth:`Router.poll_once` themselves, deterministically.
    - ``heartbeat_timeout_s``: a member whose last successful report is
      older than this is ejected (code 114).
    - ``max_failover``: in-flight re-placements one request may ride
      before the router gives up with :class:`ReplicaLostError`.
    """

    heartbeat_interval_s: float = 0.0
    heartbeat_timeout_s: float = 5.0
    max_failover: int = 2


@dataclass
class _Member:
    name: str
    replica: object
    report: dict = field(default_factory=dict)
    last_heartbeat: float = 0.0
    placeable: bool = False
    # Draining members stay in the fleet (their in-flight work finishes,
    # their reports keep flowing) but take no NEW placements; the
    # autoscaler removes them once their queue reads zero.  A drain is a
    # deliberate decision, never a fault — no 114 is minted for it.
    draining: bool = False


def _saturated(report: dict) -> bool:
    depth = report.get("queue_depth")
    cap = report.get("max_queue")
    return depth is not None and cap is not None and depth >= cap


def _key_throughput(report: dict, key: str) -> float:
    """The replica's expected speed on this key: its own measurement
    when it has served the key, else the policy profile store's prior
    (any entry — a host-speed proxy), else 0."""
    row = (report.get("throughput") or {}).get(key) or {}
    tput = row.get("rows_per_s")
    if tput:
        return float(tput)
    best = 0.0
    for entry in (report.get("profiles") or {}).values():
        v = entry.get("rows_per_s") if isinstance(entry, dict) else None
        if v:
            best = max(best, float(v))
    return best


def _cached_for_key(report: dict, key: str) -> int:
    """How many warm cached results this replica's load report claims
    for ``key`` — the fleet half of the result cache: reports without a
    cache block (older replicas, hand-built test reports) read as 0, so
    the preference only ever engages when a replica actually holds the
    key's results."""
    cache = report.get("cache")
    if not isinstance(cache, dict):
        return 0
    return int((cache.get("keys") or {}).get(key, 0))


def choose_replica(key: str, members: dict, affinity: dict) -> str | None:
    """Pure placement: replica name, or ``None`` when every placeable
    member is saturated (the caller sheds 112).

    ``members`` maps name → ``{"placeable": bool, "report": {...}}``
    (frozen — this function reads, never mutates); ``affinity`` maps
    placement key → the name that last served it.

    Order of preference after the affinity pin: a replica already
    holding cached results for this key (so a hot repeated request is a
    fleet-wide dict lookup — ONE dispatch total, not one per replica),
    then lowest queue depth, then measured throughput, then name.  The
    cache preference is binary (holds any vs none): hoarding MORE
    entries for a key must not outrank an idle replica's queue.
    """
    def open_(m) -> bool:
        return m["placeable"] and not _saturated(m["report"])

    pinned = affinity.get(key)
    if pinned is not None and pinned in members and open_(members[pinned]):
        return pinned
    candidates = [(n, m) for n, m in members.items() if open_(m)]
    if not candidates:
        return None
    return min(
        candidates,
        key=lambda nm: (
            -min(_cached_for_key(nm[1]["report"], key), 1),
            nm[1]["report"].get("queue_depth", 0),
            -_key_throughput(nm[1]["report"], key),
            nm[0],
        ),
    )[0]


class Router:
    def __init__(self, params: RouterParams | None = None):
        self.params = params or RouterParams()
        self._members: dict[str, _Member] = {}
        self._affinity: dict[str, str] = {}
        self._epoch = 0
        self._signature: int | None = None
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="skylark-router"
        )
        self._hb_thread: threading.Thread | None = None
        self._hb_stop = threading.Event()

    # -- membership ---------------------------------------------------------

    def join(self, name: str, server=None, *, url: str | None = None,
             timeout: float | None = None) -> dict:
        """Admit a replica (in-process ``server=`` or remote ``url=``).

        Fetches its load report, fences its registry signature against
        the fleet's, bumps the epoch, and marks it placeable only if
        its worker loop is already alive (which, via ``Server.start``'s
        prime-then-spawn ordering, implies its plan ladder is warm).
        Returns the membership record; raises
        :class:`WorldMismatchError` (109) on signature mismatch."""
        if (server is None) == (url is None):
            raise ValueError("pass exactly one of server= or url=")
        replica = (
            InProcessReplica(name, server)
            if server is not None
            else HttpReplica(name, url, timeout=timeout, pool=self._pool)
        )
        report = replica.load_report()
        with self._lock:
            sig = report.get("signature")
            if self._members and self._signature != sig:
                exc = WorldMismatchError(
                    f"replica {name!r} registry signature {sig} does not "
                    f"match the fleet's {self._signature}; a fleet must "
                    "serve one registry",
                    expected=self._signature,
                    got=sig,
                )
                telemetry.error_event("router.join", exc, replica=name)
                raise exc
            if not self._members:
                self._signature = sig
            member = _Member(
                name, replica, report,
                last_heartbeat=time.monotonic(),
                placeable=bool(report.get("worker_alive")),
            )
            self._members[name] = member
            self._epoch += 1
            epoch = self._epoch
        telemetry.inc("router.joins")
        telemetry.event(
            "router", "join",
            {"replica": name, "epoch": epoch,
             "placeable": member.placeable},
        )
        return {
            "replica": name,
            "epoch": epoch,
            "placeable": member.placeable,
            "signature": sig,
        }

    def handle_join(self, payload: dict) -> dict:
        """The ``POST /join`` body: ``{"name": ..., "url": ...}``."""
        t = payload.get("timeout")
        return self.join(
            str(payload.get("name") or payload.get("url")),
            url=payload["url"],
            timeout=None if t is None else float(t),
        )

    def drain(self, name: str) -> bool:
        """Take a member out of NEW placements without ejecting it: its
        in-flight and queued work finishes on the replica, its heartbeat
        keeps flowing, and :meth:`remove` retires it once idle.  This is
        the scale-down half of zero-downtime membership — the mirror of
        the join fence's prime-before-placeable.  Returns False for an
        unknown member."""
        with self._lock:
            member = self._members.get(name)
            if member is None:
                return False
            member.draining = True
            member.placeable = False
            for key in [k for k, n in self._affinity.items() if n == name]:
                del self._affinity[key]
        telemetry.inc("router.drains")
        telemetry.event("router", "drain", {"replica": name})
        return True

    def remove(self, name: str, reason: str = "drained") -> bool:
        """Clean departure: pop the member, bump the fleet epoch, ledger
        a ``leave`` event.  Unlike :meth:`eject` this mints NO code-114
        error — the member left on purpose with zero work in flight.
        Returns False for an unknown member."""
        with self._lock:
            member = self._members.pop(name, None)
            if member is None:
                return False
            for key in [k for k, n in self._affinity.items() if n == name]:
                del self._affinity[key]
            self._epoch += 1
            epoch = self._epoch
        telemetry.inc("router.leaves")
        telemetry.event(
            "router", "leave",
            {"replica": name, "epoch": epoch, "reason": reason},
        )
        return True

    def eject(self, name: str, reason: str = "heartbeat lost",
              heartbeat_age_s: float | None = None) -> None:
        """Remove a member: epoch bump, affinity entries dropped (their
        keys re-place on the next request), code-114 error event."""
        with self._lock:
            member = self._members.pop(name, None)
            if member is None:
                return
            for key in [k for k, n in self._affinity.items() if n == name]:
                del self._affinity[key]
            self._epoch += 1
            epoch = self._epoch
        exc = ReplicaLostError(
            f"replica {name!r} ejected from the fleet: {reason}",
            replica=name,
            last_heartbeat_s=heartbeat_age_s,
        )
        telemetry.inc("router.ejects")
        telemetry.error_event("router.eject", exc, replica=name, epoch=epoch)
        telemetry.event(
            "router", "eject",
            {"replica": name, "epoch": epoch, "reason": reason},
        )

    def poll_once(self, now: float | None = None) -> dict:
        """One heartbeat sweep: refresh every member's load report;
        members whose reports fail (or whose workers are dead) past the
        timeout are ejected.  Returns ``{name: placeable}`` for the
        survivors.  Deterministic — tests call this directly instead of
        racing the background thread.

        Stale-but-alive discipline: a member whose report FETCH failed
        this sweep (transport hiccup, replica mid-GC) keeps its last
        report — stamped with ``report_age_s`` so placement reads its
        age honestly — and stays placeable until the silence crosses
        ``heartbeat_timeout_s``.  Ejection fires on real silence only;
        one dropped poll is not a dead replica."""
        now = time.monotonic() if now is None else now
        with self._lock:
            snapshot = list(self._members.items())
        lost = []
        for name, member in snapshot:
            fetched = True
            try:
                report = member.replica.load_report()
                alive = bool(report.get("worker_alive"))
            except Exception:  # noqa: BLE001 — a dead peer must not kill the sweep
                report, alive, fetched = None, False, False
            with self._lock:
                if self._members.get(name) is not member:
                    continue
                age = now - member.last_heartbeat
                if fetched:
                    member.report = report
                    member.placeable = alive and not member.draining
                    if alive:
                        member.last_heartbeat = now
                    elif age > self.params.heartbeat_timeout_s:
                        lost.append((name, age))
                elif age > self.params.heartbeat_timeout_s:
                    member.placeable = False
                    lost.append((name, age))
                else:
                    # stale-but-alive: keep serving on the last report,
                    # visibly aged so placement can discount it
                    member.report = dict(
                        member.report, report_age_s=round(age, 3)
                    )
        for name, age in lost:
            self.eject(name, heartbeat_age_s=round(age, 3))
        with self._lock:
            return {n: m.placeable for n, m in self._members.items()}

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Router":
        if self.params.heartbeat_interval_s > 0 and self._hb_thread is None:
            self._hb_stop.clear()
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, name="skylark-router-hb",
                daemon=True,
            )
            self._hb_thread.start()
        return self

    def stop(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(5.0)
            self._hb_thread = None
        self._pool.shutdown(wait=False)

    def __enter__(self) -> "Router":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self.params.heartbeat_interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the heartbeat must survive
                pass

    # -- request path -------------------------------------------------------

    def submit(self, request: dict) -> Future:
        """Place and forward one request; ALWAYS returns a future
        resolving to a protocol response dict (fleet saturation and
        replica loss resolve to 112/114 envelopes — nothing raises),
        the same contract as :meth:`Server.submit`."""
        fut: Future = Future()
        self._dispatch(request, fut, attempt=0)
        return fut

    def call(self, request: dict | None = None, /, **fields) -> dict:
        req = dict(request or {}, **fields)
        return self.submit(req).result()

    def _dispatch(self, request: dict, outer: Future, attempt: int) -> None:
        key = protocol.placement_key(request)
        with self._lock:
            members = {
                n: {"placeable": m.placeable, "report": m.report}
                for n, m in self._members.items()
            }
            name = choose_replica(key, members, self._affinity)
            if name is not None:
                hit = self._affinity.get(key) == name
                self._affinity[key] = name
                member = self._members[name]
                epoch = self._epoch
        if name is None:
            if not members:
                exc: Exception = ReplicaLostError(
                    "no placeable replica in the fleet", replica=None
                )
            else:
                depths = [
                    m["report"].get("queue_depth") for m in members.values()
                ]
                exc = AdmissionError(
                    "every fleet replica is saturated; back off and retry",
                    queue_depth=max((d for d in depths if d is not None),
                                    default=None),
                )
            telemetry.inc("router.sheds")
            telemetry.error_event("router.place", exc, key=key)
            outer.set_result(
                protocol.error_response(
                    request.get("id"), exc,
                    {"events": [{"kind": "fleet_shed", "key": key}]},
                )
            )
            return
        telemetry.inc("router.placements")
        if hit:
            telemetry.inc("router.affinity_hits")
        telemetry.event(
            "router", "placement",
            {"key": key, "replica": name, "epoch": epoch,
             "affinity": hit, "attempt": attempt},
        )
        inner = member.replica.submit(request)

        def _relay(inner_fut: Future) -> None:
            try:
                resp = inner_fut.result()
            except Exception as e:  # noqa: BLE001 — in-flight replica loss
                self.eject(name, reason=f"in-flight failure: {e}")
                if attempt < self.params.max_failover:
                    telemetry.inc("router.failovers")
                    self._dispatch(request, outer, attempt + 1)
                else:
                    exc = ReplicaLostError(
                        f"request lost {attempt + 1} replicas in flight; "
                        "giving up",
                        replica=name,
                    )
                    telemetry.error_event("router.failover", exc, key=key)
                    outer.set_result(
                        protocol.error_response(
                            request.get("id"), exc, {"events": []}
                        )
                    )
                return
            # The placement→dispatch race: the replica was chosen while
            # placeable but stopped (or was ejected) before this request
            # reached its worker.  Its shutdown envelope is a 112 with
            # no queue depth (a saturation shed always carries one) —
            # that, or an infrastructure error from a member the fleet
            # already dropped, fails over transparently exactly like a
            # raised transport loss; a 114 reaches the caller only when
            # no placeable replica remains.
            err = None if resp.get("ok") else (resp.get("error") or {})
            if err is not None and attempt < self.params.max_failover:
                shutdown = (
                    err.get("code") == 112
                    and err.get("queue_depth") is None
                )
                with self._lock:
                    gone = self._members.get(name) is not member
                if shutdown or (gone and err.get("code") in (112, 114)):
                    if not gone:
                        self.eject(name, reason="shut down in flight")
                    telemetry.inc("router.failovers")
                    telemetry.event(
                        "router", "failover",
                        {"key": key, "replica": name,
                         "code": err.get("code"), "attempt": attempt + 1},
                    )
                    self._dispatch(request, outer, attempt + 1)
                    return
            trace = resp.setdefault("trace", {})
            trace["replica"] = name
            trace["fleet_epoch"] = epoch
            outer.set_result(resp)

        inner.add_done_callback(_relay)

    # -- observability ------------------------------------------------------

    def fleet_report(self) -> dict:
        now = time.monotonic()
        with self._lock:
            cache = {"hits": 0, "misses": 0, "entries": 0, "bytes": 0}
            for m in self._members.values():
                c = (m.report or {}).get("cache")
                if isinstance(c, dict):
                    for k in cache:
                        cache[k] += int(c.get(k, 0))
            return {
                "epoch": self._epoch,
                "signature": self._signature,
                # Fleet-wide result-cache rollup over the members' load
                # reports — the shared hit/miss state of the whole fleet
                # in one place (per-replica detail stays in each report).
                "cache": cache,
                "members": {
                    n: {
                        "placeable": m.placeable,
                        "draining": m.draining,
                        "heartbeat_age_s": round(now - m.last_heartbeat, 3),
                        "report": m.report,
                    }
                    for n, m in self._members.items()
                },
                "affinity": dict(self._affinity),
            }

    def stats(self) -> dict:
        counters = {
            k.split(".", 1)[1]: v
            for k, v in telemetry.REGISTRY.snapshot()["counters"].items()
            if k.startswith("router.")
        }
        return {"fleet": self.fleet_report(), "counters": counters}
