"""Cross-request coalescing executors: N requests, one fused dispatch.

A batch arrives here already grouped by coalesce key (same registered
entity, same serialized sketch, same input signature — see
``admission.take_batch``).  The executor stacks the requests' payloads,
pads the stacked block up to the ``plans/bucketing.py`` geometric
ladder, runs ONE planned ``SketchPlan`` call (plus one small jitted
solve / matmul keyed on the same rung), then de-pads and fans results
back out to the per-request futures.

Bitwise isolation contract: every executor below is built exclusively
from per-slot-pure operations — sketch applies (COLUMNWISE columns and
ROWWISE rows are independent by the transform contract), matmuls and
triangular solves whose output elements reduce only over the
contraction dimension, and elementwise maps.  One subtlety makes this
an engineering property rather than a free one: XLA's CPU gemm lowers
REMAINDER columns (a batch width that is not a multiple of the vector
tile) through a different micro-kernel with a different accumulation
schedule, so a column's bits can depend on which tile class its slot
landed in.  Columnwise dispatch widths are therefore restricted to the
lane-uniform sub-ladder (:func:`_lane_bucket` — every rung a multiple
of the base rung 8, i.e. the geometric ladder minus its lone 12-wide
rung); rowwise blocks are safe on the full ladder because rows are
never the contraction dimension.  Under that restriction a request's
result is bit-identical whatever batch it rode in: alone (padded to
the first rung), coalesced with 7 strangers, or on a different rung
entirely.  ``tests/test_serve.py`` pins this for LS-solve and
KRR-predict against the serial one-request-at-a-time path, across a
rung boundary.

Fault isolation: after every batch the per-request results are probed
finite.  A failing request (or a batch-wide exception in a >1 batch)
is re-run SOLO through the same executor — the serve-side recovery
ladder rung — and only if the solo run still fails does that request
get a structured ``NumericalHealthError`` (code 108) response; its
batch-mates keep their (bit-unaffected) results.  Every retry/fallback
lands in the request's ``trace["events"]``.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import plans, telemetry
from ..telemetry.trace import is_violating, next_id
from ..utils.exceptions import NumericalHealthError, SkylarkError
from . import dispatch, protocol

__all__ = ["run_batch"]


def _stage(x, device):
    """Host→device staging for one executor operand: the PR-11
    ``pinned_placer`` seam.  ``device=None`` (single-worker servers) is
    a no-op — the operand flows to JAX exactly as before, bit-for-bit;
    a pinned worker stages onto its own chip so K workers' dispatches
    never serialize through device 0."""
    if device is None:
        return x
    from ..streaming.pipeline import device_placer

    return device_placer(x, device)


@jax.jit
def _qr_solve(Qt, R, SB):
    """x̂ = R⁻¹ Qᵀ S b per column — the sketch-and-solve normal step."""
    from jax.scipy.linalg import solve_triangular

    return solve_triangular(R, Qt @ SB, lower=False)


@jax.jit
def _matmul(Z, W):
    return Z @ W


def _lane_bucket(k: int) -> int:
    """Smallest ladder rung >= k that is a multiple of the base rung (8).

    Coalesced COLUMNWISE widths must keep every request slot inside a
    full vector tile: XLA's CPU gemm lowers remainder columns through a
    different micro-kernel, so slots 8-11 of the 12-wide rung are NOT
    bit-equal to the same column served solo.  Every other rung on the
    ladder is a multiple of 8, so skipping the lone 12-wide rung (and
    rounding over-ladder widths up to a multiple of 8) restores per-slot
    purity.  Rowwise blocks don't need this: rows are never the
    contraction dimension, and ``tests/test_serve.py`` pins both facts.
    """
    kb = plans.bucket_for(k)
    while kb % 8:
        kb = plans.bucket_for(kb + 1)
    return kb


def _pad_cols(Bt: np.ndarray) -> tuple[np.ndarray, int]:
    """(k, m) stacked RHS rows -> transposed (m, kb) bucket block."""
    kb = _lane_bucket(Bt.shape[0])
    Bp = plans.pad_rows(Bt, kb)
    return np.ascontiguousarray(Bp.T), kb


def _execute_ls(registry, entries, device=None):
    # The entity PINNED at validation, not the registry head: a live
    # row-append/downdate landing while this batch queued published a
    # new version object — this batch still executes against the exact
    # epoch it admitted under (prime() entries carry no pin and take
    # the current head).
    system = entries[0].entity or registry.get_system(
        entries[0].request["system"]
    )
    S = entries[0].sketch or system.S
    Bt = np.stack([e.payload for e in entries])  # (k, m)
    B, kb = _pad_cols(Bt)  # (m, kb)
    if B.shape[0] < S.n:
        # Capacity-reserved system: rows [m, capacity) are virtual
        # zeros in the registered S·A, so the RHS pads with exact
        # zeros to the sketch domain (zero rows contribute zero).
        B = np.concatenate(
            [B, np.zeros((S.n - B.shape[0], kb), B.dtype)]
        )
    Bj = jnp.asarray(B, system.A.dtype)

    def single():
        return plans.apply(S, _stage(Bj, device), "columnwise")

    SB = None
    if entries[0].sketch is not None:
        # fresh-sketch slow path: the factorization is per-request
        SA = plans.apply(S, system.A, "columnwise")
        Q, R = jnp.linalg.qr(SA)
        Qt = jnp.asarray(Q).T
    else:
        Qt, R = system.Qt, system.R
        # Heavy half over every local chip when the rung clears the
        # gates; the (s, kb) solve below is the UNCHANGED light half.
        SB = dispatch.maybe_sketch_sharded(S, Bj, kb, entries, single)
    if SB is None:
        SB = single()
    X = np.asarray(_qr_solve(Qt, R, SB))  # (n, kb)
    return [X[:, i] for i in range(len(entries))], kb


def _feature_z(model, Xp, true_rows):
    """The feature block Z of a predict batch, planned and SHAPE-STABLE:
    ``Xp`` arrives padded to the rung, every map rides
    ``apply_rowwise_bucketed(pad_out=True)`` (padded rows zeroed inside
    the executable), and the concat is keyed on the rung shape alone.
    Shape stability is the latency contract: if any step here saw the
    RAW batch size, every distinct coalesce width would compile a fresh
    executable mid-traffic and stall the worker queue — ``Server.prime``
    can only pre-compile rung shapes."""
    kb = Xp.shape[0]
    blocks = []
    for S in model.maps:
        Z, _ = plans.apply_rowwise_bucketed(
            S, Xp, true_rows=true_rows, pad_out=True
        )
        if Z.shape[0] != kb:
            # gate-mismatched map (its own rung ladder) or plans-off
            # bypass: re-align to the batch rung off the hot path
            Z = jnp.asarray(plans.pad_rows(Z[:true_rows], kb))
        if model.scale_maps:
            Z = Z * jnp.asarray(np.sqrt(Z.shape[-1] / Xp.shape[-1]), Z.dtype)
        blocks.append(Z)
    return jnp.concatenate(blocks, axis=-1) if blocks else jnp.asarray(Xp)


def _kernel_jit(registry, name, model):
    # Keyed by (name, epoch): a pinned in-flight batch rebuilding the
    # OLD version's closure after a live model update must never leave
    # it where new-epoch traffic would pick it up.
    key = (name, int(getattr(model, "epoch", 0)))
    fn = registry.model_jits.get(key)
    if fn is None:
        def gram_predict(X):
            return model.kernel.gram(X, model.X_train) @ model.A

        fn = jax.jit(gram_predict)
        registry.model_jits[key] = fn
    return fn


def _execute_predict(registry, entries, device=None):
    name = entries[0].request["model"]
    model = entries[0].entity or registry.get_model(name)
    X = np.concatenate([e.payload for e in entries])  # (R, d)
    R_tot = X.shape[0]
    kb = plans.bucket_for(R_tot)
    Xp = plans.pad_rows(X, kb)
    if hasattr(model, "maps"):
        # Sharded heavy half: feature maps over every chip, the Z·W
        # matmul below unchanged.  Padding rows are garbage on the
        # sharded route (eager applies don't zero them) exactly until
        # the [:R_tot] slice — row purity keeps true rows bit-equal.
        def zsingle():
            return _feature_z(model, _stage(Xp, device), true_rows=R_tot)

        Z = dispatch.maybe_feature_sharded(model, Xp, R_tot, entries, zsingle)
        if Z is None:
            Z = zsingle()
        O = np.asarray(_matmul(Z, model.W.astype(Z.dtype)))[:R_tot]
    else:
        def osingle():
            return _kernel_jit(registry, name, model)(
                _stage(jnp.asarray(Xp), device)
            )

        O = dispatch.maybe_kernel_sharded(model, Xp, R_tot, entries, osingle)
        if O is None:
            O = osingle()
        O = np.asarray(O)[:R_tot]
    outs, at = [], 0
    for e in entries:
        r = e.payload.shape[0]
        outs.append(O[at:at + r])
        at += r
    return outs, kb


def _execute_cond_est(registry, entries, device=None):
    """Served cond-est: ONE cached probe of the system's R factor
    (``LSSystem.cond_report``), fanned to every coalesced rider.  The
    heavy spectral work happened at registration (QR of S·A); the
    per-batch cost after the first request is a dict copy per rider."""
    system = entries[0].entity or registry.get_system(
        entries[0].request["system"]
    )
    rep = system.cond_report(cache=getattr(registry, "cache", None))
    return [dict(rep) for _ in entries], len(entries)


def _execute_ppr(registry, entries, device=None):
    """Served PPR: each rider's canonical seed-set payload resolves
    through ``GraphSystem.ppr_report`` — memoized, so coalesce-mates
    (and repeat queries) with the same seed set share ONE active-support
    diffusion, the graph analogue of the cached cond-est probe.  The
    fan-out is a dict copy per rider, which is what makes coalesced ≡
    solo trivially bitwise."""
    gsys = entries[0].entity or registry.get_graph(
        entries[0].request["graph"]
    )
    cache = getattr(registry, "cache", None)
    return (
        [dict(gsys.ppr_report(e.payload, cache=cache)) for e in entries],
        len(entries),
    )


def _execute_ase_embed(registry, entries, device=None):
    """Served embedding queries against the resident ASE matrix: row
    lookup (``"rows"`` payloads) or out-of-sample neighbor projection
    (``"oos"``).  Pure host-array indexing per rider — per-slot purity
    is structural, no padding or tile discipline involved."""
    gsys = entries[0].entity or registry.get_graph(
        entries[0].request["graph"]
    )
    outs = []
    for e in entries:
        mode, idx = e.payload
        outs.append(gsys.rows(idx) if mode == "rows" else gsys.project(idx))
    return outs, len(entries)


def _execute_update(registry, entries, device=None):
    """Live-registry mutation executor.  Updates NEVER coalesce (the
    server keys each uniquely) and never solo-retry — a mutation must
    apply at most once, so a raise here surfaces as this one request's
    structured error, with nothing re-run.  The result is the minted
    epoch-ledger record: {entity, kind, epoch, ...delta counts}."""
    outs = []
    for e in entries:
        p = e.payload
        idem = p.get("idem")
        if p["kind"] == "graph_fold":
            _, rec = registry.fold_graph_edges(
                p["name"], p["edges"], idem=idem
            )
        elif p["kind"] == "row_append":
            _, rec = registry.append_system_rows(
                p["name"], p["rows"], idem=idem
            )
        else:
            _, rec = registry.downdate_system_rows(
                p["name"], p["drop"], idem=idem
            )
        outs.append(dict(rec))
    return outs, len(entries)


_EXECUTORS = {
    "ls_solve": _execute_ls,
    "cond_est": _execute_cond_est,
    "predict": _execute_predict,
    "ppr": _execute_ppr,
    "ase_embed": _execute_ase_embed,
    "update": _execute_update,
}


def _result_finite(out) -> bool:
    """The per-request finite probe, dict-aware: structured results
    (cond-est reports) probe their numeric leaves, and NaN alone is
    unhealthy — an honest ``inf`` cond for a numerically singular
    system is a legitimate served answer, not a fault."""
    if isinstance(out, dict):
        vals = [v for v in out.values() if isinstance(v, (int, float))]
        return not np.isnan(np.asarray(vals, np.float64)).any()
    return bool(np.isfinite(np.asarray(out, np.float64)).all())


def _decode(entry, out):
    """Per-request post-processing AFTER the finite probe: label decode
    for classification predicts, squeeze for single-row requests."""
    if entry.op == "predict" and entry.request.get("labels"):
        # classes snapshot onto the request at admission (server side)
        classes = entry.request.get("_classes")
        idx = np.argmax(out, axis=-1)
        out = np.asarray(classes)[idx] if classes is not None else idx
    if (
        entry.squeeze
        and getattr(out, "ndim", 0) > 0
        and entry.op in ("predict", "ase_embed")
    ):
        out = out[0]
    return out


def _stamp_phases(entry, phase_info) -> None:
    """Finalize the phase clock for one traced entry: the stamps form a
    contiguous monotonic chain (admit → pop → take → exec start → exec
    end → here), so the phases sum to the end-to-end latency by
    construction; ``plan_compile`` is carved out of the executor wall
    time via the plan-cache compile-seconds delta."""
    t0m, t1m, compile_ms = phase_info
    t_done = time.monotonic()
    exec_ms = (t1m - t0m) * 1e3
    p = entry.phases
    t_take = p.pop("_t_take", t0m)
    p["dispatch_queue"] = (t0m - t_take) * 1e3
    p["plan_compile"] = min(max(compile_ms, 0.0), exec_ms)
    p["device_execute"] = exec_ms - p["plan_compile"]
    p["depad_serialize"] = (t_done - t1m) * 1e3
    entry.phases = None  # consumed — a solo retry would restamp fresh
    phases = {k: round(v, 4) for k, v in p.items()}
    entry.trace["phases"] = phases
    if entry.t_admit is not None:
        entry.trace["e2e_ms"] = round((t_done - entry.t_admit) * 1e3, 4)
    for k, v in phases.items():
        telemetry.observe_phase(k, v)


def _finish_ok(entry, out, batch_size, bucket, t_exec_ms, registry=None,
               phase_info=None):
    entry.trace.update(
        batch_size=batch_size,
        bucket=bucket,
        coalesced=batch_size > 1,
        exec_ms=round(t_exec_ms, 4),
    )
    if entry.phases is not None and phase_info is not None:
        _stamp_phases(entry, phase_info)
    if entry.counter_base is not None:
        entry.trace["counter_base"] = entry.counter_base
    if entry.entity is not None:
        # The epoch this request was actually served at — the auditable
        # half of the live-registry bitwise contract.
        entry.trace["registry_epoch"] = int(
            getattr(entry.entity, "epoch", 0)
        )
    if registry is not None and entry.cache_key is not None:
        # Fill the front-door result cache with the DECODED per-request
        # result (the cache deep-copies and freezes on put, so a caller
        # mutating the response envelope cannot poison it).  The key
        # pins the epoch this batch served at, so a fold landing
        # mid-flight never aliases old bits onto the new version's key.
        registry.cache.put(
            entry.cache_key, out, entity=entry.cache_entity
        )
    telemetry.inc("serve.ok")
    if telemetry.enabled():
        telemetry.inc(f"serve.tenant.{entry.tenant_label}.ok")
    # a request that answered OK but only after a solo-retry / guard
    # rung is still an SLO incident: keep it in the violation ring
    telemetry.finish_trace(
        entry.tctx, "ok", violation=is_violating(entry.trace["events"])
    )
    entry.future.set_result(
        protocol.ok_response(entry.request.get("id"), out, entry.trace)
    )


def _finish_error(entry, exc, batch_size):
    entry.trace.update(batch_size=batch_size, coalesced=batch_size > 1)
    code = int(getattr(exc, "code", 100))
    if telemetry.enabled():
        telemetry.inc(f"serve.tenant.{entry.tenant_label}.errors")
    if entry.tctx is not None:
        # error_event appends onto the active trace, whose event list
        # aliases entry.trace["events"] — envelope and recorder in one
        with telemetry.activate([entry.tctx]):
            telemetry.error_event(
                f"serve.{entry.op}", exc, op=entry.op
            )
    else:
        entry.trace["events"].append(
            {"kind": "error", "code": code, "type": type(exc).__name__}
        )
    telemetry.finish_trace(entry.tctx, "error", code=code)
    entry.future.set_result(
        protocol.error_response(entry.request.get("id"), exc, entry.trace)
    )


def run_batch(registry, entries, device=None) -> None:
    """Execute one coalesced batch; every entry's future is resolved by
    the time this returns (ok, degraded-solo, or structured error).

    Tracing: ONE dispatch span id is minted per call and attached to
    every traced entry — the k requests a coalesced batch carried share
    it, and a solo retry (which re-enters here) mints a fresh one, so
    the two rungs stay distinguishable in the flight recorder.  The
    traces ride the thread's active set for the duration, so plan-cache
    and guard events emitted below land on them too."""
    tctxs = [e.tctx for e in entries if e.tctx is not None]
    if not tctxs:  # telemetry off: zero tracing work, not even a span id
        _dispatch(registry, entries, device)
        return
    sid = next_id()
    n = len(entries)
    peers = {"peers": [t.trace_id for t in tctxs]} if n > 1 else {}
    for t in tctxs:
        t.event("dispatch", span=sid, batch_size=n, **peers)
    with telemetry.activate(tctxs):
        _dispatch(registry, entries, device)


def _dispatch(registry, entries, device=None) -> None:
    executor = _EXECUTORS[entries[0].op]
    n = len(entries)
    # Phase clock: only when the worker armed at least one entry (traced
    # request with SKYLARK_PHASES on) — otherwise not even a timestamp.
    phase_t0 = (
        time.monotonic()
        if any(e.phases is not None for e in entries)
        else None
    )
    compile_before = (
        plans.stats()["compile_seconds"] if phase_t0 is not None else 0.0
    )
    t0 = time.perf_counter()
    try:
        outs, bucket = executor(registry, entries, device)
    except Exception as e:  # noqa: BLE001 — isolate, then solo-retry
        if n == 1:
            telemetry.inc("serve.errors")
            if not isinstance(e, SkylarkError):
                telemetry.event("serve", "batch_error", {"type": type(e).__name__})
            _finish_error(entries[0], e, n)
            return
        # a poisoned batch: re-run each request alone so one bad payload
        # cannot take its batch-mates down with it
        telemetry.inc("serve.fallbacks")
        for e2 in entries:
            e2.trace["events"].append(
                {"kind": "fallback", "reason": f"batch raised {type(e).__name__}"}
            )
            telemetry.inc("serve.solo_retries")
            run_batch(registry, [e2], device)
        return
    t_ms = (time.perf_counter() - t0) * 1e3
    phase_info = None
    if phase_t0 is not None:
        # Executor wall time is device time: every executor lands its
        # result via np.asarray, which blocks until the device is done.
        phase_info = (
            phase_t0,
            time.monotonic(),
            (plans.stats()["compile_seconds"] - compile_before) * 1e3,
        )
    for entry, out in zip(entries, outs):
        if not _result_finite(out):
            if n > 1:
                # this request's own data is bad (padding and batch-mates
                # cannot leak in — slot purity): solo re-run confirms, and
                # the solo path owns the structured verdict
                telemetry.inc("serve.fallbacks")
                telemetry.inc("serve.solo_retries")
                entry.trace["events"].append(
                    {"kind": "fallback", "reason": "non-finite in batch"}
                )
                telemetry.event(
                    "serve", "fallback",
                    {"op": entry.op, "id": entry.request.get("id")},
                )
                run_batch(registry, [entry], device)
                continue
            telemetry.inc("serve.errors")
            entry.trace["events"].append(
                {"kind": "fallback", "reason": "non-finite solo result"}
            )
            _finish_error(
                entry,
                NumericalHealthError(
                    "served result is non-finite after solo re-run "
                    "(request payload is numerically unhealthy)",
                    stage=f"serve_{entry.op}",
                ),
                n,
            )
            continue
        _finish_ok(entry, _decode(entry, out), n, bucket, t_ms, registry,
                   phase_info)
