"""Model and solve-system registry: load once, serve device-resident.

The registry is the serve layer's "load amplification" half: a model's
weights (``ml/model.py`` JSON + binary sidecars) or an LS system's
factorization are loaded/computed ONCE at registration and every request
afterwards hits device-resident state — the per-request cost is one
padded batch through an already-compiled plan.

- :class:`LSSystem` — a registered least-squares design matrix with its
  sketch and the QR factorization of ``S·A`` precomputed on device:
  serving a request is one COLUMNWISE sketch-apply of the coalesced RHS
  block plus one small triangular solve (sketch-and-solve, the same
  math ``linalg.exact_least_squares(SA, SB, "qr")`` does eagerly).
- Models are the ``ml/model.py`` classes verbatim (their arrays are
  jnp/device-resident by construction); ``load`` goes through the same
  polymorphic ``load_model`` dispatch the CLIs use, so the save→load
  round-trip contract pinned in ``tests/test_ml.py`` is exactly the
  serving contract.
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import plans
from ..core.context import SketchContext
from ..sketch import base as sketch_base
from ..utils.exceptions import InvalidParameters

__all__ = ["GraphSystem", "LSSystem", "Registry"]


class LSSystem:
    """A registered (A, S) pair with its sketched QR cached on device."""

    def __init__(self, name: str, A, S):
        self.name = name
        self.A = jnp.asarray(A)
        if self.A.ndim != 2:
            raise InvalidParameters(
                f"system {name!r}: A must be 2-D, got shape {self.A.shape}"
            )
        self.m, self.n = (int(d) for d in self.A.shape)
        if S.n != self.m:
            raise InvalidParameters(
                f"system {name!r}: sketch domain {S.n} != A rows {self.m}"
            )
        self.S = S
        self.dtype = self.A.dtype
        SA = plans.apply(S, self.A, "columnwise")
        Q, R = jnp.linalg.qr(SA)
        # Stored transposed: the per-batch solve consumes Qᵀ directly.
        self.Qt = jnp.asarray(Q).T
        self.R = R

    def describe(self) -> dict:
        return {
            "shape": [self.m, self.n],
            "dtype": str(self.dtype),
            "sketch": type(self.S).__name__,
            "sketch_size": int(self.S.s),
        }

    def cond_report(self) -> dict:
        """Condition / effective-rank report of the sketched system,
        probed ONCE and cached: R from QR(S·A) carries S·A's singular
        values (replicated-small n×n), so the probe is a short-budget
        ``cond_est`` on R plus one small SVD for the effective rank —
        the full (m, n) A is never touched.  Coalesced ``cond_est``
        requests for the same placement key all fan out this one dict.
        """
        rep = getattr(self, "_cond_report", None)
        if rep is None:
            import numpy as np

            from ..solvers.cond_est import CondEstParams, cond_est

            r = cond_est(
                self.R,
                SketchContext(seed=0x5EED),
                CondEstParams(iter_lim=60, powerits=25),
            )
            sv = np.asarray(jnp.linalg.svd(self.R, compute_uv=False))
            cutoff = float(np.finfo(sv.dtype).eps) * self.n * float(sv[0])
            rep = self._cond_report = {
                "system": self.name,
                "cond": float(r.cond),
                "sigma_max": float(r.sigma_max),
                "sigma_min": float(r.sigma_min),
                "effective_rank": int((sv > cutoff).sum()),
                "n": self.n,
                "sketch_size": int(self.S.s),
            }
        return rep


class GraphSystem:
    """A registered graph with its ASE embedding resident.

    The heavy work — the randomized symmetric eigensolve behind
    ``approximate_ase`` — runs ONCE at registration; every served query
    afterwards is a host-array lookup (``ase_embed``) or a memoized
    active-support diffusion (``ppr``).  The embedding is kept as host
    numpy: graph queries are small-row traffic, and pinning them off
    device keeps the chips free for the sketch executors.
    """

    def __init__(self, name: str, G, *, k: int = 8, context=None,
                 params=None):
        from ..graph.ase import ASEParams, approximate_ase
        from ..graph.graph import SimpleGraph

        if not isinstance(G, SimpleGraph):
            raise InvalidParameters(
                f"graph {name!r}: register a SimpleGraph, got "
                f"{type(G).__name__}"
            )
        if not (1 <= int(k) <= max(G.n, 1)):
            raise InvalidParameters(
                f"graph {name!r}: embedding rank {k} outside [1, {G.n}]"
            )
        self.name = name
        self.G = G
        self.k = int(k)
        context = context if context is not None else SketchContext(
            seed=0x5EED
        )
        params = params or ASEParams()
        import numpy as np

        X, lam = approximate_ase(G, self.k, context, params)
        self.X = np.asarray(X)
        self.lam = np.asarray(lam)
        self._streamed = bool(getattr(params, "streamed", False))
        self._ppr_reports: dict[tuple, dict] = {}

    def describe(self) -> dict:
        return {
            "n": int(self.G.n),
            "volume": int(self.G.volume),
            "k": self.k,
            "streamed": self._streamed,
        }

    def rows(self, idx) -> "np.ndarray":  # noqa: F821 — doc type
        """Embedding rows for vertex ids (the ``ase_embed`` lookup)."""
        return self.X[idx]

    def project(self, neighbor_ids) -> "np.ndarray":  # noqa: F821
        """Out-of-sample projection from a neighbor id list.

        For ``A = V Λ Vᵀ`` and a new vertex whose adjacency row is
        ``a``, the ASE position is ``x̂_c = (Σ_{j∈nb} X[j,c]) / λ_c``
        — for an existing vertex's own neighbor list this reproduces
        its embedding row exactly (``a_i·V = V[i,:]·Λ``).  Components
        with |λ| at the spectral floor contribute zero rather than a
        division blow-up.
        """
        import numpy as np

        s = self.X[np.asarray(neighbor_ids, np.int64)].sum(axis=0)
        floor = np.abs(self.lam).max(initial=0.0) * np.finfo(
            self.lam.dtype
        ).eps * max(self.G.n, 1)
        safe = np.abs(self.lam) > floor
        return np.divide(
            s, self.lam, out=np.zeros_like(s), where=safe
        )

    def ppr_report(self, payload: tuple) -> dict:
        """Seed-set PPR community report, memoized by the canonical
        payload ``(sorted-unique seed ids, alpha, gamma, epsilon)`` the
        server validated — coalesced riders with the same seed set share
        one diffusion, mirroring ``LSSystem.cond_report``.  The solve is
        ``find_local_cluster``'s active-support diffusion: work scales
        with the cluster found, not with the graph held."""
        rep = self._ppr_reports.get(payload)
        if rep is None:
            from ..graph.community import find_local_cluster

            seeds, alpha, gamma, epsilon = payload
            cluster, cond = find_local_cluster(
                self.G, list(seeds),
                alpha=alpha, gamma=gamma, epsilon=epsilon,
            )
            rep = self._ppr_reports[payload] = {
                "graph": self.name,
                "seeds": [int(v) for v in seeds],
                "cluster": sorted(int(v) for v in cluster),
                "conductance": float(cond),
                "alpha": float(alpha),
                "gamma": float(gamma),
                "epsilon": float(epsilon),
            }
        return rep


class Registry:
    def __init__(self):
        self.models: dict[str, object] = {}
        self.systems: dict[str, LSSystem] = {}
        self.graphs: dict[str, GraphSystem] = {}
        # per-model jitted predict closures, built lazily by the batcher
        self.model_jits: dict[str, object] = {}

    # -- models -------------------------------------------------------------

    def register_model(self, name: str, model) -> None:
        if not hasattr(model, "predict"):
            raise InvalidParameters(
                f"model {name!r} has no predict(); got {type(model).__name__}"
            )
        self.models[name] = model
        self.model_jits.pop(name, None)

    def load_model(self, name: str, path: str):
        """Load a saved ``ml/model.py`` JSON model once; serve forever."""
        from ..ml.model import load_model

        model = load_model(path)
        self.register_model(name, model)
        return model

    def get_model(self, name: str):
        try:
            return self.models[name]
        except KeyError:
            raise InvalidParameters(
                f"unknown model {name!r}; registered: {sorted(self.models)}"
            ) from None

    # -- LS systems ---------------------------------------------------------

    def register_system(
        self,
        name: str,
        A,
        *,
        context: SketchContext,
        sketch=None,
        sketch_type: str = "FJLT",
        sketch_size: int | None = None,
    ) -> LSSystem:
        """Register a least-squares design matrix.

        ``sketch`` may be a live transform, a serialized-sketch JSON
        string, or a dict (the ``native/`` interchange forms); absent,
        a fresh ``sketch_type`` transform is drawn from ``context`` —
        the server's counter stream, so registration order addresses it
        deterministically.
        """
        A = jnp.asarray(A)
        m = int(A.shape[0])
        if isinstance(sketch, str):
            sketch = sketch_base.from_json(sketch)
        elif isinstance(sketch, dict):
            sketch = sketch_base.from_dict(sketch)
        if sketch is None:
            n = int(A.shape[1]) if A.ndim == 2 else 1
            s = int(sketch_size or min(m, max(4 * n, n + 16)))
            sketch = sketch_base.create_sketch(sketch_type, m, s, context)
        system = LSSystem(name, A, sketch)
        self.systems[name] = system
        return system

    def get_system(self, name: str) -> LSSystem:
        try:
            return self.systems[name]
        except KeyError:
            raise InvalidParameters(
                f"unknown system {name!r}; registered: {sorted(self.systems)}"
            ) from None

    # -- graphs -------------------------------------------------------------

    def register_graph(
        self,
        name: str,
        G,
        *,
        k: int = 8,
        context: SketchContext | None = None,
        params=None,
    ) -> GraphSystem:
        """Register a graph: the ASE embedding is computed here, once
        (``params.streamed=True`` folds edge blocks — the adjacency is
        never materialized); ``ppr`` / ``ase_embed`` requests afterwards
        serve from the resident embedding and the memoized diffusion."""
        gsys = GraphSystem(name, G, k=k, context=context, params=params)
        self.graphs[name] = gsys
        return gsys

    def get_graph(self, name: str) -> GraphSystem:
        try:
            return self.graphs[name]
        except KeyError:
            raise InvalidParameters(
                f"unknown graph {name!r}; registered: {sorted(self.graphs)}"
            ) from None

    def describe(self) -> dict:
        models = {}
        for name, model in self.models.items():
            models[name] = {
                "kind": type(model).__name__,
                "input_dim": getattr(model, "input_dim", None),
                "classes": getattr(model, "classes", None) is not None,
            }
        return {
            "models": models,
            "systems": {k: s.describe() for k, s in self.systems.items()},
            "graphs": {k: g.describe() for k, g in self.graphs.items()},
        }
