"""Model and solve-system registry: load once, serve device-resident — live.

The registry is the serve layer's "load amplification" half: a model's
weights (``ml/model.py`` JSON + binary sidecars) or an LS system's
factorization are loaded/computed ONCE at registration and every request
afterwards hits device-resident state — the per-request cost is one
padded batch through an already-compiled plan.

- :class:`LSSystem` — a registered least-squares design matrix with its
  sketch and the QR factorization of ``S·A`` precomputed on device:
  serving a request is one COLUMNWISE sketch-apply of the coalesced RHS
  block plus one small triangular solve (sketch-and-solve, the same
  math ``linalg.exact_least_squares(SA, SB, "qr")`` does eagerly).
- Models are the ``ml/model.py`` classes verbatim (their arrays are
  jnp/device-resident by construction); ``load`` goes through the same
  polymorphic ``load_model`` dispatch the CLIs use, so the save→load
  round-trip contract pinned in ``tests/test_ml.py`` is exactly the
  serving contract.

**Live registries** (epoch discipline).  Sketches are linear, so a
registered entity can absorb updates without a re-register-the-world
restart:

- a :class:`GraphSystem` retains its SJLT ``Ω`` and the folded sketch
  ``SA = Ω·A`` from registration; new edge batches fold in through the
  same ``adjacency_sketch_fold`` scatter the streamed route uses and
  the embedding refreshes via ``ase_from_sketch``'s cheap replicated
  small math — bitwise identical to re-registering the merged graph
  from scratch, by the dyadic-exactness argument of ``graph/stream.py``;
- an :class:`LSSystem` registered with ``capacity > m`` takes
  row-append and row-downdate deltas: the sketch contribution of the
  touched rows (``S.apply_slice``) adds/subtracts into the retained
  ``S·A`` and only the small (s, n) QR re-runs;
- a model can be swapped wholesale, or a :class:`~..ml.model.KernelModel`
  can append/drop training centers (predict is linear in the center
  rows, so the delta is exact concatenation).

Every update MINTS a registry epoch (one global counter; the updated
entity is stamped with it, the decision appended to ``epoch_log`` and
the telemetry ledger).  Updated versions are NEW immutable objects —
the superseded version object stays untouched, so in-flight batches
pinned at admission keep serving its exact bits; a request that pins
``registry_epoch`` to a retired version gets a structured code-116
:class:`~..utils.exceptions.RegistryEpochError` envelope instead of
silently-new bits.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

import jax.numpy as jnp

from .. import plans, telemetry
from ..core.context import SketchContext
from ..sketch import base as sketch_base
from ..utils.exceptions import (
    InvalidParameters,
    JournalError,
    UnsupportedError,
)
from .cache import ResultCache, payload_digest

__all__ = ["GraphSystem", "LSSystem", "Registry"]


class LSSystem:
    """A registered (A, S) pair with its sketched QR cached on device.

    ``capacity`` (optional) sizes the sketch domain BEYOND the live row
    count: rows [m, capacity) are virtual zeros, so the registered
    factorization is unchanged math, and later ``appended`` rows land
    in reserved sketch-domain positions (the counter-addressed hash of
    a row depends only on its absolute index, so pre-sizing the domain
    is what makes append deltas exact).  Without it the system is
    frozen exactly as before.
    """

    def __init__(self, name: str, A, S, *, capacity: int | None = None,
                 retired=frozenset()):
        self.name = name
        self.A = jnp.asarray(A)
        if self.A.ndim != 2:
            raise InvalidParameters(
                f"system {name!r}: A must be 2-D, got shape {self.A.shape}"
            )
        self.m, self.n = (int(d) for d in self.A.shape)
        self.capacity = int(capacity) if capacity else self.m
        if self.capacity < self.m:
            raise InvalidParameters(
                f"system {name!r}: capacity {self.capacity} < A rows "
                f"{self.m}"
            )
        if S.n != self.capacity:
            raise InvalidParameters(
                f"system {name!r}: sketch domain {S.n} != row capacity "
                f"{self.capacity}"
            )
        self.S = S
        self.dtype = self.A.dtype
        self.retired = frozenset(int(i) for i in retired)
        self.epoch = 0  # stamped by Registry._mint
        if self.capacity == self.m:
            SA = plans.apply(S, self.A, "columnwise")
        else:
            Ap = jnp.zeros((self.capacity, self.n), self.dtype)
            SA = plans.apply(S, Ap.at[: self.m].set(self.A), "columnwise")
        self._set_sa(SA)

    def _set_sa(self, SA) -> None:
        self.SA = SA
        Q, R = jnp.linalg.qr(SA)
        # Stored transposed: the per-batch solve consumes Qᵀ directly.
        self.Qt = jnp.asarray(Q).T
        self.R = R

    # -- live deltas (return NEW versions; self stays frozen) ---------------

    def appended(self, rows) -> "LSSystem":
        """New version with ``rows`` appended at [m, m+r).

        The delta is ``S.apply_slice(rows, m)`` — the exact sketch
        contribution of those row positions — added into the retained
        ``S·A``; only the (s, n) QR re-runs.  Needs reserved capacity.
        """
        rows = jnp.asarray(rows, self.dtype)
        if rows.ndim == 1:
            rows = rows[None, :]
        if rows.ndim != 2 or int(rows.shape[1]) != self.n:
            raise InvalidParameters(
                f"system {self.name!r}: appended rows must be (r, {self.n})"
                f", got shape {tuple(rows.shape)}"
            )
        r = int(rows.shape[0])
        if self.m + r > self.capacity:
            raise InvalidParameters(
                f"system {self.name!r}: append of {r} rows exceeds "
                f"capacity {self.capacity} (live rows {self.m}); register "
                "with a larger capacity="
            )
        new = object.__new__(LSSystem)
        new.name, new.n, new.S = self.name, self.n, self.S
        new.capacity, new.dtype = self.capacity, self.dtype
        new.retired = self.retired
        new.epoch = self.epoch
        new.A = jnp.concatenate([self.A, rows], axis=0)
        new.m = self.m + r
        new._set_sa(self.SA + self.S.apply_slice(rows, self.m, "columnwise"))
        return new

    def downdated(self, indices) -> "LSSystem":
        """New version with the given live rows RETIRED: their sketch
        contribution is subtracted from the retained ``S·A`` and the
        rows zeroed in place (positions are kept so later appends stay
        addressable; the server zeroes the matching ``b`` entries at
        validation so retired rows drop out of the solve exactly)."""
        idx = sorted({int(i) for i in indices})
        for i in idx:
            if not (0 <= i < self.m):
                raise InvalidParameters(
                    f"system {self.name!r}: downdate index {i} outside "
                    f"[0, {self.m})"
                )
            if i in self.retired:
                raise InvalidParameters(
                    f"system {self.name!r}: row {i} is already retired"
                )
        new = object.__new__(LSSystem)
        new.name, new.n, new.S = self.name, self.n, self.S
        new.capacity, new.dtype = self.capacity, self.dtype
        new.m = self.m
        new.epoch = self.epoch
        new.retired = self.retired | frozenset(idx)
        SA = self.SA
        A = self.A
        for i in idx:
            SA = SA - self.S.apply_slice(self.A[i : i + 1], i, "columnwise")
            A = A.at[i].set(0.0)
        new.A = A
        new._set_sa(SA)
        return new

    def describe(self) -> dict:
        return {
            "shape": [self.m, self.n],
            "dtype": str(self.dtype),
            "sketch": type(self.S).__name__,
            "sketch_size": int(self.S.s),
            "capacity": self.capacity,
            "retired": len(self.retired),
            "epoch": self.epoch,
        }

    def cond_report(self, cache: "ResultCache | None" = None) -> dict:
        """Condition / effective-rank report of the sketched system,
        probed ONCE and cached: R from QR(S·A) carries S·A's singular
        values (replicated-small n×n), so the probe is a short-budget
        ``cond_est`` on R plus one small SVD for the effective rank —
        the full (m, n) A is never touched.  Coalesced ``cond_est``
        requests for the same placement key all fan out this one dict.

        The memo lives in the shared bounded :class:`ResultCache` when
        one is passed (epoch-keyed, so a new version recomputes and the
        old entry LRU-ages out); the per-object ``_cond_report``
        attribute remains as the cacheless fallback — new versions never
        copy it, so it can't survive an epoch bump either.
        """
        ck = ("cond:" + self.name, 0, self.epoch) if cache is not None \
            else None
        if cache is not None:
            rep = cache.get(ck)
            if rep is not None:
                return rep
        rep = getattr(self, "_cond_report", None)
        if rep is None:
            import numpy as np

            from ..solvers.cond_est import CondEstParams, cond_est

            r = cond_est(
                self.R,
                SketchContext(seed=0x5EED),
                CondEstParams(iter_lim=60, powerits=25),
            )
            sv = np.asarray(jnp.linalg.svd(self.R, compute_uv=False))
            cutoff = float(np.finfo(sv.dtype).eps) * self.n * float(sv[0])
            rep = self._cond_report = {
                "system": self.name,
                "cond": float(r.cond),
                "sigma_max": float(r.sigma_max),
                "sigma_min": float(r.sigma_min),
                "effective_rank": int((sv > cutoff).sum()),
                "n": self.n,
                "sketch_size": int(self.S.s),
                "epoch": self.epoch,
            }
        if cache is not None:
            cache.put(ck, rep, entity=self.name)
        return rep


class GraphSystem:
    """A registered graph with its ASE embedding resident — and live.

    The heavy work runs ONCE at registration; every served query
    afterwards is a host-array lookup (``ase_embed``) or a memoized
    active-support diffusion (``ppr``).  The embedding is kept as host
    numpy: graph queries are small-row traffic, and pinning them off
    device keeps the chips free for the sketch executors.

    The embedding route is the streaming eigensolve's: an SJLT ``Ω``
    drawn once from the registration context, the folded sketch
    ``SA = Ω·A`` (in-core BCOO apply, bit-identical to the streamed
    edge-block fold), and ``ase_from_sketch``'s replicated small math.
    Both ``Ω`` and ``SA`` are RETAINED, which is what makes the system
    live: :meth:`folded` absorbs an edge batch by one delta fold plus
    the small-math refresh — never re-touching the edges already held —
    and lands bit-identical to a from-scratch registration of the
    merged graph (adjacency entries are 0/1 and SJLT values ±2⁻¹:
    every partial sum is exact dyadic, so the fold is order-invariant
    to the last bit).  ``params.num_iterations > 0`` opts back into the
    subspace-iterated ``approximate_ase`` route — polished spectra, but
    frozen (no fold state is retained).
    """

    def __init__(self, name: str, G, *, k: int = 8, context=None,
                 params=None):
        from ..graph.ase import ASEParams
        from ..graph.graph import SimpleGraph

        if not isinstance(G, SimpleGraph):
            raise InvalidParameters(
                f"graph {name!r}: register a SimpleGraph, got "
                f"{type(G).__name__}"
            )
        if not (1 <= int(k) <= max(G.n, 1)):
            raise InvalidParameters(
                f"graph {name!r}: embedding rank {k} outside [1, {G.n}]"
            )
        self.name = name
        self.G = G
        self.k = int(k)
        context = context if context is not None else SketchContext(
            seed=0x5EED
        )
        params = params or ASEParams()
        self.epoch = 0  # stamped by Registry._mint
        self._streamed = bool(getattr(params, "streamed", False))
        import numpy as np

        if getattr(params, "num_iterations", 0):
            from ..graph.ase import approximate_ase

            X, lam = approximate_ase(G, self.k, context, params)
            self._S = None
            self._sa = None
        else:
            from ..graph.stream import (
                ase_from_sketch,
                graph_block_source,
                incore_adjacency_sketch,
                streamed_adjacency_sketch,
            )
            from ..linalg.svd import _sketch_size
            from ..sketch.hash import SJLT

            k_, s = _sketch_size(self.k, params, G.n)
            self._S = SJLT(G.n, s, context)
            if self._streamed:
                self._sa = streamed_adjacency_sketch(
                    graph_block_source(
                        G, batch_edges=getattr(params, "batch_edges", 65536)
                    ),
                    self._S, ncols=G.n,
                )
            else:
                self._sa = incore_adjacency_sketch(G, self._S)
            V, lam = ase_from_sketch(self._sa, self._S, k_)
            X = V * jnp.sqrt(jnp.abs(lam))[None, :]
        self.X = np.asarray(X)
        self.lam = np.asarray(lam)
        self._ppr_reports: dict[tuple, dict] = {}

    # -- live edge folds (return NEW versions; self stays frozen) -----------

    def folded(self, pairs) -> tuple["GraphSystem", int]:
        """New version absorbing an edge batch; returns ``(gsys, r)``
        with ``r`` the count of genuinely-new undirected edges folded.

        One ``adjacency_sketch_fold`` step over the delta block (the
        same scatter kernel the streamed registration route uses) plus
        the ``ase_from_sketch`` refresh — O(Δedges + s·n) work, and
        bitwise ≡ registering the merged graph from scratch."""
        if self._S is None:
            raise UnsupportedError(
                f"graph {self.name!r} was registered through the "
                "subspace-iterated route (num_iterations > 0); live edge "
                "folds need the retained-sketch route (num_iterations=0)"
            )
        import numpy as np

        from ..graph.stream import adjacency_sketch_fold, ase_from_sketch

        try:
            G2, fresh = self.G.with_edges(pairs)
        except KeyError as e:
            raise InvalidParameters(str(e)) from None
        new = object.__new__(GraphSystem)
        new.name, new.k, new.G = self.name, self.k, G2
        new._S, new._streamed = self._S, self._streamed
        new.epoch = self.epoch
        if fresh.size:
            _, step = adjacency_sketch_fold(self._S, self.G.n)
            acc = step(
                {"sa": self._sa, "edge": np.asarray(0, np.int64)},
                {
                    "rows": np.concatenate([fresh[:, 0], fresh[:, 1]]),
                    "cols": np.concatenate([fresh[:, 1], fresh[:, 0]]),
                    "vals": np.ones(2 * fresh.shape[0], np.float64),
                },
                0,
            )
            new._sa = acc["sa"]
            V, lam = ase_from_sketch(new._sa, self._S, self.k)
            new.X = np.asarray(V * jnp.sqrt(jnp.abs(lam))[None, :])
            new.lam = np.asarray(lam)
        else:
            new._sa = self._sa
            new.X, new.lam = self.X, self.lam
        # The graph changed: cached diffusions belong to the old version.
        new._ppr_reports = {}
        return new, int(fresh.shape[0])

    def describe(self) -> dict:
        return {
            "n": int(self.G.n),
            "volume": int(self.G.volume),
            "k": self.k,
            "streamed": self._streamed,
            "epoch": self.epoch,
        }

    def rows(self, idx) -> "np.ndarray":  # noqa: F821 — doc type
        """Embedding rows for vertex ids (the ``ase_embed`` lookup)."""
        return self.X[idx]

    def project(self, neighbor_ids) -> "np.ndarray":  # noqa: F821
        """Out-of-sample projection from a neighbor id list.

        For ``A = V Λ Vᵀ`` and a new vertex whose adjacency row is
        ``a``, the ASE position is ``x̂_c = (Σ_{j∈nb} X[j,c]) / λ_c``
        — for an existing vertex's own neighbor list this reproduces
        its embedding row exactly (``a_i·V = V[i,:]·Λ``).  Components
        with |λ| at the spectral floor contribute zero rather than a
        division blow-up.
        """
        import numpy as np

        s = self.X[np.asarray(neighbor_ids, np.int64)].sum(axis=0)
        floor = np.abs(self.lam).max(initial=0.0) * np.finfo(
            self.lam.dtype
        ).eps * max(self.G.n, 1)
        safe = np.abs(self.lam) > floor
        return np.divide(
            s, self.lam, out=np.zeros_like(s), where=safe
        )

    def ppr_report(self, payload: tuple,
                   cache: "ResultCache | None" = None) -> dict:
        """Seed-set PPR community report, memoized by the canonical
        payload ``(sorted-unique seed ids, alpha, gamma, epsilon)`` the
        server validated — coalesced riders with the same seed set share
        one diffusion, mirroring ``LSSystem.cond_report``.  The solve is
        ``find_local_cluster``'s active-support diffusion: work scales
        with the cluster found, not with the graph held.

        When the shared bounded :class:`ResultCache` is passed, the memo
        lives there — keyed on the canonical payload digest and this
        version's epoch, so hot seed sets stay O(lookup) across the
        whole serve path while bounded by LRU + byte budget instead of
        growing without limit.  The per-object ``_ppr_reports`` dict
        remains as the cacheless fallback (``folded`` resets it, so it
        never crosses an epoch)."""
        ck = ("ppr:" + self.name, payload_digest(payload), self.epoch) \
            if cache is not None else None
        if cache is not None:
            rep = cache.get(ck)
            if rep is not None:
                return rep
        rep = self._ppr_reports.get(payload)
        if rep is None:
            from ..graph.community import find_local_cluster

            seeds, alpha, gamma, epsilon = payload
            cluster, cond = find_local_cluster(
                self.G, list(seeds),
                alpha=alpha, gamma=gamma, epsilon=epsilon,
            )
            rep = self._ppr_reports[payload] = {
                "graph": self.name,
                "seeds": [int(v) for v in seeds],
                "cluster": sorted(int(v) for v in cluster),
                "conductance": float(cond),
                "alpha": float(alpha),
                "gamma": float(gamma),
                "epsilon": float(epsilon),
            }
        if cache is not None:
            cache.put(ck, rep, entity=self.name)
        return rep


class Registry:
    def __init__(self, cache: ResultCache | None = None, journal=None):
        self.models: dict[str, object] = {}
        self.systems: dict[str, LSSystem] = {}
        self.graphs: dict[str, GraphSystem] = {}
        # per-model jitted predict closures, built lazily by the batcher
        self.model_jits: dict[str, object] = {}
        # The shared bounded result cache: the front door's response
        # cache AND the cond/ppr report memo are this one instance, so
        # every consumer sees the same epoch-keyed entries and the same
        # LRU/byte bounds.  Invalidation rides _mint below.
        self.cache = cache if cache is not None else ResultCache()
        # -- live-registry epoch discipline ---------------------------------
        # One monotone counter over ALL mutations (registrations and live
        # updates alike); each current version object carries the epoch
        # it was minted at.  epoch_log is the in-process decision ledger.
        self.epoch = 0
        self.epoch_log: list[dict] = []
        self._lock = threading.RLock()
        # -- durability (write-ahead journal) -------------------------------
        # Optional serve/journal.py Journal: every mutation appends its
        # CRC-framed record (and fsyncs) BEFORE publishing, so a crashed
        # replica recovers to the exact epoch it died at (recover()).
        # _replaying suspends journaling while recovery re-executes the
        # journaled mutations through these same methods.
        self.journal = journal
        self._replaying = False
        # Bounded idempotency-receipt window for exactly-once updates
        # across router failover: (tenant, idem_key) -> the minted epoch
        # receipt.  Journal-backed — receipts ride the update records
        # and the compaction snapshot, so they survive a crash too.
        self._idem: OrderedDict[tuple[str, str], dict] = OrderedDict()
        self._idem_window = int(os.environ.get("SKYLARK_IDEM_WINDOW", "1024"))

    def _mint(self, kind: str, name: str, obj=None, **attrs) -> dict:
        with self._lock:
            self.epoch += 1
            epoch = self.epoch
            if obj is not None:
                try:
                    obj.epoch = epoch
                except AttributeError:  # exotic model classes with slots
                    pass
            rec = {"epoch": epoch, "kind": kind, "name": name, **attrs}
            self.epoch_log.append(rec)
        # Retire the mutated entity's cached results immediately.  The
        # epoch in every cache key already guarantees the next request
        # misses (it computes a NEW key); this frees the stale entries'
        # memory rather than waiting for LRU pressure.
        self.cache.invalidate(name)
        telemetry.inc("registry.epoch.bumps")
        telemetry.inc(f"registry.epoch.{kind}")
        telemetry.event("registry", "epoch", rec)
        return rec

    # -- durability ---------------------------------------------------------

    def _journal_active(self) -> bool:
        return self.journal is not None and not self._replaying

    def _journal_append(self, kind, name, payload, attrs, idem=None):
        """Durably append the mutation's record BEFORE it publishes.
        Callers hold ``self._lock``, so ``epoch + 1`` is exactly the
        epoch ``_mint`` will stamp right after the publish."""
        rec = {
            "epoch": self.epoch + 1,
            "kind": kind,
            "name": name,
            "attrs": attrs,
            "payload": payload,
        }
        if idem is not None:
            rec["idem"] = [str(idem[0]), str(idem[1])]
        self.journal.append(rec)

    def _maybe_compact(self) -> None:
        j = self.journal
        if j is None or self._replaying or not j.due():
            return
        from .journal import snapshot_registry

        leaves, meta = snapshot_registry(self)
        j.compact(leaves, meta, step=self.epoch)

    def _record_idem(self, idem, rec) -> None:
        if idem is None:
            return
        key = (str(idem[0]), str(idem[1]))
        with self._lock:
            self._idem[key] = dict(rec)
            self._idem.move_to_end(key)
            while len(self._idem) > self._idem_window:
                self._idem.popitem(last=False)

    def idem_receipt(self, tenant, key):
        """The recorded epoch receipt for ``(tenant, key)``, or ``None``
        — the dedup lookup the server makes before admitting an
        ``op:"update"`` that carries an idempotency key."""
        with self._lock:
            rec = self._idem.get((str(tenant), str(key)))
            return dict(rec) if rec is not None else None

    @classmethod
    def recover(cls, directory, *, cache: ResultCache | None = None,
                compact_every=None, keep_snapshots: int = 2) -> "Registry":
        """Rebuild a registry from its durable state directory: restore
        the newest valid compaction snapshot (field-copy, no recompute),
        then replay the journal tail through the SAME mutator code paths
        that minted it — the result is bitwise-identical to the
        never-crashed registry (entity bits, epoch counter, epoch_log,
        idempotency window).  A torn final journal line is truncated and
        counted (``journal.torn_tail``); mid-journal corruption, epoch
        gaps, or a replay that mints a different record than the journal
        holds raise :class:`~..utils.exceptions.JournalError` (118).
        The returned registry keeps the journal attached, so it keeps
        journaling from the exact epoch it died at."""
        from .journal import REPLAY_HANDLERS, Journal, read_journal, \
            restore_registry

        journal = Journal(directory, compact_every=compact_every,
                          keep_snapshots=keep_snapshots)
        reg = cls(cache=cache, journal=journal)
        reg._replaying = True
        try:
            snap_epoch = 0
            snap = journal.load_snapshot()
            if snap is not None:
                leaves, meta = snap
                restore_registry(reg, leaves, meta)
                snap_epoch = reg.epoch
            # Journal.__init__ already truncated any torn tail, so this
            # read sees a clean prefix.
            records, _ = read_journal(journal.path)
            replayed = 0
            for rec in records:
                if rec["epoch"] <= snap_epoch:
                    # Folded into the snapshot already (a crash between
                    # snapshot commit and journal truncation leaves
                    # these behind — harmless).
                    continue
                if rec["epoch"] != reg.epoch + 1:
                    raise JournalError(
                        f"journal epoch gap: record minted at epoch "
                        f"{rec['epoch']} follows registry epoch "
                        f"{reg.epoch}",
                        path=journal.path, reason="epoch-gap",
                    )
                handler = REPLAY_HANDLERS.get(rec["kind"])
                if handler is None:
                    raise JournalError(
                        f"journal record kind {rec['kind']!r} has no "
                        "replay handler",
                        path=journal.path, reason="unknown-kind",
                    )
                handler(reg, rec)
                minted = reg.epoch_log[-1]
                expect = {"epoch": rec["epoch"], "kind": rec["kind"],
                          "name": rec["name"], **rec["attrs"]}
                if minted != expect:
                    raise JournalError(
                        f"replay divergence at epoch {rec['epoch']}: "
                        f"replay minted {minted!r} but the journal "
                        f"recorded {expect!r}",
                        path=journal.path, reason="replay-divergence",
                    )
                replayed += 1
                telemetry.inc("journal.replays")
            telemetry.event("journal", "recover", {
                "dir": str(directory),
                "epoch": reg.epoch,
                "snapshot_epoch": snap_epoch,
                "replayed": replayed,
                "torn_truncated": journal.torn_truncated,
            })
        finally:
            reg._replaying = False
        return reg

    # -- models -------------------------------------------------------------

    def register_model(self, name: str, model) -> None:
        if not hasattr(model, "predict"):
            raise InvalidParameters(
                f"model {name!r} has no predict(); got {type(model).__name__}"
            )
        with self._lock:
            if self._journal_active():
                from .journal import _enc_array, encode_model

                self._journal_append(
                    "register", name, encode_model(model, _enc_array),
                    {"entity": "model"},
                )
            self.models[name] = model
            self._drop_jits(name)
            self._mint("register", name, model, entity="model")
            self._maybe_compact()

    def load_model(self, name: str, path: str):
        """Load a saved ``ml/model.py`` JSON model once; serve forever."""
        from ..ml.model import load_model

        model = load_model(path)
        self.register_model(name, model)
        return model

    def update_model(self, name: str, model=None, *, append=None,
                     drop=None, idem=None):
        """Live model update: swap wholesale (``model=``), or for a
        :class:`~..ml.model.KernelModel` append/drop training centers —
        predict is linear in the center rows, so the delta is exact
        concatenation/deletion.  Mints an epoch; the superseded model
        object is untouched (in-flight batches keep its bits)."""
        old = self.get_model(name)
        if sum(x is not None for x in (model, append, drop)) != 1:
            raise InvalidParameters(
                "update_model takes exactly one of model=, append=, drop="
            )
        if model is None:
            from ..ml.model import KernelModel

            if not isinstance(old, KernelModel):
                raise UnsupportedError(
                    f"model {name!r} ({type(old).__name__}) supports only "
                    "wholesale swap (model=); center deltas need a "
                    "KernelModel"
                )
            import numpy as np

            X_tr = np.asarray(old.X_train)
            A = np.asarray(old.A)
            if append is not None:
                X_new, A_new = append
                X_new = np.atleast_2d(np.asarray(X_new, X_tr.dtype))
                A_new = np.asarray(A_new, A.dtype).reshape(
                    X_new.shape[0], *A.shape[1:]
                )
                X_tr = np.concatenate([X_tr, X_new])
                A = np.concatenate([A, A_new])
                delta = {"appended": int(X_new.shape[0])}
                # The NORMALIZED delta (post dtype-cast/reshape) is the
                # canonical journal payload: replay re-runs this exact
                # concatenation on identical bits.
                journal_payload = lambda enc: {  # noqa: E731
                    "append_X": enc(X_new), "append_A": enc(A_new),
                }
            else:
                keep = np.setdiff1d(
                    np.arange(X_tr.shape[0]), np.asarray(drop, np.int64)
                )
                dropped = int(X_tr.shape[0]) - int(keep.shape[0])
                X_tr, A = X_tr[keep], A[keep]
                delta = {"dropped": dropped}
                drop_ids = [int(i) for i in np.asarray(drop, np.int64)]
                journal_payload = lambda enc: {"drop": drop_ids}  # noqa: E731
            model = KernelModel(old.kernel, X_tr, A, classes=old.classes)
        elif not hasattr(model, "predict"):
            raise InvalidParameters(
                f"model {name!r} update has no predict(); got "
                f"{type(model).__name__}"
            )
        else:
            delta = {"swapped": True}
            swapped = model

            def journal_payload(enc):
                from .journal import encode_model

                return {"model": encode_model(swapped, enc)}
        with self._lock:
            if self._journal_active():
                from .journal import _enc_array

                self._journal_append(
                    "model_update", name, journal_payload(_enc_array),
                    dict(delta), idem=idem,
                )
            self.models[name] = model
            self._drop_jits(name)
            rec = self._mint("model_update", name, model, **delta)
            self._record_idem(idem, rec)
            self._maybe_compact()
        return model, rec

    def _drop_jits(self, name: str) -> None:
        """Invalidate every cached predict closure for ``name`` — the
        batcher keys them by (name, epoch) so a pinned in-flight batch
        rebuilding the OLD version's closure can never be served to
        new-epoch traffic."""
        for k in [k for k in self.model_jits
                  if k == name or (isinstance(k, tuple) and k[0] == name)]:
            self.model_jits.pop(k, None)

    def get_model(self, name: str):
        try:
            return self.models[name]
        except KeyError:
            raise InvalidParameters(
                f"unknown model {name!r}; registered: {sorted(self.models)}"
            ) from None

    # -- LS systems ---------------------------------------------------------

    def register_system(
        self,
        name: str,
        A,
        *,
        context: SketchContext,
        sketch=None,
        sketch_type: str = "FJLT",
        sketch_size: int | None = None,
        capacity: int | None = None,
    ) -> LSSystem:
        """Register a least-squares design matrix.

        ``sketch`` may be a live transform, a serialized-sketch JSON
        string, or a dict (the ``native/`` interchange forms); absent,
        a fresh ``sketch_type`` transform is drawn from ``context`` —
        the server's counter stream, so registration order addresses it
        deterministically.  ``capacity`` reserves sketch-domain rows
        beyond ``A``'s for later live appends.
        """
        A = jnp.asarray(A)
        m = int(A.shape[0])
        dom = int(capacity) if capacity else m
        if isinstance(sketch, str):
            sketch = sketch_base.from_json(sketch)
        elif isinstance(sketch, dict):
            sketch = sketch_base.from_dict(sketch)
        if sketch is None:
            n = int(A.shape[1]) if A.ndim == 2 else 1
            s = int(sketch_size or min(m, max(4 * n, n + 16)))
            sketch = sketch_base.create_sketch(sketch_type, dom, s, context)
        system = LSSystem(name, A, sketch, capacity=capacity)
        with self._lock:
            if self._journal_active():
                from .journal import _enc_array, encode_system

                self._journal_append(
                    "register", name, encode_system(system, _enc_array),
                    {"entity": "system"},
                )
            self.systems[name] = system
            self._mint("register", name, system, entity="system")
            self._maybe_compact()
        return system

    def append_system_rows(self, name: str, rows,
                           idem=None) -> tuple[LSSystem, int]:
        """Live row append: publish a NEW version with ``rows`` folded
        into the retained ``S·A`` (exact ``apply_slice`` delta), mint an
        epoch, and leave the superseded version's bits untouched for
        whatever batches admitted under it."""
        with self._lock:
            old = self.get_system(name)
            new = old.appended(rows)
            if self._journal_active():
                from .journal import _enc_array

                # Journal the rows as the new version holds them (post
                # dtype-cast/reshape): the exact bits replay will append.
                self._journal_append(
                    "row_append", name,
                    {"rows": _enc_array(new.A[old.m:new.m])},
                    {"rows": int(new.m - old.m), "m": new.m}, idem=idem,
                )
            self.systems[name] = new
            rec = self._mint(
                "row_append", name, new,
                rows=int(new.m - old.m), m=new.m,
            )
            self._record_idem(idem, rec)
            self._maybe_compact()
        return new, rec

    def downdate_system_rows(self, name: str, indices,
                             idem=None) -> tuple[LSSystem, int]:
        """Live row downdate (retirement): the mirror of append —
        subtract the rows' sketch contribution, re-QR, mint an epoch."""
        with self._lock:
            old = self.get_system(name)
            new = old.downdated(indices)
            if self._journal_active():
                self._journal_append(
                    "row_downdate", name,
                    {"drop": sorted({int(i) for i in indices})},
                    {"rows": len(new.retired) - len(old.retired),
                     "retired": len(new.retired)}, idem=idem,
                )
            self.systems[name] = new
            rec = self._mint(
                "row_downdate", name, new,
                rows=len(new.retired) - len(old.retired),
                retired=len(new.retired),
            )
            self._record_idem(idem, rec)
            self._maybe_compact()
        return new, rec

    def get_system(self, name: str) -> LSSystem:
        try:
            return self.systems[name]
        except KeyError:
            raise InvalidParameters(
                f"unknown system {name!r}; registered: {sorted(self.systems)}"
            ) from None

    # -- graphs -------------------------------------------------------------

    def register_graph(
        self,
        name: str,
        G,
        *,
        k: int = 8,
        context: SketchContext | None = None,
        params=None,
    ) -> GraphSystem:
        """Register a graph: the ASE embedding is computed here, once,
        through the retained-sketch streaming route (``Ω`` and ``S·A``
        are kept, so the system is live — :meth:`fold_graph_edges`);
        ``ppr`` / ``ase_embed`` requests afterwards serve from the
        resident embedding and the memoized diffusion."""
        gsys = GraphSystem(name, G, k=k, context=context, params=params)
        with self._lock:
            if self._journal_active():
                from .journal import _enc_array, encode_graph

                self._journal_append(
                    "register", name, encode_graph(gsys, _enc_array),
                    {"entity": "graph"},
                )
            self.graphs[name] = gsys
            self._mint("register", name, gsys, entity="graph")
            self._maybe_compact()
        return gsys

    def fold_graph_edges(self, name: str, pairs,
                         idem=None) -> tuple[GraphSystem, int]:
        """Live edge fold: publish a NEW version whose retained ``Ω·A``
        absorbed the batch (one delta fold + the small-math embedding
        refresh — bitwise ≡ re-registration of the merged graph), mint
        an epoch.  In-flight batches pinned to the old version keep its
        exact bits; the old object is simply no longer the head."""
        pairs = [(u, v) for u, v in pairs]
        with self._lock:
            old = self.get_graph(name)
            new, folded = old.folded(pairs)
            if self._journal_active():
                from .journal import _json_vertex

                self._journal_append(
                    "graph_fold", name,
                    {"edges": [[_json_vertex(u), _json_vertex(v)]
                               for u, v in pairs]},
                    {"edges": folded, "volume": int(new.G.volume)},
                    idem=idem,
                )
            self.graphs[name] = new
            rec = self._mint(
                "graph_fold", name, new,
                edges=folded, volume=int(new.G.volume),
            )
            self._record_idem(idem, rec)
            self._maybe_compact()
        return new, rec

    def get_graph(self, name: str) -> GraphSystem:
        try:
            return self.graphs[name]
        except KeyError:
            raise InvalidParameters(
                f"unknown graph {name!r}; registered: {sorted(self.graphs)}"
            ) from None

    def describe(self) -> dict:
        models = {}
        for name, model in self.models.items():
            models[name] = {
                "kind": type(model).__name__,
                "input_dim": getattr(model, "input_dim", None),
                "classes": getattr(model, "classes", None) is not None,
                "epoch": getattr(model, "epoch", 0),
            }
        return {
            "models": models,
            "systems": {k: s.describe() for k, s in self.systems.items()},
            "graphs": {k: g.describe() for k, g in self.graphs.items()},
            "epoch": self.epoch,
        }
