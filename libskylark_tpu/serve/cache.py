"""Versioned, bounded result cache for the serve front door.

Sketching is deterministic — the serialized-sketch interchange the
reference ships (PAPER.md §1) exists precisely because the same seed +
the same rows give the same bits — so a repeated idempotent request
(``cond_est`` dashboard poll, hot PPR seed set, OOS embed of the same
vertices) can be re-served *bitwise* from a dict instead of burning a
device dispatch.  The cache is keyed on

    ``(placement_key, canonical payload digest, registry epoch)``

The epoch component is what makes staleness structurally impossible: a
live-registry mint (edge fold, row append/downdate, model swap) bumps
the entity's epoch, so the very next request computes a DIFFERENT key
and misses — even if the old entry were still resident.  Explicit
:meth:`ResultCache.invalidate` (called from ``Registry._mint``) is
therefore a memory optimisation, not a correctness mechanism: it frees
the retired entity's entries immediately instead of waiting for LRU
pressure.  In-flight batches that admitted pinned to the old epoch are
unaffected either way — they never consult the cache after admission.

Bounding is LRU over entry count AND a byte budget (estimated via
ndarray ``nbytes`` + repr cost for scalars), because a single cached
``ase_embed`` row block can outweigh a thousand cond reports.

Knobs: ``SKYLARK_CACHE`` (``0`` disables), ``SKYLARK_CACHE_MAX_ENTRIES``
(default 1024), ``SKYLARK_CACHE_MAX_BYTES`` (default 64 MiB).
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict

import numpy as np

from .. import telemetry

__all__ = ["ResultCache", "payload_digest", "payload_crc"]


def _canonical_bytes(obj):
    """Stable byte serialisation of a request payload component.

    ndarrays hash as dtype + shape + raw bytes (bitwise identity, the
    only identity the serve layer promises); tuples/lists recurse with
    framing so ``(1, (2, 3))`` and ``(1, 2, 3)`` differ; everything
    else falls back to ``repr`` (ints, floats, strs, None — all of
    which repr stably).
    """
    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        head = "A|%s|%s|" % (arr.dtype.str, arr.shape)
        return head.encode("ascii") + arr.tobytes()
    if isinstance(obj, (tuple, list)):
        parts = [b"T|" if isinstance(obj, tuple) else b"L|"]
        for item in obj:
            b = _canonical_bytes(item)
            parts.append(b"%d:" % len(b))
            parts.append(b)
        return b"".join(parts)
    if isinstance(obj, dict):
        items = sorted(obj.items(), key=lambda kv: repr(kv[0]))
        return _canonical_bytes(("D",) + tuple(x for kv in items for x in kv))
    return ("R|" + repr(obj)).encode("utf-8", "backslashreplace")


def payload_digest(payload):
    """128-bit BLAKE2b digest of the canonical request payload bytes.

    A real cryptographic hash, not a CRC: crc32 is linear over GF(2),
    so ANY equal-length crc32 collision of the canonical bytes also
    collides under every domain-prefixed crc32 of those bytes — doubling
    the CRC widens the word, not the collision resistance, and a
    high-QPS hot set would eventually serve another request's bits.
    BLAKE2b at 16 bytes keeps birthday collisions at ~2^-64 across any
    realistic resident set; the ``person`` tag domain-separates these
    digests from any other BLAKE2b use in the process.
    """
    data = _canonical_bytes(payload)
    h = hashlib.blake2b(data, digest_size=16, person=b"skylark-cache")
    return int.from_bytes(h.digest(), "big")


#: Legacy name (pre-review the digest was a doubled crc32).
payload_crc = payload_digest


def _value_nbytes(value):
    """Best-effort byte estimate of a cached result."""
    if isinstance(value, np.ndarray):
        return int(value.nbytes) + 64
    if isinstance(value, dict):
        return sum(_value_nbytes(v) for v in value.values()) + 64
    if isinstance(value, (tuple, list)):
        return sum(_value_nbytes(v) for v in value) + 64
    return len(repr(value)) + 48


def _copy_in(value):
    """Deep, frozen snapshot of a value entering the cache.

    Containers are rebuilt recursively and ndarrays copied with
    ``writeable=False``, so the producer keeping (and later mutating)
    its own reference — the batcher hands the same decoded result to
    the response envelope — can never alter the stored bits.  The copy
    runs once per *miss*, where a device dispatch just happened; it is
    noise next to the work it memoizes.
    """
    if isinstance(value, np.ndarray):
        arr = value.copy()
        arr.flags.writeable = False
        return arr
    if isinstance(value, dict):
        return {k: _copy_in(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_copy_in(v) for v in value]
    if isinstance(value, tuple):
        return tuple(_copy_in(v) for v in value)
    return value


def _copy_out(value):
    """Caller-safe projection of a cached value.

    Containers (dicts, lists, tuples — including nested PPR cluster /
    member lists) are rebuilt fresh so mutating the returned structure
    cannot poison the cache; ndarrays come back as read-only *views* of
    the frozen stored copy — zero data movement on the hit path, and a
    caller writing into one raises instead of corrupting every future
    hit.
    """
    if isinstance(value, np.ndarray):
        view = value.view()
        view.flags.writeable = False
        return view
    if isinstance(value, dict):
        return {k: _copy_out(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_copy_out(v) for v in value]
    if isinstance(value, tuple):
        return tuple(_copy_out(v) for v in value)
    return value


class ResultCache:
    """Bounded (LRU + byte budget) versioned result cache.

    Thread-safe; shared by the front-door response path, the
    ``cond_report``/``ppr_report`` memoizers, and (via ``stats()`` on
    the load-report plane) the router's placement tie-break.
    """

    def __init__(self, max_entries=None, max_bytes=None, enabled=None):
        if enabled is None:
            enabled = os.environ.get("SKYLARK_CACHE", "1") != "0"
        if max_entries is None:
            max_entries = int(os.environ.get(
                "SKYLARK_CACHE_MAX_ENTRIES", "1024"))
        if max_bytes is None:
            max_bytes = int(os.environ.get(
                "SKYLARK_CACHE_MAX_BYTES", str(64 * 1024 * 1024)))
        self.enabled = bool(enabled)
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = max(1, int(max_bytes))
        self._lock = threading.Lock()
        self._d = OrderedDict()          # key -> (value, nbytes, entity)
        self._by_entity = {}             # entity -> set of keys
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0

    # -- core ---------------------------------------------------------------

    def get(self, key):
        """Return the cached value for ``key`` (LRU-refreshed) or None."""
        if not self.enabled or key is None:
            return None
        with self._lock:
            rec = self._d.get(key)
            if rec is None:
                self.misses += 1
                if telemetry.enabled():
                    telemetry.inc("serve.cache.miss")
                return None
            self._d.move_to_end(key)
            self.hits += 1
        if telemetry.enabled():
            telemetry.inc("serve.cache.hit")
        return _copy_out(rec[0])

    def put(self, key, value, entity=None):
        """Insert ``value`` under ``key``, attributing it to ``entity``
        for targeted invalidation.  Oversized values (> byte budget) are
        refused rather than evicting the whole cache for one entry."""
        if not self.enabled or key is None:
            return
        nb = _value_nbytes(value)
        if nb > self.max_bytes:
            return
        value = _copy_in(value)
        with self._lock:
            old = self._d.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
                keys = self._by_entity.get(old[2])
                if keys is not None:
                    keys.discard(key)
            self._d[key] = (value, nb, entity)
            self._bytes += nb
            if entity is not None:
                self._by_entity.setdefault(entity, set()).add(key)
            while (len(self._d) > self.max_entries
                   or self._bytes > self.max_bytes):
                self._evict_lru_locked()

    def _evict_lru_locked(self):
        k, (_, nb, entity) = self._d.popitem(last=False)
        self._bytes -= nb
        self.evictions += 1
        keys = self._by_entity.get(entity)
        if keys is not None:
            keys.discard(k)
            if not keys:
                self._by_entity.pop(entity, None)
        if telemetry.enabled():
            telemetry.inc("serve.cache.evictions")

    def invalidate(self, entity):
        """Drop every key attributed to ``entity`` (a registry mint just
        retired its epoch).  Returns the number of entries dropped."""
        if entity is None:
            return 0
        with self._lock:
            keys = self._by_entity.pop(entity, None)
            if not keys:
                return 0
            n = 0
            for k in keys:
                rec = self._d.pop(k, None)
                if rec is not None:
                    self._bytes -= rec[1]
                    n += 1
            self.invalidations += n
        if telemetry.enabled() and n:
            telemetry.inc("serve.cache.invalidations", n)
        return n

    def clear(self):
        with self._lock:
            self._d.clear()
            self._by_entity.clear()
            self._bytes = 0

    # -- introspection ------------------------------------------------------

    def __len__(self):
        with self._lock:
            return len(self._d)

    def key_census(self):
        """placement_key -> cached entry count, for the router's
        fleet-wide hit sharing: a replica that already holds a hot key's
        result wins placement ties so the fleet pays ONE dispatch."""
        census = {}
        with self._lock:
            for (pkey, _crc, _epoch) in self._d:
                census[pkey] = census.get(pkey, 0) + 1
        return census

    def stats(self):
        with self._lock:
            return {
                "enabled": self.enabled,
                "entries": len(self._d),
                "bytes": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "keys": self.key_census_locked(),
            }

    def key_census_locked(self):
        census = {}
        for (pkey, _crc, _epoch) in self._d:
            census[pkey] = census.get(pkey, 0) + 1
        return census
