"""The Python client: in-process (zero-copy futures) or HTTP loopback.

``Client(server)`` talks straight to a :class:`~.server.Server` in the
same process — results come back as numpy arrays, and concurrent
callers coalesce.  ``Client(url="http://127.0.0.1:PORT")`` speaks the
:mod:`.transport` HTTP front end — results come back as the protocol's
nested lists.

HTTP clients hold one **keep-alive** connection per calling thread
(the transport speaks HTTP/1.1): under a serving workload of many
small requests, the TCP handshake would otherwise dominate the wire
cost of a ~100-byte frame.  A connection that went stale between calls
is retried once on a fresh socket; the reused-vs-fresh split is
counted (``serve.client_conn_reused`` / ``serve.client_conn_fresh``)
so a fleet bench can verify reuse is actually happening.

Every convenience method returns the protocol response dict by default;
``check=True`` unwraps ``result`` and re-raises structured errors as
their :mod:`utils.exceptions` classes (code-mapped)."""

from __future__ import annotations

import http.client
import json
import os
import threading
import uuid
from urllib.parse import urlsplit

from .. import telemetry
from . import protocol

__all__ = ["Client", "default_timeout_s"]


def default_timeout_s() -> float:
    """The bounded socket read timeout HTTP clients use when none is
    passed: ``SKYLARK_HTTP_TIMEOUT_S`` (seconds, default 60).  Bounded
    by default on purpose — a hung replica must surface as a timeout
    the router can eject on (the 114 path), never block a caller
    thread forever on ``recv``."""
    return float(os.environ.get("SKYLARK_HTTP_TIMEOUT_S", "60"))


class Client:
    def __init__(self, server=None, *, url: str | None = None,
                 timeout: float | None = None):
        if (server is None) == (url is None):
            raise ValueError("pass exactly one of server= or url=")
        self._server = server
        self._url = url.rstrip("/") if url else None
        self._timeout = default_timeout_s() if timeout is None else timeout
        self._local = threading.local()
        if self._url:
            parts = urlsplit(self._url)
            self._host = parts.hostname or "127.0.0.1"
            self._port = parts.port or 80
            self._base = parts.path.rstrip("/")

    # -- transport ----------------------------------------------------------

    def _request(self, method: str, path: str,
                 body: bytes | None = None) -> str:
        """One HTTP exchange over this thread's keep-alive connection.

        A reused connection the server has since closed fails on the
        first read — retried ONCE on a fresh socket; errors on a fresh
        connection propagate (the server is actually down)."""
        for _ in range(2):
            conn = getattr(self._local, "conn", None)
            fresh = conn is None
            if fresh:
                conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=self._timeout
                )
                self._local.conn = conn
            try:
                conn.request(
                    method, self._base + path, body=body,
                    headers={"Content-Type": "application/json"}
                    if body is not None else {},
                )
                resp = conn.getresponse()
                text = resp.read().decode()
                if resp.will_close:
                    conn.close()
                    self._local.conn = None
                telemetry.inc(
                    "serve.client_conn_fresh" if fresh
                    else "serve.client_conn_reused"
                )
                return text
            except (http.client.HTTPException, OSError):
                conn.close()
                self._local.conn = None
                if fresh:
                    raise
        raise OSError("unreachable")  # pragma: no cover

    def close(self) -> None:
        """Drop this thread's keep-alive connection (idempotent)."""
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None

    def call(self, request: dict | None = None, /, **fields) -> dict:
        req = dict(request or {}, **fields)
        if self._server is not None:
            return self._server.call(req)
        return protocol.decode(
            self._request("POST", "/", protocol.encode(req).encode())
        )

    def call_many(self, requests: list[dict]) -> list[dict]:
        """Submit concurrently (the coalescing path for remote callers)."""
        if self._server is not None:
            futures = [self._server.submit(r) for r in requests]
            return [f.result() for f in futures]
        data = json.dumps(requests, default=lambda o: o.tolist()).encode()
        return json.loads(self._request("POST", "/", data))

    def healthz(self) -> dict:
        """The server's ``/healthz`` (includes the ``load`` report the
        fleet router places by); in-process, the report directly."""
        if self._server is not None:
            return {
                "ok": True,
                "load": self._server.load_report(),
                "primed": list(self._server.primed),
            }
        return json.loads(self._request("GET", "/healthz"))

    # -- conveniences -------------------------------------------------------

    @staticmethod
    def _unwrap(response: dict, check: bool):
        if not check:
            return response
        return protocol.raise_for_error(response)["result"]

    def ls_solve(self, system: str, b, *, check: bool = False, **fields):
        return self._unwrap(
            self.call(op="ls_solve", system=system, b=b, **fields), check
        )

    def cond_est(self, system: str, *, check: bool = False, **fields):
        return self._unwrap(
            self.call(op="cond_est", system=system, **fields), check
        )

    def predict(self, model: str, x, *, labels: bool = False,
                check: bool = False, **fields):
        return self._unwrap(
            self.call(op="predict", model=model, x=x, labels=labels, **fields),
            check,
        )

    def ppr(self, graph: str, seeds, *, check: bool = False, **fields):
        return self._unwrap(
            self.call(op="ppr", graph=graph, seeds=seeds, **fields), check
        )

    def ase_embed(self, graph: str, *, check: bool = False, **fields):
        """Embedding queries: pass ``ids=`` for row lookups or
        ``neighbors=`` for an out-of-sample projection (exactly one)."""
        return self._unwrap(
            self.call(op="ase_embed", graph=graph, **fields), check
        )

    def update(self, *, check: bool = False, idem_key: str | None = None,
               **fields):
        """Live-registry mutation with exactly-once semantics: mints a
        fresh idempotency key when the caller supplies none, so a retry
        of THIS call (client timeout whose first send actually landed,
        router failover re-placement) can never double-apply — the
        server's dedup window returns the original epoch receipt."""
        if idem_key is None:
            idem_key = uuid.uuid4().hex
        return self._unwrap(
            self.call(op="update", idem_key=idem_key, **fields), check
        )

    def ping(self) -> bool:
        return bool(self.call(op="ping").get("ok"))

    def stats(self) -> dict:
        return protocol.raise_for_error(self.call(op="stats"))["result"]
