"""The Python client: in-process (zero-copy futures) or HTTP loopback.

``Client(server)`` talks straight to a :class:`~.server.Server` in the
same process — results come back as numpy arrays, and concurrent
callers coalesce.  ``Client(url="http://127.0.0.1:PORT")`` speaks the
:mod:`.transport` HTTP front end — results come back as the protocol's
nested lists.

Every convenience method returns the protocol response dict by default;
``check=True`` unwraps ``result`` and re-raises structured errors as
their :mod:`utils.exceptions` classes (code-mapped)."""

from __future__ import annotations

import json
import urllib.request

from . import protocol

__all__ = ["Client"]


class Client:
    def __init__(self, server=None, *, url: str | None = None,
                 timeout: float = 60.0):
        if (server is None) == (url is None):
            raise ValueError("pass exactly one of server= or url=")
        self._server = server
        self._url = url.rstrip("/") if url else None
        self._timeout = timeout

    # -- transport ----------------------------------------------------------

    def call(self, request: dict | None = None, /, **fields) -> dict:
        req = dict(request or {}, **fields)
        if self._server is not None:
            return self._server.call(req)
        data = protocol.encode(req).encode()
        http_req = urllib.request.Request(
            self._url + "/", data=data,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(http_req, timeout=self._timeout) as r:
            return protocol.decode(r.read().decode())

    def call_many(self, requests: list[dict]) -> list[dict]:
        """Submit concurrently (the coalescing path for remote callers)."""
        if self._server is not None:
            futures = [self._server.submit(r) for r in requests]
            return [f.result() for f in futures]
        data = json.dumps(
            requests, default=lambda o: o.tolist()
        ).encode()
        http_req = urllib.request.Request(
            self._url + "/", data=data,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(http_req, timeout=self._timeout) as r:
            return json.loads(r.read().decode())

    # -- conveniences -------------------------------------------------------

    @staticmethod
    def _unwrap(response: dict, check: bool):
        if not check:
            return response
        return protocol.raise_for_error(response)["result"]

    def ls_solve(self, system: str, b, *, check: bool = False, **fields):
        return self._unwrap(
            self.call(op="ls_solve", system=system, b=b, **fields), check
        )

    def predict(self, model: str, x, *, labels: bool = False,
                check: bool = False, **fields):
        return self._unwrap(
            self.call(op="predict", model=model, x=x, labels=labels, **fields),
            check,
        )

    def ping(self) -> bool:
        return bool(self.call(op="ping").get("ok"))

    def stats(self) -> dict:
        return protocol.raise_for_error(self.call(op="stats"))["result"]
