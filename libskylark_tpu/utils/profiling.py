"""Device-level tracing (the TPU replacement for the reference's
compile-time profiler macros, SURVEY §5: "jax.profiler traces + per-phase
wall timers").

``PhaseTimer`` (``utils.timer``) covers the wall-clock side; this module
wraps ``jax.profiler`` for op-level traces viewable in XProf/TensorBoard.
"""

from __future__ import annotations

from contextlib import contextmanager

import jax

__all__ = ["trace", "annotate"]


@contextmanager
def trace(logdir: str):
    """Capture a device trace into ``logdir`` (open with xprof/TensorBoard).

    Usage::

        with profiling.trace("/tmp/skylark-trace"):
            model = solver.train(X, y)
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (≙ the reference's per-phase timer
    labels); usable as decorator or context manager."""
    return jax.profiler.TraceAnnotation(name)
