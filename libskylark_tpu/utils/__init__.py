"""Utility subsystems (≙ reference ``utility/`` + ``base/exception.hpp``):
phase timers, exceptions, solver checkpointing."""

from . import profiling
from .checkpoint import load_solver_state, save_solver_state
from .exceptions import (
    AllocationError,
    IOError_,
    InvalidParameters,
    SkylarkError,
    SketchError,
    UnsupportedError,
)
from .timer import PhaseTimer, timer_report

__all__ = [
    "profiling",
    "PhaseTimer",
    "timer_report",
    "SkylarkError",
    "AllocationError",
    "InvalidParameters",
    "SketchError",
    "UnsupportedError",
    "IOError_",
    "save_solver_state",
    "load_solver_state",
]
