"""Utility subsystems (≙ reference ``utility/`` + ``base/exception.hpp``):
phase timers, exceptions, solver checkpointing."""

from . import profiling
from .checkpoint import CheckpointStore, load_solver_state, save_solver_state
from .exceptions import (
    AllocationError,
    CheckpointError,
    ConvergenceError,
    IOError_,
    InvalidParameters,
    SkylarkError,
    SketchError,
    UnsupportedError,
    WorldMismatchError,
)
from .timer import PhaseTimer, timer_report

__all__ = [
    "profiling",
    "PhaseTimer",
    "timer_report",
    "SkylarkError",
    "AllocationError",
    "InvalidParameters",
    "SketchError",
    "UnsupportedError",
    "IOError_",
    "ConvergenceError",
    "CheckpointError",
    "WorldMismatchError",
    "save_solver_state",
    "load_solver_state",
    "CheckpointStore",
]
