"""Optional-dependency import with an actionable install hint.

The base install depends only on ``jax`` + ``numpy``
(``pyproject.toml``); scipy / h5py / fsspec live behind extras.  Features
that need them (skylark-community, skylark-convert2hdf5, HDF5 IO, remote
fsspec sources) import through this helper so a bare install fails with
the pip command to run, not a raw ``ModuleNotFoundError`` (round-2
advisor finding).
"""

from __future__ import annotations

import importlib

__all__ = ["require"]

# module name -> the extra that provides it
_EXTRAS = {"scipy": "ml", "h5py": "io", "fsspec": "io"}


def require(module: str):
    """Import ``module`` (dotted paths allowed), or raise ImportError
    naming the ``pip install 'libskylark-tpu[extra]'`` that provides it."""
    try:
        return importlib.import_module(module)
    except ImportError as e:
        root = module.split(".", 1)[0]
        extra = _EXTRAS.get(root)
        hint = (
            f"pip install 'libskylark-tpu[{extra}]'"
            if extra
            else f"pip install {root}"
        )
        raise ImportError(
            f"{root!r} is required for this feature but is not installed; "
            f"run: {hint}"
        ) from e
