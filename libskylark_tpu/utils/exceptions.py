"""Exception hierarchy with stable error codes (≙ ``base/exception.hpp``).

The reference maps exceptions to C-API error codes; the codes are kept so
a future C shim can translate 1:1.
"""

from __future__ import annotations

__all__ = [
    "SkylarkError",
    "AllocationError",
    "InvalidParameters",
    "SketchError",
    "UnsupportedError",
    "IOError_",
    "ConvergenceError",
    "CheckpointError",
    "NumericalHealthError",
    "WorldMismatchError",
    "CollectiveTimeoutError",
    "StaleEpochError",
    "AdmissionError",
    "DeadlineExceededError",
    "ReplicaLostError",
    "RefinementError",
    "RegistryEpochError",
    "QuotaExceededError",
    "JournalError",
]


class SkylarkError(Exception):
    """Base (≙ ``skylark_exception``, code 100)."""

    code = 100


class AllocationError(SkylarkError):
    code = 101


class InvalidParameters(SkylarkError, ValueError):
    code = 102


class SketchError(SkylarkError):
    code = 103


class UnsupportedError(SkylarkError, NotImplementedError):
    code = 104


class IOError_(SkylarkError, IOError):
    code = 105


class ConvergenceError(SkylarkError):
    """An iterative solve diverged (NaN/Inf iterates) or was halted by a
    guard.  ``result`` carries the best iterate observed before the halt
    (``(X, info)`` for Krylov solvers, a model for ADMM) so callers can
    degrade gracefully instead of receiving silent garbage."""

    code = 106

    def __init__(self, msg, result=None, iteration=None):
        super().__init__(msg)
        self.result = result
        self.iteration = iteration


class CheckpointError(IOError_):
    """A checkpoint failed integrity validation (bad CRC, wrong object
    type, missing leaves, unreadable container).  Subclasses ``IOError_``
    so pre-existing IO error handling keeps working."""

    code = 107


class NumericalHealthError(SkylarkError):
    """A numerical-health guard fired and the recovery ladder could not
    repair the computation (or guarding was disabled at a point where
    the only safe continuation was a fallback).  ``stage`` names the
    pipeline stage whose probe tripped (e.g. ``"sketch_ls"``,
    ``"streaming_krr"``); ``report`` is the
    :class:`~libskylark_tpu.guard.RecoveryReport` accumulated up to the
    failure, so callers can inspect every attempt that was made."""

    code = 108

    def __init__(self, msg, stage=None, report=None):
        super().__init__(msg)
        self.stage = stage
        self.report = report


class WorldMismatchError(SkylarkError):
    """An elastic distributed stream was resumed (or joined) under a
    world that disagrees with the one that wrote its state: different
    ``jax.distributed`` world size, a different row partition, or ranks
    whose partition/epoch signatures disagree at the barrier handshake.
    Merging partial sketches across such a mismatch would silently
    combine stale or mis-addressed partials, so the engine fails fast
    instead.  ``expected``/``got`` carry the two sides of the mismatch
    (dicts or scalars, best-effort) for diagnostics."""

    code = 109

    def __init__(self, msg, expected=None, got=None):
        super().__init__(msg)
        self.expected = expected
        self.got = got


class CollectiveTimeoutError(SkylarkError):
    """A deadline-bounded collective (elastic handshake, cross-host psum)
    did not complete within its configured deadline: at least one peer
    never arrived — dead, hung, or stuck in device work.  Raised instead
    of hanging the world forever so an orchestrator can kill the job and
    resume with ``resume_policy="repartition"``.  ``phase`` names the
    collective; ``deadline_s`` is the budget that expired; ``stragglers``
    lists the ranks whose heartbeats never reached the phase (best-effort
    — empty when no heartbeat directory was configured)."""

    code = 110

    def __init__(self, msg, phase=None, deadline_s=None, stragglers=None):
        super().__init__(msg)
        self.phase = phase
        self.deadline_s = deadline_s
        self.stragglers = stragglers


class StaleEpochError(SkylarkError):
    """This process is operating at an elastic epoch the world has moved
    past: the shared root's epoch marker (or a peer's heartbeat, or a
    checkpoint slot's manifest) carries a HIGHER epoch than this writer
    was started with.  The process is fenced out — its partials belong
    to a superseded partition and must not be merged or overwritten into
    the new epoch's state.  Deliberately NOT a ``CheckpointError``: the
    store's corrupt-slot fallback must not swallow it and silently load
    an equally-stale older slot.  ``expected``/``got`` carry the two
    epochs."""

    code = 111

    def __init__(self, msg, expected=None, got=None):
        super().__init__(msg)
        self.expected = expected
        self.got = got


class AdmissionError(SkylarkError):
    """The serve layer's bounded request queue refused a request at
    admission: accepting it would exceed the configured queue depth.
    Load-shedding at the door keeps queue wait (and therefore tail
    latency) bounded under overload — the caller should back off and
    retry rather than pile on.  ``queue_depth``/``max_depth`` carry the
    observed and configured depths."""

    code = 112

    def __init__(self, msg, queue_depth=None, max_depth=None):
        super().__init__(msg)
        self.queue_depth = queue_depth
        self.max_depth = max_depth


class DeadlineExceededError(SkylarkError):
    """A served request's deadline expired before its batch dispatched
    (or before admission completed).  Shedding at dispatch time — not
    after compute — means an expired request never burns device work its
    caller has already given up on.  ``deadline_ms`` is the budget the
    request carried; ``waited_ms`` how long it actually sat queued."""

    code = 113

    def __init__(self, msg, deadline_ms=None, waited_ms=None):
        super().__init__(msg)
        self.deadline_ms = deadline_ms
        self.waited_ms = waited_ms


class ReplicaLostError(SkylarkError):
    """A serving replica disappeared from the fleet: its load-report
    heartbeat went stale past the router's timeout, its worker thread
    died, or a request in flight to it failed at the transport layer.
    The router ejects the replica from the membership table (bumping the
    fleet epoch so placement decisions are fenced, exactly like the
    elastic layer's :class:`StaleEpochError` discipline) and re-places
    the affected keys on the survivors; this error reaches a caller only
    when NO placeable replica remains.  ``replica`` names the lost
    member; ``last_heartbeat_s`` is the age of its last successful load
    report (best-effort, ``None`` when it never reported)."""

    code = 114

    def __init__(self, msg, replica=None, last_heartbeat_s=None):
        super().__init__(msg)
        self.replica = replica
        self.last_heartbeat_s = last_heartbeat_s


class RefinementError(SkylarkError):
    """Mixed-precision iterative refinement stagnated or diverged: the
    f64 residual gate was not reached before the stagnation/divergence
    detector fired (correction norms stopped contracting, or an iterate
    went non-finite).  Under the guard ladder this is absorbed as a
    resketch verdict — the ladder falls back down its existing rungs and
    ultimately to the exact dense solve — so the error reaches a caller
    only when guarding is disabled.  ``iters`` is the iteration count at
    the halt, ``residual`` the best certified gate value observed, and
    ``stage`` the pipeline stage (``"refine_ls"``)."""

    code = 115

    def __init__(self, msg, iters=None, residual=None, stage=None):
        super().__init__(msg)
        self.iters = iters
        self.residual = residual
        self.stage = stage


class RegistryEpochError(SkylarkError):
    """A served request pinned a registry version the entity no longer
    (or does not yet) serves: live registries mint a new epoch per
    update (edge fold, row append/downdate, model swap) and retire the
    superseded version once its in-flight batches drain.  Failing fast
    with the two epochs — instead of serving the CURRENT version to a
    caller that asked for a retired one — is what keeps the bitwise
    contract honest: a pinned caller either gets the exact bits of the
    version it named or a structured refusal, never silently-new bits.
    ``requested``/``current`` carry the two epochs; ``entity`` names
    the registered system/model/graph."""

    code = 116

    def __init__(self, msg, requested=None, current=None, entity=None):
        super().__init__(msg)
        self.requested = requested
        self.current = current
        self.entity = entity


class QuotaExceededError(SkylarkError):
    """A served request was shed at the door because its TENANT's
    token-bucket quota is exhausted — distinct from the global
    depth/deadline sheds (112/113), which protect the *server*: this
    code protects the *other tenants*.  A noisy tenant burning its
    bucket keeps shedding 117 while polite tenants' requests admit
    normally, so one caller's retry storm can no longer starve the
    shared queue.  ``tenant`` names the lane; ``rate``/``burst`` are
    the bucket's configured refill rate (requests/s) and capacity;
    ``retry_after_ms`` is how long until one token accrues — the
    structured backoff hint."""

    code = 117

    def __init__(self, msg, tenant=None, rate=None, burst=None,
                 retry_after_ms=None):
        super().__init__(msg)
        self.tenant = tenant
        self.rate = rate
        self.burst = burst
        self.retry_after_ms = retry_after_ms


class JournalError(IOError_):
    """The serve registry's write-ahead journal failed integrity
    validation or cannot express a mutation durably.  A torn FINAL line
    is *not* this error — a crash mid-append legitimately leaves one,
    so recovery truncates and counts it; this code fires on damage the
    crash model cannot explain: a CRC-bad or unparseable record with
    valid records AFTER it, an epoch gap between consecutive records,
    or a registered object (an exotic model class) that has no journal
    codec and therefore cannot survive a restart.  Subclasses
    ``IOError_`` like :class:`CheckpointError` so pre-existing IO error
    handling keeps working.  ``path`` names the journal file,
    ``record`` is the 1-based line number of the offending record, and
    ``reason`` is a short machine-readable tag (``"crc"``,
    ``"epoch-gap"``, ``"opaque-model"``, ...)."""

    code = 118

    def __init__(self, msg, path=None, record=None, reason=None):
        super().__init__(msg)
        self.path = path
        self.record = record
        self.reason = reason
