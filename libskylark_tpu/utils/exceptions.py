"""Exception hierarchy with stable error codes (≙ ``base/exception.hpp``).

The reference maps exceptions to C-API error codes; the codes are kept so
a future C shim can translate 1:1.
"""

from __future__ import annotations

__all__ = [
    "SkylarkError",
    "AllocationError",
    "InvalidParameters",
    "SketchError",
    "UnsupportedError",
    "IOError_",
]


class SkylarkError(Exception):
    """Base (≙ ``skylark_exception``, code 100)."""

    code = 100


class AllocationError(SkylarkError):
    code = 101


class InvalidParameters(SkylarkError, ValueError):
    code = 102


class SketchError(SkylarkError):
    code = 103


class UnsupportedError(SkylarkError, NotImplementedError):
    code = 104


class IOError_(SkylarkError, IOError):
    code = 105
