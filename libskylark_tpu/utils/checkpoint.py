"""Checkpoint/resume for long iterative solves.

The reference has serialization but no solver checkpointing (SURVEY §5:
"MPI fail-stop model; no checkpoint-restart of solver state"); this module
adds the basic capability the TPU build should provide: save/restore of a
solver's pytree state + metadata, so a long LSQR/CG/ADMM run can resume
after preemption.

Format: ONE ``<path>.npz`` holding the flattened pytree leaves plus an
embedded JSON metadata string — a single ``os.replace`` commits the
checkpoint atomically.  All counter-based transforms already round-trip
through their own JSON (``sketch.base``), so a solver checkpoint composes:
(transform JSON, state npz, iteration counter).
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np

__all__ = ["save_solver_state", "load_solver_state"]


def save_solver_state(path, state, metadata: dict | None = None) -> None:
    """``state`` is any pytree of arrays; saved atomically (tmp+rename)."""
    leaves, treedef = jax.tree.flatten(state)
    meta = {
        "skylark_object_type": "solver_checkpoint",
        "num_leaves": len(leaves),
        "treedef": str(treedef),
        "metadata": metadata or {},
    }
    tmp = str(path) + ".tmp.npz"
    np.savez(
        tmp,
        __meta__=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
        **{f"leaf_{i}": np.asarray(v) for i, v in enumerate(leaves)},
    )
    os.replace(tmp, str(path) + ".npz")


def load_solver_state(path, like=None):
    """Returns ``(state, metadata)``.  If ``like`` (a pytree prototype) is
    given, the saved leaves are unflattened into its structure; otherwise
    the flat leaf list is returned."""
    data = np.load(str(path) + ".npz")
    meta = json.loads(bytes(data["__meta__"]).decode())
    leaves = [data[f"leaf_{i}"] for i in range(meta["num_leaves"])]
    if like is not None:
        treedef = jax.tree.structure(like)
        return jax.tree.unflatten(treedef, leaves), meta["metadata"]
    return leaves, meta["metadata"]
