"""Checkpoint/resume for long iterative solves.

The reference has serialization but no solver checkpointing (SURVEY §5:
"MPI fail-stop model; no checkpoint-restart of solver state"); this module
provides the durable half of the preemption story: save/restore of a
solver's pytree state + metadata, so a long LSQR/CG/ADMM run can resume
after preemption (``resilient.ResilientRunner`` drives the chunked
execution half).

Format (version 2): ONE ``<path>.npz`` holding the flattened pytree leaves
plus an embedded JSON metadata string — a single ``os.replace`` commits the
checkpoint atomically.  The metadata records a format version, per-leaf
CRC32 checksums, and per-leaf dtype strings (numpy's npz container drops
extension dtypes like bfloat16 to raw void — the recorded dtype restores
them on load).  All counter-based transforms already round-trip through
their own JSON (``sketch.base``), so a solver checkpoint composes:
(transform JSON, state npz, iteration counter).

:class:`CheckpointStore` layers keep-last-N rotation on top, with
automatic fallback to the newest *valid* slot when the newest file is
corrupt (half-written by a preemption mid-``os.replace`` is impossible,
but corrupt-at-rest storage is not).
"""

from __future__ import annotations

import json
import os
import zipfile
import zlib

import jax
import numpy as np

from .exceptions import CheckpointError, StaleEpochError

__all__ = [
    "save_solver_state",
    "load_solver_state",
    "CheckpointStore",
    "FORMAT_VERSION",
]

FORMAT_VERSION = 2


def _leaf_bytes(arr: np.ndarray) -> bytes:
    return np.ascontiguousarray(arr).tobytes()


def _fsync_dir(directory: str) -> None:
    """Flush a directory's entry table (rename durability on POSIX).
    Best-effort: some filesystems refuse O_RDONLY dir fds — a failed
    sync must not fail the save."""
    try:
        fd = os.open(directory or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save_solver_state(path, state, metadata: dict | None = None) -> None:
    """``state`` is any pytree of arrays; saved atomically (tmp+rename).

    Durability order matters for crash safety: the tmp file's CONTENTS
    are fsynced before the rename, and the directory entry after it, so
    a host crash at any point leaves either the old slot, or the new
    slot fully written — never a named-but-empty file that would count
    as the newest slot while holding garbage.
    """
    leaves, treedef = jax.tree.flatten(state)
    arrays = [np.asarray(v) for v in leaves]
    meta = {
        "skylark_object_type": "solver_checkpoint",
        "format_version": FORMAT_VERSION,
        "num_leaves": len(arrays),
        "treedef": str(treedef),
        "leaf_dtypes": [str(a.dtype) for a in arrays],
        "leaf_crc32": [zlib.crc32(_leaf_bytes(a)) for a in arrays],
        "metadata": metadata or {},
    }
    tmp = str(path) + ".tmp.npz"
    np.savez(
        tmp,
        __meta__=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
        **{f"leaf_{i}": a for i, a in enumerate(arrays)},
    )
    with open(tmp, "rb+") as f:
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, str(path) + ".npz")
    _fsync_dir(os.path.dirname(str(path)))


def _restore_dtype(arr: np.ndarray, name: str | None) -> np.ndarray:
    if name is None:
        return arr
    want = np.dtype(name)  # extension dtypes resolve via jax's ml_dtypes
    if arr.dtype == want:
        return arr
    # npz stores bfloat16 & friends as raw void of the same itemsize.
    if arr.dtype.itemsize == want.itemsize:
        return arr.view(want)
    return arr.astype(want)


def load_solver_state(path, like=None):
    """Returns ``(state, metadata)``.  If ``like`` (a pytree prototype) is
    given, the saved leaves are unflattened into its structure; otherwise
    the flat leaf list is returned.

    Raises :class:`CheckpointError` (an ``IOError_``) when the file is not
    a solver checkpoint, leaves are missing, or a CRC32 check fails.
    """
    fname = str(path) + ".npz"
    try:
        with np.load(fname) as data:
            if "__meta__" not in data.files:
                raise CheckpointError(f"{fname}: missing __meta__ record")
            try:
                meta = json.loads(bytes(data["__meta__"]).decode())
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise CheckpointError(f"{fname}: unreadable metadata: {e}")
            if meta.get("skylark_object_type") != "solver_checkpoint":
                raise CheckpointError(
                    f"{fname}: skylark_object_type is "
                    f"{meta.get('skylark_object_type')!r}, expected "
                    f"'solver_checkpoint'"
                )
            num = meta["num_leaves"]
            present = {k for k in data.files if k.startswith("leaf_")}
            expected = {f"leaf_{i}" for i in range(num)}
            if present != expected:
                raise CheckpointError(
                    f"{fname}: num_leaves={num} but file holds "
                    f"{sorted(present)}"
                )
            # Leaves are materialized inside the with-block: np.load memory-
            # maps the zip and a leaked handle keeps the fd (and on some
            # platforms the file lock) alive indefinitely.
            leaves = [data[f"leaf_{i}"] for i in range(num)]
    except (
        OSError,
        zlib.error,
        ValueError,
        EOFError,
        KeyError,
        zipfile.BadZipFile,
    ) as e:
        if isinstance(e, CheckpointError):
            raise
        raise CheckpointError(f"{fname}: unreadable container: {e}")

    dtypes = meta.get("leaf_dtypes") or [None] * num
    crcs = meta.get("leaf_crc32")
    for i, arr in enumerate(leaves):
        if crcs is not None and zlib.crc32(_leaf_bytes(arr)) != crcs[i]:
            raise CheckpointError(f"{fname}: CRC32 mismatch on leaf_{i}")
        leaves[i] = _restore_dtype(arr, dtypes[i])

    if like is not None:
        treedef = jax.tree.structure(like)
        if treedef.num_leaves != num:
            raise CheckpointError(
                f"{fname}: prototype has {treedef.num_leaves} leaves, "
                f"checkpoint has {num}"
            )
        return jax.tree.unflatten(treedef, leaves), meta["metadata"]
    return leaves, meta["metadata"]


class CheckpointStore:
    """Keep-last-N rotation of step-indexed checkpoints in one directory.

    Slots are ``<prefix>-<step:012d>.npz``; :meth:`save` commits a new slot
    atomically then prunes the oldest beyond ``keep_last``.
    :meth:`load_latest` walks slots newest→oldest and returns the first
    that passes integrity validation, so one corrupt-at-rest file costs at
    most ``checkpoint_every`` iterations of recomputation, not the run.
    """

    def __init__(self, directory, keep_last: int = 3, prefix: str = "ckpt"):
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        self.directory = str(directory)
        self.keep_last = int(keep_last)
        self.prefix = prefix
        os.makedirs(self.directory, exist_ok=True)

    def _slot(self, step: int) -> str:
        return os.path.join(self.directory, f"{self.prefix}-{step:012d}")

    def steps(self) -> list[int]:
        """Ascending step indices of the slots currently on disk."""
        out = []
        pre, suf = self.prefix + "-", ".npz"
        for name in os.listdir(self.directory):
            if name.startswith(pre) and name.endswith(suf):
                try:
                    out.append(int(name[len(pre):-len(suf)]))
                except ValueError:
                    continue
        return sorted(out)

    def save(self, state, step: int, metadata: dict | None = None) -> str:
        meta = dict(metadata or {})
        meta["step"] = int(step)
        slot = self._slot(step)
        # save_solver_state fsyncs the slot's contents AND the directory
        # entry before returning, so by the time pruning below unlinks
        # older slots the new one is durable — a crash mid-rotation can
        # cost old slots but never the only valid one.
        save_solver_state(slot, state, meta)
        for old in self.steps()[: -self.keep_last]:
            try:
                os.remove(self._slot(old) + ".npz")
            except OSError:
                pass  # pruning is best-effort; a leftover slot is harmless
        return slot + ".npz"

    @staticmethod
    def slot_epoch(metadata: dict) -> int:
        """The elastic epoch a slot was written under.  Elastic runs stamp
        it at ``metadata["elastic"]["epoch"]``; a bare ``"epoch"`` key is
        honored too; slots that predate epochs are epoch 0."""
        elastic = metadata.get("elastic")
        if isinstance(elastic, dict) and "epoch" in elastic:
            return int(elastic["epoch"])
        return int(metadata.get("epoch", 0))

    def load_latest(self, like=None, expect_epoch: int | None = None):
        """Returns ``(state, metadata, step)`` from the newest valid slot,
        or ``None`` when no slot exists.  Raises :class:`CheckpointError`
        only when every slot on disk fails validation.

        ``expect_epoch`` (elastic resumes) pins the slot to one epoch:
        a structurally-valid newest slot whose recorded epoch differs
        raises :class:`StaleEpochError` (code 111) IMMEDIATELY — it is
        deliberately not a ``CheckpointError``, so the corrupt-slot
        fallback below cannot swallow it and silently load an equally
        stale older slot.  Corrupt slots still fall back: a stale-epoch
        verdict needs a readable manifest to be trustworthy.
        """
        steps = self.steps()
        if not steps:
            return None
        errors = []
        for step in reversed(steps):
            try:
                state, meta = load_solver_state(self._slot(step), like=like)
            except CheckpointError as e:
                errors.append(str(e))
                continue
            if expect_epoch is not None:
                have = self.slot_epoch(meta)
                if have != int(expect_epoch):
                    raise StaleEpochError(
                        f"checkpoint slot step {step} in {self.directory} "
                        f"was written at elastic epoch {have}, this resume "
                        f"runs at epoch {int(expect_epoch)}; the slot "
                        "belongs to a superseded partition — replan "
                        "instead of loading it",
                        expected=int(expect_epoch),
                        got=have,
                    )
            return state, meta, step
        raise CheckpointError(
            "no valid checkpoint among "
            f"{len(steps)} slot(s): " + "; ".join(errors)
        )
