"""Phase timers with cross-host aggregation.

≙ ``SKYLARK_TIMER_{DECLARE,INITIALIZE,RESTART,ACCUMULATE,PRINT}``
(``utility/timer.hpp:6-70``): named accumulating wall timers; the PRINT
reduction (min/max/avg over MPI ranks) becomes a min/max/avg over hosts
via ``jax.process_count``-aware psums when distributed, or a plain local
report single-host.  Device work is made observable with
``block_until_ready`` at phase boundaries (the reference's barrier).
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager

import jax

__all__ = ["PhaseTimer", "timer_report"]


class _PhaseHandle:
    """Set ``.result`` inside the phase so device work is synced on exit."""

    result = None


class PhaseTimer:
    """Accumulating named phase timers (one instance per algorithm run).

    Usage::

        t = PhaseTimer()
        with t.phase("transform") as ph:
            ph.result = S.apply(X)   # blocked on at phase exit
        print(t.report())

    JAX dispatch is asynchronous: without assigning ``ph.result`` the
    phase records only dispatch time, not device time.
    """

    def __init__(self, sync: bool = True):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self.sync = sync

    @contextmanager
    def phase(self, name: str):
        handle = _PhaseHandle()
        t0 = time.perf_counter()
        try:
            yield handle
        finally:
            if self.sync and handle.result is not None:
                jax.block_until_ready(handle.result)
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> str:
        return timer_report(self.totals, self.counts)


def timer_report(totals, counts=None) -> str:
    """min/max/avg-across-hosts shaped report (≙ timer.hpp PRINT).

    Single-process runs report local values in all three columns; under
    ``jax.distributed`` each host prints its own line-set (the reference
    reduces to rank 0 — with JAX the driver aggregates logs instead).
    """
    lines = [f"{'phase':<24}{'total(s)':>12}{'calls':>8}{'avg(s)':>12}"]
    for name in sorted(totals):
        total = totals[name]
        n = (counts or {}).get(name, 1) or 1
        lines.append(f"{name:<24}{total:>12.4f}{n:>8}{total / n:>12.4f}")
    return "\n".join(lines)
