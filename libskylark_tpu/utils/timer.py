"""Phase timers with optional cross-process min/max/avg aggregation.

≙ ``SKYLARK_TIMER_{DECLARE,INITIALIZE,RESTART,ACCUMULATE,PRINT}``
(``utility/timer.hpp:6-70``): named accumulating wall timers.  The
reference's PRINT reduces min/max/avg over ALL MPI ranks — the world
communicator (``utility/timer.hpp:44-66``); here
``timer_report(..., distributed=True)`` gathers each process's phase
scalars with ``multihost_utils.process_allgather`` (a job-global
collective over every ``jax.distributed`` process, exactly the world-
communicator semantics — it cannot be scoped to a sub-mesh, so the API
deliberately takes a boolean, not a mesh) and prints the same
three-column reduction.  Without it the report stays per-process.
Device work is made observable by assigning the phase handle's
``result`` (blocked on at phase exit — the reference's barrier).
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager

import jax
import numpy as np

__all__ = ["PhaseTimer", "timer_report", "aggregate_report"]


class _PhaseHandle:
    """Set ``.result`` inside the phase so device work is synced on exit."""

    result = None


class PhaseTimer:
    """Accumulating named phase timers (one instance per algorithm run).

    Usage::

        t = PhaseTimer()
        with t.phase("transform") as ph:
            ph.result = S.apply(X)   # blocked on at phase exit
        print(t.report())            # or t.report(mesh=mesh) multi-host

    JAX dispatch is asynchronous: without assigning ``ph.result`` the
    phase records only dispatch time, not device time.
    """

    def __init__(self, sync: bool = True):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self.sync = sync

    @contextmanager
    def phase(self, name: str):
        handle = _PhaseHandle()
        t0 = time.perf_counter()
        try:
            yield handle
        finally:
            if self.sync and handle.result is not None:
                jax.block_until_ready(handle.result)
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self, distributed: bool = False) -> str:
        return timer_report(self.totals, self.counts, distributed=distributed)


def timer_report(totals, counts=None, distributed: bool = False) -> str:
    """Phase-timer report.

    Default: local total/calls/avg table (per-process, ≙ timer.hpp PRINT
    on one rank).  With ``distributed=True``, EVERY process of the
    ``jax.distributed`` job must call with the SAME phase names (the
    reference's PRINT has the same collective contract — all world ranks
    enter the reduction; ``process_allgather`` is job-global and cannot
    be scoped to a sub-mesh): phase totals are all-gathered across
    processes and reported as min/max/avg over ranks.  In a
    single-process job (tests, one host) the gathered axis has length 1
    and min = max = avg = the local totals.
    """
    if not distributed:
        lines = [f"{'phase':<24}{'total(s)':>12}{'calls':>8}{'avg(s)':>12}"]
        for name in sorted(totals):
            total = totals[name]
            n = (counts or {}).get(name, 1) or 1
            lines.append(f"{name:<24}{total:>12.4f}{n:>8}{total / n:>12.4f}")
        return "\n".join(lines)

    from jax.experimental import multihost_utils

    names = sorted(totals)
    # The gather below aligns columns positionally, so every process must
    # bring the SAME phase-name list; a rank that recorded a different set
    # would silently misalign (or crash on a shape mismatch deep inside
    # the gather).  Validate first: gather a stable hash of the name list
    # and fail loudly on disagreement.
    import zlib

    sig = np.asarray(
        [zlib.crc32("\x00".join(names).encode()), len(names)], np.int64
    )
    sigs = np.atleast_2d(np.asarray(multihost_utils.process_allgather(sig)))
    if not (sigs == sigs[0]).all():
        raise RuntimeError(
            "timer_report(distributed=True): processes recorded different "
            f"phase-name sets (this rank has {names}); every rank must time "
            "the same phases — the reference's SKYLARK_TIMER_PRINT has the "
            "same world-collective contract (utility/timer.hpp:44-66)"
        )
    vec = np.asarray([totals[n] for n in names], np.float64)
    cnt = np.asarray([(counts or {}).get(n, 1) or 1 for n in names], np.int64)
    stacked = np.atleast_2d(np.asarray(multihost_utils.process_allgather(vec)))
    counts2d = np.atleast_2d(np.asarray(multihost_utils.process_allgather(cnt)))
    return aggregate_report(names, stacked, counts2d)


def aggregate_report(names, stacked, counts2d=None) -> str:
    """min/max/avg-over-ranks table from ``stacked`` (P, k) phase totals
    (≙ the MPI_Reduce triple of ``utility/timer.hpp:44-66``).  Split from
    :func:`timer_report` so the multi-rank reduction is testable without
    a real multi-process run."""
    P = stacked.shape[0]
    lines = [
        f"{'phase':<24}{'min(s)':>12}{'max(s)':>12}{'avg(s)':>12}"
        f"{'calls':>8}  (over {P} process{'es' if P != 1 else ''})"
    ]
    for j, name in enumerate(names):
        col = stacked[:, j]
        calls = int(counts2d[:, j].max()) if counts2d is not None else 1
        lines.append(
            f"{name:<24}{col.min():>12.4f}{col.max():>12.4f}"
            f"{col.mean():>12.4f}{calls:>8}"
        )
    return "\n".join(lines)
