"""Phase timers (local, per-process).

≙ ``SKYLARK_TIMER_{DECLARE,INITIALIZE,RESTART,ACCUMULATE,PRINT}``
(``utility/timer.hpp:6-70``): named accumulating wall timers.  The
reference's PRINT reduces min/max/avg over MPI ranks; here each process
reports locally — under ``jax.distributed`` the launcher aggregates logs
(there is no in-band host-to-host reduction for wall-clock scalars in
JAX).  Device work is made observable by assigning the phase handle's
``result`` (blocked on at phase exit — the reference's barrier).
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager

import jax

__all__ = ["PhaseTimer", "timer_report"]


class _PhaseHandle:
    """Set ``.result`` inside the phase so device work is synced on exit."""

    result = None


class PhaseTimer:
    """Accumulating named phase timers (one instance per algorithm run).

    Usage::

        t = PhaseTimer()
        with t.phase("transform") as ph:
            ph.result = S.apply(X)   # blocked on at phase exit
        print(t.report())

    JAX dispatch is asynchronous: without assigning ``ph.result`` the
    phase records only dispatch time, not device time.
    """

    def __init__(self, sync: bool = True):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self.sync = sync

    @contextmanager
    def phase(self, name: str):
        handle = _PhaseHandle()
        t0 = time.perf_counter()
        try:
            yield handle
        finally:
            if self.sync and handle.result is not None:
                jax.block_until_ready(handle.result)
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> str:
        return timer_report(self.totals, self.counts)


def timer_report(totals, counts=None) -> str:
    """Local total/calls/avg report (≙ timer.hpp PRINT, per-process)."""
    lines = [f"{'phase':<24}{'total(s)':>12}{'calls':>8}{'avg(s)':>12}"]
    for name in sorted(totals):
        total = totals[name]
        n = (counts or {}).get(name, 1) or 1
        lines.append(f"{name:<24}{total:>12.4f}{n:>8}{total / n:>12.4f}")
    return "\n".join(lines)
