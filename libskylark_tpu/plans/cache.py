"""The process-wide LRU plan cache and its observability counters.

One cache for the whole process (≙ the reference's per-transform apply
specializations being compiled once per binary): plans are keyed on the
*serialized* sketch — ``SketchTransform.to_json()`` is ~100 bytes and
fully determines the counter streams — plus the abstract input signature
``(dim, shape, dtype, sharding)`` and the donation flag, so two sketch
objects reconstructed from the same JSON (a solver re-run, a model
reload, every sweep of a sketch-and-solve loop) share one executable.

Counters (``stats()``):

- ``hits`` / ``misses``: cache lookups by outcome (a miss builds + traces
  a new plan);
- ``evictions``: plans dropped by the LRU bound
  (``SKYLARK_PLAN_CACHE_SIZE``, default 128);
- ``traces``: total jit traces executed by plan functions — the
  retrace-guard metric (a healthy streaming pass traces once per bucket,
  never once per batch);
- ``compiles`` / ``compile_seconds``: first-call executions per plan and
  the wall clock they took (trace + XLA compile + first run);
- ``bypasses``: planned entry points that fell back to the eager apply
  (plans disabled, tracer inputs, sparse blocks, ...).

All counters are monotone non-decreasing for the life of the process
(``reset_stats()`` zeroes them; ``clear()`` also drops the plans).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

from .. import telemetry

__all__ = [
    "stats", "reset", "reset_stats", "clear", "set_cache_size", "PlanCache",
]


def _default_size() -> int:
    try:
        return max(1, int(os.environ.get("SKYLARK_PLAN_CACHE_SIZE", "128")))
    except ValueError:
        return 128


class PlanCache:
    """OrderedDict-backed LRU of compiled plans + the counter block."""

    def __init__(self, max_size: int | None = None):
        self._lock = threading.RLock()
        self._plans: OrderedDict = OrderedDict()
        self.max_size = max_size if max_size is not None else _default_size()
        self._counters = self._zero()

    @staticmethod
    def _zero() -> dict:
        return {
            "hits": 0,
            "misses": 0,
            "evictions": 0,
            "traces": 0,
            "compiles": 0,
            "compile_seconds": 0.0,
            "bypasses": 0,
        }

    def bump(self, counter: str, amount=1) -> None:
        with self._lock:
            self._counters[counter] += amount

    def get_or_build(self, key, builder):
        """Return the plan under ``key``, building (and LRU-inserting) it
        with ``builder()`` on a miss."""
        with self._lock:
            plan = self._plans.get(key)
            if plan is not None:
                self._counters["hits"] += 1
                self._plans.move_to_end(key)
            else:
                self._counters["misses"] += 1
        if telemetry.enabled():
            telemetry.event(
                "plan", "cache", {"hit": plan is not None, "plan": key[0]}
            )
            # a serve dispatch in flight sees its plan-cache fate too
            telemetry.trace_event(
                "plan", hit=plan is not None, plan=key[0]
            )
        if plan is not None:
            return plan
        # Build outside the lock (builders may trip jax machinery);
        # double-insert under contention just wastes one builder call.
        plan = builder()
        with self._lock:
            existing = self._plans.get(key)
            if existing is not None:
                return existing
            self._plans[key] = plan
            while len(self._plans) > self.max_size:
                self._plans.popitem(last=False)
                self._counters["evictions"] += 1
        return plan

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counters)
            out["compile_seconds"] = round(out["compile_seconds"], 6)
            out["size"] = len(self._plans)
            out["max_size"] = self.max_size
            return out

    def reset_stats(self) -> None:
        with self._lock:
            self._counters = self._zero()

    def clear(self) -> None:
        with self._lock:
            self._plans.clear()
            self._counters = self._zero()

    def set_max_size(self, n: int) -> None:
        with self._lock:
            self.max_size = max(1, int(n))
            while len(self._plans) > self.max_size:
                self._plans.popitem(last=False)
                self._counters["evictions"] += 1


PLAN_CACHE = PlanCache()


def stats() -> dict:
    """Snapshot of the plan-cache counters (see module docstring)."""
    return PLAN_CACHE.stats()


def reset() -> None:
    """Zero the counters (the compiled plans stay cached) — the canonical
    test hook: poke this, not ``PLAN_CACHE._counters``."""
    PLAN_CACHE.reset_stats()


# Back-compat name; ``reset()`` is the documented hook.
reset_stats = reset


def clear() -> None:
    """Drop every cached plan and zero the counters."""
    PLAN_CACHE.clear()


def set_cache_size(n: int) -> None:
    """Adjust the LRU bound (evicting oldest plans if shrinking)."""
    PLAN_CACHE.set_max_size(n)
