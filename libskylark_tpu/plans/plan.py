"""Compiled sketch-apply plans: fused jit executables behind a cache.

The eager sketch path dispatches the counter-stream realization plus the
matmul/segment-sum as dozens of op-by-op XLA calls; a *plan* compiles the
whole apply into one fused ``jax.jit`` executable and caches it process-
wide (``cache.PLAN_CACHE``) keyed on the serialized sketch + the abstract
input signature, so repeated applies — every batch of a streaming pass,
every sweep of a sketch-and-solve loop, every sketch object rebuilt from
the same JSON — reuse one executable instead of re-tracing.

Three plan kinds:

- ``apply``: the full ``S.apply(A, dim)`` — literally the same function
  the eager path runs, traced once.  jit does not reorder the math (the
  matmul is one primitive either way; elementwise fusion is per-element
  exact), so the planned result is BITWISE identical to eager — the hard
  contract ``tests/test_plans.py`` pins for JLT/CWT/MMT/RFT in both dims.
- ``slice``: the streaming COLUMNWISE accumulation step
  ``acc + Omega[:, start:start+k] @ block`` with a TRACED ``start``
  (counter windows address traced offsets exactly — the P5 invariant) and
  the block padded up to the bucket ladder, so ONE executable serves all
  ragged batches of a bucket; ``acc`` is donated on backends that honor
  donation, eliminating the accumulator double-buffer.
- ``rowwise``: the streaming ROWWISE per-batch sketch on a bucketed
  block, with the transform's counter-realized hoisted operands passed
  as runtime arguments (realized once per process via the memoized
  ``hoistable_operands``, not once per executable or per batch).

``SKYLARK_NO_PLANS=1`` bypasses everything (the entry points fall back
to the eager path and count a ``bypass``); ``SKYLARK_PLAN_DONATE=0/1``
overrides the backend-based donation default.
"""

from __future__ import annotations

import os
import threading
import time

import jax
import jax.numpy as jnp

from .. import telemetry
from ..sketch.base import Dimension
from .bucketing import bucket_for, pad_rows
from .cache import PLAN_CACHE

__all__ = [
    "enabled",
    "donation_enabled",
    "fused_enabled",
    "SketchPlan",
    "apply",
    "accumulate_slice",
    "apply_rowwise_bucketed",
    "donating_jit",
    "pad_rows_to_bucket",
    "copy_for_donation",
]


def enabled() -> bool:
    """Plans are on unless ``SKYLARK_NO_PLANS=1`` (checked per call so
    tests and operators can flip it at runtime)."""
    return os.environ.get("SKYLARK_NO_PLANS", "").lower() not in ("1", "true")


def fused_enabled() -> bool:
    """Fused stream-chunk steps (``apply_slice_kernel_acc`` traced as
    the slice-plan body — the accumulator add folds into the sketch
    kernel's emit where the transform supports it) are on unless
    ``SKYLARK_NO_FUSED_CHUNKS=1``.  Checked per call; the flag also
    discriminates the plan key, so flipping it at runtime re-plans
    instead of hitting a stale executable."""
    env = os.environ.get("SKYLARK_NO_FUSED_CHUNKS", "").lower()
    return env not in ("1", "true")


def _kernel_env_token() -> tuple:
    """The env knobs that statically steer which scatter kernel a slice
    trace bakes in (``hash._window_mode`` / ``_segment_sum``).  Folded
    into the slice-plan key so a runtime flip re-traces rather than
    serving an executable built under the old routing."""
    return (
        os.environ.get("SKYLARK_PALLAS_WINDOW", ""),
        os.environ.get("SKYLARK_PALLAS_SCATTER", ""),
        os.environ.get("SKYLARK_NO_PALLAS", "0"),
    )


def donation_enabled() -> bool:
    """Donate accumulator buffers only where XLA honors donation (TPU /
    GPU — CPU silently ignores it); ``SKYLARK_PLAN_DONATE=1/0`` forces."""
    env = os.environ.get("SKYLARK_PLAN_DONATE", "").lower()
    if env in ("1", "true"):
        return True
    if env in ("0", "false"):
        return False
    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — no backend, no donation
        return False
    return backend in ("tpu", "gpu", "cuda", "rocm", "axon")


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _is_sparse(x) -> bool:
    return hasattr(x, "todense")


def _token(S) -> str:
    """The sketch's cache-key identity: its JSON serialization (~100
    bytes, fully determines the counter streams).  Memoized per instance
    — sketches are immutable."""
    tok = S.__dict__.get("_plan_token")
    if tok is None:
        tok = S.__dict__["_plan_token"] = S.to_json()
    return tok


def _sharding_key(x) -> str | None:
    try:
        sh = getattr(x, "sharding", None)
        return None if sh is None else str(sh)
    except Exception:  # noqa: BLE001 — deleted/odd arrays: no sharding key
        return None


class SketchPlan:
    """One compiled apply: a jit-wrapped function plus its counters.

    The trace counter increments inside the traced body (a Python side
    effect runs exactly once per trace), so ``plan.traces`` — and the
    process-wide ``stats()['traces']`` — measure real retraces, not
    calls.  The first call is timed through ``block_until_ready`` as the
    plan's ``compile_seconds`` (trace + XLA compile + first execution).
    """

    def __init__(self, key, fn, donate_argnums: tuple = ()):
        self.key = key
        self.calls = 0
        self.traces = 0
        self.compile_seconds = 0.0
        # First-call accounting must be claimed atomically: two threads
        # racing the same cold plan would otherwise both time the compile
        # and double-bump the process counters.
        self._lock = threading.Lock()

        def traced(*args):
            self.traces += 1
            PLAN_CACHE.bump("traces")
            return fn(*args)

        kw = {"donate_argnums": donate_argnums} if donate_argnums else {}
        self._jit = jax.jit(traced, **kw)

    def __call__(self, *args):
        with self._lock:
            first = self.calls == 0
            self.calls += 1
        if first:
            t0 = time.perf_counter()
        out = self._jit(*args)
        if first:
            jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            self.compile_seconds = dt
            PLAN_CACHE.bump("compiles")
            PLAN_CACHE.bump("compile_seconds", dt)
            if telemetry.enabled():
                telemetry.event(
                    "plan", "compile",
                    {"plan": self.key[0], "seconds": round(dt, 6)},
                )
        return out


# -- hoisted-operand flattening ---------------------------------------------
#
# ``hoistable_operands`` returns transform-specific nests mixing arrays
# with static tags (("sign", c, Mi), ((P01, v), ...), a bare Omega, or
# None).  To pass the arrays as runtime jit arguments — so the O(N·S)
# realization is NOT re-run inside (or baked as a constant into) every
# executable — split the nest into a static spec and an array leaf list.


def _split_ops(ops):
    leaves: list = []

    def walk(x):
        if isinstance(x, tuple):
            return ("t", tuple(walk(e) for e in x))
        if hasattr(x, "dtype") and hasattr(x, "shape"):
            leaves.append(x)
            return ("a", len(leaves) - 1)
        return ("s", x)

    return (None, leaves) if ops is None else (walk(ops), leaves)


def _join_ops(spec, leaves):
    if spec is None:
        return None
    tag, val = spec
    if tag == "t":
        return tuple(_join_ops(e, leaves) for e in val)
    if tag == "a":
        return leaves[val]
    return val


def _float_dtype(block):
    dt = block.data.dtype if _is_sparse(block) else block.dtype
    return dt if jnp.issubdtype(dt, jnp.floating) else jnp.dtype(jnp.float32)


# -- the three plan kinds ----------------------------------------------------


def apply(S, A, dim: Dimension | str = Dimension.COLUMNWISE):
    """Plan-cached ``S.apply(A, dim)`` — bitwise identical to eager.

    Falls back to the eager apply (counting a ``bypass``) when plans are
    disabled, when ``A`` is sparse (BCOO applies have data-dependent
    output structure), or when already inside a trace (the caller's jit
    subsumes the plan).
    """
    dim = Dimension.of(dim)
    if (
        not enabled()
        or _is_sparse(A)
        or _is_tracer(A)
        or not jax.core.trace_state_clean()
    ):
        PLAN_CACHE.bump("bypasses")
        return S.apply(A, dim)
    A = jnp.asarray(A)
    key = (
        "apply",
        _token(S),
        dim.value,
        A.shape,
        A.dtype.name,
        _sharding_key(A),
    )
    from .. import policy

    policy.note_plan("apply", S, dim=dim.value, shape=A.shape, dtype=A.dtype.name)
    plan = PLAN_CACHE.get_or_build(
        key, lambda: SketchPlan(key, lambda A_: S.apply(A_, dim))
    )
    if telemetry.enabled():
        with telemetry.span(
            "sketch.apply", dim=dim.value, shape=list(A.shape)
        ) as sp:
            sp.result = plan(A)
        return sp.result
    return plan(A)


def accumulate_slice(
    S, acc, block, start, *, donate: bool | None = None,
    true_rows: int | None = None, fused: bool | None = None,
):
    """One streaming COLUMNWISE step, planned:
    ``acc + S.apply_slice(block, start)`` (cast to ``acc.dtype``) as a
    single bucketed executable with ``start`` traced and ``acc`` donated.

    The block is zero-padded up to the bucket ladder; the slice kernel
    zeroes any operand window past the sketch domain and padded rows are
    exact zeros, so the padded contribution is exactly 0 and the
    accumulated value matches the eager ``apply_slice`` sum.  A block
    already padded host-side (``pipeline.bucketed_placer``) passes its
    real row count as ``true_rows``.  Falls back to the eager step for
    sparse blocks, transforms without a jit-safe slice kernel, or when
    plans are off.

    ``fused`` (default :func:`fused_enabled`) traces the step through
    ``S.apply_slice_kernel_acc`` — the transform's fused chunk body,
    which for the hash sketches folds the accumulator add into the
    Pallas window kernel's emit (one launch per chunk).  Fused and
    unfused are bitwise identical by the ``apply_slice_kernel_acc``
    contract; ``fused=False`` keeps the explicit two-step composite as
    the operator kill switch (``SKYLARK_NO_FUSED_CHUNKS=1`` process-
    wide, or ``StreamParams(fused_chunks=False)`` per pass).
    """
    k = block.shape[0]
    if (
        not enabled()
        or _is_sparse(block)
        or _is_tracer(block)
        or _is_tracer(acc)
        or not jax.core.trace_state_clean()
        or not getattr(S, "supports_slice_kernel", False)
        or getattr(block, "ndim", 0) != 2
        or S.n >= 1 << 31
    ):
        PLAN_CACHE.bump("bypasses")
        if true_rows is not None and true_rows != k:
            block = block[:true_rows]
        part = S.apply_slice(block, int(start), Dimension.COLUMNWISE)
        return acc + part.astype(acc.dtype)
    kb = bucket_for(k)
    block = pad_rows(block, kb)
    if donate is None:
        donate = donation_enabled()
    if fused is None:
        fused = fused_enabled()
    block = jnp.asarray(block)
    acc = jnp.asarray(acc)
    key = (
        "slice",
        _token(S),
        (kb,) + tuple(block.shape[1:]),
        block.dtype.name,
        acc.dtype.name,
        _sharding_key(acc),
        bool(donate),
        bool(fused),
        _kernel_env_token(),
    )
    from .. import policy

    policy.note_plan(
        "slice",
        S,
        shape=(kb,) + tuple(block.shape[1:]),
        dtype=block.dtype.name,
        acc_dtype=acc.dtype.name,
    )

    def build():
        if fused:
            def fn(acc_, block_, start_):
                return S.apply_slice_kernel_acc(acc_, block_, start_)
        else:
            def fn(acc_, block_, start_):
                part = S.apply_slice_kernel(block_, start_)
                return acc_ + part.astype(acc_.dtype)

        return SketchPlan(key, fn, donate_argnums=(0,) if donate else ())

    plan = PLAN_CACHE.get_or_build(key, build)
    if telemetry.enabled():
        telemetry.event(
            "plan", "slice", {"bucket": kb, "rows": k, "fused": bool(fused)}
        )
    return plan(acc, block, jnp.asarray(int(start), jnp.int32))


def apply_rowwise_bucketed(
    S, block, *, pad_out: bool = False, true_rows: int | None = None
):
    """One streaming ROWWISE batch, planned: pad the block's example
    rows up to the bucket ladder, apply through one executable per
    bucket (hoisted operands ride as runtime arguments), and return the
    true rows.

    ``pad_out=False`` returns the ``(k, S)`` sketch of the true rows
    (sliced outside the jit) — row-independent applies make every real
    row bitwise equal to the eager ragged apply (bucketing never crosses
    a transform's ``batch_size_gates``, so the algorithm choice matches
    too).  ``pad_out=True`` returns ``(Z_padded, k)`` with the padded
    rows zeroed inside the executable — the fixed-shape form consumers
    feed their own bucketed update plans (the streaming-KRR Gram).
    A block already padded host-side passes its real row count as
    ``true_rows``.
    """
    k = block.shape[0] if true_rows is None else int(true_rows)
    if (
        not enabled()
        or _is_sparse(block)
        or _is_tracer(block)
        or not jax.core.trace_state_clean()
        or getattr(block, "ndim", 0) != 2
    ):
        PLAN_CACHE.bump("bypasses")
        if k != block.shape[0]:
            block = block[:k]
        ops = S.hoistable_operands(_float_dtype(block))
        Z = S.apply_with_operands(ops, block, Dimension.ROWWISE)
        return (Z, k) if pad_out else Z
    gates = getattr(S, "batch_size_gates", ())
    kb = bucket_for(k, gates)
    if block.shape[0] not in (k, kb):
        # Host-side padding that disagrees with this transform's gates
        # (e.g. a generic placer padding a thin hash batch): recover the
        # real rows and re-bucket under the right gates.
        block = block[:k]
    block = jnp.asarray(pad_rows(block, kb))
    ops = S.hoistable_operands(_float_dtype(block))
    spec, leaves = _split_ops(ops)
    key = (
        "rowwise",
        _token(S),
        block.shape,
        block.dtype.name,
        _sharding_key(block),
        bool(pad_out),
        spec is not None,
    )
    from .. import policy

    policy.note_plan(
        "rowwise", S, shape=block.shape, dtype=block.dtype.name
    )

    def build():
        if pad_out:

            def fn(block_, k_, *op_leaves):
                ops_ = _join_ops(spec, list(op_leaves))
                Z = S.apply_with_operands(ops_, block_, Dimension.ROWWISE)
                valid = jnp.arange(kb) < k_
                return jnp.where(valid[:, None], Z, jnp.zeros((), Z.dtype))

        else:

            def fn(block_, k_, *op_leaves):
                ops_ = _join_ops(spec, list(op_leaves))
                return S.apply_with_operands(ops_, block_, Dimension.ROWWISE)

        return SketchPlan(key, fn)

    plan = PLAN_CACHE.get_or_build(key, build)
    if telemetry.enabled():
        telemetry.event("plan", "rowwise", {"bucket": kb, "rows": k})
    Z = plan(block, jnp.asarray(k, jnp.int32), *leaves)
    if pad_out:
        return Z, k
    return Z if k == kb else Z[:k]


def donating_jit(fn, donate_argnums: tuple = ()):
    """``jax.jit`` with donation applied only where the backend honors it
    (consumers: streaming accumulator updates).  Not plan-cached — jit's
    own shape-keyed cache is the right granularity for ad-hoc updates."""
    if donate_argnums and donation_enabled():
        return jax.jit(fn, donate_argnums=donate_argnums)
    return jax.jit(fn)


def pad_rows_to_bucket(block, gates: tuple = ()):
    """Convenience: ``(padded_block, true_rows)`` on the ladder."""
    k = int(block.shape[0])
    return pad_rows(block, bucket_for(k, gates)), k


def copy_for_donation(tree):
    """Device-copy every jax array leaf — used by consumers that must
    keep a pre-donation snapshot alive (the streaming engine's chunk-
    entry state, which the divergence guard may still read)."""
    def _copy(x):
        if isinstance(x, jax.Array) and not _is_tracer(x):
            return jnp.array(x, copy=True)
        return x

    return jax.tree_util.tree_map(_copy, tree)
