"""Compiled sketch-apply plans: fused executables, bucketing, donation.

The perf layer between the sketch transforms and their consumers (see
``docs/performance.md``):

- :func:`apply` — plan-cached full apply, bitwise identical to eager;
- :func:`accumulate_slice` / :func:`apply_rowwise_bucketed` — the
  bucketed, donation-aware streaming steps;
- :func:`stats` / :func:`reset_stats` / :func:`clear` — the process-wide
  plan cache and its hit/miss/trace/compile counters;
- ``SKYLARK_NO_PLANS=1`` turns the whole layer into a pass-through.
"""

from .bucketing import bucket_for, bucket_ladder, bucket_rows, pad_rows
from .cache import PLAN_CACHE, clear, reset, reset_stats, set_cache_size, stats
from .plan import (
    SketchPlan,
    accumulate_slice,
    apply,
    apply_rowwise_bucketed,
    copy_for_donation,
    donating_jit,
    donation_enabled,
    enabled,
    fused_enabled,
    pad_rows_to_bucket,
)

__all__ = [
    "apply",
    "accumulate_slice",
    "apply_rowwise_bucketed",
    "bucket_for",
    "bucket_ladder",
    "bucket_rows",
    "pad_rows",
    "pad_rows_to_bucket",
    "copy_for_donation",
    "donating_jit",
    "donation_enabled",
    "enabled",
    "fused_enabled",
    "SketchPlan",
    "PLAN_CACHE",
    "stats",
    "reset",
    "reset_stats",
    "clear",
    "set_cache_size",
]
