"""Batch bucketing: a small geometric ladder of row counts.

Streaming sources yield ragged batches (a short final block, IO-sized
reads, resumed tails), and ``jax.jit`` keys its executables on concrete
shapes — so the naive planned streaming pass compiles one executable per
distinct batch size.  Padding every batch's row count up to a small
geometric ladder caps the executable count at ``len(ladder)`` while
bounding the padding waste by the ladder's step ratio.

The ladder interleaves ``8·2^i`` and ``12·2^i`` (8, 12, 16, 24, 32, 48,
64, 96, ...): consecutive rungs are within 1.5x, so padded work is at
most 50% (usually ~25%) over the true row count, and the rung set for
any realistic batch range stays below ~20 entries.

Padding is exact for the plan kernels that consume it: COLUMNWISE slice
kernels zero out-of-domain operand windows (see
``SketchTransform.apply_slice_kernel``) and padded input rows are zero,
so padded contributions are exactly 0; ROWWISE applies are row-
independent maps whose padded output rows are sliced (or masked) away.
"""

from __future__ import annotations

import numpy as np

__all__ = ["bucket_for", "bucket_ladder", "bucket_rows", "pad_rows"]

_BASES = (8, 12)
_MAX_RUNG = 1 << 30


def bucket_ladder(max_rows: int | None = None) -> tuple[int, ...]:
    """The rung set, ascending; truncated to the first rung >= ``max_rows``
    when given (the rungs a stream of batches up to that size can use)."""
    rungs = []
    scale = 1
    while scale * _BASES[0] <= _MAX_RUNG:
        for b in _BASES:
            rungs.append(b * scale)
        scale *= 2
    rungs = tuple(sorted(rungs))
    if max_rows is None:
        return rungs
    out = []
    for r in rungs:
        out.append(r)
        if r >= max_rows:
            break
    return tuple(out)


def bucket_for(n: int, gates: tuple[int, ...] = ()) -> int:
    """Smallest rung >= ``n`` — THE public ladder lookup.

    Every consumer that needs "which executable shape does a batch of
    ``n`` land on" (the streaming placer, the planned apply paths, the
    serve layer's cross-request coalescer) asks here, so the rung set
    and its gate semantics live in exactly one place.

    ``gates`` are batch-size thresholds at which a transform switches
    algorithms (e.g. the hash sketches' one-hot-vs-scatter gate at 16
    rows): when padding ``n`` up to the rung would cross a gate, the
    batch is left unpadded so the planned batch takes the same algorithm
    — and produces the same bits — as the eager ragged apply.  The few
    in-between sizes cost one extra executable each, bounded by the gate
    count.
    """
    n = int(n)
    if n <= 0:
        raise ValueError(f"bucket_for needs a positive row count, got {n}")
    nb = n if n > _MAX_RUNG else min(r for r in bucket_ladder() if r >= n)
    for g in gates:
        if n < g <= nb:
            return n
    return nb


def bucket_rows(k: int, gates: tuple[int, ...] = ()) -> int:
    """Historical alias of :func:`bucket_for` (the streaming engine grew
    it first under this name; kept so pre-serve callers don't churn).

    ``gates`` are batch-size thresholds at which a transform switches
    algorithms (e.g. the hash sketches' one-hot-vs-scatter gate at 16
    rows): when padding ``k`` up to the rung would cross a gate, the
    batch is left unpadded so the planned batch takes the same algorithm
    """
    return bucket_for(k, gates)


def pad_rows(block, kb: int):
    """Zero-pad ``block``'s leading axis up to ``kb`` rows (host-side
    ``np.pad`` for numpy inputs so the device transfer is already
    bucket-shaped; ``jnp.pad`` for device arrays)."""
    k = block.shape[0]
    if k == kb:
        return block
    if k > kb:
        raise ValueError(f"block has {k} rows, bucket only {kb}")
    if isinstance(block, np.ndarray):
        return np.pad(block, ((0, kb - k),) + ((0, 0),) * (block.ndim - 1))
    import jax.numpy as jnp

    return jnp.pad(block, ((0, kb - k),) + ((0, 0),) * (block.ndim - 1))
