"""NaN/Inf sentinels: cheap finiteness probes at chunk and solve
boundaries.

Design constraint (ISSUE 4): sentinels must add NO extra host syncs.
Two properties make that possible:

- :func:`finite_probe` is pure graph: it reduces a pytree to ONE scalar
  bool on device (a bandwidth-cheap ``all(isfinite)`` per float leaf,
  AND-ed), so it can ride inside an existing jitted step and costs
  nothing at the host boundary until somebody reads it.
- Sum-style accumulators ABSORB NaNs: once a poisoned contribution is
  folded in, the accumulator stays non-finite forever.  A probe at the
  chunk boundary — where the resilient runner already syncs for
  checkpoint/``is_done`` bookkeeping — therefore observes any fault from
  anywhere inside the chunk, without per-batch readbacks.

:func:`tree_all_finite` is the host-side read (one sync);
:func:`check_finite` turns a failed read into a structured
:class:`~libskylark_tpu.utils.exceptions.NumericalHealthError` carrying
the offending stage.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..utils.exceptions import NumericalHealthError

__all__ = ["finite_probe", "tree_all_finite", "check_finite", "is_traced"]


def is_traced(*xs) -> bool:
    """True when any input is (or wraps) a JAX tracer — i.e. the caller
    is being traced into a larger jit.  Host-side guarding (certificate
    ``bool()`` reads, ladder control flow) cannot run there; guarded
    entrypoints use this to fall back to their unguarded graph instead
    of raising ``ConcretizationTypeError`` mid-trace."""
    return any(
        isinstance(x, jax.core.Tracer)
        or isinstance(getattr(x, "data", None), jax.core.Tracer)
        for x in xs
    )


def _float_leaves(tree):
    out = []
    for leaf in jax.tree.leaves(tree):
        a = leaf if isinstance(leaf, jax.Array) else jnp.asarray(leaf)
        if jnp.issubdtype(a.dtype, jnp.floating) or jnp.issubdtype(
            a.dtype, jnp.complexfloating
        ):
            out.append(a)
    return out


def finite_probe(tree):
    """Scalar bool array: every float/complex leaf of ``tree`` is finite.

    Stays on device (jit-compatible; no host sync) — batch it into an
    existing step graph and read it where a sync already happens.
    """
    leaves = _float_leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    return functools.reduce(
        jnp.logical_and, [jnp.all(jnp.isfinite(a)) for a in leaves]
    )


def tree_all_finite(tree) -> bool:
    """Host-side finiteness verdict — exactly one device→host sync."""
    return bool(finite_probe(tree))


def check_finite(tree, stage: str, report=None):
    """Raise :class:`NumericalHealthError` if ``tree`` has a non-finite
    float leaf; otherwise return ``tree`` unchanged."""
    if not tree_all_finite(tree):
        raise NumericalHealthError(
            f"non-finite values detected at stage {stage!r}",
            stage=stage,
            report=report,
        )
    return tree
