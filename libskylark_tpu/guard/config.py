"""Runtime knobs of the numerical-health guard layer.

All three are environment variables read PER CALL (not cached at import)
so tests and operators can flip them at runtime, matching the precedent
of ``SKYLARK_NO_PLANS`` / ``SKYLARK_PLAN_DONATE``:

- ``SKYLARK_GUARD`` — ``0``/``false`` disables the guard layer entirely:
  no sentinels, no certification, no ladder; solvers behave exactly like
  the pre-guard library (silent NaNs included — the bypass exists for
  benchmarking the overhead and for callers that guard externally).
- ``SKYLARK_GUARD_MAX_RETRIES`` — ladder length beyond the initial
  attempt (default 2: one fresh-seed resketch + one grown resketch)
  before the dense fallback rung.
- ``SKYLARK_GUARD_COND_MAX`` — certification threshold on the estimated
  condition number of a sketch output.  Default is the Blendenpik retry
  threshold ``0.1/sqrt(eps)`` for the certified dtype
  (``accelerated_...Elemental.hpp:241-252``): beyond it a sketched
  system is too ill-conditioned to trust the small solve.
"""

from __future__ import annotations

import os

import jax.numpy as jnp

__all__ = ["enabled", "max_retries", "cond_max", "GROWTH_FACTOR"]

# Geometric sketch-dimension growth per ladder rung (the Blendenpik
# retry loop doubles gamma; the ladder keeps the same factor).
GROWTH_FACTOR = 2.0


def enabled() -> bool:
    """Guarding is on unless ``SKYLARK_GUARD=0`` (checked per call)."""
    return os.environ.get("SKYLARK_GUARD", "").lower() not in ("0", "false")


def max_retries(default: int = 2) -> int:
    """Ladder retries after the initial attempt (≥ 0)."""
    raw = os.environ.get("SKYLARK_GUARD_MAX_RETRIES")
    if raw is None:
        return default
    return max(0, int(raw))


def cond_max(dtype=None) -> float:
    """Certification ceiling for cond(sketch output)."""
    raw = os.environ.get("SKYLARK_GUARD_COND_MAX")
    if raw is not None:
        return float(raw)
    eps = float(jnp.finfo(jnp.dtype(dtype or jnp.float64)).eps)
    return 0.1 / eps**0.5
