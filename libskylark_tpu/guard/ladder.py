"""The adaptive recovery ladder: resketch → grow → dense fallback.

Policy (≙ Blendenpik's retry loop generalized,
``accelerated_...Elemental.hpp:241-257``):

1. attempt 0 — the caller's own sketch (``initial``);
2. attempt 1 — fresh-seed resketch at the same size (``resketch``): an
   unlucky or corrupted draw is cured by new randomness alone;
3. attempts 2..max_retries — fresh seed AND sketch dimension grown by a
   geometric factor, clamped to the problem size (``grow``): a sketch too
   small to capture the range needs more rows, not just new ones;
4. ``fallback`` — the exact dense solve (the LAPACK-fallback analogue).

Every attempt lands in a :class:`RecoveryReport` whose ``to_dict()`` is
what solvers attach as ``info["recovery"]``.  The ladder is bounded by
``SKYLARK_GUARD_MAX_RETRIES`` and disabled entirely by ``SKYLARK_GUARD=0``
(see :mod:`~libskylark_tpu.guard.config`).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..core.context import SketchContext
from ..utils.exceptions import NumericalHealthError
from . import config
from .certify import FALLBACK, OK

__all__ = [
    "RecoveryAttempt",
    "RecoveryReport",
    "derived_context",
    "run_ladder",
]


@dataclass
class RecoveryAttempt:
    """One rung taken: what was tried and what the certificate said."""

    action: str  # initial | resketch | grow | fallback | replay
    verdict: str | None = None  # OK | RESKETCH | FALLBACK | None (replay)
    detail: str = ""
    cond: float | None = None
    sketch_size: int | None = None
    chunk: int | None = None

    def to_dict(self) -> dict:
        d = {"action": self.action}
        for k in ("verdict", "detail", "cond", "sketch_size", "chunk"):
            v = getattr(self, k)
            if v not in (None, ""):
                d[k] = v
        return d


@dataclass
class RecoveryReport:
    """Ledger of everything the guard did for one solve.

    ``to_dict()`` is the stable ``info["recovery"]`` payload:
    ``{"stage", "guarded", "recovered", "attempts": [...]}`` — with
    ``guarded=False`` (bypass) the attempts list is empty.
    """

    stage: str
    guarded: bool = True
    recovered: bool = False
    attempts: list = field(default_factory=list)

    @classmethod
    def disabled(cls, stage: str) -> "RecoveryReport":
        return cls(stage=stage, guarded=False)

    def record(self, action: str, **kw) -> RecoveryAttempt:
        a = RecoveryAttempt(action=action, **kw)
        self.attempts.append(a)
        from .. import telemetry

        if telemetry.enabled():
            # One ledger event per rung taken, with the certificate's
            # verdict riding along — the run ledger's view of the ladder.
            attrs = a.to_dict()
            attrs["stage"] = self.stage
            attrs["rung"] = len(self.attempts) - 1
            telemetry.event("guard", action, attrs)
            # ... and the request trace's view: a rung > 0 here marks
            # the trace SLO-violating (see telemetry.trace.is_violating)
            telemetry.trace_event("guard", **attrs)  # attrs carry action
            telemetry.inc("guard.attempts")
            telemetry.inc(f"guard.{action}")
        return a

    def to_dict(self) -> dict:
        return {
            "stage": self.stage,
            "guarded": self.guarded,
            "recovered": self.recovered,
            "attempts": [a.to_dict() for a in self.attempts],
        }


def derived_context(context: SketchContext, attempt: int) -> SketchContext:
    """Deterministic fresh-seed context for ladder attempt ``attempt``.

    Golden-ratio mixing of the base seed: derived seeds are distinct per
    attempt, reproducible across processes (replay/resume keeps working),
    and never collide with the base seed itself for attempt ≥ 1.
    """
    seed = (int(context.seed) ^ (0x9E3779B9 * attempt)) % (2**31 - 1)
    return SketchContext(seed=seed)


def run_ladder(
    stage: str,
    context: SketchContext,
    sketch_size: int,
    max_size: int,
    attempt_fn,
    fallback_fn,
    *,
    report: RecoveryReport | None = None,
    max_retries: int | None = None,
    growth: float | None = None,
):
    """Drive ``attempt_fn`` up the ladder; returns ``(result, report)``.

    ``attempt_fn(ctx, s, index) -> (result, Certificate)`` runs one
    sketch attempt at size ``s`` with context ``ctx`` and certifies it
    (``result`` is ignored unless the certificate is OK).
    ``fallback_fn() -> result`` is the dense rung; pass ``None`` to
    raise :class:`NumericalHealthError` on exhaustion instead.
    """
    report = report or RecoveryReport(stage=stage)
    retries = (
        max_retries if max_retries is not None else config.max_retries()
    )
    factor = growth if growth is not None else config.GROWTH_FACTOR
    s = int(sketch_size)
    for i in range(retries + 1):
        if i == 0:
            action, ctx = "initial", context
        elif i == 1:
            action, ctx = "resketch", derived_context(context, i)
        else:
            action, ctx = "grow", derived_context(context, i)
            s = min(int(s * factor), int(max_size))
        result, cert = attempt_fn(ctx, s, i)
        report.record(
            action,
            verdict=cert.verdict,
            detail=cert.detail,
            cond=cert.cond,
            sketch_size=s,
        )
        if cert.verdict == OK:
            report.recovered = i > 0
            return result, report
        if cert.verdict == FALLBACK:
            break
    if fallback_fn is None:
        raise NumericalHealthError(
            f"recovery ladder exhausted at stage {stage!r} "
            f"({len(report.attempts)} attempts)",
            stage=stage,
            report=report,
        )
    result = fallback_fn()
    report.record("fallback", verdict=FALLBACK, detail="exact dense solve")
    report.recovered = True
    return result, report
