"""Sketch certification: classify a sketch output OK | RESKETCH | FALLBACK.

The reference ships exactly this idea inside Blendenpik — estimate the
condition of the sketched factor and re-sketch / fall back to LAPACK when
the randomness came out bad (``accelerated_...Elemental.hpp:241-257``).
Here it is a reusable layer: after sketch-and-solve / sketch-and-
precondition, run the ported ``cond_est`` estimator
(:mod:`~libskylark_tpu.solvers.cond_est`, ≙ ``nla/CondEst.hpp``) on the
small sketched matrix and classify:

- ``OK`` — finite, certified cond below the ceiling: trust the sketch.
- ``RESKETCH`` — non-finite output, numerically singular (flag ``-4``),
  or cond above ``SKYLARK_GUARD_COND_MAX``: the randomness was unlucky
  (or corrupted); a fresh-seed / larger sketch is worth trying.
- ``FALLBACK`` — retrying cannot help (exhausted ladder, or a
  deterministic factorization failed): go straight to the dense rung.

:func:`certify_svd` is the randomized-SVD analogue: finiteness plus a
posterior residual check on the leading singular triplet
(``‖A v₀ − σ₀ u₀‖ ≤ tol·σ₀`` — cheap, one matvec).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..core.context import SketchContext
from . import config

__all__ = [
    "OK",
    "RESKETCH",
    "FALLBACK",
    "Certificate",
    "certify_sketch",
    "certify_svd",
    "pinv_psd_solve",
]

OK = "OK"
RESKETCH = "RESKETCH"
FALLBACK = "FALLBACK"

# The certification probe's own deterministic seed: cond_est draws its
# start/probe vectors from a context, and using the caller's would
# advance the caller's counter stream (breaking sketch reproducibility),
# so certification runs on a private fixed-seed context instead.
_PROBE_SEED = 0x5EED


@dataclass
class Certificate:
    """Outcome of one certification: the verdict plus the evidence."""

    verdict: str
    stage: str
    cond: float | None = None
    sigma_max: float | None = None
    sigma_min: float | None = None
    flag: int | None = None
    detail: str = ""

    @property
    def ok(self) -> bool:
        return self.verdict == OK


def _upcast(M):
    """cond_est wants a real f32+ matrix (bf16/f16 erfinv/SVD paths are
    not worth exercising for a probe)."""
    if M.dtype in (jnp.bfloat16, jnp.float16):
        return M.astype(jnp.float32)
    return M


def certify_sketch(
    SA,
    *,
    stage: str = "sketch",
    cond_max: float | None = None,
    condest_params=None,
) -> Certificate:
    """Certify a replicated-small sketch output ``S·A`` (s, n).

    Finiteness first (a NaN/Inf sketch is RESKETCH without estimating
    anything), then the ``cond_est`` port: numerically-singular flag
    (``-4``) or estimated cond above the ceiling → RESKETCH; else OK.
    Wide outputs certify through their transpose (same singular values).
    """
    from ..solvers.cond_est import CondEstParams, cond_est

    SA = jnp.asarray(SA)
    if not bool(jnp.all(jnp.isfinite(SA))):
        return Certificate(
            RESKETCH, stage, detail="non-finite sketch output"
        )
    M = _upcast(SA)
    if M.shape[0] < M.shape[1]:
        M = M.T
    ceiling = cond_max if cond_max is not None else config.cond_max(M.dtype)
    # A short LSQR sweep is plenty for an (s, n) replicated-small probe —
    # the default 300-iteration budget is sized for full-scale A.
    p = condest_params or CondEstParams(iter_lim=60, powerits=25)
    r = cond_est(M, SketchContext(seed=_PROBE_SEED), p)
    cond = float(r.cond)
    smax = float(r.sigma_max)
    smin = float(r.sigma_min)
    flag = int(r.flag)
    base = dict(
        stage=stage, cond=cond, sigma_max=smax, sigma_min=smin, flag=flag
    )
    if flag == -4:
        return Certificate(
            RESKETCH, detail="numerically singular (cond_est C3)", **base
        )
    # NaN-propagating comparison on purpose: only a FINITE cond below the
    # ceiling certifies OK.
    if not (cond < ceiling):
        return Certificate(
            RESKETCH, detail=f"cond estimate {cond:.3e} >= {ceiling:.3e}",
            **base,
        )
    return Certificate(OK, **base)


def certify_svd(
    A, U, s, V, *, stage: str = "randomized_svd", rtol: float | None = None
) -> Certificate:
    """Posterior check of a randomized SVD: finite factors and
    ``‖A v₀ − σ₀ u₀‖ ≤ rtol·σ₀`` for the leading triplet."""
    if not bool(
        jnp.all(jnp.isfinite(s))
        & jnp.all(jnp.isfinite(U))
        & jnp.all(jnp.isfinite(V))
    ):
        return Certificate(RESKETCH, stage, detail="non-finite SVD factors")
    s0 = float(s[0])
    if s0 == 0.0:
        # Zero leading singular value: either A ≈ 0 (fine) or a collapsed
        # sketch.  ‖A‖_F is one cheap pass and separates the two.
        normA = float(jnp.linalg.norm(A.todense() if hasattr(A, "todense") else A))
        if normA == 0.0:
            return Certificate(OK, stage, sigma_max=0.0)
        return Certificate(
            RESKETCH, stage, sigma_max=s0,
            detail="sigma_0 = 0 on a nonzero matrix",
        )
    if rtol is None:
        # Loose by design: randomized SVD's *approximation* error lives in
        # the tail, but the LEADING triplet of a healthy run is accurate;
        # only a corrupted/collapsed run misses by a large factor.
        rtol = 0.5
    res = float(jnp.linalg.norm(A @ V[:, 0] - s0 * U[:, 0]))
    if not (res <= rtol * s0):
        return Certificate(
            RESKETCH, stage, sigma_max=s0,
            detail=f"posterior residual {res:.3e} > {rtol}*sigma_0",
        )
    return Certificate(OK, stage, sigma_max=s0)


def pinv_psd_solve(G, C):
    """Eigh-based pseudoinverse solve of a symmetric PSD system ``G X = C``
    — the dense rung under a Cholesky that came back non-finite (singular
    or indefinite-by-rounding Gram)."""
    G = jnp.asarray(G)
    lam, Q = jnp.linalg.eigh(G)
    eps = jnp.finfo(lam.dtype).eps
    cutoff = jnp.maximum(lam[-1], 0) * eps * G.shape[0]
    inv = jnp.where(lam > cutoff, 1.0 / jnp.maximum(lam, cutoff), 0.0)
    return Q @ (inv[:, None] * (Q.T @ jnp.asarray(C)))
