"""Numerical-health guard layer: sentinels, sketch certification, and
the adaptive recovery ladder.

The production discipline the reference bakes into Blendenpik (condition-
estimate the sketch, re-sketch or fall back to LAPACK — SISC 2010) and
LSRN (bound the preconditioned spectrum — SISC 2014), factored out as a
subsystem the whole library wires through:

- :mod:`~libskylark_tpu.guard.sentinels` — jitted all-finite probes at
  chunk and solve boundaries (no extra host syncs), raising
  :class:`NumericalHealthError` with the offending stage;
- :mod:`~libskylark_tpu.guard.certify` — ``cond_est`` / posterior
  residual certification of sketch outputs, verdicts
  ``OK | RESKETCH | FALLBACK``;
- :mod:`~libskylark_tpu.guard.ladder` — bounded recovery policy
  (fresh-seed resketch → grow sketch dimension → exact dense solve),
  every attempt recorded in a :class:`RecoveryReport` that solvers
  attach as ``info["recovery"]``.

Env knobs (read per call): ``SKYLARK_GUARD=0`` bypass,
``SKYLARK_GUARD_MAX_RETRIES``, ``SKYLARK_GUARD_COND_MAX``.  See
``docs/numerical_health.md``.
"""

from ..utils.exceptions import NumericalHealthError
from .certify import (
    FALLBACK,
    OK,
    RESKETCH,
    Certificate,
    certify_sketch,
    certify_svd,
    pinv_psd_solve,
)
from .config import GROWTH_FACTOR, cond_max, enabled, max_retries
from .ladder import (
    RecoveryAttempt,
    RecoveryReport,
    derived_context,
    run_ladder,
)
from .sentinels import check_finite, finite_probe, is_traced, tree_all_finite

__all__ = [
    "NumericalHealthError",
    "OK",
    "RESKETCH",
    "FALLBACK",
    "Certificate",
    "certify_sketch",
    "certify_svd",
    "pinv_psd_solve",
    "enabled",
    "max_retries",
    "cond_max",
    "GROWTH_FACTOR",
    "RecoveryAttempt",
    "RecoveryReport",
    "derived_context",
    "run_ladder",
    "finite_probe",
    "tree_all_finite",
    "check_finite",
    "is_traced",
]
