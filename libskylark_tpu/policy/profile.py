"""The persistent profile store: per-(backend, dtype, shape-class) summaries.

One JSON file per writer process — ``profile-<pid>.json`` under
``SKYLARK_POLICY_DIR`` — mirroring the telemetry run-ledger discipline
(``ledger-<pid>.jsonl``): multi-process jobs never interleave writers,
and a reader merges every file it can parse.  Each file carries a CRC32
over its canonical payload so a torn write (preempted mid-``rename``,
dead filesystem, byte flip) is *skipped*, never half-trusted; merging is
last-writer-wins per profile key on the entry's ``updated`` timestamp
(ties broken by pid then filename, so every rank of a world computes the
identical merged view from the same files).

Entry schema (one per :func:`profile_key`):

.. code-block:: json

    {"runs": 7, "updated": 1754000000.0,
     "guard": {"ok": 6, "resketch": 1, "fallback": 0},
     "cond": {"last": 1.2e3, "max": 4.1e3},
     "sketch": {"type": "FJLT", "min_ok": 512, "default": 2048},
     "bf16": {"ok": 3, "fail": 0},
     "refine": {"ok": 2, "stagnate": 0, "iters": 47, "rung": "bf16+f32"},
     "routes": {"sketch": 7},
     "escalations": 0,
     "throughput": {"rows_per_s": 1.1e6, "batches": 16}}

plus a store-level ``plans`` list of hot plan-cache keys (sketch JSON +
abstract input signature — enough to replay the trace at warm start) and
a ``meta`` block (``xla_cache_dir``, plan-cache compile totals).
"""

from __future__ import annotations

import json
import math
import os
import threading
import zlib

from . import config

__all__ = [
    "shape_class",
    "profile_key",
    "ProfileStore",
    "load_entries",
    "invalidate_cache",
]

SCHEMA_VERSION = 1
# Hot-plan records kept per store file (the warm-start replay budget is
# the separate SKYLARK_POLICY_WARM_PLANS read knob).
MAX_PLAN_RECORDS = 32

_LOCK = threading.RLock()

# Merged-view cache keyed by directory; invalidated by (name, mtime_ns,
# size) stat signatures so sweeps don't re-parse the store per solve.
_CACHE: dict = {}


def shape_class(m: int, n: int) -> str:
    """Geometric shape bucket ``r<ceil log2 m>c<ceil log2 n>`` — the same
    power-of-two ladder the plan layer buckets batches on, so problems
    that share executables share profile entries."""

    def _l2(x: int) -> int:
        return max(0, math.ceil(math.log2(max(int(x), 1))))

    return f"r{_l2(m)}c{_l2(n)}"


def profile_key(kind: str, backend: str, dtype: str, m: int, n: int) -> str:
    """The store key: ``kind|backend|dtype|shape-class``."""
    return "|".join([kind, backend, str(dtype), shape_class(m, n)])


def _crc(payload: dict) -> int:
    return zlib.crc32(
        json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()
    ) & 0xFFFFFFFF


def _read_file(path: str):
    """Parse one store file; None on any corruption (torn JSON, CRC
    mismatch, wrong version) — the caller counts and skips."""
    try:
        with open(path, "rb") as fh:
            doc = json.loads(fh.read().decode("utf-8"))
    except (OSError, ValueError, UnicodeDecodeError):
        return None
    if not isinstance(doc, dict) or doc.get("version") != SCHEMA_VERSION:
        return None
    payload = doc.get("payload")
    if not isinstance(payload, dict) or doc.get("crc") != _crc(payload):
        return None
    return doc


def _merge_files(directory: str) -> dict:
    """Merged view of every parseable ``profile-*.json`` in the dir."""
    entries: dict = {}
    wins: dict = {}  # key -> (updated, pid, fname) of the current winner
    plans: dict = {}  # record-key -> {"count": n, ...record}
    meta: dict = {}
    meta_win = (-1.0, -1, "")
    corrupt = 0
    try:
        names = sorted(
            f for f in os.listdir(directory)
            if f.startswith("profile-") and f.endswith(".json")
        )
    except OSError:
        names = []
    for fname in names:
        doc = _read_file(os.path.join(directory, fname))
        if doc is None:
            corrupt += 1
            continue
        payload = doc["payload"]
        pid = int(doc.get("pid", 0))
        for key, entry in (payload.get("entries") or {}).items():
            if not isinstance(entry, dict):
                continue
            mark = (float(entry.get("updated", 0.0)), pid, fname)
            if key not in entries or mark > wins[key]:
                entries[key] = entry
                wins[key] = mark
        for rec in payload.get("plans") or []:
            if not isinstance(rec, dict):
                continue
            rk = _plan_record_key(rec)
            if rk in plans:
                plans[rk]["count"] += int(rec.get("count", 1))
            else:
                plans[rk] = dict(rec, count=int(rec.get("count", 1)))
        fmeta = payload.get("meta") or {}
        mark = (float(fmeta.get("updated", 0.0)), pid, fname)
        if fmeta and mark > meta_win:
            meta = fmeta
            meta_win = mark
    return {
        "entries": entries,
        "plans": sorted(
            plans.values(), key=lambda r: (-r["count"], _plan_record_key(r))
        ),
        "meta": meta,
        "corrupt_files": corrupt,
        "files": len(names),
    }


def _plan_record_key(rec: dict) -> str:
    return "|".join(
        str(rec.get(k))
        for k in ("plan", "sketch", "dim", "shape", "dtype", "acc_dtype")
    )


def _stat_signature(directory: str):
    try:
        names = sorted(
            f for f in os.listdir(directory)
            if f.startswith("profile-") and f.endswith(".json")
        )
    except OSError:
        return ()
    sig = []
    for f in names:
        try:
            st = os.stat(os.path.join(directory, f))
            sig.append((f, st.st_mtime_ns, st.st_size))
        except OSError:
            sig.append((f, -1, -1))
    return tuple(sig)


def load_entries(directory: str | None = None) -> dict | None:
    """The merged store view (cached by file stats); None with no dir."""
    directory = directory or config.policy_dir()
    if not directory:
        return None
    with _LOCK:
        sig = _stat_signature(directory)
        cached = _CACHE.get(directory)
        if cached is not None and cached[0] == sig:
            return cached[1]
        view = _merge_files(directory)
        _CACHE[directory] = (sig, view)
        return view


def invalidate_cache() -> None:
    """Drop the merged-view cache (test hook; reads re-stat anyway)."""
    with _LOCK:
        _CACHE.clear()


class ProfileStore:
    """This process's own profile file plus the merged read view.

    Writers fold observations into the in-memory pending state
    (:meth:`fold`, :meth:`note_plan`) and :meth:`save` rewrites
    ``profile-<pid>.json`` atomically (tmp + fsync + rename) with the
    CRC over the canonical payload.  The pending state is seeded from
    the merged view per key on first fold, so one process's file carries
    forward what previous processes learned (last-writer-wins keeps the
    newest file authoritative either way).
    """

    def __init__(self, directory: str | None = None):
        self.directory = directory or config.policy_dir()
        self._entries: dict = {}
        self._plans: dict = {}
        self._meta: dict = {}
        self._dirty = False

    # -- folding ------------------------------------------------------------

    def _seed(self, key: str) -> dict:
        entry = self._entries.get(key)
        if entry is None:
            view = load_entries(self.directory)
            merged = (view or {}).get("entries", {}).get(key)
            entry = json.loads(json.dumps(merged)) if merged else {
                "runs": 0,
                "guard": {"ok": 0, "resketch": 0, "fallback": 0},
                "cond": {"last": None, "max": None},
                "sketch": {"type": None, "min_ok": None, "default": None},
                "bf16": {"ok": 0, "fail": 0},
                "routes": {},
                "escalations": 0,
            }
            self._entries[key] = entry
        return entry

    def fold(self, key: str, obs: dict, *, now: float) -> None:
        """Merge one run observation into the pending entry for ``key``.

        ``obs`` fields (all optional): ``ok0`` (attempt-0 certificate
        OK), ``resketches``, ``fallback``, ``cond``, ``sketch_type``,
        ``sketch_size`` (certified-OK size), ``default_size``, ``route``,
        ``bf16`` / ``fp8`` (``"ok"``/``"fail"``), ``refine`` (the solve's
        ``info["refine"]`` dict: ``converged``/``iters``/``rung``),
        ``escalated``, ``rows_per_s``, ``batches``.
        """
        with _LOCK:
            e = self._seed(key)
            e["runs"] = int(e.get("runs", 0)) + 1
            e["updated"] = float(now)
            g = e.setdefault(
                "guard", {"ok": 0, "resketch": 0, "fallback": 0}
            )
            if obs.get("ok0"):
                g["ok"] = g.get("ok", 0) + 1
            g["resketch"] = g.get("resketch", 0) + int(
                obs.get("resketches", 0)
            )
            if obs.get("fallback"):
                g["fallback"] = g.get("fallback", 0) + 1
            cond = obs.get("cond")
            if cond is not None and math.isfinite(float(cond)):
                c = e.setdefault("cond", {"last": None, "max": None})
                c["last"] = float(cond)
                c["max"] = (
                    float(cond)
                    if c.get("max") is None
                    else max(float(c["max"]), float(cond))
                )
            sk = e.setdefault(
                "sketch", {"type": None, "min_ok": None, "default": None}
            )
            if obs.get("sketch_type"):
                sk["type"] = obs["sketch_type"]
            if obs.get("default_size") is not None:
                sk["default"] = int(obs["default_size"])
            if obs.get("sketch_size") is not None:
                s_ok = int(obs["sketch_size"])
                sk["min_ok"] = (
                    s_ok
                    if sk.get("min_ok") is None
                    else min(int(sk["min_ok"]), s_ok)
                )
            if obs.get("route"):
                r = e.setdefault("routes", {})
                r[obs["route"]] = r.get(obs["route"], 0) + 1
            if obs.get("bf16") in ("ok", "fail"):
                b = e.setdefault("bf16", {"ok": 0, "fail": 0})
                b[obs["bf16"]] = b.get(obs["bf16"], 0) + 1
            if obs.get("fp8") in ("ok", "fail"):
                f8 = e.setdefault("fp8", {"ok": 0, "fail": 0})
                f8[obs["fp8"]] = f8.get(obs["fp8"], 0) + 1
            rf_obs = obs.get("refine")
            if isinstance(rf_obs, dict) and rf_obs.get("converged") is not None:
                rf = e.setdefault(
                    "refine",
                    {"ok": 0, "stagnate": 0, "iters": None, "rung": None},
                )
                # A non-converged final state means refinement stagnated
                # (or fell through the ladder to the exact fallback) —
                # either way the route's premise failed for this key.
                which = "ok" if rf_obs.get("converged") else "stagnate"
                rf[which] = int(rf.get(which, 0)) + 1
                if rf_obs.get("iters") is not None:
                    rf["iters"] = int(rf_obs["iters"])
                if rf_obs.get("rung"):
                    rf["rung"] = str(rf_obs["rung"])
            if obs.get("escalated"):
                e["escalations"] = int(e.get("escalations", 0)) + 1
            if obs.get("rows_per_s") is not None:
                e["throughput"] = {
                    "rows_per_s": round(float(obs["rows_per_s"]), 3),
                    "batches": int(obs.get("batches", 0)),
                }
            self._dirty = True

    def note_plan(self, rec: dict) -> None:
        """Count one plan-cache key toward the hot-plan list."""
        with _LOCK:
            rk = _plan_record_key(rec)
            if rk in self._plans:
                self._plans[rk]["count"] += 1
            else:
                self._plans[rk] = dict(rec, count=1)
            self._dirty = True

    def set_meta(self, **kv) -> None:
        with _LOCK:
            self._meta.update({k: v for k, v in kv.items() if v is not None})
            self._dirty = True

    # -- persistence --------------------------------------------------------

    def save(self, *, now: float) -> str | None:
        """Atomically rewrite this process's profile file; returns its
        path (None when no directory is configured or nothing pending)."""
        with _LOCK:
            if not self.directory or not self._dirty:
                return None
            # Carry forward previously-merged hot plans so a short-lived
            # process does not erase a long-lived one's replay list.
            view = load_entries(self.directory) or {}
            plans = {
                _plan_record_key(r): dict(r) for r in view.get("plans", [])
            }
            for rk, rec in self._plans.items():
                if rk in plans:
                    plans[rk]["count"] = max(
                        int(plans[rk].get("count", 0)), int(rec["count"])
                    )
                else:
                    plans[rk] = dict(rec)
            top = sorted(
                plans.values(), key=lambda r: (-r["count"], _plan_record_key(r))
            )[:MAX_PLAN_RECORDS]
            meta = dict(view.get("meta") or {})
            meta.update(self._meta)
            meta["updated"] = float(now)
            payload = {
                "entries": self._entries,
                "plans": top,
                "meta": meta,
            }
            doc = {
                "version": SCHEMA_VERSION,
                "pid": os.getpid(),
                "payload": payload,
                "crc": _crc(payload),
            }
            os.makedirs(self.directory, exist_ok=True)
            path = os.path.join(
                self.directory, f"profile-{os.getpid()}.json"
            )
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, sort_keys=True)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            self._dirty = False
            invalidate_cache()
            return path
