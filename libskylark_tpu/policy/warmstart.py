"""Warm start: replay the profile's hot plan keys before first traffic.

A fresh process pays the full trace + XLA-compile cost for every plan
its predecessor already measured (``plans.stats()["compile_seconds"]``
— recorded in the profile store's meta block).  ``warm_start()``
collapses that cold start twice over:

1. **XLA compilation cache** — re-applies the persisted compilation
   cache directory (the one recorded at ``run_summary`` time, or the
   store's own ``xla-cache/`` subdirectory) so XLA reloads executables
   instead of recompiling them.  An explicitly configured cache dir
   (``--xla-cache-dir``) always wins — warm start only fills the knob
   when it is unset.
2. **Plan replay** — reconstructs the store's hottest (sketch,
   signature) keys (``SketchTransform.from_json`` + a zeros array of
   the recorded abstract shape) and pushes them through the live plan
   entry points, so the process-wide ``PlanCache`` holds the traced
   executables before the first real request arrives.

Replays are firewalled per key: a stale record (sketch type renamed,
shape no longer valid) is skipped and counted, never fatal.
"""

from __future__ import annotations

import time

from . import config
from .profile import load_entries

__all__ = ["warm_start"]


def _apply_xla_cache_dir(meta: dict, directory: str) -> str | None:
    import os
    import warnings

    import jax

    try:
        current = jax.config.jax_compilation_cache_dir
    except Exception:  # noqa: BLE001 — knob absent on old jax
        return None
    if current:
        return str(current)  # explicit configuration wins
    cache_dir = meta.get("xla_cache_dir") or os.path.join(
        directory, "xla-cache"
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        return cache_dir
    except Exception as e:  # noqa: BLE001 — cache is an optimization
        warnings.warn(
            f"policy warm start could not apply the XLA cache dir ({e!r})",
            RuntimeWarning,
            stacklevel=2,
        )
        return None


def _replay_one(rec: dict) -> bool:
    import jax.numpy as jnp

    from .. import plans
    from ..sketch.base import from_json

    S = from_json(rec["sketch"])
    kind = rec.get("plan")
    shape = tuple(int(v) for v in rec.get("shape") or ())
    dtype = jnp.dtype(rec.get("dtype") or "float32")
    if kind == "apply":
        plans.apply(S, jnp.zeros(shape, dtype), rec.get("dim") or "columnwise")
    elif kind == "slice":
        acc_dtype = jnp.dtype(rec.get("acc_dtype") or "float32")
        acc = jnp.zeros((S.s, shape[1]), acc_dtype)
        plans.accumulate_slice(S, acc, jnp.zeros(shape, dtype), 0)
    elif kind == "rowwise":
        plans.apply_rowwise_bucketed(S, jnp.zeros(shape, dtype))
    else:
        return False
    return True


def warm_start(
    directory: str | None = None, *, max_plans: int | None = None
) -> dict:
    """Prime the process from the profile store; returns a summary dict
    ``{"enabled", "profile_keys", "plans_replayed", "plans_skipped",
    "xla_cache_dir", "seconds"}``.

    Safe to call unconditionally at process start (the CLIs do, under
    ``--policy``): disabled or storeless it returns immediately."""
    summary = {
        "enabled": False,
        "profile_keys": 0,
        "plans_replayed": 0,
        "plans_skipped": 0,
        "xla_cache_dir": None,
        "seconds": 0.0,
    }
    if not config.enabled():
        return summary
    directory = directory or config.policy_dir()
    if not directory:
        return summary
    view = load_entries(directory)
    if view is None or not view.get("files"):
        # No predecessor left a store here: nothing to apply.  Returning
        # early also keeps the XLA cache knob untouched (filling it from
        # a store that does not exist would be pure side effect).
        return summary
    t0 = time.perf_counter()
    summary["enabled"] = True
    summary["profile_keys"] = len(view.get("entries", {}))
    summary["xla_cache_dir"] = _apply_xla_cache_dir(
        view.get("meta") or {}, directory
    )
    budget = config.warm_plans() if max_plans is None else max(0, max_plans)
    for rec in (view.get("plans") or [])[:budget]:
        try:
            ok = _replay_one(rec)
        except Exception:  # noqa: BLE001 — stale record: skip, not fatal
            ok = False
        summary["plans_replayed" if ok else "plans_skipped"] += 1
    summary["seconds"] = round(time.perf_counter() - t0, 6)
    from .. import telemetry

    if telemetry.enabled():
        telemetry.inc("policy.warm_plans", summary["plans_replayed"])
        telemetry.event("policy", "warm_start", dict(summary))
    return summary
