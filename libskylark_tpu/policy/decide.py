"""``choose_route``: the pure decision function of the policy layer.

≙ the reference's ``algorithms/`` regression dispatch — problems carry
tags and the library picks the solver specialization — upgraded to
decide from *measured* evidence: the profile store's per-(backend,
dtype, shape-class) summaries of what the guard, the plan cache, and
the streaming engine observed on earlier runs.

Decision contract (the elastic-world invariant): a decision is a pure
function of ``(profile entry, problem signature, pinned overrides)`` —
no RNG, no clocks, no per-rank state — so every process of a
``jax.distributed`` world reading the same store files computes the
identical decision.  And the empty-store decision IS the historical
default (same sketch family, same ``min(4n, m)`` dimension, same route,
same dtype), so attempt 0 with nothing learned is bitwise identical to
the pre-policy library.

What a matured entry can change:

- **route** — repeated dense fallbacks mean the sketch route keeps
  failing on this shape class: go straight to the exact solve.
  Repeated RESKETCH verdicts mean the problems are ill-conditioned but
  recoverable: route to the preconditioned iterative solvers
  (Blendenpik dense / LSRN sparse), whose whole design point is
  near-machine-precision on exactly those problems.
- **sketch dimension** — the recorded certificates are short-budget
  ``cond_est`` evidence; a history of comfortable margins shrinks the
  dimension toward the smallest size that certified OK (and probes one
  step below it), with the guard ladder as the safety net when the
  probe undershoots.
- **route (refine)** — once the key has refinement history on record —
  at least one certified-converged refine run and no recorded
  stagnation — AND a comfortable cond margin, healthy entries earn the
  ``refine`` route: certified mixed-precision refinement reaches
  near-machine accuracy at a fraction of the exact-f64 flops.  A single
  recorded stagnation retires the route (the history requirement fails)
  until the key's refine record is clean again.
- **precision** — bf16-first on MXU backends once the entry is healthy
  and no bf16 failure is on record; the guard certificate checks the
  narrow sketch and the caller escalates back to the input dtype on a
  RESKETCH verdict (the ``f32_accumulable`` kernel entry points make
  the narrow attempt nearly free).  One rung lower, fp8 (e4m3)
  sketch-apply with f32 accumulation: strictly harder to earn — the
  key's bf16 record must be CLEAN over at least ``min_samples`` runs
  (fp8 climbs through the bf16 rung, never skips it), no fp8 failure
  on record, the backend must pass ``config.fp8_allowed``, and the JAX
  build must carry e4m3 at all (``core.precision.fp8_available``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import config
from .profile import load_entries, profile_key

__all__ = ["ProblemSignature", "Decision", "choose_route"]

# Valid least-squares routes, in escalation order of cost.
LS_ROUTES = ("sketch", "refine", "blendenpik", "lsrn", "exact")

# A certificate is "comfortable" when the estimated cond sits at least
# this factor under the guard ceiling — margin enough that a smaller
# sketch (cond grows as the dimension shrinks toward n) stays certified.
# The f32 ceiling is 0.1/sqrt(eps) ≈ 290, so the factor must leave room
# for healthy sketches (cond of a few) to qualify.
_COMFORT_MARGIN = 16.0


@dataclass(frozen=True)
class ProblemSignature:
    """What the dispatcher is allowed to see of a problem: its tags."""

    kind: str  # "ls" | "ls_stream" | "krr" | "train"
    m: int
    n: int
    targets: int = 1
    dtype: str = "float32"
    sparse: bool = False
    backend: str = "cpu"

    @property
    def key(self) -> str:
        return profile_key(
            self.kind, self.backend, self.dtype, self.m, self.n
        )


@dataclass
class Decision:
    """One routing decision plus its provenance (``info["policy"]``)."""

    route: str
    sketch_type: str
    sketch_size: int
    compute_dtype: str | None = None
    source: str = "default"  # default | profile
    key: str = ""
    escalated: bool = False
    reasons: list = field(default_factory=list)

    def to_dict(self) -> dict:
        d = {
            "route": self.route,
            "sketch_type": self.sketch_type,
            "sketch_size": int(self.sketch_size),
            "source": self.source,
            "key": self.key,
        }
        if self.compute_dtype:
            d["compute_dtype"] = self.compute_dtype
        if self.escalated:
            d["escalated"] = True
        if self.reasons:
            d["reasons"] = list(self.reasons)
        return d


def _default_decision(sig: ProblemSignature) -> Decision:
    """The historical defaults, exactly (bit-parity anchor)."""
    if sig.kind == "ls":
        stype = "CWT" if sig.sparse else "FJLT"
        s = min(4 * sig.n, sig.m)
        return Decision("sketch", stype, s, key=sig.key)
    if sig.kind == "ls_stream":
        stype = "CWT" if sig.sparse else "JLT"
        s = min(4 * sig.n, sig.m)
        return Decision("sketch", stype, s, key=sig.key)
    if sig.kind == "krr":
        # n is the feature count the caller fixed; the route is the
        # Cholesky normal-equations solve.  Only precision is decidable.
        return Decision("cholesky", "-", sig.n, key=sig.key)
    if sig.kind == "train":
        # n is the total random-feature count the trainer's maps fixed;
        # the route is the BlockADMM consensus trainer.  Only the
        # precision rung is decidable.
        return Decision("admm", "-", sig.n, key=sig.key)
    raise ValueError(f"unknown problem kind {sig.kind!r}")


def _cond_ceiling(dtype: str) -> float:
    from ..guard import config as guard_config

    try:
        return float(guard_config.cond_max(dtype))
    except TypeError:
        return float(guard_config.cond_max())


def _healthy(entry: dict) -> bool:
    g = entry.get("guard") or {}
    return (
        int(g.get("fallback", 0)) == 0 and int(g.get("resketch", 0)) == 0
    )


def choose_route(
    sig: ProblemSignature,
    *,
    route: str | None = None,
    sketch_type: str | None = None,
    sketch_size: int | None = None,
    guard_on: bool = True,
    store_view: dict | None = None,
) -> Decision:
    """Decide (route, sketch family + dimension, precision) for ``sig``.

    Explicit overrides win unconditionally: a caller-pinned ``route`` /
    ``sketch_type`` / ``sketch_size`` is honored verbatim and the policy
    only fills the fields left open.  With the layer disabled, the store
    empty, the entry immature (< ``SKYLARK_POLICY_MIN_SAMPLES`` runs),
    or guarding off (deviations lean on certification as the safety
    net), the decision is exactly the historical default.
    """
    d = _default_decision(sig)
    if route is not None:
        d.route = route
        d.reasons.append("route pinned by caller")
    if sketch_type is not None:
        d.sketch_type = sketch_type
    if sketch_size is not None:
        d.sketch_size = int(sketch_size)
    if not config.enabled() or not guard_on:
        return d
    view = store_view if store_view is not None else load_entries()
    entry = (view or {}).get("entries", {}).get(sig.key)
    from .. import telemetry

    if entry is None or int(entry.get("runs", 0)) < config.min_samples():
        telemetry.inc("policy.profile_misses")
        return d
    telemetry.inc("policy.profile_hits")
    d.source = "profile"
    runs = max(1, int(entry.get("runs", 1)))
    g = entry.get("guard") or {}
    fallback_rate = int(g.get("fallback", 0)) / runs
    resketch_rate = int(g.get("resketch", 0)) / runs
    healthy = _healthy(entry)

    # -- route ---------------------------------------------------------------
    if route is None and sig.kind == "ls":
        if fallback_rate >= 0.5:
            d.route = "exact"
            d.reasons.append(
                f"fallback rate {fallback_rate:.2f}: sketching keeps "
                "failing on this shape class"
            )
        elif resketch_rate >= 0.5:
            d.route = "lsrn" if sig.sparse else "blendenpik"
            d.reasons.append(
                f"resketch rate {resketch_rate:.2f}: ill-conditioned but "
                "recoverable; preconditioned iterative route"
            )
        else:
            # The refine route must be EARNED through recorded refine
            # history (an "auto" caller never lands here cold): at least
            # one certified-converged run, zero recorded stagnations —
            # a single stagnation retires the route — plus a healthy
            # guard record and a comfortable cond margin so the
            # low-precision factorization has headroom.
            rf = entry.get("refine") or {}
            cond_seen = (entry.get("cond") or {}).get("max")
            if (
                healthy
                and int(rf.get("ok", 0)) >= 1
                and int(rf.get("stagnate", 0)) == 0
                and cond_seen is not None
                and float(cond_seen) * _COMFORT_MARGIN
                < _cond_ceiling(sig.dtype)
            ):
                d.route = "refine"
                d.reasons.append(
                    f"refine earned: {int(rf.get('ok', 0))} certified "
                    "refine runs, no stagnation, comfortable cond margin"
                )

    # -- sketch dimension ----------------------------------------------------
    if (
        sketch_size is None
        and d.route == "sketch"
        and sig.kind in ("ls", "ls_stream")
        and healthy
    ):
        sk = entry.get("sketch") or {}
        cond = entry.get("cond") or {}
        floor = min(2 * sig.n, sig.m)
        target = d.sketch_size
        if sk.get("min_ok") is not None:
            target = min(target, int(sk["min_ok"]))
        cond_max_seen = cond.get("max")
        if (
            cond_max_seen is not None
            and float(cond_max_seen) * _COMFORT_MARGIN
            < _cond_ceiling(sig.dtype)
        ):
            # Comfortable margin: probe one geometric step below the
            # smallest certified size.  The runtime certificate (the
            # short-budget cond_est the guard runs on every attempt 0)
            # validates the probe; an undershoot climbs the grow rung
            # and the recorded RESKETCH retires further shrinks.
            target = (target * 3) // 4
            d.reasons.append(
                f"cond margin {float(cond_max_seen):.3e} ≪ ceiling: "
                "probing a smaller sketch dimension"
            )
        new_s = max(floor, min(d.sketch_size, target))
        if new_s != d.sketch_size:
            d.sketch_size = int(new_s)
            if not d.reasons or "probing" not in d.reasons[-1]:
                d.reasons.append("shrunk to smallest certified-OK dimension")

    # -- precision -----------------------------------------------------------
    bf = entry.get("bf16") or {}
    if (
        sig.dtype == "float32"
        and not sig.sparse
        and sig.kind in ("ls", "krr", "train")
        and d.route != "refine"  # refine owns its precision rung
        and healthy
        and int(bf.get("fail", 0)) == 0
        and config.bf16_allowed(sig.backend)
    ):
        d.compute_dtype = "bfloat16"
        d.reasons.append(
            "bf16-first: healthy entry, no bf16 failure on record; guard "
            "certifies, f32 is the escalation rung"
        )
        fp = entry.get("fp8") or {}
        from ..core.precision import fp8_available

        if (
            int(bf.get("ok", 0)) >= config.min_samples()
            and int(fp.get("fail", 0)) == 0
            and config.fp8_allowed(sig.backend)
            and fp8_available()
        ):
            # The rung below: e4m3 operands, f32 accumulation.  Earned
            # only through a proven-clean bf16 history at this key, and
            # retired by a single recorded fp8 failure.
            d.compute_dtype = "float8_e4m3fn"
            d.reasons.append(
                f"fp8-first: {int(bf.get('ok', 0))} clean bf16 runs, no "
                "fp8 failure on record; guard certifies, f32 escalates"
            )
    return d
