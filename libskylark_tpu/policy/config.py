"""Runtime knobs of the adaptive execution policy layer.

All knobs are environment variables read PER CALL (the established
``SKYLARK_GUARD`` / ``SKYLARK_TELEMETRY`` discipline) so tests and
operators can flip them at runtime:

- ``SKYLARK_POLICY`` — ``0``/``false`` disables the policy layer
  entirely: no profile reads, no routing, no warm start; every routed
  entrypoint behaves exactly like the pre-policy library.  Default ON —
  but with no profile store configured (and on every key the store has
  not matured for) the decisions are bitwise identical to the historical
  defaults, so "on with nothing learned" is indistinguishable from off.
- ``SKYLARK_POLICY_DIR`` — directory of the JSON profile store
  (``profile-<pid>.json`` per writer, merged last-writer-wins on read).
  Unset: decisions stay default and nothing is ever written.
- ``SKYLARK_POLICY_MIN_SAMPLES`` — observed runs a (backend, dtype,
  shape-class) key needs before decisions may deviate from the defaults
  (default 3: one run proves nothing about the randomness).
- ``SKYLARK_POLICY_WARM_PLANS`` — hot plan keys replayed through the
  plan cache by :func:`~libskylark_tpu.policy.warm_start` (default 8).
- ``SKYLARK_POLICY_BF16`` — ``1`` force-allows the bf16-first precision
  rung on any backend (CPU tests), ``0`` force-denies it; unset, bf16 is
  considered only on MXU backends (tpu/gpu) where the
  ``f32_accumulable`` kernel entry points make it cheap.
- ``SKYLARK_POLICY_FP8`` — same contract one rung lower: ``1``
  force-allows the fp8 (e4m3) sketch-apply rung anywhere (CPU tests,
  when XLA can lower f8 there), ``0`` force-denies; unset, fp8 is
  considered only on MXU backends AND only after the key's bf16 history
  is clean (fp8 is strictly more aggressive, so it must climb through
  the bf16 rung first — ``policy/decide.py``).
"""

from __future__ import annotations

import os

__all__ = [
    "enabled",
    "policy_dir",
    "configure",
    "min_samples",
    "warm_plans",
    "bf16_allowed",
    "fp8_allowed",
]

# configure() override; None defers to SKYLARK_POLICY_DIR.
_DIR_OVERRIDE: list = [None]


def enabled() -> bool:
    """Policy is on unless ``SKYLARK_POLICY=0`` (checked per call)."""
    return os.environ.get("SKYLARK_POLICY", "").lower() not in ("0", "false")


def policy_dir() -> str | None:
    """The profile-store directory (``configure()`` wins over the env)."""
    if _DIR_OVERRIDE[0] is not None:
        return _DIR_OVERRIDE[0]
    return os.environ.get("SKYLARK_POLICY_DIR") or None


def configure(directory) -> None:
    """Point the profile store at ``directory`` (overrides
    ``SKYLARK_POLICY_DIR``; ``None`` reverts to the env knob)."""
    _DIR_OVERRIDE[0] = str(directory) if directory else None


def min_samples(default: int = 3) -> int:
    """Runs a profile key needs before decisions deviate (≥ 1)."""
    raw = os.environ.get("SKYLARK_POLICY_MIN_SAMPLES")
    if raw is None:
        return default
    try:
        return max(1, int(raw))
    except ValueError:
        return default


def warm_plans(default: int = 8) -> int:
    """Hot plan keys ``warm_start`` replays (0 disables the replay)."""
    raw = os.environ.get("SKYLARK_POLICY_WARM_PLANS")
    if raw is None:
        return default
    try:
        return max(0, int(raw))
    except ValueError:
        return default


def bf16_allowed(backend: str) -> bool:
    """May the precision rung propose bf16-first on ``backend``?"""
    raw = os.environ.get("SKYLARK_POLICY_BF16")
    if raw is not None:
        return raw.lower() not in ("0", "false", "")
    return backend in ("tpu", "gpu", "cuda", "rocm", "axon")


def fp8_allowed(backend: str) -> bool:
    """May the precision rung propose the fp8 (e4m3) sketch-apply rung
    on ``backend``?  Same override contract as :func:`bf16_allowed`;
    the history gates (clean bf16 record, no fp8 failures) live in
    ``decide.py`` — this is the hardware/env gate only."""
    raw = os.environ.get("SKYLARK_POLICY_FP8")
    if raw is not None:
        return raw.lower() not in ("0", "false", "")
    return backend in ("tpu", "gpu", "cuda", "rocm", "axon")
