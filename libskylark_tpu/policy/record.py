"""Observation recording: solve outcomes and hot plan keys → the store.

The flow mirrors the telemetry ledger's lifecycle: routed entrypoints
call :func:`consult` before the solve and :func:`observe` after it
(queuing an observation in memory), the plan layer calls
:func:`note_plan` on every planned apply (counting hot keys), and the
terminal ``telemetry.run_summary`` of the run calls :func:`flush` —
which folds everything pending into this process's profile file.  With
the layer disabled or no ``SKYLARK_POLICY_DIR`` configured, every one
of these is an allocation-free early return.
"""

from __future__ import annotations

import threading
import time

from . import config
from .decide import Decision, ProblemSignature, choose_route
from .profile import ProfileStore

__all__ = [
    "consult",
    "observe",
    "note_plan",
    "flush",
    "recording_active",
    "reset",
]

_LOCK = threading.RLock()
_STATE = {"store": None, "pending": 0}


def recording_active() -> bool:
    """True when observations will actually be persisted."""
    return config.enabled() and config.policy_dir() is not None


def _store() -> ProfileStore:
    with _LOCK:
        st = _STATE["store"]
        directory = config.policy_dir()
        if st is None or st.directory != directory:
            st = ProfileStore(directory)
            _STATE["store"] = st
        return st


def reset() -> None:
    """Drop pending state (test hook; nothing on disk is touched)."""
    with _LOCK:
        _STATE["store"] = None
        _STATE["pending"] = 0


def _backend() -> str:
    try:
        import jax

        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 — no backend: profile under "cpu"
        return "cpu"


def consult(
    kind: str,
    *,
    m: int,
    n: int,
    targets: int = 1,
    dtype,
    sparse: bool = False,
    route: str | None = None,
    sketch_type: str | None = None,
    sketch_size: int | None = None,
    guard_on: bool = True,
) -> Decision:
    """Build the signature and run :func:`~libskylark_tpu.policy.
    choose_route`; the one call every routed entrypoint makes."""
    sig = ProblemSignature(
        kind=kind,
        m=int(m),
        n=int(n),
        targets=int(targets),
        dtype=str(dtype),
        sparse=bool(sparse),
        backend=_backend(),
    )
    d = choose_route(
        sig,
        route=route,
        sketch_type=sketch_type,
        sketch_size=sketch_size,
        guard_on=guard_on,
    )
    from .. import telemetry

    telemetry.inc("policy.decisions")
    # route + provenance onto any serve trace this solve is answering
    telemetry.trace_event(
        "policy",
        route=d.route,
        sketch_type=d.sketch_type,
        sketch_size=int(d.sketch_size),
        source=d.source,
        escalated=d.escalated,
        reasons=list(d.reasons),
    )
    if d.route not in ("sketch", "cholesky"):
        telemetry.inc(f"policy.route.{d.route}")
    if d.compute_dtype == "float8_e4m3fn":
        telemetry.inc("policy.fp8_first")
    elif d.compute_dtype:
        telemetry.inc("policy.bf16_first")
    return d


def _recovery_obs(info: dict | None) -> dict:
    """Fold ``info["recovery"]`` into observation fields."""
    obs: dict = {}
    rec = (info or {}).get("recovery") or {}
    attempts = rec.get("attempts") or []
    if not rec.get("guarded", False):
        return obs
    if attempts:
        first = attempts[0]
        obs["ok0"] = first.get("verdict") == "OK"
        obs["resketches"] = sum(
            1 for a in attempts if a.get("verdict") == "RESKETCH"
        )
        obs["fallback"] = any(
            a.get("action") == "fallback" or a.get("verdict") == "FALLBACK"
            for a in attempts
        )
        for a in attempts:
            if a.get("verdict") == "OK":
                if a.get("cond") is not None:
                    obs["cond"] = a["cond"]
                if a.get("sketch_size") is not None:
                    obs["sketch_size"] = a["sketch_size"]
                break
    return obs


def observe(
    decision: Decision,
    info: dict | None,
    *,
    default_size: int | None = None,
    bf16: str | None = None,
    fp8: str | None = None,
    refine: dict | None = None,
    rows_per_s: float | None = None,
    batches: int | None = None,
) -> None:
    """Queue one run observation (persisted by the next :func:`flush`)."""
    if not recording_active() or not decision.key:
        return
    obs = _recovery_obs(info)
    obs["route"] = decision.route
    obs["sketch_type"] = decision.sketch_type
    if refine is not None:
        obs["refine"] = dict(refine)
    if default_size is not None:
        obs["default_size"] = int(default_size)
    if decision.escalated:
        obs["escalated"] = True
    if bf16 is not None:
        obs["bf16"] = bf16
    elif decision.compute_dtype == "bfloat16":
        obs["bf16"] = "ok" if obs.get("ok0", True) else "fail"
    if fp8 is not None:
        obs["fp8"] = fp8
    elif decision.compute_dtype == "float8_e4m3fn":
        obs["fp8"] = "ok" if obs.get("ok0", True) else "fail"
    if rows_per_s is not None:
        obs["rows_per_s"] = rows_per_s
        obs["batches"] = int(batches or 0)
    with _LOCK:
        _store().fold(decision.key, obs, now=time.time())
        _STATE["pending"] += 1
    from .. import telemetry

    if decision.escalated:
        telemetry.inc("policy.escalations")


def note_plan(
    plan: str,
    S,
    *,
    dim: str | None = None,
    shape=None,
    dtype: str | None = None,
    acc_dtype: str | None = None,
) -> None:
    """Count one plan-cache key toward the store's hot-plan replay list.

    Called from the plan layer on every planned apply; the record keeps
    exactly what the warm start needs to replay the trace — the sketch
    JSON plus the abstract input signature."""
    if not recording_active():
        return
    try:
        rec = {
            "plan": plan,
            "sketch": S.to_json(),
            "dim": dim,
            "shape": list(shape) if shape is not None else None,
            "dtype": dtype,
            "acc_dtype": acc_dtype,
        }
    except Exception:  # noqa: BLE001 — unserializable sketch: skip
        return
    with _LOCK:
        _store().note_plan(rec)
        _STATE["pending"] += 1


def flush(name: str | None = None, info: dict | None = None) -> str | None:
    """Persist pending observations (the ``run_summary``-time write).

    Called by ``telemetry.run_summary`` before its own enabled gate, so
    profiles persist even with telemetry off.  Also records the active
    XLA compilation-cache directory (if one is configured) so
    :func:`~libskylark_tpu.policy.warm_start` can re-apply it, and the
    plan-cache compile totals for the cold-vs-warm accounting."""
    if not recording_active():
        return None
    with _LOCK:
        if _STATE["pending"] == 0:
            return None
        store = _store()
        try:
            import jax

            cache_dir = jax.config.jax_compilation_cache_dir
        except Exception:  # noqa: BLE001 — knob absent on old jax
            cache_dir = None
        from .. import plans

        st = plans.stats()
        store.set_meta(
            xla_cache_dir=cache_dir,
            plan_compiles=st["compiles"],
            plan_compile_seconds=st["compile_seconds"],
        )
        path = store.save(now=time.time())
        if path is not None:
            _STATE["pending"] = 0
        from .. import telemetry

        telemetry.inc("policy.profile_writes")
        return path
