"""Adaptive execution policy: telemetry-driven autotuning and routing.

The decision layer that turns three passive layers — ``plans`` (what
compiles cost), ``guard`` (what the certificates said), ``telemetry``
(what the run ledger measured) — into a self-tuning runtime, ≙ the
reference's ``algorithms/`` problem-tag dispatch upgraded to decide
from measured evidence:

- **Profiles** (:mod:`~libskylark_tpu.policy.profile`): per-(backend,
  dtype, shape-class) summaries persisted to a JSON store under
  ``SKYLARK_POLICY_DIR``, one CRC-guarded file per writer process,
  merged last-writer-wins — the telemetry-ledger discipline applied to
  learned state.  Written at ``run_summary`` time.
- **Routing** (:mod:`~libskylark_tpu.policy.decide`): ``choose_route``
  picks sketch family + dimension (shrinking toward the smallest
  certified-OK size), solver route (sketch-and-solve vs Blendenpik vs
  LSRN vs exact), and precision (bf16-first with guard certification,
  f32 as the escalation rung).  Decisions are pure functions of
  (profile, signature) — deterministic and identical on every rank of
  an elastic world — and the empty-store decision is bitwise the
  historical default.
- **Warm start** (:mod:`~libskylark_tpu.policy.warmstart`): replay the
  store's hot (sketch, signature) plan keys through the live
  ``PlanCache`` and re-apply the persisted XLA compilation-cache dir
  before first traffic, collapsing cold-start compile seconds.

Consulted by ``linalg.approximate_least_squares`` /
``streaming_least_squares``, ``ml.approximate_kernel_ridge``, and
``solvers.solve_regression(solver="auto")``; gated by
``SKYLARK_POLICY`` (default on — explicit ``route=`` / params overrides
always win).  See ``docs/autotuning.md``.
"""

from .config import (
    bf16_allowed,
    configure,
    enabled,
    min_samples,
    policy_dir,
    warm_plans,
)
from .decide import Decision, ProblemSignature, choose_route
from .profile import (
    ProfileStore,
    invalidate_cache,
    load_entries,
    profile_key,
    shape_class,
)
from .record import (
    consult,
    flush,
    note_plan,
    observe,
    recording_active,
    reset,
)
from .warmstart import warm_start

__all__ = [
    "enabled",
    "policy_dir",
    "configure",
    "min_samples",
    "warm_plans",
    "bf16_allowed",
    "Decision",
    "ProblemSignature",
    "choose_route",
    "ProfileStore",
    "profile_key",
    "shape_class",
    "load_entries",
    "invalidate_cache",
    "consult",
    "observe",
    "note_plan",
    "flush",
    "recording_active",
    "reset",
    "warm_start",
]
