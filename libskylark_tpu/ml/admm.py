"""Block-splitting consensus ADMM kernel-machine trainer.

≙ ``BlockADMMSolver`` (``ml/BlockADMM.hpp:16-611``): minimizes
``Σ_i loss(o_i, y_i) + λ·reg(W)`` with ``o_i = Σ_j Z_j(x_i)ᵀ W_j`` over
feature-map blocks j, by ADMM with per-(data-partition × feature-block)
local variables and cached ``(Z·Zᵀ + I)`` Cholesky factors.  The update
equations reproduce the reference train loop (``BlockADMM.hpp:374-590``):

  per iter:  mu_ij −= Wbar;  Obar −= nu
             O    = prox_loss(Obar, 1/ρ; Y)
             W    = prox_reg(Wbar − mu, λ/ρ)
             per block j:  rhs  = Wbar_j − mu_ij_j + ZtObar_j
                                  + Z_j·(del_o/(J+1) + nu)ᵀ
                           Wi_j = (Z_jZ_jᵀ + I)⁻¹ rhs      [cached chol]
                           o_j  = Wi_jᵀ Z_j;  mu_ij_j += Wi_j
                           ZtObar_j = Z_j·o_jᵀ;  sum_o += o_j
             del_o = O − sum_o;  Obar = O − del_o/(J+1);  nu += O − Obar
             Wbar = (Σ_partitions Wi + W)/(P+1);  mu += W − Wbar

TPU re-design of the parallel schedule (SURVEY §2.7 P10): the reference
maps data partitions to MPI ranks and feature blocks to OpenMP threads.
Here data partitions are an explicit **vmapped leading axis** (size P) —
the algorithm is identical for a given P regardless of device count — and
the consensus reduction ``Σ_partitions Wi`` is a plain sum that GSPMD
lowers to a psum over ICI when the P axis is sharded across the mesh.
Feature blocks are an unrolled loop of MXU GEMMs (XLA overlaps them; no
OpenMP needed).  The whole iteration is one jitted function — no host
round-trips inside a step (the reference broadcasts Wbar over MPI every
iteration, ``BlockADMM.hpp:375``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.scipy.linalg import solve_triangular

from ..core.params import Params
from ..resilient.chunked import ChunkedSolver
from ..sketch.base import Dimension
from ..solvers.prox import get_loss, get_regularizer
from ..utils.timer import PhaseTimer
from .coding import dummy_coding
from .model import FeatureMapModel

__all__ = ["ADMMParams", "BlockADMMSolver"]


@dataclass
class _PreparedRun:
    """Everything ``train``/``chunked`` need that is NOT checkpointable
    state: the realized feature blocks, cached Cholesky factors, targets,
    the jittable step function, and the initial state tuple.  All of it is
    deterministically rebuilt from (X, Y, maps, params) on resume — only
    the state tuple rides the checkpoint."""

    Zs: list
    Ls: list
    Yp: Any
    state0: tuple
    step: Callable
    timer: PhaseTimer
    d: int
    classes: Any
    dtype: Any


@dataclass
class ADMMParams(Params):
    rho: float = 1.0
    lam: float = 0.01  # regularization weight (≙ lambda)
    maxiter: int = 20
    data_partitions: int = 1  # P (≙ MPI size)
    scale_maps: bool = False  # ≙ ScaleFeatureMaps (sqrt(sj/d) per block)


class BlockADMMSolver:
    """Trainer over a list of feature maps (≙ the ctor taking per-block
    ``featureMaps``; pass maps built by ``kernel.create_rft`` as the
    reference's ``GetSolver`` does, ``ml/hilbert.hpp:11-219``)."""

    def __init__(
        self,
        loss: str,
        regularizer: str,
        feature_maps: Sequence,
        params: ADMMParams | None = None,
    ):
        self.loss = get_loss(loss)
        self.regularizer = get_regularizer(regularizer)
        self.maps = list(feature_maps)
        if not self.maps:
            raise ValueError("BlockADMMSolver needs at least one feature map")
        self.params = params or ADMMParams()

    def _apply_map(self, S, Xp, d):
        """Vmapped columnwise feature apply: Xp (P, d, ni) → (P, sj, ni)."""
        Z = jax.vmap(lambda Xc: S.apply(Xc, Dimension.COLUMNWISE))(Xp)
        if self.params.scale_maps:
            Z = Z * jnp.asarray(np.sqrt(S.s / d), Z.dtype)
        return Z

    def _prepare(self, X, Y, classes=None, regression: bool = False) -> _PreparedRun:
        """Shared setup for :meth:`train` and :meth:`chunked`: realize the
        feature blocks, cache the Cholesky factors, build the jittable
        per-iteration step and the initial state tuple."""
        p = self.params
        X = X.todense() if hasattr(X, "todense") else jnp.asarray(X)
        n, d = X.shape
        P = int(p.data_partitions)
        if n % P:
            raise ValueError(f"n={n} not divisible by data_partitions={P}")
        ni = n // P

        label_based = getattr(self.loss, "label_based", False)
        if regression:
            T = jnp.asarray(Y)
            T = T[:, None] if T.ndim == 1 else T
            k = T.shape[1]
            Yp = T.reshape(P, ni, k).transpose(0, 2, 1)
        else:
            T, classes = dummy_coding(Y, classes, dtype=X.dtype)
            k = T.shape[1]
            if label_based:
                # Hinge/logistic take class indices (≙ the reference's
                # crammed losses consuming the raw label vector).
                cls = jnp.asarray(
                    np.searchsorted(np.asarray(classes), np.asarray(Y))
                ).astype(X.dtype)
                Yp = cls.reshape(P, ni)
            else:
                Yp = T.reshape(P, ni, k).transpose(0, 2, 1)

        # Partitioned columnwise layout: Xp (P, d, ni).
        Xp = X.reshape(P, ni, d).transpose(0, 2, 1)
        dtype = X.dtype

        J = len(self.maps)
        sizes = [S.s for S in self.maps]
        starts = np.cumsum([0] + sizes)
        D = int(starts[-1])

        # Phase timers ≙ the reference's ADMM SKYLARK_TIMER instrumentation
        # (transform/iteration/prediction, BlockADMM.hpp:357-365).
        timer = PhaseTimer()
        with timer.phase("transform") as ph:
            Zs = [self._apply_map(S, Xp, d) for S in self.maps]  # (P, sj, ni)
            ph.result = Zs
        # Cached Cholesky of Z·Zᵀ + I per (partition, block)
        # (≙ Cache[j] = inv(Z·Zᵀ + I), BlockADMM.hpp:437-441).
        with timer.phase("factor") as ph:
            Ls = [
                jnp.linalg.cholesky(
                    # highest: default f32 matmul (bf16 passes on TPU) can
                    # push Z·Zᵀ + I indefinite → silent NaN factors.
                    jnp.einsum("pst,put->psu", Z, Z, precision="highest")
                    + jnp.eye(Z.shape[1], dtype=dtype)
                )
                for Z in Zs
            ]
            ph.result = Ls

        rho = jnp.asarray(p.rho, dtype)
        lam = jnp.asarray(p.lam, dtype)
        loss, reg = self.loss, self.regularizer

        def chol_solve(L, B):  # (P, s, s) x (P, s, k)
            Ysol = jax.vmap(lambda l, b: solve_triangular(l, b, lower=True))(L, B)
            return jax.vmap(
                lambda l, b: solve_triangular(l.T, b, lower=False)
            )(L, Ysol)

        # Zs/Ls/Yp enter as ARGUMENTS, not closure captures: jit would
        # embed closed-over device arrays as constants in the serialized
        # program (gigabytes of HLO — rejected/slow on AOT compile
        # services) instead of referencing device-resident buffers.
        def step(state, Zs, Ls, Yp):
            Wbar, W, mu, O, Obar, nu, del_o, mu_ij, ZtObar, _ = state
            mu_ij = mu_ij - Wbar[None]
            Obar = Obar - nu
            O = jax.vmap(lambda ob, y: loss.prox(ob, 1.0 / rho, y))(Obar, Yp)
            W = reg.prox(Wbar - mu, lam / rho)

            sum_o = jnp.zeros_like(O)
            wbar_out = jnp.zeros_like(O)
            Wi = jnp.zeros((P, D, k), dtype)
            mu_ij_new = mu_ij
            ZtObar_new = ZtObar
            dsum = del_o / (J + 1.0) + nu  # (P, k, ni)
            for j in range(J):
                lo, hi = int(starts[j]), int(starts[j + 1])
                Z = Zs[j]  # (P, sj, ni)
                wbar_out = wbar_out + jnp.einsum(
                    "psn,sk->pkn", Z, Wbar[lo:hi]
                )
                rhs = (
                    Wbar[None, lo:hi]
                    - mu_ij[:, lo:hi]
                    + ZtObar[:, lo:hi]
                    + jnp.einsum("psn,pkn->psk", Z, dsum)
                )
                Wij = chol_solve(Ls[j], rhs)  # (P, sj, k)
                o = jnp.einsum("psk,psn->pkn", Wij, Z)
                Wi = Wi.at[:, lo:hi].set(Wij)
                mu_ij_new = mu_ij_new.at[:, lo:hi].add(Wij)
                ZtObar_new = ZtObar_new.at[:, lo:hi].set(
                    jnp.einsum("psn,pkn->psk", Z, o)
                )
                sum_o = sum_o + o

            del_o = O - sum_o
            Obar = O - del_o / (J + 1.0)
            nu = nu + O - Obar
            # Consensus: sum over partitions (psum over ICI when sharded)
            # ≙ the MPI reduce of Wi (BlockADMM.hpp:574-578).
            Wbar = (jnp.sum(Wi, axis=0) + W) / (P + 1.0)
            mu = mu + W - Wbar
            obj = jax.vmap(loss.evaluate)(wbar_out, Yp).sum() + lam * reg.evaluate(Wbar)
            return (Wbar, W, mu, O, Obar, nu, del_o, mu_ij_new, ZtObar_new, obj)

        state = (
            jnp.zeros((D, k), dtype),        # Wbar
            jnp.zeros((D, k), dtype),        # W
            jnp.zeros((D, k), dtype),        # mu
            jnp.zeros((P, k, ni), dtype),    # O
            jnp.zeros((P, k, ni), dtype),    # Obar
            jnp.zeros((P, k, ni), dtype),    # nu
            jnp.zeros((P, k, ni), dtype),    # del_o
            jnp.zeros((P, D, k), dtype),     # mu_ij
            jnp.zeros((P, D, k), dtype),     # ZtObar_ij
            jnp.zeros((), dtype),            # obj
        )
        return _PreparedRun(
            Zs=Zs, Ls=Ls, Yp=Yp, state0=state, step=step, timer=timer,
            d=d, classes=classes, dtype=dtype,
        )

    def train(self, X, Y, classes=None, regression: bool = False,
              Xv=None, Yv=None):
        """X (n, d); Y (n,) labels (classification) or (n,)/(n, t) targets
        (regression).  Optional validation set (Xv, Yv) is scored every
        iteration (≙ the per-iteration validation predict,
        ``BlockADMM.hpp:509-540``) into ``model.val_history``.  Returns a
        ``FeatureMapModel`` (with ``.classes`` and ``.history`` attached).
        BCOO input is densified (the partitioned reshape needs strides)."""
        p = self.params
        run = self._prepare(X, Y, classes, regression)
        Zs, Ls, Yp = run.Zs, run.Ls, run.Yp
        state, step, timer = run.state0, run.step, run.timer
        d, classes = run.d, run.classes
        have_val = Xv is not None and Yv is not None
        if have_val:
            Xv = Xv.todense() if hasattr(Xv, "todense") else jnp.asarray(Xv)
            Yv = np.asarray(Yv)

        history, val_history = [], []
        if not have_val:
            # All iterations in ONE jitted lax.scan: the per-iteration
            # objective readback costs a full host round-trip (multi-ms on
            # a tunnelled chip), so sync once at the end and report the
            # whole objective trace from the returned array.
            @jax.jit
            def run_all(state, Zs, Ls, Yp):
                def body(st, _):
                    st = step(st, Zs, Ls, Yp)
                    return st, st[-1]

                return jax.lax.scan(body, state, None, length=p.maxiter)

            with timer.phase("iteration"):
                state, objs = run_all(state, Zs, Ls, Yp)
                history = [float(o) for o in np.asarray(objs)]
            for it, obj in enumerate(history, 1):
                p.log(1, f"iteration {it} objective {obj:.6e}")
        else:
            step = jax.jit(step)
            for it in range(1, p.maxiter + 1):
                with timer.phase("iteration"):
                    state = step(state, Zs, Ls, Yp)
                    obj = float(state[-1])  # readback syncs the step
                history.append(obj)
                msg = f"iteration {it} objective {obj:.6e}"
                with timer.phase("prediction") as ph:
                    interim = FeatureMapModel(
                        self.maps, state[0], scale_maps=p.scale_maps,
                        input_dim=d,
                    )
                    if regression:
                        pv = np.asarray(interim.predict(Xv))
                        Yv2 = Yv if Yv.ndim > 1 else Yv[:, None]
                        metric = float(
                            np.linalg.norm(pv - Yv2)
                            / max(np.linalg.norm(Yv2), 1e-30)
                        )
                        msg += f" val relerr {metric:.4f}"
                    else:
                        pv = np.asarray(interim.predict_labels(Xv, classes))
                        metric = float((pv == Yv).mean()) * 100
                        msg += f" val accuracy {metric:.2f}"
                val_history.append(metric)
                p.log(1, msg)

        p.log(2, timer.report())
        Wbar = state[0]
        model = FeatureMapModel(
            self.maps, Wbar, scale_maps=p.scale_maps, input_dim=d,
            classes=classes,
        )
        model.history = history
        model.val_history = val_history
        model.timers = timer
        return model

    def chunked(self, X, Y, classes=None, regression: bool = False) -> ChunkedSolver:
        """Preemption-safe ADMM: a ``ChunkedSolver`` whose state pytree is
        (iteration counter, the 10-tuple ADMM state, objective trace) —
        exactly what a resumed process cannot recompute.  The feature
        blocks, Cholesky factors, and targets are rebuilt by
        :meth:`_prepare` on resume (deterministic: counter-based maps,
        pinned-precision factor products), so a run resumed from a chunk
        boundary is bit-identical to the uninterrupted chunked run.
        That kill/resume bit-identity — and the chunked-vs-``train()``
        model parity it rides on — is PINNED by
        ``tests/test_distributed_train.py::TestChunkedContract`` (the
        distributed trainer's per-rank loop reuses this exact
        ``init_state/step_chunk/extract_result`` shape).

        Validation scoring is a ``train``-only feature; drive this with
        ``resilient.ResilientRunner`` and score the returned model.
        """
        p = self.params
        run = self._prepare(X, Y, classes, regression)
        maxiter = int(p.maxiter)

        def init_state():
            return dict(
                it=jnp.zeros((), jnp.int32),
                inner=run.state0,
                objs=jnp.zeros((maxiter,), run.dtype),
            )

        # Zs/Ls/Yp enter as ARGUMENTS for the same reason as in train():
        # jit would bake closed-over device arrays into the program as
        # constants.
        @partial(jax.jit, static_argnames=("num_iters",))
        def _chunk(st, Zs, Ls, Yp, num_iters: int):
            stop = jnp.minimum(st["it"] + num_iters, maxiter)

            def cond(c):
                return c["it"] < stop

            def body(c):
                inner = run.step(c["inner"], Zs, Ls, Yp)
                return dict(
                    it=c["it"] + 1,
                    inner=inner,
                    objs=c["objs"].at[c["it"]].set(inner[-1]),
                )

            return lax.while_loop(cond, body, st)

        def step_chunk(st, num_iters: int):
            return _chunk(st, run.Zs, run.Ls, run.Yp, num_iters)

        def extract_result(st):
            it = int(st["it"])
            model = FeatureMapModel(
                self.maps, st["inner"][0], scale_maps=p.scale_maps,
                input_dim=run.d, classes=run.classes,
            )
            model.history = [float(o) for o in np.asarray(st["objs"][:it])]
            model.val_history = []
            model.timers = run.timer
            return model

        return ChunkedSolver(
            init_state=init_state,
            step_chunk=step_chunk,
            extract_result=extract_result,
            is_done=lambda st: int(st["it"]) >= maxiter,
            iteration=lambda st: int(st["it"]),
            kind="block_admm",
        )
