"""Label coding for classification (≙ ``ml/coding.hpp:7-146``).

``dummy_coding``: class labels → a ±1 one-vs-all coding matrix (the
reference's ``DummyCoding``); ``decode_labels``: argmax decode back to the
original label values.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["dummy_coding", "decode_labels"]


def dummy_coding(y, classes=None, dtype=None):
    """y (n,) labels → (T, classes): T (n, k) with +1 for the true class,
    −1 elsewhere.  ``classes`` is always returned sorted (explicit inputs
    are sorted and validated, since the index lookup requires it);
    ``dtype`` defaults to JAX's current default float."""
    y = np.asarray(y)
    if classes is None:
        classes = np.unique(y)
    else:
        classes = np.unique(np.asarray(classes))
        missing = np.setdiff1d(np.unique(y), classes)
        if missing.size:
            raise ValueError(f"labels {missing.tolist()} not in classes")
    if dtype is None:
        dtype = jnp.asarray(0.0).dtype
    k = len(classes)
    idx = np.searchsorted(classes, y)
    T = -np.ones((len(y), k))
    T[np.arange(len(y)), idx] = 1.0
    return jnp.asarray(T, dtype=dtype), classes


def decode_labels(O, classes):
    """(n, k) outputs → (n,) labels by argmax (≙ coding.hpp decode)."""
    idx = jnp.argmax(jnp.asarray(O), axis=-1)
    return jnp.asarray(np.asarray(classes))[idx]
