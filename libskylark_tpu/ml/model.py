"""Model persistence and prediction (≙ ``ml/model.hpp``).

- ``FeatureMapModel`` ≙ ``hilbert_model_t`` (model.hpp:50-276): a chain of
  serialized feature maps + a coefficient matrix; ``predict`` re-applies
  the maps.  JSON save/load reconstructs the maps through the sketch
  registry (all randomness is counter-derived, so a model is a few KB of
  JSON + the coefficients).
- ``KernelModel`` ≙ the kernel models that hold the training X
  (model.hpp:278-1255): predict via k(X_train, X_test)ᵀ·A.
- ``load_model`` ≙ ``model_container_t`` (model.hpp:1138-1255): the
  polymorphic loader that dispatches a saved model's JSON to the right
  class; the persisted ``classes`` field plays the container's
  ``get_column_coding`` role (classification models carry their label
  decoding with them).
"""

from __future__ import annotations

import json
import os
from typing import Sequence

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..sketch.base import Dimension, from_dict as sketch_from_dict

__all__ = ["FeatureMapModel", "KernelModel", "load_model"]

_SERIAL_VERSION = 2  # tracks sketch.base.SERIAL_VERSION (stream revision)


def _json_info(info):
    """Best-effort JSON image of a model's ``info`` dict (the recovery /
    policy ledgers attached by the training entrypoints).  Non-JSON
    leaves degrade to ``str`` rather than dropping the whole ledger."""
    if info is None:
        return None
    return json.loads(json.dumps(info, default=str))


def _dtype_from_name(name):
    try:
        return np.dtype(name)
    except TypeError:
        # Extension dtypes (bfloat16, float8_*) register with numpy only
        # through ml_dtypes (a jax dependency) — resolve by attribute.
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _restore_dtype(arr, name):
    """Undo the ``.npy`` container's extension-dtype erasure: ``np.save``
    writes bfloat16 (and friends) as raw 2-byte void records, and
    ``np.load`` hands back dtype ``|V2`` — unusable in any arithmetic.
    The saved dtype name rides the model JSON; same-width void arrays
    are re-viewed (bit-exact), anything else is a plain cast."""
    if not name or str(arr.dtype) == name:
        return arr
    dt = _dtype_from_name(name)
    if arr.dtype.kind == "V" and arr.dtype.itemsize == dt.itemsize:
        return arr.view(dt)
    return arr.astype(dt)


class FeatureMapModel:
    """Coefficients W over concatenated feature-map outputs.

    ``maps`` may be empty (linear model on raw features, ≙ hilbert model
    with no transforms).  ``scale_maps`` applies the reference's
    ``sqrt(sj/d)`` block scaling (``BlockADMM.hpp:425-426``).
    """

    def __init__(self, maps: Sequence, W, scale_maps: bool = False,
                 input_dim=None, classes=None):
        self.maps = list(maps)
        self.W = jnp.asarray(W)
        self.scale_maps = bool(scale_maps)
        self.input_dim = input_dim or (self.maps[0].n if self.maps else None)
        # Label coding for classification models (≙ get_column_coding,
        # model.hpp:1242-1244); None for regression.
        self.classes = None if classes is None else list(
            np.asarray(classes).tolist()
        )
        # Training ledger (info["recovery"], info["policy"]) attached by
        # the solver entrypoints; persists through save/load.
        self.info = None

    def features(self, X):
        """Concatenated (n, D) feature matrix for X (n, d); BCOO inputs
        pass through to the maps' input-sparsity apply paths."""
        if not isinstance(X, jsparse.BCOO):
            X = jnp.asarray(X)
        if not self.maps:
            return X if not isinstance(X, jsparse.BCOO) else X.todense()
        blocks = []
        for S in self.maps:
            Z = S.apply(X, Dimension.ROWWISE)
            if self.scale_maps:
                Z = Z * jnp.asarray(
                    np.sqrt(Z.shape[-1] / X.shape[-1]), Z.dtype
                )
            blocks.append(Z)
        return jnp.concatenate(blocks, axis=-1)

    def predict(self, X):
        """(n, k) outputs (decision values / regression predictions)."""
        Z = self.features(X)
        return Z @ self.W.astype(Z.dtype)

    def predict_labels(self, X, classes=None):
        O = self.predict(X)
        idx = jnp.argmax(O, axis=-1)
        classes = classes if classes is not None else self.classes
        if classes is not None:
            return jnp.asarray(classes)[idx]
        return idx

    # -- persistence (≙ hilbert_model_t::save / load) -----------------------

    def to_dict(self):
        return {
            "skylark_object_type": "model",
            "skylark_version": _SERIAL_VERSION,
            "model_type": "feature_map",
            "scale_maps": self.scale_maps,
            "input_dim": self.input_dim,
            # normalize post-hoc numpy assignments to JSON scalars
            "classes": (None if self.classes is None
                        else np.asarray(self.classes).tolist()),
            "maps": [S.to_dict() for S in self.maps],
            "coef_shape": list(self.W.shape),
            "coef_dtype": str(self.W.dtype),
            "info": _json_info(self.info),
        }

    def save(self, path: str):
        """JSON metadata + .npy coefficients next to it (the reference
        embeds the dense coefficient text in the JSON; .npy is the
        faithful-but-binary equivalent)."""
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=1)
        np.save(self._coef_path(path), np.asarray(self.W))

    @classmethod
    def load(cls, path: str):
        with open(path) as f:
            d = json.load(f)
        if d.get("model_type") != "feature_map":
            raise ValueError(f"not a feature_map model: {d.get('model_type')}")
        W = _restore_dtype(np.load(cls._coef_path(path)), d.get("coef_dtype"))
        maps = [sketch_from_dict(md) for md in d["maps"]]
        model = cls(maps, jnp.asarray(W), scale_maps=d.get("scale_maps", False),
                    input_dim=d.get("input_dim"), classes=d.get("classes"))
        model.info = d.get("info")
        return model

    @staticmethod
    def _coef_path(path):
        return os.fspath(path) + ".coef.npy"


class KernelModel:
    """Kernel-space model: predict = k(X_test, X_train) @ A."""

    def __init__(self, kernel, X_train, A, classes=None):
        self.kernel = kernel
        self.X_train = jnp.asarray(X_train)
        self.A = jnp.asarray(A)
        self.input_dim = int(self.X_train.shape[1])
        self.info = None
        self.classes = None if classes is None else list(
            np.asarray(classes).tolist()
        )

    def predict(self, X):
        K = self.kernel.gram(jnp.asarray(X), self.X_train)  # (m, n)
        return K @ self.A

    def predict_labels(self, X, classes=None):
        O = self.predict(X)
        idx = jnp.argmax(O, axis=-1)
        classes = classes if classes is not None else self.classes
        if classes is not None:
            return jnp.asarray(classes)[idx]
        return idx

    def save(self, path: str):
        from .kernels import Kernel  # noqa: F401

        d = {
            "skylark_object_type": "model",
            "skylark_version": _SERIAL_VERSION,
            "model_type": "kernel",
            "classes": (None if self.classes is None
                        else np.asarray(self.classes).tolist()),
            "kernel": self.kernel.to_dict(),
            "data_dtypes": {
                "X_train": str(self.X_train.dtype),
                "A": str(self.A.dtype),
            },
            "info": _json_info(self.info),
        }
        with open(path, "w") as f:
            json.dump(d, f, indent=1)
        np.savez(
            os.fspath(path) + ".data.npz",
            X_train=np.asarray(self.X_train),
            A=np.asarray(self.A),
        )

    @classmethod
    def load(cls, path: str):
        from .kernels import from_dict as kernel_from_dict

        with open(path) as f:
            d = json.load(f)
        if d.get("model_type") != "kernel":
            raise ValueError(f"not a kernel model: {d.get('model_type')}")
        data = np.load(os.fspath(path) + ".data.npz")
        dtypes = d.get("data_dtypes") or {}
        model = cls(
            kernel_from_dict(d["kernel"]),
            jnp.asarray(_restore_dtype(data["X_train"], dtypes.get("X_train"))),
            jnp.asarray(_restore_dtype(data["A"], dtypes.get("A"))),
            classes=d.get("classes"),
        )
        model.info = d.get("info")
        return model


_MODEL_TYPES = {
    "feature_map": FeatureMapModel,
    "kernel": KernelModel,
}


def load_model(path: str):
    """Polymorphic model loader (≙ ``model_container_t``'s ptree dispatch,
    ``ml/model.hpp:1155-1166, 1208-1220``): reads the JSON header's
    ``model_type`` and loads through the right class.  The returned model
    carries its own label coding (``.classes``) when it was trained for
    classification."""
    with open(path) as f:
        d = json.load(f)
    mtype = d.get("model_type")
    if mtype not in _MODEL_TYPES:
        raise ValueError(
            f"unknown model_type {mtype!r} (expected one of "
            f"{sorted(_MODEL_TYPES)})"
        )
    return _MODEL_TYPES[mtype].load(path)
