"""Kernel ridge regression — the five solver strategies of ``ml/krr.hpp``.

1. ``kernel_ridge``: exact — Gram + Cholesky solve (≙ ``KernelRidge``,
   krr.hpp:49-92).
2. ``approximate_kernel_ridge``: feature map + ridge solve in feature
   space (≙ ``ApproximateKernelRidge``, krr.hpp:94-197).
3. ``sketched_approximate_kernel_ridge``: additionally sketches the
   feature-space ridge problem down to t rows (≙
   ``SketchedApproximateKernelRidge``, krr.hpp:199-310).
4. ``faster_kernel_ridge``: CG on the full Gram with the random-feature
   covariance preconditioner (≙ ``FasterKernelRidge`` +
   ``feature_map_precond_t``, krr.hpp:312-543).
5. ``large_scale_kernel_ridge``: memory-bounded block coordinate descent
   over feature-map chunks with cached Cholesky factors (≙
   ``LargeScaleKernelRidge``, krr.hpp:546-727).

Convention: X (n, d) rows-as-examples; Y (n,) or (n, t).  Feature-space
solvers return ``FeatureMapModel``; kernel-space ones ``KernelModel``.

TPU notes: Gram assembly, feature application, and the covariance HERK are
the MXU ops and shard over the examples axis; the s×s factorizations are
replicated-small (≙ the reference's ``[*,*]`` / ``[STAR,STAR]`` choices).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.scipy.linalg import cho_factor, cho_solve, solve_triangular

from .. import guard, plans, telemetry
from ..core.context import SketchContext
from ..core.params import Params
from ..parallel.mesh import fully_replicated
from ..sketch.base import Dimension, create_sketch
from ..solvers.krylov import KrylovParams, cg
from .kernels import Kernel
from .model import FeatureMapModel, KernelModel

__all__ = [
    "KrrParams",
    "kernel_ridge",
    "approximate_kernel_ridge",
    "sketched_approximate_kernel_ridge",
    "faster_kernel_ridge",
    "large_scale_kernel_ridge",
    "streaming_kernel_ridge",
    "streaming_approximate_kernel_ridge",
]


@dataclass
class KrrParams(Params):
    """≙ ``krr_params_t`` (krr.hpp:8-46)."""

    use_fast: bool = False          # fast feature transforms (Fastfood)
    sketched_rr: bool = False       # sketch the feature ridge problem
    sketch_size: int = -1           # -1 → 4·s (krr.hpp:146)
    fast_sketch: bool = False       # CWT instead of FJLT for the sketch
    tolerance: float = 1e-3         # iterative tolerance
    res_print: int = 10
    iter_lim: int = 1000
    max_split: int = 0              # feature chunk size (large-scale)
    # Preemption safety (resilient.ResilientRunner over the CG path; no
    # reference counterpart — the reference is MPI fail-stop):
    checkpoint_dir: str | None = None
    checkpoint_every: int = 25      # CG iterations per checkpoint round
    resume: bool = False


def _psd_gram(A, B):
    """Gram products feeding a Cholesky run at ``precision='highest'``
    with ≥f32 OUTPUT: TPU's default f32 matmul passes through bf16,
    whose error can push ``ZᵀZ + λI`` indefinite for small λ (cho_factor
    then yields silent NaNs).  bf16 inputs keep full MXU rate — their
    products accumulate exactly in f32 — but the result must NOT round
    back to bf16 (a bf16 Gram re-introduces the same ~2e-3 hazard at the
    output; round-3 review finding), so the accumulator dtype is pinned.
    """
    acc = jnp.promote_types(A.dtype, jnp.float32)
    return jnp.dot(A, B, precision="highest", preferred_element_type=acc)


def _as2d(Y):
    Y = jnp.asarray(Y)
    return (Y[:, None], True) if Y.ndim == 1 else (Y, False)


def _dense(X):
    """Densify BCOO for Gram-matrix paths (kernel matrices are dense
    anyway); leave dense arrays untouched."""
    return X.todense() if hasattr(X, "todense") else jnp.asarray(X)


def _maybe_sparse(X):
    """Keep BCOO as-is for feature-map paths (the sketches handle it)."""
    return X if hasattr(X, "todense") else jnp.asarray(X)


def _tag(params: KrrParams) -> str:
    return "fast" if params.use_fast else "regular"


def kernel_ridge(kernel: Kernel, X, Y, lam: float, params: KrrParams | None = None):
    """Exact KRR: solve (K + λI)·A = Y; returns a ``KernelModel``."""
    params = params or KrrParams()
    X = _dense(X)
    Y2, _ = _as2d(Y)
    K = kernel.gram(X)
    n = K.shape[0]
    Kl = fully_replicated(K + lam * jnp.eye(n, dtype=K.dtype))
    A = cho_solve(cho_factor(Kl, lower=True), Y2)
    return KernelModel(kernel, X, A)


def approximate_kernel_ridge(
    kernel: Kernel,
    X,
    Y,
    lam: float,
    s: int,
    context: SketchContext,
    params: KrrParams | None = None,
):
    """Feature map Z = S(X) (n, s), then ridge: (ZᵀZ + λI)W = ZᵀY.

    ≙ ``ApproximateKernelRidge`` (krr.hpp:94-197; its ``El::Ridge`` is the
    same normal-equations solve).  Returns a ``FeatureMapModel``; under
    guarding (``SKYLARK_GUARD``, default on) a non-finite Cholesky factor
    (singular/indefinite-by-rounding regularized Gram) falls back to the
    eigh pseudoinverse solve, the coefficients pass a finiteness
    sentinel, and ``model.info["recovery"]`` records the attempts.

    Policy (``SKYLARK_POLICY``, on by default): a matured profile entry
    for this (backend, dtype, shape-class) may run the feature Gram
    bf16-first (the MXU-heavy ops; ``_psd_gram`` still accumulates
    exactly in f32), escalating back to the feature dtype when the bf16
    attempt trips the guard fallback — the decision lands in
    ``model.info["policy"]``.  With an empty store the solve is bitwise
    identical to the unrouted library.
    """
    params = params or KrrParams()
    X = _maybe_sparse(X)
    Y2, _ = _as2d(Y)
    S = kernel.create_rft(s, _tag(params), context)
    Z = plans.apply(S, X, Dimension.ROWWISE)  # (n, s)
    if params.sketched_rr:
        return _solve_sketched_ridge(S, Z, Y2, lam, s, context, params)
    # Host-side sentinel reads cannot run under an enclosing jit trace.
    guarded = guard.enabled() and not guard.is_traced(Z, Y2)
    from .. import policy

    decision = policy.consult(
        "krr",
        m=X.shape[0],
        n=int(s),
        targets=Y2.shape[1],
        dtype=Z.dtype.name,
        sparse=hasattr(X, "todense"),
        guard_on=guarded,
    )

    def ridge_solve(Zs):
        report = (
            guard.RecoveryReport(stage="approximate_krr")
            if guarded
            else guard.RecoveryReport.disabled("approximate_krr")
        )
        G = fully_replicated(
            _psd_gram(Zs.T, Zs) + lam * jnp.eye(s, dtype=Zs.dtype)
        )
        # Factor/solve in _psd_gram's ≥f32 accumulator dtype; the model's
        # coefficient dtype stays the feature dtype (API contract — bf16
        # features must not silently return an f32 model).
        c, low = cho_factor(G, lower=True)
        fellback = False
        if guarded and not guard.tree_all_finite(c):
            W = guard.pinv_psd_solve(G, Zs.T @ Y2).astype(Zs.dtype)
            report.record(
                "fallback", verdict=guard.FALLBACK,
                detail="non-finite Cholesky factor; eigh pseudoinverse solve",
            )
            report.recovered = True
            fellback = True
        else:
            W = cho_solve((c, low), Zs.T @ Y2).astype(Zs.dtype)
        if guarded:
            guard.check_finite(W, "approximate_krr", report=report)
        return W, report, fellback

    bf16_note = None
    if decision.compute_dtype == "bfloat16":
        from ..utils.exceptions import NumericalHealthError

        try:
            W, report, fellback = ridge_solve(Z.astype(jnp.bfloat16))
        except NumericalHealthError:
            W, fellback = None, True
        if fellback:
            decision.escalated = True
            bf16_note = "fail"
            W, report, _ = ridge_solve(Z)
        else:
            W = W.astype(Z.dtype)
    else:
        W, report, _ = ridge_solve(Z)
    model = FeatureMapModel([S], W)
    model.info = {"recovery": report.to_dict(), "policy": decision.to_dict()}
    policy.observe(decision, model.info, bf16=bf16_note)
    telemetry.run_summary("approximate_krr", model.info)
    return model


def _solve_sketched_ridge(S, Z, Y2, lam, s, context, params):
    """Sketch the (n, s) ridge problem down to t rows (krr.hpp:135-180)."""
    n = Z.shape[0]
    t = params.sketch_size if params.sketch_size != -1 else min(4 * s, n)
    sk_type = "CWT" if params.fast_sketch else "FJLT"
    R = create_sketch(sk_type, n, t, context)
    SZ = plans.apply(R, Z, Dimension.COLUMNWISE)  # (t, s)
    SY = plans.apply(R, Y2, Dimension.COLUMNWISE)  # (t, k)
    G = fully_replicated(_psd_gram(SZ.T, SZ) + lam * jnp.eye(s, dtype=Z.dtype))
    W = cho_solve(cho_factor(G, lower=True), SZ.T @ SY).astype(Z.dtype)
    return FeatureMapModel([S], W)


def sketched_approximate_kernel_ridge(
    kernel, X, Y, lam, s, context, params: KrrParams | None = None
):
    """≙ ``SketchedApproximateKernelRidge`` (krr.hpp:199-310)."""
    params = dataclasses.replace(params or KrrParams(), sketched_rr=True)
    return approximate_kernel_ridge(kernel, X, Y, lam, s, context, params)


class _FeatureMapPrecond:
    """(ZᵀZ + λI)⁻¹ as a preconditioner for (K + λI), via Woodbury.

    ≙ ``feature_map_precond_t`` (krr.hpp:312-450): U = Z (s, n) features;
    C = I + U·Uᵀ/λ, L = chol(C), Ũ = L⁻¹U/λ; apply(B) = B/λ − Ũᵀ(Ũ·B).
    """

    def __init__(self, kernel, lam, X, s, context, params):
        S = kernel.create_rft(s, _tag(params), context)
        U = plans.apply(S, jnp.asarray(X), Dimension.ROWWISE).T  # (s, n)
        lam = jnp.asarray(lam, U.dtype)
        C = fully_replicated(
            jnp.eye(s, dtype=U.dtype) + _psd_gram(U, U.T) / lam
        )
        L = jnp.linalg.cholesky(C)
        # Solve in C's ≥f32 dtype, store Ũ back in the feature dtype —
        # the (s, n) buffer is the precond's memory footprint.
        self.U = (solve_triangular(L, U.astype(C.dtype), lower=True) / lam).astype(
            U.dtype
        )
        self.lam = lam

    def apply(self, B):
        return B / self.lam - self.U.T @ (self.U @ B)

    def apply_adjoint(self, B):
        return self.apply(B)


def faster_kernel_ridge(
    kernel: Kernel,
    X,
    Y,
    lam: float,
    s: int,
    context: SketchContext,
    params: KrrParams | None = None,
):
    """CG on (K + λI)·A = Y preconditioned by the random-feature
    covariance (≙ ``FasterKernelRidge``, krr.hpp:452-543)."""
    params = params or KrrParams()
    X = _dense(X)
    Y2, _ = _as2d(Y)
    K = kernel.gram(X)
    n = K.shape[0]
    Kl = K + lam * jnp.eye(n, dtype=K.dtype)
    P = _FeatureMapPrecond(kernel, lam, X, s, context, params)
    kp = KrylovParams(tolerance=params.tolerance, iter_lim=params.iter_lim)
    if params.checkpoint_dir:
        # Preemption-safe CG: everything outside the CG state (Gram,
        # preconditioner) is deterministically rebuilt from (X, context)
        # on resume, so only the Krylov carry rides the checkpoint.
        from ..resilient import ResilientParams, ResilientRunner
        from ..solvers.krylov import cg_chunked

        A, info = ResilientRunner(
            cg_chunked(Kl, Y2, precond=P, params=kp),
            ResilientParams(
                am_i_printing=params.am_i_printing,
                log_level=params.log_level,
                prefix=params.prefix,
                checkpoint_dir=params.checkpoint_dir,
                checkpoint_every=params.checkpoint_every,
                resume=params.resume,
            ),
        ).run()
    else:
        A, info = cg(Kl, Y2, precond=P, params=kp)
    model = KernelModel(kernel, X, A)
    model.info = info
    return model


def _chunk_sizes(d: int, s: int, params: KrrParams) -> list[int]:
    """Feature-chunk sizes (≙ krr.hpp:573-592) — ONE implementation
    shared by the large-scale and streaming solvers: both build their
    feature maps from the same context, so identical chunking is what
    keeps their counter streams (and trained models) interchangeable."""
    sinc = d if params.max_split == 0 else max(1, params.max_split // 2)
    sizes = []
    remains = s
    while remains > 0:
        this = remains if remains <= 2 * sinc else sinc
        sizes.append(this)
        remains -= this
    return sizes


def large_scale_kernel_ridge(
    kernel: Kernel,
    X,
    Y,
    lam: float,
    s: int,
    context: SketchContext,
    params: KrrParams | None = None,
):
    """Memory-bounded block coordinate descent over feature chunks.

    ≙ ``LargeScaleKernelRidge`` (krr.hpp:546-727): chunk the s features
    into C transforms of ~max_split/2 each; iterate
      ZR = Z_c·R − λ·W_c;  δ = (Z_cZ_cᵀ + λI)⁻¹·ZR  (cached Cholesky);
      W_c += δ;  R −= Z_cᵀ·δ
    until the relative update is below tolerance.
    """
    params = params or KrrParams()
    X = _maybe_sparse(X)
    Y2, _ = _as2d(Y)
    n, d = X.shape

    sizes = _chunk_sizes(d, s, params)
    maps = [kernel.create_rft(sz, _tag(params), context) for sz in sizes]

    # Memory-bounded by construction: each chunk's Z is recomputed from
    # its counter-based map on every sweep and never held alongside the
    # others (≙ the reference re-applying featureMaps[c] per iteration;
    # only the small per-chunk Cholesky factors are cached,
    # krr.hpp:608-660).  Peak extra memory = one (n, max chunk) block.
    def chunk_Z(c):
        # Plan-cached: every sweep re-derives this chunk's features, so
        # the fused executable compiled on sweep 1 serves all of them.
        return plans.apply(maps[c], X, Dimension.ROWWISE).T  # (sz, n)

    # First sweep builds the cached factors (krr.hpp:608-660); the first
    # chunk also establishes the feature dtype for the state arrays.
    factors = []
    Ws = None
    t = Y2.shape[1]
    Z = None
    for c in range(len(maps)):
        Z = None  # release chunk c-1 before materializing chunk c
        Z = chunk_Z(c)
        if Ws is None:
            dtype = Z.dtype
            lam_ = jnp.asarray(lam, dtype)
            Ws = [jnp.zeros((sz, t), dtype) for sz in sizes]
            R = Y2.astype(dtype)
        G = fully_replicated(
            _psd_gram(Z, Z.T) + lam_ * jnp.eye(Z.shape[0], dtype=dtype)
        )
        Lc = cho_factor(G, lower=True)
        factors.append(Lc)
        ZR = Z @ R - lam_ * Ws[c]
        # cast back: the f32 factor solve must not promote the resident
        # (n, t) R / Ws state out of the feature dtype (memory contract)
        delta = cho_solve(Lc, ZR).astype(dtype)
        Ws[c] = Ws[c] + delta
        R = R - Z.T @ delta
        # Same one-chunk memory contract as the later sweeps: block until
        # this chunk executed before dispatching (= allocating) the next.
        jax.block_until_ready(delta)

    # More sweeps (krr.hpp:668-727).  The per-chunk float() readback is a
    # deliberate host sync: under async dispatch the next chunk's (n, sz)
    # Z buffer is ALLOCATED at dispatch time, so without a sync several
    # chunks can be resident at once and the one-chunk memory contract
    # (the reason this solver exists) breaks.  At capacity scale the
    # round-trip is ~3% of a sweep — not worth trading the bound for.
    for it in range(1, params.iter_lim):
        delsize = 0.0
        for c in range(len(maps)):
            Z = None  # release chunk c-1 before materializing chunk c
            Z = chunk_Z(c)
            ZR = Z @ R - lam_ * Ws[c]
            delta = cho_solve(factors[c], ZR).astype(dtype)
            Ws[c] = Ws[c] + delta
            R = R - Z.T @ delta
            delsize += float(jnp.sum(delta * delta))
        wnorm = float(
            jnp.sqrt(sum(jnp.sum(W * W) for W in Ws))
        )
        reldel = (delsize**0.5) / max(wnorm, 1e-30)
        params.log(2, f"iteration {it}, relupdate = {reldel:.2e}")
        if reldel < params.tolerance:
            break

    W = jnp.concatenate(Ws, axis=0)
    return FeatureMapModel(maps, W)


def streaming_approximate_kernel_ridge(
    kernel: Kernel,
    source,
    lam: float,
    s: int,
    context: SketchContext,
    params: KrrParams | None = None,
    *,
    targets: int = 1,
    stream_params=None,
    fault_plan=None,
):
    """One-pass :func:`approximate_kernel_ridge` over ``(X_block,
    y_block)`` batches — X never resident.

    The normal equations accumulate per batch (``G += Z_bᵀZ_b``,
    ``c += Z_bᵀy_b`` with ``Z_b = S(X_b)`` rowwise) through the
    ``streaming`` engine, which brings the prefetch pipeline and
    checkpoint/resume (``stream_params`` — a
    :class:`~libskylark_tpu.streaming.StreamParams`) along.  Trained on
    the same ``context`` seed, the model is allclose-interchangeable
    with the in-core solver's, modulo per-batch summation order.
    ``source`` is an iterable of batches or a re-openable factory
    ``f(start_batch) -> iterator`` (``io.stream_libsvm`` /
    ``io.stream_hdf5`` wrapped in a lambda both qualify).

    Guarding (``SKYLARK_GUARD``, on by default): a batch that NaN-poisons
    the accumulators is replayed at the chunk boundary and a non-finite
    Cholesky factor reroutes to the eigh pseudoinverse solve; the guard's
    :class:`~libskylark_tpu.guard.RecoveryReport` ledger lands in
    ``model.info["recovery"]``.  ``fault_plan``
    (:class:`~libskylark_tpu.resilient.FaultPlan` with
    ``nan_at``/``bad_sketch_at`` keyed by batch index) injects the
    faults the guard recovers from.
    """
    from .. import streaming

    return streaming.kernel_ridge(
        source, kernel, lam, s, context,
        targets=targets, krr_params=params, params=stream_params,
        fault_plan=fault_plan,
    )


def streaming_kernel_ridge(
    kernel: Kernel,
    block_fn,
    shape: tuple[int, int],
    Y,
    lam: float,
    s: int,
    context: SketchContext,
    params: KrrParams | None = None,
    block_rows: int = 262_144,
    feature_dtype=jnp.bfloat16,
    block_args: tuple = (),
    timer=None,
):
    """Row-streamed block coordinate descent: the single-chip face of the
    10M×4K north-star shape.

    ``block_args``: extra device arrays threaded into ``block_fn(start,
    rows, *block_args)`` as REAL jit arguments.  A ``block_fn`` that
    closes over a large device array instead would be embedded as a
    compile-time constant (and round-tripped through the host — an OOM /
    HTTP-413 on the axon tunnel); counter-generated sources need none.

    ``timer``: optional ``utils.PhaseTimer`` — sweep 0 (which absorbs
    the per-chunk program compiles and factorizations) lands in phase
    ``"sweep0"``, steady sweeps in ``"sweep"`` (the ADMM solver's
    phase-timer convention; lets benchmarks read the marginal s/sweep
    without compile-cancellation tricks).

    ``large_scale_kernel_ridge`` (≙ krr.hpp:546-727) bounds memory in the
    FEATURE direction but keeps X — and each chunk's (n, sz) Z — resident;
    at 10M×4096 neither fits one chip (X alone is 80 GB in bf16).  Here
    the EXAMPLES direction streams too: ``block_fn(start_row, rows)``
    yields X row panels (jit-traceable with a traced start, like the
    streaming-SVD contract), each chunk's features are regenerated per
    panel inside a ``fori_loop``, and only O(panel·max(d, sz)) feature
    memory plus the (n, t) residual R is ever resident.  Per sweep each
    chunk makes two panel passes (accumulate ZR = Z_c·R, then apply
    R ← R − Z_cᵀ·δ) — the BCD update equations are exactly
    ``large_scale_kernel_ridge``'s.

    The reference reaches this scale by spreading X over MPI ranks
    (krr.hpp:546's Elemental [MC,MR] X); one TPU chip instead re-reads
    the counter stream / storage.  Multi-chip runs shard the panels with
    ``mesh`` machinery upstream (see ``__graft_entry__.dryrun_multichip``).
    """
    params = params or KrrParams()
    n, d = shape
    if n % block_rows:
        # Largest divisor of n not exceeding the request: callers get a
        # working panel size instead of a divisibility error (the panel
        # size only shapes memory, not results).  A degenerate divisor
        # (n near-prime) would turn the panel loops into per-row
        # iteration — error out with an actionable message instead.
        best = max(b for b in range(1, block_rows + 1) if n % b == 0)
        # best == n is always usable (the whole problem fits in ONE
        # panel — nb=1 — the degenerate-divisor concern is moot); only
        # error when a large n truly fractures into tiny panels.
        if best < n and best < max(256, block_rows // 16):
            raise ValueError(
                f"n={n} has no usable panel divisor <= {block_rows} "
                f"(best is {best}); pad n to a composite size or pass a "
                "block_rows that divides it"
            )
        block_rows = best
    nb = n // block_rows
    Y2, _ = _as2d(Y)
    t = Y2.shape[1]

    sizes = _chunk_sizes(d, s, params)
    maps = [kernel.create_rft(sz, _tag(params), context) for sz in sizes]

    programs = [
        streaming_krr_chunk_programs(
            maps, c, sizes[c], nb, block_rows, t, lam, block_fn,
            feature_dtype,
        )
        for c in range(len(maps))
    ]
    factors = []
    Ws = [jnp.zeros((sz, t), jnp.float32) for sz in sizes]
    # Panel-major residual (see streaming_krr_chunk_programs): sharded
    # callers pay one reshard here, zero per-sweep R collectives after.
    R = Y2.astype(jnp.float32).reshape(nb, block_rows, t)

    import contextlib

    # Sweep 0 is unconditional (factors must exist), matching
    # large_scale_kernel_ridge's loop structure where the first sweep
    # runs outside the iteration count — iter_lim=0 means "one pass".
    for it in range(max(params.iter_lim, 1)):
        phase = (
            timer.phase("sweep0" if it == 0 else "sweep")
            if timer is not None
            else contextlib.nullcontext()
        )
        with phase as ph:
            delsize = 0.0
            for c, (gram, zr, apply_delta) in enumerate(programs):
                if it == 0:
                    factors.append(cho_factor(gram(*block_args), lower=True))
                ZR = zr(R, Ws[c], *block_args)
                delta = cho_solve(factors[c], ZR)
                Ws[c] = Ws[c] + delta
                R = apply_delta(R, delta, *block_args)
                delsize += float(jnp.sum(delta * delta))
            if ph is not None:
                ph.result = R
        wnorm = float(jnp.sqrt(sum(jnp.sum(W * W) for W in Ws)))
        reldel = (delsize**0.5) / max(wnorm, 1e-30)
        params.log(2, f"iteration {it}, relupdate = {reldel:.2e}")
        if it > 0 and reldel < params.tolerance:
            break

    W = jnp.concatenate(Ws, axis=0)
    return FeatureMapModel(maps, W)


def streaming_krr_chunk_programs(
    maps, c, sz, nb, block_rows, t, lam, block_fn, feature_dtype
):
    """The three jitted per-chunk programs of the streaming-KRR sweep:
    ``(gram(*bargs), zr(R, Wc, *bargs), apply_delta(R, delta, *bargs))``.

    Module-level (not a closure of :func:`streaming_kernel_ridge`) so
    the communication-cost model (``experiments/comm_model.py``, VERDICT
    r3 item 5) can AOT-lower the SAME programs on a virtual mesh and
    read the collectives out of the compiled HLO.

    All contractions consume the (block_rows, sz) panel in place via
    dot_general with an f32 preferred_element_type: bf16 panels contract
    at MXU rate with exact-f32 accumulation and are never rounded back
    (the _psd_gram hazard) nor upcast into a materialized f32 copy.
    precision='highest' pins the f32/f64 feature case.
    """
    lam_ = jnp.float32(lam)

    def chunk_Zp(start, bargs, ops):
        """(block_rows, sz) feature panel of chunk c, built in-graph.
        Natural rowwise layout: every consumer contracts it with
        ``dot_general`` directly — materializing a transpose (or an
        astype-to-f32 copy) of the panel costs ~3 extra HBM passes per
        visit, measured ~2.3 s/sweep-pass at the 10M×4096 shape.  The
        map's counter-realized operands are hoisted to ``ops`` (once per
        program, outside the panel loop): XLA does not LICM the ~11 ms
        per-visit W realization out of the fori_loop by itself."""
        Xp = block_fn(start, block_rows, *bargs).astype(feature_dtype)
        return maps[c].apply_with_operands(ops, Xp, Dimension.ROWWISE)

    def _prec(dtype):
        return None if dtype == jnp.bfloat16 else "highest"

    @jax.jit
    def gram(*bargs):
        ops = maps[c].hoistable_operands(feature_dtype)

        def body(p, G):
            Zp = chunk_Zp(p * block_rows, bargs, ops)
            blk = jax.lax.dot_general(
                Zp, Zp, (((0,), (0,)), ((), ())),
                precision=_prec(Zp.dtype),
                preferred_element_type=jnp.float32,
            )
            return G + blk

        G = jax.lax.fori_loop(
            0, nb, body, jnp.zeros((sz, sz), jnp.float32)
        )
        return G + lam_ * jnp.eye(sz, dtype=jnp.float32)

    # The residual travels as (nb, block_rows, t): panels on the LEADING
    # (unsharded) axis, rows of each panel on the shardable middle axis.
    # A traced-index slice R3[p] then never touches the sharded
    # dimension, so GSPMD keeps it local — the (N, t) layout with a
    # traced-offset dynamic_slice cost a full all-gather of R per sweep
    # on the virtual mesh (compiled-HLO finding, round 4; the one-time
    # reshard into panel-major happens outside the sweep loop).

    @jax.jit
    def zr(R3, Wc, *bargs):
        ops = maps[c].hoistable_operands(feature_dtype)

        def body(p, acc):
            Zp = chunk_Zp(p * block_rows, bargs, ops)
            Rp = jax.lax.dynamic_index_in_dim(R3, p, 0, keepdims=False)
            return acc + jax.lax.dot_general(
                Zp, Rp, (((0,), (0,)), ((), ())),
                precision=_prec(Zp.dtype),
                preferred_element_type=jnp.float32,
            )

        acc0 = jnp.zeros((sz, t), jnp.float32)
        return jax.lax.fori_loop(0, nb, body, acc0) - lam_ * Wc

    @jax.jit
    def apply_delta(R3, delta, *bargs):
        ops = maps[c].hoistable_operands(feature_dtype)

        def body(p, R3):
            Zp = chunk_Zp(p * block_rows, bargs, ops)
            upd = jax.lax.dot_general(
                Zp, delta.astype(Zp.dtype), (((1,), (0,)), ((), ())),
                precision=_prec(Zp.dtype),
                preferred_element_type=jnp.float32,
            )
            Rp = jax.lax.dynamic_index_in_dim(R3, p, 0, keepdims=False)
            return jax.lax.dynamic_update_index_in_dim(
                R3, Rp - upd, p, 0
            )

        return jax.lax.fori_loop(0, nb, body, R3)

    return gram, zr, apply_delta
