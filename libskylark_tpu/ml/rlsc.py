"""Regularized least-squares classification (≙ ``ml/rlsc.hpp:45-311``).

Each RLSC solver is its KRR counterpart on dummy-coded ±1 labels
(``ml/coding.hpp``), with argmax decoding at predict time.  Returned
models carry ``.classes`` for decoding.
"""

from __future__ import annotations

from ..core.context import SketchContext
from .coding import dummy_coding
from .kernels import Kernel
from .krr import (
    KrrParams,
    approximate_kernel_ridge,
    faster_kernel_ridge,
    kernel_ridge,
    sketched_approximate_kernel_ridge,
)

__all__ = [
    "kernel_rlsc",
    "approximate_kernel_rlsc",
    "sketched_approximate_kernel_rlsc",
    "faster_kernel_rlsc",
]


def _classify(train_fn):
    def wrapper(kernel: Kernel, X, y, lam: float, *args, **kwargs):
        T, classes = dummy_coding(y)
        model = train_fn(kernel, X, T, lam, *args, **kwargs)
        model.classes = classes
        return model

    return wrapper


# ≙ KernelRLSC / ApproximateKernelRLSC / SketchedApproximateKernelRLSC /
# FasterKernelRLSC (rlsc.hpp:45-311).
kernel_rlsc = _classify(kernel_ridge)
approximate_kernel_rlsc = _classify(approximate_kernel_ridge)
sketched_approximate_kernel_rlsc = _classify(sketched_approximate_kernel_ridge)
faster_kernel_rlsc = _classify(faster_kernel_ridge)
