"""Pairwise distance matrices (≙ ``base/distance.hpp`` and
``python-skylark/skylark/ml/distances.py``).

The reference provides three distance families with BLAS-style
``C = beta*C + alpha*dist(A, B)`` accumulate semantics:

- squared euclidean (``EuclideanDistanceMatrix``, base/distance.hpp:11-155)
- L1 (``L1DistanceMatrix``, base/distance.hpp:160-384)
- exp-semigroup, sum of elementwise sqrt
  (``ExpsemigroupDistanceMatrix``, base/distance.hpp:386-533)

TPU notes: squared euclidean is one big MXU matmul plus rank-1 norm
corrections; L1 and semigroup have no matmul form, so they run as
row-blocked broadcasts (the same O(n·m·d) loop the reference does, with
peak memory bounded to one block slab).  All functions accept dense or
BCOO inputs (BCOO is densified — the outputs are dense anyway).

Convention: rows are points.  ``D[i, j] = dist(X[i], Y[j])``, i.e. an
(n, m) matrix for X (n, d), Y (m, d) — the orientation the kernel layer
and ``KernelModel.predict`` use.  (python-skylark's ``euclidean(X, Y)``
returns the transpose of this; use ``.T`` for that layout.)
"""

from __future__ import annotations

import jax.numpy as jnp

from .kernels import _dense, _l1dist, _semigroup_dist, _sqdist

__all__ = [
    "euclidean_distance_matrix",
    "l1_distance_matrix",
    "expsemigroup_distance_matrix",
]


def _accumulate(D, alpha, beta, C):
    if beta != 0.0 and C is None:
        raise ValueError("beta != 0 requires an existing C to accumulate into")
    if C is None:
        return alpha * D
    return beta * jnp.asarray(C) + alpha * D


def euclidean_distance_matrix(X, Y=None, alpha=1.0, beta=0.0, C=None):
    """Squared euclidean distances, ``C = beta*C + alpha*D``
    (≙ ``EuclideanDistanceMatrix``, base/distance.hpp:11-79)."""
    X = _dense(X)
    Y = X if Y is None else _dense(Y)
    return _accumulate(_sqdist(X, Y), alpha, beta, C)


def l1_distance_matrix(X, Y=None, alpha=1.0, beta=0.0, C=None):
    """L1 distances (≙ ``L1DistanceMatrix``, base/distance.hpp:160-384)."""
    X = _dense(X)
    Y = X if Y is None else _dense(Y)
    return _accumulate(_l1dist(X, Y), alpha, beta, C)


def expsemigroup_distance_matrix(X, Y=None, alpha=1.0, beta=0.0, C=None):
    """Semigroup "distance" sum_k sqrt(x_k + y_k), used by the
    exp-semigroup kernel on histogram features
    (≙ ``ExpsemigroupDistanceMatrix``, base/distance.hpp:386-533).
    Inputs must be nonnegative."""
    X = _dense(X)
    Y = X if Y is None else _dense(Y)
    return _accumulate(_semigroup_dist(X, Y), alpha, beta, C)
