"""Kernel library (≙ ``ml/kernels.hpp:12-1289``).

``Kernel`` mirrors ``kernel_t``: ``gram(X, Y)`` computes the kernel matrix
and ``create_rft(s, tag, context)`` builds the matching random feature map
(tags ≙ ``ml/feature_transform_tags.hpp``: "regular", "fast", "quasi",
"sparse" where supported).

Convention: X is (n, d) with examples as **rows** (the reference's
dirX/dirY orientation tags collapse to this fixed layout; its sketches'
columnwise/rowwise tags are applied internally).  Gram matrices are
computed from sharded MXU-friendly primitives: squared-distance via the
‖x‖² + ‖y‖² − 2·X·Yᵀ expansion (≙ ``base/distance.hpp``), L1/semigroup
distances via row-blocked broadcasts (peak intermediate capped at
``_PAIRWISE_LIMIT`` elements; the reference loops the full O(n·m·d)).
"""

from __future__ import annotations

import abc
import json
import math
from typing import Any

import jax.numpy as jnp

from ..core.context import SketchContext

__all__ = [
    "Kernel",
    "LinearKernel",
    "GaussianKernel",
    "PolynomialKernel",
    "LaplacianKernel",
    "ExpSemigroupKernel",
    "MaternKernel",
    "kernel_by_name",
]


def _sqdist(X, Y):
    """Pairwise squared euclidean distances, (n, m) — one big matmul.

    The cross-term matmul runs at ``precision='highest'``: on TPU the
    default f32 matmul passes through bf16, and the ``xx + yy − 2·xy``
    differencing amplifies that to O(1) absolute errors on clustered data
    (nonzero self-distances → non-PSD Grams → Cholesky failures).  The
    reference computes Grams in f64 (base/distance.hpp); full-f32 MXU is
    the TPU parity point."""
    xx = jnp.sum(X * X, axis=1)[:, None]
    yy = jnp.sum(Y * Y, axis=1)[None, :]
    return jnp.maximum(xx + yy - 2.0 * jnp.dot(X, Y.T, precision="highest"), 0.0)


# Broadcast intermediates above this many elements are computed in row
# blocks (the reference's base/distance.hpp does the full O(n·m·d) loop;
# blocking keeps peak memory to one (B, m, d) slab).
_PAIRWISE_LIMIT = 1 << 27


def _blocked_rows(pair_fn, X, Y):
    n, d = X.shape
    m = Y.shape[0]
    if n * m * d <= _PAIRWISE_LIMIT:
        return pair_fn(X, Y)
    block = max(1, _PAIRWISE_LIMIT // max(m * d, 1))
    outs = [
        pair_fn(X[i : i + block], Y) for i in range(0, n, block)
    ]
    return jnp.concatenate(outs, axis=0)


def _l1dist(X, Y):
    """Pairwise L1 distances (row-blocked broadcast)."""
    return _blocked_rows(
        lambda a, b: jnp.sum(jnp.abs(a[:, None, :] - b[None, :, :]), axis=-1),
        X,
        Y,
    )


def _semigroup_dist(X, Y):
    """Pairwise semigroup "distance" sum_k sqrt(x_k + y_k) on nonnegative
    inputs (row-blocked broadcast)."""
    return _blocked_rows(
        lambda a, b: jnp.sum(
            jnp.sqrt(jnp.maximum(a[:, None, :] + b[None, :, :], 0.0)), axis=-1
        ),
        X,
        Y,
    )


def _dense(X):
    """Densify BCOO for Gram/distance paths (outputs are dense anyway)."""
    return X.todense() if hasattr(X, "todense") else jnp.asarray(X)


class Kernel(abc.ABC):
    """≙ ``kernel_t`` (``ml/kernels.hpp:12-70``)."""

    kernel_type: str = "abstract"

    def __init__(self, n: int):
        self.n = int(n)  # input dimension (≙ _N)

    @abc.abstractmethod
    def gram(self, X, Y=None):
        """K[i, j] = k(X[i], Y[j]); Y=None means Y=X (symmetric_gram)."""

    @abc.abstractmethod
    def create_rft(self, s: int, tag: str, context: SketchContext):
        """Feature map with s features approximating this kernel."""

    # -- serialization (≙ kernel_t::to_ptree) -------------------------------

    def _param_dict(self) -> dict[str, Any]:
        return {}

    def to_dict(self):
        d = {"kernel_type": self.kernel_type, "N": self.n}
        d.update(self._param_dict())
        return d

    def to_json(self):
        return json.dumps(self.to_dict())

    def __repr__(self):
        params = ", ".join(f"{k}={v}" for k, v in self._param_dict().items())
        return f"{type(self).__name__}(N={self.n}{', ' + params if params else ''})"


class LinearKernel(Kernel):
    """k(x, y) = xᵀy (≙ ``linear_t``, ml/kernels.hpp:156)."""

    kernel_type = "linear"

    def gram(self, X, Y=None):
        Y = X if Y is None else Y
        return jnp.dot(X, Y.T, precision="highest")

    def create_rft(self, s, tag, context):
        from ..sketch import CWT, FJLT, JLT

        # ≙ linear_t::create_rft: JLT regular / FJLT fast / CWT sparse.
        if tag == "regular":
            return JLT(self.n, s, context)
        if tag == "fast":
            return FJLT(self.n, s, context)
        if tag == "sparse":
            return CWT(self.n, s, context)
        raise ValueError(f"linear kernel has no {tag!r} feature transform")


class GaussianKernel(Kernel):
    """k(x, y) = exp(−‖x−y‖²/(2σ²)) (≙ ``gaussian_t``, ml/kernels.hpp:320)."""

    kernel_type = "gaussian"

    def __init__(self, n: int, sigma: float):
        super().__init__(n)
        self.sigma = float(sigma)

    def gram(self, X, Y=None):
        Y = X if Y is None else Y
        return jnp.exp(-_sqdist(X, Y) / (2.0 * self.sigma**2))

    def create_rft(self, s, tag, context):
        from ..sketch import FastGaussianRFT, GaussianQRFT, GaussianRFT

        if tag == "regular":
            return GaussianRFT(self.n, s, context, sigma=self.sigma)
        if tag == "fast":
            return FastGaussianRFT(self.n, s, context, sigma=self.sigma)
        if tag == "quasi":
            return GaussianQRFT(self.n, s, context, sigma=self.sigma)
        raise ValueError(f"gaussian kernel has no {tag!r} feature transform")

    def _param_dict(self):
        return {"sigma": self.sigma}


class PolynomialKernel(Kernel):
    """k(x, y) = (γ·xᵀy + c)^q (≙ ``polynomial_t``, ml/kernels.hpp:495)."""

    kernel_type = "polynomial"

    def __init__(self, n: int, q: int = 2, c: float = 1.0, gamma: float = 1.0):
        super().__init__(n)
        self.q = int(q)
        self.c = float(c)
        self.gamma = float(gamma)

    def gram(self, X, Y=None):
        Y = X if Y is None else Y
        return (
            self.gamma * jnp.dot(X, Y.T, precision="highest") + self.c
        ) ** self.q

    def create_rft(self, s, tag, context):
        from ..sketch import PPT

        if tag in ("regular", "fast"):
            return PPT(self.n, s, context, q=self.q, c=self.c, gamma=self.gamma)
        raise ValueError(f"polynomial kernel has no {tag!r} feature transform")

    def _param_dict(self):
        return {"q": self.q, "c": self.c, "gamma": self.gamma}


class LaplacianKernel(Kernel):
    """k(x, y) = exp(−‖x−y‖₁/σ) (≙ ``laplacian_t``, ml/kernels.hpp:671)."""

    kernel_type = "laplacian"

    def __init__(self, n: int, sigma: float):
        super().__init__(n)
        self.sigma = float(sigma)

    def gram(self, X, Y=None):
        Y = X if Y is None else Y
        return jnp.exp(-_l1dist(X, Y) / self.sigma)

    def create_rft(self, s, tag, context):
        from ..sketch import LaplacianQRFT, LaplacianRFT

        if tag == "regular":
            return LaplacianRFT(self.n, s, context, sigma=self.sigma)
        if tag == "quasi":
            return LaplacianQRFT(self.n, s, context, sigma=self.sigma)
        raise ValueError(f"laplacian kernel has no {tag!r} feature transform")

    def _param_dict(self):
        return {"sigma": self.sigma}


class ExpSemigroupKernel(Kernel):
    """k(x, y) = exp(−β·Σ_i √(x_i + y_i)) on histograms
    (≙ ``expsemigroup_t``, ml/kernels.hpp:844)."""

    kernel_type = "expsemigroup"

    def __init__(self, n: int, beta: float):
        super().__init__(n)
        self.beta = float(beta)

    def gram(self, X, Y=None):
        Y = X if Y is None else Y
        return jnp.exp(-self.beta * _semigroup_dist(X, Y))

    def create_rft(self, s, tag, context):
        from ..sketch import ExpSemigroupQRLT, ExpSemigroupRLT

        if tag == "regular":
            return ExpSemigroupRLT(self.n, s, context, beta=self.beta)
        if tag == "quasi":
            return ExpSemigroupQRLT(self.n, s, context, beta=self.beta)
        raise ValueError(f"expsemigroup kernel has no {tag!r} feature transform")

    def _param_dict(self):
        return {"beta": self.beta}


class MaternKernel(Kernel):
    """Matérn(ν, ℓ) kernel for half-integer ν (closed forms; ν = p + ½)
    (≙ ``matern_t``, ml/kernels.hpp:1010)."""

    kernel_type = "matern"

    def __init__(self, n: int, nu: float = 0.5, l: float = 1.0):
        super().__init__(n)
        two_nu = 2.0 * nu
        if abs(two_nu - round(two_nu)) > 1e-9 or round(two_nu) % 2 != 1:
            raise ValueError(
                f"MaternKernel gram supports half-integer nu (0.5, 1.5, ...), got {nu}"
            )
        self.nu = float(nu)
        self.l = float(l)

    def gram(self, X, Y=None):
        Y = X if Y is None else Y
        r = jnp.sqrt(_sqdist(X, Y))
        p = int(round(self.nu - 0.5))
        arg = math.sqrt(2.0 * self.nu) * r / self.l
        # k(r) = exp(−arg)·(p!/(2p)!)·Σ_{i=0}^p ((p+i)!/(i!(p−i)!))(2·arg)^(p−i)
        total = jnp.zeros_like(arg)
        for i in range(p + 1):
            coef = (
                math.factorial(p + i)
                / (math.factorial(i) * math.factorial(p - i))
            )
            total = total + coef * (2.0 * arg) ** (p - i)
        scale = math.factorial(p) / math.factorial(2 * p)
        return jnp.exp(-arg) * scale * total

    def create_rft(self, s, tag, context):
        from ..sketch import FastMaternRFT, MaternRFT

        if tag == "regular":
            return MaternRFT(self.n, s, context, nu=self.nu, l=self.l)
        if tag == "fast":
            return FastMaternRFT(self.n, s, context, nu=self.nu, l=self.l)
        raise ValueError(f"matern kernel has no {tag!r} feature transform")

    def _param_dict(self):
        return {"nu": self.nu, "l": self.l}


_KERNELS = {
    "linear": LinearKernel,
    "gaussian": GaussianKernel,
    "polynomial": PolynomialKernel,
    "laplacian": LaplacianKernel,
    "expsemigroup": ExpSemigroupKernel,
    "matern": MaternKernel,
}


def kernel_by_name(name: str, n: int, **params) -> Kernel:
    """String-typed kernel factory (≙ the C API's kernel creation)."""
    if name not in _KERNELS:
        raise ValueError(f"unknown kernel {name!r}; known: {sorted(_KERNELS)}")
    return _KERNELS[name](n, **params)


def from_dict(d: dict) -> Kernel:
    d = dict(d)
    name = d.pop("kernel_type")
    n = d.pop("N")
    return kernel_by_name(name, n, **d)
