"""Machine-learning layer (≙ reference ``ml/``): kernels, KRR/RLSC solver
families, the BlockADMM kernel-machine trainer, label coding, and model
persistence."""

from .admm import ADMMParams, BlockADMMSolver
from .coding import decode_labels, dummy_coding
from .distributed import (
    DistributedBlockADMMTrainer,
    prepare_rank_admm,
    rank_chunked_solver,
    stream_feature_blocks,
    validate_train_partition,
)
from .distances import (
    euclidean_distance_matrix,
    expsemigroup_distance_matrix,
    l1_distance_matrix,
)
from .metrics import classification_accuracy, mean_squared_error
from .nonlinear import RLS, NystromRLS, SketchPCR, SketchRLS
from .kernels import (
    ExpSemigroupKernel,
    GaussianKernel,
    Kernel,
    LaplacianKernel,
    LinearKernel,
    MaternKernel,
    PolynomialKernel,
    kernel_by_name,
)
from .krr import (
    KrrParams,
    approximate_kernel_ridge,
    faster_kernel_ridge,
    kernel_ridge,
    large_scale_kernel_ridge,
    streaming_approximate_kernel_ridge,
    streaming_kernel_ridge,
    sketched_approximate_kernel_ridge,
)
from .model import FeatureMapModel, KernelModel, load_model
from .rlsc import (
    approximate_kernel_rlsc,
    faster_kernel_rlsc,
    kernel_rlsc,
    sketched_approximate_kernel_rlsc,
)

__all__ = [
    "Kernel",
    "LinearKernel",
    "GaussianKernel",
    "PolynomialKernel",
    "LaplacianKernel",
    "ExpSemigroupKernel",
    "MaternKernel",
    "kernel_by_name",
    "KrrParams",
    "kernel_ridge",
    "approximate_kernel_ridge",
    "sketched_approximate_kernel_ridge",
    "faster_kernel_ridge",
    "large_scale_kernel_ridge",
    "streaming_kernel_ridge",
    "streaming_approximate_kernel_ridge",
    "kernel_rlsc",
    "approximate_kernel_rlsc",
    "sketched_approximate_kernel_rlsc",
    "faster_kernel_rlsc",
    "dummy_coding",
    "decode_labels",
    "euclidean_distance_matrix",
    "l1_distance_matrix",
    "expsemigroup_distance_matrix",
    "classification_accuracy",
    "mean_squared_error",
    "RLS",
    "SketchRLS",
    "NystromRLS",
    "SketchPCR",
    "ADMMParams",
    "BlockADMMSolver",
    "DistributedBlockADMMTrainer",
    "prepare_rank_admm",
    "rank_chunked_solver",
    "stream_feature_blocks",
    "validate_train_partition",
    "FeatureMapModel",
    "KernelModel",
    "load_model",
]
