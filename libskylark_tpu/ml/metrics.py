"""Prediction metrics (≙ ``skylark.metrics`` as used by
``python-skylark/skylark/ml/nonlinear.py`` doctests; the module itself is
absent from the reference tree — these are the semantics its call sites
assume)."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["classification_accuracy", "mean_squared_error"]


def classification_accuracy(predictions, labels):
    """Percent of exact label matches (0..100)."""
    predictions = jnp.ravel(jnp.asarray(predictions))
    labels = jnp.ravel(jnp.asarray(labels))
    if predictions.shape != labels.shape:
        raise ValueError(
            f"shape mismatch: {predictions.shape} vs {labels.shape}"
        )
    return 100.0 * jnp.mean(predictions == labels)


def mean_squared_error(predictions, targets):
    predictions = jnp.asarray(predictions)
    targets = jnp.asarray(targets)
    return jnp.mean((predictions - targets) ** 2)
