"""Elastic multi-host BlockADMM kernel-machine training.

≙ the reference's MPI training topology (``ml/BlockADMM.hpp:374-590``
maps data partitions to ranks and broadcasts ``Wbar`` every iteration)
rebuilt on this library's substrates: each rank **streams** its row
partition of the training set through
:func:`~libskylark_tpu.streaming.elastic.elastic_run_stream` (manifest /
handshake / epoch-fence contract, code 109/110/111 ladder), materializes
its random-feature blocks batch-by-batch through
:func:`~libskylark_tpu.plans.apply_rowwise_bucketed` (plan-compiled
executables, bucket-ladder bounded), runs the local prox updates of
:class:`~libskylark_tpu.ml.admm.BlockADMMSolver`'s step under the
resilient ``init_state/step_chunk/extract_result`` contract, and merges
consensus ONCE per outer iteration with a single
:func:`~libskylark_tpu.parallel.collectives.cross_host_psum`.

Bitwise contracts (pinned by ``tests/test_distributed_train.py``):

- **world=1 parity** — a single-process distributed run reproduces
  ``BlockADMMSolver.train`` bit-for-bit: the rowwise bucketed feature
  materialization equals ``_prepare``'s columnwise vmapped apply after
  the partition reshape, and with no collective to cross the iteration
  runs as ONE fused jit tracing the exact jaxpr of the in-process step
  (the world>1 split compiles the two halves as separate XLA programs
  whose constant-folding rewrites can differ at the ULP level, so the
  split is reserved for real collectives — see
  :func:`rank_chunked_solver`).
- **kill/resume** — commits happen only after a chunk's final consensus
  psum completed on EVERY rank, so all ranks durably hold the same
  chunk boundary; a SIGKILLed-and-resumed run replays from that
  boundary and reproduces the uninterrupted model bit-for-bit (same
  blocks, same order, same IEEE ops).
- **consensus decomposition** — global consensus leaves (``Wbar``,
  ``W``, ``mu``, ``obj``) are recomputed identically on every rank from
  the psum-merged ``Σ_partitions Wi``; per-partition leaves stay
  rank-local and never cross the wire.

The policy layer decides the precision rung (``bf16 → fp8`` operand
rounding with f32 accumulation, kind ``"train"``); attempt 0 is
guard-certified and a bad certificate on ANY rank escalates EVERY rank
back to full precision (world verdict via a second psum), recorded in
``info["recovery"]`` and observed back into the profile store.
``resume_policy="repartition"`` rides PR 7's
:func:`~libskylark_tpu.streaming.repartition.resolve_resume`: feature
buffers are row-slot (positional, not sum-decomposable), so a world
change re-streams the NEW share at the bumped epoch — within an epoch
the run stays resumable and bit-for-bit.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.scipy.linalg import solve_triangular

from .. import guard, policy, telemetry
from ..parallel.collectives import cross_host_psum
from ..plans import apply_rowwise_bucketed, bucket_for, donating_jit, pad_rows
from ..resilient.chunked import ChunkedSolver
from ..resilient.runner import ResilientParams, ResilientRunner
from ..solvers.prox import get_loss, get_regularizer
from ..streaming.drivers import _result_dtype
from ..streaming.elastic import (
    ElasticParams,
    RowPartition,
    _make_watchdog,
    _require_real_world,
    _resolve_world,
    elastic_run_stream,
    host_dir,
)
from ..streaming.repartition import resolve_resume
from ..utils.exceptions import InvalidParameters
from ..utils.timer import PhaseTimer
from .admm import ADMMParams
from .coding import dummy_coding
from .model import FeatureMapModel

__all__ = [
    "KIND",
    "DistributedBlockADMMTrainer",
    "prepare_rank_admm",
    "rank_chunked_solver",
    "stream_feature_blocks",
    "validate_train_partition",
]

KIND = "distributed_block_admm"


def validate_train_partition(partition: RowPartition, data_partitions: int) -> int:
    """Check that every rank's row range covers WHOLE ADMM data
    partitions; returns the rows-per-partition ``ni``.

    The consensus math needs each of the ``P`` data partitions to live
    entirely on one rank (per-partition leaves ``O/Obar/nu/del_o/mu_ij/
    ZtObar`` are rank-local); a partition split across ranks has no
    owner.  Pick ``batch_rows`` and world sizes whose
    :meth:`RowPartition.row_range` boundaries land on multiples of
    ``nrows / data_partitions``.
    """
    P = int(data_partitions)
    n = int(partition.nrows)
    if P < 1:
        raise InvalidParameters(f"data_partitions must be >= 1, got {P}")
    if n % P:
        raise InvalidParameters(
            f"n={n} not divisible by data_partitions={P}"
        )
    ni = n // P
    for r in range(partition.world_size):
        r0, r1 = partition.row_range(r)
        if r1 <= r0:
            raise InvalidParameters(
                f"rank {r} owns no rows ([{r0}, {r1})); every rank needs "
                "at least one data partition"
            )
        if r0 % ni or r1 % ni:
            raise InvalidParameters(
                f"rank {r} rows [{r0}, {r1}) don't align with the "
                f"{P}-partition boundaries (every {ni} rows); choose "
                "batch_rows so partition boundaries land on batch "
                "boundaries"
            )
    return ni


def stream_feature_blocks(
    source,
    maps: Sequence,
    partition: RowPartition,
    params: ElasticParams | None = None,
    *,
    dtype=None,
    targets: int = 1,
    scale_maps: bool = False,
    kind: str = KIND,
    fault_plan=None,
    report=None,
    epoch: int = 0,
):
    """This rank's feature-block materialization pass.

    Streams the rank's row window via :func:`elastic_run_stream` and
    applies every feature map to each batch through
    :func:`apply_rowwise_bucketed` (``pad_out=True``: fixed bucket
    shapes, padded rows zeroed inside the executable), writing the rows
    into row-slot buffers at the batch's local offset.  Padded rows
    temporarily clobber slots the NEXT batch overwrites, so replays and
    resumes refold bit-identically; the buffers over-allocate by one
    bucket so the final batch's padding never clips.

    Returns ``(Z_rows, Y_rows, local_batches)`` — ``Z_rows[j]`` is the
    ``(ni_local, s_j)`` rowwise feature block of map ``j`` (bitwise
    equal to the in-process ``_prepare`` apply after the partition
    reshape), ``Y_rows`` the ``(ni_local, targets)`` target rows.
    """
    params = params or ElasticParams()
    rank, world = _resolve_world(params)
    partition.validate_world(rank, world)
    r0, r1 = partition.row_range(rank)
    ni = r1 - r0
    dt = _result_dtype(dtype)
    t = int(targets)
    d = int(maps[0].n) if maps else None
    # One bucket of margin absorbs the largest padded batch the ladder
    # can produce for this stream's batch size.
    margin = bucket_for(max(1, int(partition.batch_rows)))
    nbuf = ni + margin

    def init_at(row0: int):
        return {
            "rows": np.asarray(row0, np.int64),
            "y": jnp.zeros((nbuf, t), dt),
            "z": [jnp.zeros((nbuf, int(S.s)), dt) for S in maps],
        }

    write = donating_jit(
        lambda buf, blk, off: lax.dynamic_update_slice(
            buf, blk, (off, jnp.asarray(0, jnp.int32))
        ),
        donate_argnums=(0,),
    )

    def step(acc, batch, index):
        X_b, y_b = batch
        if hasattr(X_b, "todense"):
            X_b = X_b.todense()
        k = int(X_b.shape[0])
        off = jnp.asarray(int(acc["rows"]) - r0, jnp.int32)
        zs = []
        for S, buf in zip(maps, acc["z"]):
            Zp, _ = apply_rowwise_bucketed(S, X_b, pad_out=True, true_rows=k)
            if scale_maps:
                Zp = Zp * jnp.asarray(np.sqrt(S.s / d), Zp.dtype)
            zs.append(write(buf, jnp.asarray(Zp, dt), off))
        yb = jnp.asarray(y_b, dt).reshape(k, t)
        yb = jnp.asarray(pad_rows(yb, bucket_for(k)))
        return {
            "rows": np.asarray(int(acc["rows"]) + k, np.int64),
            "y": write(acc["y"], yb, off),
            "z": zs,
        }

    acc, nbatches = elastic_run_stream(
        source, step, init_at(r0), partition, params,
        kind=kind, fault_plan=fault_plan, report=report, epoch=epoch,
    )
    rows = int(acc["rows"])
    if rows != r1:
        raise ValueError(
            f"rank {rank} folded rows [{r0}, {rows}) but its partition "
            f"share is [{r0}, {r1}); the source and partition disagree"
        )
    Z_rows = [buf[:ni] for buf in acc["z"]]
    Y_rows = acc["y"][:ni]
    return Z_rows, Y_rows, int(nbatches)


@dataclass
class _RankPrepared:
    """Everything a rank's training loop needs that is NOT checkpointable
    state — deterministically rebuilt from the streamed blocks on resume
    (only the ``dict(it, inner, objs)`` state rides the checkpoint)."""

    Zs: list
    Ls: list
    Yp: Any
    state0: tuple
    local_step: Callable
    merge_step: Callable
    timer: PhaseTimer
    d: int
    classes: Any
    dtype: Any
    P_local: int
    P_total: int
    D: int
    k: int


def prepare_rank_admm(
    loss,
    regularizer,
    maps: Sequence,
    admm: ADMMParams,
    partition: RowPartition,
    rank: int,
    Z_rows: Sequence,
    Y_rows,
    *,
    classes=None,
    regression: bool = False,
    compute_dtype=None,
) -> _RankPrepared:
    """Build this rank's partitioned blocks, Cholesky factors, targets,
    initial state, and the split step functions.

    The ADMM step is split at its single cross-rank reduction:
    ``local_step`` runs everything through the block loop and returns
    ``(core..., Σ_local Wi, Σ_local loss)``; the caller psums the last
    two; ``merge_step`` finishes ``Wbar/mu/obj`` from the merged sums.
    At world=1 the concatenation of the two computes the exact op
    sequence of :class:`BlockADMMSolver`'s fused step (bit-parity anchor
    of the tier-1 suite).

    ``compute_dtype`` (the policy precision rung) rounds the feature
    blocks through the low dtype before factoring — operand compression
    with full-precision accumulation; ``None`` keeps the historical
    full-precision path bitwise.
    """
    loss = get_loss(loss) if isinstance(loss, str) else loss
    reg = get_regularizer(regularizer) if isinstance(regularizer, str) else regularizer
    P_total = int(admm.data_partitions)
    ni_p = validate_train_partition(partition, P_total)
    r0, r1 = partition.row_range(int(rank))
    P_local = (r1 - r0) // ni_p
    dtype = Z_rows[0].dtype
    d = int(maps[0].n)

    timer = PhaseTimer()
    with timer.phase("transform") as ph:
        # (ni_local, sj) row blocks → the partitioned columnwise layout
        # (P_local, sj, ni) of the in-process trainer (bitwise: rowwise
        # apply is the transpose of the columnwise apply per row).
        Zs = [
            Z.reshape(P_local, ni_p, Z.shape[1]).transpose(0, 2, 1)
            for Z in Z_rows
        ]
        if compute_dtype is not None:
            cd = jnp.dtype(compute_dtype)
            Zs = [Z.astype(cd).astype(dtype) for Z in Zs]
        ph.result = Zs

    label_based = getattr(loss, "label_based", False)
    if regression:
        T = jnp.asarray(Y_rows)
        k = T.shape[1]
        Yp = T.reshape(P_local, ni_p, k).transpose(0, 2, 1)
    else:
        Y = np.asarray(Y_rows)[:, 0]
        if classes is None and partition.world_size > 1:
            raise InvalidParameters(
                "distributed classification needs the GLOBAL class set "
                "passed explicitly (each rank only sees its own labels)"
            )
        T, classes = dummy_coding(Y, classes, dtype=dtype)
        k = T.shape[1]
        if label_based:
            cls = jnp.asarray(
                np.searchsorted(np.asarray(classes), np.asarray(Y))
            ).astype(dtype)
            Yp = cls.reshape(P_local, ni_p)
        else:
            Yp = T.reshape(P_local, ni_p, k).transpose(0, 2, 1)

    with timer.phase("factor") as ph:
        Ls = [
            jnp.linalg.cholesky(
                jnp.einsum("pst,put->psu", Z, Z, precision="highest")
                + jnp.eye(Z.shape[1], dtype=dtype)
            )
            for Z in Zs
        ]
        ph.result = Ls

    J = len(maps)
    sizes = [int(S.s) for S in maps]
    starts = np.cumsum([0] + sizes)
    D = int(starts[-1])
    rho = jnp.asarray(admm.rho, dtype)
    lam = jnp.asarray(admm.lam, dtype)

    def chol_solve(L, B):
        Ysol = jax.vmap(lambda l, b: solve_triangular(l, b, lower=True))(L, B)
        return jax.vmap(
            lambda l, b: solve_triangular(l.T, b, lower=False)
        )(L, Ysol)

    # Zs/Ls/Yp enter as ARGUMENTS, not closure captures (jit would embed
    # closed-over device arrays as program constants) — same discipline
    # as the in-process trainer.
    def local_step(state, Zs, Ls, Yp):
        Wbar, W, mu, O, Obar, nu, del_o, mu_ij, ZtObar, _ = state
        mu_ij = mu_ij - Wbar[None]
        Obar = Obar - nu
        O = jax.vmap(lambda ob, y: loss.prox(ob, 1.0 / rho, y))(Obar, Yp)
        W = reg.prox(Wbar - mu, lam / rho)

        sum_o = jnp.zeros_like(O)
        wbar_out = jnp.zeros_like(O)
        Wi = jnp.zeros((P_local, D, k), dtype)
        mu_ij_new = mu_ij
        ZtObar_new = ZtObar
        dsum = del_o / (J + 1.0) + nu
        for j in range(J):
            lo, hi = int(starts[j]), int(starts[j + 1])
            Z = Zs[j]
            wbar_out = wbar_out + jnp.einsum("psn,sk->pkn", Z, Wbar[lo:hi])
            rhs = (
                Wbar[None, lo:hi]
                - mu_ij[:, lo:hi]
                + ZtObar[:, lo:hi]
                + jnp.einsum("psn,pkn->psk", Z, dsum)
            )
            Wij = chol_solve(Ls[j], rhs)
            o = jnp.einsum("psk,psn->pkn", Wij, Z)
            Wi = Wi.at[:, lo:hi].set(Wij)
            mu_ij_new = mu_ij_new.at[:, lo:hi].add(Wij)
            ZtObar_new = ZtObar_new.at[:, lo:hi].set(
                jnp.einsum("psn,pkn->psk", Z, o)
            )
            sum_o = sum_o + o

        del_o = O - sum_o
        Obar = O - del_o / (J + 1.0)
        nu = nu + O - Obar
        # The ONE cross-rank quantity: this rank's Σ_partitions Wi (and
        # its local loss partial).  At world=1 the psum is a no-op and
        # this is exactly the fused step's consensus sum.
        wi_sum = jnp.sum(Wi, axis=0)
        obj_local = jax.vmap(loss.evaluate)(wbar_out, Yp).sum()
        return (
            (W, mu, O, Obar, nu, del_o, mu_ij_new, ZtObar_new),
            wi_sum,
            obj_local,
        )

    def merge_step(core, wi_global, obj_global):
        W, mu, O, Obar, nu, del_o, mu_ij, ZtObar = core
        Wbar = (wi_global + W) / (P_total + 1.0)
        mu = mu + W - Wbar
        obj = obj_global + lam * reg.evaluate(Wbar)
        return (Wbar, W, mu, O, Obar, nu, del_o, mu_ij, ZtObar, obj)

    state0 = (
        jnp.zeros((D, k), dtype),            # Wbar   (global)
        jnp.zeros((D, k), dtype),            # W      (global)
        jnp.zeros((D, k), dtype),            # mu     (global)
        jnp.zeros((P_local, k, ni_p), dtype),  # O
        jnp.zeros((P_local, k, ni_p), dtype),  # Obar
        jnp.zeros((P_local, k, ni_p), dtype),  # nu
        jnp.zeros((P_local, k, ni_p), dtype),  # del_o
        jnp.zeros((P_local, D, k), dtype),   # mu_ij
        jnp.zeros((P_local, D, k), dtype),   # ZtObar_ij
        jnp.zeros((), dtype),                # obj
    )
    return _RankPrepared(
        Zs=Zs, Ls=Ls, Yp=Yp, state0=state0, local_step=local_step,
        merge_step=merge_step, timer=timer, d=d, classes=classes,
        dtype=dtype, P_local=P_local, P_total=P_total, D=D, k=k,
    )


def rank_chunked_solver(
    prep: _RankPrepared,
    maps: Sequence,
    admm: ADMMParams,
    *,
    merge: Callable | None = None,
) -> ChunkedSolver:
    """This rank's training loop as a ``ChunkedSolver``.

    State pytree ``dict(it, inner, objs)`` — the same shape as
    ``BlockADMMSolver.chunked``'s, with per-partition leaves sized to
    this rank's share.

    ``merge=None`` (world=1 / no collective) runs each outer iteration
    as ONE jitted program — the fused ``local_step ∘ merge_step``
    composition traces the exact jaxpr of ``BlockADMMSolver``'s step,
    so the world=1 trainer is bitwise-identical to the in-process
    ``train()``.  A callable ``merge`` (the distributed trainer passes
    the watchdogged ``cross_host_psum``) runs the split schedule
    ``jit(local_step) → merge → jit(merge_step)``: XLA compiles the two
    halves as separate programs, whose value-changing rewrites (e.g.
    divide-by-constant → multiply-by-reciprocal) may differ from the
    fused program's at the ULP level — so cross-WORLD-SIZE bit-identity
    is not promised, while within a world size every rank computes the
    same bits and kill/resume reproduces the uninterrupted run
    bit-for-bit (same programs, same blocks, same order).  Checkpoint
    commits happen only AFTER a chunk's final merge completed
    collectively, so every rank durably holds the same chunk boundary
    on any kill — the lockstep resume is exact.
    """
    maxiter = int(admm.maxiter)
    jit_local = jax.jit(prep.local_step)
    jit_merge = jax.jit(prep.merge_step)
    if merge is None:
        @jax.jit
        def jit_fused(st, Zs, Ls, Yp):
            core, wi, obj = prep.local_step(st, Zs, Ls, Yp)
            return prep.merge_step(core, wi, obj)

    def init_state():
        return dict(
            it=jnp.zeros((), jnp.int32),
            inner=prep.state0,
            objs=jnp.zeros((maxiter,), prep.dtype),
        )

    def step_chunk(st, num_iters: int):
        it = int(st["it"])
        stop = min(it + int(num_iters), maxiter)
        # A restored checkpoint hands back host numpy leaves; the jits
        # accept them, but the objs trace needs jnp's .at updates.
        inner, objs = st["inner"], jnp.asarray(st["objs"])
        done = 0
        while it < stop:
            if merge is None:
                inner = jit_fused(inner, prep.Zs, prep.Ls, prep.Yp)
            else:
                core, wi, obj = jit_local(inner, prep.Zs, prep.Ls, prep.Yp)
                g = merge({"wi": wi, "obj": obj})
                inner = jit_merge(
                    core, jnp.asarray(g["wi"]), jnp.asarray(g["obj"])
                )
            objs = objs.at[it].set(inner[-1])
            it += 1
            done += 1
        if done and telemetry.enabled():
            telemetry.inc("train.iterations", done)
            telemetry.inc("train.consensus", done)
        return dict(it=jnp.asarray(it, jnp.int32), inner=inner, objs=objs)

    def extract_result(st):
        it = int(st["it"])
        model = FeatureMapModel(
            list(maps), st["inner"][0], scale_maps=admm.scale_maps,
            input_dim=prep.d, classes=prep.classes,
        )
        model.history = [float(o) for o in np.asarray(st["objs"][:it])]
        model.val_history = []
        model.timers = prep.timer
        model.iterations = it
        # Prox-vs-consensus gap ‖W − Wbar‖_F: identical on every rank
        # (both leaves are global), the CLI's post-train report metric.
        model.consensus_residual = float(
            jnp.linalg.norm(st["inner"][1] - st["inner"][0])
        )
        return model

    return ChunkedSolver(
        init_state=init_state,
        step_chunk=step_chunk,
        extract_result=extract_result,
        is_done=lambda st: int(st["it"]) >= maxiter,
        iteration=lambda st: int(st["it"]),
        kind=KIND,
    )


class DistributedBlockADMMTrainer:
    """Multi-host elastic BlockADMM trainer (≙ the reference's MPI
    ``skylark_ml`` training topology).

    Every process of the ``jax.distributed`` world calls :meth:`train`
    with the same arguments; each streams its own row partition, trains
    in lockstep (one psum per outer iteration), and returns the same
    model bit-for-bit — no broadcast needed.  For simulated-rank tests
    compose :func:`stream_feature_blocks` / :func:`prepare_rank_admm` /
    :func:`rank_chunked_solver` directly and merge by hand.
    """

    def __init__(
        self,
        loss: str,
        regularizer: str,
        feature_maps: Sequence,
        params: ADMMParams | None = None,
        elastic: ElasticParams | None = None,
    ):
        self.loss = get_loss(loss)
        self.regularizer = get_regularizer(regularizer)
        self.maps = list(feature_maps)
        if not self.maps:
            raise InvalidParameters(
                "DistributedBlockADMMTrainer needs at least one feature map"
            )
        self.params = params or ADMMParams()
        self.elastic = elastic or ElasticParams()

    def train(
        self,
        source,
        partition: RowPartition,
        *,
        classes=None,
        regression: bool = False,
        dtype=None,
        targets: int | None = None,
        fault_plan=None,
        train_fault_plan=None,
        compute_dtype=None,
        registry=None,
        register_as: str | None = None,
        epoch: int = 0,
    ):
        """Train over the partitioned stream; returns ``(model, info)``.

        ``source`` is the GLOBAL batch factory (``f(start_batch) →
        iterator`` of ``(X_batch, y_batch)``) every rank receives;
        ``fault_plan`` rides the streaming pass, ``train_fault_plan``
        the iteration runner (they count different chunk clocks).
        ``registry``/``register_as`` land the trained model in a serve
        registry at end of training.
        """
        p, ep = self.params, self.elastic
        kind = KIND
        ni_p = validate_train_partition(partition, p.data_partitions)
        _require_real_world(partition)
        rank, world = _resolve_world(ep)
        partition.validate_world(rank, world)
        r0, r1 = partition.row_range(rank)
        dt = _result_dtype(dtype)
        t = int(targets or 1)
        D = int(sum(int(S.s) for S in self.maps))
        guarded = guard.enabled()
        report = (
            guard.RecoveryReport(stage=kind)
            if guarded
            else guard.RecoveryReport.disabled(kind)
        )
        if telemetry.enabled():
            telemetry.inc("train.runs")

        # Policy: the "train" kind decides only the precision rung (the
        # route IS the consensus trainer); an empty/immature store keeps
        # the full-precision default bitwise.
        k_policy = len(classes) if classes is not None else t
        decision = policy.consult(
            "train", m=partition.nrows, n=D, targets=k_policy, dtype=dt,
            sketch_size=D, guard_on=guarded,
        )
        cd = compute_dtype if compute_dtype is not None else decision.compute_dtype

        plan = None
        replay = None
        if getattr(ep, "resume_policy", "strict") == "repartition":
            epoch, plan = resolve_resume(
                ep.checkpoint_dir, partition, kind=kind, params=ep
            )
            if plan is not None:
                # Feature buffers are row-slot (positional), not
                # sum-decomposable: a world change re-streams the NEW
                # share fresh at the bumped epoch instead of merging
                # durable refs.  Within that epoch the stream and the
                # ADMM state keep their own checkpoints, so a second
                # interruption resumes the recovery bit-for-bit.
                replay = plan.replay_info()
                if telemetry.enabled():
                    telemetry.inc("train.repartitions")
        watchdog = (
            _make_watchdog(ep, ep.checkpoint_dir, rank, world, epoch)
            if ep.checkpoint_dir
            else None
        )

        with telemetry.span("train.stream", kind=kind, rank=rank):
            Z_rows, Y_rows, nbatches = stream_feature_blocks(
                source, self.maps, partition, ep, dtype=dt, targets=t,
                scale_maps=p.scale_maps, kind=kind, fault_plan=fault_plan,
                report=report, epoch=epoch,
            )

        def _prep(rung):
            with telemetry.span("train.factor", kind=kind, rung=str(rung)):
                return prepare_rank_admm(
                    self.loss, self.regularizer, self.maps, p, partition,
                    rank, Z_rows, Y_rows, classes=classes,
                    regression=regression, compute_dtype=rung,
                )

        prep = _prep(cd)
        escalated = False
        if guarded:
            # Attempt-0 certification of the (possibly precision-rounded)
            # factors — and the verdict is a WORLD decision: psum the
            # ok/not-ok flags plus the chunk-sentinel replay counts so
            # every rank takes the same rung even when only one saw the
            # failure.
            ok = bool(guard.tree_all_finite(prep.Ls)) and bool(
                guard.tree_all_finite(prep.Zs)
            )
            local_replays = sum(
                1 for a in report.attempts if a.action == "replay"
            )
            votes = cross_host_psum(
                np.asarray(
                    [0.0 if ok else 1.0, float(local_replays)], np.float64
                ),
                watchdog=watchdog,
                phase="verdict",
            )
            world_bad, world_replays = int(votes[0]), int(votes[1])
            report.record(
                "initial",
                verdict=guard.OK if not world_bad else guard.FALLBACK,
                detail=f"factor finiteness at rung {cd or str(dt)}",
            )
            report.record(
                "world",
                detail=(
                    f"psum verdict over {world} rank(s): bad_certs="
                    f"{world_bad}, chunk_replays={world_replays}"
                ),
            )
            if world_bad:
                if cd is None:
                    raise guard.NumericalHealthError(
                        "non-finite Cholesky factors at full precision",
                        stage=kind, report=report,
                    )
                # f32 escalation rung: rebuild factors at the streamed
                # dtype, recorded for the profile store.
                report.record(
                    "escalate", verdict=guard.FALLBACK,
                    detail=f"{cd} factors non-finite; full-precision "
                    "rebuild (world verdict)",
                )
                report.recovered = True
                decision.escalated = True
                escalated = True
                cd = None
                prep = _prep(None)
                if telemetry.enabled():
                    telemetry.inc("train.escalations")

        # world=1: no collective → the fused single-jit step (bitwise
        # parity with ``BlockADMMSolver.train``).  world>1: the split
        # schedule with the watchdogged psum at the seam.
        chunked = rank_chunked_solver(
            prep, self.maps, p,
            merge=(
                None
                if world == 1
                else lambda tree: cross_host_psum(
                    tree, watchdog=watchdog, phase="consensus"
                )
            ),
        )
        rp = ResilientParams(
            checkpoint_dir=(
                os.path.join(host_dir(ep.checkpoint_dir, rank, epoch), "train")
                if ep.checkpoint_dir
                else None
            ),
            checkpoint_every=ep.checkpoint_every,
            keep_last=ep.keep_last,
            resume=ep.resume,
            expect_epoch=(int(epoch) if ep.checkpoint_dir else None),
        )
        runner = ResilientRunner(
            chunked, rp,
            metadata={
                "elastic": {
                    "rank": rank, "world": world, "epoch": int(epoch),
                    "signature": int(partition.signature()),
                }
            },
            fault_plan=train_fault_plan,
        )
        with telemetry.span("train.iterate", kind=kind):
            model = runner.run()

        rung = str(cd) if cd else str(np.dtype(dt))
        info = {
            "rows": int(partition.nrows),
            "batches": int(partition.num_batches),
            "local_batches": int(nbatches),
            "world_size": int(partition.world_size),
            "rank": int(rank),
            "data_partitions": int(p.data_partitions),
            "features": D,
            "blocks": len(self.maps),
            "iters": int(model.iterations),
            "objective": model.history[-1] if model.history else None,
            "consensus_residual": model.consensus_residual,
            "precision": rung,
            "escalated": escalated,
            "resume_policy": getattr(ep, "resume_policy", "strict"),
            "epoch": int(epoch),
            "recovery": report.to_dict(),
            "replay": replay,
            "policy": decision.to_dict(),
            "registered": register_as,
        }
        model.info = info
        bf16_note = fp8_note = None
        if decision.compute_dtype == "bfloat16":
            bf16_note = "fail" if escalated else "ok"
        elif decision.compute_dtype == "float8_e4m3fn":
            fp8_note = "fail" if escalated else "ok"
        policy.observe(
            decision, info, default_size=D, bf16=bf16_note, fp8=fp8_note,
            batches=nbatches,
        )
        if registry is not None and register_as:
            # End-of-training serve hand-off: every rank holds identical
            # bits, so registering locally is world-consistent.
            registry.register_model(register_as, model)
            if telemetry.enabled():
                telemetry.inc("train.registered")
        telemetry.run_summary(kind, info)
        return model, info
