"""Class-based nonlinear regression/classification models
(≙ ``python-skylark/skylark/ml/nonlinear.py``).

Four estimators, mirroring the reference's pure-Python layer on top of the
kernel + sketch machinery:

- ``RLS`` — exact kernel regularized least squares
  (≙ ``nonlinear.py`` class ``rls``): Gram + PSD solve, predict via
  ``k(X_test, X_train) @ alpha``.
- ``SketchRLS`` — random-feature RLS (≙ class ``sketchrls``): feature map
  from ``kernel.create_rft``, normal-equation solve in feature space.
- ``NystromRLS`` — Nyström features (≙ class ``nystromrls``): sample l
  landmark rows (uniform or ridge-leverage weighted), whiten with the
  landmark Gram's inverse square root, solve in the induced feature space.
- ``SketchPCR`` — sketched kernel principal component regression
  (≙ class ``sketchpcr``).  The reference calls
  ``lowrank.approximate_domsubspace_basis`` — a module absent from its
  tree (dead import; the class cannot run upstream).  We implement the
  algebra its call site assumes: random features Z (n, s), a second-level
  sketch of size t to cheaply factor Z, SVD of the small t×s factor for
  the top-``rank`` right basis and whitener (the Blendenpik-style role
  the reference's triangular R plays), regression on the projected
  features, and weights folded back to feature space exactly as the
  reference's ``train`` does with ``R⁻¹·(V·w₀)``.

All four share the reference's label handling: multiclass labels are
±1 dummy-coded for training and argmax-decoded at prediction
(``ml/utils.py dummycoding/dummydecode``); with ``multiclass=False``
targets pass through untouched (regression).

TPU notes: every train path is (blocked) MXU matmuls plus one
replicated-small factorization (Cholesky/eigh/QR of s×s or l×l), the same
replicate-the-small-factor choice the reference makes with [*,*]
matrices.  Solves run in f32; inputs may be dense or BCOO (feature maps
consume BCOO directly; Gram paths densify).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.linalg import cho_factor, cho_solve

from ..core.context import SketchContext
from ..sketch.base import Dimension
from ..sketch.hash import CWT
from ..sketch.sampling import NURST
from .coding import decode_labels, dummy_coding
from .kernels import Kernel, _dense
from .krr import _psd_gram

__all__ = ["RLS", "SketchRLS", "NystromRLS", "SketchPCR"]


class _LabeledModel:
    """Shared ±1 dummy-coding / argmax-decoding label plumbing."""

    def __init__(self, kernel: Kernel):
        self.kernel = kernel
        self.multiclass = True
        self.classes = None

    def _encode(self, Y, multiclass):
        self.multiclass = bool(multiclass)
        if not self.multiclass:
            Y = jnp.asarray(Y)
            self.classes = None
            return Y[:, None] if Y.ndim == 1 else Y
        T, self.classes = dummy_coding(Y)
        return T

    def _decode(self, O):
        if not self.multiclass:
            return O[:, 0] if O.shape[1] == 1 else O
        return decode_labels(O, self.classes)


class RLS(_LabeledModel):
    """Exact kernel RLS (≙ nonlinear.py ``rls``)."""

    def train(self, X, Y, regularization: float = 1.0, multiclass: bool = True):
        X = _dense(X)
        T = self._encode(Y, multiclass)
        K = self.kernel.gram(X, X)
        A = K + regularization * jnp.eye(K.shape[0], dtype=K.dtype)
        self.alpha = cho_solve(cho_factor(A, lower=True), T)
        self.X_train = X
        return self

    def predict(self, Xt):
        K = self.kernel.gram(_dense(Xt), self.X_train)
        return self._decode(K @ self.alpha)


class SketchRLS(_LabeledModel):
    """Random-feature RLS (≙ nonlinear.py ``sketchrls``)."""

    def train(
        self,
        X,
        Y,
        context: SketchContext,
        random_features: int = 100,
        regularization: float = 1.0,
        multiclass: bool = True,
        subtype: str = "regular",
    ):
        T = self._encode(Y, multiclass)
        self.rft = self.kernel.create_rft(random_features, subtype, context)
        Z = self.rft.apply(X, Dimension.ROWWISE)  # (n, s)
        A = _psd_gram(Z.T, Z) + regularization * jnp.eye(
            Z.shape[1], dtype=Z.dtype
        )
        self.weights = cho_solve(cho_factor(A, lower=True), Z.T @ T)
        return self

    def predict(self, Xt):
        Zt = self.rft.apply(Xt, Dimension.ROWWISE)
        return self._decode(Zt @ self.weights)


class NystromRLS(_LabeledModel):
    """Nyström-feature RLS (≙ nonlinear.py ``nystromrls``).

    Landmarks are drawn with ``NURST`` under ``probdist`` ∈ {"uniform",
    "leverages"}; "leverages" weights rows by the ridge leverage scores
    diag(K·(K+λI)⁻¹) — the intent of the reference's (self-admittedly
    approximate) leverage branch, computed here with a PSD solve instead
    of an explicit inverse.
    """

    _EPS = 1e-8  # eigenvalue floor for the landmark Gram (≙ eps in ref)

    def train(
        self,
        X,
        Y,
        context: SketchContext,
        random_features: int = 100,
        regularization: float = 1.0,
        probdist: str = "uniform",
        multiclass: bool = True,
    ):
        X = _dense(X)
        n = X.shape[0]
        T = self._encode(Y, multiclass)
        if probdist == "uniform":
            probs = jnp.full((n,), 1.0 / n)
        elif probdist == "leverages":
            K = self.kernel.gram(X, X)
            A = K + regularization * jnp.eye(n, dtype=K.dtype)
            lev = jnp.diagonal(cho_solve(cho_factor(A, lower=True), K))
            lev = jnp.maximum(lev, 0.0)
            probs = lev / jnp.sum(lev)
        else:
            raise ValueError(f"unknown probdist {probdist!r}")
        sampler = NURST(n, random_features, context, probs)
        SX = sampler.apply(X, Dimension.COLUMNWISE)  # (l, d) landmarks
        K_ll = self.kernel.gram(SX, SX)
        evals, evecs = jnp.linalg.eigh(
            K_ll + self._EPS * jnp.eye(K_ll.shape[0], dtype=K_ll.dtype)
        )
        evals = jnp.maximum(evals, self._EPS)
        self.U = evecs / jnp.sqrt(evals)[None, :]  # whitener K_ll^{-1/2}
        Z = self.kernel.gram(X, SX) @ self.U  # (n, l) Nyström features
        A = _psd_gram(Z.T, Z) + regularization * jnp.eye(
            Z.shape[1], dtype=Z.dtype
        )
        self.weights = cho_solve(cho_factor(A, lower=True), Z.T @ T)
        self.SX = SX
        return self

    def predict(self, Xt):
        Zt = self.kernel.gram(_dense(Xt), self.SX) @ self.U
        return self._decode(Zt @ self.weights)


class SketchPCR(_LabeledModel):
    """Sketched kernel PCR (≙ nonlinear.py ``sketchpcr``; see module
    docstring for the reconstruction of its missing ``lowrank`` step)."""

    def train(
        self,
        X,
        Y,
        context: SketchContext,
        rank: int,
        s: int | None = None,
        t: int | None = None,
        multiclass: bool = True,
        subtype: str = "regular",
    ):
        if s is None:
            s = 2 * rank
        if t is None:
            t = 2 * s
        if not (rank <= s <= t):
            raise ValueError(f"need rank <= s <= t, got {rank}, {s}, {t}")
        T = self._encode(Y, multiclass)
        self.rft = self.kernel.create_rft(s, subtype, context)
        Z = self.rft.apply(X, Dimension.ROWWISE)  # (n, s)
        n = Z.shape[0]
        # Second-level sketch: t×s subspace embedding of Z's column space,
        # then SVD of the small factor.  The top-rank right basis V and
        # whitener V·Σ⁻¹ play the role of the reference's R⁻¹·V (QR-based;
        # SVD handles the t < s and t > n corners the QR route cannot).
        SZ = CWT(n, min(t, n), context).apply(Z, Dimension.COLUMNWISE)
        _, sig, Vt = jnp.linalg.svd(SZ, full_matrices=False)
        if rank > sig.shape[0]:
            raise ValueError(
                f"rank {rank} exceeds sketched factor rank {sig.shape[0]}"
            )
        whiten = Vt[:rank].T / jnp.maximum(sig[:rank], 1e-12)  # (s, rank)
        # Projected (≈ orthonormal) principal features and regression;
        # weights fold back to feature space (≙ ref train's R⁻¹·V·w0).
        Zp = Z @ whiten
        w0 = jnp.linalg.lstsq(Zp, T)[0]  # (rank, k)
        self.weights = whiten @ w0  # (s, k)
        self.rank, self.s, self.t = rank, s, t
        return self

    def predict(self, Xt):
        Zt = self.rft.apply(Xt, Dimension.ROWWISE)
        return self._decode(Zt @ self.weights)
