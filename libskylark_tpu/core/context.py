"""Deterministic RNG context: the global (seed, counter) sample stream.

Re-design of ``base/context.hpp:19-183``: every consumer of randomness
*reserves* a contiguous range of the counter stream and records its base;
values are generated lazily (and shard-locally) from the counters.  This
makes every transform reconstructible from ~100 bytes of JSON and makes
results independent of device count — the invariant the reference's
distributed-vs-local tests are built on (``tests/unit/DenseSketchApply
ElementalTest.cpp:52-102``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any

__all__ = ["SketchContext"]

_SERIAL_VERSION = 2  # tracks sketch.base.SERIAL_VERSION (stream revision)


@dataclass
class SketchContext:
    """Mutable counter-reserving context (mirrors ``context_t``).

    ``reserve(size)`` returns the base counter of a freshly reserved block
    and advances the stream — the analogue of
    ``context_t::allocate_random_samples_array`` (``base/context.hpp:94-101``).
    """

    seed: int = 0
    counter: int = 0

    def reserve(self, size: int) -> int:
        if size < 0:
            raise ValueError(f"cannot reserve a negative block ({size})")
        base = self.counter
        self.counter += int(size)
        return base

    # -- serialization (≙ base/context.hpp:50-62 to_ptree) ------------------

    def to_dict(self) -> dict[str, Any]:
        return {
            "skylark_object_type": "context",
            "skylark_version": _SERIAL_VERSION,
            "seed": int(self.seed),
            "counter": int(self.counter),
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "SketchContext":
        return cls(seed=int(d["seed"]), counter=int(d["counter"]))

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_json(cls, s: str) -> "SketchContext":
        return cls.from_dict(json.loads(s))
