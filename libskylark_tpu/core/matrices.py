"""Deterministic random matrix generation (≙ ``base/random_matrices.hpp``).

The reference guarantees the generated matrix is identical regardless of how
many MPI processes generate it (each rank fills its local entries from the
global counter stream, ``base/random_matrices.hpp:22-177``).  Here the same
guarantee falls out of the counter-based window generator: the full logical
matrix is a pure function of (seed, base), and GSPMD shards its generation
with whatever sharding the consumer requests.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from .context import SketchContext
from .random import sample_window

__all__ = ["random_matrix", "gaussian_matrix", "uniform_matrix"]


def random_matrix(
    ctx: SketchContext,
    shape: tuple[int, int],
    dist: str = "normal",
    dtype=jnp.float32,
    **params: Any,
):
    """Draw a (rows, cols) matrix from the context's stream, advancing it."""
    rows, cols = shape
    base = ctx.reserve(rows * cols)
    return sample_window(dist, ctx.seed, base, (rows, cols), dtype=dtype, **params)


def gaussian_matrix(ctx, shape, dtype=jnp.float32, mean=0.0, stddev=1.0):
    x = random_matrix(ctx, shape, "normal", dtype=dtype)
    if mean != 0.0 or stddev != 1.0:
        x = x * stddev + mean
    return x


def uniform_matrix(ctx, shape, dtype=jnp.float32, low=0.0, high=1.0):
    return random_matrix(ctx, shape, "uniform", dtype=dtype, low=low, high=high)
