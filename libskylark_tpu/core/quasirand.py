"""Quasi-random (QMC) sequences with O(1) random access.

Re-design of ``base/quasirand.hpp:9-113``: a leaped Halton sequence where
``coordinate(idx, dim)`` is a pure function — the radical inverse of
``idx * leap`` in the ``dim``-th prime base.  Random access means any shard
can compute its own coordinates, same as the counter-based RNG.

The digit loop is expressed with a fixed trip count so it stays
jit-compatible with static shapes.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.exceptions import InvalidParameters

__all__ = ["primes", "radical_inverse", "LeapedHaltonSequence"]


@lru_cache(maxsize=64)
def primes(n: int) -> np.ndarray:
    """First n primes (replaces boost::math::prime)."""
    if n <= 0:
        return np.array([], dtype=np.int64)
    limit = max(15, int(n * (np.log(n + 2) + np.log(np.log(n + 3))) * 1.2) + 10)
    while True:
        sieve = np.ones(limit, dtype=bool)
        sieve[:2] = False
        for p in range(2, int(limit**0.5) + 1):
            if sieve[p]:
                sieve[p * p :: p] = False
        found = np.flatnonzero(sieve)
        if len(found) >= n:
            return found[:n].astype(np.int64)
        limit *= 2


def radical_inverse(base, idx, ndigits: int = 41) -> jnp.ndarray:
    """Van der Corput radical inverse of ``idx + 1`` in ``base``.

    Matches ``RadialInverseFunction`` (``base/quasirand.hpp:9-20``) including
    its 1-based indexing.  ``base`` and ``idx`` broadcast elementwise.

    ``ndigits`` bounds the digit loop; the default (41 digits of base>=2)
    exhausts any 41-bit index.  Iterations past the base's last nonzero
    digit add exactly 0.0, so a smaller static bound (when the caller
    knows ``max(idx)``) is BIT-IDENTICAL, just cheaper — ``window()``
    exploits this per prime base.
    """
    fdtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    base = jnp.asarray(base)
    res0 = jnp.asarray(idx) + 1
    shape = jnp.broadcast_shapes(base.shape, res0.shape)
    fbase = base.astype(fdtype)

    def body(_, carry):
        r, m, res = carry
        m = m / fbase
        r = r + m * (res % base.astype(res.dtype)).astype(fdtype)
        res = res // base.astype(res.dtype)
        return r, m, res

    r0 = jnp.zeros(shape, fdtype)
    m0 = jnp.ones(shape, fdtype)
    res0 = jnp.broadcast_to(res0, shape)
    r, _, _ = jax.lax.fori_loop(0, ndigits, body, (r0, m0, res0))
    return r


@dataclass(frozen=True)
class LeapedHaltonSequence:
    """Leaped Halton QMC sequence (≙ ``leaped_halton_sequence_t``).

    ``coordinate(idx, i) = radical_inverse(prime(i), idx * leap)`` with the
    default leap being the (d+1)-th prime (``base/quasirand.hpp:42-46``).
    """

    d: int
    leap: int = -1

    def __post_init__(self):
        if self.d < 0:
            raise InvalidParameters(f"Halton dimension must be >= 0, got {self.d}")
        if self.leap == -1:
            object.__setattr__(self, "leap", int(primes(self.d + 1)[-1]))
            return
        if self.leap < 1:
            raise InvalidParameters(
                f"Halton leap must be a positive integer (or -1 for the "
                f"default), got {self.leap}"
            )
        # A leap sharing a factor with a base prime visits only a strict
        # subsequence of that base's digit lattice (idx * leap ≡ 0 cycles),
        # destroying equidistribution in that dimension.  Bases are prime,
        # so coprimality is exactly "no base divides the leap".
        bad = [int(p) for p in primes(self.d) if self.leap % int(p) == 0]
        if bad:
            raise InvalidParameters(
                f"Halton leap {self.leap} is not coprime with base(s) {bad}; "
                f"choose a leap not divisible by any of the first {self.d} "
                f"primes"
            )

    def coordinate(self, idx, i):
        """Value(s) at sequence index ``idx``, dimension ``i``."""
        p = jnp.asarray(primes(self.d))[jnp.asarray(i)]
        return radical_inverse(p, jnp.asarray(idx) * self.leap)

    def window(self, idx0: int, num: int, dtype=jnp.float32) -> jnp.ndarray:
        """(num, d) block of the sequence starting at index ``idx0``.

        The 41-digit loop is wasteful for most dimensions: a base-p
        digit expansion of the (static) max index in the window has only
        ``ceil(log_p(max))`` nonzero digits — 2-4 for the large primes
        that dominate wide sequences — and the iterations past it add
        exactly 0.0.  Columns are therefore grouped into a few static
        digit tiers and each tier runs its own (shorter) loop; the
        result is bit-identical to the full 41-digit loop."""
        itype = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        idx = (idx0 + jnp.arange(num, dtype=itype))[:, None] * self.leap
        p_np = primes(self.d)
        if not p_np.size:
            return jnp.zeros((num, 0), dtype)
        try:
            start = int(idx0)  # tier math needs a static window start
        except (TypeError, jax.errors.ConcretizationTypeError):
            # Traced idx0 (window() is public API): keep the old fully
            # traceable 41-digit path rather than concretizing.
            p = jnp.asarray(p_np)[None, :].astype(itype)
            return radical_inverse(p, idx).astype(dtype)
        max_res = (start + num) * self.leap + 1  # static bound on idx+1
        # Exact integer digit count (float logs undercount by one at
        # p^k boundaries, which would drop the leading digit): smallest
        # k with p^k > max_res, via arbitrary-precision Python ints.
        need = np.empty(p_np.size, np.int64)
        for j, p in enumerate(p_np):
            k, acc = 1, int(p)
            while acc <= max_res and k < 41:
                acc *= int(p)
                k += 1
            need[j] = k
        tiers = (2, 3, 4, 6, 8, 12, 16, 24, 32, 41)
        tier = np.array([min(t for t in tiers if t >= k) for k in need])
        pieces, col_order = [], []
        for t in tiers:
            sel = np.flatnonzero(tier == t)
            if not sel.size:
                continue
            pb = jnp.asarray(p_np[sel])[None, :].astype(itype)
            pieces.append(radical_inverse(pb, idx, ndigits=int(t)))
            col_order.append(sel)
        if len(pieces) == 1:
            return pieces[0].astype(dtype)
        inv = np.argsort(np.concatenate(col_order))
        return jnp.concatenate(pieces, axis=1)[:, inv].astype(dtype)

    # -- serialization ------------------------------------------------------

    def to_dict(self):
        return {
            "skylark_object_type": "qmc_sequence",
            "sequence_type": "leaped halton",
            "d": self.d,
            "leap": self.leap,
        }

    @classmethod
    def from_dict(cls, dd):
        return cls(d=int(dd["d"]), leap=int(dd["leap"]))

    def to_json(self):
        return json.dumps(self.to_dict())

    @classmethod
    def from_json(cls, s):
        return cls.from_dict(json.loads(s))
