"""Base parameter/logging conventions (≙ ``base/params.hpp:12-40``).

Every algorithm takes a params dataclass carrying the uniform observability
fields the reference threads through all solvers (`am_i_printing, log_level,
prefix, debug_level`).  JSON-round-trippable like the reference's
ptree-constructible params.
"""

from __future__ import annotations

import dataclasses
import json
import sys
from dataclasses import dataclass, field
from typing import Any, IO

__all__ = ["Params"]


@dataclass
class Params:
    am_i_printing: bool = False
    log_level: int = 0
    prefix: str = ""
    debug_level: int = 0
    log_stream: IO = field(default=None, repr=False, compare=False)

    def log(self, level: int, msg: str) -> None:
        if self.am_i_printing and self.log_level >= level:
            stream = self.log_stream if self.log_stream is not None else sys.stdout
            print(f"{self.prefix}{msg}", file=stream)

    def to_dict(self) -> dict[str, Any]:
        # Not dataclasses.asdict: that deep-copies log_stream, which is
        # unpicklable for real streams.
        return {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(self)
            if f.name != "log_stream"
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]):
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_json(cls, s: str):
        return cls.from_dict(json.loads(s))
