"""Core runtime: RNG context, counter-based sampling, QMC, random matrices.

TPU-native re-design of the reference's ``base/`` + ``utility/`` layers.
"""

from .context import SketchContext
from .matrices import gaussian_matrix, random_matrix, uniform_matrix
from .params import Params
from .quasirand import LeapedHaltonSequence, primes, radical_inverse
from .random import sample, sample_window, raw_bits, window_bits

__all__ = [
    "SketchContext",
    "Params",
    "LeapedHaltonSequence",
    "primes",
    "radical_inverse",
    "sample",
    "sample_window",
    "raw_bits",
    "window_bits",
    "random_matrix",
    "gaussian_matrix",
    "uniform_matrix",
]
