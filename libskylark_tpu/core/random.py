"""Counter-based, random-access random number generation.

This is the TPU-native re-design of the reference's Random123/Threefry stream
(``base/randgen.hpp:17-197``, ``base/context.hpp:19-183``): sample *i* of a
stream is a pure function of ``(seed, base + i)`` — no sequential state.  Any
window of any logical random array can therefore be generated locally on any
shard without communication, which is the load-bearing idea behind the whole
sketching layer (a sketch matrix is never communicated; each shard realizes
the window it needs — ``sketch/dense_transform_data.hpp:68-152``).

Implementation: JAX's Threefry-2x32 block cipher, driven explicitly.  We hand
``threefry_2x32`` a count array ``concat([ctr_hi, ctr_lo])`` so that output
element *i* is the PRF of the 64-bit counter ``(ctr_hi[i] << 32) | ctr_lo[i]``
under the key — verified window-invariant (element value depends only on its
counter, never on the window shape).  Each 64-bit counter yields 64 random
bits (the two output words).  Distributions that need more than 64 bits per
sample draw from independent *lanes* (the lane index is mixed into the key),
mirroring how the reference's MicroURNG advances ``counter[3]`` for multiple
draws per sample (``base/context.hpp:80-92``).

Everything here is jit-compatible, works under GSPMD (the counter math is
elementwise over an iota, so XLA shards it with the output), and is
deterministic across device counts, platforms, and shardings.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.extend.random import threefry_2x32

__all__ = [
    "raw_bits",
    "window_bits",
    "sample",
    "sample_window",
    "chi2_lanes",
    "DISTRIBUTIONS",
]

_GOLDEN = 0x9E3779B9  # 32-bit golden-ratio constant for lane mixing.
_MASK32 = 0xFFFFFFFF


def _key(seed: int, lane: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Threefry key from (seed, lane).  Lane picks an independent stream."""
    seed = int(seed) % (1 << 64)
    k0 = np.uint32(seed & _MASK32)
    k1 = np.uint32(((seed >> 32) ^ (lane * _GOLDEN)) & _MASK32)
    return (jnp.uint32(k0), jnp.uint32(k1))


def _split64(value: int) -> tuple[np.uint32, np.uint32]:
    value = int(value) % (1 << 64)
    return np.uint32(value >> 32), np.uint32(value & _MASK32)


def _add64(a_hi, a_lo, b_hi, b_lo):
    """64-bit add on (hi, lo) uint32 pairs (elementwise, wrap-around)."""
    lo = a_lo + b_lo
    carry = (lo < a_lo).astype(jnp.uint32)
    hi = a_hi + b_hi + carry
    return hi, lo


def _mul_u32(a_hi, a_lo, c: int):
    """(64-bit value) * (32-bit constant c), keeping low 64 bits."""
    c = int(c) & _MASK32
    c_lo = jnp.uint32(c & 0xFFFF)
    c_hi = jnp.uint32(c >> 16)
    # a_lo * c via 16-bit limbs to capture the 64-bit product in uint32 math.
    a0 = a_lo & jnp.uint32(0xFFFF)
    a1 = a_lo >> 16
    p00 = a0 * c_lo                      # up to 32 bits
    p01 = a0 * c_hi                      # shifted 16
    p10 = a1 * c_lo                      # shifted 16
    p11 = a1 * c_hi                      # shifted 32
    mid = (p00 >> 16) + (p01 & jnp.uint32(0xFFFF)) + (p10 & jnp.uint32(0xFFFF))
    lo = (p00 & jnp.uint32(0xFFFF)) | (mid << 16)
    hi = p11 + (p01 >> 16) + (p10 >> 16) + (mid >> 16)
    hi = hi + a_hi * jnp.uint32(c)
    return hi, lo


def raw_bits(seed: int, base: int, num: int, lane: int = 0, offset=0):
    """64 random bits for counters ``base+offset .. base+offset+num`` as two
    uint32 arrays.

    Pure function of (seed, lane, counter): random access, no state.
    ``offset`` may be a traced scalar (< 2^32; shard-dependent window
    starts under ``shard_map``); ``base``/``num`` must be static.  Counter
    math is uint32-pair with explicit carries, so windows crossing 2^32
    stay exact.
    """
    idx = jax.lax.iota(jnp.uint32, num)
    b_hi, b_lo = _split64(base)
    hi, lo = _add64(
        jnp.uint32(b_hi), jnp.uint32(b_lo),
        jnp.uint32(0), jnp.asarray(offset, jnp.uint32),
    )
    hi, lo = _add64(hi, lo, jnp.uint32(0), idx)
    out = threefry_2x32(_key(seed, lane), jnp.concatenate([hi, lo]))
    return out[:num], out[num:]


def window_bits(
    seed: int,
    base: int,
    full_cols: int,
    row0,
    col0,
    rows: int,
    cols: int,
    lane: int = 0,
):
    """Bits for a (rows, cols) window of a row-major logical array.

    Element (i, j) uses counter ``base + (row0+i)*full_cols + (col0+j)`` —
    the same contract as ``dense_transform_data_t::realize_matrix_view``
    (``sketch/dense_transform_data.hpp:79-152``), so a sharded realization is
    bit-identical to the single-host one.

    ``row0``/``col0`` may be traced scalars (shard-dependent offsets under
    ``shard_map``); ``rows``/``cols``/``base``/``full_cols`` must be
    static.  All counter math is uint32-pair with explicit carries, so
    windows crossing 2^32 counter boundaries stay exact.
    """
    i = jax.lax.broadcasted_iota(jnp.uint32, (rows, cols), 0)
    j = jax.lax.broadcasted_iota(jnp.uint32, (rows, cols), 1)
    if isinstance(row0, int):
        row0 = np.uint32(row0 % (1 << 32))
    if isinstance(col0, int):
        col0 = np.uint32(col0 % (1 << 32))
    # counter = base + (row0+i)*full_cols + (col0+j), uint32-pair math.
    r_hi, r_lo = _mul_u32(jnp.uint32(0), i + jnp.uint32(row0), full_cols)
    hi, lo = _add64(r_hi, r_lo, jnp.uint32(0), j + jnp.uint32(col0))
    b_hi, b_lo = _split64(base)
    hi, lo = _add64(hi, lo, jnp.uint32(b_hi), jnp.uint32(b_lo))
    out = threefry_2x32(
        _key(seed, lane), jnp.concatenate([hi.ravel(), lo.ravel()])
    )
    n = rows * cols
    return out[:n].reshape(rows, cols), out[n:].reshape(rows, cols)


# ---------------------------------------------------------------------------
# bits -> distribution values
# ---------------------------------------------------------------------------


def _uniform01(hi, lo, dtype):
    """Uniform in (0, 1) — open at both ends so logs/inverse-CDFs are safe.

    ``(k + 0.5) * 2^-bits`` with k an integer below the mantissa width is
    exact in floating point (no rounding), so the result lies in
    ``[2^-(bits+1), 1 - 2^-(bits+1)]`` and can never round to 0.0 or 1.0.
    """
    dtype = jnp.dtype(dtype)
    if dtype == jnp.float64:
        if not jax.config.jax_enable_x64:
            raise ValueError(
                "float64 sampling requires jax_enable_x64; enable it or "
                "request float32"
            )
        # 52 mantissa bits from the two words: exact (k + 0.5) * 2^-52.
        top = hi.astype(jnp.uint64) >> 7       # 25 bits
        bot = lo.astype(jnp.uint64) >> 5       # 27 bits
        k = (top << 27 | bot).astype(jnp.float64)
        return (k + 0.5) * (2.0 ** -52)
    # f32 leads with HI's top bits — the SAME leading bits as the f64
    # value, so the two dtype streams agree to ~2^-24 (cross-precision
    # determinism: an f32 TPU run and an f64/native-C run see the same
    # uniforms).  A lo-based f32 stream would be statistically independent
    # of the f64 one and silently break cross-language parity.
    k = (hi >> 8).astype(jnp.float32)          # 24 bits, exact in f32
    return ((k + np.float32(0.5)) * np.float32(2.0 ** -24)).astype(dtype)


def _uniform(hi, lo, dtype, low=0.0, high=1.0):
    return _uniform01(hi, lo, dtype) * (high - low) + low


def _normal(hi, lo, dtype):
    """Box-Muller from the counter's two 32-bit words — exact N(0, 1), one
    counter per sample.

    ~5x cheaper on the TPU VPU than the inverse-CDF (ndtri) route, which
    matters because sketch-operand generation rides the matmul's critical
    path.  u1/u2 use the (k + 0.5)·2^-b construction (exact, never 0/1):
    24 bits each in f32, 32 bits each in f64 (tail reach ~6.6 sigma).
    """
    dtype = jnp.dtype(dtype)
    if dtype == jnp.float64:
        if not jax.config.jax_enable_x64:
            raise ValueError(
                "float64 sampling requires jax_enable_x64; enable it or "
                "request float32"
            )
        u1 = (hi.astype(jnp.float64) + 0.5) * (2.0**-32)
        u2 = (lo.astype(jnp.float64) + 0.5) * (2.0**-32)
    else:
        u1 = ((hi >> 8).astype(jnp.float32) + np.float32(0.5)) * np.float32(2.0**-24)
        u2 = ((lo >> 8).astype(jnp.float32) + np.float32(0.5)) * np.float32(2.0**-24)
    z = jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(2.0 * np.pi * u2)
    return z.astype(dtype)


def _cauchy(hi, lo, dtype):
    u = _uniform01(hi, lo, dtype)
    return jnp.tan(jnp.pi * (u - 0.5)).astype(dtype)


def _rademacher(hi, lo, dtype):
    return jnp.where(lo & 1, 1.0, -1.0).astype(dtype)


def _exponential(hi, lo, dtype):
    u = _uniform01(hi, lo, dtype)
    return -jnp.log(u).astype(dtype)


def _levy(hi, lo, dtype):
    # Standard Lévy: 1 / chi2(1) = 1 / Z^2   (utility/distributions.hpp:17-35).
    z = _normal(hi, lo, dtype)
    return (1.0 / (z * z)).astype(dtype)


def _uniform_int(hi, lo, dtype, low=0, high=None):
    """Uniform integer in [low, high] inclusive (matching boost's
    uniform_int_distribution used at hash_transform_data.hpp:66-73).

    Uses a 64-bit multiply-shift (floor(x * span / 2^64) with x the full
    64-bit counter hash), so the residual bias is O(span * 2^-64) — far
    below statistical visibility — and no uint64 dtype is needed.
    """
    if high is None:
        raise ValueError("uniform_int requires an explicit 'high' bound")
    low, high = int(low), int(high)
    if high < low:
        raise ValueError(f"uniform_int needs low <= high, got [{low}, {high}]")
    span = high - low + 1
    if span > (1 << 32):
        raise ValueError(f"uniform_int span {span} exceeds 2^32")
    # x*span >> 64 via two 32x32->64 partial products in uint32-pair math.
    p1_hi, p1_lo = _mul_u32(jnp.uint32(0), hi, span)
    p2_hi, _p2_lo = _mul_u32(jnp.uint32(0), lo, span)
    s_hi, _s_lo = _add64(p1_hi, p1_lo, jnp.uint32(0), p2_hi)
    return (jnp.int64(low) + s_hi if jax.config.jax_enable_x64
            else low + s_hi.astype(jnp.int32) if high < (1 << 31)
            else low + s_hi).astype(dtype)


def chi2_lanes(seed: int, base: int, size: int, dof: int, dtype=jnp.float32):
    """χ²(dof) samples as a sum of ``dof`` squared-normal lanes over one
    reserved counter block (lanes 1..dof; lane 0 left for the caller).

    Used by the Matérn feature maps' multivariate-t row correction
    (``sqrt(2ν/χ²_{2ν})``, ≙ ``sketch/RFT_data.hpp:336-345``).
    """
    if dof < 1 or int(dof) != dof:
        raise ValueError(f"chi2_lanes needs a positive integer dof, got {dof}")
    acc = jnp.zeros((size,), dtype)
    for lane in range(int(dof)):
        z = sample("normal", seed, base, size, dtype=dtype, lane=lane + 1)
        acc = acc + z * z
    return acc


DISTRIBUTIONS = {
    "uniform": _uniform,
    "normal": _normal,
    "cauchy": _cauchy,
    "rademacher": _rademacher,
    "exponential": _exponential,
    "levy": _levy,
    "uniform_int": _uniform_int,
}


def sample(
    dist: str,
    seed: int,
    base: int,
    num: int,
    dtype=jnp.float32,
    lane: int = 0,
    offset=0,
    **params: Any,
):
    """1-D stream sample: values for counters ``base+offset ..
    base+offset+num`` (``offset`` may be traced — see :func:`raw_bits`)."""
    hi, lo = raw_bits(seed, base, num, lane, offset=offset)
    return DISTRIBUTIONS[dist](hi, lo, dtype, **params)


def sample_window(
    dist: str,
    seed: int,
    base: int,
    full_shape: tuple[int, int],
    dtype=jnp.float32,
    offset: tuple[int, int] = (0, 0),
    shape: tuple[int, int] | None = None,
    lane: int = 0,
    **params: Any,
):
    """Window of a logical row-major 2-D random array.

    ``sample_window(d, s, b, (R, C))`` == full matrix; any sub-window of it is
    bit-identical to the corresponding slice, enabling shard-local sketch
    realization (reference invariant: ``base/random_matrices.hpp:22-177``).
    """
    rows_full, cols_full = full_shape
    if shape is None:
        shape = (rows_full - offset[0], cols_full - offset[1])
    hi, lo = window_bits(
        seed, base, cols_full, offset[0], offset[1], shape[0], shape[1], lane
    )
    return DISTRIBUTIONS[dist](hi, lo, dtype, **params)
