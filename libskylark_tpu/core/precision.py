"""Precision utilities for riding the bf16 MXU with f32 data.

An f32 value splits exactly into three bf16-representable parts by
masking mantissa bits: ``x = hi + lo + lo2`` with each part carrying ≤8
leading mantissa bits.  Contracting each part against a bf16-exact
operand (±1 / small-integer sketch matrices) with f32 accumulation and
summing reproduces full f32 precision at ~3× the f32 matmul rate.

The split is built from integer bit-masking, NOT ``astype`` round-trips:
XLA's excess-precision rules elide ``f32→bf16→f32`` convert pairs (the
upcast-after-downcast is "at least as precise", so the compiler drops
it), which silently turns ``x - bf16(x)`` into zero on TPU and collapses
an astype-based split to single-bf16 accuracy — measured 1.6e-3 max-rel
on hardware vs 8e-8 for this formulation (tests/test_pallas_hw.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["bf16_split3", "f32_accumulable", "fp8_dtype", "fp8_available"]


def fp8_dtype():
    """The fp8 sketch-apply element type — e4m3 (4 exponent / 3 mantissa
    bits: the accuracy-side fp8, vs e5m2's range-side) — or ``None`` on
    JAX builds without fp8 support.  MXU fp8 matmuls accumulate in f32,
    so the precision-ladder contract (narrow operands, f32 accumulate,
    guard-certified result) carries down from bf16 unchanged; only the
    operand rounding gets coarser."""
    return getattr(jnp, "float8_e4m3fn", None)


def fp8_available() -> bool:
    """True when this JAX build can represent e4m3 at all (the ladder's
    existence check; whether the BACKEND can matmul it profitably is the
    policy layer's call — ``policy.config.fp8_allowed``)."""
    return fp8_dtype() is not None


def f32_accumulable(dtype, *, demote_f64: bool = False) -> bool:
    """True when ``dtype`` may ride an f32-accumulating kernel with
    casts at the boundary.  bf16/f16 qualify unconditionally — f32 is a
    strict superset of both, so the cast in is exact and only the final
    cast out rounds (no worse than accumulating natively in the narrow
    type, and usually much better).  f64 qualifies only when the caller
    explicitly accepts the demotion (``demote_f64=True``, i.e. a
    force-enabled kernel): x64 parity runs must keep the XLA
    full-precision lowering by default.  This is the shared dtype gate
    of the Pallas scatter family (``sketch/pallas_scatter.py``,
    ``sketch/pallas_window.py``) — the precision ladders hand out bf16
    operands and previously forced every hash scatter back to XLA."""
    dt = jnp.dtype(dtype)
    if dt in (
        jnp.dtype(jnp.float32),
        jnp.dtype(jnp.bfloat16),
        jnp.dtype(jnp.float16),
    ):
        return True
    if dt == jnp.dtype(jnp.float64):
        return bool(demote_f64)
    return False


def _mask_top(x):
    """The top-16-bit (sign+exponent+7 mantissa) part of f32 x — exactly
    representable in bf16; computed by integer masking so no convert pair
    exists for XLA to elide."""
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    return jax.lax.bitcast_convert_type(
        bits & jnp.uint32(0xFFFF0000), jnp.float32
    )


def bf16_split3(x):
    """``(hi, lo, lo2)`` bf16 arrays with ``hi + lo + lo2 ≈ x`` to ~2^-24
    relative.  ``x`` must be f32 — the split bitcasts, so value-convert
    other dtypes first (an int bit pattern would masquerade as floats).

    Magnitude contract: the ~2^-24-relative bound holds for
    ``|x| ≳ 2^-110``.  Below that, ``lo``/``lo2`` (whose exponents sit
    ~8/16 binades under ``x``'s) fall beneath bf16's subnormal floor
    (2^-133; f32 reaches 2^-149) and round to zero, so the split
    gracefully degrades toward single-bf16 relative accuracy as ``|x|``
    approaches f32's own subnormal range.  Harmless for sketching
    workloads — inputs that tiny are already below any sketch tolerance —
    but callers needing the full contract at extreme denormal scales
    should pre-scale (round-2 advisor finding)."""
    if x.dtype != jnp.float32:
        raise TypeError(
            f"bf16_split3 needs float32 input, got {x.dtype}; astype first"
        )
    hi = _mask_top(x)
    r1 = x - hi
    lo = _mask_top(r1)
    lo2 = r1 - lo
    return (
        hi.astype(jnp.bfloat16),
        lo.astype(jnp.bfloat16),
        lo2.astype(jnp.bfloat16),
    )
