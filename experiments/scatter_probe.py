"""On-chip probe for the Pallas two-pass segment-sum (VERDICT r3 #3).

Measures, at the BASELINE.md bench shape (1e7 entries → 1.024e8 slots):
  1. XLA ``jax.ops.segment_sum`` (the 28 M nnz/s reference point);
  2. the Pallas kernel end-to-end (``pallas_scatter.segment_sum_flat``);
  3. pass 1 (chunk partition-sort) alone;
  4. ``jax.lax.sort`` of the keys (is a full sort ever competitive?);
  5. parity of 1-vs-2 on the live chip.

Run on the bench chip: ``python experiments/scatter_probe.py [nnz] [T]``.
The results pick C/P and decide whether pass 2's scalar loop needs the
deeper (3-level, one-hot matmul finish) design.
"""

from __future__ import annotations

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from libskylark_tpu.sketch import pallas_scatter as ps


def timed(tag, fn, *args, reps=3):
    out = jax.block_until_ready(fn(*args))  # compile
    best = min(
        (lambda t0: (jax.block_until_ready(fn(*args)), time.perf_counter() - t0))(
            time.perf_counter()
        )[1]
        for _ in range(reps)
    )
    print(f"{tag:<40} {best * 1e3:9.2f} ms")
    return out, best


def main():
    nnz = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    T = int(sys.argv[2]) if len(sys.argv) > 2 else 1024 * 100_000
    print(f"device={jax.devices()[0]} nnz={nnz:.1e} T={T:.1e} "
          f"C={ps._C} plan(K,P,V)={ps._plan(nnz, T)}")
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    keys = jax.random.randint(k1, (nnz,), 0, T, dtype=jnp.int32)
    vals = jax.random.normal(k2, (nnz,), jnp.float32)
    jax.block_until_ready((keys, vals))

    xla_fn = jax.jit(
        lambda v, k: jnp.sum(
            jnp.abs(jax.ops.segment_sum(v, k, num_segments=T))
        )
    )
    out_x, t_x = timed("XLA segment_sum", xla_fn, vals, keys)

    t_p = None
    for mode in ("scalar", "lanemask"):
        os.environ["SKYLARK_SCATTER_ACCUM"] = mode
        try:
            # fresh jit per mode: the env flag is read at trace time
            pl_fn = jax.jit(
                lambda v, k: jnp.sum(jnp.abs(ps.segment_sum_flat(v, k, T)))
            )
            out_p, t_m = timed(f"Pallas two-pass [{mode}]", pl_fn, vals, keys)
        except Exception as e:  # noqa: BLE001 — report which mode lowers
            print(f"Pallas [{mode}] FAILED: {type(e).__name__}: "
                  f"{str(e)[:300]}")
            continue
        rel = abs(float(out_x) - float(out_p)) / max(abs(float(out_x)), 1e-30)
        print(f"{'  speedup / |sum| parity':<40} {t_x / t_m:9.2f} x   "
              f"rel={rel:.2e}")
        if t_p is None or t_m < t_p:
            t_p = t_m
    os.environ.pop("SKYLARK_SCATTER_ACCUM", None)
    if t_p is None:
        print("Pallas kernel failed to lower in every mode")
        return

    # pass 1 alone (partition-sort) — reuse internals
    from functools import partial

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    K, P, V = ps._plan(nnz, T)
    PP = P + 1
    pad = K * ps._C - nnz
    keys_p = jnp.pad(keys, (0, pad), constant_values=PP * V - 1).reshape(
        K, ps._C
    )
    vals_p = jnp.pad(vals, (0, pad)).reshape(K, ps._C)

    def pass1(kp, vp):
        sk, sv, cnt = pl.pallas_call(
            partial(ps._partition_kernel, V, PP),
            grid=(K,),
            in_specs=[
                pl.BlockSpec((1, ps._C), lambda k: (k, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, ps._C), lambda k: (k, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, ps._C), lambda k: (k, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, ps._C), lambda k: (k, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, PP), lambda k: (k, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((K, ps._C), jnp.int32),
                jax.ShapeDtypeStruct((K, ps._C), jnp.float32),
                jax.ShapeDtypeStruct((K, PP), jnp.int32),
            ],
            scratch_shapes=[pltpu.VMEM((1, ps._C), jnp.int32)],
        )(kp, vp)
        return jnp.sum(cnt) + jnp.sum(sk[0]) + jnp.sum(sv[0])

    timed("pass 1 only (partition-sort)", jax.jit(pass1), keys_p, vals_p)

    sort_fn = jax.jit(lambda k, v: jax.lax.sort((k, v), num_keys=1)[0][-1])
    timed("jax.lax.sort keys+vals (calibration)", sort_fn, keys, vals)

    print(f"\nnnz/s: XLA {nnz / t_x / 1e6:.0f} M, Pallas {nnz / t_p / 1e6:.0f} M"
          f"  (target >= {5 * nnz / t_x / 1e6:.0f} M for 5x)")


if __name__ == "__main__":
    main()
