"""Round-3 probe: input-sparsity-time hash sketches at scale + MMT/WZT
dense f32 (VERDICT r2 item 2).

Sparse config: BCOO 1e6 x 1e5, 1e8 nnz, CWT/SJLT columnwise -> BCOO.
Dense config: MMT/WZT f32 at the CWT bench shape 131072 x 4096 -> 1024.
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.sketch.hash import CWT, MMT, SJLT, WZT


def _timed(fn, *args):
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    return time.perf_counter() - t0


def _timed_np(fn, *args):
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    return time.perf_counter() - t0


def rep_diff(build, args, r1=1, r2=3, rounds=6):
    f1, f2 = build(r1), build(r2)
    _timed_np(f1, *args), _timed_np(f2, *args)
    t1s, t2s = [], []
    for _ in range(rounds):
        t1s.append(_timed_np(f1, *args))
        t2s.append(_timed_np(f2, *args))
    t1, t2 = min(t1s), min(t2s)
    return float("nan") if t2 <= t1 else (t2 - t1) / (r2 - r1)


def random_bcoo(n, m, nnz, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    rows = jax.random.randint(k1, (nnz,), 0, n, dtype=jnp.int32)
    cols = jax.random.randint(k2, (nnz,), 0, m, dtype=jnp.int32)
    data = jax.random.normal(k3, (nnz,), jnp.float32)
    idx = jnp.stack([rows, cols], axis=1)
    return jsparse.BCOO((data, idx), shape=(n, m))


def sparse_apply(cls, kw, n, m, s, nnz):
    A = random_bcoo(n, m, nnz)
    jax.block_until_ready((A.data, A.indices))

    def build(reps):
        ctx = SketchContext(seed=21)
        sketches = [cls(n, s, ctx, **kw) for _ in range(reps)]

        @jax.jit
        def run(data, idx):
            A = jsparse.BCOO((data, idx), shape=(n, m))
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                out = S.apply(A, "columnwise")
                acc += jnp.sum(jnp.abs(out.data))
            return acc

        return run

    return rep_diff(build, (A.data, A.indices))


def dense_apply(cls, kw, n, m, s, dtype):
    A = jax.random.normal(jax.random.PRNGKey(2), (n, m), dtype)

    def build(reps):
        ctx = SketchContext(seed=29)
        sketches = [cls(n, s, ctx, **kw) for _ in range(reps)]

        @jax.jit
        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                acc += jnp.sum(jnp.abs(S.apply(A, "columnwise").astype(jnp.float32)))
            return acc

        return run

    return rep_diff(build, (A,), r1=2, r2=6)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "dense"):
        for cls, kw in ((MMT, {}), (WZT, {"p": 1.5})):
            t = dense_apply(cls, kw, 131_072, 4096, 1024, jnp.float32)
            print(f"{cls.__name__} dense f32 131072x4096->1024: {t*1e3:.2f} ms",
                  flush=True)
    if which in ("all", "sparse"):
        for nnz in (10_000_000, 100_000_000):
            for cls, kw in ((CWT, {}), (SJLT, {"nnz": 4})):
                t = sparse_apply(cls, kw, 1_000_000, 100_000, 1024, nnz)
                print(f"{cls.__name__} BCOO 1e6x1e5 nnz={nnz:.0e} -> 1024: "
                      f"{t*1e3:.2f} ms", flush=True)
