"""PPT cost anatomy: FFT axis layout, rfft, and matmul-DFT on TPU."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _timed(fn, *args):
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    return time.perf_counter() - t0


def rep_diff(build, *A, r1=2, r2=6, rounds=6):
    f1, f2 = build(r1), build(r2)
    _timed(f1, *A), _timed(f2, *A)
    t1s, t2s = [], []
    for _ in range(rounds):
        t1s.append(_timed(f1, *A))
        t2s.append(_timed(f2, *A))
    t1, t2 = min(t1s), min(t2s)
    return float("nan") if t2 <= t1 else (t2 - t1) / (r2 - r1)


def fft_axis(m, s, axis):
    shape = (s, m) if axis == 0 else (m, s)

    def build(reps):
        def run(W):
            acc = jnp.zeros((), jnp.float32)
            for i in range(reps):
                P = jnp.fft.fft(W + jnp.float32(i), axis=axis)
                acc += jnp.sum(jnp.abs(jnp.real(P)))
            return acc
        return jax.jit(run)

    W = jax.random.normal(jax.random.PRNGKey(0), shape, jnp.float32)
    return rep_diff(build, W)


def rfft_last(m, s):
    def build(reps):
        def run(W):
            acc = jnp.zeros((), jnp.float32)
            for i in range(reps):
                P = jnp.fft.rfft(W + jnp.float32(i), axis=1)
                acc += jnp.sum(jnp.abs(jnp.real(P)))
            return acc
        return jax.jit(run)

    W = jax.random.normal(jax.random.PRNGKey(0), (m, s), jnp.float32)
    return rep_diff(build, W)


def irfft_last(m, s):
    def build(reps):
        def run(P):
            acc = jnp.zeros((), jnp.float32)
            for i in range(reps):
                Z = jnp.fft.irfft(P * (1.0 + i), n=s, axis=1)
                acc += jnp.sum(jnp.abs(Z))
            return acc
        return jax.jit(run)

    P = jnp.asarray(
        np.random.default_rng(0).standard_normal((m, s // 2 + 1))
        + 1j * np.random.default_rng(1).standard_normal((m, s // 2 + 1)),
        jnp.complex64,
    )
    return rep_diff(build, P)


if __name__ == "__main__":
    m, s = 131_072, 1024
    print(f"fft axis0 (s,m) c64: {fft_axis(m, s, 0)*1e3:.2f} ms", flush=True)
    print(f"fft axis1 (m,s) c64: {fft_axis(m, s, 1)*1e3:.2f} ms", flush=True)
    print(f"rfft last (m,s): {rfft_last(m, s)*1e3:.2f} ms", flush=True)
    print(f"irfft last (m,s/2+1): {irfft_last(m, s)*1e3:.2f} ms", flush=True)
