"""f32 realized-FRFT: 4 summed dots vs one stacked-contraction matmul.

The 4-dot form materializes four (m, S) f32 partials (2 GB each at
s=4096) — output traffic dominates.  Stacking the split parts along the
contraction axis does ONE dot with 4n contraction: same flops, one
output pass, at the cost of materializing the (m, 4n) bf16 concat.
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.core.precision import bf16_split3
from libskylark_tpu.sketch.frft import FastGaussianRFT


def _timed(fn, *args):
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    return time.perf_counter() - t0


def rep_diff(build, A, r1=2, r2=6, rounds=6):
    f1, f2 = build(r1), build(r2)
    _timed(f1, A), _timed(f2, A)
    t1s, t2s = [], []
    for _ in range(rounds):
        t1s.append(_timed(f1, A))
        t2s.append(_timed(f2, A))
    t1, t2 = min(t1s), min(t2s)
    return float("nan") if t2 <= t1 else (t2 - t1) / (r2 - r1)


def run(m, n, s, mode):
    def build(reps):
        ctx = SketchContext(seed=7)
        sketches = [FastGaussianRFT(n, s, ctx, sigma=2.0) for _ in range(reps)]

        def one(S, A):
            W = S._realized_w()
            w_hi, w_lo, _ = bf16_split3(W)
            a_hi, a_lo, a_lo2 = bf16_split3(A)
            if mode == "stack":
                A4 = jnp.concatenate([a_hi, a_lo, a_lo2, a_hi], axis=1)
                W4 = jnp.concatenate([w_hi, w_hi, w_hi, w_lo], axis=1)
                V = jax.lax.dot_general(
                    A4, W4, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
            else:
                mm = lambda x, w: jax.lax.dot_general(
                    x, w, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                V = mm(a_hi, w_hi) + mm(a_lo, w_hi) + mm(a_lo2, w_hi) + mm(a_hi, w_lo)
            sh = S._shifts(jnp.float32)
            return S.outscale * jnp.cos(V + sh[None, :])

        def runf(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                acc += jnp.sum(jnp.abs(one(S, A)))
            return acc

        return jax.jit(runf)

    A = jax.random.normal(jax.random.PRNGKey(1), (m, n), jnp.float32)
    return rep_diff(build, A)


if __name__ == "__main__":
    m, n = 131_072, 4096
    for s in (2048, 4096):
        for mode in ("stack", "dots"):
            print(f"f32 realized[{mode}] s={s}: {run(m, n, s, mode)*1e3:.2f} ms",
                  flush=True)
