"""Sort-free dense-accumulator formulation for BCOO hash sketches.

Current _apply_sparse: relabel + concat + BCOO.sum_duplicates (lexsort of
nnz*H entries — 4.7 s at 1e8 nnz, OOM for SJLT's 4e8).  Candidate: per
hash function, segment_sum data*v into a dense (S*m) accumulator keyed by
b[row]*m + col — no sort, no concat, O(S*m) resident.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.sketch.hash import CWT, SJLT


def _timed(fn, *args):
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    return time.perf_counter() - t0


def rep_diff(build, args, r1=1, r2=3, rounds=5):
    f1, f2 = build(r1), build(r2)
    _timed(f1, *args), _timed(f2, *args)
    t1s, t2s = [], []
    for _ in range(rounds):
        t1s.append(_timed(f1, *args))
        t2s.append(_timed(f2, *args))
    t1, t2 = min(t1s), min(t2s)
    return float("nan") if t2 <= t1 else (t2 - t1) / (r2 - r1)


def random_coo(n, m, nnz, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    rows = jax.random.randint(k1, (nnz,), 0, n, dtype=jnp.int32)
    cols = jax.random.randint(k2, (nnz,), 0, m, dtype=jnp.int32)
    data = jax.random.normal(k3, (nnz,), jnp.float32)
    return data, rows, cols


def dense_accum(cls, kw, n, m, s, nnz):
    data, rows, cols = random_coo(n, m, nnz)
    jax.block_until_ready((data, rows, cols))

    def build(reps):
        ctx = SketchContext(seed=21)
        sketches = [cls(n, s, ctx, **kw) for _ in range(reps)]

        @jax.jit
        def run(data, rows, cols):
            acc_all = jnp.zeros((), jnp.float32)
            for S in sketches:
                b = S.buckets().reshape(S.nnz, S.n)
                v = S.values(jnp.float32).reshape(S.nnz, S.n)
                out = jnp.zeros((s * m,), jnp.float32)
                for h in range(S.nnz):
                    out = out + jax.ops.segment_sum(
                        data * v[h][rows],
                        b[h][rows] * jnp.int32(m) + cols,
                        num_segments=s * m,
                    )
                acc_all += jnp.sum(jnp.abs(out))
            return acc_all

        return run

    return rep_diff(build, (data, rows, cols))


if __name__ == "__main__":
    n, m, s = 1_000_000, 100_000, 1024
    for nnz in (10_000_000, 100_000_000):
        for cls, kw in ((CWT, {}), (SJLT, {"nnz": 4})):
            t = dense_accum(cls, kw, n, m, s, nnz)
            print(f"{cls.__name__} dense-accum 1e6x1e5 nnz={nnz:.0e}: "
                  f"{t*1e3:.2f} ms", flush=True)
