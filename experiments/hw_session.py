"""One-shot hardware measurement battery for a live tunnel window.

The shared axon tunnel comes and goes; when a quiet window opens, this
driver runs the round's full measurement backlog in priority order, each
stage in its own subprocess with its own timeout and log file, so a
mid-battery hang costs one stage, not the session.

Run: ``python experiments/hw_session.py [logdir]``  (defaults to
``experiments/logs/``; prints a one-line verdict per stage).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STAGES = [
    # (name, argv, timeout_s[, extra_env])
    ("hw_guards", [sys.executable, "tests/_hw_guards.py"], 600),
    ("scatter_probe", [sys.executable, "experiments/scatter_probe.py"], 900),
    (
        "scatter_probe_c8192",
        [sys.executable, "experiments/scatter_probe.py"],
        900,
        {"SKYLARK_SCATTER_CHUNK": "8192"},
    ),
    (
        "fjlt_fused_probe",
        [sys.executable, "experiments/fjlt_fused_probe.py"],
        900,
    ),
    ("bench_full", [sys.executable, "bench.py"], 1800),
    (
        "northstar_host",
        [sys.executable, "experiments/northstar_krr.py", "host", "3"],
        1500,
    ),
]


def main() -> int:
    logdir = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        REPO, "experiments", "logs"
    )
    os.makedirs(logdir, exist_ok=True)
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    failures = 0
    for name, argv, tmo, *extra in STAGES:
        log = os.path.join(logdir, f"{name}.log")
        stage_env = dict(env, **(extra[0] if extra else {}))
        t0 = time.monotonic()
        try:
            with open(log, "w") as fh:
                rc = subprocess.run(
                    argv, stdout=fh, stderr=subprocess.STDOUT,
                    timeout=tmo, env=stage_env, cwd=REPO,
                ).returncode
            status = "ok" if rc == 0 else f"rc={rc}"
        except subprocess.TimeoutExpired:
            status = f"TIMEOUT {tmo}s"
        dt = time.monotonic() - t0
        if status != "ok":
            failures += 1
        print(f"{name:<18} {status:<12} {dt:7.1f}s  -> {log}", flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
