"""North-star single-chip run: 10M x 4096 random-feature KRR, bf16,
rows AND features streamed (ml/krr.py::streaming_kernel_ridge).

Three variants (all honest, measuring different bounds):
- "hot-panel": one resident 250k x 4096 bf16 panel reused for every
  logical row panel — data content repeats, compute/memory contract is
  exactly the 10M-row sweep.  Measures the COMPUTE path's s/sweep + MFU.
- "generated": every panel counter-generated (Box-Muller) per visit —
  true streamed synthetic data; generation-bound, like the streaming-SVD
  benchmark (BASELINE.md round 1 notes), a real IO-streamed workload
  would be storage-bound the same way.
- "host": panels fed from a host-RAM pool with a REAL ``device_put``
  per panel visit, double-buffered so the transfer of panel p+1 overlaps
  the compute of panel p (VERDICT r3 item 6).  This is the honest
  single-chip out-of-core regime: s/sweep is bounded below by
  max(compute, host-link bandwidth), and the run reports both so the
  overlap is characterized.  The panel loop lives in Python (per-panel
  jitted kernels) because a traced fori_loop cannot issue host
  transfers; the BCD updates are identical to
  ``streaming_kernel_ridge``'s (same math, same hoisted operands).

Run: python experiments/northstar_krr.py [hot|gen|host] [sweeps]
"""

from __future__ import annotations

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from libskylark_tpu import SketchContext
from libskylark_tpu.core.random import sample_window
from libskylark_tpu.ml import GaussianKernel, KrrParams, streaming_kernel_ridge

N, D, S = 10_000_000, 4096, 2048
BR = 125_000  # 80 panels
LAM = 0.1


def run_host_streamed(sweeps: int, pool=None, y=None, sigma=8.0):
    """Host-RAM-pool variant: real device_put per panel visit.

    ``pool``/``y`` are injectable for the parity test
    (tests/test_ml.py): the logical matrix is
    ``vstack(pool[p % len(pool)] for p in range(N // BR))`` and the
    returned W must match ``large_scale_kernel_ridge`` on it.
    """
    from jax.scipy.linalg import cho_factor, cho_solve

    from libskylark_tpu.sketch.base import Dimension
    from libskylark_tpu.utils import PhaseTimer

    nb = N // BR
    # Distinct host panels cycled modulo the pool: every visit pays a
    # real host->device transfer of a full 1.02 GB bf16 panel; pool
    # size only bounds host RAM (content repeats like the hot variant).
    rng = np.random.default_rng(0)
    if pool is None:
        n_pool = int(os.environ.get("SKYLARK_HOST_POOL_PANELS", "4"))
        try:
            from ml_dtypes import bfloat16 as np_bf16
        except ImportError:  # ml_dtypes ships with jax
            np_bf16 = jnp.bfloat16
        pool = [
            rng.standard_normal((BR, D), dtype=np.float32).astype(np_bf16)
            for _ in range(n_pool)
        ]
    n_pool = len(pool)

    kernel = GaussianKernel(D, sigma=sigma)
    fmap = kernel.create_rft(S, "regular", SketchContext(seed=72))
    ops = fmap.hoistable_operands(jnp.bfloat16)
    if ops is not None:
        ops = jax.block_until_ready(ops)

    def _feat(ops, Xp):
        return fmap.apply_with_operands(ops, Xp, Dimension.ROWWISE)

    @jax.jit
    def panel_gram(ops, Xp, G):
        Z = _feat(ops, Xp)
        return G + jax.lax.dot_general(
            Z, Z, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @jax.jit
    def panel_zr(ops, Xp, Rp, acc):
        Z = _feat(ops, Xp)
        return acc + jax.lax.dot_general(
            Z, Rp, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @jax.jit
    def panel_apply(ops, Xp, Rp, delta):
        Z = _feat(ops, Xp)
        upd = jax.lax.dot_general(
            Z, delta.astype(Z.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return Rp - upd

    # Residual kept as nb device panels (40 MB total).
    if y is None:
        y = np.sign(rng.standard_normal(N)).astype(np.float32)
    R = [
        jax.device_put(np.asarray(y[p * BR : (p + 1) * BR]).reshape(-1, 1))
        for p in range(nb)
    ]
    W = jnp.zeros((S, 1), jnp.float32)

    def stream(visit_fn):
        """Double-buffered panel sweep: device_put of panel p+1 issued
        before the compute of panel p is consumed."""
        d_next = jax.device_put(pool[0])
        for p in range(nb):
            d_cur = d_next
            if p + 1 < nb:
                d_next = jax.device_put(pool[(p + 1) % n_pool])
            visit_fn(p, d_cur)

    # Transfer-only probe: bandwidth of the host link, for the overlap
    # characterization printed at the end.
    probe = jax.block_until_ready(jax.device_put(pool[0]))
    t0 = time.perf_counter()
    for i in range(4):
        probe = jax.block_until_ready(jax.device_put(pool[i % n_pool]))
    h2d_gbps = 4 * pool[0].nbytes / (time.perf_counter() - t0) / 1e9
    del probe

    timer = PhaseTimer()
    t_start = time.perf_counter()
    G = jnp.zeros((S, S), jnp.float32)
    factor = None
    for it in range(max(sweeps, 1)):
        with timer.phase("sweep0" if it == 0 else "sweep") as ph:
            if it == 0:
                def g_visit(p, Xp):
                    nonlocal G
                    G = panel_gram(ops, Xp, G)

                stream(g_visit)
                G = G + jnp.float32(LAM) * jnp.eye(S, dtype=jnp.float32)
                factor = cho_factor(jax.block_until_ready(G), lower=True)
            acc = jnp.zeros((S, 1), jnp.float32)

            def zr_visit(p, Xp):
                nonlocal acc
                acc = panel_zr(ops, Xp, R[p], acc)

            stream(zr_visit)
            delta = cho_solve(factor, acc - jnp.float32(LAM) * W)
            W = W + delta

            def ap_visit(p, Xp):
                R[p] = panel_apply(ops, Xp, R[p], delta)

            stream(ap_visit)
            ph.result = R[-1]
    total = time.perf_counter() - t_start
    per_sweep = timer.totals["sweep"] / max(timer.counts["sweep"], 1)
    print(timer.report())
    flops = 4 * N * D * S
    mfu = flops / per_sweep / 197e12
    bytes_sweep = 2 * nb * pool[0].nbytes  # 2 panel passes per sweep
    print(f"variant=host sweeps={sweeps} pool={n_pool} panels")
    print(f"total (incl compile + sweep0): {total:.1f} s")
    print(f"host->device probe bandwidth: {h2d_gbps:.2f} GB/s "
          f"(transfer-bound floor: {bytes_sweep / h2d_gbps / 1e9:.2f} s/sweep "
          f"for {bytes_sweep / 1e9:.0f} GB/sweep)")
    print(f"steady: {per_sweep:.2f} s/sweep, "
          f"feature-matmul MFU {mfu*100:.1f}% of v5e bf16 peak")
    return W


def main():
    variant = sys.argv[1] if len(sys.argv) > 1 else "hot"
    sweeps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    max_split = int(sys.argv[3]) if len(sys.argv) > 3 else 1024

    if variant == "host":
        return run_host_streamed(sweeps)

    ctx_data = SketchContext(seed=71)
    base = ctx_data.reserve(N * D)

    block_args = ()
    if variant == "hot":
        # Generate the resident panel in slices: a single (BR, D)
        # Box-Muller draw transiently allocates several x its output.
        gen = jax.jit(
            lambda s0: sample_window(
                "normal", ctx_data.seed, base, (N, D),
                offset=(s0, 0), shape=(BR // 10, D), dtype=jnp.bfloat16,
            )
        )
        X0 = jax.block_until_ready(
            jnp.concatenate([gen(jnp.int32(i * BR // 10)) for i in range(10)])
        )

        def block_fn(start, rows, X0):
            # Panel content must VARY with the panel index: a loop-
            # invariant return lets XLA hoist the whole feature
            # computation out of the panel fori_loop (measured "167%
            # MFU" — LICM, not compute).  A row ROTATION is not
            # algebraically reducible (a scalar multiple or additive
            # shift could be commuted through the dot and re-hoisted);
            # cost is one extra HBM pass, the same traffic a real
            # IO-streamed panel would cost.
            return jnp.roll(X0, start // rows, axis=0)

        block_args = (X0,)
    else:
        def block_fn(start, rows):
            return sample_window(
                "normal", ctx_data.seed, base, (N, D),
                offset=(start, 0), shape=(rows, D), dtype=jnp.bfloat16,
            )

    # Labels: cheap synthetic (sign of a fixed random projection of the
    # first panel pattern) — content does not matter for the timing.
    y = jax.block_until_ready(
        jnp.asarray(
            np.sign(np.random.default_rng(0).standard_normal(N)), jnp.float32
        )
    )

    kernel = GaussianKernel(D, sigma=8.0)
    params = KrrParams(max_split=max_split, iter_lim=sweeps, tolerance=0.0)

    from libskylark_tpu.utils import PhaseTimer

    timer = PhaseTimer()
    t0 = time.perf_counter()
    model = streaming_kernel_ridge(
        kernel, block_fn, (N, D), y, LAM, S, SketchContext(seed=72),
        params, block_rows=BR, feature_dtype=jnp.bfloat16,
        block_args=block_args, timer=timer,
    )
    jax.block_until_ready(model.W)
    total = time.perf_counter() - t0
    per_sweep = timer.totals["sweep"] / timer.counts["sweep"]
    print(timer.report())

    # Dominant matmul flops per sweep: 2 panel passes per chunk, each
    # applying the chunk's feature map (2*n*d*sz) + the small Z-R ops.
    flops = 4 * N * D * S  # = 2 passes * 2*N*D*S total feature flops
    mfu = flops / per_sweep / 197e12
    print(f"variant={variant} sweeps={sweeps}")
    print(f"total (incl compile + sweep0): {total:.1f} s")
    print(f"steady: {per_sweep:.2f} s/sweep, "
          f"feature-matmul MFU {mfu*100:.1f}% of v5e bf16 peak")


if __name__ == "__main__":
    main()
