"""North-star single-chip run: 10M x 4096 random-feature KRR, bf16,
rows AND features streamed (ml/krr.py::streaming_kernel_ridge).

Two variants (both honest, measuring different bounds):
- "hot-panel": one resident 250k x 4096 bf16 panel reused for every
  logical row panel — data content repeats, compute/memory contract is
  exactly the 10M-row sweep.  Measures the COMPUTE path's s/sweep + MFU.
- "generated": every panel counter-generated (Box-Muller) per visit —
  true streamed synthetic data; generation-bound, like the streaming-SVD
  benchmark (BASELINE.md round 1 notes), a real IO-streamed workload
  would be storage-bound the same way.

Run: python experiments/northstar_krr.py [hot|gen] [sweeps]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from libskylark_tpu import SketchContext
from libskylark_tpu.core.random import sample_window
from libskylark_tpu.ml import GaussianKernel, KrrParams, streaming_kernel_ridge

N, D, S = 10_000_000, 4096, 2048
BR = 125_000  # 80 panels
LAM = 0.1


def main():
    variant = sys.argv[1] if len(sys.argv) > 1 else "hot"
    sweeps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    max_split = int(sys.argv[3]) if len(sys.argv) > 3 else 1024

    ctx_data = SketchContext(seed=71)
    base = ctx_data.reserve(N * D)

    block_args = ()
    if variant == "hot":
        # Generate the resident panel in slices: a single (BR, D)
        # Box-Muller draw transiently allocates several x its output.
        gen = jax.jit(
            lambda s0: sample_window(
                "normal", ctx_data.seed, base, (N, D),
                offset=(s0, 0), shape=(BR // 10, D), dtype=jnp.bfloat16,
            )
        )
        X0 = jax.block_until_ready(
            jnp.concatenate([gen(jnp.int32(i * BR // 10)) for i in range(10)])
        )

        def block_fn(start, rows, X0):
            # Panel content must VARY with the panel index: a loop-
            # invariant return lets XLA hoist the whole feature
            # computation out of the panel fori_loop (measured "167%
            # MFU" — LICM, not compute).  A row ROTATION is not
            # algebraically reducible (a scalar multiple or additive
            # shift could be commuted through the dot and re-hoisted);
            # cost is one extra HBM pass, the same traffic a real
            # IO-streamed panel would cost.
            return jnp.roll(X0, start // rows, axis=0)

        block_args = (X0,)
    else:
        def block_fn(start, rows):
            return sample_window(
                "normal", ctx_data.seed, base, (N, D),
                offset=(start, 0), shape=(rows, D), dtype=jnp.bfloat16,
            )

    # Labels: cheap synthetic (sign of a fixed random projection of the
    # first panel pattern) — content does not matter for the timing.
    y = jax.block_until_ready(
        jnp.asarray(
            np.sign(np.random.default_rng(0).standard_normal(N)), jnp.float32
        )
    )

    kernel = GaussianKernel(D, sigma=8.0)
    params = KrrParams(max_split=max_split, iter_lim=sweeps, tolerance=0.0)

    from libskylark_tpu.utils import PhaseTimer

    timer = PhaseTimer()
    t0 = time.perf_counter()
    model = streaming_kernel_ridge(
        kernel, block_fn, (N, D), y, LAM, S, SketchContext(seed=72),
        params, block_rows=BR, feature_dtype=jnp.bfloat16,
        block_args=block_args, timer=timer,
    )
    jax.block_until_ready(model.W)
    total = time.perf_counter() - t0
    per_sweep = timer.totals["sweep"] / timer.counts["sweep"]
    print(timer.report())

    # Dominant matmul flops per sweep: 2 panel passes per chunk, each
    # applying the chunk's feature map (2*n*d*sz) + the small Z-R ops.
    flops = 4 * N * D * S  # = 2 passes * 2*N*D*S total feature flops
    mfu = flops / per_sweep / 197e12
    print(f"variant={variant} sweeps={sweeps}")
    print(f"total (incl compile + sweep0): {total:.1f} s")
    print(f"steady: {per_sweep:.2f} s/sweep, "
          f"feature-matmul MFU {mfu*100:.1f}% of v5e bf16 peak")


if __name__ == "__main__":
    main()
