"""Round-3 probe: Fastfood + PPT TPU cost, current paths vs matmul re-designs.

Run on the real chip.  Not part of the package — measurement scratch that
informs sketch/frft.py + sketch/ppt.py design (VERDICT r2 item 1).

Findings (v5e, m=131072 n=4096):
- streaming Fastfood (two XLA WHTs + permutation gather):
  s=2048: 33.95 ms bf16 / 65.14 ms f32;  s=4096: 37.98 / 66.76
- realized-W prototype (host-built W): bf16 22.84 ms, A-bf16 x W-split2
  26.70 ms, A-split3 x W-split2 (5-pass) 72.00 ms -> 4-pass chosen
- host-built W closures hit the axon tunnel's HTTP 413 body limit at
  s=4096 -> the package builds W IN-GRAPH from the counter stream
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.sketch.frft import FastGaussianRFT
from libskylark_tpu.sketch.ppt import PPT


def _timed(fn, *args) -> float:
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    return time.perf_counter() - t0


def rep_diff(build, A, r1=2, r2=6, rounds=8) -> float:
    f1, f2 = build(r1), build(r2)
    _timed(f1, A), _timed(f2, A)
    t1s, t2s = [], []
    for _ in range(rounds):
        t1s.append(_timed(f1, A))
        t2s.append(_timed(f2, A))
    t1, t2 = min(t1s), min(t2s)
    if t2 <= t1:
        return float("nan")
    return (t2 - t1) / (r2 - r1)


def frft_package(m, n, s, dtype):
    """Times whatever path the package selects (realized gemm on TPU)."""

    def build(reps):
        ctx = SketchContext(seed=7)
        sketches = [FastGaussianRFT(n, s, ctx, sigma=2.0) for _ in range(reps)]

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                acc += jnp.sum(jnp.abs(S.apply(A, "rowwise").astype(jnp.float32)))
            return acc

        return jax.jit(run)

    A = jax.random.normal(jax.random.PRNGKey(1), (m, n), dtype=dtype)
    return rep_diff(build, A)


def ppt_current(m, n, s, q, dtype, r1=1, r2=3):
    def build(reps):
        ctx = SketchContext(seed=9)
        sketches = [PPT(n, s, ctx, q=q) for _ in range(reps)]

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                acc += jnp.sum(jnp.abs(S.apply(A, "rowwise").astype(jnp.float32)))
            return acc

        return jax.jit(run)

    A = jax.random.normal(jax.random.PRNGKey(3), (m, n), dtype=dtype)
    return rep_diff(build, A, r1=r1, r2=r2, rounds=6)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    m, n = 131_072, 4096

    if which in ("all", "frft"):
        for s in (2048, 4096):
            for dt, name in ((jnp.bfloat16, "bf16"), (jnp.float32, "f32")):
                t = frft_package(m, n, s, dt)
                print(f"FRFT package m={m} n={n} s={s} {name}: {t*1e3:.2f} ms",
                      flush=True)

    if which in ("all", "ppt"):
        for dt, name in ((jnp.float32, "f32"), (jnp.bfloat16, "bf16")):
            t = ppt_current(m, n, 1024, 3, dt)
            print(f"PPT current m={m} n={n} s=1024 q=3 {name}: {t*1e3:.2f} ms",
                  flush=True)


if __name__ == "__main__":
    main()
