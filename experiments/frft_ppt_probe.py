"""Round-3 probe: Fastfood + PPT TPU cost, current paths vs matmul re-designs.

Run on the real chip.  Not part of the package — measurement scratch that
informs sketch/frft.py + sketch/ppt.py design (VERDICT r2 item 1).
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.sketch.frft import FastGaussianRFT
from libskylark_tpu.sketch.ppt import PPT
from libskylark_tpu.core.precision import bf16_split3


def _timed(fn, *args) -> float:
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    return time.perf_counter() - t0


def rep_diff(build, A, r1=2, r2=6, rounds=8) -> float:
    f1, f2 = build(r1), build(r2)
    _timed(f1, A), _timed(f2, A)
    t1s, t2s = [], []
    for _ in range(rounds):
        t1s.append(_timed(f1, A))
        t2s.append(_timed(f2, A))
    t1, t2 = min(t1s), min(t2s)
    if t2 <= t1:
        return float("nan")
    return (t2 - t1) / (r2 - r1)


# --------------------------------------------------------------------------
# Fastfood
# --------------------------------------------------------------------------


def frft_current(m, n, s, dtype):
    def build(reps):
        ctx = SketchContext(seed=7)
        sketches = [FastGaussianRFT(n, s, ctx, sigma=2.0) for _ in range(reps)]

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                acc += jnp.sum(jnp.abs(S.apply(A, "rowwise").astype(jnp.float32)))
            return acc

        return jax.jit(run)

    A = jax.random.normal(jax.random.PRNGKey(1), (m, n), dtype=dtype)
    return rep_diff(build, A)


def frft_realized(m, n, s, dtype, mode):
    """Realize W = Sm*H*G*P*H*B per block as a dense (s, n) matrix (cheap:
    nb x nb WHTs), then one MXU matmul + cos epilogue.

    mode: 'bf16' (W and A in bf16), 'split3x2' (A split3 x W split2, 6
    passes ~ f32-exact), 'split1x2' (A bf16 x W split2, 3 passes)."""

    def build(reps):
        ctx = SketchContext(seed=7)
        sketches = [FastGaussianRFT(n, s, ctx, sigma=2.0) for _ in range(reps)]
        Ws, shifts = [], []
        for S in sketches:
            W = S._features(jnp.eye(n, dtype=jnp.float32))  # (s, n) f32
            Ws.append(W)
            shifts.append(S._shifts(jnp.float32))
        outscale = sketches[0].outscale

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for W, sh in zip(Ws, shifts):
                if mode == "bf16":
                    V = jax.lax.dot_general(
                        A.astype(jnp.bfloat16), W.astype(jnp.bfloat16).T,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                elif mode == "split1x2":
                    w_hi, w_lo, _ = bf16_split3(W)
                    A16 = A.astype(jnp.bfloat16)
                    mm = lambda x, g: jax.lax.dot_general(
                        x, g.T, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    V = mm(A16, w_hi) + mm(A16, w_lo)
                else:  # split3x2
                    w_hi, w_lo, _ = bf16_split3(W)
                    a_hi, a_lo, a_lo2 = bf16_split3(A.astype(jnp.float32))
                    mm = lambda x, g: jax.lax.dot_general(
                        x, g.T, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    V = (mm(a_hi, w_hi) + mm(a_lo, w_hi) + mm(a_lo2, w_hi)
                         + mm(a_hi, w_lo) + mm(a_lo, w_lo))
                Z = outscale * jnp.cos(V + sh[None, :])
                acc += jnp.sum(jnp.abs(Z))
            return acc

        return jax.jit(run)

    A = jax.random.normal(jax.random.PRNGKey(1), (m, n), dtype=dtype)
    return rep_diff(build, A)


def frft_accuracy(n, s):
    """Max-rel error of realized-W modes vs the f32 streaming path."""
    ctx = SketchContext(seed=7)
    S = FastGaussianRFT(n, s, ctx, sigma=2.0)
    A = jax.random.normal(jax.random.PRNGKey(2), (256, n), jnp.float32)
    ref = S.apply(A, "rowwise")
    W = S._features(jnp.eye(n, dtype=jnp.float32))
    sh = S._shifts(jnp.float32)
    out = {}
    Vb = jax.lax.dot_general(
        A.astype(jnp.bfloat16), W.astype(jnp.bfloat16).T,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    out["bf16"] = Vb
    w_hi, w_lo, _ = bf16_split3(W)
    a_hi, a_lo, a_lo2 = bf16_split3(A)
    mm = lambda x, g: jax.lax.dot_general(
        x, g.T, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    out["split1x2"] = mm(A.astype(jnp.bfloat16), w_hi) + mm(A.astype(jnp.bfloat16), w_lo)
    out["split3x2"] = (mm(a_hi, w_hi) + mm(a_lo, w_hi) + mm(a_lo2, w_hi)
                       + mm(a_hi, w_lo) + mm(a_lo, w_lo))
    errs = {}
    for k, V in out.items():
        Z = S.outscale * jnp.cos(V + sh[None, :])
        errs[k] = float(jnp.max(jnp.abs(Z - ref)))
    return errs


# --------------------------------------------------------------------------
# PPT
# --------------------------------------------------------------------------


def ppt_current(m, n, s, q, dtype):
    def build(reps):
        ctx = SketchContext(seed=9)
        sketches = [PPT(n, s, ctx, q=q) for _ in range(reps)]

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                acc += jnp.sum(jnp.abs(S.apply(A, "rowwise").astype(jnp.float32)))
            return acc

        return jax.jit(run)

    A = jax.random.normal(jax.random.PRNGKey(3), (m, n), dtype=dtype)
    return rep_diff(build, A, r1=1, r2=3, rounds=6)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    m, n = 131_072, 4096

    if which in ("all", "frft"):
        for s in (2048, 4096):
            for dt, name in ((jnp.bfloat16, "bf16"), (jnp.float32, "f32")):
                t = frft_current(m, n, s, dt)
                print(f"FRFT current  m={m} n={n} s={s} {name}: {t*1e3:.2f} ms", flush=True)
        for s in (2048, 4096):
            for mode in ("bf16", "split1x2", "split3x2"):
                t = frft_realized(m, n, s, jnp.float32, mode)
                print(f"FRFT realized[{mode}] m={m} n={n} s={s}: {t*1e3:.2f} ms", flush=True)
        print("FRFT accuracy (vs f32 streaming, n=1024 s=2048):",
              frft_accuracy(1024, 2048), flush=True)

    if which in ("all", "ppt"):
        for dt, name in ((jnp.float32, "f32"), (jnp.bfloat16, "bf16")):
            t = ppt_current(m, n, 1024, 3, dt)
            print(f"PPT current m={m} n={n} s=1024 q=3 {name}: {t*1e3:.2f} ms", flush=True)


if __name__ == "__main__":
    main()
