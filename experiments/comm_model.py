"""Communication-cost model for the v5p-32 north-star claim (VERDICT r3 #5).

AOT-compiles the SAME per-chunk streaming-KRR programs the solver runs
(``ml/krr.py::streaming_krr_chunk_programs``) over a virtual 32-device
mesh at the north-star shape, reads every collective out of the compiled
HLO (op, element type, shape, and whether it sits inside the panel
``while`` loop), and prints a bytes-per-sweep table next to a v5p ICI
bound.  This turns the ">= 45% MFU on v5p-32" extrapolation into an
engineering estimate with a numbered communication budget — no
multi-chip hardware required (the reference gets the analogous regime
from Elemental's distributed GEMMs, ``ml/krr.hpp:546``).

Run: ``python experiments/comm_model.py`` (forces 32 virtual CPU
devices; CPU-only, compile-only — nothing is executed).
"""

from __future__ import annotations

import os
import re
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=32"
).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.ml import GaussianKernel, KrrParams
from libskylark_tpu.ml.krr import _chunk_sizes, _tag, streaming_krr_chunk_programs
from libskylark_tpu.parallel import ROWS, constrain_rows, make_mesh

# North-star shape, adjusted so the panel splits evenly over 32 chips
# (10.24M rows instead of 10M; same flop density).
N_DEV = 32
N, D, S, BR = 10_240_000, 4096, 2048, 128_000
T = 1  # targets

# v5p public specs: 459 TFLOP/s bf16 per chip; ICI ~4800 Gbps/chip
# aggregate (3-D torus).  Effective all-reduce bandwidth per chip is the
# bidirectional ring figure; 2(p-1)/p ~ 2 is the classic ring factor.
V5P_PEAK_TFLOPS = 459.0
V5P_ICI_GBPS = 600.0  # GB/s per chip, aggregate
MEASURED_V5E_MFU = 0.632  # BASELINE.md round-3 single-chip measurement

_BYTES = {"f32": 4, "bf16": 2, "f64": 8, "s32": 4, "u32": 4, "pred": 1}

_COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)


def _find_collectives(hlo_text: str):
    """Yield (computation, op, dtype, shape, bytes) for every collective
    instruction in the compiled HLO."""
    comp = "?"
    for line in hlo_text.splitlines():
        m = re.match(r"\s*%?([\w.\-]+)\s*\([^)]*\)\s*->", line)
        if line.strip().startswith(("ENTRY", "%")) and "{" in line and "->" in line:
            m2 = re.search(r"%?([\w.\-]+)\s*\(", line)
            if m2:
                comp = m2.group(1)
        for op in _COLLECTIVES:
            # e.g.:  %all-reduce.3 = f32[2048,2048]{1,0} all-reduce(...)
            m3 = re.search(
                rf"=\s*(\w+)\[([\d,]*)\][^ ]*\s+{op}(?:-start)?\(", line
            )
            if m3:
                dtype, dims = m3.group(1), m3.group(2)
                shape = tuple(int(x) for x in dims.split(",") if x) or (1,)
                nbytes = int(np.prod(shape)) * _BYTES.get(dtype, 4)
                yield comp, op, dtype, shape, nbytes


def main() -> None:
    assert len(jax.devices()) >= N_DEV, jax.devices()
    mesh = make_mesh((N_DEV,), (ROWS,))
    nb = N // BR

    kernel = GaussianKernel(D, sigma=8.0)
    params = KrrParams(max_split=0)
    sizes = _chunk_sizes(D, S, params)
    ctx = SketchContext(seed=72)
    maps = [kernel.create_rft(sz, _tag(params), ctx) for sz in sizes]

    def block_fn(start, rows):
        # Panel content is irrelevant to the communication structure; a
        # cheap deterministic fill stands in for the counter stream.
        # The sharding constraint is the load-bearing part: panels are
        # data-parallel over the mesh rows, exactly as the sharded bench
        # variant runs them (__graft_entry__.dryrun_multichip).
        base = jax.lax.broadcasted_iota(jnp.bfloat16, (rows, D), 0)
        panel = base * jnp.bfloat16(1e-6) + jnp.bfloat16(
            start.astype(jnp.float32) * 1e-9
            if hasattr(start, "astype")
            else start * 1e-9
        )
        return constrain_rows(panel, mesh)

    gram, zr, apply_delta = streaming_krr_chunk_programs(
        maps, 0, sizes[0], nb, BR, T, 0.1, block_fn, jnp.bfloat16
    )

    # Panel-major residual (nb, BR, T): panel axis unsharded, panel rows
    # data-parallel over the mesh — matching the Z panel constraint.
    row_sh = NamedSharding(mesh, P(None, ROWS, None))
    rep_sh = NamedSharding(mesh, P())
    R_spec = jax.ShapeDtypeStruct((nb, BR, T), jnp.float32, sharding=row_sh)
    W_spec = jax.ShapeDtypeStruct((sizes[0], T), jnp.float32, sharding=rep_sh)
    d_spec = jax.ShapeDtypeStruct((sizes[0], T), jnp.float32, sharding=rep_sh)

    programs = {
        "gram (once, sweep 0)": (gram, ()),
        "zr (per sweep)": (zr, (R_spec, W_spec)),
        "apply_delta (per sweep)": (apply_delta, (R_spec, d_spec)),
    }

    report = {}
    for name, (fn, specs) in programs.items():
        compiled = fn.lower(*specs).compile()
        text = compiled.as_text()
        rows = list(_find_collectives(text))
        # A collective inside the panel while-loop body runs nb times.
        total = 0
        table = []
        for comp, op, dtype, shape, nbytes in rows:
            in_loop = "while" in comp or "body" in comp
            mult = nb if in_loop else 1
            total += nbytes * mult
            table.append((op, dtype, shape, nbytes, in_loop, mult))
        report[name] = (table, total)

    print(f"# Streaming-KRR collectives on a {N_DEV}-device mesh")
    print(f"# shape: N={N} d={D} S={S} block_rows={BR} nb={nb} bf16 panels\n")
    sweep_bytes = 0
    for name, (table, total) in report.items():
        print(f"{name}: total {total / 1e6:.3f} MB over ICI")
        for op, dtype, shape, nbytes, in_loop, mult in table:
            loc = f"x{mult} (panel loop)" if in_loop else "x1"
            print(f"  {op:<20} {dtype}{list(shape)} {nbytes / 1e3:.1f} kB {loc}")
        if not table:
            print("  (no collectives)")
        if "per sweep" in name:
            sweep_bytes += total
        print()

    # -- the bound ---------------------------------------------------------
    flops_sweep = 2 * 2.0 * N * D * S  # two feature-matmul passes per sweep
    per_chip_flops = flops_sweep / N_DEV
    t_compute = per_chip_flops / (V5P_PEAK_TFLOPS * 1e12 * MEASURED_V5E_MFU)
    # ring all-reduce: each chip moves 2(p-1)/p ~ 2 bytes per payload byte
    t_comm = 2.0 * sweep_bytes / (V5P_ICI_GBPS * 1e9)
    # per-collective launch latency, ~10 us each, counting loop trips
    n_colls = sum(
        (nb if in_loop else 1)
        for name, (table, _) in report.items()
        if "per sweep" in name
        for (_, _, _, _, in_loop, _) in table
    )
    t_lat = n_colls * 10e-6
    mfu_bound = MEASURED_V5E_MFU * t_compute / (t_compute + t_comm + t_lat)
    print("# v5p-32 bound")
    print(f"compute/sweep/chip: {per_chip_flops:.3e} flop "
          f"-> {t_compute * 1e3:.1f} ms at {MEASURED_V5E_MFU:.1%} of "
          f"{V5P_PEAK_TFLOPS:.0f} TF/s")
    print(f"comm/sweep: {sweep_bytes / 1e6:.3f} MB payload -> "
          f"{t_comm * 1e3:.3f} ms at {V5P_ICI_GBPS:.0f} GB/s "
          f"+ {t_lat * 1e3:.3f} ms latency ({n_colls} collectives)")
    print(f"==> bounded MFU on v5p-32: {mfu_bound:.1%} "
          f"(flagship bar: 45%)")


if __name__ == "__main__":
    main()
