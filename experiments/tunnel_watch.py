"""Tunnel watcher: poll the axon TPU backend until it answers, then run
the full hardware measurement battery (``hw_session.py``) exactly once.

The shared tunnel comes and goes (round-4 lost its entire hardware
artifact to a down window); this watcher turns "retry by hand until a
quiet window opens" into a detached loop.  Each probe is a subprocess
with its own timeout — a hung init costs one probe, not the watcher.

Run: ``python experiments/tunnel_watch.py [max_hours]`` (default 11).
Writes state to ``experiments/logs/tunnel_watch.log`` and the battery's
own per-stage logs next to it.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOGDIR = os.path.join(REPO, "experiments", "logs")
PROBE_TIMEOUT_S = 600  # live-tunnel init has been observed at 300-900 s
SLEEP_S = 180

PROBE = (
    "import time, jax\n"
    "t0 = time.time()\n"
    "d = jax.devices()[0]\n"
    "print('UP', d.platform, d.device_kind, 'init_s=%.1f' % (time.time()-t0),"
    " flush=True)\n"
)


def main() -> int:
    max_hours = float(sys.argv[1]) if len(sys.argv) > 1 else 11.0
    os.makedirs(LOGDIR, exist_ok=True)
    deadline = time.monotonic() + 3600.0 * max_hours
    logpath = os.path.join(LOGDIR, "tunnel_watch.log")
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    attempt = 0
    with open(logpath, "a") as log:
        def say(msg: str) -> None:
            stamp = time.strftime("%H:%M:%S")
            log.write(f"[{stamp}] {msg}\n")
            log.flush()
            print(f"[{stamp}] {msg}", flush=True)

        say(f"watcher start, budget {max_hours:.1f} h")
        while time.monotonic() < deadline:
            attempt += 1
            try:
                out = subprocess.run(
                    [sys.executable, "-c", PROBE],
                    capture_output=True, text=True,
                    timeout=PROBE_TIMEOUT_S, env=env, cwd=REPO,
                )
                if out.returncode == 0 and "UP" in out.stdout:
                    say(f"probe {attempt}: {out.stdout.strip().splitlines()[-1]}")
                    say("tunnel is up -> running hw_session battery")
                    rc = subprocess.run(
                        [sys.executable, "experiments/hw_session.py"],
                        stdout=log, stderr=subprocess.STDOUT,
                        env=env, cwd=REPO,
                    ).returncode
                    say(f"hw_session done rc={rc}")
                    return rc
                tail = (out.stderr or out.stdout).strip().splitlines()
                say(
                    f"probe {attempt}: down (rc={out.returncode}) "
                    + (tail[-1][:160] if tail else "")
                )
            except subprocess.TimeoutExpired:
                say(f"probe {attempt}: hung > {PROBE_TIMEOUT_S}s (killed)")
            time.sleep(SLEEP_S)
        say("watcher budget exhausted, tunnel never answered")
        return 2


if __name__ == "__main__":
    raise SystemExit(main())
