"""On-chip probe for the fused sampled-FJLT kernel (VERDICT r4 item 5).

Measures, at the acknowledged f32 large-S floor shape
(128K x 4096 -> 1024, 44.8 ms measured r2 on the two-step path):
  1. the two-step path (Pallas WHT -> full (m, NB) in HBM -> XLA
     sampled gather) — the current floor;
  2. the fused kernel (selection + rescale in the epilogue, only
     (m, S) ever written) — target < 40 ms;
  3. the SRHT 3-pass bf16-split matmul for reference (the gate's
     other contender; measured r2 as losing at this shape);
  4. parity of 1-vs-2 on the live chip (the lane-gather lowering is
     the open question — a Mosaic refusal shows up here as the probe
     warning + identical timings).

Run on the bench chip: ``python experiments/fjlt_fused_probe.py
[m] [n] [s]``.  Results decide whether the `_sampled_kernel_compiles`
gate ships enabled and recalibrate ``_GEMM_FPB`` if needed.
"""

from __future__ import annotations

import os
import sys
import time

import jax

# The axon sitecustomize force-sets jax_platforms; restore env semantics
# so a CPU smoke run (JAX_PLATFORMS=cpu) cannot hang on a down tunnel.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from libskylark_tpu import SketchContext
from libskylark_tpu.sketch import FJLT
from libskylark_tpu.sketch import fjlt as fjlt_mod
from libskylark_tpu.sketch import pallas_fut


def timed(tag, fn, *args, reps=5):
    out = jax.block_until_ready(fn(*args))  # compile
    best = min(
        (lambda t0: (jax.block_until_ready(fn(*args)),
                     time.perf_counter() - t0))(time.perf_counter())[1]
        for _ in range(reps)
    )
    print(f"{tag:<44} {best * 1e3:9.2f} ms", flush=True)
    return out, best


def main():
    m = int(sys.argv[1]) if len(sys.argv) > 1 else 131_072
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    s = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
    dev = jax.devices()[0]
    print(f"device={dev} shape {m}x{n}->{s} f32", flush=True)

    S1 = FJLT(n, s, SketchContext(seed=9))
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))
    jax.block_until_ready(A)
    D = S1._rfut.diagonal(jnp.float32)
    with jax.ensure_compile_time_eval():
        idx = np.asarray(S1._ust.samples, np.int32)

    ok_shape = pallas_fut.supported_sampled(m, n, S1._nb, s)
    tile = pallas_fut._tile_rows(m, S1._nb)
    if tile is None:
        print(
            f"shape {m}x{n} has no qualifying row tile — neither kernel "
            "path applies; nothing to measure", flush=True,
        )
        return

    def probe() -> bool:
        # supported_sampled guarantees tile is not None on this branch;
        # an unsupported shape must not crash the battery stage.
        return ok_shape and fjlt_mod._sampled_kernel_compiles(
            jnp.float32, S1._nb, s, tile
        )

    print(f"supported_sampled: {ok_shape}  probe: {probe()}", flush=True)

    def two_step(x):
        T = pallas_fut.rfut_rowwise(x, D, S1._nb)
        return jnp.float32(np.sqrt(S1._nb / s)) * T[:, jnp.asarray(idx)]

    out_two, t_two = timed("two-step (WHT kernel + XLA gather)",
                           jax.jit(two_step), A)

    if probe():
        fused = jax.jit(
            lambda x: pallas_fut.rfut_rowwise_sampled(x, D, S1._nb, idx)
        )
        out_f, t_f = timed("fused sampled kernel", fused, A)
        err = float(jnp.max(jnp.abs(out_f - out_two)))
        print(f"parity |fused - two-step| max = {err:g}", flush=True)
        print(f"speedup: {t_two / t_f:.2f}x", flush=True)
    else:
        print("fused kernel unavailable (see probe warning above)",
              flush=True)

    if S1.n * s <= fjlt_mod._GEMM_MAX_ELEMENTS:
        gemm = jax.jit(lambda x: S1._apply_srht_gemm(x, rowwise=True))
        timed("SRHT 3-pass bf16-split matmul", gemm, A)


if __name__ == "__main__":
    main()
