"""Anatomy of one streaming-KRR panel pass on v5e: where the s/sweep
beyond the 1.7 s matmul roofline goes."""

import time

import jax
import jax.numpy as jnp
import numpy as np

from libskylark_tpu import SketchContext
from libskylark_tpu.ml import GaussianKernel
from libskylark_tpu.sketch.base import Dimension

N, D, SZ, BR = 10_000_000, 4096, 2048, 125_000
NB = N // BR


def timed(f, *a):
    t0 = time.perf_counter()
    np.asarray(f(*a))
    return time.perf_counter() - t0


def bench(name, build, *args, reps=3):
    f = jax.jit(build)
    timed(f, *args)
    t = min(timed(f, *args) for _ in range(reps))
    print(f"{name}: {t:.3f} s", flush=True)
    return t


def main():
    kernel = GaussianKernel(D, sigma=8.0)
    fmap = kernel.create_rft(SZ, "regular", SketchContext(seed=72))
    X0 = jax.block_until_ready(
        jax.random.normal(jax.random.PRNGKey(0), (BR, D), jnp.bfloat16))
    R = jax.block_until_ready(
        jax.random.normal(jax.random.PRNGKey(1), (N, 1), jnp.float32))

    # (a) pure panel matmuls, no feature map: X0 @ Wfixed
    Wf = jax.block_until_ready(
        jax.random.normal(jax.random.PRNGKey(2), (D, SZ), jnp.bfloat16))

    def pure_mm(X0, Wf):
        def body(p, acc):
            scale = (jnp.float32(1.0) + p.astype(jnp.float32) / 256.0)
            Xp = X0 * scale.astype(jnp.bfloat16)
            Zp = jax.lax.dot_general(Xp, Wf, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            return acc + jnp.sum(jnp.abs(Zp[:, :8]))
        return jax.lax.fori_loop(0, NB, body, jnp.zeros((), jnp.float32))

    bench("a) 80 panels scale+matmul only", pure_mm, X0, Wf)

    # (b) + cos epilogue in bf16 (the RFT output)
    def mm_cos(X0, Wf):
        def body(p, acc):
            scale = (jnp.float32(1.0) + p.astype(jnp.float32) / 256.0)
            Xp = X0 * scale.astype(jnp.bfloat16)
            Zp = jax.lax.dot_general(Xp, Wf, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            Zp = jnp.cos(Zp).astype(jnp.bfloat16)
            return acc + jnp.sum(jnp.abs(Zp[:, :8].astype(jnp.float32)))
        return jax.lax.fori_loop(0, NB, body, jnp.zeros((), jnp.float32))

    bench("b) + cos epilogue", mm_cos, X0, Wf)

    # (c) the real feature map (counter-realized W per panel) + Zp @ Rp
    def real_pass(X0, R):
        def body(p, acc):
            scale = (jnp.float32(1.0) + p.astype(jnp.float32) / 256.0)
            Xp = X0 * scale.astype(jnp.bfloat16)
            Zp = fmap.apply(Xp, Dimension.ROWWISE).T  # (SZ, BR)
            Rp = jax.lax.dynamic_slice(R, (p * BR, 0), (BR, 1))
            return acc + jnp.dot(Zp.astype(jnp.float32), Rp,
                                 precision="highest")
        return jax.lax.fori_loop(0, NB, body,
                                 jnp.zeros((SZ, 1), jnp.float32))

    bench("c) real feature map + Zp@Rp", real_pass, X0, R)

    # (d) feature map WITHOUT the .T (layout probe)
    def real_pass_noT(X0, R):
        def body(p, acc):
            scale = (jnp.float32(1.0) + p.astype(jnp.float32) / 256.0)
            Xp = X0 * scale.astype(jnp.bfloat16)
            Zp = fmap.apply(Xp, Dimension.ROWWISE)  # (BR, SZ)
            Rp = jax.lax.dynamic_slice(R, (p * BR, 0), (BR, 1))
            return acc + jnp.dot(Zp.T.astype(jnp.float32), Rp,
                                 precision="highest")
        return jax.lax.fori_loop(0, NB, body,
                                 jnp.zeros((SZ, 1), jnp.float32))

    bench("d) same, transpose at use site", real_pass_noT, X0, R)


if __name__ == "__main__":
    main()
