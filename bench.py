"""Headline benchmark: dense JLT sketch-apply throughput (TFLOP/s per chip).

Run by the driver on real TPU hardware at round end.  Prints exactly ONE
JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

The metric is the BASELINE.json headline, "sketch-apply TFLOPS/chip" for a
JLT dense sketch: counter-based on-the-fly realization of Omega (generated
inside the fused program, never an HBM input) + bf16 MXU matmul.
``vs_baseline`` is measured TFLOP/s over the chip's bf16 peak (MFU), since
the reference publishes no numbers to beat (BASELINE.md).

Timing notes: the axon TPU tunnel does not block in ``block_until_ready``,
so all timings force a scalar readback; R independent sketch applies (each
with a distinct counter block, so XLA cannot CSE them) run inside ONE jitted
call, and the tunnel round-trip is cancelled by differencing two rep counts.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.sketch.dense import JLT


def _peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197.0
    if "v5p" in kind or "v5" in kind:
        return 459.0
    if "v6" in kind:
        return 918.0
    if "v4" in kind:
        return 275.0
    return 1.0  # CPU: report raw TFLOP/s


def _build(n, s, reps):
    ctx = SketchContext(seed=92)
    sketches = [JLT(n, s, ctx) for _ in range(reps)]

    def run(A):
        acc = jnp.zeros((), jnp.float32)
        for S in sketches:
            out = S.apply(A, "rowwise")
            # Full reduction so XLA cannot dead-code-eliminate any output tile.
            acc = acc + jnp.sum(out.astype(jnp.float32))
        return acc

    return jax.jit(run)


def _timed(fn, A) -> float:
    t0 = time.perf_counter()
    np.asarray(fn(A))  # readback forces execution through the tunnel
    return time.perf_counter() - t0


def main() -> None:
    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    if on_tpu:
        m, n, s = 262_144, 4096, 1024
        dtype = jnp.bfloat16
    else:
        m, n, s = 16_384, 1024, 256
        dtype = jnp.float32

    r1, r2 = 4, 12
    f1, f2 = _build(n, s, r1), _build(n, s, r2)
    A = jax.random.normal(jax.random.PRNGKey(0), (m, n), dtype=dtype)
    _timed(f1, A), _timed(f2, A)  # compile both

    # The shared tunnel/host adds multi-ms positive jitter; with
    # min-plus-noise timing the unbiased move is to pool MANY interleaved
    # trials and difference the two pooled minima once (min over per-round
    # differences would select noise and bias the headline high).
    t1s, t2s = [], []
    for _ in range(15):
        t1s.append(_timed(f1, A))
        t2s.append(_timed(f2, A))
    t1, t2 = min(t1s), min(t2s)
    if t2 <= t1:
        raise RuntimeError(
            f"benchmark timing inconsistent (t1={t1:.4f}s >= t2={t2:.4f}s); "
            "rerun on a quieter machine"
        )
    per_apply = (t2 - t1) / (r2 - r1)

    flops = 2.0 * m * n * s
    tflops = flops / per_apply / 1e12
    peak = _peak_tflops(dev)
    print(
        json.dumps(
            {
                "metric": "JLT dense sketch-apply throughput",
                "value": round(tflops, 3),
                "unit": "TFLOP/s/chip",
                "vs_baseline": round(tflops / peak, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
