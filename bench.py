"""Headline benchmarks, one JSON line per BASELINE.md config.

Run by the driver on real TPU hardware at round end.  Emits one JSON line
``{"metric", "value", "unit", "vs_baseline"}`` per headline config; the
LAST line is the headline metric (JLT dense sketch-apply TFLOP/s) and
carries the full table again under ``"submetrics"`` so a driver that
parses only the final line still records everything.

``vs_baseline`` semantics per line:
- the JLT headline reports measured TFLOP/s over the chip's bf16 peak
  (MFU) — the reference publishes no numbers to beat (BASELINE.md);
- every other line reports ``recorded / measured`` for times (≥ 1 means
  this round matched or beat the round-1 recorded value in BASELINE.md).

Budget discipline (round 4 — the round-3 driver capture died rc=124 with
the headline scheduled last, losing the most important rows): the two
flagship configs (JLT headline, north-star streaming KRR) run FIRST,
secondaries follow in descending importance, and a global wall-clock
budget (``SKYLARK_BENCH_BUDGET_S``, default 1500 s — deliberately under
any plausible driver timeout) governs the rest: pooling stops extending
when the deadline nears, configs that cannot fit emit an explicit
``"skipped: budget"`` row instead of dying mid-list, and a SIGTERM from
an outer timeout still flushes the final headline+submetrics line.

Timing notes: the axon TPU tunnel does not block in ``block_until_ready``,
so all timings force a scalar readback; R independent applies (each with a
distinct counter block, so XLA cannot CSE them) run inside ONE jitted
call, and the tunnel round-trip is cancelled by differencing two rep
counts, pooling minima over many interleaved rounds (min-plus-noise: the
unbiased move is one difference of pooled minima).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from libskylark_tpu.core.context import SketchContext

_T0 = time.monotonic()
_BUDGET_S = float(os.environ.get("SKYLARK_BENCH_BUDGET_S", "1500"))

# Global-budget slice the accelerator init loop must LEAVE for the CPU
# fallback (init + the CPU-sized config list).  BENCH_r05: the init loop
# burned the whole 1500 s budget on a hung tunnel and the fallback never
# got to run, so the round recorded -1 rows despite the fallback existing.
_FALLBACK_MARGIN_S = float(
    os.environ.get("SKYLARK_BENCH_FALLBACK_MARGIN_S", "120")
)

# Smoke mode (``SKYLARK_BENCH_SMOKE=1``): tiny dims and minimal pooling,
# so a subprocess regression test can drive the WHOLE artifact path —
# init, fallback, headline, final line — in seconds.  The numbers are
# meaningless; the contract (valid JSON rows, no -1 when a CPU exists)
# is what's under test.
_SMOKE = os.environ.get("SKYLARK_BENCH_SMOKE") == "1"

# Config filter (``SKYLARK_BENCH_ONLY=<substring>``): non-headline
# configs whose name does not contain the substring emit an explicit
# ``skipped: filter`` row instead of running.  The headline always runs
# — the final-line artifact contract does not bend to the filter.
_ONLY = os.environ.get("SKYLARK_BENCH_ONLY") or None


def _selected(name: str) -> bool:
    return _ONLY is None or _ONLY in name


def _remaining() -> float:
    """Seconds left in the global bench budget."""
    return _BUDGET_S - (time.monotonic() - _T0)


def _peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197.0
    if "v5p" in kind or "v5" in kind:
        return 459.0
    if "v6" in kind:
        return 918.0
    if "v4" in kind:
        return 275.0
    return 1.0  # CPU: report raw TFLOP/s


def _timed(fn, *args) -> float:
    t0 = time.perf_counter()
    np.asarray(fn(*args))  # readback forces execution through the tunnel
    return time.perf_counter() - t0


_LAST_CONTENTION: float | None = None


def _rep_diff(build, A, r1=4, r2=16, rounds=25, max_bursts=4) -> float:
    """Seconds per single apply, by differencing two rep counts.

    ``build(k)`` must return a jitted callable running k independent
    applies of the op under test, reduced to a scalar.

    Contention-adaptive pooling (round 3): minima are pooled per burst
    with pauses in between; if the burst-to-burst spread of the derived
    marginal stays ≤5% after two bursts the measurement is accepted,
    otherwise pooling extends (up to ``max_bursts``) to give transient
    host/tunnel contention more chances to clear — min-plus-noise
    justifies the final min across all bursts.  The residual spread is
    recorded in ``_LAST_CONTENTION`` and emitted with the metric, so a
    low driver capture is self-explaining (VERDICT r2 item 5).
    """
    global _LAST_CONTENTION
    _LAST_CONTENTION = None  # a failed config must not inherit a stale value
    if _SMOKE:
        # one burst, few rounds, small rep spread: enough that t2 > t1
        # holds on a quiet CPU, cheap enough for a subprocess test
        r1, r2, rounds, max_bursts = 2, 8, 3, 1
    args = A if isinstance(A, tuple) else (A,)
    f1, f2 = build(r1), build(r2)
    _timed(f1, *args), _timed(f2, *args)  # compile both
    t1s, t2s, per_burst = [], [], []
    for burst in range(max_bursts):
        if burst:
            # Budget-aware pooling (round 4): extending into another
            # burst is insurance against transient contention — worth
            # nothing if it pushes later configs past the deadline.
            if _remaining() < 60:
                break
            time.sleep(10)
        b1, b2 = [], []
        for i in range(rounds):
            b1.append(_timed(f1, *args))
            b2.append(_timed(f2, *args))
            # Keep pairs balanced: break between rounds only, and only
            # after enough rounds that a min is meaningful.
            if i >= 3 and _remaining() < 30:
                break
        t1s += b1
        t2s += b2
        if min(b2) > min(b1):
            per_burst.append((min(b2) - min(b1)) / (r2 - r1))
        if burst >= 1 and len(per_burst) >= 2:
            spread = (max(per_burst) - min(per_burst)) / min(per_burst)
            if spread <= 0.05:
                break
        if _remaining() < 60:
            break
    t1, t2 = min(t1s), min(t2s)
    if t2 <= t1:
        raise RuntimeError(
            f"benchmark timing inconsistent (t1={t1:.4f}s >= t2={t2:.4f}s); "
            "rerun on a quieter machine"
        )
    _LAST_CONTENTION = (
        round((max(per_burst) - min(per_burst)) / min(per_burst), 4)
        if len(per_burst) >= 2
        # Fewer than two bursts yielded a usable marginal: contention so
        # heavy the spread is unmeasurable — flag with -1 rather than
        # omitting the field (absent = custom-timing config, never
        # "noisy"; BASELINE.md round-3 integrity note).
        else -1.0
    )
    return (t2 - t1) / (r2 - r1)


# A CPU re-exec (see _cpu_fallback) starts a FRESH interpreter whose
# backend init trivially succeeds on cpu — the loop-guard env var is the
# only thing that carries the "this round is a fallback" fact across the
# exec boundary, so the tag is seeded from it.
_BACKEND_TAG: str | None = (
    "cpu-fallback"
    if os.environ.get("SKYLARK_BENCH_CPU_REEXEC") == "1"
    else None
)

# gRPC status tokens that mark a backend error as transient (tunnel flap,
# slow boot, device contention) rather than deterministic misconfiguration.
# Shared by the init retry loop and the mid-run rescue: both judge by
# token, not exact text (PJRT messages embed varying addresses).
_TRANSIENT_TOKENS = ("UNAVAILABLE", "DEADLINE", "RESOURCE_EXHAUSTED")


def _backend_died(e: BaseException) -> bool:
    """True when an exception looks like the accelerator backend dying
    under us (as opposed to a bug in the config being benched).  PJRT's
    "Unable to initialize backend" wrapper counts too: a first jax op
    that lazily initializes a dead plugin raises it WITHOUT any of the
    gRPC tokens in some plugin versions, and treating it as a config bug
    left the headline a -1 FAILED row on hosts with a healthy CPU."""
    msg = f"{type(e).__name__}: {e}"
    return (
        "Unable to initialize backend" in msg
        or any(t in msg for t in _TRANSIENT_TOKENS)
    )


def _resolved_backend() -> str:
    """Every artifact row self-identifies with the RESOLVED backend —
    fallback rows keep the "cpu-fallback" marker (the run is NOT on the
    accelerator the baselines were recorded on, and the driver must
    never compare one against a TPU baseline); ordinary rows carry the
    live jax backend so an artifact is interpretable without knowing
    which host produced it."""
    if _BACKEND_TAG is not None:
        return _BACKEND_TAG
    try:
        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 — backend dead: tag honestly
        return "unknown"


def _emit(metric, value, unit, vs_baseline, table, contention="auto"):
    row = {
        "metric": metric,
        "value": round(float(value), 4),
        "unit": unit,
        "vs_baseline": round(float(vs_baseline), 4),
    }
    row["backend"] = _resolved_backend()
    if contention == "auto":
        contention = _LAST_CONTENTION
    if contention is not None:
        # burst-to-burst spread of the marginal: ≤0.05 = quiet machine;
        # larger values flag host/tunnel contention the pooling could
        # not fully clear (the value is then a lower-confidence upper
        # bound on the true time).
        row["contention"] = contention
    table.append(row)
    print(json.dumps(row), flush=True)


# ---------------------------------------------------------------------------
# configs
# ---------------------------------------------------------------------------


def bench_jlt(on_tpu, table):
    """Headline: fused counter-generated Omega + bf16 MXU matmul."""
    from libskylark_tpu.sketch.dense import JLT

    if on_tpu:
        m, n, s, dtype = 262_144, 4096, 1024, jnp.bfloat16
    elif _SMOKE:
        m, n, s, dtype = 8_192, 512, 128, jnp.float32
    else:
        m, n, s, dtype = 16_384, 1024, 256, jnp.float32

    def build(reps):
        ctx = SketchContext(seed=92)
        sketches = [JLT(n, s, ctx) for _ in range(reps)]

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                # abs is NONLINEAR: it blocks XLA's reduce(dot) algebraic rewrite
                # (sum(A@B) -> (1ᵀA)(B·1)), which would gut the measurement
                acc += jnp.sum(jnp.abs(S.apply(A, "rowwise").astype(jnp.float32)))
            return acc

        return jax.jit(run)

    A = jax.random.normal(jax.random.PRNGKey(0), (m, n), dtype=dtype)
    per = _rep_diff(build, A)
    tflops = 2.0 * m * n * s / per / 1e12
    return tflops, per


def bench_fjlt(on_tpu, dtype, baseline_ms, table):
    from libskylark_tpu.sketch.fjlt import FJLT

    if on_tpu:
        m, n, s = 131_072, 4096, 1024
    else:
        m, n, s = 4096, 1024, 256

    def build(reps):
        ctx = SketchContext(seed=17)
        sketches = [FJLT(n, s, ctx) for _ in range(reps)]

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                # abs is NONLINEAR: it blocks XLA's reduce(dot) algebraic rewrite
                # (sum(A@B) -> (1ᵀA)(B·1)), which would gut the measurement
                acc += jnp.sum(jnp.abs(S.apply(A, "rowwise").astype(jnp.float32)))
            return acc

        return jax.jit(run)

    A = jax.random.normal(jax.random.PRNGKey(1), (m, n), dtype=dtype)
    per = _rep_diff(build, A, r1=4, r2=16, rounds=20)
    name = "bf16" if dtype == jnp.bfloat16 else "f32"
    _emit(
        f"FJLT {m}x{n}->{s} {name} apply",
        per * 1e3,
        "ms",
        baseline_ms / (per * 1e3) if on_tpu else 1.0,
        table,
    )


def bench_cwt(on_tpu, table):
    from libskylark_tpu.sketch.hash import CWT

    if on_tpu:
        m, n, s = 131_072, 4096, 1024
    else:
        m, n, s = 8192, 512, 128

    def build(reps):
        ctx = SketchContext(seed=29)
        sketches = [CWT(m, s, ctx) for _ in range(reps)]

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                acc += jnp.sum(jnp.abs(S.apply(A, "columnwise").astype(jnp.float32)))
            return acc

        return jax.jit(run)

    A = jax.random.normal(jax.random.PRNGKey(2), (m, n), jnp.float32)
    per = _rep_diff(build, A, r1=4, r2=12, rounds=20)
    _emit(
        f"CWT {m}x{n}->{s} dense columnwise apply",
        per * 1e3,
        "ms",
        19.8 / (per * 1e3) if on_tpu else 1.0,
        table,
    )


def bench_frft(on_tpu, dtype, baseline_ms, table):
    """Fastfood via the realized-W MXU path (sketch/frft.py round 3)."""
    from libskylark_tpu.sketch.frft import FastGaussianRFT

    if on_tpu:
        m, n, s = 131_072, 4096, 2048
    else:
        m, n, s = 4096, 256, 512

    def build(reps):
        ctx = SketchContext(seed=37)
        sketches = [FastGaussianRFT(n, s, ctx, sigma=2.0) for _ in range(reps)]

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                acc += jnp.sum(jnp.abs(S.apply(A, "rowwise").astype(jnp.float32)))
            return acc

        return jax.jit(run)

    A = jax.random.normal(jax.random.PRNGKey(5), (m, n), dtype=dtype)
    per = _rep_diff(build, A, r1=2, r2=8, rounds=15)
    name = "bf16" if dtype == jnp.bfloat16 else "f32"
    _emit(
        f"FastGaussianRFT {m}x{n}->{s} {name} apply",
        per * 1e3,
        "ms",
        baseline_ms / (per * 1e3) if on_tpu else 1.0,
        table,
    )


def bench_ppt(on_tpu, dtype, baseline_ms, table):
    """TensorSketch q=3 (bf16 = matmul-DFT path, f32 = complex FFT)."""
    from libskylark_tpu.sketch.ppt import PPT

    if on_tpu:
        m, n, s = 131_072, 4096, 1024
    else:
        m, n, s = 4096, 256, 128

    def build(reps):
        ctx = SketchContext(seed=43)
        sketches = [PPT(n, s, ctx, q=3) for _ in range(reps)]

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                acc += jnp.sum(jnp.abs(S.apply(A, "rowwise").astype(jnp.float32)))
            return acc

        return jax.jit(run)

    A = jax.random.normal(jax.random.PRNGKey(6), (m, n), dtype=dtype)
    # r2 capped at 2: three concurrent f32-FFT rep bodies overflow HBM
    # (XLA schedules their ~0.5 GB FFT temps together).
    per = _rep_diff(build, A, r1=1, r2=2, rounds=12)
    name = "bf16" if dtype == jnp.bfloat16 else "f32"
    _emit(
        f"PPT {m}x{n}->{s} q=3 {name} apply",
        per * 1e3,
        "ms",
        baseline_ms / (per * 1e3) if on_tpu else 1.0,
        table,
    )


def bench_mmt(on_tpu, table):
    """Non-sign hash sketch (Cauchy values) — the scaled-one-hot f32
    path must stay at CWT speed (hash.py round 3)."""
    from libskylark_tpu.sketch.hash import MMT

    if on_tpu:
        m, n, s = 131_072, 4096, 1024
    else:
        m, n, s = 8192, 512, 128

    def build(reps):
        ctx = SketchContext(seed=47)
        sketches = [MMT(m, s, ctx) for _ in range(reps)]

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                acc += jnp.sum(jnp.abs(S.apply(A, "columnwise").astype(jnp.float32)))
            return acc

        return jax.jit(run)

    A = jax.random.normal(jax.random.PRNGKey(7), (m, n), jnp.float32)
    per = _rep_diff(build, A, r1=4, r2=12, rounds=15)
    _emit(
        f"MMT {m}x{n}->{s} dense f32 columnwise apply",
        per * 1e3,
        "ms",
        18.1 / (per * 1e3) if on_tpu else 1.0,
        table,
    )


def bench_stream_chunk(on_tpu, table):
    """Fused stream-chunk throughput (round-8 tentpole): one streaming
    columnwise pass driven through ``plans.accumulate_slice``, with the
    per-chunk sketch-apply + accumulator-add traced as a SINGLE planned
    executable (``fused=True``, the hash sketches' window-kernel emit
    folds the add on TPU).  Emitted value is end-to-end Mrows/s over the
    whole pass; ``vs_baseline`` is the fused/unfused speedup on the same
    chunks — the two paths are bitwise identical by the
    ``apply_slice_kernel_acc`` contract, so the ratio isolates launch
    and fusion overhead.  First capture: no recorded baseline row."""
    from libskylark_tpu import plans
    from libskylark_tpu.sketch.hash import CWT, MMT

    if on_tpu:
        chunk, n, s, nchunks = 65_536, 2048, 1024, 8
    else:
        chunk, n, s, nchunks = 4096, 256, 128, 4
    m = chunk * nchunks
    X = jax.random.normal(jax.random.PRNGKey(21), (chunk, n), jnp.float32)

    for name, mk in (("CWT", CWT), ("MMT", MMT)):
        S = mk(m, s, SketchContext(seed=61))
        S.hoistable_operands(jnp.float32)  # realize outside the timings

        def run(fused):
            acc = jnp.zeros((s, n), jnp.float32)
            for c in range(nchunks):
                acc = plans.accumulate_slice(
                    S, acc, X, c * chunk, true_rows=chunk, fused=fused
                )
            return jax.block_until_ready(acc)

        plans.clear()
        run(True), run(False)  # build both plan-cache entries
        t_fused = min(_timed(run, True) for _ in range(5))
        t_unfused = min(_timed(run, False) for _ in range(5))
        _emit(
            f"{name} fused stream-chunk columnwise "
            f"{nchunks}x{chunk}x{n}->{s}",
            (m / t_fused) / 1e6,
            "Mrows/s",
            t_unfused / t_fused,
            table,
            contention=None,  # min-of-5 custom loop — no burst spread
        )


def bench_overlap(on_tpu, table):
    """Async device-overlap streaming (round-11 tentpole): the same
    columnwise CWT pass folded twice — ``overlap=True`` (host syncs only
    at chunk boundaries; batch k+1's staging rides JAX async dispatch
    under batch k's compute) vs ``overlap=False`` (``block_until_ready``
    after every fold step, the serial anchor).  The two are bitwise
    identical by the overlap contract (same blocks, same order, same
    IEEE adds — only the host's wait points move), so ``vs_baseline``
    (serial/overlapped) isolates pure dispatch-overlap win.  A second
    row reports the overlap-efficiency submetric: the fraction of
    producer (parse + host→device staging) seconds hidden under compute,
    from the prefetch counters of one overlapped pass."""
    from libskylark_tpu import streaming, telemetry
    from libskylark_tpu.sketch.hash import CWT
    from libskylark_tpu.streaming import StreamParams

    if on_tpu:
        br, n, s, nb = 65_536, 2048, 1024, 8
    else:
        br, n, s, nb = 4096, 256, 128, 4
    m = br * nb
    rng = np.random.default_rng(33)
    host = [rng.standard_normal((br, n)).astype(np.float32) for _ in range(nb)]
    S = CWT(m, s, SketchContext(seed=71))
    S.hoistable_operands(jnp.float32)  # realize outside the timings

    def run(overlap):
        return jax.block_until_ready(
            streaming.sketch(
                lambda start: iter(host[start:]),
                S,
                ncols=n,
                params=StreamParams(overlap=overlap),
            )
        )

    run(True), run(False)  # compile the planned fold once
    t_over = min(_timed(run, True) for _ in range(5))
    t_serial = min(_timed(run, False) for _ in range(5))

    prev = os.environ.get("SKYLARK_TELEMETRY")
    os.environ["SKYLARK_TELEMETRY"] = "1"
    telemetry.reset()
    try:
        run(True)
        snap = telemetry.snapshot()
    finally:
        if prev is None:
            os.environ.pop("SKYLARK_TELEMETRY", None)
        else:
            os.environ["SKYLARK_TELEMETRY"] = prev
    eff = snap.get("overlap_efficiency")
    _emit(
        f"CWT overlapped stream columnwise {nb}x{br}x{n}->{s}",
        (m / t_over) / 1e6,
        "Mrows/s",
        t_serial / t_over,
        table,
        contention=None,  # min-of-5 custom loop — no burst spread
    )
    _emit(
        f"overlap efficiency (hidden transfer fraction, {nb}x{br}x{n})",
        eff if eff is not None else -1,
        "ratio",
        1.0,
        table,
        contention=None,  # counter ratio, not a timing
    )


def bench_qrft(on_tpu, table):
    """QMC random features (Halton + inverse-CDF epilogue on the dense
    engine) — closes the transform-family perf matrix (VERDICT r3 #9).
    First capture: no recorded baseline yet, vs_baseline fixed at 1.0
    (BASELINE.md records the value this emits)."""
    from libskylark_tpu.sketch.rft import GaussianQRFT

    if on_tpu:
        m, n, s = 131_072, 4096, 2048
    else:
        m, n, s = 4096, 256, 128

    def build(reps):
        ctx = SketchContext(seed=59)
        # QRFT consumes no counters — distinct skips keep reps CSE-proof.
        sketches = [
            GaussianQRFT(n, s, ctx, sigma=4.0, skip=1 + r * s)
            for r in range(reps)
        ]

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                acc += jnp.sum(jnp.abs(S.apply(A, "rowwise").astype(jnp.float32)))
            return acc

        return jax.jit(run)

    A = jax.random.normal(jax.random.PRNGKey(10), (m, n), jnp.float32)
    per = _rep_diff(build, A, r1=2, r2=6, rounds=12)
    _emit(
        f"GaussianQRFT {m}x{n}->{s} f32 apply",
        per * 1e3,
        "ms",
        1.0,
        table,
    )


def bench_rlt(on_tpu, table):
    """Random Laplace transform (Lévy dense engine + exp epilogue).
    First capture: vs_baseline fixed at 1.0 (see bench_qrft)."""
    from libskylark_tpu.sketch.rlt import ExpSemigroupRLT

    if on_tpu:
        m, n, s = 131_072, 4096, 1024
    else:
        m, n, s = 4096, 256, 128

    def build(reps):
        ctx = SketchContext(seed=61)
        sketches = [ExpSemigroupRLT(n, s, ctx, beta=1.0) for _ in range(reps)]

        def run(A):
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                acc += jnp.sum(jnp.abs(S.apply(A, "rowwise").astype(jnp.float32)))
            return acc

        return jax.jit(run)

    # Semigroup-kernel features need non-negative inputs (histograms).
    A = jnp.abs(jax.random.normal(jax.random.PRNGKey(11), (m, n), jnp.float32))
    per = _rep_diff(build, A, r1=2, r2=6, rounds=12)
    _emit(
        f"ExpSemigroupRLT {m}x{n}->{s} f32 apply",
        per * 1e3,
        "ms",
        1.0,
        table,
    )


def bench_sparse_cwt(on_tpu, table):
    """Input-sparsity-time sketch: CWT on a 1e6x1e5 BCOO, 1e7 nnz,
    dense_output (sort-free segment_sum — hash.py round 3)."""
    from jax.experimental import sparse as jsparse

    from libskylark_tpu.sketch.hash import CWT

    if on_tpu:
        n, m, s, nnz = 1_000_000, 100_000, 1024, 10_000_000
    else:
        n, m, s, nnz = 10_000, 1_000, 128, 100_000
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(8), 3)
    rows = jax.random.randint(k1, (nnz,), 0, n, dtype=jnp.int32)
    cols = jax.random.randint(k2, (nnz,), 0, m, dtype=jnp.int32)
    data = jax.random.normal(k3, (nnz,), jnp.float32)
    idx = jnp.stack([rows, cols], axis=1)

    def build(reps):
        ctx = SketchContext(seed=53)
        sketches = [CWT(n, s, ctx) for _ in range(reps)]

        def run(data, idx):
            A = jsparse.BCOO((data, idx), shape=(n, m))
            acc = jnp.zeros((), jnp.float32)
            for S in sketches:
                out = S.apply(A, "columnwise", dense_output=True)
                acc += jnp.sum(jnp.abs(out))
            return acc

        return jax.jit(run)

    # Measure BOTH scatter paths so the driver artifact itself carries
    # the Pallas-kernel-vs-XLA comparison (round 5: the kernel has
    # hardware evidence only if a tunnel window opens; this row pair is
    # the fallback evidence).  The env var is read at trace time, so
    # each setting builds a distinct program.
    prev = os.environ.get("SKYLARK_PALLAS_SCATTER")
    try:
        # XLA row first: a forced-kernel lowering failure must not cost
        # the baseline measurement.
        from libskylark_tpu.sketch import pallas_scatter

        for tag, setting in (("xla", "0"), ("pallas", "1")):
            label = f"CWT BCOO {n}x{m} nnz={nnz:.0e} -> {s} dense_output" + (
                f" [{tag}]" if on_tpu else ""
            )
            if tag == "pallas":
                # The forced setting is honored only when the kernel's
                # own gate admits the shape — a silent XLA fallthrough
                # must not masquerade as a kernel measurement.
                if not pallas_scatter.supported(nnz, s * m):
                    _emit(
                        f"{label} (skipped: shape outside kernel gate)",
                        -1, "skipped", 0, table, contention=None,
                    )
                    continue
                if _remaining() < 0.6 * 150:
                    _emit(
                        f"{label} (skipped: budget)", -1, "skipped", 0,
                        table, contention=None,
                    )
                    continue
            if on_tpu:
                os.environ["SKYLARK_PALLAS_SCATTER"] = setting
            try:
                per = _rep_diff(build, (data, idx), r1=1, r2=3, rounds=8)
            except Exception as e:  # noqa: BLE001 — forced kernel may
                # not lower on this generation; report, keep the pair
                _emit(
                    f"{label} (FAILED: {type(e).__name__})", -1, "error",
                    0, table, contention=None,
                )
                continue
            _emit(
                label,
                per * 1e3,
                "ms",
                357.0 / (per * 1e3) if on_tpu else 1.0,
                table,
            )
            if not on_tpu:
                break  # CPU smoke: one row, no kernel path to compare
    finally:
        if prev is None:
            os.environ.pop("SKYLARK_PALLAS_SCATTER", None)
        else:
            os.environ["SKYLARK_PALLAS_SCATTER"] = prev


def bench_streaming_krr(on_tpu, table):
    """North-star single-chip config: 10M×4096 → 2048-feature KRR, rows
    AND features streamed, bf16 (BASELINE.md North-star section).
    Steady s/sweep via the solver's PhaseTimer (sweep0 absorbs compiles;
    a content-varying resident panel stands in for IO — a loop-invariant
    panel would be LICM'd into a fictitious >100% MFU reading)."""
    from libskylark_tpu.ml import (
        GaussianKernel,
        KrrParams,
        streaming_kernel_ridge,
    )
    from libskylark_tpu.utils import PhaseTimer

    if on_tpu:
        N, D, S, BR, sweeps = 10_000_000, 4096, 2048, 125_000, 3
    else:
        N, D, S, BR, sweeps = 4096, 64, 128, 512, 2

    X0 = jax.random.normal(jax.random.PRNGKey(9), (BR, D), jnp.bfloat16)

    def block_fn(start, rows, X0):
        # Per-panel row ROTATION: not algebraically reducible, so no XLA
        # simplifier can commute it out of the dot and hoist the matmul
        # (a scalar multiple could be rewritten s*dot(X0, W); an additive
        # shift folds into colsum(W) — both re-open the LICM trap).
        return jnp.roll(X0, start // rows, axis=0)

    y = jnp.asarray(
        np.sign(np.random.default_rng(0).standard_normal(N)), jnp.float32
    )
    timer = PhaseTimer()
    streaming_kernel_ridge(
        GaussianKernel(D, sigma=8.0), block_fn, (N, D), y, 0.1, S,
        SketchContext(seed=72),
        KrrParams(max_split=0, iter_lim=sweeps, tolerance=0.0),
        block_rows=BR, feature_dtype=jnp.bfloat16, block_args=(X0,),
        timer=timer,
    )
    per = timer.totals["sweep"] / timer.counts["sweep"]
    _emit(
        f"streaming KRR {N}x{D}->{S} bf16 (north-star, hot panels)",
        per,
        "s/sweep",
        2.69 / per if on_tpu else 1.0,
        table,
        contention=None,  # PhaseTimer steady sweeps — no burst spread
    )


def bench_streaming_svd(on_tpu, table):
    """The BASELINE.json headline config: 1e7x1024, k=100 (bf16 panels)."""
    from libskylark_tpu.linalg import (
        SVDParams,
        streaming_approximate_svd,
        synthetic_lowrank_blocks,
    )

    if on_tpu:
        m, n, k, br, dtype = 10_000_000, 1024, 100, 250_000, jnp.bfloat16
    else:
        m, n, k, br, dtype = 20_000, 128, 10, 5_000, jnp.float32
    ctx = SketchContext(seed=5)
    blocks = synthetic_lowrank_blocks(ctx, m, n, k, noise=0.01, dtype=dtype)

    def run():
        _, s, V = streaming_approximate_svd(
            blocks, (m, n), k, SketchContext(seed=6),
            SVDParams(num_iterations=1), block_rows=br,
        )
        return jnp.sum(s)

    _timed(run)  # compile sweep programs
    dt = min(_timed(run) for _ in range(2 if on_tpu else 3))
    _emit(
        f"streaming randomized SVD {m}x{n} k={k}",
        dt,
        "s",
        21.0 / dt if on_tpu else 1.0,
        table,
        contention=None,  # single-shot timing — no burst spread measured
    )


def bench_ridge(on_tpu, table):
    """Random-feature ridge solve (feature map + Gram + solve)."""
    from libskylark_tpu.ml import GaussianKernel

    if on_tpu:
        m, d, s = 262_144, 4096, 2048
    else:
        m, d, s = 8192, 256, 128
    kernel = GaussianKernel(d, sigma=4.0)

    def build(reps):
        ctx = SketchContext(seed=31)
        maps = [kernel.create_rft(s, "regular", ctx) for _ in range(reps)]

        def run(X, Y):
            acc = jnp.zeros((), jnp.float32)
            for fm in maps:
                Z = fm.apply(X, "rowwise").astype(jnp.bfloat16)
                G = (Z.T @ Z).astype(jnp.float32) + 0.1 * jnp.eye(s)
                W = jnp.linalg.solve(G, (Z.T @ Y.astype(Z.dtype)).astype(jnp.float32))
                acc += jnp.sum(jnp.abs(W))
            return acc

        return jax.jit(run)

    X = jax.random.normal(jax.random.PRNGKey(3), (m, d), jnp.bfloat16)
    Y = jax.random.normal(jax.random.PRNGKey(4), (m, 1), jnp.float32)

    f1, f2 = build(1), build(3)
    _timed(f1, X, Y), _timed(f2, X, Y)
    t1s, t2s = [], []
    for _ in range(10):
        t1s.append(_timed(f1, X, Y))
        t2s.append(_timed(f2, X, Y))
    per = (min(t2s) - min(t1s)) / 2
    if per <= 0:
        per = min(t1s)  # degenerate timing; report the single-solve time
    _emit(
        f"random-feature ridge solve {m}x{d}->{s} feats (marginal)",
        per * 1e3,
        "ms",
        31.0 / (per * 1e3) if on_tpu else 1.0,
        table,
        contention=None,  # custom timing loop — no burst spread measured
    )


def bench_admm(on_tpu, table):
    from libskylark_tpu.ml import ADMMParams, BlockADMMSolver, GaussianKernel

    # Marginal s/iter via (t_201 - t_1)/200: the scan-fused iteration
    # costs ~12 ms on a v5e chip, far below the fixed setup+compile that
    # rides every train() call (fresh jitted closures per call), so the
    # iteration count must be large enough that the signal (~2.4 s)
    # dominates compile jitter.  The round-1 recorded 0.92 s/iter was
    # total/iters of a 10-iteration run — fixed-cost dominated, not a
    # steady-state number (reconciled in BASELINE.md).
    if on_tpu:
        m, d, s, iters = 262_144, 128, 2048, 201
    else:
        m, d, s, iters = 4096, 16, 64, 5
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((m, d)), jnp.float32)
    y = jnp.asarray((rng.standard_normal(m) > 0) * 2.0 - 1.0, jnp.float32)
    kernel = GaussianKernel(d, sigma=2.0)
    ctx = SketchContext(seed=41)
    maps = [kernel.create_rft(s, "regular", ctx) for _ in range(2)]

    def run(n_iter):
        solver = BlockADMMSolver(
            "hinge", "l2", maps,
            ADMMParams(maxiter=n_iter, data_partitions=4),
        )
        model = solver.train(X, y)
        return jax.block_until_ready(model.W)

    # train() jits fresh closures per call, so every timed call includes
    # one trace+compile; the two programs (scan length 1 vs N) have near-
    # identical structure, so compile time CANCELS in the difference.
    # min over repeats suppresses compile/tunnel jitter.
    for attempt in range(2):
        t1 = min(_timed(lambda _: run(1), None) for _ in range(2))
        tN = min(_timed(lambda _: run(iters), None) for _ in range(2))
        if tN > t1:
            break
        time.sleep(10)  # transient contention: let it clear, retry once
    if tN <= t1:
        raise RuntimeError(
            f"ADMM timing inconsistent (t1={t1:.2f}s >= tN={tN:.2f}s)"
        )
    per = (tN - t1) / (iters - 1)
    _emit(
        f"BlockADMM {m}x{d} -> 2x{s} feats hinge+l2 P=4",
        per,
        "s/iter",
        0.92 / per if on_tpu else 1.0,
        table,
        contention=None,  # custom timing loop — no burst spread measured
    )


def bench_train(on_tpu, table):
    """Distributed-training rows (docs/distributed_training.md): (a)
    end-to-end world=1 elastic BlockADMM training throughput (rows/s:
    stream + factor + iterate) vs the in-process
    ``BlockADMMSolver.train`` on the SAME data/maps/params —
    ``vs_baseline`` is distributed/in-process rows/s (the world=1 model
    is bitwise the in-process one, so the ratio prices the elastic
    plumbing alone); (b) kill-to-first-consensus resume latency: the
    training loop is preempted right after a committed ADMM chunk, the
    world restarts with ``resume=True``, and the value is wall-seconds
    from the kill to the FIRST post-resume train-chunk commit (a train
    chunk commits only after its final consensus merge) — the restore +
    re-stream + re-factor latency a preempted world pays before forward
    progress resumes; first capture, vs_baseline fixed at 1.0; (c)
    bf16-vs-f32 train step: marginal s/iter of the fused rank step at
    ``compute_dtype=bf16`` on identical streamed blocks, with
    ``vs_baseline`` the f32/bf16 per-iteration speedup."""
    import tempfile

    from libskylark_tpu.ml import (
        ADMMParams,
        BlockADMMSolver,
        GaussianKernel,
        prepare_rank_admm,
        rank_chunked_solver,
        stream_feature_blocks,
    )
    from libskylark_tpu.ml.distributed import DistributedBlockADMMTrainer
    from libskylark_tpu.resilient import FaultPlan, SimulatedPreemption
    from libskylark_tpu.streaming import ElasticParams, RowPartition

    if on_tpu:
        n, d, s, P, iters, br = 131_072, 64, 512, 8, 40, 8192
    else:
        n, d, s, P, iters, br = 4096, 16, 64, 4, 8, 512
    rng = np.random.default_rng(29)
    X = np.asarray(rng.standard_normal((n, d)), np.float32)
    y = np.asarray(rng.standard_normal(n), np.float32)
    ctx = SketchContext(seed=29)
    kernel = GaussianKernel(d, sigma=2.0)
    maps = [kernel.create_rft(s, "regular", ctx) for _ in range(2)]
    params = ADMMParams(rho=1.0, lam=0.01, maxiter=iters, data_partitions=P)
    part = RowPartition(nrows=n, batch_rows=br, world_size=1)

    def source(start):
        def gen():
            for b in range(start, part.num_batches):
                lo = b * br
                yield X[lo : lo + br], y[lo : lo + br]

        return gen()

    # (a) rows/s through the elastic trainer vs the in-process solver.
    # Both time one full train() including its per-call trace+compile —
    # the same contract either entry point gives a fresh caller.
    t0 = time.perf_counter()
    m_ref = BlockADMMSolver("squared", "l2", maps, params).train(
        jnp.asarray(X), jnp.asarray(y), regression=True
    )
    jax.block_until_ready(m_ref.W)
    t_base = time.perf_counter() - t0
    t0 = time.perf_counter()
    m_dist, _ = DistributedBlockADMMTrainer(
        "squared", "l2", maps, params, ElasticParams(prefetch=0)
    ).train(source, part, regression=True)
    jax.block_until_ready(m_dist.W)
    t_dist = time.perf_counter() - t0
    _emit(
        f"distributed ADMM train {n}x{d}->2x{s} P={P} (world=1)",
        n / t_dist,
        "rows/s",
        t_base / t_dist,
        table,
        contention=None,  # single end-to-end interval per entry point
    )

    # (b) kill right after a committed train chunk, resume, stamp the
    # first post-resume commit (= first completed consensus chunk).
    class _FirstCommit(FaultPlan):
        def __init__(self):
            super().__init__()
            self.t = None

        def after_commit(self, chunk):
            if self.t is None:
                self.t = time.perf_counter()

    with tempfile.TemporaryDirectory() as root:
        ck = dict(checkpoint_dir=root, checkpoint_every=2, prefetch=0)
        try:
            DistributedBlockADMMTrainer(
                "squared", "l2", maps, params, ElasticParams(**ck)
            ).train(
                source, part, regression=True,
                train_fault_plan=FaultPlan(preempt_after_chunk=0),
            )
            raise RuntimeError("train preemption never fired")
        except SimulatedPreemption:
            t_kill = time.perf_counter()
        first = _FirstCommit()
        DistributedBlockADMMTrainer(
            "squared", "l2", maps, params, ElasticParams(resume=True, **ck)
        ).train(source, part, regression=True, train_fault_plan=first)
    _emit(
        "train resume kill-to-first-consensus (world=1)",
        first.t - t_kill,
        "s",
        1.0,
        table,
        contention=None,  # single wall-clock interval, not pooled
    )

    # (c) marginal s/iter of the fused rank step, bf16 vs f32, on the
    # SAME streamed blocks (stream once, factor per dtype; iteration 0
    # absorbs the compile, the rest are steady-state).
    Z_rows, Y_rows, _ = stream_feature_blocks(
        source, maps, part, ElasticParams(prefetch=0), targets=1
    )

    def per_iter(cd):
        prep = prepare_rank_admm(
            "squared", "l2", maps, params, part, 0, Z_rows, Y_rows,
            regression=True, compute_dtype=cd,
        )
        solver = rank_chunked_solver(prep, maps, params)
        st = solver.step_chunk(solver.init_state(), 1)  # compile + warm
        jax.block_until_ready(st["inner"][0])
        k = iters - 1
        t0 = time.perf_counter()
        st = solver.step_chunk(st, k)
        jax.block_until_ready(st["inner"][0])
        return (time.perf_counter() - t0) / k

    t_f32 = per_iter(None)
    t_bf16 = per_iter(jnp.bfloat16)
    _emit(
        f"distributed train step bf16 P={P} 2x{s} feats",
        t_bf16,
        "s/iter",
        t_f32 / t_bf16,
        table,
        contention=None,  # custom timing loop — no burst spread measured
    )


def bench_serve(on_tpu, table):
    """Serving SLO (docs/serving.md): sustained single-row QPS through
    the cross-request coalescing server vs the SAME server pinned serial
    (``max_coalesce=1``), for LS-solve and KRR-predict, with client-side
    p50/p99 submetrics.  The coalescing claim is throughput-shaped — N
    concurrent single-row requests ride ONE fused plan dispatch instead
    of N — so the row to watch is the coalesced/serial QPS ratio
    (``vs_baseline``; the SLO contract targets >= 3x)."""
    import concurrent.futures as cf

    from libskylark_tpu import serve
    from libskylark_tpu.ml.kernels import GaussianKernel
    from libskylark_tpu.ml.model import FeatureMapModel

    m, n = (8192, 64) if on_tpu else (512, 16)
    d, feats = 24, 64
    total = 64 if _SMOKE else 256
    workers = 16
    rng = np.random.default_rng(11)
    A = rng.standard_normal((m, n))
    maps = [GaussianKernel(d, 1.3).create_rft(
        feats, "regular", SketchContext(seed=31)
    )]
    model = FeatureMapModel(
        maps, rng.standard_normal((feats, 4)), scale_maps=True
    )
    rhs = [rng.standard_normal(m) for _ in range(8)]
    xs = [rng.standard_normal(d) for _ in range(8)]

    def drive(make_req, max_coalesce, n_requests=None):
        n = n_requests or total
        params = serve.ServeParams(
            max_coalesce=max_coalesce, max_queue=4 * n,
            warm_start=False, prime=True,
        )
        srv = serve.Server(params, seed=13)
        srv.registry.register_system(
            "sys", A, context=SketchContext(seed=29)
        )
        srv.registry.register_model("mdl", model)
        srv.start()

        def one(i):
            t0 = time.perf_counter()
            r = srv.call(make_req(i))
            dt_ms = (time.perf_counter() - t0) * 1e3
            if not r["ok"]:
                raise RuntimeError(r["error"]["message"])
            return dt_ms

        with cf.ThreadPoolExecutor(max_workers=workers) as pool:
            list(pool.map(one, range(workers)))  # warm every rung first
            t0 = time.perf_counter()
            lat = sorted(pool.map(one, range(n)))
        wall = time.perf_counter() - t0
        srv.stop()
        return (
            n / wall,
            lat[len(lat) // 2],
            lat[min(len(lat) - 1, int(len(lat) * 0.99))],
        )

    cases = [
        ("LS-solve",
         lambda i: serve.make_request("ls_solve", system="sys",
                                      b=rhs[i % len(rhs)])),
        ("KRR-predict",
         lambda i: serve.make_request("predict", model="mdl",
                                      x=xs[i % len(xs)])),
    ]
    for op, mk in cases:
        qps_s, p50_s, p99_s = drive(mk, 1)
        qps_c, p50_c, p99_c = drive(mk, 32)
        _emit(f"serve {op} serial QPS", qps_s, "req/s", 1.0, table,
              contention=None)
        _emit(f"serve {op} coalesced QPS", qps_c, "req/s", qps_c / qps_s,
              table, contention=None)
        _emit(f"serve {op} coalesced p50", p50_c, "ms", p50_s / p50_c,
              table, contention=None)
        _emit(f"serve {op} coalesced p99", p99_c, "ms", p99_s / p99_c,
              table, contention=None)

    # Trace-overhead submetric (docs/observability.md): the SAME
    # coalesced drive, telemetry ON in both modes, tracing isolated by
    # its SKYLARK_TRACE sub-gate — so the ratio charges ONLY what this
    # plane added (mint/span events/flight recorder), not the
    # pre-existing counter+ledger cost.  The SLO contract is
    # vs_baseline >= 0.95 — tracing may cost < 5% QPS — and the
    # minted/finished counts ride the artifact so the traced run proves
    # it actually traced every request (vs_baseline 1.0 there means
    # every minted trace finished into the recorder).
    from libskylark_tpu import telemetry as _tel

    op, mk = cases[0]
    prev = {
        k: os.environ.get(k) for k in ("SKYLARK_TELEMETRY", "SKYLARK_TRACE")
    }
    try:
        # Interleaved A/B, median per mode: one drive is ~100ms of
        # wall, so scheduler jitter would otherwise dwarf the <=5%
        # effect being measured — and sequential best-of-N still
        # confounds the ratio with run-order drift (a box that warms
        # or degrades across the measurement window biases whichever
        # mode ran last).  Alternating modes puts the drift in both.
        os.environ["SKYLARK_TELEMETRY"] = "1"
        qps = {"0": [], "1": []}
        minted = finished = 0
        # 4x-length drives: at ~100ms of wall per drive the OS scheduler
        # is the biggest term in a single sample's variance.
        n_req = (4 * total) if not _SMOKE else total
        for _ in range(3):
            for mode in ("0", "1"):
                os.environ["SKYLARK_TRACE"] = mode
                _tel.reset()
                qps[mode].append(drive(mk, 32, n_requests=n_req)[0])
                if mode == "1":
                    counters = _tel.REGISTRY.snapshot()["counters"]
                    minted += counters.get("trace.minted", 0)
                    finished += counters.get("trace.finished", 0)
        qps_off = sorted(qps["0"])[1]
        qps_on = sorted(qps["1"])[1]
    finally:
        _tel.reset()
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    _emit(f"serve {op} traced QPS", qps_on, "req/s", qps_on / qps_off,
          table, contention=None)
    _emit(f"serve {op} traces minted", minted, "traces",
          (finished / minted) if minted else 0.0, table, contention=None)


def bench_attribution(on_tpu, table):
    """Phase-clock attribution (docs/observability.md, "Latency
    attribution"): the SAME coalesced serve drive, telemetry AND tracing
    held ON in both modes, the per-request phase clock isolated by its
    SKYLARK_PHASES sub-gate — so the ratio charges only what attribution
    added (monotonic stamps, phase histograms) on top of the already-on
    trace plane.  Contract: vs_baseline >= 0.95.  The decomposition row
    then proves the phases mean something: a traced request's recorded
    phases must sum to its own end-to-end latency within 10%
    (``vs_baseline`` there IS the sum/e2e ratio — 1.0 means the phase
    chain tiles the request wall exactly)."""
    import concurrent.futures as cf

    from libskylark_tpu import serve
    from libskylark_tpu import telemetry as _tel

    m, n = (8192, 64) if on_tpu else (512, 16)
    total = 64 if _SMOKE else 256
    workers = 16
    rng = np.random.default_rng(23)
    A = rng.standard_normal((m, n))
    rhs = [rng.standard_normal(m) for _ in range(8)]

    def drive(n_requests):
        params = serve.ServeParams(
            max_coalesce=32, max_queue=4 * n_requests,
            warm_start=False, prime=True,
        )
        srv = serve.Server(params, seed=13)
        srv.registry.register_system(
            "sys", A, context=SketchContext(seed=29)
        )
        srv.start()

        def one(i):
            r = srv.call(serve.make_request(
                "ls_solve", system="sys", b=rhs[i % len(rhs)]
            ))
            if not r["ok"]:
                raise RuntimeError(r["error"]["message"])

        with cf.ThreadPoolExecutor(max_workers=workers) as pool:
            list(pool.map(one, range(workers)))  # warm every rung first
            t0 = time.perf_counter()
            list(pool.map(one, range(n_requests)))
        wall = time.perf_counter() - t0
        srv.stop()
        return n_requests / wall

    prev = {
        k: os.environ.get(k)
        for k in ("SKYLARK_TELEMETRY", "SKYLARK_TRACE", "SKYLARK_PHASES")
    }
    ratio = 0.0
    try:
        # Interleaved A/B, median of 3 per mode, 4x-length drives —
        # the same discipline as the trace-overhead row above:
        # alternating modes puts box-level drift into both samples
        # instead of whichever mode ran last.
        os.environ["SKYLARK_TELEMETRY"] = "1"
        os.environ["SKYLARK_TRACE"] = "1"
        qps = {"0": [], "1": []}
        n_req = (4 * total) if not _SMOKE else total
        for _ in range(3):
            for mode in ("0", "1"):
                os.environ["SKYLARK_PHASES"] = mode
                _tel.reset()
                qps[mode].append(drive(n_req))
        qps_off = sorted(qps["0"])[1]
        qps_on = sorted(qps["1"])[1]

        # Decomposition: one traced request; its phase clock must
        # account for its own end-to-end wall.  Fresh rhs so the
        # front-door cache cannot answer (cache hits carry no phases).
        os.environ["SKYLARK_PHASES"] = "1"
        _tel.reset()
        params = serve.ServeParams(
            max_coalesce=4, warm_start=False, prime=True
        )
        srv = serve.Server(params, seed=13)
        srv.registry.register_system(
            "sys", A, context=SketchContext(seed=29)
        )
        srv.start()
        try:
            srv.call(serve.make_request(
                "ls_solve", system="sys", b=rng.standard_normal(m)
            ))  # warm the rung: the measured request must not compile
            r = srv.call(serve.make_request(
                "ls_solve", system="sys", b=rng.standard_normal(m)
            ))
            envelope = r.get("trace") or {}
            phases = envelope.get("phases") or {}
            e2e = envelope.get("e2e_ms") or 0.0
            if phases and e2e:
                ratio = sum(phases.values()) / e2e
        finally:
            srv.stop()
    finally:
        _tel.reset()
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    _emit("serve phase-clock QPS", qps_on, "req/s", qps_on / qps_off,
          table, contention=None)
    _emit("serve phase sum/e2e", ratio, "ratio", ratio, table,
          contention=None)


def bench_cache(on_tpu, table):
    """Front-door QoS + result cache (docs/serving.md, "QoS + caching").

    Two contracts, two row groups:

    - **Hot-set QPS, cache on vs off**: the same 8-vector hot set driven
      through the same SERIAL server (``max_coalesce=1`` — one dispatch
      per request, so the row isolates the per-dispatch cost the cache
      removes rather than letting coalescing amortise it) twice —
      ``cache=False`` pays a device dispatch per request, ``cache=True``
      re-serves every repeat bitwise from the dict.  ``vs_baseline`` on
      the cache-on row is the speedup; the acceptance floor is 5x on
      CPU.
    - **Adversarial-tenant fairness**: a polite tenant's p99 alone, then
      the SAME polite traffic while a noisy tenant floods the door with
      QoS lanes on (cache off, so the flood is real device work).  The
      deficit-round-robin lanes must keep the polite tenant's p99 within
      2x of its solo p99 (``vs_baseline`` = solo/adversarial >= 0.5) —
      without lanes the polite requests would queue behind the entire
      flood."""
    import concurrent.futures as cf
    import threading

    from libskylark_tpu import serve

    m, n = (8192, 64) if on_tpu else (512, 16)
    total = 64 if _SMOKE else 256
    workers = 16
    rng = np.random.default_rng(17)
    A = rng.standard_normal((m, n))
    hot = [rng.standard_normal(m) for _ in range(8)]

    def req(i, tenant=None):
        r = serve.make_request("ls_solve", system="sys", b=hot[i % len(hot)])
        if tenant is not None:
            r["tenant"] = tenant
        return r

    def make_server(cache_on, max_coalesce=16):
        srv = serve.Server(
            serve.ServeParams(
                max_coalesce=max_coalesce, max_queue=4096, warm_start=False,
                prime=True, cache=cache_on,
                tenant_weights={"polite": 1.0, "noisy": 1.0},
            ),
            seed=13,
        )
        srv.registry.register_system("sys", A, context=SketchContext(seed=29))
        return srv.start()

    def one(srv, i, tenant=None):
        t0 = time.perf_counter()
        r = srv.call(req(i, tenant))
        dt_ms = (time.perf_counter() - t0) * 1e3
        if not r["ok"]:
            raise RuntimeError(r["error"]["message"])
        return dt_ms

    # -- hot-set QPS, cache off vs on ---------------------------------------
    qps = {}
    for cache_on in (False, True):
        srv = make_server(cache_on, max_coalesce=1)
        with cf.ThreadPoolExecutor(max_workers=workers) as pool:
            list(pool.map(lambda i: one(srv, i), range(workers)))  # warm
            t0 = time.perf_counter()
            list(pool.map(lambda i: one(srv, i), range(total)))
            qps[cache_on] = total / (time.perf_counter() - t0)
        hits = srv.cache.stats()["hits"]
        srv.stop()
    _emit("serve cache-off hot-set QPS", qps[False], "req/s", 1.0, table,
          contention=None)
    _emit("serve cache-on hot-set QPS", qps[True], "req/s",
          qps[True] / qps[False], table, contention=None)
    _emit("serve cache hits", hits, "hits",
          hits / (total + workers), table, contention=None)

    # -- adversarial-tenant fairness ----------------------------------------
    def polite_p99(srv):
        with cf.ThreadPoolExecutor(max_workers=4) as pool:
            lat = sorted(pool.map(
                lambda i: one(srv, i, tenant="polite"), range(total // 4)
            ))
        return lat[min(len(lat) - 1, int(len(lat) * 0.99))]

    srv = make_server(False)
    p99_solo = polite_p99(srv)
    stop = threading.Event()

    def flood(j):
        i = 0
        while not stop.is_set():
            one(srv, j * 7919 + i, tenant="noisy")
            i += 1

    flooders = [
        threading.Thread(target=flood, args=(j,), daemon=True)
        for j in range(workers - 4)
    ]
    for t in flooders:
        t.start()
    try:
        p99_mixed = polite_p99(srv)
    finally:
        stop.set()
        for t in flooders:
            t.join(timeout=30)
        srv.stop()
    _emit("serve polite solo p99", p99_solo, "ms", 1.0, table,
          contention=None)
    _emit("serve polite adversarial p99", p99_mixed, "ms",
          p99_solo / p99_mixed, table, contention=None)


def bench_durability(on_tpu, table):
    """Durable serve state (docs/serving.md, "Durable serving"):

    - **Update-op QPS, journal-on vs journal-off**: the same serial
      server driving idempotency-keyed row appends through the wire
      ``update`` op, once process-state only and once with a
      ``state_dir`` — so every mint pays a CRC frame + fsync before it
      publishes.  ``vs_baseline`` on the journal-on row is on/off; the
      acceptance floor is 0.8x (durability may cost at most 20% of
      update throughput at bench scale).
    - **Kill-to-placeable recovery latency**: ``Registry.recover`` wall
      seconds on a state dir holding 1k journaled updates (smoke: 100),
      compaction OFF (pure tail replay) vs compaction ON (snapshot +
      short tail).  ``vs_baseline`` on the compacted row is
      replay/compacted — the snapshot path must not lose to replaying
      every record through the real mutators.
    """
    import shutil
    import tempfile

    from libskylark_tpu import serve
    from libskylark_tpu.serve.journal import Journal
    from libskylark_tpu.serve.registry import Registry

    n_updates = 32 if _SMOKE else 192
    n_recover = 100 if _SMOKE else 1000
    m, n = (2048, 32) if on_tpu else (256, 8)
    rng = np.random.default_rng(17)
    A = rng.standard_normal((m, n))
    rows = [rng.standard_normal((1, n)) for _ in range(8)]

    def drive(state_dir):
        srv = serve.Server(
            serve.ServeParams(warm_start=False, prime=False,
                              state_dir=state_dir),
            seed=13,
        )
        # CWT: the hash-family transform with a columnwise partial
        # rule — FJLT has none and refuses live appends.
        srv.register_system(
            "sys", A, context=SketchContext(seed=29), sketch_type="CWT",
            sketch_size=4 * n, capacity=m + n_updates + 8,
        )
        srv.start()
        # Warm the append path before timing (first call pays traces).
        srv.call(op="update", system="sys", append=rows[0],
                 idem_key="warm")
        t0 = time.perf_counter()
        for i in range(n_updates):
            r = srv.call(op="update", system="sys", append=rows[i % 8],
                         idem_key=f"bench-{i}")
            if not r["ok"]:
                raise RuntimeError(r["error"]["message"])
        wall = time.perf_counter() - t0
        srv.stop()
        return n_updates / wall

    def build_state(directory, compact_every):
        reg = Registry(
            journal=Journal(directory, compact_every=compact_every)
        )
        reg.register_system(
            "sys", A, context=SketchContext(seed=29), sketch_type="CWT",
            sketch_size=4 * n, capacity=m + n_recover + 8,
        )
        for i in range(n_recover):
            reg.append_system_rows("sys", rows[i % 8],
                                   idem=("bench", str(i)))

    with tempfile.TemporaryDirectory() as td:
        qps_off = drive(None)
        qps_on = drive(os.path.join(td, "qps"))
        _emit("serve update QPS journal-off", qps_off, "req/s", 1.0,
              table, contention=None)
        _emit("serve update QPS journal-on", qps_on, "req/s",
              qps_on / qps_off, table, contention=None)
        shutil.rmtree(os.path.join(td, "qps"))

        replay_dir = os.path.join(td, "replay")
        snap_dir = os.path.join(td, "snap")
        build_state(replay_dir, 0)            # journal only: full replay
        build_state(snap_dir, 256)            # snapshot + short tail
        t0 = time.perf_counter()
        reg = Registry.recover(replay_dir)
        t_replay = time.perf_counter() - t0
        assert reg.epoch == n_recover + 1
        t0 = time.perf_counter()
        reg = Registry.recover(snap_dir)
        t_snap = time.perf_counter() - t0
        assert reg.epoch == n_recover + 1
    _emit("serve recovery replay-only", t_replay, "s", 1.0, table,
          contention=None)
    _emit("serve recovery compacted", t_snap, "s", t_replay / t_snap,
          table, contention=None)


def bench_refine(on_tpu, table):
    """Certified mixed-precision refinement vs the exact f64 QR solve
    (docs/performance.md): wall-clock to MATCHED accuracy on the same
    (A, b).  The refine route sketches A once, QR-factors S·A at the
    low working precision, and drives f64 residuals through the
    triangular preconditioner until the guard-certified gate passes;
    the reference is the f64 Householder QR solve of the full system.
    ``vs_baseline`` on the solve row is the speedup (target >= 1.5x on
    CPU); the matched-accuracy row is ``||A x_refine - b|| / ||A
    x_exact - b||`` and must sit at ~1.0 for the speedup to count —
    a fast wrong answer is worth nothing."""
    from jax.experimental import enable_x64

    from libskylark_tpu.linalg.least_squares import exact_least_squares
    from libskylark_tpu.solvers.refine import (
        RefineParams,
        refine_least_squares,
    )

    if on_tpu:
        m, n = 32_768, 768
    elif _SMOKE:
        m, n = 2048, 128
    else:
        m, n = 8192, 512
    rounds = 2 if _SMOKE else 5
    rng = np.random.default_rng(23)
    with enable_x64():
        A = jnp.asarray(rng.standard_normal((m, n)))
        b = jnp.asarray(
            A @ rng.standard_normal(n) + 1e-3 * rng.standard_normal(m)
        )

        def run_exact():
            t0 = time.perf_counter()
            X = exact_least_squares(A, b, alg="qr")
            jax.block_until_ready(X)
            return time.perf_counter() - t0, X

        def run_refine():
            t0 = time.perf_counter()
            X, info = refine_least_squares(
                A, b, SketchContext(seed=101), RefineParams()
            )
            jax.block_until_ready(X)
            return time.perf_counter() - t0, X, info

        run_exact(), run_refine()  # compile / plan-cache warmup
        te, Xe = min((run_exact() for _ in range(rounds)),
                     key=lambda r: r[0])
        tr, Xr, info = min((run_refine() for _ in range(rounds)),
                           key=lambda r: r[0])
        r_exact = float(jnp.linalg.norm(A @ Xe - b))
        r_refine = float(jnp.linalg.norm(A @ Xr - b))
    rf = info.get("refine") or {}
    _emit(
        f"refine {m}x{n} mixed-precision solve ({rf.get('rung')}, "
        f"{rf.get('iters')} sweeps)",
        tr * 1e3, "ms", te / tr, table, contention=None,
    )
    _emit(
        "refine matched-accuracy residual",
        r_refine / r_exact if r_exact > 0 else -1.0,
        "ratio", 1.0 if rf.get("converged") else 0.0, table,
        contention=None,
    )


def bench_cond_est(on_tpu, table):
    """Served cond-est QPS (docs/serving.md): the placement-keyed
    cached-probe endpoint under concurrent single-shot load.  The probe
    itself ran once at prime time; every request after it is a dict fan
    through the coalescing batcher, so this row measures the serving
    plane's fixed overhead on its cheapest op."""
    import concurrent.futures as cf

    from libskylark_tpu import serve

    m, n = (8192, 64) if on_tpu else (512, 16)
    total = 64 if _SMOKE else 512
    workers = 16
    rng = np.random.default_rng(7)
    A = rng.standard_normal((m, n))
    params = serve.ServeParams(
        max_coalesce=32, max_queue=4 * total, warm_start=False, prime=True
    )
    srv = serve.Server(params, seed=5)
    srv.registry.register_system("sys", A, context=SketchContext(seed=3))
    srv.start()

    def one(i):
        r = srv.call(serve.make_request("cond_est", system="sys", id=i))
        if not r["ok"]:
            raise RuntimeError(r["error"]["message"])

    with cf.ThreadPoolExecutor(max_workers=workers) as pool:
        list(pool.map(one, range(workers)))  # warm the dispatch path
        t0 = time.perf_counter()
        list(pool.map(one, range(total)))
        wall = time.perf_counter() - t0
    srv.stop()
    _emit(
        "serve cond-est QPS", total / wall, "req/s", 1.0, table,
        contention=None,
    )


def bench_fleet(on_tpu, table):
    """Fleet scaling (docs/serving.md, fleet section): the sustained
    mixed single-row drive (LS-solve + KRR-predict — two placement
    keys, so workers AND replicas both have parallel work) through
    (a) one worker, (b) two device-pinned workers on one admission
    queue, and (c) a 2-replica fleet behind the front-door router.
    ``vs_baseline`` on the (b)/(c) QPS rows is the scaling ratio over
    (a); the acceptance target is >= 1.7x on multi-chip hardware, and
    on a single-device/single-core host the honest ratio is ~1x and
    lands as measured.  The p99 row guards the tail: its ratio is
    p99_1w/p99_2w, so >= 0.67 means the 2-worker tail stayed within
    1.5x of single-worker.  The last row is the device-parallel
    dispatch census: value = sharded programs parity-probed on this
    backend, ratio = fraction that verified bitwise (a tombstoned
    program still serves correct bits through the single-device path,
    so this is hardware truth, not a correctness gate)."""
    import concurrent.futures as cf

    from libskylark_tpu import serve
    from libskylark_tpu import telemetry as _tel
    from libskylark_tpu.ml.kernels import GaussianKernel
    from libskylark_tpu.ml.model import FeatureMapModel
    from libskylark_tpu.serve import dispatch

    m, n = (8192, 64) if on_tpu else (512, 16)
    d, feats = 24, 64
    total = 64 if _SMOKE else 256
    clients = 16
    rng = np.random.default_rng(17)
    A = rng.standard_normal((m, n))
    maps = [GaussianKernel(d, 1.3).create_rft(
        feats, "regular", SketchContext(seed=33)
    )]
    model = FeatureMapModel(
        maps, rng.standard_normal((feats, 4)), scale_maps=True
    )
    rhs = [rng.standard_normal(m) for _ in range(8)]
    xs = [rng.standard_normal(d) for _ in range(8)]

    def make_server(workers):
        srv = serve.Server(
            serve.ServeParams(
                max_coalesce=32, max_queue=8 * total,
                warm_start=False, prime=True, workers=workers,
            ),
            seed=13,
        )
        srv.registry.register_system(
            "sys", A, context=SketchContext(seed=29)
        )
        srv.registry.register_model("mdl", model)
        return srv

    def mk(i):
        if i % 2 == 0:
            return serve.make_request(
                "ls_solve", system="sys", b=rhs[i % len(rhs)]
            )
        return serve.make_request(
            "predict", model="mdl", x=xs[i % len(xs)]
        )

    def drive(front, stoppers):
        def one(i):
            t0 = time.perf_counter()
            r = front.call(mk(i))
            dt_ms = (time.perf_counter() - t0) * 1e3
            if not r["ok"]:
                raise RuntimeError(r["error"]["message"])
            return dt_ms

        try:
            with cf.ThreadPoolExecutor(max_workers=clients) as pool:
                list(pool.map(one, range(clients)))  # warm every rung
                t0 = time.perf_counter()
                lat = sorted(pool.map(one, range(total)))
            wall = time.perf_counter() - t0
        finally:
            for s in stoppers:
                s.stop()
        return (
            total / wall,
            lat[min(len(lat) - 1, int(len(lat) * 0.99))],
        )

    srv1 = make_server(1).start()
    qps1, p99_1 = drive(srv1, [srv1])
    srv2 = make_server(2).start()
    qps2, p99_2 = drive(srv2, [srv2])
    ra, rb = make_server(1).start(), make_server(1).start()
    router = serve.Router()
    router.join("a", server=ra)
    router.join("b", server=rb)
    qps_r, _ = drive(router, [router, ra, rb])

    _emit("serve fleet 1-worker QPS", qps1, "req/s", 1.0, table,
          contention=None)
    _emit("serve fleet 2-worker QPS", qps2, "req/s", qps2 / qps1, table,
          contention=None)
    _emit("serve fleet 2-worker p99", p99_2, "ms", p99_1 / p99_2, table,
          contention=None)
    _emit("serve fleet 2-replica routed QPS", qps_r, "req/s",
          qps_r / qps1, table, contention=None)

    # Device-parallel dispatch census: force the shard gate open, run
    # the same drive once, and count how many sharded programs the
    # one-time parity probe verified bitwise on this backend.
    prev = {
        k: os.environ.get(k)
        for k in ("SKYLARK_SERVE_SHARD", "SKYLARK_TELEMETRY")
    }
    try:
        os.environ["SKYLARK_SERVE_SHARD"] = "1"
        os.environ["SKYLARK_TELEMETRY"] = "1"
        _tel.reset()
        dispatch.clear_cache()
        srv = make_server(1).start()
        drive(srv, [srv])
        counters = _tel.REGISTRY.snapshot()["counters"]
    finally:
        dispatch.clear_cache()
        _tel.reset()
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    verified = counters.get("serve.sharded_verified", 0)
    probed = verified + counters.get("serve.sharded_rejected", 0)
    _emit("serve sharded probes verified", verified, "programs",
          (verified / probed) if probed else 0.0, table, contention=None)


def bench_autoscale(on_tpu, table):
    """Serve through change (docs/serving.md "serve through change" +
    docs/fault_tolerance.md): the round-16 robustness measurements.
    Two registry rows first: wall ms for a live graph edge fold and a
    live LS row append — each publishes a NEW epoch-stamped version
    while in-flight batches keep the old bits (the bitwise contract is
    pinned in tests/test_live_registry.py; this row is what a caller
    pays for it).  Then two fleet rows: scale-up reaction — wall ms
    from a hot p99 signal to the autoscaler's spawned replica joined
    behind the fence (prime-before-placeable, so the number includes
    the full plan-ladder compile); and rolling-drain QPS — the mixed
    drive sustained WHILE the autoscaler drains the fleet 2 -> 1
    mid-traffic.  The ratio on the QPS row is the fraction of calls
    that returned ok; the zero-downtime discipline (drain to zero,
    clean leave, never a 114) makes 1.0 the acceptance target."""
    import concurrent.futures as cf

    from libskylark_tpu import serve
    from libskylark_tpu import telemetry as _tel
    from libskylark_tpu.graph.graph import SimpleGraph
    from libskylark_tpu.serve.registry import Registry

    # -- live-registry epoch bumps (no server: Registry-level timing) --
    nv = 2048 if on_tpu else 256
    ring = [(i, (i + 1) % nv) for i in range(nv)]
    chords = [(i, (i + 7) % nv) for i in range(0, nv, 3)]

    def fold_once():
        reg = Registry()
        reg.register_graph(
            "g", SimpleGraph(ring), k=4, context=SketchContext(seed=5)
        )
        # readback of the refreshed embedding forces the whole delta
        return _timed(lambda: reg.fold_graph_edges("g", chords)[0].X)

    fold_s = min(fold_once() for _ in range(2 if _SMOKE else 3))

    m, n = (8192, 64) if on_tpu else (512, 16)
    blk = 128 if on_tpu else 32
    reps = 2 if _SMOKE else 3
    rng = np.random.default_rng(23)
    A = rng.standard_normal((m, n))
    reg = Registry()
    # SJLT: the only baked-in transform with the columnwise apply_slice
    # a live append needs; capacity reserves sketch-domain rows for it.
    reg.register_system(
        "sys", A, context=SketchContext(seed=3),
        sketch_type="SJLT", capacity=m + (reps + 1) * blk,
    )
    app_s = min(
        _timed(
            lambda: reg.append_system_rows(
                "sys", rng.standard_normal((blk, n))
            )[0].R
        )
        for _ in range(reps)
    )
    _emit(
        f"registry live graph fold {nv}v epoch bump", fold_s * 1e3, "ms",
        1.0, table, contention=None,
    )
    _emit(
        f"registry live row append {blk}x{n} epoch bump", app_s * 1e3,
        "ms", 1.0, table, contention=None,
    )

    # -- autoscaled fleet: scale-up reaction + rolling-drain QPS --
    total = 48 if _SMOKE else 160
    clients = 8
    rhs = [rng.standard_normal(m) for _ in range(8)]

    def make_server():
        srv = serve.Server(
            serve.ServeParams(
                max_coalesce=16, max_queue=8 * total,
                warm_start=False, prime=True, workers=1,
            ),
            seed=13,
        )
        srv.registry.register_system(
            "sys", A, context=SketchContext(seed=29)
        )
        return srv

    prev = os.environ.get("SKYLARK_TELEMETRY")
    stoppers = []
    try:
        # telemetry ON: the p99 the autoscaler steers on only records
        # under the flag, and the shed counter certifies the QPS row.
        os.environ["SKYLARK_TELEMETRY"] = "1"
        _tel.reset()
        core = make_server().start()
        router = serve.Router()
        router.join("core", server=core)
        stoppers = [router, core]
        scaler = serve.Autoscaler(
            router,
            lambda name: make_server(),
            serve.AutoscaleParams(
                min_replicas=1, max_replicas=2,
                queue_high=1e9, queue_low=1e9,
                p99_high_ms=1e-4,  # any recorded latency reads as hot
                cooldown_ticks=0, idle_ticks=10**9,
            ),
        )

        def one(i):
            r = router.call(serve.make_request(
                "ls_solve", system="sys", b=rhs[i % len(rhs)]
            ))
            return bool(r["ok"])

        with cf.ThreadPoolExecutor(max_workers=clients) as pool:
            list(pool.map(one, range(clients)))  # warm + record the p99
            t0 = time.perf_counter()
            for _ in range(64):
                if scaler.step().get("action") == "scale_up":
                    break
            else:
                raise RuntimeError(
                    "autoscaler never scaled up under a hot p99"
                )
            react_ms = (time.perf_counter() - t0) * 1e3
            members = router.fleet_report()["members"]
            if sum(1 for v in members.values() if v.get("placeable")) != 2:
                raise RuntimeError(
                    "scaled-up replica is not placeable behind the fence"
                )

            # flip the loop to idle so it drains back to 1 mid-drive
            scaler.params.p99_high_ms = None
            scaler.params.idle_ticks = 1
            deadline = time.monotonic() + 120.0
            t0 = time.perf_counter()
            futs = [pool.submit(one, i) for i in range(total)]
            while len(router.fleet_report()["members"]) > 1:
                scaler.step()
                if time.monotonic() > deadline:
                    raise RuntimeError("rolling drain did not converge")
                time.sleep(0.002)
            oks = sum(1 for f in futs if f.result())
            wall = time.perf_counter() - t0
        counters = _tel.REGISTRY.snapshot()["counters"]
        shed = counters.get("serve.shed_admission", 0)
        lost = counters.get("router.ejects", 0)
        if shed or lost:
            raise RuntimeError(
                f"rolling drain was not clean (shed={shed}, ejects={lost})"
            )
    finally:
        for s in stoppers:
            s.stop()
        _tel.reset()
        if prev is None:
            os.environ.pop("SKYLARK_TELEMETRY", None)
        else:
            os.environ["SKYLARK_TELEMETRY"] = prev
    _emit(
        "serve autoscale scale-up reaction (prime->placeable)", react_ms,
        "ms", 1.0, table, contention=None,
    )
    _emit(
        "serve autoscale rolling-drain QPS (2->1 mid-traffic)",
        total / wall, "req/s", oks / total, table, contention=None,
    )


def bench_plan_cache(on_tpu, table):
    """Plan-cache cold vs warm: what one compiled sketch-apply plan costs
    to build (trace + compile + first exec) against what the cached
    executable costs per call.  The pair is the observability contract of
    the plan layer: warm ≪ cold is the whole point of caching, and the
    hit/miss counters printed with the rows prove the second call was a
    cache hit, not a silent retrace."""
    from libskylark_tpu import plans
    from libskylark_tpu.sketch.dense import JLT

    if on_tpu:
        m, n, s = 8192, 2048, 512
    else:
        m, n, s = 2048, 256, 64
    # m sits ON the bucket ladder so cold/warm time the same executable
    # shape (no padding asymmetry between the two measurements).
    X = jax.random.normal(jax.random.PRNGKey(7), (m, n), jnp.float32)
    S = JLT(n, s, SketchContext(seed=77))
    S.hoistable_operands(jnp.float32)  # realize operands OUTSIDE the timings

    plans.clear()
    plans.reset_stats()
    cold = _timed(lambda: plans.apply_rowwise_bucketed(S, X))
    st0 = plans.stats()
    warm = min(
        _timed(lambda: plans.apply_rowwise_bucketed(S, X)) for _ in range(10)
    )
    st1 = plans.stats()
    if st0["misses"] < 1 or st1["hits"] < 10:
        raise RuntimeError(
            f"plan cache counters inconsistent (misses={st0['misses']}, "
            f"hits={st1['hits']}); cold/warm split is not trustworthy"
        )
    _emit(
        f"plan-cache cold apply {m}x{n}->{s} (trace+compile+exec)",
        cold * 1e3,
        "ms",
        1.0,
        table,
        contention=None,  # single-shot by construction — cold happens once
    )
    _emit(
        f"plan-cache warm apply {m}x{n}->{s} (cached executable)",
        warm * 1e3,
        "ms",
        cold / warm,  # speedup of the cached path over plan construction
        table,
        contention=None,  # min-of-10 custom loop — no burst spread measured
    )


def bench_guard_overhead(on_tpu, table):
    """What the numerical-health guard costs: guarded vs unguarded
    sketch-and-solve LS on the same problem (docs/numerical_health.md's
    overhead contract).  The guarded run pays one ``certify_sketch``
    (short-budget cond_est on the replicated-small S·A) plus one
    finiteness probe; the emitted value is the guarded/unguarded time
    ratio (1.0 = free).  First capture: vs_baseline fixed at 1.0."""
    from libskylark_tpu.linalg import approximate_least_squares

    if on_tpu:
        m, n = 262_144, 512
    else:
        m, n = 16_384, 128
    A = jax.random.normal(jax.random.PRNGKey(12), (m, n), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(13), (m,), jnp.float32)

    def run():
        return approximate_least_squares(A, b, SketchContext(seed=99))

    prev = os.environ.get("SKYLARK_GUARD")
    try:
        os.environ["SKYLARK_GUARD"] = "0"
        _timed(run)  # compile the sketch+solve programs
        unguarded = min(_timed(run) for _ in range(6))
        os.environ["SKYLARK_GUARD"] = "1"
        _timed(run)  # compile the certification (cond_est) program
        guarded = min(_timed(run) for _ in range(6))
    finally:
        if prev is None:
            os.environ.pop("SKYLARK_GUARD", None)
        else:
            os.environ["SKYLARK_GUARD"] = prev
    _emit(
        f"guard overhead sketch-and-solve LS {m}x{n} (guarded/unguarded)",
        guarded / unguarded,
        "x",
        1.0,
        table,
        contention=None,  # ratio of two min-pooled timings
    )


def bench_telemetry(on_tpu, table):
    """Telemetry-layer submetric: one streamed sketch-and-solve LS pass
    under ``SKYLARK_TELEMETRY=1``, reporting the two derived ratios of
    ``telemetry.snapshot()`` (docs/observability.md): the plan-cache hit
    rate of the pass and the prefetch producer/consumer overlap.  First
    capture: vs_baseline fixed at 1.0 (BASELINE.md records the values)."""
    from libskylark_tpu import plans, telemetry
    from libskylark_tpu.linalg import streaming_least_squares

    if on_tpu:
        n, d, br = 262_144, 512, 32_768
    else:
        n, d, br = 8192, 64, 1024

    def batches(start):
        rng = np.random.default_rng(21)
        for i in range(n // br):
            X = rng.standard_normal((br, d)).astype(np.float32)
            y = rng.standard_normal(br).astype(np.float32)
            if i >= start:
                yield X, y

    prev = os.environ.get("SKYLARK_TELEMETRY")
    os.environ["SKYLARK_TELEMETRY"] = "1"
    telemetry.reset()
    plans.reset()
    try:
        streaming_least_squares(batches, n, d, SketchContext(seed=88))
        snap = telemetry.snapshot()
    finally:
        if prev is None:
            os.environ.pop("SKYLARK_TELEMETRY", None)
        else:
            os.environ["SKYLARK_TELEMETRY"] = prev
    hit = snap["plan_cache_hit_rate"]
    overlap = snap["prefetch_overlap"]
    _emit(
        f"telemetry plan-cache hit rate (streamed LS {n}x{d})",
        hit if hit is not None else -1,
        "ratio",
        1.0,
        table,
        contention=None,  # counter ratio, not a timing
    )
    _emit(
        f"telemetry prefetch overlap (streamed LS {n}x{d})",
        overlap if overlap is not None else -1,
        "ratio",
        1.0,
        table,
        contention=None,  # counter ratio, not a timing
    )


def bench_policy(on_tpu, table):
    """Adaptive-policy submetric (docs/autotuning.md): the same guarded
    sketch-and-solve LS pass run cold (empty profile store + empty plan
    cache) and warm (after ``policy.warm_start`` replays the persisted
    hot plans), reporting the plan-compile seconds each pass pays, plus
    the profile-learned sketch-dimension ratio once the store matures.
    Warm < cold is the warm-start contract of ISSUE 9; the dim ratio
    shows the autotuner actually shrinking toward the smallest
    certified-OK size.  First capture: vs_baseline fixed at 1.0."""
    import shutil
    import tempfile

    from libskylark_tpu import plans, policy
    from libskylark_tpu.linalg import approximate_least_squares

    if on_tpu:
        m, n = 65_536, 256
    else:
        m, n = 4096, 64
    A = jax.random.normal(jax.random.PRNGKey(31), (m, n), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(32), (m,), jnp.float32)

    env_keys = ("SKYLARK_POLICY", "SKYLARK_POLICY_DIR",
                "SKYLARK_POLICY_MIN_SAMPLES", "SKYLARK_GUARD")
    saved = {k: os.environ.get(k) for k in env_keys}
    tmp = tempfile.mkdtemp(prefix="skylark-bench-policy-")
    os.environ["SKYLARK_POLICY"] = "1"
    os.environ["SKYLARK_GUARD"] = "1"
    os.environ.pop("SKYLARK_POLICY_DIR", None)
    os.environ["SKYLARK_POLICY_MIN_SAMPLES"] = "3"
    try:
        prev_xla_cache = jax.config.jax_compilation_cache_dir
    except Exception:  # noqa: BLE001 — knob absent on old jax
        prev_xla_cache = False  # sentinel: don't restore
    try:
        policy.configure(tmp)
        policy.reset()
        policy.invalidate_cache()

        # -- cold: empty store, empty plan cache; the pass pays every
        # plan trace+compile itself.  A fresh same-seed context per call
        # keeps the sketch (and so the plan keys) bitwise identical
        # between the cold and warm passes.
        plans.clear()
        plans.reset_stats()
        approximate_least_squares(A, b, SketchContext(seed=41))
        cold = plans.stats()["compile_seconds"]
        policy.flush()  # persist the profile + hot-plan records

        # -- warm: new "process" (cleared plan cache + merged-view
        # reload), replay the recorded plans, then run the same pass.
        # Its compile seconds are what warm start did NOT save.
        plans.clear()
        policy.invalidate_cache()
        ws = policy.warm_start(tmp)
        plans.reset_stats()
        approximate_least_squares(A, b, SketchContext(seed=41))
        st = plans.stats()
        warm = st["compile_seconds"]
        if ws["plans_replayed"] < 1 or st["hits"] < 1:
            raise RuntimeError(
                f"warm start replayed {ws['plans_replayed']} plans, "
                f"{st['hits']} hits; cold/warm split is not trustworthy"
            )
        _emit(
            f"policy cold LS pass {m}x{n} plan-compile",
            cold * 1e3, "ms", 1.0, table,
            contention=None,  # single-shot by construction
        )
        _emit(
            f"policy warm LS pass {m}x{n} plan-compile (after replay)",
            warm * 1e3, "ms",
            # compile seconds warm start removed; a perfect replay pays
            # 0.0 warm, so the speedup is floored at the 1ms resolution
            # the compile timer can meaningfully distinguish.
            cold / max(warm, 1e-3),
            table,
            contention=None,
        )

        # -- autotuned sketch dimension: mature the profile past
        # min_samples and read the decided/default ratio of the next
        # pass (shrink-toward-smallest-certified-OK, decide.py).
        for k in range(3):
            approximate_least_squares(A, b, SketchContext(seed=41))
        policy.flush()
        policy.invalidate_cache()
        _, info = approximate_least_squares(
            A, b, SketchContext(seed=41), return_info=True
        )
        dec = info["policy"]
        _emit(
            f"policy sketch-dim ratio LS {m}x{n} (decided/default, "
            f"source={dec['source']})",
            dec["sketch_size"] / min(4 * n, m), "ratio", 1.0, table,
            contention=None,  # a decision, not a timing
        )
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        policy.configure(None)
        policy.reset()
        policy.invalidate_cache()
        if prev_xla_cache is not False:
            # warm_start fills the XLA cache knob when unset; put back
            # whatever the process had (tmp is about to be deleted).
            try:
                jax.config.update("jax_compilation_cache_dir", prev_xla_cache)
            except Exception:  # noqa: BLE001
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def bench_elastic_resume(on_tpu, table):
    """Elastic-resume submetric (docs/fault_tolerance.md): a world=1
    partitioned streaming fold is preempted mid-pass right after a chunk
    commit, then resumed from the per-host checkpoints; the emitted value
    is wall-seconds from the kill to the FIRST post-resume fold landing —
    the restore + ledger-replay latency a real preempted host pays before
    it makes forward progress again.  Dry-run scale on purpose: the cost
    is dominated by checkpoint restore and plan/ledger I/O, not FLOPs.
    First capture: vs_baseline fixed at 1.0."""
    import tempfile

    from libskylark_tpu.plans import accumulate_slice
    from libskylark_tpu.resilient import FaultPlan, SimulatedPreemption
    from libskylark_tpu.sketch.hash import CWT
    from libskylark_tpu.streaming import ElasticParams, RowPartition
    from libskylark_tpu.streaming.elastic import elastic_run_stream

    n, d, br = 8192, 64, 512  # 16 batches, preempt after chunk 7
    rng = np.random.default_rng(77)
    A = rng.standard_normal((n, d))
    blocks = [jnp.asarray(A[lo : lo + br]) for lo in range(0, n, br)]
    S = CWT(n, 256, SketchContext(seed=77))
    part = RowPartition(nrows=n, batch_rows=br, world_size=1)
    init = {
        "sa": jnp.zeros((S.s, d), jnp.float32),
        "row": np.asarray(0, np.int64),
    }
    first_fold: list[float] = []

    def step(acc, block, index):
        row = int(acc["row"])
        out = {
            "sa": accumulate_slice(S, acc["sa"], block, row),
            "row": np.asarray(row + block.shape[0], np.int64),
        }
        if not first_fold:
            jax.block_until_ready(out["sa"])
            first_fold.append(time.perf_counter())
        return out

    def factory(start):
        return iter(blocks[start:])

    with tempfile.TemporaryDirectory() as root:
        params = ElasticParams(
            checkpoint_dir=root, checkpoint_every=1, prefetch=0
        )
        try:
            elastic_run_stream(
                factory, step, init, part, params,
                fault_plan=FaultPlan(preempt_after_chunk=7),
            )
            raise RuntimeError("preemption never fired")
        except SimulatedPreemption:
            t_kill = time.perf_counter()
        first_fold.clear()
        elastic_run_stream(
            factory, step, init, part,
            ElasticParams(
                checkpoint_dir=root, checkpoint_every=1, prefetch=0,
                resume=True,
            ),
        )
    _emit(
        f"elastic resume kill-to-first-fold (world=1, {n}x{d})",
        first_fold[0] - t_kill,
        "s",
        1.0,
        table,
        contention=None,  # single wall-clock interval, not pooled
    )


def bench_graph(on_tpu, table):
    """Graph-analytics rows (docs/graph.md): (a) streamed edge-fold
    sketch throughput (edges/s) vs the dense route on the SAME graph —
    the dense baseline materializes the (n, n) adjacency and applies
    the sketch to it, which is the pre-streaming in-core path; the
    streamed fold touches O(edges) and must win by >= 1.3x even on CPU
    (``vs_baseline`` is the speedup).  (b) Elastic ASE kill-resume:
    wall-seconds from a mid-pass preemption to the FIRST post-resume
    edge fold landing (same shape as the elastic-resume row, over the
    graph fold).  (c) Served PPR QPS, coalesced vs serial — same-seed
    riders share one memoized diffusion, so the coalesced server
    answers N concurrent requests with ~1 solve."""
    import concurrent.futures as cf
    import tempfile

    from libskylark_tpu import serve
    from libskylark_tpu.graph import SimpleGraph
    from libskylark_tpu.graph.stream import (
        adjacency_sketch_fold,
        graph_block_source,
        streamed_adjacency_sketch,
    )
    from libskylark_tpu.resilient import FaultPlan, SimulatedPreemption
    from libskylark_tpu.sketch.hash import SJLT
    from libskylark_tpu.streaming import ElasticParams, RowPartition
    from libskylark_tpu.streaming.elastic import elastic_run_stream

    n, m = (16384, 400_000) if on_tpu else (2048, 30_000)
    if _SMOKE:
        n, m = 256, 2_000
    s = 128
    rng = np.random.default_rng(23)
    G = SimpleGraph(map(tuple, rng.integers(0, n, (m, 2)).tolist()))
    E = G.volume // 2
    S = SJLT(G.n, s, SketchContext(seed=23))
    src = graph_block_source(G, batch_edges=max(E, 1))

    def streamed():
        return streamed_adjacency_sketch(src, S, ncols=G.n)

    def dense():
        return S.apply(jnp.asarray(G.adjacency()), "columnwise")

    _timed(streamed), _timed(dense)  # compile both routes
    reps = 1 if _SMOKE else 3
    t_st = min(_timed(streamed) for _ in range(reps))
    t_dn = min(_timed(dense) for _ in range(reps))
    _emit(
        f"graph streamed sketch ({E} edges, n={G.n}, s={s})",
        E / t_st, "edges/s", t_dn / t_st, table, contention=None,
    )

    # (b) kill -> first post-resume fold, world=1 edge partition.
    br = max(E // 16, 1)
    init_at, step = adjacency_sketch_fold(S, G.n)
    part = RowPartition(nrows=E, batch_rows=br, world_size=1)
    first_fold: list[float] = []

    def timed_step(acc, block, index):
        out = step(acc, block, index)
        if not first_fold:
            jax.block_until_ready(out["sa"])
            first_fold.append(time.perf_counter())
        return out

    fold_src = graph_block_source(G, batch_edges=br)
    with tempfile.TemporaryDirectory() as root:
        try:
            elastic_run_stream(
                fold_src, timed_step, init_at(0), part,
                ElasticParams(
                    checkpoint_dir=root, checkpoint_every=1, prefetch=0
                ),
                fault_plan=FaultPlan(preempt_after_chunk=3),
            )
            raise RuntimeError("preemption never fired")
        except SimulatedPreemption:
            t_kill = time.perf_counter()
        first_fold.clear()
        elastic_run_stream(
            fold_src, timed_step, init_at(0), part,
            ElasticParams(
                checkpoint_dir=root, checkpoint_every=1, prefetch=0,
                resume=True,
            ),
        )
    _emit(
        f"graph ASE resume kill-to-first-fold ({E} edges)",
        first_fold[0] - t_kill, "s", 1.0, table, contention=None,
    )

    # (c) served PPR QPS: coalesced vs serial, fresh same-seed servers.
    total = 16 if _SMOKE else 96
    workers = 16
    Gq = SimpleGraph(
        map(tuple, rng.integers(0, 256, (2_000, 2)).tolist())
    )

    def drive(max_coalesce):
        srv = serve.Server(
            serve.ServeParams(
                max_coalesce=max_coalesce, max_queue=4 * total,
                warm_start=False,
            ),
            seed=23,
        )
        srv.register_graph("g", Gq, k=8)
        srv.start()

        def one(i):
            r = srv.call(op="ppr", graph="g", seeds=[i % 8])
            if not r["ok"]:
                raise RuntimeError(r["error"]["message"])

        with cf.ThreadPoolExecutor(max_workers=workers) as pool:
            list(pool.map(one, range(8)))  # warm the memo per seed
            t0 = time.perf_counter()
            list(pool.map(one, range(total)))
            wall = time.perf_counter() - t0
        srv.stop()
        return total / wall

    qps_s = drive(1)
    qps_c = drive(32)
    _emit("serve graph PPR serial QPS", qps_s, "req/s", 1.0, table,
          contention=None)
    _emit("serve graph PPR coalesced QPS", qps_c, "req/s", qps_c / qps_s,
          table, contention=None)


_FINAL: dict | None = None
_FINAL_PRINTED = False


def _print_final() -> None:
    """Print the LAST line (headline + full submetrics table) exactly once.

    Also wired to SIGTERM: if an outer ``timeout`` fires anyway, the
    driver still records a complete final line with everything measured
    so far (the round-3 rc=124 artifact lost the headline entirely)."""
    global _FINAL_PRINTED
    if _FINAL is None or _FINAL_PRINTED:
        return
    _FINAL_PRINTED = True
    print(json.dumps(_FINAL), flush=True)


class _FilteredOut(Exception):
    """Control-flow marker: the config was deselected by SKYLARK_BENCH_ONLY."""


class _BackendUnavailable:
    """Sentinel returned by :func:`_init_backend` when the init budget is
    exhausted; carries the last error string for the FAILED artifact."""

    def __init__(self, error: str):
        self.error = error


def _init_backend():
    """Backend init as a failable, retriable phase (VERDICT r4: the one
    unguarded line in the file was ``jax.devices()[0]``, and it cost the
    round its entire artifact when the tunnel was down).  Re-attempts
    ``jax.devices()`` with backoff — clearing JAX's cached init failure
    between attempts — for up to ``SKYLARK_BENCH_INIT_BUDGET_S``
    (default: 40 % of the bench budget, capped at 900 s).  Returns the
    device, or a :class:`_BackendUnavailable` sentinel on final failure;
    the caller emits a parseable ``FAILED: backend-unavailable``
    artifact and exits 0."""
    if (
        os.environ.get("SKYLARK_BENCH_SIM_INIT_FAIL") == "1"
        and os.environ.get("SKYLARK_BENCH_CPU_REEXEC") != "1"
    ):
        # Test hook (mirror of SKYLARK_BENCH_SIM_POISON): pretend the
        # accelerator init exhausted its budget so a regression test can
        # drive the whole rescue chain on a healthy host.  Ignored in
        # the re-exec'd child, which must init for real.
        return _BackendUnavailable("sim-init-fail: backend init suppressed")
    init_budget = float(
        os.environ.get(
            "SKYLARK_BENCH_INIT_BUDGET_S", str(min(900.0, 0.4 * _BUDGET_S))
        )
    )
    delay, last, hard_errors, init_fails = 5.0, "unknown", 0, 0
    while True:
        try:
            return jax.devices()[0]
        except Exception as e:  # noqa: BLE001 — UNAVAILABLE, tunnel flaps
            last = f"{type(e).__name__}: {e}"
            # Errors that don't self-identify as transient are almost
            # always deterministic misconfiguration (wrong platform, no
            # plugin) — give them one retry, then stop burning the init
            # budget.  Transience is judged by gRPC status tokens in the
            # message (UNAVAILABLE = tunnel flap, DEADLINE = slow
            # backend boot, RESOURCE_EXHAUSTED = device contention), not
            # exact text: PJRT messages embed varying addresses.
            #
            # EXCEPT: "Unable to initialize backend" wraps the plugin's
            # own init failure, and the wrapped gRPC text usually embeds
            # UNAVAILABLE — so the token test alone retried a dead
            # plugin for the whole init budget (BENCH_r05: every retry
            # re-raised the identical message and the CPU fallback got
            # only the scraps).  Init-phase failures are capped at a few
            # attempts regardless of token, then the fallback engages
            # with most of the budget still unspent.
            init_fails += 1 if "Unable to initialize backend" in last else 0
            hard_errors += 0 if any(t in last for t in _TRANSIENT_TOKENS) else 1
            if hard_errors >= 2 or init_fails >= 3:
                return _BackendUnavailable(last)
            print(
                json.dumps(
                    {
                        "metric": "backend-init retry",
                        "value": round(_remaining(), 1),
                        "unit": "s-remaining",
                        "vs_baseline": 0,
                        "error": last[:500],
                    }
                ),
                file=sys.stderr,
                flush=True,
            )
        # Two budget checks (BENCH_r05: a single blocked jax.devices()
        # attempt can eat many minutes, so an init-budget-only check can
        # overshoot the GLOBAL budget and leave the CPU fallback no time
        # to run — the round then records -1 backend-unavailable rows
        # despite the fallback existing).  Stop retrying the accelerator
        # while the remaining global budget can still fit the fallback
        # plus the CPU-sized config list.
        if (
            time.monotonic() - _T0 > init_budget
            or _remaining() < _FALLBACK_MARGIN_S
        ):
            return _BackendUnavailable(last)
        try:  # un-stick the cached failure so the next attempt is real
            import jax.extend.backend as _eb

            _eb.clear_backends()
        except Exception:  # noqa: BLE001 — best-effort
            pass
        time.sleep(min(delay, max(1.0, init_budget - (time.monotonic() - _T0))))
        delay = min(delay * 1.7, 60.0)


def _reexec_cpu(reason: str) -> str | None:
    """Replace this interpreter with a fresh ``JAX_PLATFORMS=cpu`` one —
    the rescue of last resort when in-process recovery can't purge
    poisoned plugin-registry state (clear_backends() brings the cached
    init failure straight back).  The loop guard env var keeps a
    genuinely CPU-less host from exec-looping, and the REMAINING global
    budget rides along so the new process doesn't restart the clock.
    Returns an error string ONLY if the exec could not happen (guard
    tripped or execvpe itself failed); on success it never returns."""
    if os.environ.get("SKYLARK_BENCH_CPU_REEXEC") == "1":
        return "re-exec loop guard: already running as the cpu re-exec"
    env = dict(os.environ)
    env["SKYLARK_BENCH_CPU_REEXEC"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["SKYLARK_BENCH_BUDGET_S"] = str(round(max(60.0, _remaining()), 1))
    print(
        json.dumps(
            {
                "metric": "backend fallback re-exec",
                "value": round(_remaining(), 1),
                "unit": "s-remaining",
                "vs_baseline": 0,
                "error": reason[:500],
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    sys.stderr.flush()
    sys.stdout.flush()
    try:
        os.execvpe(sys.executable, [sys.executable] + sys.argv, env)
    except OSError as e:  # noqa: BLE001 — caller falls back to sentinel
        return f"execvpe: {type(e).__name__}: {e}"
    return None  # unreachable


def _cpu_fallback(sentinel: _BackendUnavailable):
    """Accelerator init exhausted its retry budget: drop to host CPU so
    the round still records REAL numbers (tagged ``"backend":
    "cpu-fallback"`` on every row) instead of a -1 error artifact.  The
    CPU-sized configs are the same ones a ``JAX_PLATFORMS=cpu`` smoke run
    measures, so the rows are comparable across rounds even when the
    tunnel is down.  Returns the CPU device, or the (annotated) sentinel
    if even local CPU init fails."""
    global _BACKEND_TAG
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Multiple attempts, each step individually firewalled (BENCH_r05:
    # the fallback was a single try block, so ONE failing sub-step — a
    # clear_backends() quirk, a stale config — lost the whole rescue and
    # the reason vanished into the truncated error field).
    errors: list[str] = []
    dev = None
    if (
        os.environ.get("SKYLARK_BENCH_SIM_POISON") == "1"
        and os.environ.get("SKYLARK_BENCH_CPU_REEXEC") != "1"
    ):
        # Test hook: pretend the in-process rescue cannot revive CPU
        # (poisoned plugin registry), forcing the re-exec path below —
        # the only way a regression test can exercise execvpe without a
        # real broken plugin install.
        errors.append("sim-poison: in-process cpu rescue suppressed")
    else:
        dev = _cpu_attempts(errors)
    if dev is None:
        # UNCONDITIONAL re-exec (BENCH_r05 follow-up): the old
        # ``JAX_PLATFORMS=cpu``-means-broken-host heuristic was wrong —
        # an in-process CPU init failure usually means the plugin
        # registry is poisoned IN THIS INTERPRETER (clear_backends()
        # resurrects the cached failure), which a fresh interpreter
        # survives.  The loop guard inside _reexec_cpu is the real
        # protection against a genuinely CPU-less host exec-looping.
        exec_err = _reexec_cpu(sentinel.error + "; " + " | ".join(errors))
        if exec_err:
            errors.append(exec_err)
    if dev is None:
        sentinel.error += "; cpu-fallback failed: " + " | ".join(errors)
        return sentinel
    _BACKEND_TAG = "cpu-fallback"
    print(
        json.dumps(
            {
                "metric": "backend fallback",
                "value": 0,
                "unit": "info",
                "vs_baseline": 0,
                "backend": _BACKEND_TAG,
                "error": sentinel.error[:500],
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    return dev


def _cpu_attempts(errors: list[str]):
    """The in-process slice of the CPU rescue: three firewalled attempts
    to re-point jax at host CPU.  Returns the device or ``None``."""
    for attempt in range(3):
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception as e:  # noqa: BLE001 — best-effort; env var rules
            errors.append(f"config: {type(e).__name__}: {e}")
        try:
            import jax.extend.backend as _eb

            _eb.clear_backends()  # drop the cached accelerator-init failure
        except Exception as e:  # noqa: BLE001 — best-effort
            errors.append(f"clear: {type(e).__name__}: {e}")
        try:
            return jax.devices("cpu")[0]
        except Exception as e:  # noqa: BLE001 — retry; CPU init is local
            errors.append(f"devices[{attempt}]: {type(e).__name__}: {e}")
            time.sleep(2.0)
    return None


def main() -> None:
    global _FINAL
    # The axon sitecustomize force-sets jax_platforms to "axon,cpu",
    # overriding the JAX_PLATFORMS env var; restore env semantics so a
    # CPU smoke run (JAX_PLATFORMS=cpu python bench.py) cannot hang on
    # a congested tunnel it never wanted.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    def _flush_on_term(signum, frame):
        _print_final()
        sys.exit(0)

    # Handler + provisional FAILED _FINAL are installed BEFORE backend
    # init: a driver timeout that fires mid-retry-loop still flushes a
    # parseable artifact (the round-4 failure mode).
    provisional = {
        "metric": "JLT dense sketch-apply throughput "
        "(FAILED: killed-during-backend-init)",
        "value": -1,
        "unit": "error",
        "vs_baseline": 0,
    }
    _FINAL = dict(provisional, submetrics=[dict(provisional)])
    signal.signal(signal.SIGTERM, _flush_on_term)

    dev = _init_backend()
    if isinstance(dev, _BackendUnavailable):
        # Before declaring the round lost, try the host CPU: a real
        # (tagged) cpu-fallback table beats a -1 error artifact in every
        # downstream comparison.
        dev = _cpu_fallback(dev)
    if isinstance(dev, _BackendUnavailable):
        # Same last-line contract as every other terminal path: the
        # FAILED headline carries a (single-row) submetrics table and
        # goes out through _print_final.
        row = {
            "metric": "JLT dense sketch-apply throughput "
            "(FAILED: backend-unavailable)",
            "value": -1,
            "unit": "error",
            "vs_baseline": 0,
            "error": dev.error[:800],
        }
        print(json.dumps(row), flush=True)
        _FINAL = dict(row, submetrics=[dict(row)])
        _print_final()
        sys.exit(0)
    # Init succeeded: re-stamp the provisional so a kill during the
    # headline bench is attributed to the right phase (_FINAL holds
    # copies, so rebuild it rather than mutating `provisional`).
    provisional["metric"] = (
        "JLT dense sketch-apply throughput (FAILED: killed-before-headline)"
    )
    _FINAL = dict(provisional, submetrics=[dict(provisional)])
    on_tpu = dev.platform in ("tpu", "axon")
    peak = _peak_tflops(dev)
    table: list[dict] = []

    def _mid_run_rescue(e: BaseException) -> bool:
        """The accelerator died AFTER a healthy init (tunnel drop,
        multichip backend revoked mid-list): drop to host CPU once so
        every remaining config records a real (tagged) number instead of
        a -1 FAILED row — the same contract _cpu_fallback gives the
        init-exhausted branch.  Configs already measured keep their
        accelerator rows; the backend tag marks the switch point."""
        nonlocal on_tpu, peak
        if not _backend_died(e):
            return False
        if _BACKEND_TAG is not None:
            # Already on the in-process CPU fallback and the backend
            # STILL died: poisoned plugin-registry state survived
            # clear_backends().  Escalate to the fresh-interpreter
            # re-exec (loop-guarded — a process that already IS the
            # re-exec gets the guard string back and degrades to a
            # FAILED row instead of exec-looping).
            _reexec_cpu(f"mid-run on fallback: {type(e).__name__}: {e}")
            return False
        dev2 = _cpu_fallback(
            _BackendUnavailable(f"mid-run: {type(e).__name__}: {e}")
        )
        if isinstance(dev2, _BackendUnavailable):
            return False
        on_tpu = False  # the config lambdas read this cell at call time
        peak = _peak_tflops(dev2)
        return True

    # -- flagships FIRST (round 4): a budget/timeout can no longer eat
    # the rows the driver exists to record.  The headline is firewalled
    # like every other config — a congested-tunnel RuntimeError from
    # _rep_diff must degrade to a FAILED row (after one CPU-rescue
    # retry), not abort the whole bench before anything printed.
    def _headline_row():
        tflops, _ = bench_jlt(on_tpu, table)
        row = {
            "metric": "JLT dense sketch-apply throughput",
            "value": round(float(tflops), 3),
            "unit": "TFLOP/s/chip",
            "vs_baseline": round(float(tflops) / peak, 4),
        }
        if _LAST_CONTENTION is not None:
            row["contention"] = _LAST_CONTENTION
        return row

    try:
        headline_row = _headline_row()
    except Exception as e:  # noqa: BLE001 — report, don't abort
        err = e
        headline_row = None
        if _mid_run_rescue(e):
            try:
                headline_row = _headline_row()
            except Exception as e2:  # noqa: BLE001
                err = e2
        if headline_row is None:
            headline_row = {
                "metric": (
                    f"JLT dense sketch-apply throughput (FAILED: {type(err).__name__})"
                ),
                "value": -1,
                "unit": "error",
                "vs_baseline": 0,
            }
    headline_row["backend"] = _resolved_backend()
    table.append(dict(headline_row))
    print(json.dumps(headline_row), flush=True)
    # submetrics aliases the LIVE table: rows appended below are included
    # when the final line prints (or the SIGTERM flush fires).
    _FINAL = dict(headline_row, submetrics=table)

    try:
        if not _selected("streaming KRR"):
            raise _FilteredOut
        bench_streaming_krr(on_tpu, table)
    except _FilteredOut:
        _emit(
            "streaming KRR (skipped: filter)", -1, "skipped", 0, table,
            contention=None,
        )
    except Exception as e:  # noqa: BLE001 — report, don't abort
        if _mid_run_rescue(e):
            try:
                bench_streaming_krr(on_tpu, table)
            except Exception as e:  # noqa: BLE001
                _emit(
                    f"streaming KRR (FAILED: {type(e).__name__})", -1,
                    "error", 0, table, contention=None,
                )
        else:
            _emit(
                f"streaming KRR (FAILED: {type(e).__name__})", -1, "error",
                0, table, contention=None,
            )

    # -- secondaries, descending importance.  Each carries a rough cost
    # estimate (compile + pooled measurement, seconds on the tunnel);
    # when the remaining budget cannot plausibly fit a config it emits
    # an explicit skip row instead of dying mid-list (VERDICT r3 #1).
    # Never-captured rows ride near the front (VERDICT r4 item 3: QRFT /
    # RLT sat at positions 13-14 for three rounds and never landed; the
    # FJLT f32 row also moves up — it is the round-5 fused-kernel
    # measurement).  Rows with round-2/3 captures queue behind them.
    secondaries = [
        # Round-18 rows lead (never captured): the front-door result
        # cache + multi-tenant QoS lanes (docs/serving.md, "QoS +
        # caching") — hot-set QPS cache-on vs off, and the
        # adversarial-tenant fairness p99 pair.
        # Round-20 rows lead (never captured): latency attribution
        # (docs/observability.md, "Latency attribution") — phase-clock
        # on/off QPS (floor 0.95x) and the phase-decomposition
        # sum/e2e ratio.
        ("serve attribution", 60,
         lambda: bench_attribution(on_tpu, table)),
        # Round-19 rows lead (never captured): durable serve state
        # (docs/serving.md, "Durable serving") — update-op QPS with the
        # write-ahead journal on vs off (floor 0.8x) and
        # kill-to-placeable recovery latency, compacted vs replay-only.
        ("serve durability", 60, lambda: bench_durability(on_tpu, table)),
        ("serve cache", 60, lambda: bench_cache(on_tpu, table)),
        # Round-17 rows next (never captured): elastic multi-host
        # BlockADMM training (docs/distributed_training.md) — world=1
        # rows/s vs the in-process solver, kill-to-first-consensus
        # resume latency, and the bf16 train-step submetric.
        ("distributed train", 120, lambda: bench_train(on_tpu, table)),
        # Round-16 rows next (never captured): chaos-driven autoscaler +
        # epoch-versioned live registries (docs/serving.md, "serve
        # through change") — live fold/append epoch-bump latency,
        # scale-up reaction, and rolling-drain QPS.
        ("serve autoscale", 60, lambda: bench_autoscale(on_tpu, table)),
        # Round-15 row next (never captured): streamed graph sketching
        # + elastic ASE resume + served PPR QPS (docs/graph.md).
        ("graph analytics", 60, lambda: bench_graph(on_tpu, table)),
        # Round-14 rows next (never captured): the certified
        # mixed-precision refine solve (docs/performance.md) and the
        # served cond-est endpoint (docs/serving.md).
        ("refine solve", 60, lambda: bench_refine(on_tpu, table)),
        ("serve cond-est", 40, lambda: bench_cond_est(on_tpu, table)),
        # Plan-cache cold/warm first among the never-captured rows: it is
        # the round-6 perf-layer measurement and costs almost nothing.
        ("plan cache", 40, lambda: bench_plan_cache(on_tpu, table)),
        # Guard overhead next among never-captured rows: the round-6
        # robustness-layer measurement (docs/numerical_health.md).
        ("guard overhead", 60, lambda: bench_guard_overhead(on_tpu, table)),
        # Telemetry ratios ride with the never-captured rows: cheap, and
        # they certify the observability layer on real hardware.
        ("telemetry", 60, lambda: bench_telemetry(on_tpu, table)),
        # Adaptive-policy cold/warm rides with the never-captured rows:
        # the round-9 warm-start contract (docs/autotuning.md) — plan
        # compile seconds with and without the profile-store replay.
        ("policy", 60, lambda: bench_policy(on_tpu, table)),
        # Serving SLO rides with the never-captured rows: the round-10
        # throughput contract (docs/serving.md) — coalesced vs serial
        # QPS with p50/p99 for single-row LS-solve and KRR-predict.
        ("serve SLO", 90, lambda: bench_serve(on_tpu, table)),
        # Fleet scaling rides behind it: the round-13 measurement
        # (docs/serving.md fleet section) — 2 pinned workers and a
        # 2-replica routed fleet vs one worker, plus the sharded-
        # dispatch parity-probe census.
        ("serve fleet", 90, lambda: bench_fleet(on_tpu, table)),
        # Elastic resume latency rides with them: the round-7
        # fault-tolerance measurement (docs/fault_tolerance.md), world=1
        # dry-run scale so it costs seconds, not minutes.
        ("elastic resume", 30, lambda: bench_elastic_resume(on_tpu, table)),
        # Fused stream-chunk rides with the never-captured rows: the
        # round-8 kernel-layer measurement (fused single-launch chunks
        # vs the two-step composite on identical data).
        ("fused stream-chunk", 90, lambda: bench_stream_chunk(on_tpu, table)),
        # Overlapped streaming rides with it: the round-11 measurement
        # (async-dispatch overlap vs per-step sync on identical data,
        # plus the hidden-transfer-fraction submetric).
        ("stream overlap", 90, lambda: bench_overlap(on_tpu, table)),
        ("streaming SVD", 150, lambda: bench_streaming_svd(on_tpu, table)),
        ("sparse CWT", 150, lambda: bench_sparse_cwt(on_tpu, table)),
        ("QRFT", 90, lambda: bench_qrft(on_tpu, table)),
        ("RLT", 80, lambda: bench_rlt(on_tpu, table)),
        ("FJLT f32", 90, lambda: bench_fjlt(on_tpu, jnp.float32, 44.8, table)),
        ("FJLT bf16", 80, lambda: bench_fjlt(on_tpu, jnp.bfloat16, 5.9, table)),
        ("CWT", 80, lambda: bench_cwt(on_tpu, table)),
        ("MMT", 80, lambda: bench_mmt(on_tpu, table)),
        ("FastRFT bf16", 100, lambda: bench_frft(on_tpu, jnp.bfloat16, 16.1, table)),
        ("PPT bf16", 120, lambda: bench_ppt(on_tpu, jnp.bfloat16, 70.7, table)),
        ("FastRFT f32", 120, lambda: bench_frft(on_tpu, jnp.float32, 51.2, table)),
        ("PPT f32", 150, lambda: bench_ppt(on_tpu, jnp.float32, 149.4, table)),
        ("ridge", 80, lambda: bench_ridge(on_tpu, table)),
        ("ADMM", 160, lambda: bench_admm(on_tpu, table)),
    ]
    for name, est_s, fn in secondaries:
        if not _selected(name):
            _emit(
                f"{name} (skipped: filter)", -1, "skipped", 0, table,
                contention=None,
            )
            continue
        if on_tpu and _remaining() < 0.6 * est_s:
            _emit(
                f"{name} (skipped: budget)", -1, "skipped", 0, table,
                contention=None,
            )
            continue
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — report, don't abort
            if _mid_run_rescue(e):
                # Backend died mid-list: retry THIS config on the CPU
                # fallback (the lambda re-reads on_tpu), then continue
                # down the list there.
                try:
                    fn()
                    continue
                except Exception as e2:  # noqa: BLE001
                    e = e2
            _emit(
                f"{name} (FAILED: {type(e).__name__})", -1, "error", 0, table,
                contention=None,
            )

    _print_final()


if __name__ == "__main__":
    main()
