"""Least-squares solver comparison (≙ ``examples/least_squares.cpp:10-50``).

Solves one overdetermined problem with the exact, sketch-and-solve, and
Blendenpik solvers and prints residual / normal-equation residual /
distance-to-exact for each — the same three quality metrics the reference
example prints.

Run: python examples/least_squares_demo.py [m] [n]
"""

import os
import sys

# runnable from anywhere: repo root is one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

import libskylark_tpu as sky


def report(name, A, b, x, x_exact):
    r = np.asarray(A @ x - b)
    res = np.linalg.norm(r)
    res_atr = np.linalg.norm(np.asarray(A.T @ jnp.asarray(r)))
    fac = np.linalg.norm(np.asarray(x) - x_exact) / max(np.linalg.norm(x_exact), 1e-30)
    print(f"{name:<16} ||Ax-b|| = {res:.6e}   ||A'r|| = {res_atr:.3e}   "
          f"||x-x*||/||x*|| = {fac:.3e}")


def main():
    m, n = (int(x) for x in (sys.argv[1:3] + [50000, 500][len(sys.argv) - 1 :]))
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal(m).astype(np.float32))

    x_exact = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]

    x = sky.linalg.exact_least_squares(A, b)
    report("exact (QR)", A, b, x, x_exact)

    x = sky.linalg.approximate_least_squares(A, b, sky.SketchContext(seed=1))
    report("sketch-and-solve", A, b, x, x_exact)

    x, info = sky.linalg.faster_least_squares(A, b, sky.SketchContext(seed=2))
    report(f"blendenpik({int(info['iterations'])}it)", A, b, x, x_exact)


if __name__ == "__main__":
    main()
