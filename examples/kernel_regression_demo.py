"""Random-feature kernel regression end-to-end.

Trains a Gaussian-kernel model on synthetic data three ways (exact KRR,
random-feature ridge, BlockADMM) and compares test error — the skylark-ml
pipeline without the CLI.

Run: python examples/kernel_regression_demo.py
"""

import os
import sys

# runnable from anywhere: repo root is one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

import libskylark_tpu as sky


def main():
    rng = np.random.default_rng(0)
    n, d = 4000, 10
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (np.sin(X.sum(1)) + 0.1 * rng.standard_normal(n)).astype(np.float32)
    Xtr, ytr, Xte, yte = X[:3000], y[:3000], X[3000:], y[3000:]

    kernel = sky.ml.GaussianKernel(d, sigma=2.5)

    def test_err(model):
        pred = np.asarray(model.predict(jnp.asarray(Xte)))[:, 0]
        return np.sqrt(np.mean((pred - yte) ** 2))

    m1 = sky.ml.kernel_ridge(kernel, jnp.asarray(Xtr), jnp.asarray(ytr), 0.05)
    print(f"exact KRR           test RMSE = {test_err(m1):.4f}")

    m2 = sky.ml.approximate_kernel_ridge(
        kernel, jnp.asarray(Xtr), jnp.asarray(ytr), 0.05, 2048,
        sky.SketchContext(seed=1),
    )
    print(f"random-feature KRR  test RMSE = {test_err(m2):.4f}")

    ctx = sky.SketchContext(seed=2)
    maps = [kernel.create_rft(512, "regular", ctx) for _ in range(4)]
    solver = sky.ml.BlockADMMSolver(
        "squared", "l2", maps,
        sky.ml.ADMMParams(rho=1.0, lam=1e-4, maxiter=30),
    )
    m3 = solver.train(Xtr, ytr, regression=True)
    print(f"BlockADMM           test RMSE = {test_err(m3):.4f}")

    m2.save("/tmp/krr_model.json")
    m2b = sky.ml.FeatureMapModel.load("/tmp/krr_model.json")
    print(f"model round-trip:   test RMSE = {test_err(m2b):.4f} (identical)")


if __name__ == "__main__":
    main()
