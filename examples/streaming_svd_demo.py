"""Streaming randomized SVD demo (≙ ``nla/skylark_svd.cpp --profile``).

Factors a logical matrix that is never materialized: row panels are
regenerated from the counter stream inside each sweep, so memory stays at
one panel + small accumulators no matter how large m is.  Checks the
factorization quality against a materialized copy (small default sizes;
scale m up to the 1e7-row regime with the same code).

Run: python examples/streaming_svd_demo.py [m] [n] [rank]
"""

import os
import sys

# runnable from anywhere: repo root is one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import libskylark_tpu as sky
from libskylark_tpu.linalg import (
    SVDParams,
    streaming_approximate_svd,
    synthetic_lowrank_blocks,
)


def main():
    m, n, k = (
        int(x) for x in (sys.argv[1:4] + [65536, 256, 10][len(sys.argv) - 1 :])
    )
    block_rows = max(1024, m // 16)
    if m % block_rows:  # trim m to a panel multiple (demo semantics)
        trimmed = m - m % block_rows
        print(f"trimming m {m} -> {trimmed} (multiple of {block_rows} panels)")
        m = trimmed

    ctx = sky.SketchContext(seed=38734)
    block_fn = synthetic_lowrank_blocks(ctx, m, n, k, noise=0.01)
    u_block, s, V = streaming_approximate_svd(
        block_fn, (m, n), k, ctx,
        SVDParams(num_iterations=1), block_rows=block_rows,
    )
    print(f"streamed {m}x{n} in {m // block_rows} panels of {block_rows} rows")
    print(f"leading singular values: {np.asarray(s)[:5]}")

    if m * n <= 1 << 24:  # materialize only at demo sizes
        A = np.vstack(
            [np.asarray(block_fn(i, block_rows)) for i in range(0, m, block_rows)]
        )
        U = np.vstack(
            [np.asarray(u_block(i)) for i in range(m // block_rows)]
        )
        rec = U @ np.diag(np.asarray(s)) @ np.asarray(V).T
        rel = np.linalg.norm(rec - A) / np.linalg.norm(A)
        print(f"rank-{k} reconstruction relative error: {rel:.2e}")
        ortho = np.abs(U.T @ U - np.eye(k)).max()
        print(f"U orthonormality defect: {ortho:.2e}")


if __name__ == "__main__":
    main()
