"""Nonlinear estimator demo (≙ the python-skylark ``ml/nonlinear.py``
doctest workflow): exact kernel RLS vs its three approximations on one
classification problem.

Run: python examples/nonlinear_models_demo.py
"""

import os
import sys

# runnable from anywhere: repo root is one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import libskylark_tpu as sky
from libskylark_tpu.ml import (
    RLS,
    GaussianKernel,
    NystromRLS,
    SketchPCR,
    SketchRLS,
    classification_accuracy,
)


def main():
    rng = np.random.default_rng(0)
    n_per, d = 200, 10
    X = np.vstack(
        [rng.standard_normal((n_per, d)), rng.standard_normal((n_per, d)) + 3.0]
    )
    y = np.array([1] * n_per + [2] * n_per)
    perm = rng.permutation(len(y))
    X, y = X[perm], y[perm]
    Xtr, ytr, Xte, yte = X[:300], y[:300], X[300:], y[300:]

    kernel = GaussianKernel(d, sigma=3.0)
    ctx = sky.SketchContext(seed=123)

    models = [
        ("RLS (exact kernel)", RLS(kernel).train(Xtr, ytr, 1e-3)),
        (
            "SketchRLS (256 random features)",
            SketchRLS(kernel).train(Xtr, ytr, ctx, 256, 1e-3),
        ),
        (
            "NystromRLS (64 leverage-weighted landmarks)",
            NystromRLS(kernel).train(
                Xtr, ytr, ctx, 64, 1e-3, probdist="leverages"
            ),
        ),
        ("SketchPCR (rank 32)", SketchPCR(kernel).train(Xtr, ytr, ctx, 32)),
    ]
    for name, model in models:
        acc = float(classification_accuracy(model.predict(Xte), yte))
        print(f"{name:45s} test accuracy {acc:5.1f}%")


if __name__ == "__main__":
    main()
