"""Streaming kernel-ridge demo (rows AND features streamed).

The single-chip machinery behind the 10M×4096 north-star
(BASELINE.md): ``streaming_kernel_ridge`` never holds X or a feature
chunk — ``block_fn`` yields row panels (here sliced from a small
in-memory X; at scale, counter-generated or IO-backed), features are
regenerated per panel, and only one panel plus the (n, t) residual is
resident.  Checks predictions against ``large_scale_kernel_ridge`` on
the same data (identical BCD updates from the same context).

Run: python examples/streaming_krr_demo.py [n] [d] [features]
"""

import os
import sys

# runnable from anywhere: repo root is one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from libskylark_tpu import SketchContext
from libskylark_tpu.ml import (
    GaussianKernel,
    KrrParams,
    large_scale_kernel_ridge,
    streaming_kernel_ridge,
)


def main():
    n, d, s = (
        int(x) for x in (sys.argv[1:4] + [4096, 32, 256][len(sys.argv) - 1 :])
    )
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    y = jnp.asarray(np.tanh(np.asarray(X) @ rng.standard_normal(d)), jnp.float32)
    kernel = GaussianKernel(d, sigma=float(np.sqrt(d)))
    params = KrrParams(max_split=s // 2, iter_lim=15, tolerance=1e-7)

    def block_fn(start, rows):
        return jax.lax.dynamic_slice(X, (start, 0), (rows, d))

    model = streaming_kernel_ridge(
        kernel, block_fn, (n, d), y, 0.1, s, SketchContext(seed=7),
        params, block_rows=max(256, n // 16), feature_dtype=jnp.float32,
    )
    pred = np.asarray(model.predict(X))[:, 0]
    print(f"streaming KRR: n={n} d={d} s={s}, "
          f"corr(pred, y) = {np.corrcoef(pred, np.asarray(y))[0, 1]:.4f}")

    ref = large_scale_kernel_ridge(
        kernel, X, y, 0.1, s, SketchContext(seed=7), params
    )
    rel = np.abs(pred - np.asarray(ref.predict(X))[:, 0]).max() / (
        np.abs(pred).max() + 1e-30
    )
    print(f"vs large_scale_kernel_ridge (same context): max rel {rel:.2e}")


if __name__ == "__main__":
    main()
