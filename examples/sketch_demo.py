"""Dense sketch demo across layouts (≙ ``examples/hp_dense.cpp:1-110``).

Applies a JLT rowwise/columnwise, locally and sharded over the default
mesh, and checks the sharded results match the local ones — the
reference's distribution-combination sweep collapsed to sharding specs.

Run: python examples/sketch_demo.py [m] [n] [s]
"""

import os
import sys

# runnable from anywhere: repo root is one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

import libskylark_tpu as sky
from libskylark_tpu.parallel import default_mesh, rowwise_sharded, shard_rows


def main():
    m, n, s = (int(x) for x in (sys.argv[1:4] + [2048, 512, 64][len(sys.argv) - 1 :]))
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))

    ctx = sky.SketchContext(seed=38734)
    S = sky.sketch.JLT(n, s, ctx)

    SA_row = S.apply(A, "rowwise")  # A @ Omega^T
    SA_col = S.apply(A.T, "columnwise")  # Omega @ A^T
    print(f"rowwise  {A.shape} -> {SA_row.shape}")
    print(f"columnwise {A.T.shape} -> {SA_col.shape}")
    print(
        "norm preservation (rowwise): "
        f"{float(jnp.linalg.norm(SA_row) / jnp.linalg.norm(A)):.4f}"
    )

    mesh = default_mesh()
    out = rowwise_sharded(S, shard_rows(A, mesh), mesh)
    delta = float(jnp.max(jnp.abs(out - SA_row)))
    print(f"sharded ({tuple(mesh.shape.values())} mesh) vs local: max |delta| = {delta}")

    # Serialization round-trip (~100 bytes of JSON).
    js = S.to_json()
    S2 = sky.sketch.from_json(js)
    same = bool(jnp.all(S2.apply(A, "rowwise") == SA_row))
    print(f"JSON round-trip ({len(js)} bytes): bit-identical = {same}")


if __name__ == "__main__":
    main()
