"""Sharded sparse sketching demo (≙ the CombBLAS path of
``sketch/hash_transform_CombBLAS.hpp`` + ``examples/hp_dense.cpp``'s
distribution sweep, for sparse inputs).

Shows the three P6 schedule families on the default mesh:
  1. dense-merge 1-D (``columnwise_sharded_sparse`` — one psum);
  2. sparse-out 1-D (``columnwise_sharded_sparse_out`` — one all_to_all
     entry exchange, output row-block-sharded BCOO, never densified);
  3. sparse-out 2-D (``columnwise_sharded_sparse_out_2d`` — input AND
     output on the √p×√p grid, exchange over the mesh row axis only);
and checks all of them against the local BCOO apply.

Run: python examples/sharded_sparse_demo.py [n] [m] [s]
"""

import os
import sys

# runnable from anywhere: repo root is one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

import libskylark_tpu as sky
from libskylark_tpu.parallel import (
    columnwise_sharded_sparse,
    columnwise_sharded_sparse_out,
    columnwise_sharded_sparse_out_2d,
    default_mesh,
)


def main():
    n, m, s = (
        int(x) for x in (sys.argv[1:4] + [4096, 256, 512][len(sys.argv) - 1 :])
    )
    rng = np.random.default_rng(0)
    M = rng.standard_normal((n, m)).astype(np.float32)
    M[rng.random((n, m)) > 0.05] = 0.0  # ~5% dense
    A = jsparse.BCOO.fromdense(jnp.asarray(M))
    print(f"A: {A.shape} BCOO, nse={A.nse}")

    S = sky.sketch.CWT(n, s, sky.SketchContext(seed=1729))
    ref = S.apply(A, "columnwise")  # local BCOO→BCOO, deferred dups
    ref_dense = np.asarray(ref.todense())

    mesh = default_mesh()
    out_dense = columnwise_sharded_sparse(S, A, mesh)
    np.testing.assert_allclose(
        np.asarray(out_dense), ref_dense, rtol=1e-5, atol=1e-5
    )
    print(f"1. dense-merge 1-D on {mesh.shape}: OK (psum into (S, m))")

    out_sp = columnwise_sharded_sparse_out(S, A, mesh)
    np.testing.assert_allclose(
        np.asarray(out_sp.todense()), ref_dense, rtol=1e-5, atol=1e-5
    )
    print(
        f"2. sparse-out 1-D: OK (per-shard entry arrays {out_sp.data.shape},"
        f" to_bcoo nse={out_sp.to_bcoo().nse})"
    )

    S2 = sky.sketch.CWT(s, 64, sky.SketchContext(seed=2027))
    chained = out_sp.sketch_columnwise(S2, dense_output=True)
    ref2 = np.asarray(S2.apply(ref, "columnwise").todense())
    np.testing.assert_allclose(np.asarray(chained), ref2, rtol=1e-5, atol=1e-5)
    print(f"2b. device-resident chain S2·(S1·A): OK {chained.shape}")

    # default_mesh() is already a near-square 2-axis grid over all
    # devices; odd device counts or non-dividing shapes skip with the
    # library's own error rather than crashing mid-demo.
    try:
        out_2d = columnwise_sharded_sparse_out_2d(S, A, mesh)
        np.testing.assert_allclose(
            np.asarray(out_2d.todense()), ref_dense, rtol=1e-5, atol=1e-5
        )
        print(
            f"3. sparse-out 2-D on grid {tuple(mesh.shape.values())}: OK "
            f"(col_block={out_2d.col_block})"
        )
    except ValueError as e:
        print(f"3. sparse-out 2-D: skipped on this mesh ({e})")


if __name__ == "__main__":
    main()
