"""Native C core tests: bit/tolerance parity with the JAX path, C API
round-trips, LIBSVM parser equivalence and speed sanity."""

import json

import numpy as np
import pytest

from libskylark_tpu import SketchContext, native
from libskylark_tpu.core.random import sample
from libskylark_tpu.sketch import CWT, JLT, UST, WZT, from_json

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++)"
)


class TestRNGParity:
    def test_integer_draws_bit_identical(self):
        # rademacher: exact parity with the JAX threefry stream.
        out = np.empty(1000, np.float64)
        native.lib().sl_sample(12345, 777, 1000, 2, 0, out)
        ref = np.asarray(sample("rademacher", 12345, 777, 1000, dtype="float64"))
        np.testing.assert_array_equal(out, ref)

    def test_uniform_bit_identical(self):
        out = np.empty(500, np.float64)
        native.lib().sl_sample(9, 0, 500, 4, 0, out)
        ref = np.asarray(sample("uniform", 9, 0, 500, dtype="float64"))
        np.testing.assert_array_equal(out, ref)

    def test_normal_cauchy_close(self):
        for dist, code in [("normal", 0), ("cauchy", 1), ("exponential", 3)]:
            out = np.empty(2000, np.float64)
            native.lib().sl_sample(42, 100, 2000, code, 0, out)
            ref = np.asarray(sample(dist, 42, 100, 2000, dtype="float64"))
            np.testing.assert_allclose(out, ref, rtol=1e-12, atol=1e-12)


class TestNativeKernelGram:
    """≙ capi/ckernel.cpp: native grams match the JAX kernel layer."""

    @pytest.mark.parametrize("name,params,pykernel", [
        ("linear", {}, lambda ml, d: ml.LinearKernel(d)),
        ("gaussian", {"p1": 2.0}, lambda ml, d: ml.GaussianKernel(d, 2.0)),
        ("polynomial", {"p1": 3, "p2": 1.5, "p3": 0.5},
         lambda ml, d: ml.PolynomialKernel(d, 3, 1.5, 0.5)),
        ("laplacian", {"p1": 1.5}, lambda ml, d: ml.LaplacianKernel(d, 1.5)),
        ("matern", {"p1": 1.5, "p2": 2.0},
         lambda ml, d: ml.MaternKernel(d, 1.5, 2.0)),
    ])
    def test_matches_jax_kernels(self, name, params, pykernel):
        from libskylark_tpu import ml

        rng = np.random.default_rng(0)
        X = rng.standard_normal((12, 5))
        Y = rng.standard_normal((7, 5))
        K = native.kernel_gram(name, X, Y, **params)
        ref = np.asarray(pykernel(ml, 5).gram(X, Y))
        np.testing.assert_allclose(K, ref, rtol=1e-10, atol=1e-12)

    def test_expsemigroup(self):
        from libskylark_tpu.ml import ExpSemigroupKernel

        rng = np.random.default_rng(1)
        X = np.abs(rng.standard_normal((8, 4)))
        K = native.kernel_gram("expsemigroup", X, p1=0.3)
        ref = np.asarray(ExpSemigroupKernel(4, 0.3).gram(X))
        np.testing.assert_allclose(K, ref, rtol=1e-10)


class TestNativeNLA:
    """≙ capi/cnla.cpp: native randomized SVD / sketch-and-solve LS."""

    def test_svd_exact_on_low_rank(self):
        rng = np.random.default_rng(2)
        A = rng.standard_normal((120, 30)) @ rng.standard_normal((30, 40))
        r = 30
        ctx = native.NativeContext(seed=7)
        U, S, V = native.approximate_svd(ctx, A, r, num_iterations=2)
        rec = U @ np.diag(S) @ V.T
        assert np.linalg.norm(rec - A) / np.linalg.norm(A) < 1e-8
        np.testing.assert_allclose(U.T @ U, np.eye(r), atol=1e-10)
        np.testing.assert_allclose(V.T @ V, np.eye(r), atol=1e-10)
        s_true = np.linalg.svd(A, compute_uv=False)[:r]
        np.testing.assert_allclose(S, s_true, rtol=1e-8)

    def test_svd_ordering_and_shapes(self):
        rng = np.random.default_rng(3)
        A = rng.standard_normal((50, 20))
        ctx = native.NativeContext(seed=9)
        U, S, V = native.approximate_svd(ctx, A, 5, num_iterations=3)
        assert U.shape == (50, 5) and S.shape == (5,) and V.shape == (20, 5)
        assert np.all(np.diff(S) <= 1e-12)

    def test_least_squares_residual(self):
        rng = np.random.default_rng(4)
        A = rng.standard_normal((2000, 30))
        x_true = rng.standard_normal(30)
        b = A @ x_true
        ctx = native.NativeContext(seed=11)
        x = native.approximate_least_squares(ctx, A, b)
        # consistent system: sketch-and-solve recovers the solution
        np.testing.assert_allclose(x, x_true, rtol=1e-6, atol=1e-8)
        # multi-RHS
        B = np.stack([b, 2 * b], axis=1)
        X2 = native.approximate_least_squares(ctx, A, B)
        np.testing.assert_allclose(X2[:, 1], 2 * x_true, rtol=1e-6, atol=1e-8)


class TestNativeModelPredict:
    """≙ capi/cml.cpp: native prediction from a saved FeatureMapModel."""

    def test_matches_python_predict(self, tmp_path):
        from libskylark_tpu.ml import FeatureMapModel, GaussianKernel

        rng = np.random.default_rng(5)
        d, s, k = 6, 32, 3
        ctx = SketchContext(seed=31)
        kernel = GaussianKernel(d, sigma=2.0)
        maps = [kernel.create_rft(s, "regular", ctx) for _ in range(2)]
        W = rng.standard_normal((2 * s, k))
        model = FeatureMapModel(maps, W, scale_maps=True, input_dim=d)
        path = tmp_path / "m.json"
        model.save(path)

        X = rng.standard_normal((20, d))
        ref = np.asarray(model.predict(X))
        out = native.model_predict(path, X)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-8)

    def test_linear_model_no_maps(self, tmp_path):
        from libskylark_tpu.ml import FeatureMapModel

        rng = np.random.default_rng(6)
        W = rng.standard_normal((4, 2))
        model = FeatureMapModel([], W, input_dim=4)
        path = tmp_path / "lin.json"
        model.save(path)
        X = rng.standard_normal((7, 4))
        np.testing.assert_allclose(
            native.model_predict(path, X), X @ W, rtol=1e-12
        )

    def test_missing_file_errors(self, tmp_path):
        from libskylark_tpu.utils.exceptions import SkylarkError

        with pytest.raises(SkylarkError):
            native.model_predict(tmp_path / "nope.json", np.zeros((2, 3)))

    @pytest.mark.slow
    def test_1d_coef_squeezes_like_python(self, tmp_path):
        from libskylark_tpu.ml import FeatureMapModel, GaussianKernel

        rng = np.random.default_rng(7)
        ctx = SketchContext(seed=41)
        maps = [GaussianKernel(4, 1.5).create_rft(16, "regular", ctx)]
        W1 = rng.standard_normal(16)  # 1-D coefficients
        model = FeatureMapModel(maps, W1, input_dim=4)
        path = tmp_path / "m1d.json"
        model.save(path)
        X = rng.standard_normal((9, 4))
        ref = np.asarray(model.predict(X))
        out = native.model_predict(path, X)
        assert out.shape == ref.shape  # (9,), not (9, 1)
        np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-9)

    @pytest.mark.slow
    def test_handle_reuse(self, tmp_path):
        from libskylark_tpu.ml import FeatureMapModel, GaussianKernel

        rng = np.random.default_rng(8)
        ctx = SketchContext(seed=43)
        maps = [GaussianKernel(3, 2.0).create_rft(8, "regular", ctx)]
        model = FeatureMapModel(maps, rng.standard_normal((8, 2)), input_dim=3)
        path = tmp_path / "mh.json"
        model.save(path)
        nm = native.NativeModel(path)
        assert nm.num_outputs == 2
        X = rng.standard_normal((5, 3))
        out1 = nm.predict(X)
        out2 = nm.predict(X)  # repeated predicts on one handle
        np.testing.assert_array_equal(out1, out2)
        np.testing.assert_allclose(
            out1, np.asarray(model.predict(X)), rtol=1e-6, atol=1e-9
        )

    def test_old_version_sketch_warns(self):
        from libskylark_tpu.sketch import JLT, from_json

        S = JLT(10, 4, SketchContext(seed=1))
        d = S.serialize()
        d["skylark_version"] = 1
        import json as _json

        with pytest.warns(UserWarning, match="stream revision"):
            from_json(_json.dumps(d))

    def test_c_side_stream_version(self, tmp_path):
        """Pure-C consumers detect pre-revision models: sl_model_load
        parses skylark_version; sl_model_stream_version exposes it
        (ADVICE round 1, skylark_native.cpp sl_model_load)."""
        import ctypes
        import json as _json

        from libskylark_tpu.ml import FeatureMapModel, GaussianKernel

        L = native.lib()
        assert L.sl_stream_revision() == 2

        rng = np.random.default_rng(9)
        ctx = SketchContext(seed=47)
        maps = [GaussianKernel(3, 2.0).create_rft(8, "regular", ctx)]
        model = FeatureMapModel(maps, rng.standard_normal((8, 2)), input_dim=3)
        path = tmp_path / "mv.json"
        model.save(path)

        h = ctypes.c_void_p()
        assert L.sl_model_load(str(path).encode(), ctypes.byref(h)) == 0
        assert L.sl_model_stream_version(h) == 2
        L.sl_model_free(h)

        # Rewrite as a version-1 model; the C parser must report 1 and
        # the Python wrapper must warn off the C-side value.
        d = _json.loads(path.read_text())
        d2 = {"skylark_version": 1}
        d2.update({k: v for k, v in d.items() if k != "skylark_version"})
        path.write_text(_json.dumps(d2))
        h = ctypes.c_void_p()
        assert L.sl_model_load(str(path).encode(), ctypes.byref(h)) == 0
        assert L.sl_model_stream_version(h) == 1
        L.sl_model_free(h)
        with pytest.warns(UserWarning, match="stream revision 1"):
            native.NativeModel(path)

    def test_version_scoped_to_toplevel(self, tmp_path):
        """A per-map skylark_version must not masquerade as the model's
        stream version when the top-level key is absent or ordered after
        the maps array (ADVICE round 2, js_without_maps)."""
        import ctypes
        import json as _json

        from libskylark_tpu.ml import FeatureMapModel, GaussianKernel

        L = native.lib()
        rng = np.random.default_rng(11)
        ctx = SketchContext(seed=48)
        maps = [GaussianKernel(3, 2.0).create_rft(8, "regular", ctx)]
        model = FeatureMapModel(maps, rng.standard_normal((8, 2)), input_dim=3)
        path = tmp_path / "mv2.json"
        model.save(path)
        d = _json.loads(path.read_text())
        assert d["maps"][0]["skylark_version"] >= 2  # per-map key exists

        # Top-level version absent: default 1, NOT the per-map value.
        d_no_top = {k: v for k, v in d.items() if k != "skylark_version"}
        d_no_top = {"maps": d_no_top.pop("maps"), **d_no_top}
        path.write_text(_json.dumps(d_no_top))
        h = ctypes.c_void_p()
        assert L.sl_model_load(str(path).encode(), ctypes.byref(h)) == 0
        assert L.sl_model_stream_version(h) == 1
        L.sl_model_free(h)

        # Top-level version ordered AFTER maps (foreign writer): found.
        d_after = {k: v for k, v in d.items() if k != "skylark_version"}
        d_after = {"maps": d_after.pop("maps"), **d_after,
                   "skylark_version": d["skylark_version"]}
        path.write_text(_json.dumps(d_after))
        h = ctypes.c_void_p()
        assert L.sl_model_load(str(path).encode(), ctypes.byref(h)) == 0
        assert L.sl_model_stream_version(h) == d["skylark_version"]
        L.sl_model_free(h)


def test_supported_sketch_transforms_introspection():
    """≙ sl_supported_sketch_transforms (capi/csketch.cpp:74+): every C-API
    type reports both directions on the collapsed matrix kind."""
    combos = native.supported_sketch_transforms()
    assert len(combos) == 34  # 17 types x 2 directions
    names = {c[0] for c in combos}
    assert names == {
        "JLT", "CT", "CWT", "MMT", "WZT", "UST", "FJLT", "GaussianRFT",
        "LaplacianRFT", "ExpSemigroupRLT", "MaternRFT", "FastGaussianRFT",
        "FastMaternRFT", "GaussianQRFT", "LaplacianQRFT",
        "ExpSemigroupQRLT", "PPT",
    }
    for c in combos:
        assert c[1:3] == ("Matrix", "Matrix")
        assert c[3] in ("columnwise", "rowwise")


class TestCAPI:
    def test_context_counter_matches_python(self):
        nctx = native.NativeContext(5)
        pctx = SketchContext(seed=5)
        ns = native.NativeSketch.create(nctx, "JLT", 30, 10)
        ps = JLT(30, 10, pctx)
        assert nctx.counter == pctx.counter
        ns2 = native.NativeSketch.create(nctx, "CWT", 30, 10)
        ps2 = CWT(30, 10, pctx)
        assert nctx.counter == pctx.counter

    @pytest.mark.parametrize("stype,cls,param", [
        ("JLT", JLT, 0.0), ("CWT", CWT, 0.0),
    ])
    @pytest.mark.slow
    def test_apply_matches_python(self, rng, stype, cls, param):
        n, s, m = 40, 12, 7
        A = rng.standard_normal((n, m))
        nctx = native.NativeContext(3)
        ns = native.NativeSketch.create(nctx, stype, n, s, param)
        ps = cls(n, s, SketchContext(seed=3))
        out_n = ns.apply(A, "columnwise")
        out_p = np.asarray(ps.apply(A, "columnwise"))
        np.testing.assert_allclose(out_n, out_p, rtol=1e-9, atol=1e-11)
        out_n = ns.apply(A.T, "rowwise")
        out_p = np.asarray(ps.apply(A.T, "rowwise"))
        np.testing.assert_allclose(out_n, out_p, rtol=1e-9, atol=1e-11)

    def test_wzt_and_ust_match(self, rng):
        n, s, m = 30, 8, 4
        A = rng.standard_normal((n, m))
        nctx = native.NativeContext(8)
        ns = native.NativeSketch.create(nctx, "WZT", n, s, 1.5)
        from libskylark_tpu.sketch import WZT

        ps = WZT(n, s, SketchContext(seed=8), p=1.5)
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A)), rtol=1e-9, atol=1e-11
        )
        nctx2 = native.NativeContext(9)
        nu = native.NativeSketch.create(nctx2, "UST", n, s, 0.0)  # no-replace
        pu = UST(n, s, SketchContext(seed=9), replace=False)
        np.testing.assert_allclose(
            nu.apply(A), np.asarray(pu.apply(A)), rtol=1e-12
        )

    @pytest.mark.slow
    def test_serialization_cross_language(self, rng):
        # native JSON → Python reconstruction → same sketch; and back.
        n, s = 25, 6
        nctx = native.NativeContext(4)
        ns = native.NativeSketch.create(nctx, "JLT", n, s)
        js = ns.to_json()
        ps = from_json(js)
        A = rng.standard_normal((n, 3))
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A)), rtol=1e-9, atol=1e-11
        )
        # Python JSON → native
        ps2 = JLT(n, s, SketchContext(seed=4))
        ns2 = native.NativeSketch.from_json(ps2.to_json())
        np.testing.assert_allclose(
            ns2.apply(A), np.asarray(ps2.apply(A)), rtol=1e-9, atol=1e-11
        )

    def test_error_codes(self):
        nctx = native.NativeContext(1)
        from libskylark_tpu.utils.exceptions import SkylarkError

        with pytest.raises(SkylarkError):
            native.NativeSketch.create(nctx, "NOPE", 5, 3)


class TestLibsvmParser:
    def test_matches_python_parser(self, tmp_path, rng):
        from libskylark_tpu.io import read_libsvm, write_libsvm

        X = rng.standard_normal((50, 12))
        X[rng.random((50, 12)) < 0.4] = 0
        y = rng.integers(0, 5, 50).astype(float)
        write_libsvm(tmp_path / "f", X, y)
        # native path (if enabled) vs forced-python path must agree
        X1, y1 = read_libsvm(tmp_path / "f", n_features=12)
        data = (tmp_path / "f").read_bytes()
        labels, rows, cols, vals, max_col = native.parse_libsvm_bytes(data)
        X2 = np.zeros((len(labels), 12))
        X2[rows, cols] = vals
        np.testing.assert_allclose(X2, X, rtol=1e-15)
        np.testing.assert_allclose(labels, y)
        np.testing.assert_allclose(X1, X, rtol=1e-15)

    def test_comments_and_blanks(self):
        data = b"# header\n\n1 1:2.5 3:1 # trailing\n-1 2:0.5\n"
        labels, rows, cols, vals, max_col = native.parse_libsvm_bytes(data)
        np.testing.assert_allclose(labels, [1, -1])
        assert max_col == 3
        np.testing.assert_array_equal(cols, [0, 2, 1])
        np.testing.assert_allclose(vals, [2.5, 1.0, 0.5])

    def test_large_file_multithreaded(self, tmp_path, rng):
        # >64KiB triggers the threaded path.
        lines = []
        for i in range(5000):
            feats = " ".join(
                f"{j+1}:{rng.standard_normal():.6f}" for j in rng.choice(100, 8)
            )
            lines.append(f"{i % 3} {feats}")
        (tmp_path / "big").write_text("\n".join(lines) + "\n")
        data = (tmp_path / "big").read_bytes()
        assert len(data) > (1 << 16)
        labels, rows, cols, vals, max_col = native.parse_libsvm_bytes(data)
        assert len(labels) == 5000
        assert len(vals) == 5000 * 8
        # row indices must be globally consistent (file order)
        assert rows[0] == 0 and rows[-1] == 4999
        np.testing.assert_allclose(labels[:3], [0, 1, 2])


class TestExtendedNativeTypes:
    """FJLT / RFT / RLT native applies match the JAX path."""

    @pytest.mark.slow
    def test_fjlt_matches_python(self, rng):
        from libskylark_tpu.sketch import FJLT

        n, s, m = 100, 24, 6  # pads to nb=128
        A = rng.standard_normal((n, m))
        nctx = native.NativeContext(21)
        ns = native.NativeSketch.create(nctx, "FJLT", n, s)
        ps = FJLT(n, s, SketchContext(seed=21))
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
            rtol=1e-9, atol=1e-11,
        )
        pctx = SketchContext(seed=21)
        FJLT(n, s, pctx)
        assert nctx.counter == pctx.counter

    @pytest.mark.parametrize("stype,pname,param", [
        ("GaussianRFT", "GaussianRFT", 2.5),
        ("LaplacianRFT", "LaplacianRFT", 1.5),
    ])
    @pytest.mark.slow
    def test_rft_matches_python(self, rng, stype, pname, param):
        import libskylark_tpu.sketch as sk

        n, s, m = 30, 16, 5
        A = rng.standard_normal((n, m))
        nctx = native.NativeContext(22)
        ns = native.NativeSketch.create(nctx, stype, n, s, param)
        ps = getattr(sk, pname)(n, s, SketchContext(seed=22), sigma=param)
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
            rtol=1e-8, atol=1e-10,
        )

    @pytest.mark.slow
    def test_rlt_matches_python(self, rng):
        from libskylark_tpu.sketch import ExpSemigroupRLT

        n, s, m = 20, 12, 4
        A = rng.random((n, m))  # histograms: nonnegative
        nctx = native.NativeContext(23)
        ns = native.NativeSketch.create(nctx, "ExpSemigroupRLT", n, s, 0.4)
        ps = ExpSemigroupRLT(n, s, SketchContext(seed=23), beta=0.4)
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
            rtol=1e-8, atol=1e-10,
        )

    @pytest.mark.slow
    def test_extended_serialization_roundtrip(self, rng):
        from libskylark_tpu.sketch import from_json

        A = rng.standard_normal((50, 3))
        nctx = native.NativeContext(24)
        ns = native.NativeSketch.create(nctx, "GaussianRFT", 50, 8, 3.0)
        ps = from_json(ns.to_json())
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
            rtol=1e-8, atol=1e-10,
        )


class TestFastfoodMaternNative:
    def test_matern_matches_python(self, rng):
        from libskylark_tpu.sketch import MaternRFT

        n, s, m = 24, 10, 4
        A = rng.standard_normal((n, m))
        nctx = native.NativeContext(31)
        ns = native.NativeSketch.create(nctx, "MaternRFT", n, s, 1.5, 2.0)
        ps = MaternRFT(n, s, SketchContext(seed=31), nu=1.5, l=2.0)
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
            rtol=1e-8, atol=1e-10,
        )
        pctx = SketchContext(seed=31)
        MaternRFT(n, s, pctx, nu=1.5, l=2.0)
        assert nctx.counter == pctx.counter

    def test_fastgaussian_matches_python(self, rng):
        from libskylark_tpu.sketch import FastGaussianRFT

        n, s, m = 20, 40, 3  # nb=32, numblks=2
        A = rng.standard_normal((n, m))
        nctx = native.NativeContext(32)
        ns = native.NativeSketch.create(nctx, "FastGaussianRFT", n, s, 1.7)
        ps = FastGaussianRFT(n, s, SketchContext(seed=32), sigma=1.7)
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
            rtol=1e-7, atol=1e-9,
        )
        pctx = SketchContext(seed=32)
        FastGaussianRFT(n, s, pctx, sigma=1.7)
        assert nctx.counter == pctx.counter

    def test_fastmatern_matches_python(self, rng):
        from libskylark_tpu.sketch import FastMaternRFT

        n, s, m = 12, 20, 3  # nb=16, numblks=2
        A = rng.standard_normal((n, m))
        nctx = native.NativeContext(33)
        ns = native.NativeSketch.create(nctx, "FastMaternRFT", n, s, 1.0, 1.5)
        ps = FastMaternRFT(n, s, SketchContext(seed=33), nu=1.0, l=1.5)
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
            rtol=1e-7, atol=1e-9,
        )

    def test_serialization_roundtrip_new_types(self, rng):
        from libskylark_tpu.sketch import from_json

        A = rng.standard_normal((16, 2))
        for stype, p1, p2 in [
            ("MaternRFT", 2.5, 1.2), ("FastGaussianRFT", 0.9, 0.0),
            ("FastMaternRFT", 0.5, 2.0),
        ]:
            nctx = native.NativeContext(34)
            ns = native.NativeSketch.create(nctx, stype, 16, 8, p1, p2)
            ps = from_json(ns.to_json())
            np.testing.assert_allclose(
                ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
                rtol=1e-7, atol=1e-9,
            )


class TestQMCAndPPTNative:
    """The final 4 types: QMC feature maps + TensorSketch → 16/16."""

    @pytest.mark.parametrize("stype,pname", [
        ("GaussianQRFT", "GaussianQRFT"), ("LaplacianQRFT", "LaplacianQRFT"),
    ])
    @pytest.mark.slow
    def test_qrft_matches_python(self, rng, stype, pname):
        import libskylark_tpu.sketch as sk

        n, s, m = 12, 10, 4
        A = rng.standard_normal((n, m))
        nctx = native.NativeContext(41)
        ns = native.NativeSketch.create(nctx, stype, n, s, 1.8, 50.0)
        ps = getattr(sk, pname)(n, s, SketchContext(seed=41), sigma=1.8, skip=50)
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
            rtol=1e-8, atol=1e-10,
        )
        assert nctx.counter == 0  # QMC consumes no counters

    @pytest.mark.slow
    def test_qrlt_matches_python(self, rng):
        from libskylark_tpu.sketch import ExpSemigroupQRLT

        n, s, m = 8, 12, 3
        A = rng.random((n, m))
        nctx = native.NativeContext(42)
        ns = native.NativeSketch.create(nctx, "ExpSemigroupQRLT", n, s, 0.3, 25.0)
        ps = ExpSemigroupQRLT(n, s, SketchContext(seed=42), beta=0.3, skip=25)
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
            rtol=1e-8, atol=1e-10,
        )

    @pytest.mark.parametrize("s", [16, 12, 17, 100])
    def test_ppt_matches_python(self, rng, s):
        """Any S: pow2 rides the radix-2 kernel, non-pow2 the Bluestein
        chirp-z (round 3 — the former pow2-only restriction is gone,
        restoring parity with the reference's FFTW-backed PPT)."""
        from libskylark_tpu.sketch import PPT

        n, m = 10, 5
        A = rng.standard_normal((n, m))
        nctx = native.NativeContext(43)
        ns = native.NativeSketch.create(nctx, "PPT", n, s, 0.5, 2.0, 3.0)
        ps = PPT(n, s, SketchContext(seed=43), q=3, c=0.5, gamma=2.0)
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
            rtol=1e-9, atol=1e-11,
        )
        pctx = SketchContext(seed=43)
        PPT(n, s, pctx, q=3, c=0.5, gamma=2.0)
        assert nctx.counter == pctx.counter

    def test_ppt_invalid_q_rejected(self):
        from libskylark_tpu.utils.exceptions import SkylarkError

        nctx = native.NativeContext(44)
        with pytest.raises(SkylarkError):
            native.NativeSketch.create(nctx, "PPT", 10, 12, 1.0, 1.0, -1.0)

    @pytest.mark.slow
    def test_all_16_serialization_roundtrips(self, rng):
        from libskylark_tpu.sketch import from_json

        A = np.abs(rng.standard_normal((16, 2)))
        cases = [
            ("JLT", 0.0, 0.0, 0.0), ("CT", 1.5, 0.0, 0.0),
            ("CWT", 0.0, 0.0, 0.0), ("MMT", 0.0, 0.0, 0.0),
            ("WZT", 1.5, 0.0, 0.0), ("UST", 1.0, 0.0, 0.0),
            ("FJLT", 0.0, 0.0, 0.0), ("GaussianRFT", 2.0, 0.0, 0.0),
            ("LaplacianRFT", 1.0, 0.0, 0.0), ("ExpSemigroupRLT", 0.4, 0.0, 0.0),
            ("MaternRFT", 1.5, 1.0, 0.0), ("FastGaussianRFT", 1.0, 0.0, 0.0),
            ("FastMaternRFT", 0.5, 1.0, 0.0), ("GaussianQRFT", 1.0, 7.0, 0.0),
            ("LaplacianQRFT", 1.0, 7.0, 0.0), ("ExpSemigroupQRLT", 0.3, 7.0, 0.0),
            ("PPT", 1.0, 1.0, 2.0),
        ]
        for stype, p1, p2, p3 in cases:
            nctx = native.NativeContext(45)
            ns = native.NativeSketch.create(nctx, stype, 16, 8, p1, p2, p3)
            ps = from_json(ns.to_json())
            np.testing.assert_allclose(
                ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
                rtol=1e-7, atol=1e-9, err_msg=stype,
            )

    def test_ppt_zero_c_roundtrip(self, rng):
        # c=0 (homogeneous polynomial kernel) must be preserved.
        from libskylark_tpu.sketch import PPT, from_json

        n, s = 6, 8
        A = rng.standard_normal((n, 2))
        ps = PPT(n, s, SketchContext(seed=46), q=2, c=0.0, gamma=1.0)
        ns = native.NativeSketch.from_json(ps.to_json())
        np.testing.assert_allclose(
            ns.apply(A), np.asarray(ps.apply(A, "columnwise")),
            rtol=1e-9, atol=1e-11,
        )
