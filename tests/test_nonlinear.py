"""Nonlinear-model layer tests (≙ python-skylark ``ml/nonlinear.py`` +
``ml/distances.py``): RLS / SketchRLS / NystromRLS / SketchPCR accuracy on
separable data, agreement with exact RLS, distance-matrix numerics, and
metric helpers."""

import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import SketchContext
from libskylark_tpu.ml import (
    RLS,
    GaussianKernel,
    LinearKernel,
    NystromRLS,
    SketchPCR,
    SketchRLS,
    classification_accuracy,
    euclidean_distance_matrix,
    expsemigroup_distance_matrix,
    l1_distance_matrix,
    mean_squared_error,
)


def blobs(rng, n_per, d, k=2, sep=4.0):
    Xs, ys = [], []
    for c in range(k):
        Xs.append(rng.standard_normal((n_per, d)) + sep * c)
        ys.append(np.full(n_per, c + 1))  # 1-based labels like the ref
    X = np.vstack(Xs)
    y = np.concatenate(ys)
    perm = rng.permutation(len(y))
    return X[perm], y[perm]


class TestDistances:
    def test_euclidean(self, rng):
        X = rng.standard_normal((7, 3))
        Y = rng.standard_normal((5, 3))
        D = np.asarray(euclidean_distance_matrix(X, Y))
        ref = ((X[:, None] - Y[None, :]) ** 2).sum(-1)
        np.testing.assert_allclose(D, ref, atol=1e-10)

    def test_l1_and_semigroup(self, rng):
        X = np.abs(rng.standard_normal((6, 4)))
        Y = np.abs(rng.standard_normal((3, 4)))
        np.testing.assert_allclose(
            np.asarray(l1_distance_matrix(X, Y)),
            np.abs(X[:, None] - Y[None, :]).sum(-1),
            atol=1e-12,
        )
        np.testing.assert_allclose(
            np.asarray(expsemigroup_distance_matrix(X, Y)),
            np.sqrt(X[:, None] + Y[None, :]).sum(-1),
            atol=1e-12,
        )

    def test_accumulate_semantics(self, rng):
        X = rng.standard_normal((4, 2))
        C0 = np.ones((4, 4))
        D = np.asarray(euclidean_distance_matrix(X, alpha=2.0, beta=3.0, C=C0))
        ref = 3.0 * C0 + 2.0 * ((X[:, None] - X[None, :]) ** 2).sum(-1)
        np.testing.assert_allclose(D, ref, atol=1e-10)
        with pytest.raises(ValueError):
            euclidean_distance_matrix(X, beta=1.0)

    def test_symmetric_default(self, rng):
        X = rng.standard_normal((5, 3))
        np.testing.assert_allclose(
            np.asarray(l1_distance_matrix(X)),
            np.asarray(l1_distance_matrix(X, X)),
            atol=1e-12,
        )


class TestMetrics:
    def test_accuracy(self):
        assert float(classification_accuracy([1, 2, 2, 1], [1, 2, 1, 1])) == 75.0
        with pytest.raises(ValueError):
            classification_accuracy([1, 2], [1, 2, 3])

    def test_mse(self):
        assert float(mean_squared_error([1.0, 3.0], [0.0, 1.0])) == 2.5


class TestRLS:
    @pytest.mark.slow
    def test_classification(self, rng):
        X, y = blobs(rng, 40, 5)
        model = RLS(GaussianKernel(5, 3.0)).train(X, y, regularization=1e-3)
        pred = model.predict(X)
        assert float(classification_accuracy(pred, y)) > 95.0

    def test_regression_matches_direct(self, rng):
        X = rng.standard_normal((30, 4))
        y = rng.standard_normal(30)
        lam = 0.5
        model = RLS(LinearKernel(4)).train(X, y, lam, multiclass=False)
        K = X @ X.T
        alpha = np.linalg.solve(K + lam * np.eye(30), y)
        np.testing.assert_allclose(
            np.asarray(model.predict(X)), K @ alpha, rtol=1e-6, atol=1e-8
        )


class TestSketchRLS:
    def test_classification(self, rng):
        X, y = blobs(rng, 50, 6)
        ctx = SketchContext(seed=5)
        model = SketchRLS(GaussianKernel(6, 3.0)).train(
            X, y, ctx, random_features=256, regularization=1e-3
        )
        assert float(classification_accuracy(model.predict(X), y)) > 92.0

    @pytest.mark.slow
    def test_approaches_exact_rls(self, rng):
        """More features → predictions approach exact kernel RLS (the
        reference's doctest contract: sketched accuracy tracks exact)."""
        X, y = blobs(rng, 40, 4, sep=3.0)
        exact = RLS(GaussianKernel(4, 2.0)).train(X, y, 1e-2)
        ctx = SketchContext(seed=11)
        sk = SketchRLS(GaussianKernel(4, 2.0)).train(
            X, y, ctx, random_features=1024, regularization=1e-2
        )
        agree = np.mean(
            np.asarray(exact.predict(X)) == np.asarray(sk.predict(X))
        )
        assert agree > 0.95


class TestNystromRLS:
    @pytest.mark.parametrize("probdist", ["uniform", "leverages"])
    def test_classification(self, rng, probdist):
        X, y = blobs(rng, 50, 5)
        ctx = SketchContext(seed=7)
        model = NystromRLS(GaussianKernel(5, 3.0)).train(
            X, y, ctx, random_features=60, regularization=1e-3, probdist=probdist
        )
        assert float(classification_accuracy(model.predict(X), y)) > 92.0

    def test_bad_probdist(self, rng):
        X, y = blobs(rng, 10, 3)
        with pytest.raises(ValueError):
            NystromRLS(GaussianKernel(3, 1.0)).train(
                X, y, SketchContext(seed=1), probdist="nope"
            )


class TestSketchPCR:
    def test_classification(self, rng):
        X, y = blobs(rng, 50, 6)
        ctx = SketchContext(seed=13)
        model = SketchPCR(GaussianKernel(6, 3.0)).train(X, y, ctx, rank=64)
        assert float(classification_accuracy(model.predict(X), y)) > 90.0

    def test_regression_low_rank_recovery(self, rng):
        """PCR on a linear kernel with rank ≥ d recovers a linear map."""
        X = rng.standard_normal((80, 5))
        w = rng.standard_normal(5)
        y = X @ w
        ctx = SketchContext(seed=3)
        model = SketchPCR(LinearKernel(5)).train(
            X, y, ctx, rank=5, s=5, t=40, multiclass=False
        )
        pred = np.asarray(model.predict(X))
        assert float(mean_squared_error(pred, y)) < 1e-3 * float(np.var(y))

    def test_param_validation(self, rng):
        X, y = blobs(rng, 10, 3)
        with pytest.raises(ValueError):
            SketchPCR(GaussianKernel(3, 1.0)).train(
                X, y, SketchContext(seed=1), rank=8, s=4
            )
