"""Streaming engine tests: partial-sketch equivalence, drivers, prefetch
pipeline, and kill-and-resume.

The lock is the counter contract's streaming analogue: a sketch applied
block-by-block through ``apply_slice`` + merge must equal the whole-matrix
apply (exactly for ROWWISE concat, to summation-order rounding for
COLUMNWISE sums), and a pass killed mid-stream and resumed from its
checkpoint must be BIT-FOR-BIT the uninterrupted pass (same fold order).
All on small synthetic data — tier-1.
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import sparse as jsparse

from libskylark_tpu import sketch as sk
from libskylark_tpu import streaming
from libskylark_tpu.core import SketchContext
from libskylark_tpu.streaming import Prefetcher, StreamParams, skip_batches

pytestmark = pytest.mark.streaming

N, M, S_OUT = 40, 5, 12
BATCH = 7  # deliberately does not divide N (last block is ragged)


def blocks_of(*arrays, batch=BATCH):
    n = arrays[0].shape[0]
    out = []
    for lo in range(0, n, batch):
        sl = tuple(a[lo : lo + batch] for a in arrays)
        out.append(sl[0] if len(arrays) == 1 else sl)
    return out


def make_transform(kind, n, s, ctx):
    if kind == "GaussianRFT":
        return sk.GaussianRFT(n, s, ctx, sigma=1.3)
    return sk.create_sketch(kind, n, s, context=ctx)


# ---------------------------------------------------------------------------
# partial-sketch protocol
# ---------------------------------------------------------------------------


class TestPartialSketchEquivalence:
    KINDS = ["JLT", "CT", "CWT", "MMT", "WZT", "GaussianRFT"]

    @pytest.mark.parametrize("kind", KINDS)
    def test_columnwise_stream_matches_whole(self, kind, rng):
        ctx = SketchContext(seed=5)
        S = make_transform(kind, N, S_OUT, ctx)
        A = jnp.asarray(rng.standard_normal((N, M)))
        want = np.asarray(S.apply(A, "columnwise"))
        got = streaming.sketch(blocks_of(A), S, "columnwise", ncols=M)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-12, atol=1e-12)

    @pytest.mark.parametrize("kind", KINDS)
    def test_rowwise_stream_matches_whole(self, kind, rng):
        ctx = SketchContext(seed=6)
        S = make_transform(kind, N, S_OUT, ctx)
        A = jnp.asarray(rng.standard_normal((17, N)))  # rows carry full N
        want = np.asarray(S.apply(A, "rowwise"))
        got = streaming.sketch(blocks_of(A, batch=5), S, "rowwise")
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-12, atol=1e-12)

    def test_columnwise_sparse_blocks(self, rng):
        ctx = SketchContext(seed=7)
        S = make_transform("CWT", N, S_OUT, ctx)
        A = rng.standard_normal((N, M))
        A[rng.random((N, M)) < 0.6] = 0.0
        want = np.asarray(S.apply(jnp.asarray(A), "columnwise"))
        sparse_blocks = [
            jsparse.BCOO.fromdense(jnp.asarray(b)) for b in blocks_of(A)
        ]
        got = streaming.sketch(sparse_blocks, S, "columnwise", ncols=M)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-12, atol=1e-12)

    def test_apply_slice_bounds_checked(self, rng):
        S = make_transform("JLT", N, S_OUT, SketchContext(seed=8))
        block = jnp.asarray(rng.standard_normal((BATCH, M)))
        with pytest.raises(ValueError, match="outside the sketch domain"):
            S.apply_slice(block, N - 2, "columnwise")
        with pytest.raises(ValueError, match="outside the sketch domain"):
            S.apply_slice(block, -1, "columnwise")

    def test_unsupported_transform_says_so(self, rng):
        from libskylark_tpu.utils.exceptions import UnsupportedError

        S = sk.create_sketch("FJLT", 64, 16, context=SketchContext(seed=9))
        with pytest.raises(UnsupportedError, match="partial-sketch"):
            S.apply_slice(jnp.zeros((8, 3)), 0, "columnwise")

    def test_row_count_mismatch_rejected(self, rng):
        S = make_transform("JLT", N, S_OUT, SketchContext(seed=10))
        A = jnp.asarray(rng.standard_normal((N - BATCH, M)))  # short stream
        with pytest.raises(ValueError, match="sketch domain"):
            streaming.sketch(blocks_of(A), S, "columnwise", ncols=M)


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


class TestStreamingDrivers:
    def test_least_squares_matches_direct_sketch_solve(self, rng):
        from libskylark_tpu.linalg import streaming_least_squares
        from libskylark_tpu.linalg.least_squares import (
            LeastSquaresParams,
            exact_least_squares,
        )

        n, d = 60, 4
        A = rng.standard_normal((n, d))
        b = A @ rng.standard_normal(d) + 0.01 * rng.standard_normal(n)
        params = LeastSquaresParams(sketch_type="JLT", sketch_size=16)
        x, info = streaming_least_squares(
            blocks_of(jnp.asarray(A), jnp.asarray(b)), n, d,
            SketchContext(seed=11), params,
        )
        assert info["rows"] == n and info["batches"] == -(-n // BATCH)
        # fresh context, same seed: contexts are stateful counter
        # reservers, so the reference sketch must not share one
        S = sk.create_sketch("JLT", n, 16, context=SketchContext(seed=11))
        want = exact_least_squares(
            S.apply(jnp.asarray(A), "columnwise"),
            S.apply(jnp.asarray(b)[:, None], "columnwise"),
        )[:, 0]
        np.testing.assert_allclose(np.asarray(x), np.asarray(want), rtol=1e-10)

    def test_kernel_ridge_matches_incore(self, rng):
        from libskylark_tpu.ml import kernel_by_name
        from libskylark_tpu.ml.krr import (
            approximate_kernel_ridge,
            streaming_approximate_kernel_ridge,
        )

        n, d, s = 50, 3, 32
        X = rng.standard_normal((n, d))
        y = rng.standard_normal(n)
        kernel = kernel_by_name("gaussian", d, sigma=1.0)
        model_in = approximate_kernel_ridge(
            kernel, jnp.asarray(X), jnp.asarray(y), 0.1, s,
            SketchContext(seed=12),
        )
        model_st = streaming_approximate_kernel_ridge(
            kernel, blocks_of(jnp.asarray(X), jnp.asarray(y)), 0.1, s,
            SketchContext(seed=12),
        )
        assert model_st.info["rows"] == n
        np.testing.assert_allclose(
            np.asarray(model_st.predict(jnp.asarray(X))),
            np.asarray(model_in.predict(jnp.asarray(X))),
            rtol=1e-8, atol=1e-10,
        )

    def test_empty_stream_raises(self):
        S = make_transform("JLT", N, S_OUT, SketchContext(seed=13))
        with pytest.raises(ValueError, match="empty stream"):
            streaming.sketch([], S, "rowwise")

    def test_rowwise_checkpoint_rejected(self, tmp_path):
        S = make_transform("JLT", N, S_OUT, SketchContext(seed=14))
        with pytest.raises(ValueError, match="rowwise"):
            streaming.sketch(
                [], S, "rowwise",
                params=StreamParams(checkpoint_dir=str(tmp_path)),
            )

    def test_one_shot_iterable_cannot_reopen(self):
        factory = streaming.as_block_factory(iter([1, 2, 3]))
        assert list(factory(0)) == [1, 2, 3]
        with pytest.raises(ValueError, match="one-shot"):
            factory(0)
        factory2 = streaming.as_block_factory([1, 2])
        with pytest.raises(ValueError, match="one-shot"):
            factory2(1)  # starting past 0 needs a real factory


# ---------------------------------------------------------------------------
# kill-and-resume (riding the resilient runtime)
# ---------------------------------------------------------------------------


@pytest.mark.faults
class TestKillAndResume:
    def _factory(self, A):
        def factory(start):
            return skip_batches(iter(blocks_of(A)), start) if start \
                else iter(blocks_of(A))

        return factory

    @pytest.mark.parametrize("kind", ["JLT", "CWT", "GaussianRFT"])
    def test_resumed_pass_is_bitwise_identical(self, kind, tmp_path, rng):
        from libskylark_tpu.resilient import FaultPlan, SimulatedPreemption

        ctx = SketchContext(seed=15)
        S = make_transform(kind, N, S_OUT, ctx)
        A = jnp.asarray(rng.standard_normal((N, M)))
        want = np.asarray(
            streaming.sketch(self._factory(A), S, "columnwise", ncols=M)
        )

        ck = str(tmp_path / f"ck_{kind}")
        params = StreamParams(checkpoint_dir=ck, checkpoint_every=2)
        with pytest.raises(SimulatedPreemption):
            streaming.sketch(
                self._factory(A), S, "columnwise", ncols=M, params=params,
                fault_plan=FaultPlan(preempt_after_chunk=1),
            )
        got = streaming.sketch(
            self._factory(A), S, "columnwise", ncols=M,
            params=StreamParams(
                checkpoint_dir=ck, checkpoint_every=2, resume=True
            ),
        )
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_least_squares_resume(self, tmp_path, rng):
        from libskylark_tpu.linalg import streaming_least_squares
        from libskylark_tpu.linalg.least_squares import LeastSquaresParams
        from libskylark_tpu.resilient import FaultPlan, SimulatedPreemption

        n, d = 60, 4
        A = jnp.asarray(rng.standard_normal((n, d)))
        b = jnp.asarray(rng.standard_normal(n))
        lsp = LeastSquaresParams(sketch_type="CWT", sketch_size=16)
        # fresh context per call: contexts are stateful counter
        # reservers, and each call creates its own sketch
        ctx = lambda: SketchContext(seed=16)  # noqa: E731

        def factory(start):
            it = iter(blocks_of(A, b))
            return skip_batches(it, start) if start else it

        want, _ = streaming_least_squares(factory, n, d, ctx(), lsp)
        ck = str(tmp_path / "ck")
        with pytest.raises(SimulatedPreemption):
            streaming_least_squares(
                factory, n, d, ctx(), lsp,
                stream_params=StreamParams(
                    checkpoint_dir=ck, checkpoint_every=2,
                ),
                fault_plan=FaultPlan(preempt_after_chunk=1),
            )
        got, info = streaming_least_squares(
            factory, n, d, ctx(), lsp,
            stream_params=StreamParams(
                checkpoint_dir=ck, checkpoint_every=2, resume=True
            ),
        )
        assert info["rows"] == n
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------------
# prefetch pipeline
# ---------------------------------------------------------------------------


class TestPrefetcher:
    def test_order_and_exhaustion(self):
        items = list(range(25))
        with Prefetcher(iter(items), depth=3, placer=None) as pf:
            assert list(pf) == items
        assert pf.stats.consumed == len(items)
        assert pf.stats.produced == len(items)
        assert pf.stats.hits + pf.stats.waits >= len(items)

    def test_producer_exception_propagates(self):
        def source():
            yield 1
            raise RuntimeError("disk on fire")

        pf = Prefetcher(source(), depth=2, placer=None)
        assert next(pf) == 1
        with pytest.raises(RuntimeError, match="disk on fire"):
            for _ in pf:
                pass
        pf.close()

    def test_backpressure_bounds_readahead(self):
        pulled = []

        def source():
            for i in range(100):
                pulled.append(i)
                yield i

        depth = 2
        pf = Prefetcher(source(), depth=depth, placer=None)
        assert next(pf) == 0
        time.sleep(0.3)  # let the producer run as far as it can
        # ≤ depth staged + 1 in the producer's hand + the 1 consumed
        assert len(pulled) <= depth + 2
        pf.close()
        assert len(pulled) < 100  # close() released the thread early

    def test_placer_applied(self):
        pf = Prefetcher(iter([1, 2]), depth=1, placer=lambda x: x * 10)
        assert list(pf) == [10, 20]

    def test_overlap_smoke(self):
        """The overlap proof: with IO time ≈ compute time, the pipelined
        wall clock must beat the serial sum, and at least one batch must
        already be staged when asked for (stats.hits)."""
        nbatch, io_s, compute_s = 8, 0.03, 0.03

        def source():
            for i in range(nbatch):
                time.sleep(io_s)  # simulated parse + transfer
                yield i

        t0 = time.perf_counter()
        pf = Prefetcher(source(), depth=2, placer=None)
        for _ in pf:
            time.sleep(compute_s)  # simulated device compute
        wall = time.perf_counter() - t0
        serial = nbatch * (io_s + compute_s)
        assert wall < 0.9 * serial, (
            f"no overlap: wall {wall:.3f}s vs serial {serial:.3f}s "
            f"(stats: {pf.stats})"
        )
        assert pf.stats.hits >= 1, f"never found a staged batch: {pf.stats}"


class TestStreamParams:
    def test_prefetch_knobs_ride_resilient_params(self, tmp_path):
        p = StreamParams(
            prefetch=4, checkpoint_dir=str(tmp_path), checkpoint_every=3
        )
        assert p.prefetch == 4
        assert p.checkpoint_dir == str(tmp_path)
        assert p.checkpoint_every == 3

    def test_stream_with_prefetch_disabled(self, rng):
        S = make_transform("JLT", N, S_OUT, SketchContext(seed=17))
        A = jnp.asarray(rng.standard_normal((N, M)))
        want = np.asarray(S.apply(A, "columnwise"))
        got = streaming.sketch(
            blocks_of(A), S, "columnwise", ncols=M,
            params=StreamParams(prefetch=0),
        )
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-12)
