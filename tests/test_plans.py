"""Plan-layer tests: the bitwise contract, the retrace guard, and the
cache's observability counters.

The plan layer's hard promise (``docs/performance.md``) is that compiling
an apply changes WHEN the math runs, never WHAT it computes: planned
results are bit-for-bit the eager results, and a streaming pass traces
once per bucket shape, not once per batch.  Everything here runs on the
CPU test mesh and is tier-1 except the ``perf``-marked wall-clock check
(machine-sensitive; opt in with ``SKYLARK_RUN_PERF=1``).
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import plans
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.sketch import CWT, JLT, MMT, GaussianRFT


def _mk(cls, n, s, seed=11, **kw):
    return cls(n, s, SketchContext(seed=seed), **kw)


# One linear dense, two hash-based, one feature map: together they cover
# the matmul, segment-sum, and pointwise-epilogue plan bodies.
TRANSFORMS = [
    pytest.param(lambda n, s: _mk(JLT, n, s), id="JLT"),
    pytest.param(lambda n, s: _mk(CWT, n, s), id="CWT"),
    pytest.param(lambda n, s: _mk(MMT, n, s), id="MMT"),
    pytest.param(
        lambda n, s: _mk(GaussianRFT, n, s, sigma=1.3), id="GaussianRFT"
    ),
]


class TestBitwiseParity:
    """planned == eager, to the bit, both dims (the hard contract)."""

    @pytest.mark.parametrize("dim", ["columnwise", "rowwise"])
    @pytest.mark.parametrize("make", TRANSFORMS)
    def test_planned_equals_eager(self, make, dim, rng):
        n, s, m = 96, 48, 37
        S = make(n, s)
        shape = (n, m) if dim == "columnwise" else (m, n)
        A = jnp.asarray(rng.standard_normal(shape))
        eager = np.asarray(S.apply(A, dim))
        planned = np.asarray(plans.apply(S, A, dim))
        np.testing.assert_array_equal(planned, eager)
        # The cached second call runs the same executable: same bits.
        np.testing.assert_array_equal(
            np.asarray(plans.apply(S, A, dim)), eager
        )

    @pytest.mark.parametrize("k", [5, 20, 33, 48])
    def test_rowwise_bucketed_bitwise(self, k, rng):
        # Real rows of a bucket-padded batch are bitwise the eager ragged
        # apply: row-independent applies + exact-zero padding.
        n, s = 24, 32
        S = _mk(JLT, n, s, seed=3)
        X = jnp.asarray(rng.standard_normal((k, n)))
        eager = np.asarray(S.apply(X, "rowwise"))
        Z = np.asarray(plans.apply_rowwise_bucketed(S, X))
        assert Z.shape == eager.shape
        np.testing.assert_array_equal(Z, eager)

    def test_pad_out_zeroes_dead_rows(self, rng):
        S = _mk(GaussianRFT, 16, 24, seed=7, sigma=0.9)
        X = jnp.asarray(rng.standard_normal((13, 16)))
        Zp, k = plans.apply_rowwise_bucketed(S, X, pad_out=True)
        assert k == 13
        assert Zp.shape[0] == plans.bucket_rows(13)
        np.testing.assert_array_equal(np.asarray(Zp[13:]), 0.0)
        np.testing.assert_array_equal(
            np.asarray(Zp[:13]), np.asarray(S.apply(X, "rowwise"))
        )


class TestRetraceGuard:
    """Ragged streaming batches compile once per BUCKET, never per batch."""

    # 8 ragged batch sizes covering 4 ladder buckets (12, 24, 32, 48).
    SIZES = [23, 17, 40, 9, 31, 25, 30, 25]

    def test_one_trace_per_bucket(self, rng):
        n, m, s = sum(self.SIZES), 12, 32
        S = _mk(CWT, n, s, seed=13)
        A = rng.standard_normal((n, m))
        buckets = {plans.bucket_rows(k) for k in self.SIZES}
        assert len(self.SIZES) >= 8 > len(buckets)

        plans.clear()  # count traces of a fresh cache from zero

        def one_pass():
            acc = jnp.zeros((s, m))
            row = 0
            for k in self.SIZES:
                acc = plans.accumulate_slice(
                    S, acc, jnp.asarray(A[row : row + k]), row
                )
                row += k
            return acc

        acc = one_pass()
        st1 = plans.stats()
        assert st1["bypasses"] == 0, "slice path unexpectedly fell back"
        assert st1["traces"] <= len(buckets)
        assert st1["misses"] == len(buckets)

        # Second pass: every plan is a cache hit, zero new traces.
        acc2 = one_pass()
        st2 = plans.stats()
        assert st2["traces"] == st1["traces"]
        assert st2["misses"] == st1["misses"]
        assert st2["hits"] >= st1["hits"] + len(self.SIZES)

        # Same executables, same accumulation order: identical bits; and
        # the streamed sum matches the in-core apply to fp round-off.
        np.testing.assert_array_equal(np.asarray(acc), np.asarray(acc2))
        full = np.asarray(S.apply(jnp.asarray(A), "columnwise"))
        np.testing.assert_allclose(np.asarray(acc), full, atol=1e-10)

    def test_rowwise_one_trace_per_bucket(self, rng):
        n, s = 20, 16
        S = _mk(JLT, n, s, seed=17)
        plans.clear()
        buckets = {plans.bucket_rows(k) for k in self.SIZES}
        for k in self.SIZES:
            plans.apply_rowwise_bucketed(
                S, jnp.asarray(rng.standard_normal((k, n)))
            )
        st = plans.stats()
        assert st["traces"] <= len(buckets)
        assert st["misses"] == len(buckets)


class TestCacheObservability:
    """stats() counters: monotone, bypass-aware, LRU-bounded."""

    def test_counters_monotone_and_env_bypass(self, rng, monkeypatch):
        monkeypatch.delenv("SKYLARK_NO_PLANS", raising=False)
        S = _mk(JLT, 32, 16, seed=9)
        A = jnp.asarray(rng.standard_normal((32, 7)))
        st0 = plans.stats()
        plans.apply(S, A, "columnwise")
        plans.apply(S, A, "columnwise")
        st1 = plans.stats()
        for key in (
            "hits", "misses", "evictions", "traces", "compiles",
            "compile_seconds", "bypasses",
        ):
            assert st1[key] >= st0[key], key
        assert st1["hits"] + st1["misses"] >= st0["hits"] + st0["misses"] + 2

        # SKYLARK_NO_PLANS=1 turns the layer into a counted pass-through.
        monkeypatch.setenv("SKYLARK_NO_PLANS", "1")
        assert not plans.enabled()
        st2 = plans.stats()
        out = plans.apply(S, A, "columnwise")
        st3 = plans.stats()
        assert st3["bypasses"] == st2["bypasses"] + 1
        assert st3["hits"] == st2["hits"]
        assert st3["misses"] == st2["misses"]
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(S.apply(A, "columnwise"))
        )
        monkeypatch.delenv("SKYLARK_NO_PLANS")
        assert plans.enabled()

    def test_lru_eviction(self, rng):
        S = _mk(JLT, 16, 8, seed=21)
        old_max = plans.stats()["max_size"]
        plans.clear()
        try:
            plans.set_cache_size(2)
            for m in (3, 4, 5, 6):  # 4 distinct shape keys, bound 2
                plans.apply(
                    S, jnp.asarray(rng.standard_normal((16, m))), "columnwise"
                )
            st = plans.stats()
            assert st["size"] <= 2
            assert st["evictions"] >= 2
        finally:
            plans.set_cache_size(old_max)

    def test_hoistable_operands_memoized(self):
        S = _mk(JLT, 32, 16, seed=5)
        a = S.hoistable_operands(jnp.dtype("float64"))
        b = S.hoistable_operands(jnp.dtype("float64"))
        assert a is b  # one realization per (sketch, dtype) per process
        c = S.hoistable_operands(jnp.dtype("float32"))
        assert c is not a
        assert c.dtype == jnp.float32


class TestBucketing:
    def test_ladder_is_geometric_and_monotone(self):
        lad = plans.bucket_ladder(4096)
        assert lad[0] == 8
        assert all(a < b for a, b in zip(lad, lad[1:]))
        # padding overhead is bounded: consecutive rungs within 1.5x
        assert all(b <= a * 1.5 + 1e-9 for a, b in zip(lad, lad[1:]))

    def test_bucket_rows_respects_gates(self):
        # padding must never cross an algorithm gate: 15 stays 15 with a
        # gate at 16 (padding to 16 would flip the one-hot/scatter choice)
        assert plans.bucket_rows(15, (16,)) == 15
        assert plans.bucket_rows(17, (16,)) >= 17
        assert plans.bucket_rows(12) == 12  # on the ladder already


@pytest.mark.perf
class TestPerfTimings:
    """Wall-clock assertions — machine-sensitive, SKYLARK_RUN_PERF=1 only."""

    def test_warm_apply_beats_cold(self, rng):
        S = _mk(JLT, 256, 64, seed=33)
        X = jnp.asarray(rng.standard_normal((512, 256)))
        plans.clear()
        t0 = time.perf_counter()
        np.asarray(plans.apply_rowwise_bucketed(S, X))
        cold = time.perf_counter() - t0
        warm = min(
            (lambda t: (np.asarray(plans.apply_rowwise_bucketed(S, X)),
                        time.perf_counter() - t)[1])(time.perf_counter())
            for _ in range(5)
        )
        assert warm < cold, (warm, cold)
