"""Tests for parity-gap extras: regression framework, AsyFCG, SJLT,
timers, exceptions, solver checkpoint/resume."""

import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import SketchContext
from libskylark_tpu.sketch import SJLT, from_json
from libskylark_tpu.solvers import (
    KrylovParams,
    RegressionProblem,
    asy_fcg,
    lsqr,
    solve_regression,
)
from libskylark_tpu.utils import (
    PhaseTimer,
    SkylarkError,
    SketchError,
    load_solver_state,
    save_solver_state,
)


def spd(rng, n, cond=100.0):
    Q = np.linalg.qr(rng.standard_normal((n, n)))[0]
    lam = np.logspace(0, -np.log10(cond), n)
    return jnp.asarray(Q @ np.diag(lam) @ Q.T)


class TestRegressionFramework:
    def test_exact_dispatch(self, rng):
        A = jnp.asarray(rng.standard_normal((80, 10)))
        b = jnp.asarray(rng.standard_normal(80))
        x = solve_regression(RegressionProblem(A), b, solver="exact")
        x_ref = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
        np.testing.assert_allclose(np.asarray(x), x_ref, rtol=1e-8, atol=1e-10)

    def test_ridge_augmentation(self, rng):
        A = jnp.asarray(rng.standard_normal((60, 8)))
        b = jnp.asarray(rng.standard_normal(60))
        lam = 0.5
        x = solve_regression(
            RegressionProblem(A, regularization="ridge", lam=lam), b
        )
        x_ref = np.linalg.solve(
            np.asarray(A.T @ A) + lam * np.eye(8), np.asarray(A.T @ b)
        )
        np.testing.assert_allclose(np.asarray(x), x_ref, rtol=1e-7, atol=1e-9)

    def test_accelerated_dispatch(self, rng):
        A = jnp.asarray(rng.standard_normal((500, 12)))
        b = jnp.asarray(rng.standard_normal(500))
        x, info = solve_regression(
            RegressionProblem(A), b, solver="accelerated",
            context=SketchContext(seed=1),
        )
        x_ref = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
        np.testing.assert_allclose(np.asarray(x), x_ref, rtol=1e-6, atol=1e-8)

    def test_l1_regression_robust_to_outliers(self, rng):
        # l1 should shrug off gross outliers that wreck l2.
        m, n = 3000, 5
        A = rng.standard_normal((m, n))
        x_true = rng.standard_normal(n)
        b = A @ x_true
        idx = rng.choice(m, 100, replace=False)
        b[idx] += 100 * rng.standard_normal(100)
        x1 = solve_regression(
            RegressionProblem(jnp.asarray(A), penalty="l1"),
            jnp.asarray(b),
            context=SketchContext(seed=2),
        )
        x2 = np.linalg.lstsq(A, b, rcond=None)[0]
        e1 = np.linalg.norm(np.asarray(x1) - x_true)
        e2 = np.linalg.norm(x2 - x_true)
        assert e1 < e2

    @pytest.mark.slow
    def test_sketched_dispatch(self, rng):
        A = jnp.asarray(rng.standard_normal((800, 10)))
        b = jnp.asarray(rng.standard_normal(800))
        x = solve_regression(
            RegressionProblem(A), b, solver="sketched",
            context=SketchContext(seed=3),
        )
        assert np.all(np.isfinite(np.asarray(x)))


class TestAsyFCG:
    @pytest.mark.slow
    def test_spd_solve(self, rng):
        A = spd(rng, 96, cond=1e3)
        b = jnp.asarray(rng.standard_normal(96))
        x, info = asy_fcg(
            A, b, SketchContext(seed=4),
            KrylovParams(iter_lim=100, tolerance=1e-9),
            inner_sweeps=2, block_size=32,
        )
        np.testing.assert_allclose(
            np.asarray(A @ x), np.asarray(b), rtol=1e-5, atol=1e-6
        )
        # preconditioning should beat plain FCG iteration count
        from libskylark_tpu.solvers import flexible_cg

        _, info_plain = flexible_cg(
            A, b, params=KrylovParams(iter_lim=100, tolerance=1e-9)
        )
        assert int(info["iterations"]) <= int(info_plain["iterations"])


class TestSJLT:
    @pytest.mark.slow
    def test_norm_preservation_statistical(self, rng):
        n, s = 300, 100
        X = jnp.asarray(rng.standard_normal((n, 6)))
        norms = np.linalg.norm(np.asarray(X), axis=0)
        errs = []
        for rep in range(5):
            S = SJLT(n, s, SketchContext(seed=rep), nnz=4)
            SX = S.apply(X, "columnwise")
            errs.append(np.abs(np.linalg.norm(np.asarray(SX), axis=0) - norms) / norms)
        assert np.mean(errs) < 3.0 / np.sqrt(s)

    @pytest.mark.slow
    def test_rowwise_matches_columnwise(self, rng):
        n, s = 50, 20
        X = rng.standard_normal((7, n))
        S1 = SJLT(n, s, SketchContext(seed=5), nnz=3)
        S2 = SJLT(n, s, SketchContext(seed=5), nnz=3)
        np.testing.assert_allclose(
            np.asarray(S1.apply(jnp.asarray(X), "rowwise")),
            np.asarray(S2.apply(jnp.asarray(X.T), "columnwise")).T,
            rtol=1e-6,
        )

    def test_cwt_is_nnz1_special_case_shape(self, rng):
        S = SJLT(40, 16, SketchContext(seed=6), nnz=1)
        out = S.apply(jnp.asarray(rng.standard_normal((40, 3))))
        assert out.shape == (16, 3)

    def test_json_roundtrip(self, rng):
        S = SJLT(30, 10, SketchContext(seed=7), nnz=2)
        S2 = from_json(S.to_json())
        X = jnp.asarray(rng.standard_normal((30, 2)))
        np.testing.assert_array_equal(
            np.asarray(S.apply(X)), np.asarray(S2.apply(X))
        )


class TestUtils:
    def test_phase_timer(self):
        t = PhaseTimer()
        with t.phase("a"):
            sum(range(1000))
        with t.phase("a"):
            pass
        rep = t.report()
        assert "a" in rep and t.counts["a"] == 2

    def test_timer_report_distributed_aggregation(self):
        """timer_report(distributed=True) gathers per-process phase
        scalars and reports min/max/avg over ranks (≙ utility/
        timer.hpp:44-66 PRINT's world-communicator MPI reduction).
        Single-process job: gathered axis is 1, so min = max = avg = the
        local totals."""
        t = PhaseTimer()
        with t.phase("solve"):
            sum(range(1000))
        rep = t.report(distributed=True)
        assert "solve" in rep and "min(s)" in rep and "over 1 process" in rep
        local = t.totals["solve"]
        row = [ln for ln in rep.splitlines() if ln.startswith("solve")][0]
        mn, mx, avg, calls = row.split()[1:5]
        assert float(mn) == float(mx) == float(avg) == round(local, 4)
        assert int(calls) == 1

    def test_timer_aggregate_multirank_shape(self):
        """The multi-rank reduction itself, with synthetic 4-process
        data (what a real jax.distributed run would gather)."""
        import numpy as np

        from libskylark_tpu.utils.timer import aggregate_report

        stacked = np.array(
            [[1.0, 10.0], [3.0, 10.0], [2.0, 10.0], [6.0, 10.0]]
        )
        counts = np.array([[2, 1]] * 4)
        rep = aggregate_report(["comm", "prox"], stacked, counts)
        assert "over 4 processes" in rep
        comm = [ln for ln in rep.splitlines() if ln.startswith("comm")][0]
        mn, mx, avg, calls = comm.split()[1:5]
        assert (float(mn), float(mx), float(avg)) == (1.0, 6.0, 3.0)
        assert int(calls) == 2
        prox = [ln for ln in rep.splitlines() if ln.startswith("prox")][0]
        assert float(prox.split()[1]) == float(prox.split()[2]) == 10.0

    def test_timer_report_distributed_synthetic_ranks(self, monkeypatch):
        """The full ``timer_report(distributed=True)`` path over a
        synthetic 3-process gather (monkeypatched ``process_allgather``):
        the stacked (P, k) totals must flow through
        :func:`aggregate_report` into per-phase min/max/avg columns."""
        import numpy as np

        from libskylark_tpu.utils.timer import timer_report

        P = 3
        calls = {"n": 0}

        def fake_allgather(x):
            x = np.asarray(x)
            calls["n"] += 1
            if calls["n"] == 1:  # signature gather: all ranks agree
                return np.stack([x] * P)
            if x.dtype == np.float64:  # totals: rank r scaled by r+1
                return np.stack([x * (r + 1) for r in range(P)])
            return np.stack([x] * P)  # counts

        monkeypatch.setattr(
            "jax.experimental.multihost_utils.process_allgather",
            fake_allgather,
        )
        rep = timer_report(
            {"solve": 2.0, "sketch": 1.0},
            {"solve": 4, "sketch": 2},
            distributed=True,
        )
        assert "over 3 processes" in rep
        solve = [ln for ln in rep.splitlines() if ln.startswith("solve")][0]
        mn, mx, avg, nc = solve.split()[1:5]
        assert (float(mn), float(mx), float(avg)) == (2.0, 6.0, 4.0)
        assert int(nc) == 4
        sketch = [ln for ln in rep.splitlines() if ln.startswith("sketch")][0]
        assert (float(sketch.split()[1]), float(sketch.split()[2])) == (1.0, 3.0)

    def test_timer_report_distributed_misalignment_guard(self, monkeypatch):
        """Mismatched phase-name sets across ranks must raise the
        CRC-signature RuntimeError BEFORE any totals gather — silent
        positional misalignment is the failure mode the guard exists
        to catch (utility/timer.hpp:44-66's world-collective contract)."""
        import numpy as np

        import pytest

        from libskylark_tpu.utils.timer import timer_report

        def fake_allgather(x):
            x = np.asarray(x)
            # Signature gather: rank 1 hashed a different name list.
            other = np.array([int(x[0]) ^ 0x5A5A, int(x[1]) + 1], np.int64)
            return np.stack([x, other])

        monkeypatch.setattr(
            "jax.experimental.multihost_utils.process_allgather",
            fake_allgather,
        )
        with pytest.raises(RuntimeError, match="different phase-name sets"):
            timer_report({"solve": 1.0}, {"solve": 1}, distributed=True)

    def test_exception_codes(self):
        assert issubclass(SketchError, SkylarkError)
        assert SketchError.code == 103
        with pytest.raises(SkylarkError):
            raise SketchError("boom")

    def test_checkpoint_roundtrip(self, tmp_path, rng):
        state = {
            "X": jnp.asarray(rng.standard_normal((5, 3))),
            "it": jnp.asarray(7),
            "nested": [jnp.asarray([1.0, 2.0])],
        }
        save_solver_state(tmp_path / "ck", state, {"iter": 7})
        state2, meta = load_solver_state(tmp_path / "ck", like=state)
        assert meta["iter"] == 7
        np.testing.assert_allclose(state2["X"], np.asarray(state["X"]))
        np.testing.assert_allclose(state2["nested"][0], [1.0, 2.0])

    def test_checkpoint_resume_lsqr(self, tmp_path, rng):
        # Save x mid-solve, resume via x0, match the uninterrupted solve.
        A = jnp.asarray(rng.standard_normal((100, 12)))
        b = jnp.asarray(rng.standard_normal(100))
        x_partial, _ = lsqr(A, b, params=KrylovParams(iter_lim=4))
        save_solver_state(tmp_path / "lsqr", {"x": x_partial})
        st, _ = load_solver_state(tmp_path / "lsqr", like={"x": x_partial})
        x_resumed, _ = lsqr(
            A, b, params=KrylovParams(iter_lim=300), x0=jnp.asarray(st["x"])
        )
        x_ref = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
        np.testing.assert_allclose(np.asarray(x_resumed), x_ref, rtol=1e-6, atol=1e-8)
