"""Async device-overlap streaming tests (round-11 tentpole).

The lock is the overlap contract (``streaming/overlap.py``): an
overlapped pass folds the SAME blocks in the SAME order through the
SAME planned executables as the serial pass — only the host's
``block_until_ready`` points move (per-chunk boundary vs per-step) —
so overlapped and serial results must be BITWISE identical for every
sketch family, ragged tails included.  On top of that: the kill switch
(``SKYLARK_NO_OVERLAP=1``) must force the serial discipline through the
default-on resolution, a pass killed and resumed MID-OVERLAP with
buffer donation forced on must still be bit-for-bit the uninterrupted
run (the chunk-boundary sync runs BEFORE checkpoint capture, so the
snapshot can never see an in-flight donated accumulator), and the
overlapped pass must fund the telemetry overlap-efficiency submetric.
All on small synthetic data — tier-1.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import sketch as sk
from libskylark_tpu import streaming
from libskylark_tpu.core import SketchContext
from libskylark_tpu.streaming import StreamParams, overlap, skip_batches

pytestmark = pytest.mark.overlap

N, M, S_OUT = 40, 5, 12
BATCH = 7  # deliberately does not divide N (last block is ragged)

KINDS = ["CWT", "MMT", "JLT"]


def blocks_of(A, batch=BATCH):
    return [A[lo : lo + batch] for lo in range(0, A.shape[0], batch)]


def factory_of(A):
    def factory(start):
        it = iter(blocks_of(A))
        return skip_batches(it, start) if start else it

    return factory


def run_pass(A, S, *, overlap_flag, params=None):
    params = params or StreamParams(overlap=overlap_flag)
    return np.asarray(
        streaming.sketch(factory_of(A), S, "columnwise", ncols=M,
                         params=params)
    )


class TestOverlapResolution:
    def test_default_on(self, monkeypatch):
        monkeypatch.delenv("SKYLARK_NO_OVERLAP", raising=False)
        assert overlap.enabled(None) is True
        assert overlap.enabled(True) is True
        assert overlap.enabled(False) is False

    def test_kill_switch_wins(self, monkeypatch):
        monkeypatch.setenv("SKYLARK_NO_OVERLAP", "1")
        assert overlap.enabled(None) is False
        assert overlap.enabled(True) is False


class TestOverlapBitwise:
    @pytest.mark.parametrize("kind", KINDS)
    def test_overlapped_equals_serial(self, kind, rng):
        A = jnp.asarray(rng.standard_normal((N, M)), jnp.float32)
        want = run_pass(
            A, sk.create_sketch(kind, N, S_OUT, context=SketchContext(seed=5)),
            overlap_flag=False,
        )
        got = run_pass(
            A, sk.create_sketch(kind, N, S_OUT, context=SketchContext(seed=5)),
            overlap_flag=True,
        )
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("kind", KINDS)
    def test_kill_switch_pass_is_bitwise_too(self, kind, monkeypatch, rng):
        # The env kill switch flips the sync discipline, never the math:
        # a defaulted pass under SKYLARK_NO_OVERLAP=1 stays bitwise equal
        # to the overlapped pass.
        A = jnp.asarray(rng.standard_normal((N, M)), jnp.float32)
        got = run_pass(
            A, sk.create_sketch(kind, N, S_OUT, context=SketchContext(seed=6)),
            overlap_flag=True,
        )
        monkeypatch.setenv("SKYLARK_NO_OVERLAP", "1")
        want = run_pass(
            A, sk.create_sketch(kind, N, S_OUT, context=SketchContext(seed=6)),
            overlap_flag=None,
        )
        np.testing.assert_array_equal(got, want)


@pytest.mark.faults
class TestKillResumeMidOverlap:
    @pytest.mark.parametrize("kind", KINDS)
    def test_resume_under_donation_is_bitwise(
        self, kind, tmp_path, monkeypatch, rng
    ):
        # Donation forced ON + overlap ON: the checkpoint written at the
        # preemption boundary must hold a settled accumulator (the
        # chunk-boundary sync runs before capture), never a buffer a
        # donating step is still allowed to alias — a resumed pass that
        # is bit-for-bit the uninterrupted one proves it.
        from libskylark_tpu import plans
        from libskylark_tpu.resilient import FaultPlan, SimulatedPreemption

        monkeypatch.setenv("SKYLARK_PLAN_DONATE", "1")
        plans.clear()
        try:
            A = jnp.asarray(rng.standard_normal((N, M)), jnp.float32)
            mk = lambda: sk.create_sketch(  # noqa: E731
                kind, N, S_OUT, context=SketchContext(seed=15)
            )
            want = run_pass(A, mk(), overlap_flag=True)

            ck = str(tmp_path / f"ck_{kind}")
            with pytest.raises(SimulatedPreemption):
                streaming.sketch(
                    factory_of(A), mk(), "columnwise", ncols=M,
                    params=StreamParams(
                        checkpoint_dir=ck, checkpoint_every=2, overlap=True
                    ),
                    fault_plan=FaultPlan(preempt_after_chunk=1),
                )
            got = streaming.sketch(
                factory_of(A), mk(), "columnwise", ncols=M,
                params=StreamParams(
                    checkpoint_dir=ck, checkpoint_every=2, resume=True,
                    overlap=True,
                ),
            )
            np.testing.assert_array_equal(np.asarray(got), want)
        finally:
            plans.clear()  # drop donating executables for later tests


@pytest.mark.telemetry
class TestOverlapTelemetry:
    def test_efficiency_submetric(self, monkeypatch, rng):
        from libskylark_tpu import telemetry

        monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
        telemetry.reset()
        A = jnp.asarray(rng.standard_normal((N, M)), jnp.float32)
        S = sk.create_sketch("CWT", N, S_OUT, context=SketchContext(seed=8))
        run_pass(A, S, overlap_flag=True)
        snap = telemetry.snapshot()
        counters = snap["counters"]
        # one boundary sync per chunk, and the producer/wait counters
        # that fund the efficiency ratio
        assert counters.get("stream.sync_chunks", 0) >= 1
        assert "prefetch.producer_seconds" in counters
        assert "prefetch.wait_seconds" in counters
        eff = snap["overlap_efficiency"]
        assert eff is not None and 0.0 <= eff <= 1.0

    def test_serial_pass_counts_no_chunk_syncs(self, monkeypatch, rng):
        from libskylark_tpu import telemetry

        monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
        telemetry.reset()
        A = jnp.asarray(rng.standard_normal((N, M)), jnp.float32)
        S = sk.create_sketch("CWT", N, S_OUT, context=SketchContext(seed=9))
        run_pass(A, S, overlap_flag=False)
        counters = telemetry.snapshot()["counters"]
        assert counters.get("stream.sync_chunks", 0) == 0
