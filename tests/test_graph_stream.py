"""Streamed graph sketching tests: edge-block folds are BITWISE equal
to the in-core BCOO apply (the dyadic-exactness contract of
``graph/stream.py``), across block sizes, simulated rank partitions,
kill-resume, and the chained sharded schedule; ``stream_arc_list``
matches ``SimpleGraph`` edge-for-edge on messy files; served PPR/embed
queries coalesce without changing a bit."""

import numpy as np
import pytest

import jax.numpy as jnp

from libskylark_tpu import SketchContext
from libskylark_tpu.graph import (
    ASEParams,
    SimpleGraph,
    approximate_ase,
    chained_adjacency_sketch,
    graph_block_source,
    incore_adjacency_sketch,
    streamed_adjacency_sketch,
    streaming_ase,
)
from libskylark_tpu.io import arc_list_source, scan_arc_list, stream_arc_list
from libskylark_tpu.sketch import CWT, SJLT
from libskylark_tpu.utils.exceptions import InvalidParameters

pytestmark = pytest.mark.graph


def random_graph(rng, n=64, m=400):
    e = rng.integers(0, n, (m, 2))
    return SimpleGraph(map(tuple, e.tolist()))


def edges_of(G):
    """Canonical undirected (lo, hi) pairs, CSR order."""
    rows = np.repeat(np.arange(G.n), G.degrees)
    keep = rows < G.indices
    return np.stack([rows[keep], G.indices[keep]], axis=1)


# ---------------------------------------------------------------------------
# streamed fold ≡ in-core apply (bitwise)
# ---------------------------------------------------------------------------


class TestStreamedSketch:
    @pytest.mark.parametrize("Skind", [CWT, SJLT])
    @pytest.mark.parametrize("batch_edges", [7, 64, 10_000])
    def test_streamed_equals_incore_bitwise(self, rng, Skind, batch_edges):
        G = random_graph(rng)
        S = Skind(G.n, 24, SketchContext(seed=1))
        want = np.asarray(incore_adjacency_sketch(G, S))
        got = np.asarray(
            streamed_adjacency_sketch(
                graph_block_source(G, batch_edges=batch_edges),
                S, ncols=G.n,
            )
        )
        np.testing.assert_array_equal(got, want)

    def test_elastic_world1_partition_route(self, rng):
        from libskylark_tpu.streaming.elastic import (
            ElasticParams,
            RowPartition,
        )

        G = random_graph(rng)
        E = G.volume // 2
        S = SJLT(G.n, 24, SketchContext(seed=2))
        part = RowPartition(nrows=E, batch_rows=50, world_size=1)
        got = np.asarray(
            streamed_adjacency_sketch(
                graph_block_source(G, batch_edges=50), S, ncols=G.n,
                partition=part, params=ElasticParams(),
            )
        )
        want = np.asarray(incore_adjacency_sketch(G, S))
        np.testing.assert_array_equal(got, want)

    def test_two_rank_simulated_merge(self, rng):
        """Rank partials folded independently psum to the in-core bits
        (simulated world: ElasticParams(rank=, world_size=2) drives the
        fold directly; the merge is an explicit sum)."""
        from libskylark_tpu.graph.stream import adjacency_sketch_fold
        from libskylark_tpu.streaming.elastic import (
            ElasticParams,
            RowPartition,
            elastic_run_stream,
        )

        G = random_graph(rng)
        E = G.volume // 2
        S = CWT(G.n, 16, SketchContext(seed=3))
        init_at, step = adjacency_sketch_fold(S, G.n)
        part = RowPartition(nrows=E, batch_rows=37, world_size=2)
        parts = []
        for r in range(2):
            e0, e1 = part.row_range(r)
            acc, _ = elastic_run_stream(
                graph_block_source(G, batch_edges=37), step, init_at(e0),
                part, ElasticParams(rank=r, world_size=2),
                kind="graph_distributed_sketch",
            )
            assert int(acc["edge"]) == e1  # partition end-check holds
            parts.append(np.asarray(acc["sa"]))
        merged = parts[0] + parts[1]
        want = np.asarray(incore_adjacency_sketch(G, S))
        np.testing.assert_array_equal(merged, want)

    @pytest.mark.faults
    def test_kill_resume_bitwise(self, rng, tmp_path):
        from libskylark_tpu.resilient import FaultPlan, SimulatedPreemption
        from libskylark_tpu.streaming import StreamParams

        G = random_graph(rng)
        S = SJLT(G.n, 24, SketchContext(seed=4))
        src = graph_block_source(G, batch_edges=30)
        want = np.asarray(
            streamed_adjacency_sketch(src, S, ncols=G.n)
        )
        ck = str(tmp_path / "ck")
        with pytest.raises(SimulatedPreemption):
            streamed_adjacency_sketch(
                src, S, ncols=G.n,
                params=StreamParams(checkpoint_dir=ck, checkpoint_every=2),
                fault_plan=FaultPlan(preempt_after_chunk=1),
            )
        got = np.asarray(
            streamed_adjacency_sketch(
                src, S, ncols=G.n,
                params=StreamParams(
                    checkpoint_dir=ck, checkpoint_every=2, resume=True
                ),
            )
        )
        np.testing.assert_array_equal(got, want)

    def test_chained_sharded_equals_streamed_chain(self, rng):
        """S₂·(S₁·A) through the sharded sparse-out schedule ≡ the
        streamed-fold chain, bitwise."""
        G = random_graph(rng)
        ctx = SketchContext(seed=5)
        S1 = CWT(G.n, 16, ctx)
        S2 = CWT(16, 8, ctx)
        incore = np.asarray(chained_adjacency_sketch(G, S1, S2))
        streamed = np.asarray(
            chained_adjacency_sketch(G, S1, S2, streamed=True,
                                     batch_edges=23)
        )
        np.testing.assert_array_equal(streamed, incore)

    def test_chained_size_mismatch_rejected(self, rng):
        G = random_graph(rng, n=16, m=40)
        ctx = SketchContext(seed=6)
        with pytest.raises(InvalidParameters, match="S2.n == S1.s"):
            chained_adjacency_sketch(G, CWT(G.n, 8, ctx), CWT(12, 4, ctx))

    def test_non_hash_sketch_rejected(self, rng):
        from libskylark_tpu.graph.stream import adjacency_sketch_fold
        from libskylark_tpu.sketch import JLT

        with pytest.raises(InvalidParameters, match="hash sketch"):
            adjacency_sketch_fold(JLT(32, 8, SketchContext(seed=7)), 32)


# ---------------------------------------------------------------------------
# stream_arc_list (file → blocks)
# ---------------------------------------------------------------------------


class TestStreamArcList:
    def test_matches_simple_graph_on_messy_file(self, tmp_path):
        """Comments, duplicates, reversed duplicates, self-loops, extra
        columns, and a torn last line: the streamed blocks hold exactly
        SimpleGraph's edge set, ids from the same first-seen interning."""
        text = (
            "# comment\n"
            "% another\n"
            "a b\n"
            "b c 3.5\n"
            "a b\n"        # duplicate
            "b a\n"        # reversed duplicate
            "c c\n"        # self-loop, dropped by name
            "\n"
            "d\n"          # short line, skipped
            "c d"          # torn last line: no trailing newline
        )
        (tmp_path / "g").write_text(text)
        G = SimpleGraph([("a", "b"), ("b", "c"), ("c", "d")])
        index, E = scan_arc_list(tmp_path / "g")
        assert E == 3
        assert index == G.index
        blocks = list(stream_arc_list(tmp_path / "g", index=index))
        rows = np.concatenate([b["rows"] for b in blocks])
        cols = np.concatenate([b["cols"] for b in blocks])
        assert rows.size == 2 * E
        got = {(int(min(u, v)), int(max(u, v))) for u, v in zip(rows, cols)}
        assert got == {tuple(e) for e in edges_of(G).tolist()}

    @pytest.mark.parametrize("chunk_bytes", [7, 64, 1 << 20])
    def test_blocks_independent_of_chunk_bytes(self, tmp_path, rng,
                                               chunk_bytes):
        lines = [
            f"{rng.integers(0, 40)} {rng.integers(0, 40)}"
            for _ in range(300)
        ]
        (tmp_path / "g").write_text("\n".join(lines) + "\n")
        ref = list(stream_arc_list(tmp_path / "g", batch_edges=17))
        got = list(
            stream_arc_list(
                tmp_path / "g", batch_edges=17, chunk_bytes=chunk_bytes
            )
        )
        assert len(got) == len(ref)
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(a["rows"], b["rows"])
            np.testing.assert_array_equal(a["cols"], b["cols"])
            np.testing.assert_array_equal(a["vals"], b["vals"])

    def test_fixed_block_sizes(self, tmp_path):
        lines = [f"{i} {i + 1}" for i in range(10)]
        (tmp_path / "g").write_text("\n".join(lines) + "\n")
        blocks = list(stream_arc_list(tmp_path / "g", batch_edges=4))
        assert [b["rows"].size // 2 for b in blocks] == [4, 4, 2]

    def test_streamed_file_sketch_equals_incore(self, tmp_path, rng):
        """End-to-end: file → arc_list_source → fold ≡ SimpleGraph →
        BCOO apply, bitwise."""
        e = rng.integers(0, 48, (250, 2))
        (tmp_path / "g").write_text(
            "".join(f"{u} {v}\n" for u, v in e.tolist())
        )
        G = SimpleGraph(map(tuple, e.tolist()))
        index, E = scan_arc_list(tmp_path / "g")
        assert E == G.volume // 2
        S = SJLT(G.n, 24, SketchContext(seed=8))
        got = np.asarray(
            streamed_adjacency_sketch(
                arc_list_source(tmp_path / "g", index=index, batch_edges=31),
                S, ncols=G.n,
            )
        )
        want = np.asarray(incore_adjacency_sketch(G, S))
        np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# streaming ASE
# ---------------------------------------------------------------------------


class TestStreamingASE:
    def test_exact_on_low_rank_graph(self):
        """K_{10,14} has rank-2 adjacency with eigenvalues ±√140: the
        one-pass Nyström route recovers spectrum and reconstruction to
        fp accuracy once s ≥ rank."""
        G = SimpleGraph(
            [(f"l{i}", f"r{j}") for i in range(10) for j in range(14)]
        )
        X, lam = streaming_ase(
            graph_block_source(G, batch_edges=13), G.n, 2,
            SketchContext(seed=9),
        )
        lam = np.asarray(lam)
        np.testing.assert_allclose(
            np.sort(lam), [-np.sqrt(140), np.sqrt(140)], rtol=1e-10
        )
        X = np.asarray(X)
        A_hat = (X * np.sign(lam)[None, :]) @ X.T
        np.testing.assert_allclose(A_hat, G.adjacency(), atol=1e-6)

    def test_ase_params_streamed_routes_bitwise(self, rng):
        G = random_graph(rng, n=40, m=150)
        X1, lam1 = approximate_ase(
            G, 3, SketchContext(seed=10),
            ASEParams(num_iterations=0, streamed=True, batch_edges=29),
        )
        X2, lam2 = streaming_ase(
            graph_block_source(G, batch_edges=29), G.n, 3,
            SketchContext(seed=10),
        )
        np.testing.assert_array_equal(np.asarray(X1), np.asarray(X2))
        np.testing.assert_array_equal(np.asarray(lam1), np.asarray(lam2))

    def test_streamed_independent_of_block_size(self, rng):
        G = random_graph(rng, n=40, m=150)
        outs = [
            np.asarray(
                streaming_ase(
                    graph_block_source(G, batch_edges=be), G.n, 3,
                    SketchContext(seed=11),
                )[0]
            )
            for be in (11, 150)
        ]
        np.testing.assert_array_equal(outs[0], outs[1])

    def test_subspace_iteration_rejected(self, rng):
        from libskylark_tpu.linalg.svd import SVDParams

        G = random_graph(rng, n=20, m=60)
        with pytest.raises(InvalidParameters, match="one-pass"):
            streaming_ase(
                graph_block_source(G), G.n, 2, SketchContext(seed=12),
                SVDParams(num_iterations=2),
            )


# ---------------------------------------------------------------------------
# served graph queries
# ---------------------------------------------------------------------------


@pytest.mark.serve
class TestServedGraph:
    def _graph(self):
        return SimpleGraph(
            [(f"v{i}", f"v{j}") for i in range(8) for j in range(8, 20)]
        )

    def _server(self, max_coalesce):
        from libskylark_tpu.serve.server import ServeParams, Server

        srv = Server(
            ServeParams(max_coalesce=max_coalesce, warm_start=False)
        )
        srv.register_graph("web", self._graph(), k=4)
        return srv

    def test_ppr_coalesced_equals_solo(self):
        def run(mc):
            with self._server(mc) as srv:
                futs = [
                    srv.submit(
                        {"op": "ppr", "graph": "web",
                         "seeds": ["v0", "v1"], "id": i}
                    )
                    for i in range(12)
                ]
                return [f.result() for f in futs]

        solo, coal = run(1), run(16)
        for a, b in zip(solo, coal):
            assert a["ok"] and b["ok"]
            assert a["result"] == b["result"]

    def test_ppr_seed_order_and_names_canonicalize(self):
        G = self._graph()
        with self._server(16) as srv:
            by_name = srv.call(op="ppr", graph="web", seeds=["v0", "v1"])
            by_id = srv.call(
                op="ppr", graph="web",
                seeds=[G.index["v1"], G.index["v0"]],
            )
            assert by_name["ok"]
            assert by_name["result"] == by_id["result"]

    def test_ase_embed_rows_and_oos(self):
        G = self._graph()
        with self._server(16) as srv:
            one = srv.call(op="ase_embed", graph="web", ids="v3")
            row = np.asarray(one["result"])
            assert row.shape == (4,)  # scalar id squeezes
            many = np.asarray(
                srv.call(
                    op="ase_embed", graph="web",
                    ids=[G.index["v3"], G.index["v5"]],
                )["result"]
            )
            assert many.shape == (2, 4)
            np.testing.assert_array_equal(many[0], row)
            # OOS projection from an existing vertex's own neighbor
            # list reproduces its embedding row (a_i·V = V[i,:]·Λ).
            nb = [int(x) for x in G.neighbors(G.index["v3"])]
            proj = np.asarray(
                srv.call(
                    op="ase_embed", graph="web", neighbors=nb
                )["result"]
            )
            np.testing.assert_allclose(proj, row, atol=1e-10)

    def test_client_wrappers_and_census(self):
        from libskylark_tpu.serve.client import Client

        with self._server(16) as srv:
            assert srv.census()["graphs"] == ["web"]
            assert any(p.startswith("graph:web:k=4") for p in srv.primed)
            c = Client(srv)
            rep = c.ppr("web", ["v0"], check=True)
            assert rep["graph"] == "web" and 0 <= rep["conductance"] <= 1
            row = np.asarray(c.ase_embed("web", ids="v0", check=True))
            assert row.shape == (4,)
