"""CLI driver tests for skylark-krr and skylark-ml + graft entry points."""

import numpy as np
import pytest

from libskylark_tpu.io import write_libsvm


@pytest.fixture
def blob_files(tmp_path, rng):
    d = 4
    X0 = rng.standard_normal((40, d)) - 1.5
    X1 = rng.standard_normal((40, d)) + 1.5
    X = np.vstack([X0, X1])
    y = np.array([1] * 40 + [2] * 40)
    perm = rng.permutation(80)
    X, y = X[perm], y[perm]
    write_libsvm(tmp_path / "train", X[:64], y[:64])
    write_libsvm(tmp_path / "test", X[64:], y[64:])
    return tmp_path


class TestKrrCLI:
    @pytest.mark.parametrize("alg", [0, 1, 2])
    def test_classification(self, blob_files, alg, capsys):
        from libskylark_tpu.cli.krr import main

        rc = main([
            "--trainfile", str(blob_files / "train"),
            "--testfile", str(blob_files / "test"),
            "--modelfile", str(blob_files / "m.json"),
            "-a", str(alg), "--sigma", "2.0", "-f", "256",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        acc = float(out.split("Test accuracy:")[1].split("%")[0])
        assert acc > 85.0

    def test_regression(self, tmp_path, rng, capsys):
        from libskylark_tpu.cli.krr import main

        X = rng.standard_normal((100, 3))
        y = X.sum(1)
        write_libsvm(tmp_path / "train", X, y)
        write_libsvm(tmp_path / "test", X[:20], y[:20])
        rc = main([
            "--trainfile", str(tmp_path / "train"),
            "--testfile", str(tmp_path / "test"),
            "--modelfile", str(tmp_path / "m.json"),
            "-a", "2", "--regression", "--sigma", "3.0", "-f", "512",
            "--lambda", "0.001",
        ])
        assert rc == 0
        err = float(capsys.readouterr().out.split("relative error:")[1])
        assert err < 0.2


class TestMlCLI:
    def test_train_and_predict(self, blob_files, capsys):
        from libskylark_tpu.cli.ml import main

        rc = main([
            "--trainfile", str(blob_files / "train"),
            "--testfile", str(blob_files / "test"),
            "--modelfile", str(blob_files / "admm.json"),
            "-l", "hinge", "-g", "2.0", "-f", "256", "-n", "2",
            "-i", "25", "--lambda", "0.005",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        acc = float(out.split("Test accuracy:")[1].split("%")[0])
        assert acc > 85.0

    def test_predict_from_saved_model(self, blob_files, capsys):
        from libskylark_tpu.cli.ml import main

        main([
            "--trainfile", str(blob_files / "train"),
            "--modelfile", str(blob_files / "admm2.json"),
            "-l", "squared", "-g", "2.0", "-f", "128", "-n", "2", "-i", "15",
        ])
        capsys.readouterr()
        rc = main([
            "--testfile", str(blob_files / "test"),
            "--modelfile", str(blob_files / "admm2.json"),
        ])
        assert rc == 0
        out = capsys.readouterr().out
        acc = float(out.split("Test accuracy:")[1].split("%")[0])
        assert acc > 85.0


class TestGraftEntry:
    def test_entry_compiles(self):
        import sys

        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g
        import jax

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (256, 10)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_dryrun_multichip_8(self):
        import sys

        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g

        g.dryrun_multichip(8)
