"""CLI driver tests for skylark-krr and skylark-ml + graft entry points."""

import numpy as np
import pytest

from libskylark_tpu.io import write_libsvm


@pytest.fixture
def blob_files(tmp_path, rng):
    d = 4
    X0 = rng.standard_normal((40, d)) - 1.5
    X1 = rng.standard_normal((40, d)) + 1.5
    X = np.vstack([X0, X1])
    y = np.array([1] * 40 + [2] * 40)
    perm = rng.permutation(80)
    X, y = X[perm], y[perm]
    write_libsvm(tmp_path / "train", X[:64], y[:64])
    write_libsvm(tmp_path / "test", X[64:], y[64:])
    return tmp_path


class TestKrrCLI:
    @pytest.mark.slow
    @pytest.mark.parametrize("alg", [0, 1, 2])
    def test_classification(self, blob_files, alg, capsys):
        from libskylark_tpu.cli.krr import main

        rc = main([
            "--trainfile", str(blob_files / "train"),
            "--testfile", str(blob_files / "test"),
            "--modelfile", str(blob_files / "m.json"),
            "-a", str(alg), "--sigma", "2.0", "-f", "256",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        acc = float(out.split("Test accuracy:")[1].split("%")[0])
        assert acc > 85.0

    @pytest.mark.slow
    def test_regression(self, tmp_path, rng, capsys):
        from libskylark_tpu.cli.krr import main

        X = rng.standard_normal((100, 3))
        y = X.sum(1)
        write_libsvm(tmp_path / "train", X, y)
        write_libsvm(tmp_path / "test", X[:20], y[:20])
        rc = main([
            "--trainfile", str(tmp_path / "train"),
            "--testfile", str(tmp_path / "test"),
            "--modelfile", str(tmp_path / "m.json"),
            "-a", "2", "--regression", "--sigma", "3.0", "-f", "512",
            "--lambda", "0.001",
        ])
        assert rc == 0
        err = float(capsys.readouterr().out.split("relative error:")[1])
        assert err < 0.2


class TestMlCLI:
    @pytest.mark.slow
    def test_train_and_predict(self, blob_files, capsys):
        from libskylark_tpu.cli.ml import main

        rc = main([
            "--trainfile", str(blob_files / "train"),
            "--testfile", str(blob_files / "test"),
            "--modelfile", str(blob_files / "admm.json"),
            "-l", "hinge", "-g", "2.0", "-f", "256", "-n", "2",
            "-i", "25", "--lambda", "0.005",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        acc = float(out.split("Test accuracy:")[1].split("%")[0])
        assert acc > 85.0

    @pytest.mark.slow
    def test_predict_from_saved_model(self, blob_files, capsys):
        from libskylark_tpu.cli.ml import main

        main([
            "--trainfile", str(blob_files / "train"),
            "--modelfile", str(blob_files / "admm2.json"),
            "-l", "squared", "-g", "2.0", "-f", "128", "-n", "2", "-i", "15",
        ])
        capsys.readouterr()
        rc = main([
            "--testfile", str(blob_files / "test"),
            "--modelfile", str(blob_files / "admm2.json"),
        ])
        assert rc == 0
        out = capsys.readouterr().out
        acc = float(out.split("Test accuracy:")[1].split("%")[0])
        assert acc > 85.0


class TestGraftEntry:
    def test_entry_compiles(self):
        import sys

        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g
        import jax

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (256, 10)
        assert np.all(np.isfinite(np.asarray(out)))

    @pytest.mark.slow
    def test_dryrun_multichip_8(self):
        import sys

        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g

        g.dryrun_multichip(8)


class TestStreamingPredict:
    def test_streaming_matches_batch(self, blob_files, capsys):
        from libskylark_tpu.cli.ml import main

        main([
            "--trainfile", str(blob_files / "train"),
            "--modelfile", str(blob_files / "sp.json"),
            "-l", "squared", "-g", "2.0", "-f", "128", "-n", "2", "-i", "15",
        ])
        capsys.readouterr()
        rc = main([
            "--testfile", str(blob_files / "test"),
            "--modelfile", str(blob_files / "sp.json"),
            "--outputfile", str(blob_files / "preds.txt"),
            "--batch", "5",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        acc_stream = float(out.split("Test accuracy:")[1].split("%")[0])
        preds = (blob_files / "preds.txt").read_text().splitlines()
        assert len(preds) == 16
        rc = main([
            "--testfile", str(blob_files / "test"),
            "--modelfile", str(blob_files / "sp.json"),
        ])
        out = capsys.readouterr().out
        acc_batch = float(out.split("Test accuracy:")[1].split("%")[0])
        assert acc_stream == acc_batch


class TestStreamLibsvm:
    def test_batches_cover_file(self, tmp_path, rng):
        import numpy as np

        from libskylark_tpu.io import read_libsvm, stream_libsvm, write_libsvm

        X = rng.standard_normal((23, 6))
        y = rng.standard_normal(23)
        write_libsvm(tmp_path / "s", X, y)
        chunks = list(stream_libsvm(tmp_path / "s", 6, batch=7))
        assert [len(c[1]) for c in chunks] == [7, 7, 7, 2]
        Xall = np.vstack([c[0] for c in chunks])
        yall = np.concatenate([c[1] for c in chunks])
        Xr, yr = read_libsvm(tmp_path / "s", n_features=6)
        np.testing.assert_allclose(Xall, Xr, rtol=1e-15)
        np.testing.assert_allclose(yall, yr, rtol=1e-15)


class TestStreamLibsvmSparse:
    def test_sparse_batches_match_dense(self, tmp_path, rng):
        import numpy as np

        from libskylark_tpu.io import stream_libsvm, write_libsvm

        X = rng.standard_normal((17, 8))
        X[rng.random((17, 8)) < 0.6] = 0
        y = rng.standard_normal(17)
        write_libsvm(tmp_path / "sp", X, y)
        dense = list(stream_libsvm(tmp_path / "sp", 8, batch=6))
        sparse = list(stream_libsvm(tmp_path / "sp", 8, batch=6, sparse=True))
        assert len(dense) == len(sparse) == 3
        for (Xd, yd), (Xs, ys) in zip(dense, sparse):
            np.testing.assert_allclose(np.asarray(Xs.todense()), Xd, rtol=1e-15)
            np.testing.assert_allclose(ys, yd)


class TestHdf5FileFormat:
    """convert2hdf5 → train/test round trip (VERDICT r3 item 8): both
    CLIs accept --fileformat hdf5_dense/hdf5_sparse end-to-end
    (≙ ml/options.hpp:46-47,173-174; ml/io.hpp:869-889)."""

    def test_convert_then_krr_hdf5_dense(self, blob_files, capsys):
        from libskylark_tpu.cli.convert2hdf5 import main as convert
        from libskylark_tpu.cli.krr import main as krr

        for split in ("train", "test"):
            rc = convert([
                str(blob_files / split), str(blob_files / f"{split}.h5")
            ])
            assert rc == 0
        capsys.readouterr()
        rc = krr([
            "--trainfile", str(blob_files / "train.h5"),
            "--testfile", str(blob_files / "test.h5"),
            "--modelfile", str(blob_files / "mh.json"),
            "--fileformat", "hdf5_dense",
            "-a", "2", "--sigma", "2.0", "-f", "256",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        acc = float(out.split("Test accuracy:")[1].split("%")[0])
        assert acc > 85.0

    def test_convert_then_ml_hdf5_sparse(self, blob_files, capsys):
        from libskylark_tpu.cli.convert2hdf5 import main as convert
        from libskylark_tpu.cli.ml import main as ml

        for split in ("train", "test"):
            rc = convert([
                str(blob_files / split), str(blob_files / f"{split}s.h5"),
                "--sparse",
            ])
            assert rc == 0
        capsys.readouterr()
        rc = ml([
            "--trainfile", str(blob_files / "trains.h5"),
            "--testfile", str(blob_files / "tests.h5"),
            "--modelfile", str(blob_files / "admmh.json"),
            "--fileformat", "hdf5_sparse",
            "-l", "hinge", "-g", "2.0", "-f", "256", "-n", "2",
            "-i", "25", "--lambda", "0.005",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        acc = float(out.split("Test accuracy:")[1].split("%")[0])
        assert acc > 85.0

    def test_hdf5_streaming_predict_matches_batch(self, blob_files, capsys):
        from libskylark_tpu.cli.convert2hdf5 import main as convert
        from libskylark_tpu.cli.ml import main as ml

        ml([
            "--trainfile", str(blob_files / "train"),
            "--modelfile", str(blob_files / "sph.json"),
            "-l", "squared", "-g", "2.0", "-f", "128", "-n", "2", "-i", "15",
        ])
        convert([str(blob_files / "test"), str(blob_files / "testh.h5")])
        capsys.readouterr()
        rc = ml([
            "--testfile", str(blob_files / "testh.h5"),
            "--modelfile", str(blob_files / "sph.json"),
            "--fileformat", "hdf5_dense",
            "--outputfile", str(blob_files / "predh.txt"),
            "--batch", "5",
        ])
        assert rc == 0
        acc_stream = float(
            capsys.readouterr().out.split("Test accuracy:")[1].split("%")[0]
        )
        assert len((blob_files / "predh.txt").read_text().splitlines()) == 16
        rc = ml([
            "--testfile", str(blob_files / "test"),
            "--modelfile", str(blob_files / "sph.json"),
        ])
        acc_batch = float(
            capsys.readouterr().out.split("Test accuracy:")[1].split("%")[0]
        )
        assert acc_stream == acc_batch

    def test_stream_hdf5_sparse_batches(self, tmp_path, rng):
        from libskylark_tpu.io import read_hdf5, stream_hdf5, write_hdf5

        X = rng.standard_normal((17, 8))
        X[rng.random((17, 8)) < 0.6] = 0
        y = rng.standard_normal(17)
        write_hdf5(tmp_path / "s.h5", X, y, sparse=True)
        chunks = list(stream_hdf5(tmp_path / "s.h5", batch=6))
        assert [len(c[1]) for c in chunks] == [6, 6, 5]
        Xall = np.vstack([np.asarray(c[0].todense()) for c in chunks])
        yall = np.concatenate([c[1] for c in chunks])
        Xr, yr = read_hdf5(tmp_path / "s.h5", sparse=False)
        np.testing.assert_allclose(Xall, Xr, rtol=1e-15)
        np.testing.assert_allclose(yall, yr, rtol=1e-15)


class TestModelRoundTripAcrossCLIs:
    def test_krr_kernel_model_reloads_with_classes(self, blob_files):
        """A kernel-space model saved by skylark-krr (-a 0) reloads via
        the polymorphic load_model with its label coding intact
        (≙ model_container_t dispatch, model.hpp:1138-1255)."""
        from libskylark_tpu.cli.krr import main
        from libskylark_tpu.io import read_libsvm
        from libskylark_tpu.ml import KernelModel, load_model

        rc = main([
            "--trainfile", str(blob_files / "train"),
            "--modelfile", str(blob_files / "km.json"),
            "-a", "0", "--sigma", "2.0",
        ])
        assert rc == 0
        m = load_model(blob_files / "km.json")
        assert isinstance(m, KernelModel)
        assert m.classes is not None and len(m.classes) >= 2
        Xt, yt = read_libsvm(blob_files / "test")
        pred = np.asarray(m.predict_labels(Xt))
        assert (pred == yt).mean() > 0.85
