"""Durable serve state (ISSUE PR 19): WAL, crash recovery, exactly-once.

The load-bearing contracts:

- **Every mint journals BEFORE it publishes.**  All five
  ``Registry._mint`` kinds append a CRC-framed, epoch-stamped record to
  ``registry-journal.jsonl`` (fsync'd) before the mutation is visible;
  ``Registry.recover`` replays snapshot + journal tail to a registry
  **bitwise-identical** to one that never crashed — same entity bits,
  same epoch counter, same ``epoch_log``.
- **Torn tails are the crash model; mid-file damage is not.**  A
  SIGKILL mid-append leaves at most one torn/CRC-bad FINAL line: the
  journal reader truncates and counts it.  A bad record *followed by
  valid ones* cannot come from that crash — it raises code-118
  ``JournalError`` (reason ``"crc"``) instead of guessing.  The same
  torn-frame discipline holds across the repo's JSONL readers, each
  with its own documented failure mode (parametrized below).
- **Exactly-once across failover.**  ``op:"update"`` requests carry an
  ``idem_key``; the dedup window is keyed ``(tenant, idem_key)``,
  rides the journal/snapshot, and a replayed key — same process or a
  recovered one — returns the ORIGINAL epoch receipt without minting.
- **SIGKILL chaos drill** (subprocess, uncatchable): a live replica
  killed between journal append and publish recovers to the same bits
  as a never-crashed control; a tear mid-frame recovers to the bits
  *before* that update.
"""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from libskylark_tpu import serve, telemetry
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.graph.graph import SimpleGraph
from libskylark_tpu.serve import journal as journal_mod
from libskylark_tpu.serve.journal import Journal, read_journal
from libskylark_tpu.serve.registry import Registry
from libskylark_tpu.utils import exceptions as ex
from libskylark_tpu.utils.checkpoint import CheckpointStore

pytestmark = pytest.mark.durability

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)

# The chaos child doubles as the digest library (tests/ is not a
# package — load it by path, same trick as its own subprocess entry).
_spec = importlib.util.spec_from_file_location(
    "_journal_child", os.path.join(_HERE, "_journal_child.py")
)
_JC = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_JC)

N_V = 16
RING = [(v, (v + 1) % N_V) for v in range(N_V)]


def _km(rng):
    from libskylark_tpu.ml.kernels import GaussianKernel
    from libskylark_tpu.ml.model import KernelModel

    return KernelModel(
        GaussianKernel(12, sigma=1.1),
        rng.standard_normal((10, 12)),
        rng.standard_normal((10, 3)),
    )


def _journaled_registry(directory, **jkw):
    """A registry journaling into ``directory`` with one entity of each
    flavor registered (CWT system — FJLT has no columnwise partial rule
    and refuses live appends)."""
    reg = Registry(journal=Journal(str(directory), **jkw))
    rng = np.random.default_rng(3)
    reg.register_system(
        "sys", rng.standard_normal((24, 5)), context=SketchContext(seed=9),
        sketch_type="CWT", sketch_size=32, capacity=96,
    )
    reg.register_graph(
        "g", SimpleGraph(RING), k=2, context=SketchContext(seed=5)
    )
    reg.register_model("krr", _km(rng))
    return reg, rng


def _mutate_all_kinds(reg, rng):
    """One of every replayable mutation, idempotency keys included."""
    reg.append_system_rows("sys", rng.standard_normal((3, 5)),
                           idem=("t0", "a"))
    reg.fold_graph_edges("g", [(0, 5), (3, 9)], idem=("t0", "b"))
    reg.downdate_system_rows("sys", [1, 4], idem=("t0", "c"))
    reg.update_model("krr", append=(rng.standard_normal((2, 12)),
                                    rng.standard_normal((2, 3))))
    reg.update_model("krr", drop=[10])
    reg.update_model("krr", model=_km(rng))


# ---------------------------------------------------------------------------
# journal replay: bitwise recovery


def test_recover_bitwise_all_kinds(tmp_path):
    reg, rng = _journaled_registry(tmp_path)
    _mutate_all_kinds(reg, rng)
    assert reg.epoch == 9  # 3 registrations + 6 mutations

    rec = Registry.recover(str(tmp_path))
    assert _JC.digest(rec) == _JC.digest(reg)
    # The recovered registry is LIVE: it journals onward and stays in
    # lockstep with the original applying the same next mutation.
    rows = rng.standard_normal((2, 5))
    reg.append_system_rows("sys", rows)
    rec.append_system_rows("sys", rows)
    assert _JC.digest(rec) == _JC.digest(reg)


def test_compaction_snapshot_then_tail_replay(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    reg, rng = _journaled_registry(tmp_path, compact_every=4)
    _mutate_all_kinds(reg, rng)
    # 9 appends at compact_every=4 → at least one snapshot committed,
    # journal holding only the post-snapshot tail.
    store = CheckpointStore(str(tmp_path), prefix=journal_mod.SNAP_PREFIX)
    assert store.steps(), "compaction never committed a snapshot slot"
    records, torn = read_journal(
        os.path.join(str(tmp_path), journal_mod.JOURNAL_NAME)
    )
    assert torn == 0 and len(records) < reg.epoch
    counters = telemetry.REGISTRY.snapshot()["counters"]
    assert counters.get("journal.compactions", 0) >= 1
    assert counters.get("journal.appends", 0) == 9

    rec = Registry.recover(str(tmp_path))
    assert _JC.digest(rec) == _JC.digest(reg)
    # Replays counted; idempotency receipts survive the snapshot ride.
    counters = telemetry.REGISTRY.snapshot()["counters"]
    assert counters.get("journal.replays", 0) >= len(records)
    assert rec.idem_receipt("t0", "a")["epoch"] == 4


def test_torn_tail_truncated_and_counted(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    reg, rng = _journaled_registry(tmp_path)
    reg.append_system_rows("sys", rng.standard_normal((2, 5)))
    before = _JC.digest(reg)
    path = os.path.join(str(tmp_path), journal_mod.JOURNAL_NAME)
    with open(path, "ab") as f:  # SIGKILL mid-append: half a frame
        f.write(b'{"crc": 12345, "rec": {"epoch": 5, "kind": "row_ap')

    telemetry.REGISTRY.reset()
    rec = Registry.recover(str(tmp_path))
    assert rec.journal.torn_truncated == 1
    assert _JC.digest(rec) == before  # the torn record never happened
    counters = telemetry.REGISTRY.snapshot()["counters"]
    assert counters.get("journal.torn_tail", 0) == 1
    # The truncation is durable: a second recovery sees a clean file.
    assert Registry.recover(str(tmp_path)).journal.torn_truncated == 0


def test_midfile_corruption_raises_118(tmp_path):
    reg, rng = _journaled_registry(tmp_path)
    reg.append_system_rows("sys", rng.standard_normal((2, 5)))
    path = os.path.join(str(tmp_path), journal_mod.JOURNAL_NAME)
    lines = open(path, "rb").read().splitlines(keepends=True)
    assert len(lines) >= 3
    lines[1] = lines[1][:20] + b"XX" + lines[1][22:]  # not the tail
    with open(path, "wb") as f:
        f.writelines(lines)

    with pytest.raises(ex.JournalError) as ei:
        Registry.recover(str(tmp_path))
    assert ei.value.code == 118
    assert ei.value.reason == "crc"
    assert ei.value.record == 2  # 1-based line number of the damage


# ---------------------------------------------------------------------------
# torn-frame semantics across the repo's JSONL readers (satellite):
# every reader tolerates a torn FINAL line; what each does with damage
# beyond the crash model is its own documented contract.


def _torn(line: bytes) -> bytes:
    return line[: max(1, len(line) // 2)].rstrip(b"\n")


@pytest.mark.parametrize(
    "reader,damage",
    [
        ("journal", "torn-tail"),
        ("journal", "mid-file"),
        ("progress", "torn-tail"),
        ("progress", "mid-file"),
        ("ledger-fold", "torn-tail"),
        ("snapshot", "stale-epoch"),
    ],
)
def test_torn_frame_semantics(tmp_path, reader, damage):
    if reader == "journal":
        reg, rng = _journaled_registry(tmp_path)
        reg.append_system_rows("sys", rng.standard_normal((2, 5)))
        path = os.path.join(str(tmp_path), journal_mod.JOURNAL_NAME)
        lines = open(path, "rb").read().splitlines(keepends=True)
        if damage == "torn-tail":
            with open(path, "wb") as f:
                f.writelines(lines[:-1])
                f.write(_torn(lines[-1]))
            records, torn = read_journal(path)
            assert torn == 1 and len(records) == len(lines) - 1
        else:  # a torn line with valid records AFTER it: code 118
            with open(path, "wb") as f:
                f.writelines(lines[:1])
                f.write(_torn(lines[1]) + b"\n")
                f.writelines(lines[2:])
            with pytest.raises(ex.JournalError) as ei:
                read_journal(path)
            assert ei.value.code == 118
    elif reader == "progress":
        from libskylark_tpu.streaming.elastic import read_progress

        path = tmp_path / "progress.jsonl"
        recs = [{"seq": i, "attrs": {"epoch": 1}, "i": i} for i in range(4)]
        lines = [json.dumps(r).encode() + b"\n" for r in recs]
        if damage == "torn-tail":
            path.write_bytes(b"".join(lines[:-1]) + _torn(lines[-1]))
            got = read_progress(path)
            assert [r["i"] for r in got] == [0, 1, 2]
        else:
            # Mid-file garbage is LEGITIMATE here: a host that resumed
            # after its own torn tail appends valid records after the
            # tear.  read_progress keeps intact prefix AND suffix —
            # this tolerance is load-bearing for elastic resume (the
            # registry journal, whose replay must be gapless, is the
            # reader that hard-fails instead).
            path.write_bytes(
                lines[0] + _torn(lines[1]) + b"\n" + b"".join(lines[2:])
            )
            got = read_progress(path)
            assert [r["i"] for r in got] == [0, 2, 3]
    elif reader == "ledger-fold":
        # The fleet fold rides read_progress per host: a host with a
        # torn tail still folds (its intact records count), and
        # records from a superseded epoch are fenced out as stale —
        # the 111-flavored tolerance at the aggregation layer.
        from libskylark_tpu.telemetry.fleet import fold_ledgers

        hdir = tmp_path / "host-00000"
        hdir.mkdir()
        # Pin the root epoch so the intact-but-stale record below is
        # fenced against the MARKER, not voted in by its own epoch.
        (tmp_path / "epoch.json").write_text(json.dumps(
            {"skylark_object_type": "elastic_epoch", "epoch": 0}
        ))
        good = [
            {"seq": i, "attrs": {"epoch": 0, "rank": 0, "rows": 2}}
            for i in range(3)
        ]
        stale_rec = {"seq": 9, "attrs": {"epoch": 5, "rank": 0}}
        lines = [json.dumps(r).encode() + b"\n"
                 for r in good + [stale_rec]]
        (hdir / "progress.jsonl").write_bytes(
            b"".join(lines[:-1]) + _torn(lines[-1])
        )
        view = fold_ledgers(str(tmp_path))
        assert view["lost_hosts"] == []
        assert view["ranks"][0]["records"] == 3
        # ...and a wrong-epoch record that DID survive intact is
        # fenced, not folded.
        (hdir / "progress.jsonl").write_bytes(b"".join(lines))
        view = fold_ledgers(str(tmp_path))
        assert view["stale_records"] == 1
        assert view["ranks"][0]["records"] == 3
    else:
        # The compaction snapshot rides CheckpointStore: an epoch-
        # pinned load of a slot from another life is the 111 hard-fail
        # (StaleEpochError), not a silent stale restore.
        store = CheckpointStore(str(tmp_path), prefix="registry-snap")
        store.save({"x": np.arange(3.0)}, step=7, metadata={"epoch": 7})
        state, meta, step = store.load_latest(expect_epoch=7)
        assert step == 7 and store.slot_epoch(meta) == 7
        with pytest.raises(ex.StaleEpochError) as ei:
            store.load_latest(expect_epoch=9)
        assert ei.value.code == 111


# ---------------------------------------------------------------------------
# exactly-once updates through the server, including across recovery


def _durable_server(state_dir, recover=False):
    srv = serve.Server(
        serve.ServeParams(
            warm_start=False, prime=False,
            state_dir=str(state_dir), recover=recover,
        ),
        seed=11,
    )
    if not recover:
        rng = np.random.default_rng(3)
        srv.register_system(
            "sys", rng.standard_normal((24, 5)),
            sketch_type="CWT", capacity=96,
        )
    return srv.start()


def test_server_update_exactly_once_across_recovery(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    srv = _durable_server(tmp_path)
    rows = np.arange(10.0).reshape(2, 5).tolist()
    r1 = srv.call(op="update", system="sys", append=rows, idem_key="k1")
    assert r1["ok"]
    epoch1 = r1["result"]["epoch"]
    m1 = srv.registry.get_system("sys").m

    # Same key replays: original receipt, NO new epoch, no new rows.
    r2 = srv.call(op="update", system="sys", append=rows, idem_key="k1")
    assert r2["ok"] and r2["result"]["epoch"] == epoch1
    assert srv.registry.get_system("sys").m == m1
    assert any(e["kind"] == "idem_replay" for e in r2["trace"]["events"])
    counters = telemetry.REGISTRY.snapshot()["counters"]
    assert counters.get("serve.idem_hits", 0) == 1

    # A fresh key applies.
    r3 = srv.call(op="update", system="sys", append=rows, idem_key="k2")
    assert r3["ok"] and r3["result"]["epoch"] == epoch1 + 1

    # Bad keys shed at the door (102), before queue/quota pressure.
    bad = srv.call(op="update", system="sys", append=rows, idem_key="")
    assert not bad["ok"] and bad["error"]["code"] == 102
    srv.stop()

    # Failover: a NEW process recovers the journal and the replayed
    # key still answers with the ORIGINAL receipt — exactly once.
    srv2 = _durable_server(tmp_path, recover=True)
    assert srv2.registry.epoch == epoch1 + 1
    r4 = srv2.call(op="update", system="sys", append=rows, idem_key="k1")
    assert r4["ok"] and r4["result"]["epoch"] == epoch1
    assert srv2.registry.get_system("sys").m == m1 + 2  # only k2's rows
    srv2.stop()


def test_client_update_mints_idem_key(tmp_path):
    srv = _durable_server(tmp_path)
    try:
        sent = []
        orig = srv.call

        class _Loopback(serve.Client):
            def __init__(self):
                pass

            def call(self, request=None, /, **fields):
                req = dict(request or {}, **fields)
                sent.append(req)
                return orig(req)

        c = _Loopback()
        rows = np.arange(10.0).reshape(2, 5).tolist()
        r = c.update(system="sys", append=rows)
        assert r["ok"] and len(sent[0]["idem_key"]) == 32  # uuid4 hex
        # The SAME minted key retries as a replay, not a re-apply.
        r2 = c.call(dict(sent[0]))
        assert r2["result"]["epoch"] == r["result"]["epoch"]
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# SIGKILL chaos drill (subprocess — the death is uncatchable)


def test_sigkill_chaos_recovers_control_bits(tmp_path):
    """Kill a live replica inside the update commit window, both edges:

    - AFTER the journal append is durable, BEFORE publish → recovery
      replays the record: bits == a control that ran all 4 updates.
    - MID-frame (torn tail) on update 4 → recovery truncates: bits ==
      the same 4-update control (the 5th never happened).
    """
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    script = os.path.join(_HERE, "_journal_child.py")

    def spawn(d, mode, updates):
        os.makedirs(d, exist_ok=True)
        return subprocess.Popen(
            [sys.executable, script, str(d), mode, str(updates)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=_REPO,
        )

    procs = {
        "control": spawn(tmp_path / "ctl", "control", 4),
        "die-after": spawn(tmp_path / "die", "die-after", 4),
        # torn on update index 4 → updates 0..3 durable: same control.
        "torn": spawn(tmp_path / "torn", "torn", 5),
    }
    outs = {m: p.communicate(timeout=300) for m, p in procs.items()}
    out, err = outs["control"]
    assert procs["control"].returncode == 0 and "JOURNAL-OK" in out, (
        out, err[-2000:]
    )
    for mode in ("die-after", "torn"):
        assert procs[mode].returncode == -9, (mode, outs[mode])

    control = json.load(open(tmp_path / "ctl" / "digest.json"))
    for mode, d in (("die-after", "die"), ("torn", "torn")):
        got = _JC.digest(Registry.recover(str(tmp_path / d)))
        assert got == control, (mode, got["epoch"], control["epoch"])


# ---------------------------------------------------------------------------
# static contracts: codecs, CLI flags, marker registration


def test_every_mint_kind_has_codec_and_replay_handler():
    """The journal is only exactly-once if EVERY mint kind round-trips:
    a new ``Registry._mint`` call site must ship a journal record kind
    and a replay handler in the same PR."""
    import re

    src = open(
        os.path.join(_REPO, "libskylark_tpu", "serve", "registry.py"),
        encoding="utf-8",
    ).read()
    minted = set(re.findall(r'_mint\(\s*\n?\s*"(\w+)"', src))
    journaled = set(re.findall(r'_journal_append\(\s*\n?\s*"(\w+)"', src))
    assert minted == {
        "register", "graph_fold", "row_append", "row_downdate",
        "model_update",
    }
    assert journaled == minted, (
        "mint kinds without a journal append (or vice versa): "
        f"{minted ^ journaled}"
    )
    assert set(journal_mod.RECORD_KINDS) == minted
    assert set(journal_mod.REPLAY_HANDLERS) == minted


def test_durability_marker_and_cli_flags_registered():
    conftest = open(os.path.join(_HERE, "conftest.py"),
                    encoding="utf-8").read()
    assert '"durability": DURABILITY_TIMEOUT_S' in conftest
    assert "durability:" in conftest  # the marker description line
    cli = open(
        os.path.join(_REPO, "libskylark_tpu", "cli", "serve.py"),
        encoding="utf-8",
    ).read()
    for flag in ("--state-dir", "--recover", "--journal-compact-every"):
        assert flag in cli, f"skylark-serve lost {flag}"


# ---------------------------------------------------------------------------
# HTTP socket timeouts (satellite): hung ≠ dead, but hung must RAISE


def test_client_default_timeout_env(monkeypatch):
    monkeypatch.setenv("SKYLARK_HTTP_TIMEOUT_S", "7.5")
    assert serve.client.default_timeout_s() == 7.5
    c = serve.Client(url="http://127.0.0.1:1")
    assert c._timeout == 7.5
    assert serve.Client(url="http://127.0.0.1:1", timeout=2.0)._timeout == 2.0
    monkeypatch.delenv("SKYLARK_HTTP_TIMEOUT_S")
    assert serve.client.default_timeout_s() == 60.0


def test_router_counts_report_timeouts(monkeypatch):
    import socket as socket_mod

    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    rep = serve.HttpReplica("r0", "http://127.0.0.1:1", retries=1)
    rep._sleep = lambda s: None

    def hung():
        raise socket_mod.timeout("recv timed out")

    rep._client.healthz = hung
    with pytest.raises(TimeoutError):
        rep.load_report()
    counters = telemetry.REGISTRY.snapshot()["counters"]
    # attempt 0 + the final attempt both counted
    assert counters.get("router.report_timeouts", 0) == 2

    # A non-timeout transport error does NOT count as a hang.
    def refused():
        raise ConnectionRefusedError("nope")

    rep._client.healthz = refused
    with pytest.raises(ConnectionRefusedError):
        rep.load_report()
    counters = telemetry.REGISTRY.snapshot()["counters"]
    assert counters.get("router.report_timeouts", 0) == 2


# ---------------------------------------------------------------------------
# skylark-top hardening (satellite): dying replicas never traceback it


def test_top_renders_malformed_json_as_unreachable(monkeypatch):
    from libskylark_tpu.cli import top

    shapes = {
        "http://a/healthz": {"_error": "JSONDecodeError: truncated"},
        "http://b/healthz": {"registry": "nope", "primed": 3,
                             "load": "garbage", "fleet": [1, 2]},
        "http://b/stats": {"counters": None, "latency": [0.1]},
        "http://b/traces": {"recent": {"not": "a list"}, "violations": 7},
    }
    monkeypatch.setattr(
        top, "_fetch_json",
        lambda url, timeout=2.0: shapes.get(url, {"_error": "boom"}),
    )

    def _args(*urls):
        return type(
            "A", (), {"url": list(urls), "root": None,
                      "telemetry_dir": None},
        )()

    status = {}
    frame = top.render_frame(_args("http://a"), status)
    assert "UNREACHABLE" in frame
    assert status == {"urls": 1, "answered": 0}

    # Replica b answers /healthz with junk-shaped (but dict) JSON:
    # every section renders defensively, nothing raises.
    status = {}
    frame = top.render_frame(_args("http://b"), status)
    assert status["answered"] == 1
    assert "serve http://b" in frame


def test_top_once_exit_codes(monkeypatch, tmp_path, capsys):
    from libskylark_tpu.cli import top

    monkeypatch.setattr(
        top, "_fetch_json",
        lambda url, timeout=2.0: {"_error": "ConnectionRefusedError"},
    )
    assert top.main(["--url", "http://dead:1", "--once"]) == 1
    capsys.readouterr()

    answers = {"http://live/healthz": {"registry": {}, "primed": []}}
    monkeypatch.setattr(
        top, "_fetch_json",
        lambda url, timeout=2.0: answers.get(url, {"_error": "dead"}),
    )
    # One live member among dead ones: a partially-dead fleet is still
    # an answer, not a monitoring failure.
    rc = top.main(["--url", "http://live", "--url", "http://dead:1",
                   "--once"])
    assert rc == 0
    capsys.readouterr()

    # No URLs at all (ledger/root mode) never fails on reachability.
    assert top.main(["--root", str(tmp_path), "--once"]) == 0
