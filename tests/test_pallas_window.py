"""Pallas window-kernel + fused stream-chunk tests, interpret mode.

Tier-1 on CPU CI (ISSUE 8): ``interpret=True`` executes the kernel body
as traced jax ops, so the grid/BlockSpec plumbing, the scalar-loop
accumulate, the padding seams, and the fused-emit bitwise contract are
all exercised on every PR — not only under SKYLARK_RUN_PERF=1 on TPU.
The compiled-lowering half of the battery lives in
``tests/_hw_guards.py`` / ``test_pallas_hw.py``.

x64 is on (conftest), so every array here is built f32 explicitly — the
window kernel's default dtype gate routes f64 to XLA on purpose.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import plans, streaming
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.core.precision import f32_accumulable
from libskylark_tpu.resilient import FaultPlan
from libskylark_tpu.sketch import pallas_scatter, pallas_window
from libskylark_tpu.sketch.hash import (
    CWT,
    MMT,
    SJLT,
    WZT,
    _segment_sum_rows,
    _window_mode,
)
from libskylark_tpu.streaming import StreamParams

pytestmark = pytest.mark.kernels


@pytest.fixture
def window_interpret():
    """Force the window kernel in interpret mode for the duration of a
    test; the plan key carries the env token, but clear the cache anyway
    so cross-test state can't mask a routing bug."""
    old = os.environ.get("SKYLARK_PALLAS_WINDOW")
    os.environ["SKYLARK_PALLAS_WINDOW"] = "interpret"
    plans.clear()
    try:
        yield
    finally:
        if old is None:
            del os.environ["SKYLARK_PALLAS_WINDOW"]
        else:
            os.environ["SKYLARK_PALLAS_WINDOW"] = old
        plans.clear()


def _rand(rng, shape, dtype=jnp.float32):
    return jnp.asarray(rng.standard_normal(shape), dtype)


# ---------------------------------------------------------------------------
# kernel vs XLA reference
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "k,s,m",
    [
        (7, 12, 5),      # tiny ragged streaming chunk
        (130, 10, 1),    # single-column (the LS driver's sb vector)
        (1000, 96, 200), # off-tile m
        (257, 8, 384),   # multi-lane-tile, S below one sublane tile
        (2048, 1000, 130),  # S off the 8-sublane grid, k over one chunk
    ],
)
def test_scatter_rows_matches_segment_sum(rng, k, s, m):
    A = _rand(rng, (k, m))
    b = jnp.asarray(rng.integers(0, s, k), jnp.int32)
    v = _rand(rng, k)
    out = pallas_window.scatter_rows(A, b, v, s, interpret=True)
    ref = jax.ops.segment_sum(v[:, None] * A, b, num_segments=s)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_scatter_rows_hot_bucket(rng):
    """Every entry lands in one bucket — the scalar loop's worst-case
    RMW chain must still sum exactly in entry order."""
    k, s, m = 300, 16, 24
    A = _rand(rng, (k, m))
    v = _rand(rng, k)
    b = jnp.full((k,), 11, jnp.int32)
    out = pallas_window.scatter_rows(A, b, v, s, interpret=True)
    ref = jax.ops.segment_sum(v[:, None] * A, b, num_segments=s)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )
    assert np.all(np.asarray(out[:11]) == 0) and np.all(
        np.asarray(out[12:]) == 0
    )


def test_scatter_rows_acc_fold_bitwise(rng):
    """The fused emit (acc + scratch inside the kernel) must be BITWISE
    the unfused composite — this is the whole basis of the fused
    stream-chunk path's planned≡eager contract."""
    k, s, m = 500, 40, 36
    A = _rand(rng, (k, m))
    b = jnp.asarray(rng.integers(0, s, k), jnp.int32)
    v = _rand(rng, k)
    acc = _rand(rng, (s, m))
    part = pallas_window.scatter_rows(A, b, v, s, interpret=True)
    fused = pallas_window.scatter_rows(A, b, v, s, acc=acc, interpret=True)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(acc + part))


def test_scatter_rows_bf16_input(rng):
    """bf16 operand, f32 accumulate: the cast in is exact, so the result
    matches the f32 reference of the upcast operand."""
    k, s, m = 320, 17, 40
    A = _rand(rng, (k, m), jnp.bfloat16)
    b = jnp.asarray(rng.integers(0, s, k), jnp.int32)
    v = _rand(rng, k)
    out = pallas_window.scatter_rows(A, b, v, s, interpret=True)
    ref = jax.ops.segment_sum(
        v[:, None] * A.astype(jnp.float32), b, num_segments=s
    )
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_scatter_rows_rejects_non_f32_acc(rng):
    A = _rand(rng, (8, 4))
    b = jnp.zeros((8,), jnp.int32)
    v = _rand(rng, 8)
    acc = jnp.zeros((4, 4), jnp.float64)
    with pytest.raises(TypeError, match="float32"):
        pallas_window.scatter_rows(A, b, v, 4, acc=acc, interpret=True)


def test_window_self_check_interpret():
    assert pallas_window.self_check(2048, 257, 96, interpret=True) < 1e-5


# ---------------------------------------------------------------------------
# stacked multi-hash scatter (SJLT, nnz > 1) — ISSUE 11
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("nnz", [2, 3, 4])
def test_scatter_rows_stacked_matches_segment_sum(rng, nnz):
    k, s, m = 500, 40, 36
    A = _rand(rng, (k, m))
    b = jnp.asarray(rng.integers(0, s, (nnz, k)), jnp.int32)
    v = _rand(rng, (nnz, k))
    out = pallas_window.scatter_rows(A, b, v, s, interpret=True)
    ref = jax.ops.segment_sum(
        (v[:, :, None] * A[None, :, :]).reshape(-1, m),
        b.reshape(-1),
        num_segments=s,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_scatter_rows_stacked_nnz1_degenerates(rng):
    """A (1, k) stacked call is the SAME layout as the 1-D call — bitwise,
    not just numerically (the nnz=1 fast path must not fork)."""
    k, s, m = 257, 24, 17
    A = _rand(rng, (k, m))
    b = jnp.asarray(rng.integers(0, s, k), jnp.int32)
    v = _rand(rng, k)
    flat = pallas_window.scatter_rows(A, b, v, s, interpret=True)
    stacked = pallas_window.scatter_rows(A, b[None, :], v[None, :], s,
                                         interpret=True)
    np.testing.assert_array_equal(np.asarray(flat), np.asarray(stacked))


def test_scatter_rows_stacked_acc_fold_bitwise(rng):
    """The fused emit holds for nnz > 1 too: acc + part in one launch is
    bitwise acc + part in two."""
    k, s, m, nnz = 300, 16, 24, 3
    A = _rand(rng, (k, m))
    b = jnp.asarray(rng.integers(0, s, (nnz, k)), jnp.int32)
    v = _rand(rng, (nnz, k))
    acc = _rand(rng, (s, m))
    part = pallas_window.scatter_rows(A, b, v, s, interpret=True)
    fused = pallas_window.scatter_rows(A, b, v, s, acc=acc, interpret=True)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(acc + part))


def test_scatter_rows_stacked_shape_mismatch_rejected(rng):
    A = _rand(rng, (8, 4))
    b = jnp.zeros((2, 8), jnp.int32)
    v = _rand(rng, (3, 8))
    with pytest.raises(ValueError, match="shape"):
        pallas_window.scatter_rows(A, b, v, 4, interpret=True)


def test_stacked_self_check_interpret():
    assert pallas_window.self_check(1000, 96, 40, nnz=3, interpret=True) < 1e-5


def test_sjlt_kernel_path_matches_xla_path(rng, window_interpret):
    """SJLT (nnz=4) through the stacked single-launch kernel agrees with
    the XLA per-hash fold (different kernels — tolerance, not bits)."""
    S = SJLT(N, S_OUT, SketchContext(seed=5))
    A = _rand(rng, (N, M))
    kern = S.apply_slice(A[:7], 0)
    os.environ["SKYLARK_PALLAS_WINDOW"] = "0"
    xla = S.apply_slice(A[:7], 0)
    scale = float(jnp.max(jnp.abs(xla))) or 1.0
    np.testing.assert_allclose(
        np.asarray(kern), np.asarray(xla), rtol=1e-5, atol=1e-5 * scale
    )


def test_rowwise_kernel_path_matches_xla_path(rng, window_interpret):
    """ROWWISE dense apply normalizes to the sublane scatter by one
    transpose; kernel vs XLA on the same sketch, tolerance not bits."""
    S = _hash(CWT)
    A = _rand(rng, (9, N))
    kern = S.apply(A, "rowwise")
    os.environ["SKYLARK_PALLAS_WINDOW"] = "0"
    xla = S.apply(A, "rowwise")
    scale = float(jnp.max(jnp.abs(xla))) or 1.0
    np.testing.assert_allclose(
        np.asarray(kern), np.asarray(xla), rtol=1e-5, atol=1e-5 * scale
    )


# ---------------------------------------------------------------------------
# FJLT sampled-transform gather epilogue — ISSUE 11
# ---------------------------------------------------------------------------


def test_gather_scaled_rows_bitwise_xla(rng):
    """The gather kernel is pure row selection + one elementwise multiply
    in the same dtype — bitwise EQUAL to the XLA take, by contract."""
    nrows, s, m = 600, 48, 36
    T = _rand(rng, (nrows, m))
    idx = jnp.asarray(rng.integers(0, nrows, s), jnp.int32)
    scale = jnp.float32(0.3125)
    out = pallas_window.gather_scaled_rows(T, idx, scale, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(scale * T[idx, :])
    )


def test_gather_self_check_interpret():
    assert pallas_window.self_check_gather(interpret=True) == 0.0


def test_gather_gates():
    # (R_pad * TM) must fit the VMEM budget: 2000*384 does, a
    # million-row source does not
    assert pallas_window.supported_gather(2000, 512, 320)
    assert not pallas_window.supported_gather(1_000_000, 512, 320)
    assert pallas_window.worthwhile_gather(2000, 4096, 320)
    assert not pallas_window.worthwhile_gather(2000, 8, 320)


def test_fjlt_gather_epilogue_bitwise_xla(rng, monkeypatch):
    """FJLT's sampled-transform epilogue through the gather kernel must
    be bitwise the XLA sampling of the same transform output."""
    from libskylark_tpu.sketch import fjlt as fjlt_mod

    n, s, m = 64, 24, 7
    A = _rand(rng, (n, m))
    monkeypatch.setenv("SKYLARK_NO_SRHT_GEMM", "1")
    monkeypatch.setenv("SKYLARK_PALLAS_GATHER", "0")
    S = fjlt_mod.FJLT(n, s, SketchContext(seed=9))
    xla = S.apply(A, "columnwise")
    monkeypatch.setenv("SKYLARK_PALLAS_GATHER", "interpret")
    S2 = fjlt_mod.FJLT(n, s, SketchContext(seed=9))
    kern = S2.apply(A, "columnwise")
    np.testing.assert_array_equal(np.asarray(kern), np.asarray(xla))


# ---------------------------------------------------------------------------
# dispatcher routing (static decisions only)
# ---------------------------------------------------------------------------


def test_window_mode_defaults_to_xla_off_tpu():
    if jax.default_backend() == "tpu":
        pytest.skip("TPU default routing is probed on hardware")
    assert _window_mode(1000, 64, 128, jnp.float32) == "xla"


def test_window_mode_forced_and_disabled(window_interpret):
    assert _window_mode(1000, 64, 128, jnp.float32) == "interpret"
    assert _window_mode(1000, 64, 128, jnp.bfloat16) == "interpret"
    # f64 demotes ONLY under a forced mode
    assert _window_mode(1000, 64, 128, jnp.float64) == "interpret"
    os.environ["SKYLARK_PALLAS_WINDOW"] = "0"
    assert _window_mode(1000, 64, 128, jnp.float32) == "xla"
    os.environ["SKYLARK_PALLAS_WINDOW"] = ""
    assert _window_mode(1000, 64, 128, jnp.float64) == "xla"
    os.environ["SKYLARK_NO_PALLAS"] = "1"
    try:
        os.environ["SKYLARK_PALLAS_WINDOW"] = "interpret"
        assert _window_mode(1000, 64, 128, jnp.float32) == "xla"
    finally:
        del os.environ["SKYLARK_NO_PALLAS"]


def test_f32_accumulable_gate():
    assert f32_accumulable(jnp.float32)
    assert f32_accumulable(jnp.bfloat16)
    assert f32_accumulable(jnp.float16)
    assert not f32_accumulable(jnp.float64)
    assert f32_accumulable(jnp.float64, demote_f64=True)
    assert not f32_accumulable(jnp.int32)


def test_segment_sum_rows_oversized_falls_back(window_interpret):
    """A sketch dimension past the VMEM gate must route to XLA even
    under a forced mode — forced honors `supported`, not `worthwhile`."""
    big_s = 5_000_000
    assert not pallas_window.supported(100, big_s, 128)
    assert _window_mode(100, 128, big_s, jnp.float32) == "xla"


# ---------------------------------------------------------------------------
# bf16/f64-tolerant flat-kernel entry (pallas_scatter)
# ---------------------------------------------------------------------------


def test_flat_entry_bf16(rng):
    nnz, s = 4 * pallas_scatter._C, 1024
    vals = _rand(rng, nnz, jnp.bfloat16)
    keys = jnp.asarray(rng.integers(0, s, nnz), jnp.int32)
    out = pallas_scatter.segment_sum_flat(vals, keys, s, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = jax.ops.segment_sum(
        vals.astype(jnp.float32), keys, num_segments=s
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=2e-2, atol=1e-2
    )


def test_flat_entry_f64(rng):
    nnz, s = 4 * pallas_scatter._C, 1024
    vals = _rand(rng, nnz, jnp.float64)
    keys = jnp.asarray(rng.integers(0, s, nnz), jnp.int32)
    out = pallas_scatter.segment_sum_flat(vals, keys, s, interpret=True)
    assert out.dtype == jnp.float64
    ref = jax.ops.segment_sum(vals, keys, num_segments=s)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


# ---------------------------------------------------------------------------
# hash dispatcher: eager / kernel-path / planned-fused parity
# ---------------------------------------------------------------------------

N, S_OUT, M = 40, 12, 5
RAGGED = (7, 7, 7, 7, 7, 5)  # covers N with a ragged tail


def _hash(cls, seed=5):
    return cls(N, S_OUT, SketchContext(seed=seed))


@pytest.mark.parametrize("cls", [CWT, MMT, WZT])
def test_slice_kernel_matches_eager_dispatch(rng, cls, window_interpret):
    """apply_slice (eager, concrete start) and apply_slice_kernel
    (traced-start form) route through the same dispatcher mode, so on
    in-domain windows they are bitwise identical."""
    S = _hash(cls)
    A = _rand(rng, (N, M))
    start = 7
    blk = A[start : start + 7]
    eager = S.apply_slice(blk, start)
    kern = S.apply_slice_kernel(blk, jnp.asarray(start, jnp.int32))
    np.testing.assert_array_equal(np.asarray(eager), np.asarray(kern))


@pytest.mark.parametrize("cls", [CWT, MMT, WZT])
def test_kernel_path_matches_xla_path(rng, cls, window_interpret):
    """The interpret-kernel slice must agree numerically with the XLA
    slice of the same window (different kernels — tolerance, not bits)."""
    S = _hash(cls)
    A = _rand(rng, (N, M))
    kern = S.apply_slice(A[:7], 0)
    os.environ["SKYLARK_PALLAS_WINDOW"] = "0"
    xla = S.apply_slice(A[:7], 0)
    scale = float(jnp.max(jnp.abs(xla))) or 1.0
    np.testing.assert_allclose(
        np.asarray(kern), np.asarray(xla), rtol=1e-5, atol=1e-5 * scale
    )


@pytest.mark.parametrize("cls", [CWT, MMT, WZT, SJLT])
def test_planned_fused_bitwise_eager_ragged(rng, cls, window_interpret):
    """THE fused-chunk contract: planned-fused accumulation over ragged
    batches is bitwise the eager composite fold (CWT/MMT/WZT take the
    single-launch fused kernel; SJLT nnz=4 rides the SAME launch with
    its hashes stacked on the sublane grid — ISSUE 11)."""
    S = _hash(cls)
    A = _rand(rng, (N, M))
    acc_e = jnp.zeros((S_OUT, M), jnp.float32)
    acc_p = jnp.zeros((S_OUT, M), jnp.float32)
    start = 0
    for k in RAGGED:
        blk = A[start : start + k]
        acc_e = acc_e + S.apply_slice(blk, start).astype(jnp.float32)
        acc_p = plans.accumulate_slice(S, acc_p, blk, start)
        start += k
    np.testing.assert_array_equal(np.asarray(acc_e), np.asarray(acc_p))
    # and the fold still matches the one-shot apply numerically
    np.testing.assert_allclose(
        np.asarray(acc_p), np.asarray(S.apply(A)), rtol=1e-4, atol=1e-4
    )


@pytest.mark.parametrize("cls", [CWT, WZT])
def test_fused_vs_unfused_plans_bitwise(rng, cls, window_interpret):
    """SKYLARK_NO_FUSED_CHUNKS / fused=False is a pure kill switch: the
    two-step composite plan produces the same bits as the fused plan."""
    S = _hash(cls)
    A = _rand(rng, (N, M))
    accs = {True: jnp.zeros((S_OUT, M), jnp.float32),
            False: jnp.zeros((S_OUT, M), jnp.float32)}
    start = 0
    for k in RAGGED:
        blk = A[start : start + k]
        for fused in (True, False):
            accs[fused] = plans.accumulate_slice(
                S, accs[fused], blk, start, fused=fused
            )
        start += k
    np.testing.assert_array_equal(
        np.asarray(accs[True]), np.asarray(accs[False])
    )


def test_default_path_unchanged_without_env(rng):
    """With no forcing env, CPU routing stays XLA end to end — the
    planned≡eager contract of the pre-kernel code must be untouched."""
    assert _window_mode(7, M, S_OUT, jnp.float64) == "xla"
    S = _hash(CWT)
    A = jnp.asarray(rng.standard_normal((N, M)))  # f64 under x64
    acc_e = jnp.zeros((S_OUT, M), A.dtype)
    acc_p = jnp.zeros((S_OUT, M), A.dtype)
    start = 0
    for k in RAGGED:
        blk = A[start : start + k]
        acc_e = acc_e + S.apply_slice(blk, start)
        acc_p = plans.accumulate_slice(S, acc_p, blk, start)
        start += k
    np.testing.assert_array_equal(np.asarray(acc_e), np.asarray(acc_p))


# ---------------------------------------------------------------------------
# fused chunks through the streaming drivers + guard replay
# ---------------------------------------------------------------------------


def _ls_stream_factory(A, b, nbatches):
    rows = A.shape[0] // nbatches

    def factory(start):
        return iter(
            [
                (
                    jnp.asarray(A[i * rows : (i + 1) * rows], jnp.float32),
                    jnp.asarray(b[i * rows : (i + 1) * rows], jnp.float32),
                )
                for i in range(start, nbatches)
            ]
        )

    return factory


@pytest.mark.guard
def test_guard_replay_through_fused_kernel_bit_identical(
    rng, window_interpret
):
    """Sentinel replay of a poisoned batch through the FUSED stream-
    chunk kernel stays bit-identical to the clean pass (satellite 4):
    CWT + f32 accumulators, so the single-launch fused path serves both
    the original fold and the guard's replay."""
    m, n, nb = 240, 6, 8
    A = rng.normal(size=(m, n))
    b = A @ rng.normal(size=n) + 1e-3 * rng.normal(size=m)
    factory = _ls_stream_factory(A, b, nb)

    def run(fault_plan=None):
        S = CWT(m, 4 * n, SketchContext(seed=3))
        return streaming.sketch_least_squares(
            factory, S, ncols=n, dtype=jnp.float32, fault_plan=fault_plan
        )

    x0, info0 = run()
    assert info0["recovery"]["recovered"] is False
    x1, info1 = run(FaultPlan(nan_at=3))
    rec = info1["recovery"]
    assert rec["recovered"] is True
    assert any(a["action"] == "replay" for a in rec["attempts"])
    np.testing.assert_array_equal(np.asarray(x0), np.asarray(x1))


@pytest.mark.streaming
def test_stream_params_fused_kill_switch(rng, window_interpret):
    """StreamParams(fused_chunks=False) threads through the drivers and
    produces the same bits as the fused default."""
    from libskylark_tpu import streaming

    S = _hash(CWT)
    A = rng.standard_normal((N, M)).astype(np.float32)

    def run(fused):
        blocks = [
            jnp.asarray(A[lo : lo + 7]) for lo in range(0, N, 7)
        ]
        return streaming.sketch(
            lambda start: iter(blocks[start:]), S, ncols=M,
            dtype=jnp.float32,
            params=StreamParams(fused_chunks=fused),
        )

    np.testing.assert_array_equal(
        np.asarray(run(True)), np.asarray(run(False))
    )
