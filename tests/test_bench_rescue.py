"""Subprocess regression tests for bench.py's backend-rescue chain.

The contract under test (BASELINE.md integrity notes): on a host with a
healthy CPU, a broken accelerator backend must never cost the round its
artifact — the bench drops to host CPU (tagged ``"backend":
"cpu-fallback"``), the headline is a REAL measurement, and no row in the
final submetrics table is a ``-1`` error row.  The chain has two rungs:

1. in-process rescue — re-point jax at CPU and clear the cached init
   failure (``_cpu_attempts``);
2. re-exec rescue — when the in-process rescue cannot purge poisoned
   plugin-registry state, replace the interpreter with a fresh
   ``JAX_PLATFORMS=cpu`` one via ``execvpe`` (budget and loop guard
   carried in env).  ``SKYLARK_BENCH_SIM_POISON=1`` suppresses rung 1 so
   a test can drive rung 2 without a genuinely broken plugin install.

Both tests run the real bench.py in smoke mode (tiny dims) with the
config filter set to a non-matching string, so only the headline
measures and everything else emits ``skipped: filter`` rows.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.faults

_BENCH = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")


def _run_bench(extra_env, timeout=110):
    env = dict(os.environ)
    env.pop("SKYLARK_BENCH_CPU_REEXEC", None)  # never inherit the loop guard
    env.update(
        JAX_PLATFORMS="bogus",  # accelerator init fails deterministically
        SKYLARK_BENCH_SMOKE="1",
        SKYLARK_BENCH_ONLY="zzz-match-nothing",
        SKYLARK_BENCH_BUDGET_S="600",
    )
    # second update so extra_env may OVERRIDE the defaults (a duplicate
    # keyword in one update() call is a TypeError)
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, _BENCH],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
    )


def _parse_rows(stdout):
    rows = [json.loads(line) for line in stdout.splitlines() if line.strip()]
    assert rows, f"bench produced no stdout rows:\n{stdout}"
    return rows


def _assert_healthy_artifact(out):
    assert out.returncode == 0, (
        f"bench exited {out.returncode}\nstdout:\n{out.stdout}"
        f"\nstderr:\n{out.stderr}"
    )
    rows = _parse_rows(out.stdout)
    final = rows[-1]
    # the LAST line is the headline + full submetrics table
    assert "submetrics" in final, f"final line is not the artifact: {final}"
    assert final["unit"] != "error" and final["value"] != -1, (
        f"headline is a FAILED row despite a healthy CPU: {final['metric']}"
    )
    assert final.get("backend") == "cpu-fallback", (
        "fallback rows must self-identify so the driver never compares "
        f"them against TPU baselines: {final}"
    )
    for row in final["submetrics"]:
        assert row["unit"] != "error", f"-1 error row in artifact: {row}"
        if row["value"] == -1:
            # the only legitimate -1 rows are explicit filter skips
            assert row["unit"] == "skipped", f"-1 row not a skip: {row}"
    return final


def test_broken_backend_falls_back_in_process_no_error_rows():
    """Rung 1: JAX_PLATFORMS=bogus, healthy CPU -> in-process rescue.

    The artifact must be complete and real (no -1 rows) without any
    re-exec: the in-process CPU attempts succeed on a healthy host.
    """
    out = _run_bench({})
    _assert_healthy_artifact(out)
    assert "backend fallback re-exec" not in out.stderr, (
        "in-process rescue should succeed without escalating to execvpe"
    )
    assert "backend fallback" in out.stderr  # the rung-1 stderr marker


def test_poisoned_rescue_escalates_to_cpu_reexec():
    """Rung 2: sim-poison suppresses the in-process rescue, forcing the
    execvpe re-exec.  The re-exec'd interpreter must still deliver the
    full artifact (loop guard seeds the cpu-fallback tag across exec).
    """
    out = _run_bench({"SKYLARK_BENCH_SIM_POISON": "1"})
    assert "backend fallback re-exec" in out.stderr, (
        f"expected the execvpe escalation marker on stderr:\n{out.stderr}"
    )
    _assert_healthy_artifact(out)


def test_init_fail_on_healthy_cpu_rescued_in_process():
    """``SKYLARK_BENCH_SIM_INIT_FAIL`` suppresses backend init even with
    ``JAX_PLATFORMS=cpu``: on a healthy host rung 1 must still deliver
    the full artifact without escalating — the init-exhaustion path and
    the in-process CPU rescue are independent."""
    out = _run_bench(
        {"JAX_PLATFORMS": "cpu", "SKYLARK_BENCH_SIM_INIT_FAIL": "1"}
    )
    _assert_healthy_artifact(out)
    assert "backend fallback re-exec" not in out.stderr


def test_init_exhaustion_reexecs_even_when_already_on_cpu():
    """Regression (review BENCH_r05): ``_cpu_fallback`` used to skip the
    re-exec rescue when the configured platform was ALREADY ``cpu``,
    reasoning a CPU re-exec could not do better — but an init failure
    whose cache an in-process ``clear_backends()`` cannot purge
    (simulated by SIM_INIT_FAIL + SIM_POISON, both ignored by the
    re-exec'd child via the loop-guard env) is exactly the case a fresh
    interpreter fixes.  The rescue must be unconditional: healthy host,
    no -1 rows, artifact delivered by the re-exec'd process."""
    out = _run_bench(
        {
            "JAX_PLATFORMS": "cpu",
            "SKYLARK_BENCH_SIM_INIT_FAIL": "1",
            "SKYLARK_BENCH_SIM_POISON": "1",
        }
    )
    assert "backend fallback re-exec" in out.stderr, (
        f"expected the execvpe escalation marker on stderr:\n{out.stderr}"
    )
    _assert_healthy_artifact(out)
