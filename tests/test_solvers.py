"""Solver tests: Krylov methods, Blendenpik/LSRN, cond_est, block GS, prox.

Patterned on the reference's solver usage (LSQR inside Blendenpik reaching
near machine precision; CG on SPD systems) and on standard prox identities.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import SketchContext
from libskylark_tpu.solvers import (
    FasterLeastSquaresParams,
    KrylovParams,
    MatPrecond,
    cg,
    chebyshev,
    cond_est,
    faster_least_squares,
    flexible_cg,
    get_loss,
    get_regularizer,
    lsqr,
    lsrn_least_squares,
    randomized_block_gauss_seidel,
)


def spd(rng, n, cond=100.0):
    Q = np.linalg.qr(rng.standard_normal((n, n)))[0]
    lam = np.logspace(0, -np.log10(cond), n)
    return jnp.asarray(Q @ np.diag(lam) @ Q.T)


class TestLSQR:
    @pytest.mark.slow
    def test_well_conditioned(self, rng):
        A = jnp.asarray(rng.standard_normal((200, 30)))
        b = jnp.asarray(rng.standard_normal(200))
        x, info = lsqr(A, b, params=KrylovParams(iter_lim=200))
        x_ref = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
        np.testing.assert_allclose(np.asarray(x), x_ref, rtol=1e-6, atol=1e-8)

    @pytest.mark.slow
    def test_multi_rhs(self, rng):
        A = jnp.asarray(rng.standard_normal((150, 20)))
        B = jnp.asarray(rng.standard_normal((150, 4)))
        X, info = lsqr(A, B, params=KrylovParams(iter_lim=200))
        X_ref = np.linalg.lstsq(np.asarray(A), np.asarray(B), rcond=None)[0]
        np.testing.assert_allclose(np.asarray(X), X_ref, rtol=1e-6, atol=1e-8)

    def test_square_consistent(self, rng):
        A = jnp.asarray(spd(rng, 40, cond=10))
        x_true = rng.standard_normal(40)
        b = A @ x_true
        x, info = lsqr(A, b, params=KrylovParams(iter_lim=300, tolerance=1e-12))
        np.testing.assert_allclose(np.asarray(x), x_true, rtol=1e-5, atol=1e-7)

    def test_jittable(self, rng):
        A = jnp.asarray(rng.standard_normal((100, 10)))
        b = jnp.asarray(rng.standard_normal(100))
        x, _ = jax.jit(lambda A, b: lsqr(A, b))(A, b)
        assert np.all(np.isfinite(np.asarray(x)))


class TestCG:
    def test_spd_solve(self, rng):
        A = spd(rng, 60, cond=50)
        b = jnp.asarray(rng.standard_normal(60))
        x, info = cg(A, b, params=KrylovParams(iter_lim=300, tolerance=1e-12))
        np.testing.assert_allclose(
            np.asarray(A @ x), np.asarray(b), rtol=1e-6, atol=1e-8
        )

    def test_preconditioned_faster(self, rng):
        A = spd(rng, 80, cond=1e4)
        b = jnp.asarray(rng.standard_normal((80, 2)))
        M = MatPrecond(jnp.linalg.inv(A))  # perfect preconditioner
        _, info_pre = cg(A, b, precond=M, params=KrylovParams(iter_lim=100, tolerance=1e-10))
        _, info_no = cg(A, b, params=KrylovParams(iter_lim=100, tolerance=1e-10))
        assert int(info_pre["iterations"]) < int(info_no["iterations"])


class TestFlexibleCG:
    def test_spd_solve(self, rng):
        A = spd(rng, 50, cond=100)
        b = jnp.asarray(rng.standard_normal(50))
        x, info = flexible_cg(
            A, b, params=KrylovParams(iter_lim=200, tolerance=1e-10)
        )
        np.testing.assert_allclose(
            np.asarray(A @ x), np.asarray(b), rtol=1e-5, atol=1e-7
        )

    def test_variable_preconditioner(self, rng):
        A = spd(rng, 40, cond=100)
        b = jnp.asarray(rng.standard_normal(40))
        D = jnp.diag(A)

        def precond(R, it):  # Jacobi, slightly perturbed per iteration
            return R / (D[:, None] * (1.0 + 1e-3 * jnp.cos(it.astype(R.dtype))))

        x, info = flexible_cg(
            A, b, precond=precond, params=KrylovParams(iter_lim=200, tolerance=1e-10)
        )
        np.testing.assert_allclose(
            np.asarray(A @ x), np.asarray(b), rtol=1e-5, atol=1e-7
        )


class TestChebyshev:
    def test_spd_with_bounds(self, rng):
        A = spd(rng, 50, cond=20)
        lam = np.linalg.eigvalsh(np.asarray(A))
        b = jnp.asarray(rng.standard_normal(50))
        x, _ = chebyshev(
            A, b, float(lam[0]) * 0.9, float(lam[-1]) * 1.1,
            params=KrylovParams(iter_lim=300),
        )
        np.testing.assert_allclose(
            np.asarray(A @ x), np.asarray(b), rtol=1e-4, atol=1e-5
        )


class TestBlendenpik:
    def test_near_machine_precision(self, rng):
        A = jnp.asarray(rng.standard_normal((3000, 50)))
        b = jnp.asarray(rng.standard_normal(3000))
        x, info = faster_least_squares(A, b, SketchContext(seed=11))
        x_ref = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
        np.testing.assert_allclose(np.asarray(x), x_ref, rtol=1e-8, atol=1e-10)
        assert info["attempts"] == 1

    def test_ill_conditioned(self, rng):
        # cond ~1e6 — the preconditioner should still crack it.
        U = np.linalg.qr(rng.standard_normal((1000, 30)))[0]
        V = np.linalg.qr(rng.standard_normal((30, 30)))[0]
        A = jnp.asarray(U @ np.diag(np.logspace(0, -6, 30)) @ V)
        x_true = rng.standard_normal(30)
        b = A @ jnp.asarray(x_true)
        x, _ = faster_least_squares(
            A, b, SketchContext(seed=12),
            FasterLeastSquaresParams(krylov=KrylovParams(iter_lim=100)),
        )
        r = np.linalg.norm(np.asarray(A @ x) - np.asarray(b))
        assert r <= 1e-6 * np.linalg.norm(np.asarray(b))

    def test_multi_rhs(self, rng):
        A = jnp.asarray(rng.standard_normal((800, 20)))
        B = jnp.asarray(rng.standard_normal((800, 3)))
        X, _ = faster_least_squares(A, B, SketchContext(seed=13))
        X_ref = np.linalg.lstsq(np.asarray(A), np.asarray(B), rcond=None)[0]
        np.testing.assert_allclose(np.asarray(X), X_ref, rtol=1e-7, atol=1e-9)


class TestLSRN:
    def test_rank_deficient(self, rng):
        # LSRN handles rank deficiency; returns min-norm-ish solution.
        base = rng.standard_normal((500, 10))
        A = jnp.asarray(np.hstack([base, base[:, :5]]))  # rank 10, 15 cols
        b = jnp.asarray(rng.standard_normal(500))
        x, _ = lsrn_least_squares(A, b, SketchContext(seed=14))
        r = np.linalg.norm(np.asarray(A @ x) - np.asarray(b))
        x_ref = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
        r_ref = np.linalg.norm(np.asarray(A) @ x_ref - np.asarray(b))
        assert r <= r_ref * (1 + 1e-5)


class TestCondEst:
    def _spectrum_matrix(self, rng, m, n, s):
        U = np.linalg.qr(rng.standard_normal((m, n)))[0]
        V = np.linalg.qr(rng.standard_normal((n, n)))[0]
        return jnp.asarray(U @ np.diag(s) @ V)

    def test_known_condition(self, rng):
        s = np.logspace(0, -3, 20)
        A = self._spectrum_matrix(rng, 400, 20, s)
        r = cond_est(A, SketchContext(seed=21))
        cond, smax, smin = r.cond, r.sigma_max, r.sigma_min
        assert abs(float(smax) - 1.0) < 0.05
        assert abs(float(smin) - 1e-3) / 1e-3 < 0.2
        assert abs(float(cond) - 1e3) / 1e3 < 0.25

    def test_certificates(self, rng):
        """The certificate contract of CondEst.hpp:55-63: unit vectors with
        A v_max ≈ σ_max u_max and A v_min ≈ σ_min_c u_min."""
        s = np.logspace(0, -2, 30)
        A = self._spectrum_matrix(rng, 300, 30, s)
        r = cond_est(A, SketchContext(seed=5))
        for v in (r.u_max, r.v_max, r.u_min, r.v_min):
            assert abs(float(jnp.linalg.norm(v)) - 1.0) < 1e-4
        res_max = float(
            jnp.linalg.norm(A @ r.v_max - r.sigma_max * r.u_max)
        )
        assert res_max < 1e-3 * float(r.sigma_max)
        res_min = float(
            jnp.linalg.norm(A @ r.v_min - r.sigma_min_c * r.u_min)
        )
        # v_min certifies sigma_min_c exactly by construction.
        assert res_min < 1e-4 * float(r.sigma_max)
        # Certified estimate upper-bounds the best estimate, and both
        # bracket the true sigma_min from above.
        assert float(r.sigma_min_c) >= float(r.sigma_min) - 1e-7
        assert float(r.sigma_min) >= 1e-2 * (1 - 0.05)

    def test_identity_flags_cond_one(self, rng):
        A = jnp.eye(50)
        r = cond_est(A, SketchContext(seed=3))
        assert float(r.cond) < 1.0 + 1e-3
        # Either the cond-1 early exit (-1) or C1/C2 convergence fired.
        assert int(r.flag) in (-1, -2, -3)

    def test_flag_convergence(self, rng):
        s = np.logspace(0, -1, 10)
        A = self._spectrum_matrix(rng, 200, 10, s)
        r = cond_est(A, SketchContext(seed=9))
        assert int(r.flag) in (-1, -2, -3)  # converged, not -6

    def test_blendenpik_precond_is_certified_wellconditioned(self, rng):
        """Wiring check: Blendenpik's R-preconditioned operator A·R⁻¹ has
        CondEst-certified condition ≈ 1 (the property the retry loop in
        accelerated_...Elemental.hpp:225-246 exists to guarantee)."""
        from libskylark_tpu.solvers.accelerated import _sketch_once
        from libskylark_tpu.sketch.base import Dimension

        A = jnp.asarray(rng.standard_normal((600, 15)))
        SA = _sketch_once(A, 60, "FJLT", SketchContext(seed=33))
        R = jnp.linalg.qr(SA, mode="r")
        import jax.scipy.linalg as jsl

        A_pre = jsl.solve_triangular(R.T, A.T, lower=True).T  # A R⁻¹
        r = cond_est(A_pre, SketchContext(seed=34))
        assert float(r.cond) < 3.0

    # -- adversarial inputs: estimates must come back as certificates, --
    # -- never as crashes or NaNs (guard layer depends on this)        --

    @pytest.mark.guard
    def test_rank_deficient_certifies_not_crashes(self, rng):
        """Exactly rank-deficient A: xhat has a null-space component LSQR
        can never resolve, so the certified σ_min collapses toward 0 —
        the result must stay finite with a huge cond (or the -4 singular
        flag), not NaN-poison downstream guards."""
        B = rng.standard_normal((120, 6))
        A = jnp.asarray(np.concatenate([B, B], axis=1))  # rank 6 of 12
        r = cond_est(A, SketchContext(seed=41))
        for field in r:
            assert np.isfinite(np.asarray(field)).all()
        assert float(r.cond) > 1e6 or int(r.flag) == -4
        # certificates still honor the contract A v ≈ σ u
        res_min = float(
            jnp.linalg.norm(A @ r.v_min - r.sigma_min_c * r.u_min)
        )
        assert res_min < 1e-4 * float(r.sigma_max)

    @pytest.mark.guard
    def test_orthogonal_cond_one_early_exit(self, rng):
        """cond(Q) = 1 exactly: the sweep must terminate via an early-exit
        flag (cond≈1 / C1 / C2), reporting cond ≈ 1 — not run to the -6
        iteration limit."""
        Q = jnp.asarray(np.linalg.qr(rng.standard_normal((80, 16)))[0])
        r = cond_est(Q, SketchContext(seed=43))
        assert float(r.cond) < 1.2
        assert int(r.flag) in (-1, -2, -3)

    @pytest.mark.guard
    def test_power_iteration_zero_start_vector(self, rng):
        """A zero v0 must fall back to a uniform start inside
        _power_sigma_max and still certify the dominant triplet — the
        unguarded 0/0 normalization would NaN every downstream field."""
        from libskylark_tpu.solvers.cond_est import _power_sigma_max

        A = jnp.asarray(rng.standard_normal((60, 8)))
        sigma, u, v = _power_sigma_max(
            lambda x: A @ x, lambda y: A.T @ y, jnp.zeros(8), 100
        )
        for field in (sigma, u, v):
            assert np.isfinite(np.asarray(field)).all()
        want = float(jnp.linalg.norm(A, ord=2))
        assert abs(float(sigma) - want) < 1e-6 * want
        assert float(jnp.linalg.norm(A @ v - sigma * u)) < 1e-8 * want

    @pytest.mark.guard
    def test_power_iteration_near_zero_start_vector(self, rng):
        """A denormal-scale v0 normalizes through the guard unchanged."""
        from libskylark_tpu.solvers.cond_est import _power_sigma_max

        A = jnp.asarray(rng.standard_normal((60, 8)))
        v0 = jnp.asarray(rng.standard_normal(8)) * 1e-300
        sigma, u, v = _power_sigma_max(
            lambda x: A @ x, lambda y: A.T @ y, v0, 100
        )
        assert np.isfinite(np.asarray(sigma)) and float(sigma) > 0
        assert abs(float(jnp.linalg.norm(v)) - 1.0) < 1e-8


class TestBlockGaussSeidel:
    @pytest.mark.slow
    def test_spd_converges(self, rng):
        A = spd(rng, 100, cond=50) + 0.5 * jnp.eye(100)
        x_true = rng.standard_normal(100)
        b = A @ jnp.asarray(x_true)
        x, info = randomized_block_gauss_seidel(
            A, b, SketchContext(seed=31), block_size=16, sweeps=40
        )
        np.testing.assert_allclose(np.asarray(x), x_true, rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_deterministic_given_context(self, rng):
        A = spd(rng, 30) + jnp.eye(30)
        b = jnp.asarray(rng.standard_normal(30))
        x1, _ = randomized_block_gauss_seidel(A, b, SketchContext(seed=5), 8, 5)
        x2, _ = randomized_block_gauss_seidel(A, b, SketchContext(seed=5), 8, 5)
        np.testing.assert_array_equal(np.asarray(x1), np.asarray(x2))


class TestProx:
    def _check_prox_is_argmin(self, lossobj, V, lam, Y, rng):
        """prox must beat nearby points on lam*loss(X,Y) + 0.5||X-V||²."""
        X = lossobj.prox(V, lam, Y)
        obj = lambda Z: lam * lossobj.evaluate(Z, Y) + 0.5 * jnp.sum((Z - V) ** 2)
        base = float(obj(X))
        for _ in range(10):
            pert = X + 0.01 * jnp.asarray(rng.standard_normal(X.shape))
            assert float(obj(pert)) >= base - 1e-6 * max(1.0, abs(base))

    def test_squared_prox_closed_form(self, rng):
        V = jnp.asarray(rng.standard_normal((1, 20)))
        Y = jnp.asarray(rng.standard_normal((1, 20)))
        loss = get_loss("squared")
        X = loss.prox(V, 0.7, Y)
        np.testing.assert_allclose(
            np.asarray(X), (np.asarray(V) + 0.7 * np.asarray(Y)) / 1.7, rtol=1e-6
        )

    @pytest.mark.parametrize("name", ["squared", "lad", "hinge"])
    def test_prox_minimizes_binary(self, name, rng):
        V = jnp.asarray(rng.standard_normal((1, 25)))
        Y = jnp.asarray(np.sign(rng.standard_normal(25)))
        self._check_prox_is_argmin(get_loss(name), V, 0.5, Y, rng)

    @pytest.mark.slow
    def test_logistic_prox_minimizes_multiclass(self, rng):
        V = jnp.asarray(rng.standard_normal((4, 15)))
        Y = jnp.asarray(rng.integers(0, 4, 15))
        self._check_prox_is_argmin(get_loss("logistic"), V, 0.5, Y, rng)

    def test_hinge_evaluate_multiclass(self, rng):
        O = jnp.asarray(rng.standard_normal((3, 10)))
        Y = jnp.asarray(rng.integers(0, 3, 10))
        v = float(get_loss("hinge").evaluate(O, Y))
        assert v >= 0

    def test_regularizer_prox(self, rng):
        V = jnp.asarray(rng.standard_normal((5, 6)))
        np.testing.assert_allclose(
            np.asarray(get_regularizer("l2").prox(V, 1.0)), np.asarray(V) / 2
        )
        X1 = np.asarray(get_regularizer("l1").prox(V, 0.3))
        assert np.all(np.abs(X1) <= np.maximum(np.abs(np.asarray(V)) - 0.3, 0) + 1e-12)
        np.testing.assert_allclose(
            np.asarray(get_regularizer("none").prox(V, 2.0)), np.asarray(V)
        )


class TestAsyFcgSchedules:
    def test_per_iteration_schedules_differ(self, rng):
        """≙ AsyFCG's fresh randomized sweep per outer iteration
        (AsyFCG.hpp:8): the counter window shifts with the iteration
        index, so two iterations draw different GS schedules."""
        import jax.numpy as jnp

        from libskylark_tpu.core.random import sample
        from libskylark_tpu.solvers.gauss_seidel import gs_num_blocks

        n, bs, sweeps = 64, 16, 2
        nblocks = gs_num_blocks(n, bs)
        per_iter = sweeps * nblocks
        ctx = SketchContext(seed=77)
        base = ctx.reserve(10 * per_iter)
        u0 = sample("uniform", 77, base, per_iter, offset=jnp.uint32(0))
        u1 = sample(
            "uniform", 77, base, per_iter, offset=jnp.uint32(per_iter)
        )
        assert not np.array_equal(np.asarray(u0), np.asarray(u1))

    @pytest.mark.slow
    def test_converges_and_deterministic(self, rng):
        from libskylark_tpu.solvers.asynch import asy_fcg

        M = rng.standard_normal((60, 60))
        A = jnp.asarray(M @ M.T + 60 * np.eye(60))
        b = A @ jnp.asarray(rng.standard_normal(60))
        x1, info1 = asy_fcg(A, b, SketchContext(seed=13), block_size=16)
        x2, info2 = asy_fcg(A, b, SketchContext(seed=13), block_size=16)
        np.testing.assert_array_equal(np.asarray(x1), np.asarray(x2))
        assert float(jnp.linalg.norm(A @ x1 - b)) < 1e-3 * float(
            jnp.linalg.norm(b)
        )


class TestCondEstSparse:
    @pytest.mark.slow
    def test_bcoo_stays_sparse(self, rng):
        """cond_est takes BCOO without densifying (matvec-only, as the
        reference's template works on any multipliable type)."""
        from jax.experimental import sparse as jsparse

        D = rng.standard_normal((150, 20)) * (rng.random((150, 20)) < 0.1)
        Asp = jsparse.BCOO.fromdense(jnp.asarray(D))
        r = cond_est(Asp, SketchContext(seed=15))
        dense = np.asarray(Asp.todense())
        sv = np.linalg.svd(dense, compute_uv=False)
        sv = sv[sv > 1e-10]
        assert abs(float(r.sigma_max) - sv[0]) / sv[0] < 0.05
