"""Adaptive execution policy (ISSUE PR 9): profile store, routing
decisions, warm start, and the bit-parity contract.

All tests run under the ``policy`` marker (tier-1, 120 s per-test
alarm).  The load-bearing contract: with the layer disabled OR the
store empty, every solve is bitwise identical to the pre-policy
defaults; decisions are pure functions of (merged store view, problem
signature), so every process reading the same files decides the same.

``SketchContext`` is stateful — every comparison below constructs a
fresh same-seed context per call so bitwise equality is meaningful.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import plans, policy
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.linalg.least_squares import (
    approximate_least_squares,
    streaming_least_squares,
)
from libskylark_tpu.policy.decide import LS_ROUTES, ProblemSignature, choose_route
from libskylark_tpu.policy.profile import ProfileStore, load_entries
from libskylark_tpu.resilient import FaultPlan

pytestmark = pytest.mark.policy


@pytest.fixture
def policy_env(tmp_path, monkeypatch):
    """Clean policy world: enabled, guarded, fresh store dir, and no
    leakage of SKYLARK_POLICY* knobs between tests."""
    monkeypatch.setenv("SKYLARK_POLICY", "1")
    monkeypatch.setenv("SKYLARK_GUARD", "1")
    monkeypatch.setenv("SKYLARK_POLICY_MIN_SAMPLES", "3")
    monkeypatch.delenv("SKYLARK_POLICY_DIR", raising=False)
    monkeypatch.delenv("SKYLARK_POLICY_BF16", raising=False)
    store = str(tmp_path / "policy-store")
    policy.configure(store)
    policy.reset()
    policy.invalidate_cache()
    plans.clear()
    plans.reset_stats()
    yield store
    policy.configure(None)
    policy.reset()
    policy.invalidate_cache()


def _ls_problem(seed=5, m=240, n=8, dtype=np.float32):
    rng = np.random.default_rng(seed)
    A = rng.normal(size=(m, n)).astype(dtype)
    x_true = rng.normal(size=n).astype(dtype)
    b = (A @ x_true + 1e-3 * rng.normal(size=m)).astype(dtype)
    return jnp.asarray(A), jnp.asarray(b)


def _mature(A, b, runs=3, seed=7):
    """Run enough guarded solves to push the entry past min_samples.
    Every solve flushes through run_summary, so the store is on disk
    (and the merged-view cache invalidated) after each call."""
    for _ in range(runs):
        approximate_least_squares(A, b, SketchContext(seed=seed))


# ---------------------------------------------------------------------------
# bit-parity: empty store / disabled layer == historical defaults


def test_empty_store_is_bitwise_default(policy_env, monkeypatch):
    A, b = _ls_problem()
    monkeypatch.setenv("SKYLARK_POLICY", "0")
    x_off = np.asarray(approximate_least_squares(A, b, SketchContext(seed=7)))
    monkeypatch.setenv("SKYLARK_POLICY", "1")
    x_on, info = approximate_least_squares(
        A, b, SketchContext(seed=7), return_info=True
    )
    assert np.array_equal(x_off, np.asarray(x_on))
    assert info["policy"]["source"] == "default"
    assert info["policy"]["route"] == "sketch"


def test_empty_store_streaming_bit_parity(policy_env, monkeypatch):
    rng = np.random.default_rng(3)
    n, d, br = 512, 16, 128
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = rng.standard_normal(n).astype(np.float32)

    def batches(start):
        for i in range(start, n // br):
            yield X[i * br : (i + 1) * br], y[i * br : (i + 1) * br]

    monkeypatch.setenv("SKYLARK_POLICY", "0")
    x_off, _ = streaming_least_squares(batches, n, d, SketchContext(seed=9))
    monkeypatch.setenv("SKYLARK_POLICY", "1")
    x_on, info = streaming_least_squares(batches, n, d, SketchContext(seed=9))
    assert np.array_equal(np.asarray(x_off), np.asarray(x_on))
    assert info["policy"]["source"] == "default"


def test_immature_entry_stays_default(policy_env):
    """Below min_samples the profile must not influence decisions."""
    A, b = _ls_problem()
    _mature(A, b, runs=2)
    _, info = approximate_least_squares(
        A, b, SketchContext(seed=7), return_info=True
    )
    assert info["policy"]["source"] == "default"


# ---------------------------------------------------------------------------
# determinism: pure function of (store view, signature)


def test_decision_is_deterministic_across_processes(policy_env):
    A, b = _ls_problem()
    _mature(A, b, runs=4)
    view = load_entries(policy_env)
    sig = ProblemSignature(kind="ls", m=240, n=8, dtype="float32")
    here = choose_route(sig, store_view=view).to_dict()
    assert here["source"] == "profile"
    child = (
        "import json\n"
        "from libskylark_tpu.policy.decide import ProblemSignature, "
        "choose_route\n"
        "from libskylark_tpu.policy.profile import load_entries\n"
        f"view = load_entries({policy_env!r})\n"
        "sig = ProblemSignature(kind='ls', m=240, n=8, dtype='float32')\n"
        "print(json.dumps(choose_route(sig, store_view=view).to_dict()))\n"
    )
    env = dict(os.environ, SKYLARK_POLICY="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", child], capture_output=True, text=True,
        env=env, timeout=90, check=True,
    )
    there = json.loads(out.stdout.strip().splitlines()[-1])
    assert there == here


def test_decision_repeatable_on_same_view(policy_env):
    A, b = _ls_problem()
    _mature(A, b, runs=4)
    sig = ProblemSignature(kind="ls", m=240, n=8, dtype="float32")
    d1 = choose_route(sig, store_view=load_entries(policy_env)).to_dict()
    policy.invalidate_cache()
    d2 = choose_route(sig, store_view=load_entries(policy_env)).to_dict()
    assert d1 == d2


# ---------------------------------------------------------------------------
# what a matured profile may change


def test_matured_profile_shrinks_sketch_dim(policy_env):
    A, b = _ls_problem()
    _mature(A, b, runs=4)
    x, info = approximate_least_squares(
        A, b, SketchContext(seed=7), return_info=True
    )
    dec = info["policy"]
    assert dec["source"] == "profile"
    assert dec["sketch_size"] < min(4 * 8, 240)  # shrunk below default
    assert dec["sketch_size"] >= min(2 * 8, 240)  # never below the floor
    # the shrunk sketch still certifies on attempt 0
    assert info["recovery"]["attempts"][0]["verdict"] == "OK"
    assert np.all(np.isfinite(np.asarray(x)))


def test_explicit_overrides_beat_profile(policy_env):
    A, b = _ls_problem()
    _mature(A, b, runs=4)
    from libskylark_tpu.linalg.least_squares import LeastSquaresParams

    _, info = approximate_least_squares(
        A, b, SketchContext(seed=7),
        LeastSquaresParams(sketch_type="JLT", sketch_size=32),
        route="sketch", return_info=True,
    )
    dec = info["policy"]
    assert dec["sketch_type"] == "JLT"
    assert dec["sketch_size"] == 32
    assert dec["route"] == "sketch"


def test_unknown_route_rejected(policy_env):
    A, b = _ls_problem()
    with pytest.raises(ValueError, match="route"):
        approximate_least_squares(
            A, b, SketchContext(seed=7), route="warp-drive"
        )
    assert "warp-drive" not in LS_ROUTES


def test_bf16_first_escalates_to_f32_on_bad_certificate(
    policy_env, monkeypatch
):
    """bf16-first with a poisoned attempt 0: the certificate is not OK,
    so the call escalates back to the full-precision rerun and the store
    records the bf16 failure (which retires bf16-first for the key)."""
    A, b = _ls_problem()
    _mature(A, b, runs=3)
    monkeypatch.setenv("SKYLARK_POLICY_BF16", "1")
    x, info = approximate_least_squares(
        A, b, SketchContext(seed=7),
        fault_plan=FaultPlan(nan_at=0), return_info=True,
    )
    dec = info["policy"]
    assert dec["compute_dtype"] == "bfloat16"
    assert dec["escalated"] is True
    assert np.asarray(x).dtype == np.float32
    assert np.all(np.isfinite(np.asarray(x)))
    # the recorded failure retires bf16-first on the next decision
    policy.invalidate_cache()
    entry = load_entries(policy_env)["entries"][dec["key"]]
    assert entry["bf16"]["fail"] >= 1
    sig = ProblemSignature(kind="ls", m=240, n=8, dtype="float32")
    nxt = choose_route(sig, store_view=load_entries(policy_env))
    assert nxt.compute_dtype is None


def test_bf16_clean_run_stays_bf16_and_matches_dtype(
    policy_env, monkeypatch
):
    A, b = _ls_problem()
    _mature(A, b, runs=3)
    monkeypatch.setenv("SKYLARK_POLICY_BF16", "1")
    x, info = approximate_least_squares(
        A, b, SketchContext(seed=7), return_info=True
    )
    assert info["policy"]["compute_dtype"] == "bfloat16"
    assert "escalated" not in info["policy"]
    assert np.asarray(x).dtype == np.float32  # cast back before the solve


# ---------------------------------------------------------------------------
# store: merge, corruption, persistence


def test_corrupt_store_files_are_skipped_not_trusted(policy_env):
    store = ProfileStore(policy_env)
    store.fold("ls|cpu|float32|r8c3", {"ok0": True, "route": "sketch"},
               now=100.0)
    assert store.save(now=100.0) is not None
    # torn write: plain garbage
    with open(os.path.join(policy_env, "profile-9001.json"), "w") as fh:
        fh.write('{"version": 1, "payl')
    # byte flip: valid JSON, wrong CRC
    with open(os.path.join(policy_env, "profile-9002.json"), "w") as fh:
        json.dump({"version": 1, "pid": 9002,
                   "payload": {"entries": {"x": {"runs": 99}}},
                   "crc": 12345}, fh)
    policy.invalidate_cache()
    view = load_entries(policy_env)
    assert view["corrupt_files"] == 2
    assert set(view["entries"]) == {"ls|cpu|float32|r8c3"}
    assert view["entries"]["ls|cpu|float32|r8c3"]["runs"] == 1


def test_merge_is_last_writer_wins_per_key(policy_env):
    # Two "processes" write the same key; both files end up on disk
    # (saves are renamed aside, since both stores share this test's pid)
    # and the reader must pick the newer entry.
    a = ProfileStore(policy_env)
    a.fold("k", {"ok0": True, "route": "sketch"}, now=100.0)
    os.replace(a.save(now=100.0),
               os.path.join(policy_env, "profile-1111.json"))
    policy.invalidate_cache()
    b = ProfileStore(policy_env)
    b.fold("k", {"ok0": True, "route": "sketch"}, now=200.0)
    os.replace(b.save(now=200.0),
               os.path.join(policy_env, "profile-2222.json"))
    policy.invalidate_cache()
    view = load_entries(policy_env)
    # the newer file's entry (updated=200) wins; it seeded from the
    # merged view, so the run count carried forward to 2
    assert view["entries"]["k"]["updated"] == 200.0
    assert view["entries"]["k"]["runs"] == 2


def test_observations_persist_and_fold(policy_env):
    A, b = _ls_problem()
    _mature(A, b, runs=3)
    view = load_entries(policy_env)
    key = ProblemSignature(kind="ls", m=240, n=8, dtype="float32").key
    entry = view["entries"][key]
    assert entry["runs"] == 3
    assert entry["guard"]["ok"] == 3
    assert entry["guard"]["fallback"] == 0
    assert entry["sketch"]["default"] == 32
    assert entry["routes"] == {"sketch": 3}
    assert entry["cond"]["max"] is not None


# ---------------------------------------------------------------------------
# warm start


def test_warm_start_replays_plans_bitwise(policy_env):
    A, b = _ls_problem()
    x0 = np.asarray(approximate_least_squares(A, b, SketchContext(seed=7)))
    view = load_entries(policy_env)
    assert view["plans"], "solve should have recorded hot plan keys"

    prev_cache = jax.config.jax_compilation_cache_dir
    try:
        # a "new process": empty plan cache, fresh merged view
        plans.clear()
        plans.reset_stats()
        policy.invalidate_cache()
        ws = policy.warm_start(policy_env)
        assert ws["enabled"] is True
        assert ws["plans_replayed"] >= 1
        assert ws["plans_skipped"] == 0
        assert plans.stats()["traces"] >= 1
        st0 = plans.stats()
        x1 = np.asarray(
            approximate_least_squares(A, b, SketchContext(seed=7))
        )
        assert np.array_equal(x0, x1)  # replay never changes results
        assert plans.stats()["hits"] > st0["hits"]  # and the replay hit
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_cache)


def test_warm_start_disabled_or_storeless_is_noop(policy_env, monkeypatch):
    assert policy.warm_start(str(policy_env) + "-missing")["enabled"] is False
    monkeypatch.setenv("SKYLARK_POLICY", "0")
    assert policy.warm_start(policy_env)["enabled"] is False


# ---------------------------------------------------------------------------
# disabled layer: no reads, no writes


def test_disabled_layer_writes_nothing(policy_env, monkeypatch):
    monkeypatch.setenv("SKYLARK_POLICY", "0")
    A, b = _ls_problem()
    approximate_least_squares(A, b, SketchContext(seed=7))
    assert not os.path.isdir(policy_env) or not os.listdir(policy_env)
