"""Child process for the multi-process ``jax.distributed`` tests.

Usage: ``python tests/_distributed_child.py <proc_id> <num_procs> <port>``.

Each process initializes the distributed runtime against a localhost
coordinator (≙ one rank of the reference's ``mpirun -np 2`` unit tests,
``tests/unit/CMakeLists.txt:11-38``), then runs the cross-process
checks and prints one ``CHECK <name> OK`` line per check plus a final
``DIST-OK``.  The parent treats missing lines as failures.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    proc_id, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    # 2 virtual CPU devices per process → a 4-device global mesh spanning
    # both processes (collectives must cross the process boundary).
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    import jax

    # The axon sitecustomize force-sets jax_platforms to "axon,cpu";
    # this test is a CPU multi-process test by construction.
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=proc_id,
        initialization_timeout=60,
    )

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # jax < 0.5 keeps it under jax.experimental
        from jax.experimental.shard_map import shard_map

    assert jax.process_count() == nprocs, jax.process_count()
    assert len(jax.devices()) == 2 * nprocs, jax.devices()
    print("CHECK world OK", flush=True)

    mesh = Mesh(np.asarray(jax.devices()), ("p",))
    nglobal = len(jax.devices())

    # -- 1. cross-process psum -------------------------------------------
    # Global arange sharded one element per device; psum must see every
    # process's contribution (gloo collectives over the loopback).
    sh = NamedSharding(mesh, P("p"))
    x = jax.make_array_from_callback(
        (nglobal,), sh, lambda idx: np.arange(nglobal, dtype=np.float32)[idx]
    )
    summed = jax.jit(
        shard_map(
            lambda a: jax.lax.psum(a, "p"), mesh=mesh,
            in_specs=P("p"), out_specs=P(),
        )
    )(x)
    got = float(np.asarray(summed.addressable_data(0))[0])
    want = float(np.arange(nglobal).sum())
    assert got == want, (got, want)
    print("CHECK psum OK", flush=True)

    # -- 2. sharded sketch parity across the process boundary ------------
    # Counter-based RNG: both processes realize the SAME JLT from
    # (seed, counter) alone, so each local shard of the P2 rowwise apply
    # must equal the matching rows of an unsharded local apply.
    from libskylark_tpu import SketchContext
    from libskylark_tpu.parallel import rowwise_sharded
    from libskylark_tpu.sketch.dense import JLT

    # Row count derived from the world size (odd worlds: 64 rows over 10
    # devices is exactly the divisibility bug -np 5 runs exist to catch).
    m, n, s = 8 * nglobal, 32, 16
    X_full = np.random.default_rng(7).standard_normal((m, n)).astype(
        np.float32
    )
    S = JLT(n, s, SketchContext(seed=21))
    ref = np.asarray(S.apply(jnp.asarray(X_full), "rowwise"))
    Xg = jax.make_array_from_callback(
        (m, n), NamedSharding(mesh, P("p", None)), lambda idx: X_full[idx]
    )
    out = rowwise_sharded(S, Xg, mesh)
    for shard in out.addressable_shards:
        np.testing.assert_allclose(
            np.asarray(shard.data), ref[shard.index], rtol=1e-5, atol=1e-6
        )
    print("CHECK sketch-parity OK", flush=True)

    # -- 2b. cross-process psum_scatter ----------------------------------
    # Row-sharded (G, G) arange; tiled psum_scatter over the lane axis
    # leaves each device its slice of the column sums — every element
    # crosses the process boundary.  Gloo may not implement every
    # collective; an UNIMPLEMENTED here degrades to a reasoned SKIP line
    # (the parent accepts either) so one missing collective cannot mask
    # the rest of the battery.
    X_np = np.arange(nglobal * nglobal, dtype=np.float32).reshape(
        nglobal, nglobal
    )
    Xsh = jax.make_array_from_callback(
        (nglobal, nglobal),
        NamedSharding(mesh, P("p", None)),
        lambda idx: X_np[idx],
    )
    # The try covers ONLY the collective execution (where UNIMPLEMENTED
    # surfaces); the value assertions run outside it, so a collective
    # that runs but miscomputes still fails the rank.
    try:
        colsums = jax.jit(
            shard_map(
                lambda a: jax.lax.psum_scatter(
                    a, "p", scatter_dimension=1, tiled=True
                ),
                mesh=mesh, in_specs=P("p", None), out_specs=P(None, "p"),
            )
        )(Xsh)
        jax.block_until_ready(colsums)
    except Exception as e:  # noqa: BLE001 — collective unsupported here
        colsums = None
        print(
            f"CHECK psum-scatter SKIP({type(e).__name__}: {str(e)[:120]})",
            flush=True,
        )
    if colsums is not None:
        want_cols = X_np.sum(axis=0)
        for shard in colsums.addressable_shards:
            np.testing.assert_allclose(
                np.asarray(shard.data), want_cols[None, shard.index[1]],
                rtol=1e-6, atol=0,
            )
        print("CHECK psum-scatter OK", flush=True)

    # -- 2c. cross-process all_to_all ------------------------------------
    # Tiled all_to_all turns the row-sharded X into the column-sharded X
    # (device i ends with X[:, i]) — a pure cross-process data exchange.
    try:
        cols = jax.jit(
            shard_map(
                lambda a: jax.lax.all_to_all(
                    a, "p", split_axis=1, concat_axis=0, tiled=True
                ),
                mesh=mesh, in_specs=P("p", None), out_specs=P(None, "p"),
            )
        )(Xsh)
        jax.block_until_ready(cols)
    except Exception as e:  # noqa: BLE001 — collective unsupported here
        cols = None
        print(
            f"CHECK all-to-all SKIP({type(e).__name__}: {str(e)[:120]})",
            flush=True,
        )
    if cols is not None:
        for shard in cols.addressable_shards:
            np.testing.assert_allclose(
                np.asarray(shard.data), X_np[:, shard.index[1]],
                rtol=0, atol=0,
            )
        print("CHECK all-to-all OK", flush=True)

    # -- 2d. P6 sparse schedule over the multi-process mesh --------------
    # columnwise_sharded_sparse's compiled program (host COO row-block
    # split + in-shard counter windows + one psum merge) with its inputs
    # built as GLOBAL arrays — the sparse schedule's psum crosses the
    # process boundary for the first time (VERDICT r4 item 3).
    from jax.experimental import sparse as jsparse

    from libskylark_tpu.parallel.collectives import (
        _columnwise_sparse_program,
        _shard_coo_rows,
    )
    from libskylark_tpu.sketch.hash import CWT

    rng = np.random.default_rng(11)
    N_sp, m_sp, s_sp = 4 * nglobal, 8, 16
    M = rng.standard_normal((N_sp, m_sp)).astype(np.float32)
    M[rng.random((N_sp, m_sp)) > 0.3] = 0.0
    A_sp = jsparse.BCOO.fromdense(jnp.asarray(M))
    S_sp = CWT(N_sp, s_sp, SketchContext(seed=29))
    block = N_sp // nglobal
    d, lr, cc = (np.asarray(a) for a in _shard_coo_rows(A_sp, nglobal, block))

    def _globalize(arr):
        return jax.make_array_from_callback(
            arr.shape, NamedSharding(mesh, P("p", None)),
            lambda idx: arr[idx],
        )

    out_sp = _columnwise_sparse_program(S_sp, m_sp, block, mesh, False)(
        _globalize(d), _globalize(lr), _globalize(cc)
    )
    ref_sp = np.asarray(S_sp.apply(A_sp, "columnwise").todense())
    np.testing.assert_allclose(
        np.asarray(out_sp.addressable_data(0)), ref_sp, rtol=1e-5, atol=1e-5
    )
    print("CHECK sparse-p6 OK", flush=True)

    # -- 2e. sparse-OUT schedule across the process boundary --------------
    # The round-5 all_to_all entry exchange (columnwise_sharded_sparse_out
    # routes relabeled nonzeros to their output-row owner): every entry
    # crosses processes here, and the result stays sharded BCOO.
    from libskylark_tpu.parallel.collectives import (
        ShardedBCOO,
        _columnwise_sparse_out_program,
    )

    if cols is None:
        # Gate on the 2c probe: the exchange needs the same gloo
        # all_to_all — degrade to the same reasoned SKIP instead of
        # crashing the rank (and poisoning the other world sizes).
        print("CHECK sparse-out SKIP(all_to_all unsupported here)",
              flush=True)
    else:
        s_so = 2 * nglobal
        S_so = CWT(N_sp, s_so, SketchContext(seed=31))
        cap_so = S_so.nnz * d.shape[1]
        dv, rv, cv = _columnwise_sparse_out_program(
            S_so, block, s_so // nglobal, cap_so, mesh
        )(_globalize(d), _globalize(lr), _globalize(cc))
        # Assemble THIS process's addressable shards and check them
        # against the local apply (full gather needs all processes; each
        # rank owns its row blocks).
        ref_so = np.asarray(S_so.apply(A_sp, "columnwise").todense())
        ob = s_so // nglobal
        for sh_d, sh_r, sh_c in zip(
            dv.addressable_shards, rv.addressable_shards,
            cv.addressable_shards,
        ):
            k = sh_d.index[0].start or 0  # global shard row = owner
            dd = np.asarray(sh_d.data).ravel()
            rr_l = np.asarray(sh_r.data).ravel()
            cc_l = np.asarray(sh_c.data).ravel()
            blk = np.zeros((ob, m_sp), np.float32)
            np.add.at(blk, (rr_l, cc_l), dd)
            np.testing.assert_allclose(
                blk, ref_so[k * ob : (k + 1) * ob], rtol=1e-5, atol=1e-5
            )
        wrapped = ShardedBCOO(dv, rv, cv, (s_so, m_sp), ob, mesh)
        assert wrapped.shape == (s_so, m_sp) and wrapped.row_block == ob
        print("CHECK sparse-out OK", flush=True)

    # -- 3. timer_report(distributed=True) over the world -----------------
    import time

    from libskylark_tpu.utils import PhaseTimer
    from libskylark_tpu.utils.timer import timer_report

    t = PhaseTimer()
    with t.phase("work"):
        time.sleep(0.2 * (proc_id + 1))  # rank-skewed totals
    report = t.report(distributed=True)
    assert f"over {nprocs} processes" in report, report
    row = next(line for line in report.splitlines() if "work" in line)
    cols = row.split()
    tmin, tmax = float(cols[1]), float(cols[2])
    assert tmax > tmin, report  # the skew must be visible in min/max
    print("CHECK timer-report OK", flush=True)

    # -- 4. mismatched phase sets must raise, not misalign ----------------
    bad = {"only_on_rank_1": 1.0} if proc_id else {"only_on_rank_0": 1.0}
    try:
        timer_report(bad, distributed=True)
    except RuntimeError as e:
        assert "different" in str(e)
        print("CHECK timer-mismatch OK", flush=True)
    else:
        raise AssertionError("mismatched phase names did not raise")

    print("DIST-OK", flush=True)
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
