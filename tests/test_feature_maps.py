"""Feature-map and FFT-family sketch tests.

Oracles (reference test strategy, SURVEY §4 + statistical regression
style):
- WHT/DCT: orthonormality + exact small-case identity.
- FJLT: norm preservation in expectation (JL property), JSON round-trip.
- RFT/QRFT/FastRFT: feature inner products approximate the kernel
  (Gaussian/Laplacian/Matérn), statistical tolerance.
- RLT: approximates the exponential semigroup kernel on histograms.
- PPT: approximates the polynomial kernel.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import SketchContext
from libskylark_tpu.sketch import (
    FJLT,
    PPT,
    RFUT,
    ExpSemigroupQRLT,
    ExpSemigroupRLT,
    FastGaussianRFT,
    FastMaternRFT,
    GaussianQRFT,
    GaussianRFT,
    LaplacianQRFT,
    LaplacianRFT,
    MaternRFT,
    dct,
    from_json,
    wht,
)


class TestWHT:
    def test_matches_dense_hadamard(self, rng):
        for n in (2, 8, 64, 512):
            H = np.array([[1.0]])
            while H.shape[0] < n:
                H = np.block([[H, H], [H, -H]])
            x = rng.standard_normal((n, 3))
            np.testing.assert_allclose(
                np.asarray(wht(jnp.asarray(x), axis=0)),
                H @ x / np.sqrt(n),
                rtol=1e-10,
                atol=1e-12,
            )

    def test_orthonormal(self, rng):
        x = jnp.asarray(rng.standard_normal((128, 5)))
        y = wht(x, axis=0)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(y), axis=0),
            np.linalg.norm(np.asarray(x), axis=0),
            rtol=1e-12,
        )
        np.testing.assert_allclose(
            np.asarray(wht(y, axis=0)), np.asarray(x), atol=1e-10
        )

    def test_axis1(self, rng):
        x = jnp.asarray(rng.standard_normal((3, 16)))
        np.testing.assert_allclose(
            np.asarray(wht(x, axis=1)),
            np.asarray(wht(x.T, axis=0)).T,
            rtol=1e-12,
        )

    def test_non_pow2_raises(self, rng):
        with pytest.raises(ValueError, match="power-of-2"):
            wht(jnp.ones((12, 2)))


class TestDCT:
    def test_orthonormal(self, rng):
        x = jnp.asarray(rng.standard_normal((60, 4)))
        y = dct(x, axis=0)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(y), axis=0),
            np.linalg.norm(np.asarray(x), axis=0),
            rtol=1e-10,
        )


class TestRFUT:
    def test_norm_preserving(self, rng):
        x = jnp.asarray(rng.standard_normal((100, 7)))
        T = RFUT(100, SketchContext(seed=5), fut="wht")
        y = T.apply(x, "columnwise")
        assert y.shape == (128, 7)  # padded to pow2
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(y), axis=0),
            np.linalg.norm(np.asarray(x), axis=0),
            rtol=1e-10,
        )

    @pytest.mark.slow
    def test_dct_exact_size(self, rng):
        x = jnp.asarray(rng.standard_normal((60, 3)))
        T = RFUT(60, SketchContext(seed=6), fut="dct")
        assert T.apply(x, "columnwise").shape == (60, 3)


class TestFJLT:
    @pytest.mark.slow
    @pytest.mark.parametrize("fut", ["wht", "dct"])
    def test_norm_preservation_statistical(self, rng, fut):
        n, s, m = 200, 64, 5
        X = jnp.asarray(rng.standard_normal((n, m)))
        norms = np.linalg.norm(np.asarray(X), axis=0)
        errs = []
        for rep in range(5):
            S = FJLT(n, s, SketchContext(seed=rep), fut=fut)
            SX = S.apply(X, "columnwise")
            errs.append(np.abs(np.linalg.norm(np.asarray(SX), axis=0) - norms) / norms)
        # average relative norm distortion ~ 1/sqrt(s); allow 3x slack
        assert np.mean(errs) < 3.0 / np.sqrt(s)

    @pytest.mark.slow
    def test_rowwise_consistent(self, rng):
        n, s = 100, 32
        X = jnp.asarray(rng.standard_normal((4, n)))
        S = FJLT(n, s, SketchContext(seed=3))
        R1 = S.apply(X, "rowwise")
        S2 = FJLT(n, s, SketchContext(seed=3))
        R2 = S2.apply(X.T, "columnwise").T
        np.testing.assert_allclose(np.asarray(R1), np.asarray(R2), rtol=1e-10)

    @pytest.mark.slow
    def test_json_roundtrip(self, rng):
        S = FJLT(50, 16, SketchContext(seed=9))
        S2 = from_json(S.to_json())
        X = jnp.asarray(rng.standard_normal((50, 2)))
        np.testing.assert_array_equal(
            np.asarray(S.apply(X, "columnwise")),
            np.asarray(S2.apply(X, "columnwise")),
        )


class TestFJLTSrhtGemm:
    """The subsampled-Hadamard-as-matmul path must produce the SAME
    transform as the streamed WHT + gather (same samples, same diagonal;
    only the evaluation order differs)."""

    @pytest.mark.parametrize(
        "dim,shape", [("rowwise", (8, 300)), ("columnwise", (300, 8))]
    )
    @pytest.mark.slow
    def test_matches_wht_gather(self, rng, monkeypatch, dim, shape):
        n, s = 300, 32
        A = jnp.asarray(rng.standard_normal(shape))
        S = FJLT(n, s, SketchContext(seed=17))
        monkeypatch.setenv("SKYLARK_NO_SRHT_GEMM", "1")
        ref = S.apply(A, dim)  # streamed WHT + gather
        monkeypatch.delenv("SKYLARK_NO_SRHT_GEMM")
        monkeypatch.setattr(FJLT, "_gemm_wins", lambda self, dtype: True)
        out = S.apply(A, dim)
        assert out.dtype == A.dtype
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-10, atol=1e-10
        )

    def test_pow2_n_no_padding(self, rng, monkeypatch):
        n, s = 256, 64
        A = jnp.asarray(rng.standard_normal((4, n)))
        S = FJLT(n, s, SketchContext(seed=23))
        monkeypatch.setenv("SKYLARK_NO_SRHT_GEMM", "1")
        ref = S.apply(A, "rowwise")
        monkeypatch.delenv("SKYLARK_NO_SRHT_GEMM")
        out = S._apply_srht_gemm(A, rowwise=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-10, atol=1e-10
        )

    def test_gate(self, monkeypatch):
        ctx = SketchContext(seed=1)
        # measured configs from BASELINE.md (n=4096):
        assert FJLT(4096, 256, ctx)._gemm_wins(jnp.float32)
        # f32 s=1024 now WINS via the 3-pass bf16 split (round-2 fix of
        # the documented large-S f32 gather bottleneck)
        assert FJLT(4096, 1024, ctx)._gemm_wins(jnp.float32)
        assert FJLT(4096, 1024, ctx)._gemm_wins(jnp.bfloat16)
        # huge S: matmul flops dominate → streamed path
        assert not FJLT(4096, 4096, ctx)._gemm_wins(jnp.float32)
        # f64 keeps the exact-matmul gate (CPU parity runs): tighter
        # crossover than the f32 split (fpb 80 vs 500/3 per pass)
        assert not FJLT(4096, 2048, ctx)._gemm_wins(jnp.float64)
        # element cap (ADVICE r1): a huge realized G must not transiently
        # blow HBM even when the flops gate would fire (large-n small-S
        # columnwise case)
        assert not FJLT(1 << 20, 128, ctx)._gemm_wins(jnp.bfloat16)
        monkeypatch.setenv("SKYLARK_NO_SRHT_GEMM", "1")
        assert not FJLT(4096, 128, ctx)._gemm_wins(jnp.float32)

    def test_f32_split_accuracy(self, rng, monkeypatch):
        """The 3-pass bf16 split reproduces the f32 WHT+gather transform
        to f32-accumulation accuracy (the split itself is exact to ~24
        mantissa bits; only summation order differs)."""
        import jax

        n, s = 512, 128
        A32 = jnp.asarray(rng.standard_normal((16, n)), jnp.float32)
        S = FJLT(n, s, SketchContext(seed=71))
        monkeypatch.setenv("SKYLARK_NO_SRHT_GEMM", "1")
        ref = S.apply(A32, "rowwise")
        monkeypatch.delenv("SKYLARK_NO_SRHT_GEMM")
        out = S._apply_srht_gemm(A32, rowwise=True)
        assert out.dtype == jnp.float32
        scale = float(jnp.linalg.norm(A32) / np.sqrt(s))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5,
            atol=2e-5 * scale,
        )


def _kernel_mse(Z, K):
    """Mean abs error between feature inner products and kernel matrix."""
    G = np.asarray(Z.T @ Z)
    return np.mean(np.abs(G - K))


def _gaussian_K(X, sigma):
    D2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
    return np.exp(-D2 / (2 * sigma**2))


def _laplacian_K(X, sigma):
    D1 = np.abs(X[:, None, :] - X[None, :, :]).sum(-1)
    return np.exp(-D1 / sigma)


class TestRFT:
    @pytest.mark.slow
    def test_gaussian_kernel_approx(self, rng):
        d, m, s, sigma = 10, 20, 4096, 2.0
        X = rng.standard_normal((m, d))
        K = _gaussian_K(X, sigma)
        F = GaussianRFT(d, s, SketchContext(seed=1), sigma=sigma)
        Z = F.apply(jnp.asarray(X.T), "columnwise")  # (s, m)
        assert _kernel_mse(Z, K) < 0.05

    @pytest.mark.slow
    def test_laplacian_kernel_approx(self, rng):
        d, m, s, sigma = 8, 20, 8192, 3.0
        X = rng.standard_normal((m, d))
        K = _laplacian_K(X, sigma)
        F = LaplacianRFT(d, s, SketchContext(seed=2), sigma=sigma)
        Z = F.apply(jnp.asarray(X.T), "columnwise")
        assert _kernel_mse(Z, K) < 0.08

    @pytest.mark.slow
    def test_matern_features_finite_and_shaped(self, rng):
        F = MaternRFT(6, 512, SketchContext(seed=3), nu=1.5, l=2.0)
        Z = F.apply(jnp.asarray(rng.standard_normal((6, 9))), "columnwise")
        assert Z.shape == (512, 9)
        assert np.all(np.isfinite(np.asarray(Z)))
        with pytest.raises(ValueError, match="2\\*nu"):
            MaternRFT(6, 64, SketchContext(seed=4), nu=0.7)

    @pytest.mark.slow
    def test_rowwise_matches_columnwise(self, rng):
        d, s = 7, 128
        X = rng.standard_normal((5, d))
        F1 = GaussianRFT(d, s, SketchContext(seed=5), sigma=1.5)
        F2 = GaussianRFT(d, s, SketchContext(seed=5), sigma=1.5)
        np.testing.assert_allclose(
            np.asarray(F1.apply(jnp.asarray(X), "rowwise")),
            np.asarray(F2.apply(jnp.asarray(X.T), "columnwise")).T,
            rtol=1e-6, atol=1e-8,
        )

    def test_json_roundtrip(self, rng):
        F = GaussianRFT(5, 64, SketchContext(seed=6), sigma=0.7)
        F2 = from_json(F.to_json())
        X = jnp.asarray(rng.standard_normal((5, 3)))
        np.testing.assert_array_equal(
            np.asarray(F.apply(X, "columnwise")),
            np.asarray(F2.apply(X, "columnwise")),
        )


class TestQRFT:
    @pytest.mark.slow
    def test_gaussian_kernel_approx_qmc(self, rng):
        # QMC should beat plain MC at equal S (or at least match).
        d, m, s, sigma = 6, 15, 1024, 2.0
        X = rng.standard_normal((m, d))
        K = _gaussian_K(X, sigma)
        F = GaussianQRFT(d, s, SketchContext(seed=1), sigma=sigma, skip=1000)
        Z = F.apply(jnp.asarray(X.T), "columnwise")
        assert _kernel_mse(Z, K) < 0.05

    def test_laplacian_qrft_finite(self, rng):
        F = LaplacianQRFT(5, 256, SketchContext(seed=2), sigma=1.0, skip=100)
        Z = F.apply(jnp.asarray(rng.standard_normal((5, 4))), "columnwise")
        assert np.all(np.isfinite(np.asarray(Z)))

    @pytest.mark.slow
    def test_deterministic_in_skip(self, rng):
        X = jnp.asarray(rng.standard_normal((5, 3)))
        Z1 = GaussianQRFT(5, 64, SketchContext(seed=1), skip=7).apply(X)
        Z2 = GaussianQRFT(5, 64, SketchContext(seed=99), skip=7).apply(X)
        np.testing.assert_array_equal(np.asarray(Z1), np.asarray(Z2))


class TestFastRFT:
    def test_gaussian_kernel_approx(self, rng):
        d, m, s, sigma = 16, 15, 4096, 2.0
        X = rng.standard_normal((m, d))
        K = _gaussian_K(X, sigma)
        F = FastGaussianRFT(d, s, SketchContext(seed=1), sigma=sigma)
        Z = F.apply(jnp.asarray(X.T), "columnwise")
        assert _kernel_mse(Z, K) < 0.06

    @pytest.mark.slow
    def test_matern_finite(self, rng):
        F = FastMaternRFT(10, 256, SketchContext(seed=2), nu=1.0, l=1.5)
        Z = F.apply(jnp.asarray(rng.standard_normal((10, 6))), "columnwise")
        assert Z.shape == (256, 6)
        assert np.all(np.isfinite(np.asarray(Z)))

    def test_rowwise_matches_columnwise(self, rng):
        d, s = 12, 128
        X = rng.standard_normal((4, d))
        F1 = FastGaussianRFT(d, s, SketchContext(seed=3), sigma=1.0)
        F2 = FastGaussianRFT(d, s, SketchContext(seed=3), sigma=1.0)
        np.testing.assert_allclose(
            np.asarray(F1.apply(jnp.asarray(X), "rowwise")),
            np.asarray(F2.apply(jnp.asarray(X.T), "columnwise")).T,
            rtol=1e-6, atol=1e-8,
        )

    def test_json_roundtrip(self, rng):
        F = FastGaussianRFT(9, 64, SketchContext(seed=4), sigma=1.2)
        F2 = from_json(F.to_json())
        X = jnp.asarray(rng.standard_normal((9, 2)))
        np.testing.assert_array_equal(
            np.asarray(F.apply(X, "columnwise")),
            np.asarray(F2.apply(X, "columnwise")),
        )

    @pytest.mark.parametrize("dim", ["rowwise", "columnwise"])
    @pytest.mark.parametrize(
        "cls,kw",
        [(FastGaussianRFT, {"sigma": 1.7}), (FastMaternRFT, {"nu": 1.5, "l": 0.9})],
    )
    def test_realized_matches_streaming(self, rng, monkeypatch, cls, kw, dim):
        """The realized-W MXU path (big bf16/f32 batches) must agree with
        the exact streaming form to the 4-pass split's ~2^-16-relative
        pre-cos bound (sketch/frft.py round-3 fast path)."""
        n, s, m = 24, 64, 128  # nb=32; batch >= 4*nb fires the gate
        A = rng.standard_normal((m, n)).astype(np.float32)
        arr = jnp.asarray(A if dim == "rowwise" else A.T)
        S = cls(n, s, SketchContext(seed=11), **kw)
        batch = m
        monkeypatch.setenv("SKYLARK_FRFT_GEMM", "1")  # CPU: force TPU path
        assert S._realize_wins(jnp.float32, batch)
        Z_fast = S.apply(arr, dim)
        monkeypatch.setenv("SKYLARK_NO_FRFT_GEMM", "1")
        assert not S._realize_wins(jnp.float32, batch)
        Z_exact = S.apply(arr, dim)
        np.testing.assert_allclose(
            np.asarray(Z_fast), np.asarray(Z_exact), atol=5e-4
        )

    def test_hoistable_operands_parity(self, rng):
        """apply_with_operands(hoistable_operands(dtype), A) must equal
        apply(A) bit-for-bit — streaming consumers hoist the W
        realization out of their panel loops (XLA does not LICM it)."""
        from libskylark_tpu.sketch.rft import GaussianRFT, MaternRFT

        for cls, kw in (
            (GaussianRFT, {"sigma": 1.7}),
            (MaternRFT, {"nu": 1.5, "l": 0.9}),
        ):
            n, s, m = 24, 32, 8
            F = cls(n, s, SketchContext(seed=17), **kw)
            A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
            ops = F.hoistable_operands(jnp.float32)
            assert ops is not None
            np.testing.assert_array_equal(
                np.asarray(F.apply_with_operands(ops, A, "rowwise")),
                np.asarray(F.apply(A, "rowwise")),
            )
            np.testing.assert_array_equal(
                np.asarray(F.apply_with_operands(ops, A.T, "columnwise")),
                np.asarray(F.apply(A.T, "columnwise")),
            )
            # None ops / default transforms fall back to plain apply
            np.testing.assert_array_equal(
                np.asarray(F.apply_with_operands(None, A, "rowwise")),
                np.asarray(F.apply(A, "rowwise")),
            )
            # apply's input coercion carries over (review regression:
            # int inputs must not truncate W / run an int epilogue)
            Ai = np.arange(m * n).reshape(m, n) % 5
            np.testing.assert_array_equal(
                np.asarray(F.apply_with_operands(ops, Ai, "rowwise")),
                np.asarray(F.apply(Ai, "rowwise")),
            )

    def test_hoistable_operands_fastrft(self, rng, monkeypatch):
        """FastRFT hoisting: (realized W, shifts) — matches the forced
        realized apply exactly, and the streaming-KRR 'fast' tag path
        gets the same loop-hoisting as plain RFT."""
        from libskylark_tpu.sketch import FastGaussianRFT

        n, s, m = 24, 64, 160
        F = FastGaussianRFT(n, s, SketchContext(seed=19), sigma=2.0)
        A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
        ops = F.hoistable_operands(jnp.float32)
        assert ops is not None and len(ops) == 2
        monkeypatch.setenv("SKYLARK_FRFT_GEMM", "1")
        assert F._realize_wins(jnp.float32, m)
        ref = F.apply(A, "rowwise")  # realized path
        np.testing.assert_array_equal(
            np.asarray(F.apply_with_operands(ops, A, "rowwise")),
            np.asarray(ref),
        )
        assert F.hoistable_operands(jnp.float64) is None

    def test_realized_gate_bounds(self, monkeypatch):
        S = FastGaussianRFT(24, 64, SketchContext(seed=12), sigma=1.0)
        assert not S._realize_wins(jnp.float32, 10_000)  # CPU backend: off
        monkeypatch.setenv("SKYLARK_FRFT_GEMM", "1")
        assert not S._realize_wins(jnp.float64, 10_000)  # f64 stays exact
        assert not S._realize_wins(jnp.float32, 64)      # small batch
        big = FastGaussianRFT(
            1 << 13, 1 << 14, SketchContext(seed=13), sigma=1.0
        )
        assert big.numblks * big._nb * big._nb > (64 << 20)
        assert not big._realize_wins(jnp.float32, 1 << 20)  # W cap


class TestRLT:
    @pytest.mark.slow
    def test_expsemigroup_kernel_approx(self, rng):
        # k(x,y) = exp(-beta * sum_i sqrt(x_i + y_i)) on histograms.
        d, m, s, beta = 5, 12, 16384, 0.3
        X = rng.random((m, d))  # non-negative
        K = np.exp(
            -beta * np.sqrt(X[:, None, :] + X[None, :, :]).sum(-1)
        )
        F = ExpSemigroupRLT(d, s, SketchContext(seed=1), beta=beta)
        Z = F.apply(jnp.asarray(X.T), "columnwise")
        assert _kernel_mse(Z, K) < 0.05

    @pytest.mark.slow
    def test_qrlt_finite_and_kernel(self, rng):
        d, m, s, beta = 4, 10, 4096, 0.25
        X = rng.random((m, d))
        K = np.exp(-beta * np.sqrt(X[:, None, :] + X[None, :, :]).sum(-1))
        F = ExpSemigroupQRLT(d, s, SketchContext(seed=2), beta=beta, skip=500)
        Z = F.apply(jnp.asarray(X.T), "columnwise")
        assert np.all(np.isfinite(np.asarray(Z)))
        assert _kernel_mse(Z, K) < 0.1


class TestPPT:
    def test_polynomial_kernel_approx(self, rng):
        d, m, s = 10, 15, 8192
        q, c, gamma = 2, 1.0, 0.5
        X = rng.standard_normal((m, d)) / np.sqrt(d)
        K = (gamma * (X @ X.T) + c) ** q
        F = PPT(d, s, SketchContext(seed=1), q=q, c=c, gamma=gamma)
        Z = F.apply(jnp.asarray(X.T), "columnwise")
        assert _kernel_mse(Z, K) < 0.05

    def test_exact_expectation_q1(self, rng):
        # q=1: CWT preserves inner products exactly in expectation; with
        # the constant term the feature map satisfies E[<z(x),z(y)>] =
        # gamma x.y + c. Sanity-check one draw loosely.
        d, s = 8, 4096
        x = rng.standard_normal(d)
        y = rng.standard_normal(d)
        F = PPT(d, s, SketchContext(seed=3), q=1, c=2.0, gamma=1.5)
        zx = np.asarray(F.apply(jnp.asarray(x), "columnwise"))
        zy = np.asarray(F.apply(jnp.asarray(y), "columnwise"))
        expected = 1.5 * float(x @ y) + 2.0
        assert abs(zx @ zy - expected) < 0.7

    def test_json_roundtrip(self, rng):
        F = PPT(6, 32, SketchContext(seed=4), q=3, c=0.5, gamma=2.0)
        F2 = from_json(F.to_json())
        X = jnp.asarray(rng.standard_normal((6, 3)))
        np.testing.assert_allclose(
            np.asarray(F.apply(X, "columnwise")),
            np.asarray(F2.apply(X, "columnwise")),
            rtol=1e-10,
        )

    def test_jittable(self, rng):
        F = PPT(6, 64, SketchContext(seed=5), q=2)
        Z = jax.jit(lambda X: F.apply(X, "columnwise"))(
            jnp.asarray(rng.standard_normal((6, 4)))
        )
        assert Z.shape == (64, 4)

    @pytest.mark.slow
    def test_bf16_dft_matches_fft(self, rng, monkeypatch):
        """The bf16 matmul-DFT fast path (sketch/ppt.py round 3) must
        agree with the complex-FFT path to bf16 feature accuracy and
        with the f64 exact path to ~1% of the feature scale."""
        import libskylark_tpu.sketch.ppt as pptmod

        monkeypatch.setattr(pptmod, "_DFT_MIN_BATCH", 8)
        monkeypatch.setenv("SKYLARK_PPT_DFT", "1")  # CPU: force TPU path
        n, s, m = 24, 16, 64
        A = rng.standard_normal((n, m))
        F = PPT(n, s, SketchContext(seed=7), q=3, c=0.7, gamma=1.3)
        A16 = jnp.asarray(A).astype(jnp.bfloat16)
        Z_dft = F.apply(A16, "columnwise")
        assert Z_dft.dtype == jnp.bfloat16
        monkeypatch.setenv("SKYLARK_NO_PPT_DFT", "1")
        Z_fft = F.apply(A16, "columnwise")
        Z64 = F.apply(jnp.asarray(A), "columnwise")
        scale = float(jnp.max(jnp.abs(Z64)))
        d_paths = float(
            jnp.max(jnp.abs(Z_dft.astype(jnp.float64) - Z_fft.astype(jnp.float64)))
        )
        d_exact = float(
            jnp.max(jnp.abs(Z_dft.astype(jnp.float64) - np.asarray(Z64)))
        )
        assert d_paths / scale < 0.02
        assert d_exact / scale < 0.02
