"""Front-door result cache (ISSUE PR 18): versioned, bounded, bitwise.

The load-bearing contracts:

- **Hits are bitwise and cost zero device work.**  A repeated idempotent
  request re-serves the stored bits from a dict — ``run_batch`` never
  runs — and the envelope says so (``cache_hit`` trace event).
- **Staleness is structurally impossible.**  The key carries the pinned
  entity's registry epoch, so a live-registry mint (fold/append/downdate)
  makes the VERY NEXT request compute a different key and miss — explicit
  invalidation only frees memory early.
- **In-flight batches are unaffected** either way: epoch-pinned entries
  never consult the cache after admission (same bits as PR 16).
- **The cond/PPR report memoizers are the same cache**: bounded, shared,
  epoch-invalidated — no more unbounded per-system ``_ppr_reports``.
- **The router reads the hit state off the load-report plane**: a replica
  already holding a hot key's results wins placement ties (binary
  preference), so the fleet pays ONE dispatch for a hot key.
"""

import numpy as np
import pytest

from libskylark_tpu import serve
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.graph.graph import SimpleGraph
from libskylark_tpu.serve import batcher
from libskylark_tpu.serve.cache import (
    ResultCache,
    payload_crc,
    payload_digest,
)
from libskylark_tpu.serve.registry import Registry
from libskylark_tpu.serve.router import choose_replica
from libskylark_tpu.utils import exceptions as ex

pytestmark = pytest.mark.cache

M, N = 48, 6
_rng = np.random.default_rng(21)
A_LS = _rng.standard_normal((M, N))
ROWS = _rng.standard_normal((4, N))
B = _rng.standard_normal(M)

N_V = 24
RING = [(i, (i + 1) % N_V) for i in range(N_V)]
CHORDS = [(i, (i + 5) % N_V) for i in range(0, N_V, 3)]


def _server(seed=1, **params):
    params.setdefault("warm_start", False)
    params.setdefault("prime", False)
    params.setdefault("cache", True)
    srv = serve.Server(serve.ServeParams(**params), seed=seed)
    srv.registry.register_system(
        "sys", A_LS, context=SketchContext(seed=9),
        sketch_type="SJLT", sketch_size=32, capacity=M + 8,
    )
    return srv


# ---------------------------------------------------------------------------
# the cache object: keys, bounds, invalidation


def test_payload_digest_is_stable_and_discriminating():
    b = np.arange(8, dtype=np.float64)
    assert payload_digest(b) == payload_digest(b.copy())  # bitwise identity
    assert payload_digest(b) != payload_digest(b.astype(np.float32))
    assert payload_digest(b) != payload_digest(b.reshape(2, 4))
    # framing: nesting and container kind both matter
    assert payload_digest((1, (2, 3))) != payload_digest((1, 2, 3))
    assert payload_digest([1, 2]) != payload_digest((1, 2))
    # dicts hash order-independently
    assert payload_digest({"a": 1, "b": 2}) == payload_digest(
        {"b": 2, "a": 1}
    )
    # a real 128-bit hash (BLAKE2b), NOT a CRC: crc32 is linear over
    # GF(2), so equal-length crc collisions survived any number of
    # domain-prefixed crc passes — a silent wrong-bits hazard at QPS
    assert payload_digest(B) < 2**128
    assert payload_crc is payload_digest  # legacy name kept


def test_lru_entry_bound_and_byte_budget():
    c = ResultCache(max_entries=2, max_bytes=10**6, enabled=True)
    c.put(("k1", 0, 1), {"v": 1})
    c.put(("k2", 0, 1), {"v": 2})
    assert c.get(("k1", 0, 1)) == {"v": 1}  # refreshes k1's recency
    c.put(("k3", 0, 1), {"v": 3})  # evicts k2 (LRU), not k1
    assert c.get(("k2", 0, 1)) is None and c.get(("k1", 0, 1)) == {"v": 1}
    assert c.evictions == 1

    tiny = ResultCache(max_entries=64, max_bytes=2048, enabled=True)
    big = np.zeros(100)  # ~864 bytes each with overhead
    tiny.put(("a", 0, 1), big)
    tiny.put(("b", 0, 1), big)
    tiny.put(("c", 0, 1), big)  # byte budget forces an eviction
    assert len(tiny) < 3 and tiny.stats()["bytes"] <= 2048
    # an oversized value is refused outright, not admitted by eviction
    tiny.put(("huge", 0, 1), np.zeros(4096))
    assert tiny.get(("huge", 0, 1)) is None


def test_invalidate_drops_only_the_entity_and_copies_out():
    c = ResultCache(max_entries=16, max_bytes=10**6, enabled=True)
    c.put(("k1", 0, 1), {"v": 1}, entity="sys")
    c.put(("k2", 0, 1), {"v": 2}, entity="sys")
    c.put(("k3", 0, 1), {"v": 3}, entity="other")
    assert c.invalidate("sys") == 2
    assert c.get(("k1", 0, 1)) is None and c.get(("k3", 0, 1)) == {"v": 3}
    assert c.invalidate("gone") == 0
    # a caller mutating the returned dict cannot poison the cache
    got = c.get(("k3", 0, 1))
    got["v"] = 999
    assert c.get(("k3", 0, 1)) == {"v": 3}


def test_cached_values_are_isolated_from_callers():
    """Neither side of the cache can reach the stored bits (REVIEW):
    put() deep-copies-and-freezes, so the producer keeping its live
    reference (the batcher's response envelope) cannot alter the entry;
    get() rebuilds containers and hands ndarrays back as read-only
    views, so writing into a hit raises instead of poisoning every
    subsequent hit."""
    c = ResultCache(max_entries=16, max_bytes=10**6, enabled=True)

    # producer-side: mutating the object AFTER put() changes nothing
    arr = np.arange(4, dtype=np.float64)
    rep = {"result": arr, "cluster": [1, 2], "nested": {"m": [3]}}
    c.put(("k", 0, 1), rep)
    arr[:] = -1.0
    rep["cluster"].append(99)
    rep["nested"]["m"].append(99)
    got = c.get(("k", 0, 1))
    assert np.array_equal(got["result"], np.arange(4, dtype=np.float64))
    assert got["cluster"] == [1, 2] and got["nested"]["m"] == [3]

    # consumer-side: nested containers are fresh per hit...
    got["cluster"].append(7)
    got["nested"]["m"].append(7)
    again = c.get(("k", 0, 1))
    assert again["cluster"] == [1, 2] and again["nested"]["m"] == [3]
    # ...and arrays are read-only views — mutation raises, never aliases
    with pytest.raises(ValueError):
        again["result"][0] = 123.0
    assert np.array_equal(
        c.get(("k", 0, 1))["result"], np.arange(4, dtype=np.float64)
    )

    # bare-ndarray values get the same treatment
    c.put(("k2", 0, 1), np.ones(3))
    hit = c.get(("k2", 0, 1))
    with pytest.raises(ValueError):
        hit[0] = 5.0


def test_cache_env_knobs(monkeypatch):
    monkeypatch.setenv("SKYLARK_CACHE", "0")
    off = ResultCache()
    assert not off.enabled
    off.put(("k", 0, 1), {"v": 1})
    assert off.get(("k", 0, 1)) is None and len(off) == 0
    monkeypatch.setenv("SKYLARK_CACHE", "1")
    monkeypatch.setenv("SKYLARK_CACHE_MAX_ENTRIES", "7")
    monkeypatch.setenv("SKYLARK_CACHE_MAX_BYTES", "1234")
    on = ResultCache()
    assert on.enabled and on.max_entries == 7 and on.max_bytes == 1234


# ---------------------------------------------------------------------------
# the served hot path: bitwise hits, zero device work


def test_cache_hit_is_bitwise_and_skips_dispatch(monkeypatch):
    dispatches = []
    real = batcher.run_batch
    monkeypatch.setattr(
        batcher, "run_batch",
        lambda reg, entries, device=None: dispatches.append(len(entries))
        or real(reg, entries, device),
    )
    srv = _server().start()
    try:
        r1 = srv.call(op="ls_solve", system="sys", b=B)
        n_after_first = len(dispatches)
        r2 = srv.call(op="ls_solve", system="sys", b=B)
    finally:
        srv.stop()
    assert r1["ok"] and r2["ok"]
    # bitwise: the hit re-serves the exact stored bits
    assert np.array_equal(np.asarray(r1["result"]), np.asarray(r2["result"]))
    assert len(dispatches) == n_after_first  # zero device work on the hit
    assert r2["trace"].get("cache_hit") is True
    assert any(e["kind"] == "cache_hit" for e in r2["trace"]["events"])
    assert not r1["trace"].get("cache_hit")
    st = srv.cache.stats()
    assert st["hits"] == 1 and st["misses"] == 1
    assert st["keys"] == {"ls:sys": 1}


def test_fresh_sketch_requests_never_cache():
    srv = _server().start()
    try:
        srv.call(op="ls_solve", system="sys", b=B, fresh_sketch=True)
        r2 = srv.call(op="ls_solve", system="sys", b=B, fresh_sketch=True)
    finally:
        srv.stop()
    # each fresh-sketch solve draws a unique counter-addressed sketch:
    # the request is DEFINED to differ, so neither fills nor hits
    assert r2["ok"] and not r2["trace"].get("cache_hit")
    assert srv.cache.hits == 0 and len(srv.cache) == 0


def test_cache_disabled_param_means_no_hits():
    srv = _server(cache=False).start()
    try:
        r1 = srv.call(op="ls_solve", system="sys", b=B)
        r2 = srv.call(op="ls_solve", system="sys", b=B)
    finally:
        srv.stop()
    assert r1["ok"] and r2["ok"] and not r2["trace"].get("cache_hit")
    assert len(srv.cache) == 0


# ---------------------------------------------------------------------------
# the live-registry seam: epoch keys, invalidation, pinned in-flight


def test_registry_mint_observed_by_the_very_next_request():
    srv = _server().start()
    try:
        r1 = srv.call(op="cond_est", system="sys")
        hit = srv.call(op="cond_est", system="sys")
        assert hit["trace"].get("cache_hit") is True
        srv.registry.append_system_rows("sys", ROWS)
        # the IDENTICAL request now keys on epoch 2: a structural miss
        # (payload and placement key are unchanged — only the epoch
        # component of the cache key moved), freshly served
        r3 = srv.call(op="cond_est", system="sys")
    finally:
        srv.stop()
    assert r1["trace"]["registry_epoch"] == 1
    assert r3["ok"] and not r3["trace"].get("cache_hit")
    assert r3["trace"]["registry_epoch"] == 2
    # the mint also freed the retired epoch's entries immediately
    assert srv.cache.stats()["invalidations"] >= 1


def test_ppr_cache_invalidates_across_graph_fold():
    srv = _server(seed=2)
    srv.registry.register_graph(
        "g", SimpleGraph(RING), k=4, context=SketchContext(seed=5)
    )
    srv.start()
    try:
        r1 = srv.call(op="ppr", graph="g", seeds=[1, 2])
        hit = srv.call(op="ppr", graph="g", seeds=[2, 1])  # canonical order
        assert hit["trace"].get("cache_hit") is True
        up = srv.call(op="update", graph="g", edges=CHORDS)
        assert up["ok"]
        r3 = srv.call(op="ppr", graph="g", seeds=[1, 2])
    finally:
        srv.stop()
    assert r1["ok"] and r3["ok"]
    assert not r3["trace"].get("cache_hit")  # epoch moved → structural miss
    assert r3["trace"]["registry_epoch"] == r1["trace"]["registry_epoch"] + 1


def test_inflight_epoch_pin_stays_bitwise_with_cache_on():
    live, ref = _server(), _server()
    # admit BEFORE the worker starts, then move the registry head: the
    # queued entry stamped its cache key (and its version pin) at epoch 1
    fut = live.submit(serve.make_request("ls_solve", system="sys", b=B))
    live.registry.append_system_rows("sys", ROWS)
    live.start()
    got = fut.result()
    live.stop()

    ref.start()
    want = ref.call(serve.make_request("ls_solve", system="sys", b=B))
    ref.stop()

    assert got["ok"] and want["ok"]
    assert np.array_equal(
        np.asarray(got["result"]), np.asarray(want["result"])
    )
    assert got["trace"]["registry_epoch"] == 1
    assert not got["trace"].get("cache_hit")


def test_repeat_retire_still_refuses_with_102():
    srv = _server().start()
    try:
        first = srv.call(op="update", system="sys", drop=[3])
        assert first["ok"] and first["result"]["kind"] == "row_downdate"
        again = srv.call(op="update", system="sys", drop=[3])
    finally:
        srv.stop()
    assert not again["ok"] and again["error"]["code"] == 102
    with pytest.raises(ex.InvalidParameters):
        serve.raise_for_error(again)


# ---------------------------------------------------------------------------
# the report memoizers ride the same bounded cache


def test_cond_and_ppr_reports_memoize_on_shared_cache():
    reg = Registry()
    system = reg.register_system(
        "sys", A_LS, context=SketchContext(seed=3),
        sketch_type="SJLT", sketch_size=32, capacity=M + 8,
    )
    rep1 = system.cond_report(cache=reg.cache)
    h0 = reg.cache.hits
    rep2 = system.cond_report(cache=reg.cache)
    assert reg.cache.hits == h0 + 1 and rep1 == rep2

    gsys = reg.register_graph(
        "g", SimpleGraph(RING), k=4, context=SketchContext(seed=5)
    )
    payload = ((1, 2), 0.85, 5.0, 0.001)
    p1 = gsys.ppr_report(payload, cache=reg.cache)
    h1 = reg.cache.hits
    p2 = gsys.ppr_report(payload, cache=reg.cache)
    assert reg.cache.hits == h1 + 1
    assert p1["cluster"] == p2["cluster"]
    assert p1["conductance"] == p2["conductance"]

    # a fold mints a new epoch: the memo key moves with it
    new, _ = reg.fold_graph_edges("g", CHORDS)
    m0 = reg.cache.misses
    new.ppr_report(payload, cache=reg.cache)
    assert reg.cache.misses == m0 + 1


# ---------------------------------------------------------------------------
# the fleet half: load-report census and placement tie-break


def test_load_report_carries_cache_block_and_tenants():
    srv = _server().start()
    try:
        srv.call(op="cond_est", system="sys")
        report = srv.load_report()
    finally:
        srv.stop()
    cache = report["cache"]
    assert cache["enabled"] and cache["entries"] >= 1
    # two entries share the placement key: the cond-report memo and the
    # front-door response — the census the router tie-breaks on
    assert cache["keys"].get("cond:sys", 0) >= 1
    assert report["tenants"] == {}  # nothing queued at snapshot time


def test_router_prefers_replica_holding_cached_key():
    def member(depth, cache_keys=None):
        report = {"queue_depth": depth, "max_queue": 64}
        if cache_keys is not None:
            report["cache"] = {"keys": cache_keys}
        return {"placeable": True, "report": report}

    members = {
        "idle": member(0),
        "warm": member(3, {"ls:sys": 2}),
    }
    # a replica already holding the key's results wins placement ties
    # even against an emptier queue: ONE fleet dispatch for a hot key
    assert choose_replica("ls:sys", members, {}) == "warm"
    # the preference is binary and per-key: other keys fall back to
    # queue depth, and reports without a cache block read as zero
    assert choose_replica("ppr:g", members, {}) == "idle"
    assert choose_replica("ls:sys", {"a": member(1), "b": member(0)}, {}) == "b"
    # the affinity pin still wins over the cache preference
    assert choose_replica("ls:sys", members, {"ls:sys": "idle"}) == "idle"


# ---------------------------------------------------------------------------
# marker contract


@pytest.mark.cache
def test_cache_marker_registered_tier1():
    """Marker contract (ISSUE PR 18): the ``cache`` marker must stay a
    registered tier-1 mark with a hard per-test alarm — cache tests run
    live servers (worker thread + blocking queue), which could otherwise
    wedge the tier-1 run.  Static over conftest so dropping the mark
    (or demoting it to slow) fails here."""
    import pathlib

    src = (pathlib.Path(__file__).parent / "conftest.py").read_text()
    assert '"cache": CACHE_TIMEOUT_S' in src, (
        "the cache marker lost its _TIMEOUT_MARKS alarm entry"
    )
    assert "CACHE_TIMEOUT_S = 120" in src
    assert '"markers",\n        "cache:' in src, (
        "the cache marker is no longer registered via addinivalue_line"
    )
