"""Leaped-Halton quasirandom core (ISSUE PR 14 satellite): window
determinism across execution modes, serialization round-trip, and
parameter validation.

The determinism contract is deliberately two-tier: WITHIN a mode
(eager-vs-eager, jit-vs-jit) windows are bitwise reproducible — that is
what the plan cache and the QJLT interchange lean on — while ACROSS
modes XLA may fuse the digit recurrence differently, so jit-vs-eager is
pinned to allclose at a few ulp, not bit equality.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import enable_x64

from libskylark_tpu.core.quasirand import LeapedHaltonSequence, primes
from libskylark_tpu.utils.exceptions import InvalidParameters


def test_window_deterministic_within_each_mode():
    seq = LeapedHaltonSequence(40)

    def eager():
        return np.asarray(seq.window(100, 32))

    jitted = jax.jit(
        lambda: seq.window(100, 32), static_argnums=()
    )

    e1, e2 = eager(), eager()
    np.testing.assert_array_equal(e1, e2)
    j1 = np.asarray(jitted())
    j2 = np.asarray(jax.jit(lambda: seq.window(100, 32))())
    np.testing.assert_array_equal(j1, j2)
    # cross-mode: same values up to a few ulp, NOT pinned bitwise
    np.testing.assert_allclose(e1, j1, rtol=0, atol=4 * np.finfo(np.float32).eps)


def test_window_values_are_halton():
    """Spot-check against the textbook definition: base-2 and base-3
    radical inverses of ``idx*leap + 1`` (the sequence skips the all-zero
    index-0 point, as the reference does)."""
    seq = LeapedHaltonSequence(2, leap=1)
    w = np.asarray(seq.window(1, 4, dtype=jnp.float32))

    def rad(p, i):
        f, r = 1.0, 0.0
        while i:
            f /= p
            r += f * (i % p)
            i //= p
        return r

    expect = np.array(
        [[rad(2, i), rad(3, i)] for i in range(2, 6)], np.float32
    )
    np.testing.assert_allclose(w, expect, atol=1e-6)


def test_json_round_trip_preserves_windows_incl_dtype():
    seq = LeapedHaltonSequence(24, leap=101)
    back = LeapedHaltonSequence.from_json(seq.to_json())
    assert back == seq  # frozen dataclass: d and leap both survive
    np.testing.assert_array_equal(
        np.asarray(seq.window(7, 16, dtype=jnp.float32)),
        np.asarray(back.window(7, 16, dtype=jnp.float32)),
    )
    with enable_x64():
        a = seq.window(7, 16, dtype=jnp.float64)
        b = back.window(7, 16, dtype=jnp.float64)
        assert a.dtype == jnp.float64 and b.dtype == jnp.float64
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dict_round_trip_fields():
    d = LeapedHaltonSequence(5, leap=13).to_dict()
    assert d["sequence_type"] == "leaped halton"
    assert d["d"] == 5 and d["leap"] == 13
    assert LeapedHaltonSequence.from_dict(d) == LeapedHaltonSequence(5, 13)


def test_default_leap_is_next_prime_and_coprime():
    for dim in (1, 4, 10, 40):
        seq = LeapedHaltonSequence(dim)
        assert seq.leap == int(primes(dim + 1)[-1])
        assert all(seq.leap % int(p) for p in primes(dim))


def test_negative_dimension_rejected():
    with pytest.raises(InvalidParameters, match="dimension"):
        LeapedHaltonSequence(-1)


def test_nonpositive_leap_rejected():
    for leap in (0, -2):
        with pytest.raises(InvalidParameters, match="positive"):
            LeapedHaltonSequence(4, leap=leap)


def test_leap_sharing_base_factor_rejected():
    # d=3 → bases (2, 3, 5); leap 6 shares factors with 2 AND 3
    with pytest.raises(InvalidParameters, match=r"coprime.*\[2, 3\]"):
        LeapedHaltonSequence(3, leap=6)
    # 7 is coprime with all of (2, 3, 5): accepted
    assert LeapedHaltonSequence(3, leap=7).leap == 7
