"""ML layer tests: kernels, KRR family, RLSC, BlockADMM, model persistence.

Oracles: exact KRR vs direct solve; approximate/faster/large-scale KRR vs
exact KRR predictions; RLSC classification accuracy on separable data;
ADMM objective decrease + accuracy; model JSON round-trips (reference test
style: ``python-skylark/skylark/tests/ml/*``, SURVEY §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import SketchContext
from libskylark_tpu.ml import (
    ADMMParams,
    BlockADMMSolver,
    FeatureMapModel,
    GaussianKernel,
    KernelModel,
    KrrParams,
    LaplacianKernel,
    LinearKernel,
    MaternKernel,
    PolynomialKernel,
    approximate_kernel_ridge,
    approximate_kernel_rlsc,
    dummy_coding,
    faster_kernel_ridge,
    kernel_by_name,
    kernel_ridge,
    kernel_rlsc,
    large_scale_kernel_ridge,
    sketched_approximate_kernel_ridge,
)


def two_blobs(rng, n_per, d, sep=3.0):
    X0 = rng.standard_normal((n_per, d)) - sep / 2
    X1 = rng.standard_normal((n_per, d)) + sep / 2
    X = np.vstack([X0, X1])
    y = np.array([0] * n_per + [1] * n_per)
    perm = rng.permutation(len(y))
    return X[perm], y[perm]


class TestKernels:
    def test_gaussian_gram(self, rng):
        X = rng.standard_normal((10, 4))
        K = np.asarray(GaussianKernel(4, 2.0).gram(jnp.asarray(X)))
        D2 = ((X[:, None] - X[None, :]) ** 2).sum(-1)
        np.testing.assert_allclose(K, np.exp(-D2 / 8.0), rtol=1e-10)

    def test_linear_polynomial_laplacian(self, rng):
        X = rng.standard_normal((8, 3))
        Xj = jnp.asarray(X)
        np.testing.assert_allclose(
            np.asarray(LinearKernel(3).gram(Xj)), X @ X.T, rtol=1e-12
        )
        np.testing.assert_allclose(
            np.asarray(PolynomialKernel(3, q=2, c=1.0, gamma=0.5).gram(Xj)),
            (0.5 * X @ X.T + 1.0) ** 2,
            rtol=1e-12,
        )
        D1 = np.abs(X[:, None] - X[None, :]).sum(-1)
        np.testing.assert_allclose(
            np.asarray(LaplacianKernel(3, 2.0).gram(Xj)), np.exp(-D1 / 2.0),
            rtol=1e-12,
        )

    def test_matern_halfinteger_forms(self, rng):
        X = rng.standard_normal((6, 3))
        r = np.sqrt(np.maximum(((X[:, None] - X[None, :]) ** 2).sum(-1), 0))
        # nu=0.5 -> exp(-r/l)
        K = np.asarray(MaternKernel(3, nu=0.5, l=1.5).gram(jnp.asarray(X)))
        np.testing.assert_allclose(K, np.exp(-r / 1.5), rtol=1e-6)
        # nu=1.5 -> (1+sqrt(3)r/l)exp(-sqrt(3)r/l)
        K = np.asarray(MaternKernel(3, nu=1.5, l=2.0).gram(jnp.asarray(X)))
        a = np.sqrt(3) * r / 2.0
        np.testing.assert_allclose(K, (1 + a) * np.exp(-a), rtol=1e-6)

    def test_gram_rft_consistency(self, rng):
        # feature map inner products approximate the gram matrix
        X = jnp.asarray(rng.standard_normal((12, 5)))
        k = GaussianKernel(5, 2.0)
        S = k.create_rft(4096, "regular", SketchContext(seed=1))
        Z = S.apply(X, "rowwise")  # (n, s)
        assert float(jnp.mean(jnp.abs(Z @ Z.T - k.gram(X)))) < 0.05

    def test_factory(self):
        k = kernel_by_name("gaussian", 7, sigma=1.5)
        assert isinstance(k, GaussianKernel) and k.sigma == 1.5


class TestKRR:
    @pytest.mark.slow
    def test_exact_matches_direct(self, rng):
        X = jnp.asarray(rng.standard_normal((40, 5)))
        y = jnp.asarray(rng.standard_normal(40))
        k = GaussianKernel(5, 1.5)
        m = kernel_ridge(k, X, y, 0.1)
        K = np.asarray(k.gram(X))
        a_ref = np.linalg.solve(K + 0.1 * np.eye(40), np.asarray(y))
        np.testing.assert_allclose(np.asarray(m.A)[:, 0], a_ref, rtol=1e-6, atol=1e-9)
        # predictions on train ~ K a
        np.testing.assert_allclose(
            np.asarray(m.predict(X))[:, 0], K @ a_ref, rtol=1e-6, atol=1e-8
        )

    @pytest.mark.slow
    def test_approximate_close_to_exact(self, rng):
        X = jnp.asarray(rng.standard_normal((150, 6)))
        y = jnp.asarray(np.sin(np.asarray(X).sum(1)))
        k = GaussianKernel(6, 2.0)
        exact = kernel_ridge(k, X, y, 0.05)
        approx = approximate_kernel_ridge(
            k, X, y, 0.05, 2048, SketchContext(seed=2)
        )
        pe = np.asarray(exact.predict(X))[:, 0]
        pa = np.asarray(approx.predict(X))[:, 0]
        assert np.mean(np.abs(pe - pa)) < 0.1

    @pytest.mark.slow
    def test_sketched_approximate(self, rng):
        X = jnp.asarray(rng.standard_normal((300, 4)))
        y = jnp.asarray(np.asarray(X).sum(1))
        k = GaussianKernel(4, 3.0)
        m = sketched_approximate_kernel_ridge(
            k, X, y, 0.05, 256, SketchContext(seed=3)
        )
        pred = np.asarray(m.predict(X))[:, 0]
        assert np.corrcoef(pred, np.asarray(y))[0, 1] > 0.9

    @pytest.mark.slow
    def test_faster_matches_exact(self, rng):
        X = jnp.asarray(rng.standard_normal((120, 5)))
        y = jnp.asarray(rng.standard_normal(120))
        k = GaussianKernel(5, 2.0)
        exact = kernel_ridge(k, X, y, 0.1)
        fast = faster_kernel_ridge(
            k, X, y, 0.1, 512, SketchContext(seed=4),
            KrrParams(tolerance=1e-10, iter_lim=500),
        )
        np.testing.assert_allclose(
            np.asarray(fast.A), np.asarray(exact.A), rtol=1e-4, atol=1e-6
        )

    @pytest.mark.slow
    def test_large_scale_close_to_approximate(self, rng):
        X = jnp.asarray(rng.standard_normal((200, 6)))
        y = jnp.asarray(np.sin(np.asarray(X).sum(1)))
        k = GaussianKernel(6, 2.0)
        m = large_scale_kernel_ridge(
            k, X, y, 0.1, 512, SketchContext(seed=5),
            KrrParams(max_split=256, iter_lim=50, tolerance=1e-8),
        )
        assert len(m.maps) > 1  # actually chunked
        pred = np.asarray(m.predict(X))[:, 0]
        assert np.corrcoef(pred, np.asarray(y))[0, 1] > 0.9

    @pytest.mark.slow
    def test_multi_target(self, rng):
        X = jnp.asarray(rng.standard_normal((60, 4)))
        Y = jnp.asarray(rng.standard_normal((60, 3)))
        m = kernel_ridge(GaussianKernel(4, 1.0), X, Y, 0.5)
        assert m.predict(X).shape == (60, 3)

    def test_bf16_features_keep_dtype_contract(self, rng):
        """bf16 features: _psd_gram's pinned ≥f32 accumulator must not
        leak into the returned model dtype (round-3 review), and the
        f32-factored solve must track an f32-feature run to bf16
        accuracy."""
        import jax.numpy as jnp

        from libskylark_tpu.ml import approximate_kernel_ridge

        n, d, s = 256, 8, 64
        X = rng.standard_normal((n, d)).astype(np.float32)
        y = np.tanh(X @ rng.standard_normal(d)).astype(np.float32)
        k = GaussianKernel(d, sigma=2.0)
        m16 = approximate_kernel_ridge(
            k, jnp.asarray(X).astype(jnp.bfloat16), jnp.asarray(y),
            0.1, s, SketchContext(seed=9),
        )
        assert m16.W.dtype == jnp.bfloat16
        m32 = approximate_kernel_ridge(
            k, jnp.asarray(X), jnp.asarray(y), 0.1, s, SketchContext(seed=9)
        )
        p16 = np.asarray(m16.predict(jnp.asarray(X)), np.float64)
        p32 = np.asarray(m32.predict(jnp.asarray(X)), np.float64)
        scale = np.abs(p32).max() + 1e-30
        assert np.abs(p16 - p32).max() / scale < 0.05  # bf16-level

    @pytest.mark.slow
    def test_streaming_matches_large_scale(self, rng):
        """streaming_kernel_ridge (rows AND features streamed — the
        single-chip 10M×4K north-star machinery) runs the same BCD
        updates as large_scale_kernel_ridge: same context → same maps →
        near-identical W."""
        import jax

        from libskylark_tpu.ml import streaming_kernel_ridge

        n, d, s = 512, 16, 64
        X = jnp.asarray(rng.standard_normal((n, d)))
        y = jnp.asarray(np.tanh(np.asarray(X) @ rng.standard_normal(d)))
        k = GaussianKernel(d, sigma=2.0)
        params = KrrParams(max_split=32, iter_lim=20, tolerance=1e-6)
        m1 = large_scale_kernel_ridge(
            k, X, y, 0.1, s, SketchContext(seed=11), params
        )
        m2 = streaming_kernel_ridge(
            k,
            lambda start, rows: jax.lax.dynamic_slice(X, (start, 0), (rows, d)),
            (n, d), y, 0.1, s, SketchContext(seed=11), params,
            block_rows=128, feature_dtype=X.dtype,
        )
        assert len(m2.maps) == len(m1.maps) > 1
        np.testing.assert_allclose(
            np.asarray(m2.W), np.asarray(m1.W), rtol=1e-4, atol=1e-7
        )
        # model predicts like any FeatureMapModel, identically to m1
        np.testing.assert_allclose(
            np.asarray(m2.predict(X)), np.asarray(m1.predict(X)),
            rtol=1e-4, atol=1e-6,
        )

    def test_host_streamed_matches_large_scale(self, rng):
        """The host-RAM-pool sweep loop (experiments/northstar_krr.py,
        VERDICT r3 item 6 — real device_put per panel) runs the same BCD
        math as large_scale_kernel_ridge: same context → same map →
        near-identical W on the logical vstack of the pool."""
        import os
        import sys

        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        import experiments.northstar_krr as ns

        n_panels, br, d, s = 4, 64, 16, 32
        pool = [
            rng.standard_normal((br, d)).astype(np.float32)
            for _ in range(2)
        ]
        X = np.vstack([pool[p % 2] for p in range(n_panels)])
        y = np.tanh(X @ rng.standard_normal(d)).astype(np.float32)
        old = ns.N, ns.D, ns.S, ns.BR, ns.LAM
        try:
            ns.N, ns.D, ns.S, ns.BR, ns.LAM = n_panels * br, d, s, br, 0.1
            W_host = np.asarray(ns.run_host_streamed(3, pool=pool, y=y,
                                                     sigma=2.0))
        finally:
            ns.N, ns.D, ns.S, ns.BR, ns.LAM = old
        m_ref = large_scale_kernel_ridge(
            GaussianKernel(d, sigma=2.0), jnp.asarray(X), jnp.asarray(y),
            0.1, s, SketchContext(seed=72),
            KrrParams(max_split=0, iter_lim=3, tolerance=0.0),
        )
        np.testing.assert_allclose(
            W_host, np.asarray(m_ref.W), rtol=1e-3, atol=1e-5
        )

    @pytest.mark.slow
    def test_streaming_small_n_default_block_rows(self, rng):
        """Small n with the DEFAULT block_rows must fall back to one
        whole-problem panel (nb=1), not raise (round-3 advisor finding:
        the degenerate-divisor guard spuriously rejected every
        n < block_rows//16 because best==n was not exempted)."""
        import jax

        from libskylark_tpu.ml import streaming_kernel_ridge

        n, d, s = 500, 8, 32  # 500 < 262144//16; divisors of 500 top out at n
        X = jnp.asarray(rng.standard_normal((n, d)))
        y = jnp.asarray(np.tanh(np.asarray(X) @ rng.standard_normal(d)))
        m = streaming_kernel_ridge(
            GaussianKernel(d, sigma=2.0),
            lambda start, rows: jax.lax.dynamic_slice(X, (start, 0), (rows, d)),
            (n, d), y, 0.1, s, SketchContext(seed=11),
            KrrParams(max_split=0, iter_lim=3, tolerance=0.0),
            feature_dtype=X.dtype,  # default block_rows=262144 on purpose
        )
        assert np.asarray(m.W).shape[0] == s


class TestRLSC:
    @pytest.mark.slow
    def test_kernel_rlsc_separable(self, rng):
        X, y = two_blobs(rng, 40, 4)
        m = kernel_rlsc(GaussianKernel(4, 2.0), jnp.asarray(X), y, 0.01)
        pred = np.asarray(m.predict_labels(jnp.asarray(X), m.classes))
        assert (pred == y).mean() > 0.95

    def test_approximate_rlsc(self, rng):
        X, y = two_blobs(rng, 50, 5)
        m = approximate_kernel_rlsc(
            GaussianKernel(5, 2.0), jnp.asarray(X), y, 0.01, 1024,
            SketchContext(seed=7),
        )
        pred = np.asarray(m.predict_labels(jnp.asarray(X), m.classes))
        assert (pred == y).mean() > 0.95

    def test_dummy_coding(self):
        T, classes = dummy_coding(np.array([2, 0, 1, 0]))
        np.testing.assert_array_equal(classes, [0, 1, 2])
        np.testing.assert_array_equal(
            np.asarray(T),
            [[-1, -1, 1], [1, -1, -1], [-1, 1, -1], [1, -1, -1]],
        )


@pytest.mark.slow
class TestBlockADMM:
    def _maps(self, d, blocks, s_each, seed=11, sigma=2.0):
        ctx = SketchContext(seed=seed)
        k = GaussianKernel(d, sigma)
        return [k.create_rft(s_each, "regular", ctx) for _ in range(blocks)]

    def test_objective_decreases(self, rng):
        X, y = two_blobs(rng, 32, 4)
        solver = BlockADMMSolver(
            "squared", "l2", self._maps(4, 2, 64),
            ADMMParams(rho=1.0, lam=0.01, maxiter=15),
        )
        m = solver.train(X, y)
        h = m.history
        assert h[-1] <= h[0]

    def test_classification_accuracy(self, rng):
        X, y = two_blobs(rng, 40, 4)
        solver = BlockADMMSolver(
            "hinge", "l2", self._maps(4, 2, 128),
            ADMMParams(rho=1.0, lam=0.005, maxiter=30),
        )
        m = solver.train(X, y)
        pred = np.asarray(m.predict_labels(jnp.asarray(X), m.classes))
        assert (pred == y).mean() > 0.9

    def test_validation_classification(self, rng):
        X, y = two_blobs(rng, 40, 4)
        solver = BlockADMMSolver(
            "hinge", "l2", self._maps(4, 2, 128),
            ADMMParams(rho=1.0, lam=0.005, maxiter=8),
        )
        m = solver.train(X, y, Xv=X[:32], Yv=y[:32])
        assert len(m.val_history) == 8
        assert m.val_history[-1] > 85.0  # percent accuracy
        assert len(m.history) == 8

    def test_validation_multitarget_regression(self, rng):
        X = jnp.asarray(rng.standard_normal((64, 4)))
        W = rng.standard_normal((4, 2))
        T = np.asarray(X) @ W
        solver = BlockADMMSolver(
            "squared", "l2", self._maps(4, 1, 64),
            ADMMParams(rho=1.0, lam=1e-4, maxiter=20),
        )
        m = solver.train(X, T, regression=True, Xv=X[:16], Yv=T[:16])
        assert len(m.val_history) == 20
        assert m.val_history[-1] < 0.5  # relative error shrinks

    def test_scan_and_stepwise_objective_agree(self, rng):
        # the fused-scan (no validation) and per-iteration (validation)
        # paths must produce the same objective trajectory
        X, y = two_blobs(rng, 24, 3)
        maps = self._maps(3, 1, 64, seed=9)
        kw = dict(rho=1.0, lam=0.01, maxiter=5)
        m1 = BlockADMMSolver("squared", "l2", maps, ADMMParams(**kw)).train(X, y)
        m2 = BlockADMMSolver("squared", "l2", maps, ADMMParams(**kw)).train(
            X, y, Xv=X[:8], Yv=y[:8]
        )
        np.testing.assert_allclose(m1.history, m2.history, rtol=1e-8)

    def test_data_partitions_invariance(self, rng):
        # P=1 vs P=4 run the *block-split* algorithm — results differ
        # slightly (different splitting), but both must train well; and
        # the P axis must divide n.
        X, y = two_blobs(rng, 32, 3)
        for P in (1, 4):
            solver = BlockADMMSolver(
                "squared", "l2", self._maps(3, 1, 64, seed=5),
                ADMMParams(rho=1.0, lam=0.01, maxiter=25, data_partitions=P),
            )
            m = solver.train(X, y)
            pred = np.asarray(m.predict_labels(jnp.asarray(X), m.classes))
            assert (pred == y).mean() > 0.9, f"P={P}"

    def test_regression_mode(self, rng):
        X = rng.standard_normal((64, 3))
        y = X.sum(1) + 0.01 * rng.standard_normal(64)
        solver = BlockADMMSolver(
            "squared", "l2", self._maps(3, 1, 256, sigma=3.0),
            ADMMParams(rho=1.0, lam=1e-4, maxiter=40),
        )
        m = solver.train(X, y, regression=True)
        pred = np.asarray(m.predict(jnp.asarray(X)))[:, 0]
        assert np.corrcoef(pred, y)[0, 1] > 0.95

    def test_logistic_multiclass(self, rng):
        # 3-class blobs
        d = 3
        X = np.vstack([
            rng.standard_normal((30, d)) + off
            for off in ([-4, 0, 0], [4, 0, 0], [0, 4, 0])
        ])
        y = np.repeat([0, 1, 2], 30)
        solver = BlockADMMSolver(
            "logistic", "l2", self._maps(d, 2, 128, sigma=3.0),
            ADMMParams(rho=1.0, lam=0.003, maxiter=30),
        )
        m = solver.train(X, y)
        pred = np.asarray(m.predict_labels(jnp.asarray(X), m.classes))
        assert (pred == y).mean() > 0.9


class TestModelPersistence:
    def test_feature_map_model_roundtrip(self, tmp_path, rng):
        X, y = two_blobs(rng, 30, 4)
        m = approximate_kernel_rlsc(
            GaussianKernel(4, 2.0), jnp.asarray(X), y, 0.01, 256,
            SketchContext(seed=8),
        )
        path = tmp_path / "model.json"
        m.save(path)
        m2 = FeatureMapModel.load(path)
        np.testing.assert_allclose(
            np.asarray(m.predict(jnp.asarray(X))),
            np.asarray(m2.predict(jnp.asarray(X))),
            rtol=1e-6,
        )

    @pytest.mark.slow
    def test_kernel_model_roundtrip(self, tmp_path, rng):
        X = jnp.asarray(rng.standard_normal((25, 3)))
        y = jnp.asarray(rng.standard_normal(25))
        m = kernel_ridge(GaussianKernel(3, 1.0), X, y, 0.1)
        path = tmp_path / "km.json"
        m.save(path)
        m2 = KernelModel.load(path)
        np.testing.assert_allclose(
            np.asarray(m.predict(X)), np.asarray(m2.predict(X)), rtol=1e-8
        )


class TestModelContainer:
    """≙ model_container_t (model.hpp:1138-1255): polymorphic load +
    embedded label coding."""

    @pytest.mark.slow
    def test_load_model_dispatch_feature_map(self, tmp_path, rng):
        from libskylark_tpu.core.context import SketchContext
        from libskylark_tpu.ml import FeatureMapModel, GaussianKernel, load_model

        ctx = SketchContext(seed=11)
        maps = [GaussianKernel(4, 1.0).create_rft(8, "regular", ctx)]
        m = FeatureMapModel(maps, rng.standard_normal((8, 3)), input_dim=4,
                            classes=[3, 7, 9])
        m.save(tmp_path / "fm.json")
        m2 = load_model(tmp_path / "fm.json")
        assert isinstance(m2, FeatureMapModel)
        assert m2.classes == [3, 7, 9]
        X = rng.standard_normal((6, 4))
        # predict_labels decodes through the embedded coding by default
        lbl = np.asarray(m2.predict_labels(X))
        assert set(lbl.tolist()) <= {3, 7, 9}
        np.testing.assert_allclose(
            np.asarray(m2.predict(X)), np.asarray(m.predict(X)),
            rtol=1e-6, atol=1e-7,
        )

    def test_load_model_dispatch_kernel(self, tmp_path, rng):
        from libskylark_tpu.ml import GaussianKernel, KernelModel, load_model

        Xtr = rng.standard_normal((10, 3))
        m = KernelModel(GaussianKernel(3, 1.5), Xtr,
                        rng.standard_normal((10, 2)), classes=[0, 1])
        m.save(tmp_path / "km.json")
        m2 = load_model(tmp_path / "km.json")
        assert isinstance(m2, KernelModel)
        assert m2.classes == [0, 1]
        X = rng.standard_normal((4, 3))
        np.testing.assert_allclose(
            np.asarray(m2.predict(X)), np.asarray(m.predict(X)),
            rtol=1e-6, atol=1e-7,
        )

    def test_posthoc_numpy_classes_serialize(self, tmp_path, rng):
        from libskylark_tpu.ml import FeatureMapModel, load_model

        m = FeatureMapModel([], rng.standard_normal((5, 2)), input_dim=5)
        m.classes = np.asarray([1.0, 2.0])  # legacy post-hoc assignment
        m.save(tmp_path / "p.json")
        assert load_model(tmp_path / "p.json").classes == [1.0, 2.0]

    def test_unknown_model_type_raises(self, tmp_path):
        import json

        import pytest

        from libskylark_tpu.ml import load_model

        (tmp_path / "x.json").write_text(json.dumps({"model_type": "mystery"}))
        with pytest.raises(ValueError, match="mystery"):
            load_model(tmp_path / "x.json")


class TestModelDtypeRoundTrip:
    """ISSUE PR 10 satellite: the full save->load matrix over model kinds
    x coefficient dtypes x attached info ledgers.  ``np.save`` erases
    extension dtypes (bfloat16 comes back as raw ``|V2`` void records);
    the dtype tags in the model JSON must restore the arrays BIT-exactly,
    and ``info["recovery"]``/``info["policy"]`` must ride along."""

    _INFO = {"recovery": {"attempts": 2, "verdict": "FALLBACK"},
             "policy": {"route": "qr"}}

    @staticmethod
    def _dtype(name):
        try:
            return np.dtype(name)
        except TypeError:
            import ml_dtypes

            return np.dtype(getattr(ml_dtypes, name))

    @pytest.mark.parametrize("dtype_name", ["float32", "float64", "bfloat16"])
    def test_feature_map_matrix(self, tmp_path, rng, dtype_name):
        from libskylark_tpu.ml import FeatureMapModel, GaussianKernel, load_model

        dt = self._dtype(dtype_name)
        ctx = SketchContext(seed=21)
        maps = [GaussianKernel(4, 1.0).create_rft(8, "regular", ctx)]
        W = rng.standard_normal((8, 3)).astype(dt)
        m = FeatureMapModel(maps, jnp.asarray(W), scale_maps=True,
                            input_dim=4, classes=[5, 6, 7])
        m.info = dict(self._INFO)
        path = tmp_path / f"fm-{dtype_name}.json"
        m.save(path)

        m2 = load_model(path)
        assert isinstance(m2, FeatureMapModel)
        assert str(m2.W.dtype) == dtype_name
        assert np.asarray(m2.W).tobytes() == W.tobytes()  # bit-exact
        assert m2.classes == [5, 6, 7]
        assert m2.info["recovery"]["verdict"] == "FALLBACK"
        assert m2.info["policy"] == {"route": "qr"}
        X = rng.standard_normal((6, 4))
        assert (np.asarray(m2.predict(X)) == np.asarray(m.predict(X))).all()

    @pytest.mark.parametrize("dtype_name", ["float32", "float64", "bfloat16"])
    def test_kernel_matrix(self, tmp_path, rng, dtype_name):
        from libskylark_tpu.ml import GaussianKernel, KernelModel, load_model

        dt = self._dtype(dtype_name)
        Xtr = rng.standard_normal((10, 3)).astype(dt)
        Am = rng.standard_normal((10, 2)).astype(dt)
        m = KernelModel(GaussianKernel(3, 1.5), jnp.asarray(Xtr),
                        jnp.asarray(Am), classes=[0, 1])
        m.info = dict(self._INFO)
        path = tmp_path / f"km-{dtype_name}.json"
        m.save(path)

        m2 = load_model(path)
        assert isinstance(m2, KernelModel)
        assert str(m2.X_train.dtype) == dtype_name
        assert str(m2.A.dtype) == dtype_name
        assert np.asarray(m2.X_train).tobytes() == Xtr.tobytes()
        assert np.asarray(m2.A).tobytes() == Am.tobytes()
        assert m2.classes == [0, 1]
        assert m2.info["recovery"]["attempts"] == 2
        X = rng.standard_normal((4, 3))
        assert (np.asarray(m2.predict(X)) == np.asarray(m.predict(X))).all()

    def test_info_absent_stays_none(self, tmp_path, rng):
        from libskylark_tpu.ml import FeatureMapModel, load_model

        m = FeatureMapModel([], rng.standard_normal((5, 2)), input_dim=5)
        m.save(tmp_path / "n.json")
        assert load_model(tmp_path / "n.json").info is None

    def test_non_json_info_leaves_degrade_to_str(self, tmp_path, rng):
        from libskylark_tpu.ml import FeatureMapModel, load_model

        m = FeatureMapModel([], rng.standard_normal((5, 2)), input_dim=5)
        m.info = {"recovery": {"residual": np.float64(0.25)}}
        m.save(tmp_path / "j.json")
        info = load_model(tmp_path / "j.json").info
        assert info["recovery"]["residual"] in (0.25, "0.25")
