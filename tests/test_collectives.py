"""shard_map sketch schedules + panel-blocked dense apply + linear CLI."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import SketchContext
from libskylark_tpu.parallel import (
    ROWS,
    columnwise_sharded,
    columnwise_sharded_sparse,
    default_mesh,
    make_mesh,
    rowwise_sharded,
    rowwise_sharded_sparse,
    shard_rows,
)
from libskylark_tpu.sketch import CWT, JLT, SJLT, WZT
from libskylark_tpu.sketch import dense as dense_mod


@pytest.mark.slow
class TestShardMapSchedules:
    def test_rowwise_communication_free_matches_local(self, rng):
        n, s, m = 64, 16, 128
        A = jnp.asarray(rng.standard_normal((m, n)))
        mesh = default_mesh()
        S = JLT(n, s, SketchContext(seed=1))
        ref = S.apply(A, "rowwise")
        out = rowwise_sharded(S, shard_rows(A, mesh), mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-10)

    def test_rowwise_hash_sketch(self, rng):
        n, s, m = 48, 12, 64
        A = jnp.asarray(rng.standard_normal((m, n)))
        mesh = default_mesh()
        S = CWT(n, s, SketchContext(seed=2))
        ref = S.apply(A, "rowwise")
        out = rowwise_sharded(S, shard_rows(A, mesh), mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-10)

    def test_columnwise_psum_matches_local(self, rng):
        n, s, m = 128, 32, 24
        A = jnp.asarray(rng.standard_normal((n, m)))
        mesh = default_mesh()
        S = JLT(n, s, SketchContext(seed=3))
        ref = S.apply(A, "columnwise")
        out = columnwise_sharded(S, shard_rows(A, mesh), mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-8, atol=1e-10
        )

    def test_columnwise_psum_scatter(self, rng):
        n, s, m = 64, 32, 8
        A = jnp.asarray(rng.standard_normal((n, m)))
        mesh = default_mesh()
        S = JLT(n, s, SketchContext(seed=4))
        ref = S.apply(A, "columnwise")
        out = columnwise_sharded(S, shard_rows(A, mesh), mesh, scatter=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-8, atol=1e-10
        )


def _random_bcoo(rng, shape, density=0.1):
    from jax.experimental import sparse as jsparse

    M = rng.standard_normal(shape) * (rng.random(shape) < density)
    return jsparse.BCOO.fromdense(jnp.asarray(M)), M


def test_capacity_suggestion_rejects_2d_mesh(rng):
    """The 1-D capacity helper's n/p row blocks don't match the 2-D
    grid's row-axis exchange — a silently wrong capacity would drop
    entries, so multi-axis meshes must be refused loudly.  Meshes are
    built directly (no make_mesh) so this runs tier-1 regardless of the
    installed JAX's AxisType support."""
    from jax.sharding import Mesh

    from libskylark_tpu.parallel import suggest_sparse_out_capacity

    S = CWT(32, 16, SketchContext(seed=43))
    A, _ = _random_bcoo(rng, (32, 6), density=0.3)
    devs = np.array(jax.devices())
    with pytest.raises(ValueError, match="1-D only"):
        suggest_sparse_out_capacity(
            S, A, Mesh(devs.reshape(4, 2), ("r", "c"))
        )
    assert suggest_sparse_out_capacity(S, A, Mesh(devs, (ROWS,))) >= 1


@pytest.mark.slow
class TestSparseShardedSchedules:
    """P6: sharded sparse hash sketches must equal the single-device BCOO
    apply (same counter windows → same buckets/values, only the schedule
    differs)."""

    @pytest.mark.parametrize(
        "sketch_cls,kw", [(CWT, {"nnz": 1}), (SJLT, {"nnz": 4}), (WZT, {"p": 1.5})]
    )
    def test_columnwise_psum(self, rng, sketch_cls, kw):
        n, s, m = 128, 16, 24
        A, _ = _random_bcoo(rng, (n, m))
        mesh = default_mesh()
        S = sketch_cls(n, s, SketchContext(seed=5), **kw)
        ref = S.apply(A, "columnwise").todense()
        out = columnwise_sharded_sparse(S, A, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-8, atol=1e-10
        )

    def test_columnwise_psum_scatter(self, rng):
        n, s, m = 64, 32, 8
        A, _ = _random_bcoo(rng, (n, m))
        mesh = default_mesh()
        S = CWT(n, s, SketchContext(seed=6))
        ref = S.apply(A, "columnwise").todense()
        out = columnwise_sharded_sparse(S, A, mesh, scatter=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-8, atol=1e-10
        )

    def test_rowwise_communication_free(self, rng):
        n, s, m = 96, 12, 64
        A, _ = _random_bcoo(rng, (m, n))
        mesh = default_mesh()
        S = CWT(n, s, SketchContext(seed=7))
        ref = S.apply(A, "rowwise").todense()
        out = rowwise_sharded_sparse(S, A, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-8, atol=1e-10
        )

    def test_ragged_row_blocks(self, rng):
        # skew all nonzeros into the first row block: padding must stay
        # harmless and the result exact
        from jax.experimental import sparse as jsparse

        n, s, m = 64, 8, 8
        M = np.zeros((n, m))
        M[: n // 8] = rng.standard_normal((n // 8, m))
        A = jsparse.BCOO.fromdense(jnp.asarray(M))
        mesh = default_mesh()
        S = CWT(n, s, SketchContext(seed=8))
        ref = S.apply(A, "columnwise").todense()
        out = columnwise_sharded_sparse(S, A, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-8, atol=1e-10
        )

    def test_shape_validation(self, rng):
        A, _ = _random_bcoo(rng, (60, 8))
        mesh = default_mesh()
        S = CWT(64, 8, SketchContext(seed=9))
        with pytest.raises(ValueError):
            columnwise_sharded_sparse(S, A, mesh)  # wrong N
        S2 = CWT(60, 8, SketchContext(seed=10))
        with pytest.raises(ValueError):
            columnwise_sharded_sparse(S2, A, mesh)  # 60 % 8 != 0


@pytest.mark.slow
class TestSparseOutSchedules:
    """SURVEY row 65 (SpParMat → SpParMat, ``hash_transform_CombBLAS.hpp:
    136-302``): sharded sparse sketches whose OUTPUT stays sparse and
    sharded — columnwise routes relabeled entries to their output-row
    owner through one fixed-capacity all_to_all exchange; rowwise is
    communication-free.  Parity target: the local BCOO→BCOO apply."""

    @pytest.mark.parametrize(
        "sketch_cls,kw", [(CWT, {}), (SJLT, {"nnz": 3}), (WZT, {})]
    )
    def test_columnwise_matches_local(self, rng, sketch_cls, kw):
        from libskylark_tpu.parallel import columnwise_sharded_sparse_out

        n, s, m = 64, 40, 12
        mesh = default_mesh()
        S = sketch_cls(n, s, SketchContext(seed=41), **kw)
        A, _ = _random_bcoo(rng, (n, m), density=0.3)
        out = columnwise_sharded_sparse_out(S, A, mesh)
        ref = S.apply(A, "columnwise")
        np.testing.assert_allclose(
            np.asarray(out.todense()), np.asarray(ref.todense()),
            rtol=1e-5, atol=1e-5,
        )

    def test_columnwise_to_bcoo_stays_sparse(self, rng):
        from libskylark_tpu.parallel import columnwise_sharded_sparse_out

        n, s, m = 64, 4096, 8  # output (4096, 8): dense merge would be 32k
        mesh = default_mesh()
        S = CWT(n, s, SketchContext(seed=42))
        A, _ = _random_bcoo(rng, (n, m), density=0.2)
        out = columnwise_sharded_sparse_out(S, A, mesh)
        # Per-shard storage is entry-proportional, never (S, m):
        p = mesh.size
        assert out.data.shape[1] <= p * S.nnz * max(1, A.nse)
        bc = out.to_bcoo()
        assert bc.shape == (s, m)
        # ≤ one output entry per input nonzero (dedup can only shrink)
        assert bc.nse <= S.nnz * A.nse + 1


    def test_rowwise_matches_local(self, rng):
        from libskylark_tpu.parallel import rowwise_sharded_sparse_out

        n, s, m = 96, 24, 64
        mesh = default_mesh()
        for S in (
            CWT(n, s, SketchContext(seed=43)),
            SJLT(n, s, SketchContext(seed=44), nnz=2),
        ):
            A, _ = _random_bcoo(rng, (m, n), density=0.25)
            out = rowwise_sharded_sparse_out(S, A, mesh)
            ref = S.apply(A, "rowwise")
            np.testing.assert_allclose(
                np.asarray(out.todense()), np.asarray(ref.todense()),
                rtol=1e-5, atol=1e-5,
            )

    def test_columnwise_shape_validation(self, rng):
        from libskylark_tpu.parallel import columnwise_sharded_sparse_out

        mesh = default_mesh()
        A, _ = _random_bcoo(rng, (64, 8))
        S = CWT(64, 12, SketchContext(seed=45))  # 12 % 8 != 0
        with pytest.raises(ValueError, match="divisible"):
            columnwise_sharded_sparse_out(S, A, mesh)

    @pytest.mark.parametrize(
        "sketch_cls,kw", [(CWT, {}), (SJLT, {"nnz": 3})]
    )
    def test_2d_grid_matches_local(self, rng, sketch_cls, kw):
        """Full SpParMat→SpParMat: input on a (4, 2) grid, output on the
        SAME grid, routing column-local over the mesh row axis."""
        from libskylark_tpu.parallel import (
            columnwise_sharded_sparse_out_2d,
            make_mesh,
        )

        mesh = make_mesh((4, 2), ("r", "c"))
        n, s, m = 32, 16, 10
        S = sketch_cls(n, s, SketchContext(seed=61), **kw)
        A, _ = _random_bcoo(rng, (n, m), density=0.35)
        out = columnwise_sharded_sparse_out_2d(S, A, mesh)
        assert out.col_block == m // 2
        ref = S.apply(A, "columnwise")
        np.testing.assert_allclose(
            np.asarray(out.todense()), np.asarray(ref.todense()),
            rtol=1e-5, atol=1e-5,
        )

    def test_property_sweep_random_configs(self, rng):
        """Randomized property sweep: shapes, densities, sketch types,
        and capacity choices drawn per round; parity vs the local BCOO
        apply must hold for every draw (edge shards, hot buckets, and
        sparse corners appear naturally across draws)."""
        from libskylark_tpu.parallel import (
            columnwise_sharded_sparse_out,
            suggest_sparse_out_capacity,
        )

        mesh = default_mesh()
        p = mesh.size
        # The capacity helper is strictly 1-D (it refuses multi-axis
        # meshes); the 1-D schedule flattens the 2-D default mesh to p
        # devices, so a flat p-device mesh gives the matching count.
        flat = make_mesh((p,), (ROWS,))
        for trial in range(6):
            n = p * int(rng.integers(2, 9))
            m = int(rng.integers(1, 14))
            s = p * int(rng.integers(1, 7))
            density = float(rng.uniform(0.05, 0.9))
            cls, kw = [(CWT, {}), (SJLT, {"nnz": 2}), (WZT, {})][trial % 3]
            S = cls(n, s, SketchContext(seed=100 + trial), **kw)
            A, _ = _random_bcoo(rng, (n, m), density=density)
            if trial % 2 == 0:
                # Half the trials run f32: the bitcast single-exchange
                # lane of _exchange_entries is otherwise invisible under
                # the suite's forced x64 (the known f32-parity trap).
                from jax.experimental import sparse as jsparse

                A = jsparse.BCOO(
                    (A.data.astype(jnp.float32), A.indices), shape=A.shape
                )
            cap = (
                None if trial % 2
                else suggest_sparse_out_capacity(S, A, flat)
            )
            out = columnwise_sharded_sparse_out(S, A, mesh, capacity=cap)
            ref = S.apply(A, "columnwise")
            np.testing.assert_allclose(
                np.asarray(out.todense()), np.asarray(ref.todense()),
                rtol=1e-5, atol=1e-5,
                err_msg=f"trial={trial} n={n} m={m} s={s} "
                        f"density={density:.2f} cap={cap}",
            )

    def test_empty_matrix(self, rng):
        """nse=0 input: all shards hold only padding; the result is the
        all-zero sketch (and to_bcoo's empty-keep path)."""
        from jax.experimental import sparse as jsparse

        from libskylark_tpu.parallel import columnwise_sharded_sparse_out

        mesh = default_mesh()
        n, s, m = 32, 16, 4
        A = jsparse.BCOO.fromdense(jnp.zeros((n, m), jnp.float32), nse=1)
        S = CWT(n, s, SketchContext(seed=48))
        out = columnwise_sharded_sparse_out(S, A, mesh)
        np.testing.assert_array_equal(
            np.asarray(out.todense()), np.zeros((s, m), np.float32)
        )

    def test_chain_device_resident(self, rng):
        """S2·(S1·A) chained on-device: the sharded result's per-shard
        entry arrays feed the next schedule directly — no host exit, no
        densification in between.  Both the dense-merge and sparse-out
        second hops must match the local chain (duplicates are fine:
        hashing is linear in entries)."""
        from libskylark_tpu.parallel import columnwise_sharded_sparse_out

        mesh = default_mesh()
        n, m, s1, s2 = 64, 10, 40, 16
        S1 = CWT(n, s1, SketchContext(seed=71))
        S2 = SJLT(s1, s2, SketchContext(seed=72), nnz=2)
        A, _ = _random_bcoo(rng, (n, m), density=0.3)
        mid = columnwise_sharded_sparse_out(S1, A, mesh)
        ref = np.asarray(
            S2.apply(S1.apply(A, "columnwise"), "columnwise").todense()
        )
        dense_chain = mid.sketch_columnwise(S2, dense_output=True)
        np.testing.assert_allclose(
            np.asarray(dense_chain), ref, rtol=1e-5, atol=1e-5
        )
        sparse_chain = mid.sketch_columnwise(S2, dense_output=False)
        np.testing.assert_allclose(
            np.asarray(sparse_chain.todense()), ref, rtol=1e-5, atol=1e-5
        )
        # Validation: wrong inner dimension, non-divisible scatter, and
        # 2-D-grid sources all raise cleanly.
        with pytest.raises(ValueError, match="S2.n"):
            mid.sketch_columnwise(CWT(s1 + 8, 8, SketchContext(seed=73)))
        with pytest.raises(ValueError, match="divisible"):
            mid.sketch_columnwise(
                CWT(s1, 12, SketchContext(seed=74)), scatter=True
            )
        from libskylark_tpu.parallel import (
            columnwise_sharded_sparse_out_2d,
            make_mesh,
        )

        grid = make_mesh((4, 2), ("r", "c"))
        mid2d = columnwise_sharded_sparse_out_2d(
            CWT(n, 16, SketchContext(seed=75)), A, grid
        )
        with pytest.raises(ValueError, match="2-D grid"):
            mid2d.sketch_columnwise(CWT(16, 8, SketchContext(seed=76)))

    def test_2d_grid_needs_2d_mesh(self, rng):
        from libskylark_tpu.parallel import (
            columnwise_sharded_sparse_out_2d,
            make_mesh,
        )

        mesh = make_mesh((8,), ("p",))  # 1-axis: must be rejected
        A, _ = _random_bcoo(rng, (64, 8))
        S = CWT(64, 16, SketchContext(seed=62))
        with pytest.raises(ValueError, match="2-axis"):
            columnwise_sharded_sparse_out_2d(S, A, mesh)

    def test_safe_capacity_never_drops_on_hot_bucket(self, rng):
        """Adversarial: a sketch where EVERY input row hashes to a
        bucket owned by ONE shard must survive the default capacity
        (all entries of one source to one destination).  The
        concentration is constructed, not seed-hunted — a uniform hash
        never concentrates 32 rows on one of 8 owners by chance."""
        from libskylark_tpu.parallel import columnwise_sharded_sparse_out

        class HotCWT(CWT):
            """Every coordinate hashes to bucket 1 (owner shard 0)."""

            def buckets(self, start=0, num=None):
                base = super().buckets(start=start, num=num)
                return jnp.ones_like(base)

        n, s, m = 32, 16, 4
        mesh = default_mesh()
        S = HotCWT(n, s, SketchContext(seed=46))
        A, _ = _random_bcoo(rng, (n, m), density=0.9)
        out = columnwise_sharded_sparse_out(S, A, mesh)
        ref = S.apply(A, "columnwise")  # local path uses the same override
        np.testing.assert_allclose(
            np.asarray(out.todense()), np.asarray(ref.todense()),
            rtol=1e-5, atol=1e-5,
        )

    def test_tight_capacity_ignores_padding(self, rng):
        """Padding entries ride the sentinel destination, so a capacity
        equal to the true max per-(src, dst) REAL entry count loses
        nothing even when shards are skewed (some heavily padded)."""
        from libskylark_tpu.parallel import columnwise_sharded_sparse_out

        n, s, m = 64, 16, 6
        mesh = default_mesh()
        p = mesh.size
        # Skewed rows: all nonzeros in the first row block.
        M = np.zeros((n, m))
        M[: n // p] = rng.standard_normal((n // p, m))
        from jax.experimental import sparse as jsparse

        A = jsparse.BCOO.fromdense(jnp.asarray(M, jnp.float32))
        S = CWT(n, s, SketchContext(seed=47))
        from libskylark_tpu.parallel import suggest_sparse_out_capacity

        # helper is 1-D only; the flat mesh matches the flattened schedule
        need = suggest_sparse_out_capacity(S, A, make_mesh((p,), (ROWS,)))
        # Tight: with one hot source block and a near-uniform hash over
        # p destinations, the exact count sits near nse/p — far under
        # the drop-proof default of nnz*nse.
        assert need < S.nnz * A.nse // 2
        out = columnwise_sharded_sparse_out(S, A, mesh, capacity=need)
        ref = S.apply(A, "columnwise")
        np.testing.assert_allclose(
            np.asarray(out.todense()), np.asarray(ref.todense()),
            rtol=1e-5, atol=1e-5,
        )


@pytest.mark.slow
class TestSparse2DGrid:
    """P6 2-D option (≙ hash_transform_CombBLAS's √p×√p grid): nonzeros
    owned by (row-block, col-block); per-shard local (S, m/pc)
    accumulators, one psum over the mesh ROW axis, output col-sharded."""

    @pytest.mark.parametrize(
        "sketch_cls,kw", [(CWT, {}), (SJLT, {"nnz": 3}), (WZT, {"p": 1.5})]
    )
    def test_matches_local(self, rng, sketch_cls, kw):
        from libskylark_tpu.parallel import columnwise_sharded_sparse_2d

        n, m, s = 128, 32, 16
        A, _ = _random_bcoo(rng, (n, m), density=0.15)
        mesh = default_mesh()  # ('rows', 'cols') = (2, 4)
        S = sketch_cls(n, s, SketchContext(seed=21), **kw)
        ref = S.apply(A, "columnwise").todense()
        out = columnwise_sharded_sparse_2d(S, A, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-8, atol=1e-10
        )

    def test_skewed_cells(self, rng):
        # All nonzeros in one grid cell: padding stays harmless.
        from jax.experimental import sparse as jsparse

        from libskylark_tpu.parallel import columnwise_sharded_sparse_2d

        n, m, s = 64, 16, 8
        M = np.zeros((n, m))
        M[:8, :2] = rng.standard_normal((8, 2))
        A = jsparse.BCOO.fromdense(jnp.asarray(M))
        mesh = default_mesh()
        S = CWT(n, s, SketchContext(seed=22))
        ref = S.apply(A, "columnwise").todense()
        out = columnwise_sharded_sparse_2d(S, A, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-8, atol=1e-10
        )

    def test_needs_2d_mesh(self, rng):
        from libskylark_tpu.parallel import (
            columnwise_sharded_sparse_2d,
            make_mesh,
        )

        A, _ = _random_bcoo(rng, (64, 16))
        S = CWT(64, 8, SketchContext(seed=23))
        with pytest.raises(ValueError, match="2-axis"):
            columnwise_sharded_sparse_2d(S, A, make_mesh((8,), ("rows",)))

    def test_exactly_one_allreduce_over_rows(self, rng):
        """Schedule lock: the merge is ONE all-reduce (over the mesh row
        axis only); nothing else communicates."""
        from libskylark_tpu.parallel.collectives import (
            _columnwise_sparse_2d_program,
            _shard_coo_grid,
        )

        n, m, s = 128, 32, 16
        A, _ = _random_bcoo(rng, (n, m), density=0.15)
        mesh = default_mesh()
        pr, pc = mesh.shape["rows"], mesh.shape["cols"]
        S = CWT(n, s, SketchContext(seed=24))
        d, lr, lc = _shard_coo_grid(A, pr, pc, n // pr, m // pc)
        counts = _collective_counts(
            _columnwise_sparse_2d_program(S, n // pr, m // pc, mesh),
            d, lr, lc,
        )
        assert counts == {"all-reduce": 1}, counts


_COLLECTIVE_RE = __import__("re").compile(
    r"\b(all-reduce|reduce-scatter|all-gather|all-to-all|"
    r"collective-permute)(?:-start)?\("
)


def _collective_counts(fn, *args):
    """Counts of collective instructions in the fully compiled HLO."""
    from collections import Counter

    txt = jax.jit(fn).lower(*args).compile().as_text()
    return Counter(m.group(1) for m in _COLLECTIVE_RE.finditer(txt))


class TestCompiledCommunicationSchedules:
    """P2/P5/P6 are *schedule* invariants, not just value invariants: the
    reference documents rowwise sketch-apply as communication-free and
    columnwise as one reduction (``doc/sphinx/sketching.rst:104-118``).
    Value-parity tests can't catch a JAX/XLA upgrade or refactor that
    silently starts communicating, so these assert collective-op counts
    in the compiled HLO itself (VERDICT round 2 item 4)."""

    def test_rowwise_dense_zero_collectives(self, rng):
        n, s, m = 64, 16, 128
        mesh = default_mesh()
        S = JLT(n, s, SketchContext(seed=31))
        A = shard_rows(jnp.asarray(rng.standard_normal((m, n))), mesh)
        counts = _collective_counts(lambda a: rowwise_sharded(S, a, mesh), A)
        assert not counts, f"rowwise schedule must be comm-free, got {counts}"

    def test_rowwise_hash_zero_collectives(self, rng):
        n, s, m = 48, 12, 64
        mesh = default_mesh()
        S = CWT(n, s, SketchContext(seed=32))
        A = shard_rows(jnp.asarray(rng.standard_normal((m, n))), mesh)
        counts = _collective_counts(lambda a: rowwise_sharded(S, a, mesh), A)
        assert not counts, f"rowwise schedule must be comm-free, got {counts}"

    def test_columnwise_exactly_one_allreduce(self, rng):
        n, s, m = 128, 32, 24
        mesh = default_mesh()
        S = JLT(n, s, SketchContext(seed=33))
        A = shard_rows(jnp.asarray(rng.standard_normal((n, m))), mesh)
        counts = _collective_counts(
            lambda a: columnwise_sharded(S, a, mesh), A
        )
        assert counts == {"all-reduce": 1}, counts

    def test_columnwise_scatter_exactly_one_reduce_scatter(self, rng):
        n, s, m = 64, 32, 8
        mesh = default_mesh()
        S = JLT(n, s, SketchContext(seed=34))
        A = shard_rows(jnp.asarray(rng.standard_normal((n, m))), mesh)
        counts = _collective_counts(
            lambda a: columnwise_sharded(S, a, mesh, scatter=True), A
        )
        assert counts == {"reduce-scatter": 1}, counts

    @staticmethod
    def _split_coo(A, mesh, block):
        from libskylark_tpu.parallel.collectives import _shard_coo_rows

        return _shard_coo_rows(A, mesh.size, block)

    @pytest.mark.slow
    def test_sparse_rowwise_zero_collectives(self, rng):
        from libskylark_tpu.parallel.collectives import _rowwise_sparse_program

        n, s, m = 96, 12, 64
        mesh = default_mesh()
        S = CWT(n, s, SketchContext(seed=35))
        A, _ = _random_bcoo(rng, (m, n))
        # The COO row-block split is host-side; the device program (the
        # part a schedule regression could infect) is lowered directly.
        d, lr, cc = self._split_coo(A, mesh, m // mesh.size)
        counts = _collective_counts(_rowwise_sparse_program(S, m // mesh.size, mesh), d, lr, cc)
        assert not counts, f"sparse rowwise must be comm-free, got {counts}"

    def test_sparse_columnwise_exactly_one_allreduce(self, rng):
        from libskylark_tpu.parallel.collectives import (
            _columnwise_sparse_program,
        )

        n, s, m = 128, 16, 24
        mesh = default_mesh()
        S = SJLT(n, s, SketchContext(seed=36), nnz=4)
        A, _ = _random_bcoo(rng, (n, m))
        d, lr, cc = self._split_coo(A, mesh, n // mesh.size)
        counts = _collective_counts(
            _columnwise_sparse_program(S, m, n // mesh.size, mesh, False),
            d, lr, cc,
        )
        assert counts == {"all-reduce": 1}, counts

    @pytest.mark.slow
    def test_sparse_columnwise_scatter_one_reduce_scatter(self, rng):
        from libskylark_tpu.parallel.collectives import (
            _columnwise_sparse_program,
        )

        n, s, m = 64, 32, 8
        mesh = default_mesh()
        S = CWT(n, s, SketchContext(seed=37))
        A, _ = _random_bcoo(rng, (n, m))
        d, lr, cc = self._split_coo(A, mesh, n // mesh.size)
        counts = _collective_counts(
            _columnwise_sparse_program(S, m, n // mesh.size, mesh, True),
            d, lr, cc,
        )
        assert counts == {"reduce-scatter": 1}, counts

    @pytest.mark.slow
    @pytest.mark.parametrize("dtype,want", [(jnp.float32, 1), (jnp.float64, 2)])
    def test_sparse_out_columnwise_all_to_all_only(self, rng, dtype, want):
        """The sparse→sparse columnwise schedule is an entry EXCHANGE:
        f32 rides ONE packed all-to-all (values bitcast into the index
        buffer), f64 two (values + packed indices); no reduction
        collective, and — the row-65 point — no (S, m) dense
        accumulator anywhere in the program."""
        from jax.experimental import sparse as jsparse

        from libskylark_tpu.parallel.collectives import (
            _columnwise_sparse_out_program,
        )

        n, s, m = 64, 40, 12
        mesh = default_mesh()
        S = SJLT(n, s, SketchContext(seed=38), nnz=3)
        M = rng.standard_normal((n, m)) * (rng.random((n, m)) < 0.3)
        A = jsparse.BCOO.fromdense(jnp.asarray(M, dtype))
        block = n // mesh.size
        d, lr, cc = self._split_coo(A, mesh, block)
        cap = S.nnz * d.shape[1]
        counts = _collective_counts(
            _columnwise_sparse_out_program(
                S, block, s // mesh.size, cap, mesh
            ),
            d, lr, cc,
        )
        assert counts == {"all-to-all": want}, counts

    @pytest.mark.slow
    def test_sparse_out_2d_one_row_axis_all_to_all(self, rng):
        """The 2-D sparse-out exchange rides the mesh ROW axis only:
        one all-to-all (f32), no reduction collective, no dense block."""
        from jax.experimental import sparse as jsparse

        from libskylark_tpu.parallel import make_mesh
        from libskylark_tpu.parallel.collectives import (
            _columnwise_sparse_out_2d_program,
            _shard_coo_grid,
        )

        n, s, m = 32, 16, 10
        mesh = make_mesh((4, 2), ("r", "c"))
        S = CWT(n, s, SketchContext(seed=63))
        M = rng.standard_normal((n, m)) * (rng.random((n, m)) < 0.35)
        A = jsparse.BCOO.fromdense(jnp.asarray(M, jnp.float32))
        d, lr, lc = _shard_coo_grid(A, 4, 2, n // 4, m // 2)
        cap = S.nnz * d.shape[2]
        counts = _collective_counts(
            _columnwise_sparse_out_2d_program(S, n // 4, s // 4, cap, mesh),
            d, lr, lc,
        )
        assert counts == {"all-to-all": 1}, counts

    @pytest.mark.slow
    def test_sparse_out_rowwise_zero_collectives(self, rng):
        from libskylark_tpu.parallel.collectives import (
            _rowwise_sparse_out_program,
        )

        n, s, m = 96, 24, 64
        mesh = default_mesh()
        S = CWT(n, s, SketchContext(seed=39))
        A, _ = _random_bcoo(rng, (m, n), density=0.25)
        d, lr, cc = self._split_coo(A, mesh, m // mesh.size)
        counts = _collective_counts(
            _rowwise_sparse_out_program(S, mesh), d, lr, cc
        )
        assert not counts, f"sparse-out rowwise must be comm-free, got {counts}"

    def test_traced_start_requires_num(self):
        S = CWT(64, 8, SketchContext(seed=11))
        with pytest.raises(ValueError, match="num is required"):
            jax.jit(lambda o: S.buckets(start=o))(jnp.uint32(3))


class TestPanelBlockedApply:
    @pytest.mark.slow
    def test_blocked_matches_unblocked(self, rng, monkeypatch):
        n, s, m = 250, 32, 10  # 250 % panel != 0 -> exercises the remainder
        A = jnp.asarray(rng.standard_normal((n, m)))
        S = JLT(n, s, SketchContext(seed=5))
        ref = S.apply(A, "columnwise")
        ref_r = S.apply(A.T, "rowwise")  # references BEFORE forcing panels
        monkeypatch.setattr(dense_mod, "MAX_REALIZE_ELEMENTS", 1024)
        out = S.apply(A, "columnwise")
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-9, atol=1e-11
        )
        out_r = S.apply(A.T, "rowwise")
        np.testing.assert_allclose(
            np.asarray(out_r), np.asarray(ref_r), rtol=1e-9, atol=1e-11
        )

    @pytest.mark.slow
    def test_sparse_over_threshold_raises(self, rng, monkeypatch):
        from jax.experimental import sparse as jsparse

        from libskylark_tpu.utils.exceptions import UnsupportedError

        monkeypatch.setattr(dense_mod, "MAX_REALIZE_ELEMENTS", 64)
        S = JLT(32, 8, SketchContext(seed=7))
        A = jsparse.BCOO.fromdense(jnp.eye(32))
        with pytest.raises(UnsupportedError, match="CWT"):
            S.apply(A, "columnwise")

    def test_traced_offset_window_crosses_2_32(self):
        # window_bits with base near 2^32: traced vs concrete offsets must
        # agree bit-for-bit (the carry path).
        from libskylark_tpu.core.random import window_bits

        base = (1 << 32) - 64
        hi_c, lo_c = window_bits(5, base, 1000, 0, 40, 3, 50)
        off = jnp.asarray(40, jnp.uint32)
        hi_t, lo_t = jax.jit(
            lambda o: window_bits(5, base, 1000, 0, o, 3, 50)
        )(off)
        np.testing.assert_array_equal(np.asarray(hi_c), np.asarray(hi_t))
        np.testing.assert_array_equal(np.asarray(lo_c), np.asarray(lo_t))

    def test_blocked_jittable(self, rng, monkeypatch):
        monkeypatch.setattr(dense_mod, "MAX_REALIZE_ELEMENTS", 512)
        S = JLT(100, 16, SketchContext(seed=6))
        A = jnp.asarray(rng.standard_normal((100, 4)))
        out = jax.jit(lambda X: S.apply(X, "columnwise"))(A)
        assert out.shape == (16, 4)


class TestLinearCLI:
    @pytest.mark.slow
    def test_solves(self, tmp_path, rng, capsys):
        from libskylark_tpu.cli.linear import main
        from libskylark_tpu.io import write_libsvm

        A = rng.standard_normal((500, 10))
        x_true = rng.standard_normal(10)
        b = A @ x_true
        write_libsvm(tmp_path / "p", A, b)
        rc = main([str(tmp_path / "p"), "--solution", str(tmp_path / "x.npy")])
        assert rc == 0
        x = np.load(tmp_path / "x.npy")
        np.testing.assert_allclose(x, x_true, rtol=1e-4, atol=1e-6)


class TestStreamingKrrCommSchedule:
    """HLO lock for the sharded streaming-KRR chunk programs — the comm
    structure the v5p-32 bound in BASELINE.md is computed from
    (``experiments/comm_model.py``).  Two load-bearing properties:
    (1) XLA hoists the per-panel partial-contraction psums OUT of the
    panel while-loop (one all-reduce per program, not nb); (2) the
    traced-offset dynamic_slice of the row-sharded residual costs
    all-gathers of R — known, bounded, and counted in the model.  A JAX
    upgrade that regresses either changes these counts."""

    def _programs(self):
        from libskylark_tpu.ml import GaussianKernel, KrrParams
        from libskylark_tpu.ml.krr import (
            _chunk_sizes,
            _tag,
            streaming_krr_chunk_programs,
        )
        from libskylark_tpu.parallel import constrain_rows

        mesh = default_mesh()
        N, D, S, BR, T = 64 * mesh.size, 16, 8, 16 * mesh.size, 1
        kernel = GaussianKernel(D, sigma=2.0)
        params = KrrParams(max_split=0)
        sizes = _chunk_sizes(D, S, params)
        maps = [
            kernel.create_rft(sz, _tag(params), SketchContext(seed=72))
            for sz in sizes
        ]

        def block_fn(start, rows):
            base = jax.lax.broadcasted_iota(jnp.float32, (rows, D), 0)
            return constrain_rows(base * 1e-3, mesh)

        progs = streaming_krr_chunk_programs(
            maps, 0, sizes[0], N // BR, BR, T, 0.1, block_fn, jnp.float32
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        row_sh = NamedSharding(mesh, P(None, mesh.axis_names[0], None))
        rep_sh = NamedSharding(mesh, P())
        R = jax.ShapeDtypeStruct(
            (N // BR, BR, T), jnp.float32, sharding=row_sh
        )
        W = jax.ShapeDtypeStruct((sizes[0], T), jnp.float32, sharding=rep_sh)
        return progs, R, W

    @staticmethod
    def _counts(jitted, *specs):
        from collections import Counter

        txt = jitted.lower(*specs).compile().as_text()
        return Counter(m.group(1) for m in _COLLECTIVE_RE.finditer(txt))

    def test_gram_one_allreduce_hoisted(self):
        (gram, _, _), R, W = self._programs()
        counts = self._counts(gram)
        assert counts == {"all-reduce": 1}, counts

    def test_zr_schedule(self):
        """Panel-major R (round 4): the traced-index panel slice stays
        off the sharded axis, so zr's only collective is the hoisted
        partial-contraction psum — the R all-gather is GONE."""
        (_, zr, _), R, W = self._programs()
        counts = self._counts(zr, R, W)
        assert counts == {"all-reduce": 1}, counts

    def test_apply_delta_schedule(self):
        (_, _, apply_delta), R, W = self._programs()
        counts = self._counts(apply_delta, R, W)
        assert not counts, counts
