"""Sketch layer tests, mirroring the reference's test strategy (SURVEY §4):

- dist-vs-local golden consistency -> here: sharded-vs-single-device equality
  (≙ tests/unit/DenseSketchApplyElementalTest.cpp:52-102); works because the
  sketch is a deterministic function of (seed, counter) independent of
  sharding.
- white-box semantics: realize the sketch operator explicitly and check the
  apply against a direct matmul/scatter (≙ tests/unit/test_utils.hpp:14-35).
- serialization round-trip (≙ tests/unit/SerializationTest.cpp).
- statistical bounds with repeats and union-success for randomized claims
  (≙ tests/regression/svd_test.py:24-80).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import sparse as jsparse
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from libskylark_tpu import sketch
from libskylark_tpu.core import SketchContext

DENSE_TYPES = ["JLT", "CT"]
HASH_TYPES = ["CWT", "MMT", "WZT"]
ALL_TYPES = DENSE_TYPES + HASH_TYPES + ["UST"]


def make(kind, n, s, ctx):
    return sketch.create_sketch(kind, n, s, context=ctx)


def dense_operator(S, n, dtype=jnp.float64):
    """Materialize the (s, n) operator by applying to the identity."""
    return np.asarray(S.apply(jnp.eye(n, dtype=dtype), "columnwise"))


# ---------------------------------------------------------------------------
# semantics
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("kind", ALL_TYPES)
def test_columnwise_rowwise_consistency(kind, rng):
    """A @ Omega.T == (Omega @ A.T).T — rowwise is the transpose of
    columnwise with the same realized operator."""
    n, s, m = 37, 11, 5
    ctx = SketchContext(seed=3)
    S = make(kind, n, s, ctx)
    A = jnp.asarray(rng.standard_normal((m, n)))
    out_row = S.apply(A, "rowwise")
    out_col = S.apply(A.T, "columnwise")
    assert out_row.shape == (m, s)
    np.testing.assert_allclose(np.asarray(out_row), np.asarray(out_col).T, rtol=1e-12)


@pytest.mark.slow
@pytest.mark.parametrize("kind", ALL_TYPES)
def test_apply_matches_explicit_operator(kind, rng):
    """Columnwise apply == (operator realized via identity) @ A."""
    n, s, m = 29, 13, 7
    ctx = SketchContext(seed=7)
    S = make(kind, n, s, ctx)
    op = dense_operator(S, n)
    A = rng.standard_normal((n, m))
    out = np.asarray(S.apply(jnp.asarray(A), "columnwise"))
    np.testing.assert_allclose(out, op @ A, rtol=1e-10, atol=1e-12)


@pytest.mark.slow
def test_jlt_scale_and_distribution():
    n, s = 400, 200
    ctx = SketchContext(seed=11)
    S = sketch.JLT(n, s, ctx)
    op = dense_operator(S, n)
    # entries ~ N(0, 1/s): mean ~0, var ~1/s
    assert abs(op.mean()) < 3.0 / np.sqrt(n * s * (1.0 / s))
    np.testing.assert_allclose(op.var(), 1.0 / s, rtol=0.05)


def test_cwt_structure():
    """Each column of the CWT operator has exactly one ±1 entry."""
    n, s = 64, 16
    ctx = SketchContext(seed=5)
    S = sketch.CWT(n, s, ctx)
    op = dense_operator(S, n)
    nnz_per_col = (op != 0).sum(axis=0)
    np.testing.assert_array_equal(nnz_per_col, np.ones(n))
    vals = op[op != 0]
    assert set(np.unique(vals)) <= {-1.0, 1.0}


def test_wzt_values():
    n, s, p = 50, 10, 1.5
    ctx = SketchContext(seed=9)
    S = sketch.WZT(n, s, ctx, p=p)
    op = dense_operator(S, n)
    nnz_per_col = (op != 0).sum(axis=0)
    np.testing.assert_array_equal(nnz_per_col, np.ones(n))


def test_ust_selection(rng):
    n, s = 40, 8
    A = rng.standard_normal((n, 3))
    for replace in (True, False):
        ctx = SketchContext(seed=13)
        S = sketch.UST(n, s, ctx, replace=replace)
        idx = np.asarray(S.samples)
        assert idx.shape == (s,)
        assert ((0 <= idx) & (idx < n)).all()
        if not replace:
            assert len(np.unique(idx)) == s
        out = np.asarray(S.apply(jnp.asarray(A), "columnwise"))
        np.testing.assert_array_equal(out, A[idx, :])


def test_nurst_weighted(rng):
    n, s = 30, 2000
    probs = np.zeros(n)
    probs[3] = 0.7
    probs[17] = 0.3
    ctx = SketchContext(seed=21)
    S = sketch.NURST(n, s, ctx, probs=probs)
    idx = np.asarray(S.samples)
    assert set(np.unique(idx)) <= {3, 17}
    frac = (idx == 3).mean()
    assert 0.6 < frac < 0.8


# ---------------------------------------------------------------------------
# sparse inputs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", HASH_TYPES)
@pytest.mark.slow
def test_hash_sparse_matches_dense(kind, rng):
    n, s, m = 32, 8, 6
    A = rng.standard_normal((n, m))
    A[rng.random((n, m)) < 0.7] = 0.0
    Asp = jsparse.BCOO.fromdense(jnp.asarray(A))
    ctx1, ctx2 = SketchContext(seed=2), SketchContext(seed=2)
    S1 = make(kind, n, s, ctx1)
    S2 = make(kind, n, s, ctx2)
    dense_out = np.asarray(S1.apply(jnp.asarray(A), "columnwise"))
    sparse_out = np.asarray(S2.apply(Asp, "columnwise").todense())
    np.testing.assert_allclose(sparse_out, dense_out, rtol=1e-10, atol=1e-12)
    # rowwise too
    dense_r = np.asarray(S1.apply(jnp.asarray(A.T), "rowwise"))
    sparse_r = np.asarray(
        S2.apply(jsparse.BCOO.fromdense(jnp.asarray(A.T)), "rowwise").todense()
    )
    np.testing.assert_allclose(sparse_r, dense_r, rtol=1e-10, atol=1e-12)


@pytest.mark.parametrize("kind", DENSE_TYPES)
@pytest.mark.slow
def test_dense_sketch_sparse_input(kind, rng):
    n, s, m = 24, 6, 5
    A = rng.standard_normal((n, m))
    A[rng.random((n, m)) < 0.6] = 0.0
    ctx1, ctx2 = SketchContext(seed=4), SketchContext(seed=4)
    S1 = make(kind, n, s, ctx1)
    S2 = make(kind, n, s, ctx2)
    want = np.asarray(S1.apply(jnp.asarray(A), "columnwise"))
    got = S2.apply(jsparse.BCOO.fromdense(jnp.asarray(A)), "columnwise")
    got = np.asarray(got.todense() if hasattr(got, "todense") else got)
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-12)


# ---------------------------------------------------------------------------
# sharding invariance (the dist-vs-local oracle)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("kind", DENSE_TYPES + HASH_TYPES)
def test_sharded_equals_local(kind, rng):
    """Apply on a fully-sharded A equals apply on a single device.

    ≙ the reference's distributed-vs-local golden-consistency tests; the
    8 virtual CPU devices stand in for 8 chips (conftest.py)."""
    n, s, m = 64, 16, 8
    A = jnp.asarray(rng.standard_normal((n, m)))
    ctx_local = SketchContext(seed=17)
    S_local = make(kind, n, s, ctx_local)
    want = np.asarray(S_local.apply(A, "columnwise"))

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
    A_sharded = jax.device_put(A, NamedSharding(mesh, P("x", None)))
    ctx_dist = SketchContext(seed=17)
    S_dist = make(kind, n, s, ctx_dist)
    apply_jit = jax.jit(lambda a: S_dist.apply(a, "columnwise"))
    got = np.asarray(apply_jit(A_sharded))
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-10)


@pytest.mark.slow
def test_window_realization_matches_full():
    """Any window of the realized dense operator == slice of full operator
    (shard-local realization invariant, P5)."""
    n, s = 40, 12
    ctx = SketchContext(seed=23)
    S = sketch.JLT(n, s, ctx)
    full = np.asarray(S.realize(jnp.float64))
    win = np.asarray(S.realize(jnp.float64, offset=(3, 7), shape=(5, 11)))
    np.testing.assert_array_equal(win, full[3:8, 7:18])


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("kind", ALL_TYPES)
def test_serialization_roundtrip(kind, rng):
    n, s, m = 25, 9, 4
    ctx = SketchContext(seed=31, counter=1000)
    S1 = make(kind, n, s, ctx)
    blob = S1.to_json()
    S2 = sketch.from_json(blob)
    assert type(S2) is type(S1)
    A = jnp.asarray(rng.standard_normal((n, m)))
    np.testing.assert_array_equal(
        np.asarray(S1.apply(A, "columnwise")),
        np.asarray(S2.apply(A, "columnwise")),
    )
    # context advanced identically on reconstruction path
    assert json.loads(blob)["creation_context"]["counter"] == 1000


@pytest.mark.slow
def test_context_counter_accounting():
    """Each transform advances the shared stream; transforms built from the
    same context stream are independent (≙ base/context.hpp:91-101)."""
    ctx = SketchContext(seed=1)
    S1 = sketch.JLT(30, 10, ctx)
    c_after_jlt = ctx.counter
    assert c_after_jlt == 300
    S2 = sketch.CWT(30, 10, ctx)
    assert ctx.counter == 300 + 30 + 30
    op1 = dense_operator(S1, 30)
    # rebuild from serialized form and confirm identical operator
    op1b = dense_operator(sketch.from_json(S1.to_json()), 30)
    np.testing.assert_array_equal(op1, op1b)


# ---------------------------------------------------------------------------
# statistical quality (≙ tests/regression/svd_test.py style)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["JLT", "CWT"])
def test_l2_embedding_preserves_singular_values(kind):
    """σ_i(SA) within σ_i(A)·(1±0.5) for all i, for at least one of 5 seeds
    (union-success over repeats, the reference's statistical template)."""
    n, d, s = 1000, 10, 100
    rng = np.random.default_rng(0)
    A = rng.standard_normal((n, d))
    sv = np.linalg.svd(A, compute_uv=False)
    ok = False
    for seed in range(5):
        ctx = SketchContext(seed=seed)
        S = sketch.create_sketch(kind, n, s, context=ctx)
        SA = np.asarray(S.apply(jnp.asarray(A), "columnwise"))
        sv_sk = np.linalg.svd(SA, compute_uv=False)
        if (np.abs(sv_sk - sv) <= 0.5 * sv).all():
            ok = True
            break
    assert ok, f"{kind}: no repeat satisfied the 0.5 relative bound"


class TestHashScatterFallback:
    """The segment_sum path (production path for huge N*S) must stay
    covered: force it by shrinking the one-hot threshold."""

    @pytest.mark.slow
    def test_scatter_matches_onehot(self, rng, monkeypatch):
        import jax.numpy as jnp
        from libskylark_tpu import SketchContext
        from libskylark_tpu.sketch import CWT, SJLT

        A = jnp.asarray(rng.standard_normal((50, 20)))
        for cls, kw in ((CWT, {}), (SJLT, {"nnz": 3})):
            S = cls(50, 12, SketchContext(seed=9), **kw)
            ref = S.apply(A, "columnwise")
            ref_r = S.apply(A.T, "rowwise")
            monkeypatch.setattr(cls, "_ONEHOT_LIMIT", 0)
            out = S.apply(A, "columnwise")
            out_r = S.apply(A.T, "rowwise")
            monkeypatch.undo()
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=1e-10, atol=1e-12
            )
            np.testing.assert_allclose(
                np.asarray(out_r), np.asarray(ref_r), rtol=1e-10, atol=1e-12
            )


@pytest.mark.slow
class TestSparseDenseOutput:
    """``dense_output=True`` (≙ hash_transform_Mixed.hpp sparse→dense):
    sort-free per-hash segment_sum must equal the BCOO relabel path."""

    @pytest.mark.parametrize(
        "cls,kw",
        [("CWT", {}), ("SJLT", {"nnz": 3}), ("WZT", {"p": 1.5})],
    )
    def test_matches_bcoo_path(self, rng, cls, kw):
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse

        import libskylark_tpu.sketch as sk
        from libskylark_tpu import SketchContext

        n, m, s = 96, 24, 16
        M = rng.standard_normal((n, m)) * (rng.random((n, m)) < 0.2)
        A = jsparse.BCOO.fromdense(jnp.asarray(M))
        S = getattr(sk, cls)(n, s, SketchContext(seed=4), **kw)
        ref = S.apply(A, "columnwise").todense()
        out = S.apply(A, "columnwise", dense_output=True)
        assert not isinstance(out, jsparse.BCOO)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-10, atol=1e-12
        )
        At = jsparse.BCOO.fromdense(jnp.asarray(M.T))
        np.testing.assert_allclose(
            np.asarray(S.apply(At, "rowwise", dense_output=True)),
            np.asarray(S.apply(At, "rowwise").todense()),
            rtol=1e-10, atol=1e-12,
        )

    def test_dense_out_limit(self, rng):
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse

        from libskylark_tpu import SketchContext
        from libskylark_tpu.sketch import CWT

        A = jsparse.BCOO.fromdense(jnp.asarray(rng.standard_normal((8, 4))))
        S = CWT(8, 4, SketchContext(seed=5))
        S._DENSE_OUT_LIMIT = 8  # S*batch = 16 > 8
        with pytest.raises(ValueError, match="dense_output"):
            S.apply(A, "columnwise", dense_output=True)


class TestHoistableOperands:
    """hoistable_operands / apply_with_operands across the hash family
    and FJLT: bit-identical to plain apply (the streaming-consumer
    seam; dense/RFT/FastRFT variants live in test_feature_maps.py)."""

    @pytest.mark.parametrize(
        "cls,kw",
        [("CWT", {}), ("SJLT", {"nnz": 3}), ("MMT", {}), ("WZT", {"p": 1.5})],
    )
    @pytest.mark.slow
    @pytest.mark.parametrize("dim", ["rowwise", "columnwise"])
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_hash_family(self, rng, cls, kw, dim, dtype):
        import jax.numpy as jnp

        import libskylark_tpu.sketch as sk
        from libskylark_tpu import SketchContext

        dt = jnp.dtype(dtype)
        n, s, m = 64, 16, 40
        S = getattr(sk, cls)(n, s, SketchContext(seed=3), **kw)
        A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32).astype(dt)
        arr = A if dim == "rowwise" else A.T
        ops = S.hoistable_operands(dt)
        assert ops is not None
        np.testing.assert_array_equal(
            np.asarray(S.apply_with_operands(ops, arr, dim)),
            np.asarray(S.apply(arr, dim)),
        )
        assert S.hoistable_operands(jnp.float64) is None
        # None ops falls back; f64 inputs keep apply's exact matmul
        np.testing.assert_array_equal(
            np.asarray(S.apply_with_operands(None, arr, dim)),
            np.asarray(S.apply(arr, dim)),
        )
        A64 = jnp.asarray(rng.standard_normal((m, n)))
        arr64 = A64 if dim == "rowwise" else A64.T
        np.testing.assert_array_equal(
            np.asarray(S.apply_with_operands(ops, arr64, dim)),
            np.asarray(S.apply(arr64, dim)),
        )

    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_fjlt(self, rng, dtype):
        import jax.numpy as jnp

        from libskylark_tpu import SketchContext
        from libskylark_tpu.sketch import FJLT

        dt = jnp.dtype(dtype)
        n, s, m = 64, 16, 40
        S = FJLT(n, s, SketchContext(seed=5))
        A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32).astype(dt)
        ops = S.hoistable_operands(dt)
        assert ops is not None
        assert S._gemm_wins(dt)  # gemm path active at this shape
        np.testing.assert_array_equal(
            np.asarray(S.apply_with_operands(ops, A, "rowwise")),
            np.asarray(S.apply(A, "rowwise")),
        )
        np.testing.assert_array_equal(
            np.asarray(S.apply_with_operands(ops, A.T, "columnwise")),
            np.asarray(S.apply(A.T, "columnwise")),
        )
        assert S.hoistable_operands(jnp.float64) is None


class TestHashBf16Split:
    """Sign-valued hash sketches ride the bf16 MXU (hash matrix =
    c * small-integer matrix, exact in bf16); the f32 3-pass split must
    reproduce the exact-f32 one-hot result to f32-accumulation accuracy."""

    def test_f32_split_matches_exact(self, rng):
        import jax.numpy as jnp
        from libskylark_tpu import SketchContext
        from libskylark_tpu.sketch import CWT, SJLT

        A32 = jnp.asarray(rng.standard_normal((64, 40)), jnp.float32)
        for cls, kw in ((CWT, {}), (SJLT, {"nnz": 2}),):
            S = cls(64, 16, SketchContext(seed=5), **kw)
            out = S.apply(A32, "columnwise")
            assert out.dtype == jnp.float32
            M = np.asarray(S._hash_matrix(jnp.float64))
            ref = M.T @ np.asarray(A32, np.float64)
            scale = np.abs(ref).max() + 1e-30
            np.testing.assert_allclose(
                np.asarray(out, np.float64), ref,
                rtol=5e-6, atol=5e-6 * scale,
            )

    def test_nonsign_values_scaled_onehot_path(self, rng):
        """MMT/WZT (non-sign values) fold v into A so the 0/1 bucket
        matrix is bf16-exact; the f32 3-pass split of (v ⊙ A) must match
        the f64 hash-matrix oracle to f32-product accuracy (round-3
        re-design of the f32 one-hot path; ≙ MMT_data.hpp:21-44,
        WZT_data.hpp:45-127)."""
        import jax.numpy as jnp
        from libskylark_tpu import SketchContext
        from libskylark_tpu.sketch import MMT, WZT

        for cls, kw in ((MMT, {}), (WZT, {"p": 1.5})):
            S = cls(30, 8, SketchContext(seed=6), **kw)
            assert S._sign_scale() is None
            A32 = jnp.asarray(rng.standard_normal((30, 20)), jnp.float32)
            out = S.apply(A32, "columnwise")
            assert out.dtype == jnp.float32
            M = np.asarray(S._hash_matrix(jnp.float64))
            ref = M.T @ np.asarray(A32, np.float64)
            scale = np.abs(ref).max() + 1e-30
            np.testing.assert_allclose(
                np.asarray(out, np.float64), ref,
                rtol=5e-5, atol=5e-5 * scale,
            )
            out_r = S.apply(A32.T, "rowwise")  # same path, rowwise
            np.testing.assert_allclose(
                np.asarray(out_r, np.float64), ref.T,
                rtol=5e-5, atol=5e-5 * scale,
            )

    def test_integer_input_onehot_path(self, rng):
        """Int inputs are value-converted before the bitcast split (a raw
        bitcast would turn negative ints into NaNs — review regression)."""
        import jax.numpy as jnp
        from libskylark_tpu import SketchContext
        from libskylark_tpu.sketch import CWT

        A = jnp.asarray(rng.integers(-50, 50, (64, 20)), jnp.int32)
        S = CWT(64, 16, SketchContext(seed=8))
        out = np.asarray(S.apply(A, "columnwise"))
        assert np.isfinite(out).all()
        M = np.asarray(S._hash_matrix(jnp.float64))
        np.testing.assert_allclose(
            out, M.T @ np.asarray(A, np.float64), rtol=1e-5, atol=1e-4
        )
