"""Chaos-driven fleet autoscaler (ISSUE PR 16): serve through change.

Two layers of drills:

- **Deterministic control-loop tests** over fake load-report-only
  replicas: scale-up on pressure with cooldown hysteresis, idle-streak
  scale-down that drains to zero before leaving membership, min/max
  bounds, spawn failure as a ledgered decision (never a crash), and the
  forced removal of a wedged drain.
- **Chaos drills** with real serving replicas and a
  :class:`FleetFaultPlan` firing at exact control ticks: die-under-load
  (the autoscaler restores capacity; no caller sees a 114 while a
  placeable replica remains), a join storm (every joiner rides the
  signature fence and is placeable only with a live worker), a slow
  heartbeat (stale-but-alive, never ejected for one dropped poll), and
  a flapping replica (membership converges, zero shed work).
"""

import time
from concurrent.futures import Future

import numpy as np
import pytest

from libskylark_tpu import serve, telemetry
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.resilient import FleetFaultPlan

pytestmark = [pytest.mark.fleet, pytest.mark.chaos]

M, N = 64, 5
_rng = np.random.default_rng(77)
A = _rng.standard_normal((M, N))
RHS = [_rng.standard_normal(M) for _ in range(8)]


class FakeServer:
    """A load-report-only replica: the control loop reads reports and
    membership, so deterministic loop tests need no real workers."""

    def __init__(self, name, depth=0.0):
        self.name = name
        self.depth = depth
        self.started = False
        self.stopped = False
        self.fail_reports = 0  # raise on the next N load_report fetches

    def start(self):
        self.started = True
        return self

    def stop(self, timeout=None):
        self.stopped = True

    def submit(self, request):
        fut = Future()
        fut.set_result(
            {"ok": True, "result": "pong", "trace": {"events": []}}
        )
        return fut

    def load_report(self):
        if self.fail_reports > 0:
            self.fail_reports -= 1
            raise OSError("report fetch timed out")
        return {
            "queue_depth": self.depth,
            "max_queue": 64,
            "worker_alive": self.started and not self.stopped,
            "throughput": {},
            "latency": {},
            "primed": [],
            "census": {},
            "signature": 1234,
        }


def _fake_fleet(params, fault_plan=None, cores=2, timeout_s=60.0):
    router = serve.Router(
        serve.RouterParams(heartbeat_timeout_s=timeout_s)
    )
    core = [FakeServer(f"core-{i}").start() for i in range(cores)]
    for s in core:
        router.join(s.name, server=s)
    spawned = []

    def factory(name):
        s = FakeServer(name)
        spawned.append(s)
        return s

    scaler = serve.Autoscaler(
        router, factory, params, fault_plan=fault_plan
    )
    return router, core, spawned, scaler


def _params(**kw):
    base = dict(
        min_replicas=2, max_replicas=4, queue_high=4.0, queue_low=1.0,
        cooldown_ticks=2, idle_ticks=2, drain_timeout_s=30.0,
    )
    base.update(kw)
    return serve.AutoscaleParams(**base)


# ---------------------------------------------------------------------------
# the control loop, deterministically


def test_scale_up_on_pressure_with_cooldown_and_max_bound():
    router, core, spawned, scaler = _fake_fleet(_params())
    for s in core:
        s.depth = 10.0
    d = scaler.step()
    assert d["action"] == "scale_up" and d["replica"] == "auto-1"
    assert spawned[0].started  # factory server started BEFORE joining
    assert router.fleet_report()["members"]["auto-1"]["placeable"]
    # cooldown: one replica's worth of effect must land first
    assert [scaler.step()["action"] for _ in range(2)] == [
        "cooldown", "cooldown",
    ]
    # still hot -> second spawn; then the max bound holds the line
    spawned[0].depth = 10.0
    assert scaler.step()["action"] == "scale_up"
    scaler.step(), scaler.step()  # cooldown x2
    spawned[1].depth = 10.0
    assert scaler.step()["action"] == "hold"
    assert len(router.fleet_report()["members"]) == 4
    router.stop()


def test_idle_drain_returns_fleet_to_floor_lifo():
    router, core, spawned, scaler = _fake_fleet(_params())
    for s in core:
        s.depth = 10.0
    scaler.step()  # -> auto-1
    scaler.step(), scaler.step()  # cooldown
    for s in core:
        s.depth = 10.0
    scaler.step()  # -> auto-2
    scaler.step(), scaler.step()  # cooldown
    for s in core + spawned:
        s.depth = 0.0

    drained = []
    for _ in range(16):
        d = scaler.step()
        if d["action"] == "scale_down":
            drained.append(d["replica"])
        if len(router.fleet_report()["members"]) == 2:
            break
    # newest owned replica first (LIFO), drained to zero then removed,
    # and the owned server is stopped after it leaves
    assert drained == ["auto-2", "auto-1"]
    assert all(s.stopped for s in spawned)
    assert set(router.fleet_report()["members"]) == {"core-0", "core-1"}
    # at the floor: further idle ticks hold, the core is never drained
    for _ in range(4):
        assert scaler.step()["action"] in ("hold", "cooldown")
    assert set(router.fleet_report()["members"]) == {"core-0", "core-1"}
    assert not any(s.stopped for s in core)
    router.stop()


def test_spawn_failure_is_a_ledgered_decision_not_a_crash():
    router = serve.Router()
    core = [FakeServer("core-0").start()]
    router.join("core-0", server=core[0])

    def factory(name):
        raise RuntimeError("no capacity in the cell")

    scaler = serve.Autoscaler(
        router, factory, _params(min_replicas=1, cooldown_ticks=0)
    )
    core[0].depth = 10.0
    d = scaler.step()
    assert d["action"] == "scale_up_failed" and "RuntimeError" in d["error"]
    # the loop keeps deciding; membership is unchanged
    assert scaler.step()["action"] == "scale_up_failed"
    assert set(router.fleet_report()["members"]) == {"core-0"}
    assert any(
        r["action"] == "scale_up_failed" for r in scaler.ledger
    )
    router.stop()


def test_drain_timeout_forces_removal_of_wedged_replica():
    router, core, spawned, scaler = _fake_fleet(
        _params(cooldown_ticks=0, idle_ticks=1, drain_timeout_s=5.0)
    )
    for s in core:
        s.depth = 10.0
    scaler.step()  # -> auto-1
    for s in core:
        s.depth = 0.0
    spawned[0].depth = 3.0  # never reaches zero: a wedged queue
    d = scaler.step()
    assert d["action"] == "scale_down" and d["replica"] == "auto-1"
    # within the window the drain waits ...
    scaler.step()
    assert "auto-1" in router.fleet_report()["members"]
    # ... past it the replica is removed anyway and stopped
    scaler.step(now=time.monotonic() + 6.0)
    assert "auto-1" not in router.fleet_report()["members"]
    assert spawned[0].stopped
    router.stop()


def test_report_shape_and_ledger_tail():
    router, core, spawned, scaler = _fake_fleet(_params())
    for s in core:
        s.depth = 10.0
    scaler.step()
    rep = scaler.report()
    assert rep["tick"] == 1 and rep["owned"] == ["auto-1"]
    assert rep["draining"] == [] and rep["cooldown"] == 2
    assert rep["params"]["max_replicas"] == 4
    last = rep["ledger"][-1]
    assert last["action"] == "scale_up" and last["tick"] == 1
    assert {"placeable", "mean_depth", "p99_ms"} <= set(last)
    router.stop()


def test_slow_heartbeat_is_stale_but_alive_never_ejected():
    plan = FleetFaultPlan(slow_heartbeat_at=2, slow_heartbeat_s=1.0)
    router, core, spawned, scaler = _fake_fleet(_params(), fault_plan=plan)
    plan.bind_fleet(
        slow_report=lambda s: setattr(core[0], "fail_reports", 1)
    )
    scaler.step()
    d = scaler.step()  # the fault fires; core-0's fetch fails this sweep
    # one dropped poll is not a dead replica: still placeable, its last
    # report stamped with its age
    assert d["placeable"] == 2
    member = router.fleet_report()["members"]["core-0"]
    assert member["placeable"]
    assert member["report"]["report_age_s"] >= 0.0
    # the next sweep recovers the live report
    scaler.step()
    report = router.fleet_report()["members"]["core-0"]["report"]
    assert "report_age_s" not in report
    router.stop()


def test_flapping_replica_membership_converges():
    plan = FleetFaultPlan(flap_at=2, flap_times=2)
    router, core, spawned, scaler = _fake_fleet(
        _params(min_replicas=1, idle_ticks=10**6), fault_plan=plan,
        timeout_s=0.0,
    )
    flappers = []

    def kill():
        core[1].stop()

    def spawn():
        s = FakeServer(f"flap-{len(flappers)}").start()
        flappers.append(s)
        router.join(s.name, server=s)

    plan.bind_fleet(kill=kill, spawn=spawn)
    transitions = []
    for _ in range(6):
        scaler.step()
        transitions.append(len(router.fleet_report()["members"]))
    fleet = router.fleet_report()
    router.stop()
    # tick 2 killed core-1 (ejected by the sweep), tick 3 spawned a
    # replacement; membership converged and stayed converged
    assert transitions[-1] == 2 and transitions[-1] == transitions[-2]
    assert "core-1" not in fleet["members"]
    assert "flap-0" in fleet["members"]
    assert fleet["members"]["flap-0"]["placeable"]


# ---------------------------------------------------------------------------
# chaos drills on real serving replicas


def _real_replica():
    srv = serve.Server(
        serve.ServeParams(
            max_coalesce=8, warm_start=False, prime=False
        ),
        seed=42,
    )
    srv.registry.register_system("sys", A, context=SketchContext(seed=9))
    return srv


def test_die_under_load_drill_restores_capacity_no_visible_114(
    monkeypatch,
):
    """A replica dies abruptly under traffic at tick 2.  The router
    fails the in-flight work over to survivors, the sweep ejects the
    corpse, and the autoscaler (p99 target tripped) restores the fleet
    to two placeable replicas — every caller answer ok throughout."""
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    r1, r2 = _real_replica().start(), _real_replica().start()
    router = serve.Router(serve.RouterParams(heartbeat_timeout_s=0.0))
    router.join("r1", server=r1)
    router.join("r2", server=r2)
    plan = FleetFaultPlan(die_under_load_at=2)
    plan.bind_fleet(kill=lambda: r2.stop(0.5))
    scaler = serve.Autoscaler(
        router, lambda name: _real_replica(),
        serve.AutoscaleParams(
            min_replicas=1, max_replicas=2, queue_high=1e9,
            queue_low=-1.0, p99_high_ms=1e-4, cooldown_ticks=0,
            idle_ticks=10**6,
        ),
        fault_plan=plan,
    )
    responses = []
    for tick in range(5):
        responses += [
            router.call(op="ls_solve", system="sys", b=b)
            for b in RHS[:2]
        ]
        scaler.step()
    fleet = router.fleet_report()
    snap = telemetry.snapshot()
    router.stop()
    r1.stop()
    for srv in scaler._owned.values():
        srv.stop()
    telemetry.REGISTRY.reset()

    # no caller ever saw a 114 (or any error) while placeable remained
    assert all(r["ok"] for r in responses)
    placeable = [
        n for n, m in fleet["members"].items() if m["placeable"]
    ]
    assert len(placeable) == 2 and "r2" not in fleet["members"]
    assert any(n.startswith("auto-") for n in placeable)
    assert snap["router"]["ejects"] >= 1  # the corpse was fenced out
    assert snap["autoscale"]["scale_ups"] >= 1


def test_join_storm_every_joiner_fenced_and_placeable():
    r1 = _real_replica().start()
    router = serve.Router()
    router.join("r1", server=r1)
    joined = []

    def spawn():
        srv = _real_replica().start()
        joined.append(srv)
        router.join(f"storm-{len(joined)}", server=srv)

    plan = FleetFaultPlan(join_storm_at=1, join_storm_size=3)
    plan.bind_fleet(spawn=spawn)
    scaler = serve.Autoscaler(
        router, lambda name: _real_replica(),
        serve.AutoscaleParams(min_replicas=1, max_replicas=8,
                              idle_ticks=10**6),
        fault_plan=plan,
    )
    scaler.step()
    fleet = router.fleet_report()
    # all three joiners cleared the signature fence and are placeable
    assert len(fleet["members"]) == 4
    assert all(m["placeable"] for m in fleet["members"].values())
    # traffic through the stormed fleet stays clean
    results = [
        router.call(op="ls_solve", system="sys", b=b) for b in RHS[:4]
    ]
    assert all(r["ok"] for r in results)
    # a registry-mismatched joiner is still refused outright (109)
    odd = serve.Server(
        serve.ServeParams(warm_start=False, prime=False), seed=42
    )
    odd.registry.register_system(
        "other", A, context=SketchContext(seed=9)
    )
    odd.start()
    from libskylark_tpu.utils import exceptions as ex

    with pytest.raises(ex.WorldMismatchError):
        router.join("odd", server=odd)
    router.stop()
    odd.stop()
    r1.stop()
    for srv in joined:
        srv.stop()
